"""Flash-attention tests: the exact blockwise jnp fallback and the Pallas
kernel (interpreter mode on CPU) against plain SDPA."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from quintnet_tpu.nn.attention import sdpa
from quintnet_tpu.ops.flash_attention import blockwise_attention
from quintnet_tpu.ops.pallas_attention import pallas_flash_attention


def _qkv(b=2, h=2, s=64, d=32, keyseed=0):
    ks = jax.random.split(jax.random.key(keyseed), 3)
    return tuple(jax.random.normal(k, (b, h, s, d)) for k in ks)


@pytest.mark.parametrize("causal", [False, True])
def test_blockwise_matches_sdpa(causal):
    q, k, v = _qkv()
    ref = sdpa(q, k, v, causal=causal)
    out = blockwise_attention(q, k, v, causal=causal, block_q=16, block_k=16)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)


def test_blockwise_ragged_seq():
    q, k, v = _qkv(s=50)  # not a block multiple -> padding path
    ref = sdpa(q, k, v, causal=True)
    out = blockwise_attention(q, k, v, causal=True, block_q=16, block_k=16)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_pallas_kernel_interpret_matches_sdpa(causal):
    q, k, v = _qkv(s=128, d=64)
    ref = sdpa(q, k, v, causal=causal)
    out = pallas_flash_attention(q, k, v, causal, 64, 64, True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_pallas_kernel_grads(causal):
    """Hand-tiled Pallas dQ/dK/dV kernels (interpret mode) == autodiff
    through plain SDPA, incl. the causally-pruned grid."""
    q, k, v = _qkv(s=64, d=32)
    w = jax.random.normal(jax.random.key(9), q.shape)

    def ref_loss(q_, k_, v_):
        return jnp.sum(sdpa(q_, k_, v_, causal=causal) * w)

    def fa_loss(q_, k_, v_):
        return jnp.sum(
            pallas_flash_attention(q_, k_, v_, causal, 32, 32, True) * w)

    g_ref = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
    g_fa = jax.grad(fa_loss, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_fa, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=5e-5)


def test_pallas_kernel_grads_rectangular_blocks():
    """block_q != block_k exercises the _block_live pruning geometry off
    the square-block fast path."""
    q, k, v = _qkv(s=128, d=32)
    w = jax.random.normal(jax.random.key(9), q.shape)

    def ref_loss(q_, k_, v_):
        return jnp.sum(sdpa(q_, k_, v_, causal=True) * w)

    def fa_loss(q_, k_, v_):
        return jnp.sum(
            pallas_flash_attention(q_, k_, v_, True, 32, 64, True) * w)

    g_ref = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
    g_fa = jax.grad(fa_loss, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_fa, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=5e-5)

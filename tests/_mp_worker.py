"""Worker for tests/test_multihost.py — one process of a 2-process
CPU run (4 virtual devices each, 8 global) training ViT on a dp4 x tp2
mesh with BOTH per-host feeding modes. Not collected by pytest
(underscore prefix); launched as `python tests/_mp_worker.py <pid> ...`.
"""

import json
import sys


def main():
    proc_id = int(sys.argv[1])
    num_procs = int(sys.argv[2])
    port = sys.argv[3]
    outfile = sys.argv[4]

    from quintnet_tpu.core import runtime

    runtime.initialize(
        coordinator_address=f"localhost:{port}",
        num_processes=num_procs,
        process_id=proc_id,
        local_device_count=4,
        platform="cpu",
    )

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from quintnet_tpu.core.config import Config
    from quintnet_tpu.models.vit import ViTConfig, vit_init, vit_model_spec
    from quintnet_tpu.parallel.strategy import get_strategy

    assert jax.device_count() == 8, jax.device_count()
    assert jax.process_count() == num_procs

    cfg_model = ViTConfig(image_size=14, patch_size=7, in_channels=1,
                          hidden_dim=16, depth=4, num_heads=2,
                          num_classes=10)
    cfg = Config.from_dict({
        "mesh_dim": [4, 2],
        "mesh_name": ["dp", "tp"],
        "training": {"batch_size": 16,
                     "gradient_accumulation_steps": 1,
                     "grad_clip_norm": None},
    })

    # identical host-global data/params on every process (same seeds)
    x = jax.random.normal(jax.random.key(1), (16, 14, 14, 1))
    y = jax.random.randint(jax.random.key(2), (16,), 0, 10)
    x, y = np.asarray(x), np.asarray(y)

    model = vit_model_spec(cfg_model)
    opt = optax.sgd(0.05)
    strat = get_strategy("dp_tp", cfg)
    assert strat.is_multiprocess
    step = strat.make_train_step(model, opt)

    def param_sqsum(mesh, p):
        fn = jax.jit(
            lambda t: sum(jnp.sum(jnp.square(l))
                          for l in jax.tree.leaves(t)),
            out_shardings=NamedSharding(mesh, P()))
        return float(fn(p))

    cfg_fsdp = Config.from_dict({
        "mesh_dim": [4, 2],
        "mesh_name": ["dp", "tp"],
        "training": {"batch_size": 16, "fsdp": True,
                     "gradient_accumulation_steps": 1,
                     "grad_clip_norm": None},
    })
    strat_fsdp = get_strategy("dp_tp", cfg_fsdp)
    step_fsdp = strat_fsdp.make_train_step(model, opt)

    results = {}
    for mode in ("global", "local", "fsdp"):
        st = strat_fsdp if mode == "fsdp" else strat
        stp = step_fsdp if mode == "fsdp" else step
        params = st.shard_params(model, vit_init(jax.random.key(0),
                                                 cfg_model))
        opt_state = st.init_opt_state(model, opt, params)
        losses = []
        for _ in range(2):
            if mode == "local":
                # true per-host feeding: this process passes ONLY its rows
                from quintnet_tpu.core.runtime import host_local_slice

                specs = st.batch_partition_specs(model)
                shard_x = NamedSharding(st.mesh, specs)
                sl = host_local_slice(shard_x, x.shape)
                b = st.shard_batch_local((x[sl], y[sl[:1]]), model)
            else:
                # "fsdp": ZeRO-3 param storage over the multi-process dp
                # axis — gathers cross the process boundary (gloo)
                b = st.shard_batch((x, y), model)
            params, opt_state, loss = stp(params, opt_state, b)
            losses.append(float(loss))
        results[mode] = {"losses": losses,
                         "param_sqsum": param_sqsum(st.mesh, params)}

    with open(outfile, "w") as f:
        json.dump({"process": proc_id, **results}, f)
    print(f"worker {proc_id} done", flush=True)


if __name__ == "__main__":
    main()

"""remat policy ("dots") and scan_unroll are pure perf knobs: loss and
grads must be identical (up to float reassociation) to the plain path.

The reference has no analogue (torch checkpointing is absent there);
these guard the round-4 tuning surface (bench --remat-policy /
--scan-unroll, GPT2Config.scan_unroll).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from quintnet_tpu.models.gpt2 import (GPT2Config, clm_loss, gpt2_apply,
                                      gpt2_init)


def _loss_fn(cfg, remat):
    def f(params, ids):
        logits = gpt2_apply(params, ids, cfg, remat=remat)
        return clm_loss(logits, ids)

    return jax.jit(jax.value_and_grad(f))


@pytest.fixture(scope="module")
def setup():
    cfg = GPT2Config.tiny()
    params = gpt2_init(jax.random.key(0), cfg)
    ids = jnp.asarray(np.random.default_rng(0).integers(
        0, cfg.vocab_size, size=(2, 16), dtype=np.int32))
    base_loss, base_grads = _loss_fn(cfg, False)(params, ids)
    return cfg, params, ids, base_loss, base_grads


@pytest.mark.fast
@pytest.mark.parametrize("remat", [True, "dots"])
def test_remat_policies_match_plain(setup, remat):
    cfg, params, ids, base_loss, base_grads = setup
    loss, grads = _loss_fn(cfg, remat)(params, ids)
    assert jnp.allclose(loss, base_loss, rtol=1e-6)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6),
        grads, base_grads)


@pytest.mark.parametrize("unroll", [2, 4])
def test_scan_unroll_matches_unrolled(setup, unroll):
    cfg, params, ids, base_loss, base_grads = setup
    ucfg = GPT2Config.tiny(scan_unroll=unroll)
    loss, grads = _loss_fn(ucfg, True)(params, ids)
    assert jnp.allclose(loss, base_loss, rtol=1e-6)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6),
        grads, base_grads)


def test_dots_policy_under_sharded_strategy():
    """remat='dots' must survive the full shard_map train step (the
    string rides through ModelSpec -> stacked_blocks_apply untouched)."""
    import optax

    from quintnet_tpu.core.config import Config
    from quintnet_tpu.models.vit import ViTConfig, vit_init, vit_model_spec
    from quintnet_tpu.parallel.strategy import get_strategy

    vcfg = ViTConfig(image_size=28, patch_size=7, in_channels=1,
                     hidden_dim=16, depth=4, num_heads=2, num_classes=10)
    cfg = Config.from_dict({
        "mesh_dim": [2, 2], "mesh_name": ["dp", "tp"],
        "training": {"batch_size": 8, "grad_clip_norm": None,
                     "remat": True, "remat_policy": "dots"},
    })
    params = vit_init(jax.random.key(0), vcfg)
    x = jax.random.normal(jax.random.key(1), (8, 28, 28, 1))
    y = jax.random.randint(jax.random.key(2), (8,), 0, 10)
    opt = optax.sgd(0.05)

    losses = {}
    updated = {}
    for remat in (False, cfg.training.remat_mode):
        strat = get_strategy("dp_tp", cfg)
        model = vit_model_spec(vcfg, remat=remat)
        # fresh copies: the train step donates its param buffers, and
        # shard_params may alias the host tree's arrays
        p = strat.shard_params(model, jax.tree.map(jnp.array, params))
        s = strat.init_opt_state(model, opt, p)
        b = strat.shard_batch((x, y))
        p2, _, loss = strat.make_train_step(model, opt)(p, s, b)
        losses[remat] = float(loss)
        updated[remat] = jax.device_get(p2)
    assert cfg.training.remat_mode == "dots"
    np.testing.assert_allclose(losses[False], losses["dots"], rtol=1e-5)
    # the post-update params pin the GRADIENTS equal too (loss alone is
    # computed pre-update and could not catch a wrong dots backward)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-4,
                                                atol=1e-6),
        updated[False], updated["dots"])

"""tools/pod_run.py — the pod-operations driver (the reference's Modal
launcher workflow: upload -> train streamed -> list checkpoints ->
merge-and-test, gpt2_train_modal_run.py:202-340,595-640).

The full loop is rehearsed end-to-end here on CPU: prepare a run dir
from the committed CNN/DM fixture, train a tiny GPT-2 through the real
entry (checkpoints + model_config.json land in the volume layout), then
merge-test restores, exports HF safetensors, reloads the exported file
and reports val loss/ppl + generations.
"""

import json
import os
import subprocess
import sys

import pytest

from quintnet_tpu.tools import pod_run

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CSV = os.path.join(REPO, "tests", "fixtures", "cnn_dm_tiny.csv")


@pytest.mark.fast
def test_plan_prints_runbook(capsys):
    rc = pod_run.main(["plan", "--run-dir", "runs/demo",
                       "--tpu-name", "my-v5e"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "gcloud compute tpus tpu-vm ssh my-v5e --worker=all" in out
    assert "--multihost" in out
    assert "runs/demo/\n" in out        # volume layout section
    assert "merge-test" in out          # post-run loop documented
    assert "list-checkpoints" in out


@pytest.mark.fast
def test_prepare_stages_volume_layout(tmp_path):
    run = str(tmp_path / "run1")
    model_dir = tmp_path / "hf_model"
    model_dir.mkdir()
    (model_dir / "model.safetensors").write_bytes(b"\0" * 128)
    rc = pod_run.main(["prepare", "--run-dir", run,
                       "--model", str(model_dir), "--dataset", CSV])
    assert rc == 0
    for sub in ("model", "data", "checkpoints", "export", "logs"):
        assert os.path.isdir(os.path.join(run, sub))
    assert os.path.exists(os.path.join(run, "data", "cnn_dm_tiny.csv"))
    assert os.path.exists(os.path.join(run, "model", "model.safetensors"))
    man = json.load(open(os.path.join(run, "manifest.json")))
    assert man["data"][0]["file"] == "cnn_dm_tiny.csv"
    assert man["model"][0]["bytes"] == 128


@pytest.mark.fast
def test_prepare_missing_dataset_fails(tmp_path):
    rc = pod_run.main(["prepare", "--run-dir", str(tmp_path / "r"),
                       "--dataset", str(tmp_path / "nope.csv")])
    assert rc == 1


def _tiny_config(tmp_path):
    cfg = tmp_path / "tiny.yaml"
    cfg.write_text(
        "mesh_dim: [2]\nmesh_name: ['dp']\n"
        "training:\n  batch_size: 4\n  epochs: 1\n  log_every: 0\n"
        "  learning_rate: 0.001\n  optimizer: adamw\n"
        "data:\n  max_seq_length: 64\n  train_samples: 4\n"
        "  val_samples: 4\n")
    return str(cfg)


@pytest.mark.slow
def test_pod_run_full_loop(tmp_path):
    """prepare -> train (real entry, subprocess) -> list-checkpoints ->
    merge-test, all against the run-dir volume layout."""
    run = str(tmp_path / "run1")
    assert pod_run.main(["prepare", "--run-dir", run,
                         "--dataset", CSV]) == 0

    env = dict(os.environ,
               PYTHONPATH=REPO + os.pathsep + os.environ.get(
                   "PYTHONPATH", ""))
    train_cmd = [
        sys.executable, "-m", "quintnet_tpu.examples.gpt2_finetune",
        "--simulate", "2", "--tiny", "--epochs", "1",
        "--config", _tiny_config(tmp_path),
        "--csv", os.path.join(run, "data", "cnn_dm_tiny.csv"),
        "--checkpoint-dir", os.path.join(run, "checkpoints"),
    ]
    # drive through pod_run train so the tee/log path is exercised too
    proc = subprocess.run(
        [sys.executable, "-m", "quintnet_tpu.tools.pod_run", "train",
         "--run-dir", run, "--"] + train_cmd,
        capture_output=True, text=True, env=env, cwd=REPO, timeout=600)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    log = open(os.path.join(run, "logs", "train.log")).read()
    assert "train_loss" in log  # streamed output captured

    assert pod_run.main(["list-checkpoints", "--run-dir", run]) == 0
    assert os.path.exists(os.path.join(run, "checkpoints",
                                       "model_config.json"))

    rc = pod_run.main(["merge-test", "--run-dir", run,
                       "--csv", os.path.join(run, "data",
                                             "cnn_dm_tiny.csv"),
                       "--gen-samples", "1", "--batch-size", "2",
                       "--max-length", "64"])
    assert rc == 0
    exports = os.listdir(os.path.join(run, "export"))
    assert any(f.endswith(".safetensors") for f in exports)


@pytest.mark.fast
def test_train_restart_loop_disarms_chaos(tmp_path, monkeypatch):
    """A QT_CHAOS kill armed in the supervisor's environment is consumed
    by the attempt it killed: the relaunch must not re-arm the same
    kill_at_step (it would fire before the cursor can pass it and the
    run could never complete)."""
    run = str(tmp_path / "r")
    monkeypatch.setenv("QT_CHAOS", json.dumps({"kill_at_step": 1}))
    child = ("import os, sys;"
             "sys.exit(113 if os.environ.get('QT_CHAOS') else 0)")
    rc = pod_run.main(["train", "--run-dir", run, "--max-restarts", "2",
                       "--", sys.executable, "-c", child])
    # attempt 1 dies armed (rc 113); attempt 2 runs disarmed and passes
    assert rc == 0
    log = open(os.path.join(run, "logs", "train.log")).read()
    assert "cleared QT_CHAOS" in log


@pytest.mark.fast
def test_merge_test_without_config_fails(tmp_path):
    run = str(tmp_path / "r2")
    os.makedirs(os.path.join(run, "checkpoints"))
    assert pod_run.main(["merge-test", "--run-dir", run]) == 1

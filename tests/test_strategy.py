"""Strategy facade + hybrid (2D/3D) integration tests.

The reference's 3D integration test is an empty TODO class
(tests/test_hybrid.py:10-19); these are the real thing: every strategy
in the registry produces the same loss and parameter update as
single-device training on the global batch.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from quintnet_tpu.core.config import Config
from quintnet_tpu.models.vit import (
    ViTConfig,
    cross_entropy_loss,
    vit_apply,
    vit_init,
    vit_model_spec,
    vit_to_tp_layout,
)
from quintnet_tpu.parallel.strategy import get_strategy

CFG = ViTConfig(image_size=14, patch_size=7, in_channels=1, hidden_dim=16,
                depth=4, num_heads=2, num_classes=10)


def _config(mesh_dim, mesh_name, schedule="afab", grad_acc=1):
    return Config.from_dict({
        "mesh_dim": list(mesh_dim),
        "mesh_name": list(mesh_name),
        "training": {
            "batch_size": 16,
            "gradient_accumulation_steps": grad_acc,
            "schedule": schedule,
            "grad_clip_norm": None,
        },
    })


def _data(n=16):
    x = jax.random.normal(jax.random.key(1), (n, 14, 14, 1))
    y = jax.random.randint(jax.random.key(2), (n,), 0, 10)
    return x, y


def _reference_update(params, batch, opt):
    def loss_fn(p):
        x, y = batch
        return cross_entropy_loss(vit_apply(p, x, CFG), y)

    loss, g = jax.value_and_grad(loss_fn)(params)
    p2 = optax.apply_updates(params, opt.update(g, opt.init(params), params)[0])
    return loss, p2


def _run_strategy(name, cfg, params, batch):
    strat = get_strategy(name, cfg)
    model = vit_model_spec(CFG)
    opt = optax.sgd(0.05)
    p = strat.shard_params(model, params)
    s = strat.init_opt_state(model, opt, p)
    b = strat.shard_batch(batch)
    step = strat.make_train_step(model, opt)
    p2, _, loss = step(p, s, b)
    return float(loss), p2


@pytest.mark.parametrize(
    "name,mesh_dim,mesh_name,schedule,grad_acc",
    [
        ("dp", [8], ["dp"], "afab", 1),
        ("tp", [2], ["tp"], "afab", 1),
        ("pp", [4], ["pp"], "afab", 4),
        ("pp", [4], ["pp"], "1f1b", 4),
        ("dp_tp", [4, 2], ["dp", "tp"], "afab", 1),
        ("dp_pp", [2, 4], ["dp", "pp"], "1f1b", 4),
        ("tp_pp", [2, 4], ["tp", "pp"], "1f1b", 2),
        ("3d", [2, 2, 2], ["dp", "tp", "pp"], "1f1b", 2),
        ("3d", [2, 2, 2], ["dp", "tp", "pp"], "afab", 2),
    ],
)
def test_strategy_matches_single_device(name, mesh_dim, mesh_name,
                                        schedule, grad_acc):
    cfg = _config(mesh_dim, mesh_name, schedule, grad_acc)
    params = vit_init(jax.random.key(0), CFG)
    batch = _data()
    opt = optax.sgd(0.05)

    loss_ref, p_ref = _reference_update(params, batch, opt)
    loss, p2 = _run_strategy(name, cfg, params, batch)

    np.testing.assert_allclose(loss, float(loss_ref), rtol=1e-5)

    tp = cfg.tp_size
    p_ref_layout = vit_to_tp_layout(p_ref, CFG, tp)
    flat = jax.tree_util.tree_leaves_with_path(p2)
    ref = dict(jax.tree_util.tree_leaves_with_path(p_ref_layout))
    for path, leaf in flat:
        np.testing.assert_allclose(
            np.asarray(jax.device_get(leaf)), np.asarray(ref[path]),
            rtol=2e-4, atol=1e-5, err_msg=f"{name}:{path}")


def test_auto_strategy_derivation():
    cfg = _config([2, 2, 2], ["dp", "tp", "pp"])
    strat = get_strategy("auto", cfg)
    assert strat.name == "3d"
    assert strat.batch_axes == ("dp",)
    assert strat.model_axes == ("tp",)
    assert strat.partial_axes == ("pp",)


def test_unknown_strategy_rejected():
    with pytest.raises(ValueError):
        get_strategy("5d_hype", _config([1], ["dp"]))


def test_strategy_axis_mismatch_rejected():
    cfg = _config([2, 4], ["dp", "pp"])
    with pytest.raises(ValueError):
        get_strategy("tp", cfg)

"""Fault-tolerance goldens: a killed-and-resumed run must be
BIT-IDENTICAL to an uninterrupted one.

The contract under test (quintnet_tpu/ft/): params/opt arrays ride in
orbax, the host-side ``TrainCursor`` (epoch, step, epoch losses,
``History``) rides as a JSON item in the same step directory, dropout
seeds are pure functions of (config seed, epoch, step), and the data
order is a pure function of (epoch seed, step) — so replaying from any
checkpointed cursor reproduces the uninterrupted trajectory exactly.
Kill modes exercised: in-process hard kill (``ChaosKilled``), graceful
SIGTERM preemption (emergency snapshot), and checkpoint corruption with
fallback to the previous good step.
"""

import json
import os

import numpy as np
import pytest

import jax

from quintnet_tpu.core.config import Config
from quintnet_tpu.data import ArrayDataset, make_batches
from quintnet_tpu.data.datasets import skip_batches, synthetic_mnist
from quintnet_tpu.ft import (
    ChaosKilled,
    ChaosMonkey,
    FTContext,
    GoodputMeter,
    PreemptionHandler,
    TrainCursor,
    TrainingPreempted,
    corrupt_checkpoint,
)
from quintnet_tpu.ft.preempt import CadenceController
from quintnet_tpu.models.vit import ViTConfig, vit_model_spec
from quintnet_tpu.train.checkpoint import CheckpointManager, CheckpointRestoreError
from quintnet_tpu.train.trainer import History, Trainer

VCFG = ViTConfig(image_size=28, patch_size=7, in_channels=1, hidden_dim=16,
                 depth=2, num_heads=2, num_classes=10)

# 48 samples / batch 16 = 3 steps/epoch; 2 epochs = 6 global steps.
SAMPLES, BATCH, EPOCHS = 48, 16, 2


def _cfg(mesh_dim, mesh_name, **training):
    t = {"batch_size": BATCH, "epochs": EPOCHS, "optimizer": "adam",
         "learning_rate": 1e-3, "log_every": 0, "seed": 0}
    t.update(training)
    return Config.from_dict({"mesh_dim": mesh_dim, "mesh_name": mesh_name,
                             "training": t})


def _dataset():
    x, y = synthetic_mnist(SAMPLES, seed=0)
    return ArrayDataset(x, y)


def _batches_fn(ds):
    # two-positional-arg factory: map-style skip-to-cursor (start_batch
    # slices the shuffled index, no skipped sample materialised)
    return lambda ep, start=0: make_batches(ds, BATCH, seed=ep,
                                            start_batch=start)


def _trainer(cfg, ckpt_dir, logs=None):
    log = (logs.append if logs is not None else (lambda s: None))
    return Trainer(cfg, vit_model_spec(VCFG), task_type="classification",
                   checkpoint_dir=ckpt_dir, log_fn=log)


def _assert_trees_equal(a, b):
    la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def _golden_kill_resume(mesh_dim, mesh_name, tmp_path):
    """Uninterrupted vs kill-at-step-6 (+mid-epoch resume from the step-5
    cadence checkpoint): final params and loss series bit-identical."""
    ds = _dataset()
    bf = _batches_fn(ds)

    # --- uninterrupted reference run (no checkpointing at all) ---------
    t_ref = _trainer(_cfg(mesh_dim, mesh_name), None)
    hist_ref = t_ref.fit(bf)
    params_ref, _ = t_ref._final_state

    # --- attempt 1: cadence saves every 2 steps, hard-kill after 6 -----
    # saves land at global steps 2, 3 (epoch end), 5; the kill at 6
    # fires BEFORE the epoch-end save, so the newest checkpoint is the
    # MID-EPOCH cursor (epoch 1, step 2) — the resume replays step 6.
    ck = str(tmp_path / "ck")
    cfg = _cfg(mesh_dim, mesh_name, save_every_steps=2)
    t1 = _trainer(cfg, ck)
    chaos = ChaosMonkey(kill_at_step=6, mode="raise")
    with pytest.raises(ChaosKilled):
        t1.fit(bf, ft=FTContext(chaos=chaos))
    t1.wait_for_saves()

    # --- attempt 2: fresh Trainer, resume from the cursor --------------
    logs = []
    t2 = _trainer(cfg, ck, logs)
    hist = t2.fit(bf)
    params, _ = t2._final_state

    assert any("continuing at epoch 1 step 2" in s for s in logs), logs
    assert hist.train_loss == hist_ref.train_loss
    assert hist.val_loss == hist_ref.val_loss
    _assert_trees_equal(params, params_ref)


def test_kill_resume_bit_identical_single_device(tmp_path):
    _golden_kill_resume([1], ["dp"], tmp_path)


def test_kill_resume_bit_identical_2axis_mesh(tmp_path):
    _golden_kill_resume([2, 2], ["dp", "tp"], tmp_path)


def test_sigterm_preemption_emergency_snapshot_and_resume(tmp_path):
    """Graceful path: SIGTERM (chaos-delivered to self) sets the handler
    flag, the loop finishes the in-flight step, writes one synchronous
    emergency snapshot, and raises TrainingPreempted; the resumed run is
    bit-identical to an uninterrupted one and the restored History keeps
    the pre-crash epochs (the to_jsonl clobber fix)."""
    ds = _dataset()
    bf = _batches_fn(ds)

    t_ref = _trainer(_cfg([1], ["dp"]), None)
    hist_ref = t_ref.fit(bf)
    params_ref, _ = t_ref._final_state

    ck = str(tmp_path / "ck")
    cfg = _cfg([1], ["dp"])  # NO cadence: only the emergency snapshot
    t1 = _trainer(cfg, ck)
    meter = GoodputMeter()
    with PreemptionHandler() as handler:
        ft = FTContext(preemption=handler,
                       chaos=ChaosMonkey(kill_at_step=4, mode="sigterm"),
                       goodput=meter)
        with pytest.raises(TrainingPreempted) as ei:
            t1.fit(bf, ft=ft)
    # preempted after global step 4 = epoch 1 step 1 (mid-epoch)
    assert (ei.value.epoch, ei.value.step_in_epoch) == (1, 1)
    assert ei.value.global_step == 4
    rep = meter.report(completed=False)
    assert rep["steps_run"] == 4 and rep["reached"] == 4
    assert rep["save_blocking_s"] > 0  # the emergency save is synchronous

    t2 = _trainer(cfg, ck)
    hist = t2.fit(bf)
    params, _ = t2._final_state
    assert hist.train_loss == hist_ref.train_loss
    _assert_trees_equal(params, params_ref)

    # History survived the crash: the jsonl written AFTER resume holds
    # the full run — epoch-0 row included — and wall time is cumulative
    # across both attempts (not just the resumed process's clock).
    p = str(tmp_path / "hist.jsonl")
    hist.to_jsonl(p)
    rows = [json.loads(l) for l in open(p)]
    assert [r["epoch"] for r in rows[:-1]] == list(range(EPOCHS))
    assert rows[-1]["wall_time_s"] == pytest.approx(hist.wall_time_s,
                                                    abs=0.01)
    assert hist.wall_time_s > 0


def test_corrupt_latest_falls_back_to_previous_good_step(tmp_path):
    """Truncate the newest checkpoint: resume must fall back one cadence
    interval (not crash, not restart the run) and still reach the
    bit-identical final state."""
    ds = _dataset()
    bf = _batches_fn(ds)
    ck = str(tmp_path / "ck")
    cfg = _cfg([1], ["dp"], save_every_steps=2)

    t_ref = _trainer(cfg, ck)
    hist_ref = t_ref.fit(bf)
    params_ref, _ = t_ref._final_state
    t_ref.wait_for_saves()

    mgr = CheckpointManager(ck)
    steps = mgr.all_steps()
    assert len(steps) >= 2
    bad = steps[-1]
    corrupt_checkpoint(ck, bad, kind="truncate")

    logs = []
    t2 = _trainer(cfg, ck, logs)
    params, opt, cursor = t2.resume_state()
    assert cursor is not None
    assert t2._last_ckpt_step == steps[-2]
    assert cursor.global_step == steps[-2]
    assert any("fallback" in s and str(bad) in s for s in logs), logs

    # finishing from the fallback point reproduces the reference run
    t3 = _trainer(cfg, ck)
    hist = t3.fit(bf)
    params3, _ = t3._final_state
    assert hist.train_loss == hist_ref.train_loss
    _assert_trees_equal(params3, params_ref)


def test_corrupt_step_rewritten_on_replay(tmp_path):
    """A step the restore fallback proved unreadable must be REWRITTEN
    when deterministic replay re-reaches it — otherwise the corrupt
    copy shadows every later save attempt at that step and each new
    preemption falls back to the same old good step (zero forward
    progress when preemptions arrive faster than two cadence
    intervals)."""
    ds = _dataset()
    bf = _batches_fn(ds)
    ck = str(tmp_path / "ck")
    cfg = _cfg([1], ["dp"], save_every_steps=2)

    t1 = _trainer(cfg, ck)
    t1.fit(bf)
    t1.wait_for_saves()
    bad = CheckpointManager(ck).latest_step()  # final boundary save
    corrupt_checkpoint(ck, bad, kind="truncate")

    logs = []
    t2 = _trainer(cfg, ck, logs)
    t2.fit(bf)  # falls back one interval, replays through `bad`
    t2.wait_for_saves()
    assert any("fallback" in s for s in logs), logs

    mgr = CheckpointManager(ck)
    assert mgr.latest_step() == bad
    state = mgr.restore()  # the corrupt copy was replaced and loads
    assert set(state) >= {"params", "opt", "epoch"}
    assert mgr.restore_cursor()["step_in_epoch"] == 0


def test_cadence_on_epoch_final_batch_heals_to_boundary_cursor(tmp_path):
    """``save_every_steps`` dividing steps-per-epoch makes every cadence
    save land on an epoch's final batch at the same global step as the
    epoch-boundary save; the boundary save must rewrite the mid-epoch
    cursor (same arrays, boundary shape), or the run's newest on-disk
    cursor is forever mid-epoch-shaped, the History on disk misses the
    final epoch, and resume_or_init refuses a directory that in fact
    sits at a true epoch boundary."""
    ds = _dataset()
    ck = str(tmp_path / "ck")
    cfg = _cfg([1], ["dp"], save_every_steps=3)  # == steps per epoch
    t1 = _trainer(cfg, ck)
    hist = t1.fit(_batches_fn(ds))
    t1.wait_for_saves()

    cur = CheckpointManager(ck).restore_cursor()
    assert (cur["epoch"], cur["step_in_epoch"]) == (EPOCHS, 0)
    assert cur["history"]["train_loss"] == hist.train_loss
    # the epoch-level API accepts the directory again
    t2 = _trainer(cfg, ck)
    _p, _o, start_epoch = t2.resume_or_init()
    assert start_epoch == EPOCHS


def test_preemption_handler_requires_checkpoint_dir():
    """exit-75 means "snapshot saved, relaunch me"; a trainer that has
    nowhere to write the snapshot must refuse the contract up front,
    not log 'emergency snapshot saved' while every relaunch silently
    restarts from epoch 0."""
    t = _trainer(_cfg([1], ["dp"]), None)
    with PreemptionHandler() as handler:
        with pytest.raises(ValueError, match="checkpoint_dir"):
            t.fit(_batches_fn(_dataset()),
                  ft=FTContext(preemption=handler))


def test_restore_error_names_step_and_fallback(tmp_path):
    """CheckpointManager.restore on a torn step raises an actionable
    CheckpointRestoreError (which step, which fallbacks) instead of a
    raw orbax traceback; the named fallback step actually loads."""
    ds = _dataset()
    ck = str(tmp_path / "ck")
    cfg = _cfg([1], ["dp"], save_every_steps=2)
    t = _trainer(cfg, ck)
    t.fit(_batches_fn(ds))
    t.wait_for_saves()

    mgr = CheckpointManager(ck)
    steps = mgr.all_steps()
    corrupt_checkpoint(ck, steps[-1], kind="truncate")
    with pytest.raises(CheckpointRestoreError) as ei:
        mgr.restore()
    err = ei.value
    assert err.step == steps[-1]
    assert err.available[0] == steps[-2]
    assert str(steps[-2]) in str(err) and "restore_with_fallback" in str(err)
    # and the advertised recovery works
    state = mgr.restore(step=err.available[0])
    assert set(state) >= {"params", "opt", "epoch"}


def test_injected_restore_failures_walk_the_fallback_chain(tmp_path):
    """fail_restores=N makes the first N restore attempts raise without
    touching disk — resume lands N checkpoints back."""
    ds = _dataset()
    ck = str(tmp_path / "ck")
    cfg = _cfg([1], ["dp"], save_every_steps=2)
    t = _trainer(cfg, ck)
    t.fit(_batches_fn(ds))
    t.wait_for_saves()
    steps = CheckpointManager(ck).all_steps()
    assert len(steps) >= 2

    t2 = _trainer(cfg, ck)
    _p, _o, cursor = t2.resume_state(
        chaos=ChaosMonkey(fail_restores=1))
    assert cursor.global_step == steps[-2]


def test_pre_ft_single_item_checkpoint_still_restores(tmp_path):
    """Checkpoints written by the PREVIOUS release are a single
    StandardSave item (no Composite, no cursor). The new restore path
    must read them — orbax refuses Composite args on a single-item
    step, so restore() retries with the legacy layout — and resume
    degrades to epoch granularity instead of misreporting every healthy
    step as corrupt."""
    import orbax.checkpoint as ocp

    cfg = _cfg([1], ["dp"])
    t = _trainer(cfg, str(tmp_path / "ck"))
    params, opt = t.init_state()
    legacy = ocp.CheckpointManager(
        str(tmp_path / "ck"),
        options=ocp.CheckpointManagerOptions(create=True))
    legacy.save(2, args=ocp.args.StandardSave(
        {"params": params, "opt": opt, "epoch": 2}))
    legacy.wait_until_finished()
    legacy.close()

    t2 = _trainer(cfg, str(tmp_path / "ck"))
    _p, _o, cursor = t2.resume_state()
    assert (cursor.epoch, cursor.step_in_epoch) == (3, 0)
    assert cursor.global_step == 2  # anchored at the legacy index
    state = CheckpointManager(str(tmp_path / "ck")).restore()
    assert int(state["epoch"]) == 2


def test_preemption_during_eval_honored_at_epoch_boundary(tmp_path):
    """SIGTERM that lands while evaluate() runs (the per-step poll can't
    see it) must not start the next epoch: the epoch-end checkpoint is
    made durable and TrainingPreempted carries the boundary cursor."""
    ds = _dataset()
    ck = str(tmp_path / "ck")
    t = _trainer(_cfg([1], ["dp"]), ck)
    with PreemptionHandler() as handler:
        ft = FTContext(preemption=handler)

        def val_fn(ep):
            handler.request()  # "signal" arrives mid-eval of epoch 0
            return make_batches(ds, BATCH, seed=100 + ep, shuffle=False)

        with pytest.raises(TrainingPreempted) as ei:
            t.fit(_batches_fn(ds), val_batches_fn=val_fn, ft=ft)
    assert (ei.value.epoch, ei.value.step_in_epoch) == (1, 0)
    # the boundary checkpoint is on disk and resumable
    t2 = _trainer(_cfg([1], ["dp"]), ck)
    _p, _o, cursor = t2.resume_state()
    assert (cursor.epoch, cursor.step_in_epoch) == (1, 0)


def test_resume_or_init_refuses_mid_epoch_checkpoint(tmp_path):
    """An external epoch-level loop must not be handed mid-epoch params
    labelled as an epoch boundary (it would re-apply the epoch's first
    steps); resume_or_init raises and points at fit/resume_state."""
    ds = _dataset()
    ck = str(tmp_path / "ck")
    cfg = _cfg([1], ["dp"], save_every_steps=2)
    t1 = _trainer(cfg, ck)
    with pytest.raises(ChaosKilled):
        t1.fit(_batches_fn(ds),
               ft=FTContext(chaos=ChaosMonkey(kill_at_step=6, mode="raise")))
    t1.wait_for_saves()  # newest checkpoint: mid-epoch cursor (1, 2)

    t2 = _trainer(cfg, ck)
    with pytest.raises(RuntimeError, match="mid-epoch.*resume_state"):
        t2.resume_or_init()
    # step-granular resume of the same directory still works
    hist = _trainer(cfg, ck).fit(_batches_fn(ds))
    assert len(hist.train_loss) == EPOCHS


def test_legacy_epoch_indexed_checkpoint_degrades_cleanly(tmp_path):
    """A cursor-less (pre-ft, epoch-indexed) checkpoint resumes at epoch
    granularity with global_step anchored at the restored orbax index,
    so new global-step-indexed saves sort strictly after it — an
    emergency snapshot in the first resumed steps is never skipped."""
    ds = _dataset()
    ck = str(tmp_path / "ck")
    cfg = _cfg([1], ["dp"])
    t1 = _trainer(cfg, ck)
    params, opt = t1.init_state()
    t1.save(3, params, opt)  # legacy epoch-indexed save, no cursor
    t1.wait_for_saves()

    t2 = _trainer(cfg, ck)
    _p, _o, cursor = t2.resume_state()
    assert (cursor.epoch, cursor.step_in_epoch) == (4, 0)
    assert cursor.global_step == 3  # anchored at the legacy index
    assert t2._last_ckpt_step == 3
    # a save one step into the resumed run is NOT silently dropped
    cursor.global_step += 1
    cursor.step_in_epoch = 1
    assert t2.save_state(_p, _o, cursor, wait=True) > 0
    assert CheckpointManager(ck).latest_step() == 4


def test_batches_fn_signature_variants():
    """The resume offset reaches ONLY parameters literally named
    start/start_batch (second positional or keyword-only); unrelated
    two-argument factories are never hijacked, and a required offset
    parameter works on fresh runs (skip=0)."""
    from quintnet_tpu.train.trainer import _call_batches_fn

    calls = []
    res = _call_batches_fn(lambda ep, start: calls.append((ep, start)), 1, 2)
    assert res[1] is True and calls == [(1, 2)]
    res = _call_batches_fn(lambda ep, start: calls.append((ep, start)), 1, 0)
    assert res[1] is True and calls[-1] == (1, 0)  # required 2nd positional

    def kw_only(ep, *, start_batch=0):
        calls.append(("kw", ep, start_batch))
    assert _call_batches_fn(kw_only, 2, 3)[1] is True
    assert calls[-1] == ("kw", 2, 3)

    # a second positional with an UNRELATED name keeps its default — the
    # offset must not silently hijack it (shuffle=2 would corrupt the run)
    def unrelated(ep, shuffle=True):
        calls.append(("un", ep, shuffle))
    assert _call_batches_fn(unrelated, 4, 2)[1] is False
    assert calls[-1] == ("un", 4, True)

    assert _call_batches_fn(lambda ep: calls.append(ep), 6, 7)[1] is False
    assert calls[-1] == 6


def test_goodput_aggregate_incomplete_run_counts_only_checkpointed():
    """A run that never completed: useful steps stop at the last
    CHECKPOINTED step, not the furthest step a killed attempt reached."""
    from quintnet_tpu.ft.goodput import aggregate

    attempts = [{"resumed_at": 0, "reached": 11, "steps_run": 11,
                 "wall_s": 0.0, "save_blocking_s": 0.0, "restore_s": 0.0,
                 "fallback_steps": 0, "completed": False,
                 "synthetic": True}]
    g = aggregate(attempts, wall_s=10.0, final_step=10)
    assert g["useful_steps"] == 10
    assert g["lost_steps"] == 1
    # completed attempts still win over final_step
    attempts.append({"resumed_at": 10, "reached": 12, "steps_run": 2,
                     "wall_s": 4.0, "save_blocking_s": 1.0,
                     "restore_s": 0.5, "fallback_steps": 0,
                     "completed": True})
    g = aggregate(attempts, wall_s=10.0, final_step=10)
    assert g["useful_steps"] == 12
    assert g["lost_steps"] == 1


# ---------------------------------------------------------------------------
# unit-level pieces


def test_cursor_roundtrip_json_exact():
    h = History(train_loss=[2.0, 1.5], val_loss=[1.8], val_metric=[0.5],
                wall_time_s=3.25, best_val_loss=1.8, best_epoch=0)
    c = TrainCursor(epoch=1, step_in_epoch=2, global_step=5,
                    loss_sum=2.5667000000000001, loss_count=2,
                    history=h, seed=7)
    back = TrainCursor.from_dict(json.loads(json.dumps(c.to_dict())))
    assert back == c
    assert TrainCursor.from_dict(None) is None
    # unknown keys from a newer writer are tolerated
    d = c.to_dict()
    d["future_field"] = 1
    assert TrainCursor.from_dict(d) == c


def test_cadence_controller_or_combination():
    c = CadenceController(0, 0.0)
    assert not c.enabled and not c.should_save(10**6)
    c = CadenceController(3, 0.0)
    assert not c.should_save(2)
    assert c.should_save(3)
    c.saved(3)
    assert not c.should_save(5) and c.should_save(6)
    # time leg fires independently of the step leg
    c = CadenceController(0, 10.0)
    assert c.enabled and not c.should_save(10**6)
    c._last_save_t -= 11
    assert c.should_save(1)


def test_chaos_from_env():
    env = {"QT_CHAOS": json.dumps({"kill_at_step": 7, "mode": "sigterm",
                                   "fail_restores": 2})}
    m = ChaosMonkey.from_env(env)
    assert (m.kill_at_step, m.mode, m.fail_restores) == (7, "sigterm", 2)
    assert ChaosMonkey.from_env({}) is None


def test_start_batch_matches_generic_skip():
    """The map-style start_batch= slice and the generic consume-and-
    discard skip yield the same remaining batch stream."""
    ds = _dataset()
    a = list(make_batches(ds, BATCH, seed=3, start_batch=2))
    b = list(skip_batches(make_batches(ds, BATCH, seed=3), 2))
    assert len(a) == len(b) == 1
    np.testing.assert_array_equal(a[0][0], b[0][0])
    np.testing.assert_array_equal(a[0][1], b[0][1])
    # skipping EXACTLY to the end is a legitimate epoch-end resume
    assert list(skip_batches(make_batches(ds, BATCH, seed=3), 3)) == []
    # skipping PAST the end means the data changed under the cursor —
    # loud failure, not a silent empty epoch
    with pytest.raises(ValueError, match="ended after 3"):
        skip_batches(make_batches(ds, BATCH, seed=3), 9)

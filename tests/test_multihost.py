"""Multi-host runtime test: a REAL 2-process jax.distributed run
(localhost coordinator, 4 virtual CPU devices per process, gloo
collectives) training dp4 x tp2 ViT with per-process data feeding, to
parity with the single-process result.

Reference analogue: torchrun rendezvous + DistributedSampler
(core/mesh.py:196-251, examples/full_3d.py:129-155) — which the
reference can only exercise on real multi-GPU hosts; here it runs in CI.
"""

import json
import os
import subprocess
import sys

import jax
import numpy as np
import optax
import pytest

from quintnet_tpu.models.vit import (
    ViTConfig,
    cross_entropy_loss,
    vit_apply,
    vit_init,
)

CFG = ViTConfig(image_size=14, patch_size=7, in_channels=1, hidden_dim=16,
                depth=4, num_heads=2, num_classes=10)
PORT = "12397"


def _single_process_reference():
    x = jax.random.normal(jax.random.key(1), (16, 14, 14, 1))
    y = jax.random.randint(jax.random.key(2), (16,), 0, 10)
    params = vit_init(jax.random.key(0), CFG)
    opt = optax.sgd(0.05)
    state = opt.init(params)

    def loss_fn(p):
        return cross_entropy_loss(vit_apply(p, x, CFG), y)

    losses = []
    for _ in range(2):
        loss, g = jax.value_and_grad(loss_fn)(params)
        upd, state = opt.update(g, state, params)
        params = optax.apply_updates(params, upd)
        losses.append(float(loss))
    sqsum = float(sum(np.sum(np.square(np.asarray(l)))
                      for l in jax.tree.leaves(params)))
    return losses, sqsum


def test_two_process_dp_tp_matches_single_process(tmp_path):
    ref_losses, ref_sqsum = _single_process_reference()

    env = dict(os.environ)
    # workers pick their own device count/platform; the conftest's
    # 8-device XLA flag and any axon pinning must not leak in
    env.pop("XLA_FLAGS", None)
    env.pop("JAX_PLATFORMS", None)
    env["PYTHONPATH"] = os.getcwd()

    worker = os.path.join(os.path.dirname(__file__), "_mp_worker.py")
    outs = [str(tmp_path / f"w{i}.json") for i in range(2)]
    procs = [
        subprocess.Popen(
            [sys.executable, worker, str(i), "2", PORT, outs[i]],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
        for i in range(2)
    ]
    logs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=540)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("multi-process workers timed out")
        logs.append(out.decode(errors="replace"))
    for i, p in enumerate(procs):
        assert p.returncode == 0, f"worker {i} failed:\n{logs[i][-4000:]}"

    for i in range(2):
        with open(outs[i]) as f:
            res = json.load(f)
        for mode in ("global", "local", "fsdp"):
            np.testing.assert_allclose(
                res[mode]["losses"], ref_losses, rtol=1e-5,
                err_msg=f"worker {i} mode {mode} losses")
            np.testing.assert_allclose(
                res[mode]["param_sqsum"], ref_sqsum, rtol=1e-5,
                err_msg=f"worker {i} mode {mode} params")

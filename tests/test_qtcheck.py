"""qtcheck golden tests: the static-analysis layer that pins QuintNet's
communication contracts (quintnet_tpu/analysis/).

- Collective-census goldens: the dp / tp / zero / 3D train steps and
  the serve prefill/decode programs must put EXACTLY the collectives
  the declarative specs (analysis/specs.py) derive from program
  structure on the wire — a single extra all-gather anywhere in
  parallel/ or serve/ fails these with a named per-axis diff.
- Recompile sentinel: the serve engine compiles exactly ONE prefill +
  ONE decode program across a mixed request trace (admissions,
  retirements, block growth, preemption), enforced at call time.
- Linter rules: each QT rule fires on a synthetic footgun snippet and
  respects pragmas.
- Baseline gate: the committed tools/qtcheck_baseline.json matches the
  tree EXACTLY (no new violations, no stale entries) — the same
  no-drift discipline tests/test_bench_stale.py applies to bench
  artifacts.
"""

import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from quintnet_tpu.analysis.jaxpr_audit import (collective_census,
                                               donation_report,
                                               dtype_report)
from quintnet_tpu.analysis.lint import (compare_baseline, lint_paths,
                                        lint_source, load_baseline,
                                        violations_to_baseline)
from quintnet_tpu.analysis.recompile import (RecompileError,
                                             RecompileSentinel)
from quintnet_tpu.analysis import specs as census_specs
from quintnet_tpu.core import collectives as cc
from quintnet_tpu.core.config import Config
from quintnet_tpu.models.vit import ViTConfig, vit_init, vit_model_spec
from quintnet_tpu.parallel.strategy import get_strategy

REPO = os.path.join(os.path.dirname(__file__), "..")

VIT = ViTConfig(image_size=14, patch_size=7, in_channels=1, hidden_dim=16,
                depth=4, num_heads=2, num_classes=10)


def _train_setup(mesh_dim, mesh_name, optimizer="adamw", **training):
    cfg = Config.from_dict({
        "mesh_dim": list(mesh_dim), "mesh_name": list(mesh_name),
        "training": {"batch_size": 8, "optimizer": optimizer, **training},
    })
    strat = get_strategy("auto", cfg)
    model = vit_model_spec(VIT)
    opt = optax.adamw(1e-3)
    params = strat.shard_params(model, vit_init(jax.random.key(0), VIT))
    state = strat.init_opt_state(model, opt, params)
    x = jax.random.normal(jax.random.key(1), (8, 14, 14, 1))
    y = jax.random.randint(jax.random.key(2), (8,), 0, 10)
    batch = strat.shard_batch((x, y), model)
    step = strat.make_train_step(model, opt)
    return strat, model, step, params, state, batch


N_LEAVES = len(jax.tree.leaves(vit_init(jax.random.key(0), VIT)))


# ---------------------------------------------------------------------
# collective-census goldens (train steps)
# ---------------------------------------------------------------------

class TestTrainStepCensus:
    def test_dp_exact_counts(self):
        """dp train step: one all_reduce per gradient leaf + the loss
        pmean, nothing else, dp axis only."""
        _, _, step, params, state, batch = _train_setup([2], ["dp"])
        census = collective_census(step, params, state, batch, 0)
        expect = census_specs.expected_dp_train_step(N_LEAVES)
        assert census.diff(expect) == [], census.as_dict()
        assert census.dynamic == 0

    def test_dp_tp_2axis_exact_counts(self):
        """2-axis dp x tp mesh: each axis sees exactly its own pattern
        — the composition adds no cross terms. This census walks the
        row-parallel psums of every block (nn/attention, nn/layers),
        the replicated-grad syncs, and the clip-norm psums."""
        strat, model, step, params, state, batch = _train_setup(
            [2, 2], ["dp", "tp"])
        n, n_repl, n_shard = census_specs.spec_leaf_counts(
            strat.param_specs(model), "tp")
        census = collective_census(step, params, state, batch, 0)
        expect = census_specs.expected_dp_tp_train_step(
            n, VIT.depth, n_repl, n_shard)
        assert census.diff(expect) == [], census.as_dict()

    def test_zero1_exact_counts(self):
        """ZeRO-1 = the dp census + exactly ONE all_gather (flat param
        re-assembly). If optimizer-state sharding ever started
        gathering per leaf, this pins it."""
        _, _, step, params, state, batch = _train_setup(
            [2], ["dp"], optimizer="zero1_adamw")
        census = collective_census(step, params, state, batch, 0)
        expect = census_specs.expected_zero1_train_step(N_LEAVES)
        assert census.diff(expect) == [], census.as_dict()

    def test_zero2_exact_counts(self):
        """ZeRO-2 collapses the per-leaf grad pmeans into ONE
        reduce_scatter — the halved-traffic contract, verified
        structurally rather than by wire measurements."""
        _, _, step, params, state, batch = _train_setup(
            [2], ["dp"], optimizer="zero2_adamw")
        census = collective_census(step, params, state, batch, 0)
        expect = census_specs.expected_zero2_train_step()
        assert census.diff(expect) == [], census.as_dict()

    def test_3d_1f1b_exact_counts(self):
        """Full 3D (dp x tp x pp, 1F1B): per-microbatch tp psums (incl.
        the recompute forward), stage-boundary ppermutes, pp grad
        syncs, dp leaf pmeans — all pinned per axis."""
        strat, model, step, params, state, batch = _train_setup(
            [2, 2, 2], ["dp", "tp", "pp"],
            gradient_accumulation_steps=2, schedule="1f1b")
        pspecs = strat.param_specs(model)
        _, tp_repl, tp_shard = census_specs.spec_leaf_counts(pspecs, "tp")
        _, pp_repl, pp_shard = census_specs.spec_leaf_counts(pspecs, "pp")
        census = collective_census(step, params, state, batch, 0)
        expect = census_specs.expected_3d_train_step(
            N_LEAVES, VIT.depth, tp_repl, tp_shard, pp_repl, pp_shard,
            n_micro=2, pp_size=2)
        assert census.diff(expect) == [], census.as_dict()
        assert census.dynamic == 0  # no while_loops in any train step


class TestTpLayerCensus:
    """Pin parallel/tp.py's layer functions DIRECTLY: these counts are
    what an extra collective inserted into column_parallel_linear /
    row_parallel_linear changes first."""

    def _mesh(self):
        return Mesh(np.array(jax.devices()[:2]), ("tp",))

    def _params(self):
        k = jax.random.key(0)
        pc = {"w": jax.random.normal(k, (8, 16)),
              "b": jnp.zeros((16,))}
        pr = {"w": jax.random.normal(k, (16, 8)),
              "b": jnp.zeros((8,))}
        x = jax.random.normal(k, (4, 8))
        return pc, pr, x

    def _specs(self):
        from quintnet_tpu.parallel.tp import column_spec, row_spec

        return (column_spec(stacked=False), row_spec(stacked=False))

    def test_column_row_forward_exactly_one_psum(self):
        """Megatron block pattern (column no-gather -> row psum): ONE
        all_reduce per forward, zero gathers."""
        from quintnet_tpu.parallel import tp

        cs, rs = self._specs()

        def fwd(pc, pr, x):
            h = tp.column_parallel_linear(pc, x, axis="tp")
            y = tp.row_parallel_linear(pr, h, axis="tp")
            return jnp.sum(y)

        f = cc.shard_map_fn(fwd, self._mesh(),
                            in_specs=(cs, rs, P(None)), out_specs=P())
        census = collective_census(f, *self._params())
        assert census.as_dict() == {"tp": {"all_reduce": 1}}, \
            census.as_dict()

    def test_column_row_grad_adds_exactly_one_psum(self):
        """value_and_grad doubles it (the transpose re-syncs the
        replicated cotangent): 2 all_reduce, still zero gathers."""
        from quintnet_tpu.parallel import tp

        cs, rs = self._specs()

        def loss(pc, pr, x):
            h = tp.column_parallel_linear(pc, x, axis="tp")
            y = tp.row_parallel_linear(pr, h, axis="tp")
            return jnp.sum(y)

        def vg(pc, pr, x):
            return jax.value_and_grad(loss, argnums=(0, 1))(pc, pr, x)

        f = cc.shard_map_fn(vg, self._mesh(),
                            in_specs=(cs, rs, P(None)),
                            out_specs=(P(), self._specs()))
        census = collective_census(f, *self._params())
        assert census.as_dict() == {"tp": {"all_reduce": 2}}, \
            census.as_dict()

    def test_gather_output_costs_one_all_gather_and_its_transpose(self):
        """column gather_output=True: +1 all_gather forward, and its
        autodiff transpose is a reduce_scatter in the backward — the
        exact comm signature of the gathered variant."""
        from quintnet_tpu.parallel import tp

        cs, _ = self._specs()

        def loss(pc, x):
            return jnp.sum(tp.column_parallel_linear(
                pc, x, axis="tp", gather_output=True))

        def vg(pc, x):
            return jax.value_and_grad(loss)(pc, x)

        f = cc.shard_map_fn(vg, self._mesh(),
                            in_specs=(cs, P(None)),
                            out_specs=(P(), cs))
        pc, _, x = self._params()
        census = collective_census(f, pc, x)
        assert census.as_dict() == {
            "tp": {"all_gather": 1, "reduce_scatter": 1}}, census.as_dict()


# ---------------------------------------------------------------------
# serve programs: census + the one-compiled-program invariant
# ---------------------------------------------------------------------

class TestServe:
    @pytest.fixture(scope="class")
    def gpt2(self):
        from quintnet_tpu.models.gpt2 import GPT2Config, gpt2_init

        cfg = GPT2Config.tiny(n_layer=2)
        return cfg, gpt2_init(jax.random.key(0), cfg)

    def _engine(self, cfg, params, mesh=None, **kw):
        from quintnet_tpu.serve import ServeEngine, gpt2_family

        kw.setdefault("max_slots", 3)
        kw.setdefault("block_size", 4)
        kw.setdefault("num_blocks", 24)
        kw.setdefault("max_seq_len", 32)
        return ServeEngine(gpt2_family(cfg), params, mesh=mesh, **kw)

    def _prefill_args(self, eng, params, bucket):
        # one bucket program's args: tail ids padded to the bucket
        # width, dynamic (start, t0) split, COW scalars
        ids = np.zeros((1, bucket), np.int32)
        row = np.zeros((eng.table_width,), np.int32)
        kp, vp = eng.pool.caches()
        return (params, kp, vp, jnp.asarray(ids), jnp.int32(1),
                jnp.int32(3), jnp.asarray(row), jnp.int32(0),
                jnp.int32(0), jnp.asarray(eng._key_data[0]))

    def _decode_args(self, eng, params):
        kp, vp = eng.pool.caches()
        return (params, kp, vp, jnp.asarray(eng._tok),
                jnp.asarray(eng._pos), jnp.asarray(eng._tables),
                jnp.asarray(eng._key_data))

    def test_single_device_census_is_collective_free(self, gpt2):
        cfg, params = gpt2
        eng = self._engine(cfg, params)
        cases = [(eng._prefills[b].fn,
                  self._prefill_args(eng, params, b),
                  census_specs.expected_serve_prefill(cfg.n_layer))
                 for b in eng.prefill_buckets]
        cases.append((eng._decode.fn, self._decode_args(eng, params),
                      census_specs.expected_serve_decode(cfg.n_layer)))
        for fn, args, spec in cases:
            census = collective_census(fn, *args)
            assert census.diff(spec) == [], census.as_dict()
            assert census.total() == 0

    def test_tp_census_two_psums_per_layer_every_bucket(self, gpt2):
        """Head-sharded serving: exactly 2 row-parallel psums per block
        per program (attention out-proj + MLP down-proj), nothing else
        — the engine's batching/paging/prefix-cache COW adds NO
        collectives, and EVERY prefill bucket width carries the same
        census (the bucket only changes a batch-like dim)."""
        cfg, params = gpt2
        mesh = Mesh(np.array(jax.devices()[:2]), ("tp",))
        eng = self._engine(cfg, params, mesh=mesh)
        assert len(eng.prefill_buckets) >= 2  # actually bucketed
        cases = [(eng._prefills[b].fn,
                  self._prefill_args(eng, params, b),
                  census_specs.expected_serve_prefill(cfg.n_layer,
                                                      tp_axis="tp"))
                 for b in eng.prefill_buckets]
        cases.append((eng._decode.fn, self._decode_args(eng, params),
                      census_specs.expected_serve_decode(cfg.n_layer,
                                                         tp_axis="tp")))
        for fn, args, spec in cases:
            census = collective_census(fn, *args)
            assert census.diff(spec) == [], census.as_dict()

    def test_pallas_census_matches_xla_per_backend(self, gpt2):
        """The attention-backend ladder (analysis/specs.attn_kernels)
        must not move a single collective: under tp the pallas decode
        program carries EXACTLY the xla decode census (2 row-parallel
        psums per layer — the kernel sits strictly inside the per-layer
        attention; a pallas_call has no collectives), for the
        passthrough f32 pool AND the scaled int8 one. A kernel that
        snuck a gather/psum into the wire would fail with a named
        diff."""
        from quintnet_tpu.analysis.specs import attn_kernels

        cfg, params = gpt2
        mesh = Mesh(np.array(jax.devices()[:2]), ("tp",))
        spec = census_specs.expected_serve_decode(cfg.n_layer,
                                                  tp_axis="tp")
        for kv_dtype in ("f32", "int8"):
            per_backend = {}
            for kernel in attn_kernels():
                eng = self._engine(cfg, params, mesh=mesh,
                                   kv_dtype=kv_dtype,
                                   attn_kernel=kernel)
                caches = eng.pool.caches()
                args = (params, *caches, jnp.asarray(eng._tok),
                        jnp.asarray(eng._pos), jnp.asarray(eng._tables),
                        jnp.asarray(eng._key_data))
                census = collective_census(eng._decode.fn, *args)
                assert census.diff(spec) == [], (kernel, kv_dtype,
                                                 census.as_dict())
                per_backend[kernel] = census.as_dict()
            assert per_backend["pallas"] == per_backend["xla"]

    def test_one_prefill_one_decode_across_mixed_trace(self, gpt2):
        """The PR 1 serving promise as a sentinel-enforced invariant:
        staggered arrivals, varying prompt lengths, retirements, block
        growth and a forced preemption all hit the SAME two compiled
        programs. A second lowering would raise RecompileError at the
        call that caused it."""
        cfg, params = gpt2
        # pool sized to force growth + preemption mid-trace
        eng = self._engine(cfg, params, max_slots=3, block_size=2,
                           num_blocks=12, max_seq_len=16)
        rng = np.random.default_rng(0)
        prompts = [np.asarray(rng.integers(0, cfg.vocab_size, (n,)),
                              np.int32) for n in (3, 5, 4, 6, 3)]
        arrivals = [0, 1, 2, 5, 8]
        submitted, step = 0, 0
        while submitted < len(prompts) or eng.has_work:
            while (submitted < len(prompts)
                   and arrivals[submitted] <= step):
                eng.submit(prompts[submitted], 5)
                submitted += 1
            eng.step()
            step += 1
            assert step < 500
        assert eng.metrics.finished == len(prompts)
        assert eng.compile_stats() == {"prefill": 1, "decode": 1}
        eng.assert_compile_count()  # raises with a diff on violation

    def test_donation_no_aliasable_misses(self, gpt2):
        """Every aliasable buffer of the serve programs is donated
        (pool caches, token rows, key state) in every prefill bucket:
        peak memory is paid once."""
        cfg, params = gpt2
        eng = self._engine(cfg, params)
        cases = [(eng._prefills[b].fn, self._prefill_args(eng, params, b))
                 for b in eng.prefill_buckets]
        cases.append((eng._decode.fn, self._decode_args(eng, params)))
        for fn, args in cases:
            rep = donation_report(fn, *args)
            assert rep.undonated_aliasable == [], rep.summary()
            assert rep.donated_bytes > 0

    def _spec_engine(self, cfg, params, mesh=None, **kw):
        from quintnet_tpu.serve import SpecConfig

        return self._engine(cfg, params, mesh=mesh, spec=SpecConfig(),
                            **kw)

    def _verify_args(self, eng, params, k):
        # one verify bucket's args: [S, k+1] token runs, per-row
        # (start, tail_len), full tables, per-row key state
        S = eng.max_slots
        kp, vp = eng.pool.caches()
        return (params, kp, vp,
                jnp.asarray(np.zeros((S, k + 1), np.int32)),
                jnp.asarray(np.zeros((S,), np.int32)),
                jnp.asarray(np.ones((S,), np.int32)),
                jnp.asarray(eng._tables), jnp.asarray(eng._key_data))

    def test_verify_census_matches_decode_every_bucket(self, gpt2):
        """The speculative verify programs (serve/spec.py) are the
        decode step widened to k+1 tokens per row: single-device they
        must be collective-free, under tp exactly the decode census —
        2 row-parallel psums per layer, nothing else, identical for
        EVERY draft-length bucket (the bucket only changes a
        batch-like dim; the draft scatter/gather adds no
        collectives)."""
        cfg, params = gpt2
        eng = self._engine(cfg, params)
        assert eng.compile_stats() == {"prefill": 0, "decode": 0}
        seng = self._spec_engine(cfg, params)
        assert tuple(seng._verifies) == seng.spec.buckets
        for k in seng.spec.buckets:
            census = collective_census(
                seng._verifies[k].fn, *self._verify_args(seng, params, k))
            spec = census_specs.expected_serve_verify(cfg.n_layer)
            assert census.diff(spec) == [], census.as_dict()
            assert census.total() == 0

        mesh = Mesh(np.array(jax.devices()[:2]), ("tp",))
        teng = self._spec_engine(cfg, params, mesh=mesh)
        for k in teng.spec.buckets:
            census = collective_census(
                teng._verifies[k].fn, *self._verify_args(teng, params, k))
            spec = census_specs.expected_serve_verify(cfg.n_layer,
                                                      tp_axis="tp")
            assert census.diff(spec) == [], census.as_dict()

    def test_verify_donation_no_aliasable_misses(self, gpt2):
        """Every verify bucket donates its aliasable buffers: the pool
        caches update in place and the [S, P] ids row aliases the
        candidate-token output (key_data does NOT alias — the chain
        output is [S, P, keysize], a different shape)."""
        cfg, params = gpt2
        eng = self._spec_engine(cfg, params)
        for k in eng.spec.buckets:
            rep = donation_report(eng._verifies[k].fn,
                                  *self._verify_args(eng, params, k))
            assert rep.undonated_aliasable == [], rep.summary()
            assert rep.donated_bytes > 0

    @pytest.mark.parametrize("sp", [2, 4])
    def test_sp_prefill_census_ppermutes_are_f_of_sp(self, gpt2, sp):
        """The ring sp-prefill programs (long-context serving,
        serve/longctx.py): per layer, the stacked chunk K/V pair and
        its position vector rotate sp scan steps (2*sp ppermutes) plus
        one all_gather reassembling the chunk for the pool scatter,
        plus ONE program-wide psum extracting the last position's
        hidden row — analysis/specs.expected_serve_sp_prefill, a pure
        function of (n_layers, sp), identical for EVERY bucket width
        (sp shards the bucket, it never changes the wire). An extra
        collective from a refactor fails here with a named diff. The
        decode program on the same mesh stays collective-FREE (it runs
        replicated)."""
        from quintnet_tpu.serve import ServeEngine, gpt2_family

        cfg, params = gpt2
        mesh = Mesh(np.array(jax.devices()[:sp]), ("sp",))
        eng = ServeEngine(gpt2_family(cfg), params, mesh=mesh,
                          sp_axis="sp", max_slots=3, block_size=4,
                          num_blocks=24, max_seq_len=32)
        assert eng.sp_axis == "sp"
        spec = census_specs.expected_serve_sp_prefill(cfg.n_layer, sp)
        for b in eng.prefill_buckets:
            census = collective_census(
                eng._prefills[b].fn, *self._prefill_args(eng, params, b))
            assert census.diff(spec) == [], census.as_dict()
            assert census.total() == 2 * sp * cfg.n_layer \
                + cfg.n_layer + 1
        dec = collective_census(eng._decode.fn,
                                *self._decode_args(eng, params))
        assert dec.total() == 0


# ---------------------------------------------------------------------
# MoE serving: expert-parallel collective census
# ---------------------------------------------------------------------

class TestServeMoE:
    """Census goldens for expert-parallel serving
    (analysis/specs.expected_serve_moe): under ep>1 every program kind
    — every prefill bucket, the decode step, every verify bucket —
    carries EXACTLY 2 all_to_alls per MoE layer (the nn/moe.py
    dispatch + combine) and nothing else on the ep axis; the
    capacity-bounded scatter/gather is local and the router
    replicated. ep=1 (and no mesh) is the dense-replicated program:
    ZERO collectives — the census face of the ep=1 == dense
    bit-identity contract. The dense families' own censuses are
    pinned by TestServe above; these goldens prove MoE adds all_to_all
    and ONLY all_to_all, and only on the ep axis."""

    _engine = TestServe._engine
    _spec_engine = TestServe._spec_engine
    _prefill_args = TestServe._prefill_args
    _decode_args = TestServe._decode_args
    _verify_args = TestServe._verify_args

    @pytest.fixture(scope="class")
    def gpt2(self):
        from quintnet_tpu.models.gpt2 import GPT2Config, gpt2_init

        cfg = GPT2Config.tiny(n_layer=2, n_experts=4, expert_top_k=2)
        return cfg, gpt2_init(jax.random.key(0), cfg)

    def test_ep_census_two_all_to_alls_per_moe_layer(self, gpt2):
        cfg, params = gpt2
        mesh = Mesh(np.array(jax.devices()[:2]), ("ep",))
        eng = self._spec_engine(cfg, params, mesh=mesh, ep_axis="ep")
        assert eng.ep_axis == "ep"
        spec = census_specs.expected_serve_moe(cfg.n_layer,
                                               ep_axis="ep")
        cases = [(eng._prefills[b].fn,
                  self._prefill_args(eng, params, b))
                 for b in eng.prefill_buckets]
        cases.append((eng._decode.fn, self._decode_args(eng, params)))
        cases.extend((eng._verifies[k].fn,
                      self._verify_args(eng, params, k))
                     for k in eng.spec.buckets)
        for fn, args in cases:
            census = collective_census(fn, *args)
            assert census.diff(spec) == [], census.as_dict()
            assert census.total() == 2 * cfg.n_layer

    def test_ep_times_tp_census_composes(self, gpt2):
        """ep x tp: the dense tp census (2 row-parallel psums per
        layer — the expert FFN's down-proj psum folds into the same
        count) PLUS the 2 per-layer ep all_to_alls, each axis
        accounted separately."""
        cfg, params = gpt2
        if len(jax.devices()) < 4:
            pytest.skip("needs 4 devices")
        mesh = Mesh(np.array(jax.devices()[:4]).reshape(2, 2),
                    ("ep", "tp"))
        eng = self._engine(cfg, params, mesh=mesh, ep_axis="ep")
        spec = census_specs.expected_serve_moe(cfg.n_layer,
                                               ep_axis="ep",
                                               tp_axis="tp")
        cases = [(eng._prefills[b].fn,
                  self._prefill_args(eng, params, b))
                 for b in eng.prefill_buckets]
        cases.append((eng._decode.fn, self._decode_args(eng, params)))
        for fn, args in cases:
            census = collective_census(fn, *args)
            assert census.diff(spec) == [], census.as_dict()

    def test_ep1_census_is_collective_free(self, gpt2):
        """A size-1 ep mesh nulls ep_axis at construction — the
        programs are the dense-replicated MoE math, zero collectives
        (expected_serve_moe with ep_axis=None)."""
        cfg, params = gpt2
        mesh = Mesh(np.array(jax.devices()[:1]), ("ep",))
        eng = self._engine(cfg, params, mesh=mesh, ep_axis="ep")
        assert eng.ep_axis is None
        assert census_specs.expected_serve_moe(cfg.n_layer) == {}
        for b in eng.prefill_buckets:
            census = collective_census(
                eng._prefills[b].fn, *self._prefill_args(eng, params, b))
            assert census.total() == 0

    def test_ep_donation_no_aliasable_misses(self, gpt2):
        """The widened MoE return (the trailing routing-stats dict)
        must not cost a donation: every aliasable buffer of every ep
        program is still donated."""
        cfg, params = gpt2
        mesh = Mesh(np.array(jax.devices()[:2]), ("ep",))
        eng = self._engine(cfg, params, mesh=mesh, ep_axis="ep")
        cases = [(eng._prefills[b].fn,
                  self._prefill_args(eng, params, b))
                 for b in eng.prefill_buckets]
        cases.append((eng._decode.fn, self._decode_args(eng, params)))
        for fn, args in cases:
            rep = donation_report(fn, *args)
            assert rep.undonated_aliasable == [], rep.summary()
            assert rep.donated_bytes > 0


# ---------------------------------------------------------------------
# serve programs: dtype-promotion census per KV layout policy
# ---------------------------------------------------------------------

class TestServeDtypeCensus:
    """The dtype_report goldens for the serving programs, pinned PER
    KV-POOL LAYOUT POLICY (serve/kv_quant.py): the f32/bf16 passthrough
    programs carry no silent f64 upcasts and no 16-bit accumulation
    (softmax and scores stay f32 — the engine's mixed-precision
    contract), and the scaled int8 / fake_quant programs — whose
    kernels now dequantize inside the gathered view and quantize on
    scatter — introduce NONE either: quant math accumulates in f32,
    int8 is storage only. A half-accum dot or accidental x64 in any
    policy's prefill/decode/verify fails here with the primitive
    named. The collective census is policy-invariant too (the scaled
    paths are local gather/scatter arithmetic)."""

    @pytest.fixture(scope="class")
    def gpt2(self):
        from quintnet_tpu.models.gpt2 import GPT2Config, gpt2_init

        cfg = GPT2Config.tiny(n_layer=2)
        return cfg, gpt2_init(jax.random.key(0), cfg)

    def _engine(self, cfg, params, kv_dtype, mesh=None, **kw):
        from quintnet_tpu.serve import ServeEngine, SpecConfig, gpt2_family

        kw.setdefault("max_slots", 3)
        kw.setdefault("block_size", 4)
        kw.setdefault("num_blocks", 24)
        kw.setdefault("max_seq_len", 32)
        kw.setdefault("spec", SpecConfig())
        return ServeEngine(gpt2_family(cfg), params, mesh=mesh,
                           kv_dtype=kv_dtype, **kw)

    def _cases(self, eng, params):
        """(fn, args) for one bucket of each program family."""
        b = eng.prefill_buckets[0]
        k = eng.spec.buckets[0]
        S = eng.max_slots
        pools = eng.pool.caches()
        prefill = (params, *pools, jnp.zeros((1, b), jnp.int32),
                   jnp.int32(1), jnp.int32(3),
                   jnp.zeros((eng.table_width,), jnp.int32),
                   jnp.int32(0), jnp.int32(0),
                   jnp.asarray(eng._key_data[0]))
        decode = (params, *pools, jnp.asarray(eng._tok),
                  jnp.asarray(eng._pos), jnp.asarray(eng._tables),
                  jnp.asarray(eng._key_data))
        verify = (params, *pools,
                  jnp.zeros((S, k + 1), jnp.int32),
                  jnp.zeros((S,), jnp.int32), jnp.ones((S,), jnp.int32),
                  jnp.asarray(eng._tables), jnp.asarray(eng._key_data))
        return [(eng._prefills[b].fn, prefill),
                (next(iter(eng._decodes.values())).fn, decode),
                (eng._verifies[k].fn, verify)]

    @pytest.mark.parametrize("kv_dtype",
                             ["f32", "bf16", "int8", "fake_quant"])
    def test_dtype_census_clean_every_policy(self, gpt2, kv_dtype):
        cfg, params = gpt2
        eng = self._engine(cfg, params, kv_dtype)
        assert eng.kv_policy.name == kv_dtype
        for fn, args in self._cases(eng, params):
            issues = dtype_report(fn, *args)
            assert issues == [], (kv_dtype, [i.detail for i in issues])

    def test_int8_tp_collective_census_unchanged(self, gpt2):
        """Quantization adds NO collectives: the int8 programs under
        tp=2 carry exactly the f32 census — 2 row-parallel psums per
        block, nothing for the scales (they shard with the heads and
        dequant/requant is rank-local)."""
        cfg, params = gpt2
        mesh = Mesh(np.array(jax.devices()[:2]), ("tp",))
        eng = self._engine(cfg, params, "int8", mesh=mesh)
        specs = [census_specs.expected_serve_prefill(cfg.n_layer,
                                                     tp_axis="tp"),
                 census_specs.expected_serve_decode(cfg.n_layer,
                                                    tp_axis="tp"),
                 census_specs.expected_serve_verify(cfg.n_layer,
                                                    tp_axis="tp")]
        for (fn, args), spec in zip(self._cases(eng, params), specs):
            census = collective_census(fn, *args)
            assert census.diff(spec) == [], census.as_dict()

    def test_int8_single_device_collective_free(self, gpt2):
        cfg, params = gpt2
        eng = self._engine(cfg, params, "int8")
        for fn, args in self._cases(eng, params):
            assert collective_census(fn, *args).total() == 0

    def test_scaled_programs_donate_scales(self, gpt2):
        """The scale arrays update in place every step — they must be
        donated like the pools (no aliasable misses in any scaled
        program)."""
        cfg, params = gpt2
        eng = self._engine(cfg, params, "int8")
        for fn, args in self._cases(eng, params):
            rep = donation_report(fn, *args)
            assert rep.undonated_aliasable == [], rep.summary()


# ---------------------------------------------------------------------
# serve programs: dtype-promotion census per WEIGHT layout policy
# ---------------------------------------------------------------------

class TestWeightDtypeCensus:
    """The same census ladder for the packed-weight policies
    (serve/weight_quant.py): the int8/fp8 programs dequantize inside
    the serving matmuls (nn/layers.quantized_matmul upcasts the packed
    operand, dots in f32, applies the per-channel scale after), so no
    policy may introduce a half-accum dot or a silent x64 — the int8
    storage is NOT an accumulation dtype. The collective census is
    weight-policy-invariant too: under tp the w_scale leaves shard
    with their columns (augment_weight_specs) and the per-column
    multiply is rank-local, so the scaled programs carry exactly the
    f32 census and the single-device programs stay collective-free."""

    @pytest.fixture(scope="class")
    def gpt2(self):
        from quintnet_tpu.models.gpt2 import GPT2Config, gpt2_init

        cfg = GPT2Config.tiny(n_layer=2)
        return cfg, gpt2_init(jax.random.key(0), cfg)

    def _engine(self, cfg, params, weights_dtype, mesh=None, **kw):
        from quintnet_tpu.serve import ServeEngine, SpecConfig, gpt2_family

        kw.setdefault("max_slots", 3)
        kw.setdefault("block_size", 4)
        kw.setdefault("num_blocks", 24)
        kw.setdefault("max_seq_len", 32)
        kw.setdefault("spec", SpecConfig())
        return ServeEngine(gpt2_family(cfg), params, mesh=mesh,
                           weights_dtype=weights_dtype, **kw)

    # same program surface as the KV census — but invoked with the
    # engine's own (policy-packed) param tree
    _cases = TestServeDtypeCensus._cases

    @pytest.mark.parametrize("weights_dtype", [
        "f32", "bf16", "int8",
        pytest.param("fp8", marks=pytest.mark.skipif(
            not hasattr(jnp, "float8_e4m3fn"),
            reason="no float8_e4m3fn in this jax")),
        "fake_quant"])
    def test_dtype_census_clean_every_policy(self, gpt2, weights_dtype):
        cfg, params = gpt2
        eng = self._engine(cfg, params, weights_dtype)
        assert eng.weight_policy.name == weights_dtype
        for fn, args in self._cases(eng, eng.params):
            issues = dtype_report(fn, *args)
            assert issues == [], (weights_dtype,
                                  [i.detail for i in issues])

    def test_int8_tp_collective_census_unchanged(self, gpt2):
        """Packed weights add NO collectives under tp=2: the programs
        carry exactly the f32 census (row-parallel psums per block,
        nothing for the w_scale leaves)."""
        cfg, params = gpt2
        from quintnet_tpu.models.gpt2 import gpt2_to_tp_layout

        mesh = Mesh(np.array(jax.devices()[:2]), ("tp",))
        tp_params = gpt2_to_tp_layout(params, cfg, 2)
        eng = self._engine(cfg, tp_params, "int8", mesh=mesh)
        specs = [census_specs.expected_serve_prefill(cfg.n_layer,
                                                     tp_axis="tp"),
                 census_specs.expected_serve_decode(cfg.n_layer,
                                                    tp_axis="tp"),
                 census_specs.expected_serve_verify(cfg.n_layer,
                                                    tp_axis="tp")]
        for (fn, args), spec in zip(self._cases(eng, eng.params),
                                    specs):
            census = collective_census(fn, *args)
            assert census.diff(spec) == [], census.as_dict()

    def test_int8_single_device_collective_free(self, gpt2):
        cfg, params = gpt2
        eng = self._engine(cfg, params, "int8")
        for fn, args in self._cases(eng, eng.params):
            assert collective_census(fn, *args).total() == 0

    def test_packed_programs_keep_pool_donation(self, gpt2):
        """Packing the weights must not disturb the donation story:
        the KV pools still alias in place, and the packed w/w_scale
        leaves (read-only params) are correctly NOT aliasable."""
        cfg, params = gpt2
        eng = self._engine(cfg, params, "int8")
        for fn, args in self._cases(eng, eng.params):
            rep = donation_report(fn, *args)
            assert rep.undonated_aliasable == [], rep.summary()


# ---------------------------------------------------------------------
# recompile sentinel unit behaviour
# ---------------------------------------------------------------------

class TestRecompileSentinel:
    def test_counts_distinct_abstract_signatures(self):
        s = RecompileSentinel("t", jax.jit(lambda x: x + 1))
        s(jnp.zeros((2,)))
        s(jnp.ones((2,)))              # same signature
        assert s.compile_count == 1
        s(jnp.zeros((3,)))             # new shape
        assert s.compile_count == 2
        s(jnp.zeros((2,), jnp.int32))  # new dtype
        assert s.compile_count == 3

    def test_max_compiles_raises_before_dispatch_with_diff(self):
        calls = []
        s = RecompileSentinel("t", lambda x: calls.append(1),
                              max_compiles=1)
        s(jnp.zeros((2,)))
        with pytest.raises(RecompileError, match=r"float32\[2\]"):
            s(jnp.zeros((4,)))
        assert len(calls) == 1  # the violating call never dispatched

    def test_assert_compile_count(self):
        s = RecompileSentinel("t", lambda x: x)
        s(jnp.zeros((2,)))
        s.assert_compile_count(1)
        with pytest.raises(RecompileError, match="expected 2"):
            s.assert_compile_count(2)

    def test_trainer_step_is_wrapped(self):
        """Trainer wires its step through the sentinel: one lowering for
        a constant-shape loop, count visible for assertion."""
        from quintnet_tpu.train.trainer import Trainer

        cfg = Config.from_dict({
            "mesh_dim": [2], "mesh_name": ["dp"],
            "training": {"batch_size": 8, "epochs": 1}})
        trainer = Trainer(cfg, vit_model_spec(VIT))
        params, state = trainer.init_state()
        x = np.zeros((8, 14, 14, 1), np.float32)
        y = np.zeros((8,), np.int64)
        hist = trainer.fit(lambda ep: [(x, y)] * 2)
        assert len(hist.train_loss) == 1
        trainer.assert_compile_count(steps=1)


# ---------------------------------------------------------------------
# dtype report
# ---------------------------------------------------------------------

class TestDtypeReport:
    def test_flags_f64_upcast(self):
        from jax.experimental import enable_x64

        def f(x):
            return jnp.sum(x.astype(jnp.float64))

        with enable_x64():
            issues = dtype_report(f, jnp.zeros((4,), jnp.float32))
        assert any(i.kind == "f64-upcast" for i in issues), issues

    def test_flags_half_precision_accumulation(self):
        def f(a, b):
            return jnp.dot(a, b)  # bf16 x bf16 -> accumulates in bf16

        issues = dtype_report(f, jnp.zeros((4, 4), jnp.bfloat16),
                              jnp.zeros((4, 4), jnp.bfloat16))
        assert any(i.kind == "half-accum"
                   and i.primitive == "dot_general" for i in issues)

    def test_clean_with_f32_accumulation(self):
        """The mixed-precision recipe — bf16 operands, f32 accumulate —
        passes (and jnp.sum upcasts 16-bit reductions by itself)."""
        def f(a, b):
            return (jnp.dot(a, b, preferred_element_type=jnp.float32),
                    jnp.sum(a, axis=0))

        assert dtype_report(f, jnp.zeros((4, 4), jnp.bfloat16),
                            jnp.zeros((4, 4), jnp.bfloat16)) == []

    def test_train_step_is_clean(self):
        """The shipped dp train step neither upcasts to f64 nor
        accumulates in 16-bit."""
        _, _, step, params, state, batch = _train_setup([2], ["dp"])
        assert dtype_report(step, params, state, batch, 0) == []


# ---------------------------------------------------------------------
# donation report
# ---------------------------------------------------------------------

class TestDonationReport:
    def test_flags_undonated_train_state(self):
        opt = optax.sgd(1e-2)

        def step(p, s, g):
            u, s = opt.update(g, s, p)
            return optax.apply_updates(p, u), s

        p = {"w": jnp.zeros((32, 32))}
        s = opt.init(p)
        rep = donation_report(jax.jit(step), p, s, p)
        assert rep.undonated_aliasable, rep.summary()

        rep2 = donation_report(jax.jit(step, donate_argnums=(0, 1)),
                               p, s, p)
        # donated params claim the only (32, 32) output slot; the grads
        # arg has nowhere left to alias -> nothing is flagged
        assert rep2.undonated_aliasable == [], rep2.summary()

    def test_parallel_train_step_donates_params_and_opt(self):
        """The inner jit of make_parallel_train_step donates params and
        opt_state — the auditor confirms no aliasable leaf outside the
        batch is left undonated."""
        _, _, step, params, state, batch = _train_setup([2], ["dp"])
        step(params, state, batch, 0)  # materialise compiled["fn"]
        # params/opt were donated by that call; rebuild fresh ones
        _, _, _, params, state, batch = _train_setup([2], ["dp"])


# ---------------------------------------------------------------------
# linter rules (synthetic snippets)
# ---------------------------------------------------------------------

SNIPPET_JIT_NP = """
import jax, numpy as np

@jax.jit
def f(x):
    y = np.random.normal(size=3)
    z = np.asarray(x)
    return x + y.sum() + z
"""

SNIPPET_SHARD_MAP = """
import numpy as np
from quintnet_tpu.core import collectives as cc

def local_step(p, b):
    noise = np.random.normal(size=3)
    return p + noise.sum()

step = cc.shard_map_fn(local_step, None, in_specs=(), out_specs=())
"""

SNIPPET_TRACER_BRANCH = """
import jax, jax.numpy as jnp

@jax.jit
def f(x):
    if jnp.any(x > 0):
        return x
    return -x
"""

SNIPPET_HOST_SYNC = """
def run(step_fn, params, batches):
    losses = []
    for b in batches:
        params, loss = step_fn(params, b)
        losses.append(float(loss))
    return losses
"""

SNIPPET_MUTABLE_DEFAULT = """
import numpy as np

def f(x, acc=[], table=np.zeros(4)):
    acc.append(x)
    return table
"""

SNIPPET_TIMING = """
import time

def bench(step, params, b):
    t0 = time.perf_counter()
    for _ in range(10):
        out = step(params, b)
    return time.perf_counter() - t0
"""

SNIPPET_TIMING_OK = """
import time, jax

def bench(step, params, b):
    t0 = time.perf_counter()
    for _ in range(10):
        out = step(params, b)
    jax.block_until_ready(out)
    return time.perf_counter() - t0
"""


class TestLintRules:
    def _rules(self, src):
        return {v.rule for v in lint_source(src, "x.py")}

    def test_np_and_rng_in_jit(self):
        rules = self._rules(SNIPPET_JIT_NP)
        assert "QT102" in rules  # np.random.normal
        assert "QT101" in rules  # np.asarray

    def test_function_passed_to_shard_map_is_traced(self):
        assert "QT102" in self._rules(SNIPPET_SHARD_MAP)

    def test_tracer_branch(self):
        assert "QT103" in self._rules(SNIPPET_TRACER_BRANCH)

    def test_host_sync_in_step_loop(self):
        assert "QT104" in self._rules(SNIPPET_HOST_SYNC)

    def test_float_outside_step_loop_not_flagged(self):
        src = "def f(x):\n    return float(x)\n"
        assert self._rules(src) == set()

    def test_mutable_and_array_defaults(self):
        vs = [v for v in lint_source(SNIPPET_MUTABLE_DEFAULT, "x.py")
              if v.rule == "QT105"]
        assert len(vs) == 2  # the list AND the np.zeros default

    def test_timing_without_sync_flagged_with_sync_clean(self):
        assert "QT106" in self._rules(SNIPPET_TIMING)
        assert "QT106" not in self._rules(SNIPPET_TIMING_OK)

    def test_pragma_suppresses_specific_rule(self):
        src = SNIPPET_HOST_SYNC.replace(
            "losses.append(float(loss))",
            "losses.append(float(loss))  # qtcheck: ok[QT104]")
        assert "QT104" not in self._rules(src)
        # a pragma for a DIFFERENT rule does not suppress
        src2 = SNIPPET_HOST_SYNC.replace(
            "losses.append(float(loss))",
            "losses.append(float(loss))  # qtcheck: ok[QT106]")
        assert "QT104" in self._rules(src2)

    def test_host_math_float_not_flagged(self):
        src = ("import numpy as np\n"
               "def run(step_fn, xs):\n"
               "    for x in xs:\n"
               "        step_fn(x)\n"
               "        y = float(np.exp(1.0))\n")
        assert self._rules(src) == set()


# ---------------------------------------------------------------------
# baseline gate (tier-1 CI): committed baseline == tree, exactly
# ---------------------------------------------------------------------

class TestBaselineGate:
    BASELINE = os.path.join(REPO, "tools", "qtcheck_baseline.json")

    def test_lint_baseline_gate(self):
        """THE gate: zero new violations, zero stale entries. Mirrors
        tests/test_bench_stale.py — the committed file cannot drift
        from the tree in either direction."""
        violations = lint_paths(["quintnet_tpu", "tools", "bench.py"],
                                root=REPO)
        baseline = load_baseline(self.BASELINE)
        new, stale = compare_baseline(violations, baseline)
        assert new == [], "\n".join(new)
        assert stale == [], "\n".join(stale)

    def test_baseline_entries_all_carry_notes(self):
        """Every grandfathered violation must say WHY it is allowed —
        a baseline without justifications is just a mute button."""
        baseline = load_baseline(self.BASELINE)
        missing = [e for e in baseline["violations"] if not e.get("note")]
        assert missing == [], missing

    def test_cli_gate_passes(self):
        """The exact command CI documents:
        python -m quintnet_tpu.tools.qtcheck --baseline
        tools/qtcheck_baseline.json."""
        from quintnet_tpu.tools.qtcheck import main

        rc = main(["--baseline", self.BASELINE, "--root", REPO])
        assert rc == 0

    def test_cli_detects_new_violation(self, tmp_path, capsys):
        """A fresh footgun in a linted file fails the gate (exit 1) and
        is reported as NEW."""
        from quintnet_tpu.tools.qtcheck import main

        bad = tmp_path / "pkg"
        bad.mkdir()
        (bad / "mod.py").write_text(SNIPPET_JIT_NP)
        rc = main([str(bad), "--root", str(tmp_path),
                   "--baseline", self.BASELINE])
        assert rc == 1
        assert "NEW" in capsys.readouterr().out

    def test_stale_baseline_fails(self, tmp_path):
        """Fixing a legacy violation without regenerating the baseline
        fails the gate — the staleness half of the discipline."""
        import json

        stale_base = violations_to_baseline([])
        stale_base["violations"] = [{
            "rule": "QT106", "path": "nonexistent.py",
            "symbol": "gone", "count": 1, "line": 1}]
        p = tmp_path / "base.json"
        p.write_text(json.dumps(stale_base))
        clean = tmp_path / "pkg"
        clean.mkdir()
        (clean / "ok.py").write_text("x = 1\n")
        from quintnet_tpu.tools.qtcheck import main

        rc = main([str(clean), "--root", str(tmp_path),
                   "--baseline", str(p)])
        assert rc == 1

"""LoRA contracts: zero-init identity, adapter-only training, sharding
spec derivation, merged export. (No reference analogue — full-weight
finetuning only there; these pin the upgrade's semantics.)
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from quintnet_tpu.models.gpt2 import (GPT2Config, clm_loss, gpt2_apply,
                                      gpt2_init)
from quintnet_tpu.models.lora import (LoRAConfig, lora_init,
                                      lora_merge_tree, lora_param_count,
                                      lora_partition_specs, lora_wrap)

CFG = GPT2Config.tiny()
LCFG = LoRAConfig(rank=4, alpha=8.0)


@pytest.fixture(scope="module")
def base():
    params = gpt2_init(jax.random.key(0), CFG)
    ids = jnp.asarray(np.random.default_rng(0).integers(
        0, CFG.vocab_size, size=(2, 16), dtype=np.int32))
    return params, ids


@pytest.mark.fast
def test_zero_init_is_identity(base):
    params, ids = base
    lora = lora_init(jax.random.key(1), params["blocks"], LCFG)
    merged = lora_merge_tree(params, lora, LCFG)
    np.testing.assert_allclose(gpt2_apply(merged, ids, CFG),
                               gpt2_apply(params, ids, CFG),
                               rtol=1e-6, atol=1e-6)


def test_adapter_shapes_and_count(base):
    params, _ = base
    lora = lora_init(jax.random.key(1), params["blocks"], LCFG)
    # qkv, attn.proj, mlp.fc, mlp.proj adapted in every stacked layer
    q = lora["attn"]["qkv"]
    assert q["a"].shape == (CFG.n_layer, CFG.n_embd, 4)
    assert q["b"].shape == (CFG.n_layer, 4, 3 * CFG.n_embd)
    assert (q["b"] == 0).all()
    n_base = sum(int(np.prod(l.shape)) for l in jax.tree.leaves(params))
    assert lora_param_count(lora) < 0.2 * n_base


def test_lora_training_moves_only_adapters(base):
    params, ids = base
    lora = lora_init(jax.random.key(1), params["blocks"], LCFG)
    fwd = lora_wrap(lambda p, i: gpt2_apply(p, i, CFG), params, LCFG)
    opt = optax.adam(1e-2)
    state = opt.init(lora)

    @jax.jit
    def step(lora, state):
        loss, g = jax.value_and_grad(
            lambda l: clm_loss(fwd(l, ids), ids))(lora)
        up, state = opt.update(g, state, lora)
        return optax.apply_updates(lora, up), state, loss

    l0 = None
    for _ in range(10):
        lora, state, loss = step(lora, state)
        l0 = l0 if l0 is not None else float(loss)
    assert float(loss) < l0  # adapters alone reduce the loss
    # b moved off zero; base params untouched by construction
    assert float(jnp.abs(lora["attn"]["qkv"]["b"]).max()) > 0.0


def test_partition_specs_follow_weight_sharding():
    from jax.sharding import PartitionSpec as P

    from quintnet_tpu.parallel.tp import block_specs

    bspecs = block_specs(tp_axis="tp", stacked=True)
    specs = lora_partition_specs(bspecs, LCFG)
    # qkv is column-parallel (out sharded) -> b carries tp on out
    assert specs["attn"]["qkv"]["a"] == P(None, None, None)
    assert specs["attn"]["qkv"]["b"] == P(None, None, "tp")
    # attn.proj is row-parallel (in sharded) -> a carries tp on in
    assert specs["attn"]["proj"]["a"] == P(None, "tp", None)
    assert specs["attn"]["proj"]["b"] == P(None, None, None)


def test_merged_model_generates(base):
    params, _ = base
    from quintnet_tpu.models.gpt2_generate import gpt2_generate

    lora = lora_init(jax.random.key(2), params["blocks"], LCFG)
    merged = lora_merge_tree(params, lora, LCFG)
    out = gpt2_generate(merged, np.zeros((1, 4), np.int32), CFG,
                        max_new_tokens=2)
    assert out.shape == (1, 6)


@pytest.mark.fast
def test_lora_save_load_roundtrip(base, tmp_path):
    from quintnet_tpu.models.lora import load_lora, save_lora

    params, _ = base
    lora = lora_init(jax.random.key(3), params["blocks"], LCFG)
    p = str(tmp_path / "adapters.safetensors")
    save_lora(lora, LCFG, p)
    back, cfg2 = load_lora(p)
    assert cfg2 == LCFG
    jax.tree.map(lambda a, b: np.testing.assert_array_equal(a, b),
                 lora, back)


@pytest.mark.fast
def test_lora_roundtrip_golden_dtypes_and_llama_targets(tmp_path):
    """The serving registry's input contract: save_lora/load_lora is a
    TREE-equal, CONFIG-equal round trip — non-f32 factors keep their
    dtype (a bf16-trained adapter must not silently upcast on reload)
    and the full LLAMA_TARGETS name set survives the metadata
    comma-join."""
    from quintnet_tpu.models.llama import LlamaConfig, llama_init
    from quintnet_tpu.models.lora import (LLAMA_TARGETS, load_lora,
                                          save_lora)

    lcfg_m = LlamaConfig.tiny()
    params = llama_init(jax.random.key(0), lcfg_m, dtype=jnp.bfloat16)
    cfg = LoRAConfig(rank=2, alpha=4.0, targets=LLAMA_TARGETS)
    lora = lora_init(jax.random.key(1), params["blocks"], cfg)
    # make b non-trivial so equality is a real check, keep bf16
    lora = jax.tree.map(
        lambda l: (l + jax.random.normal(jax.random.key(7), l.shape,
                                         l.dtype) * 0.1).astype(l.dtype),
        lora)
    p = str(tmp_path / "llama_adapters.safetensors")
    save_lora(lora, cfg, p)
    back, cfg2 = load_lora(p)

    assert cfg2 == cfg                      # rank, alpha AND targets
    assert cfg2.targets == LLAMA_TARGETS
    flat_a = jax.tree_util.tree_leaves_with_path(lora)
    flat_b = jax.tree_util.tree_leaves_with_path(back)
    assert [k for k, _ in flat_a] == [k for k, _ in flat_b]  # tree-equal
    for (_, a), (_, b) in zip(flat_a, flat_b):
        assert b.dtype == jnp.bfloat16     # dtype preserved
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.fast
def test_lora_config_validation():
    """Construction-time rejection: rank < 1 is meaningless, and a
    target name containing ',' would be silently split into phantom
    targets by the save_lora metadata comma-join on reload."""
    with pytest.raises(ValueError, match="rank"):
        LoRAConfig(rank=0)
    with pytest.raises(ValueError, match="rank"):
        LoRAConfig(rank=-3)
    with pytest.raises(ValueError, match=","):
        LoRAConfig(targets=("qkv", "fc,proj"))
    with pytest.raises(ValueError, match="non-empty"):
        LoRAConfig(targets=())
    LoRAConfig(rank=1)  # the minimum is legal


def test_tp_shard_local_merge_matches_single_device(base):
    """The module docstring's claim: with lora_partition_specs, merging
    INSIDE shard_map is exact — no collectives — for column- and
    row-parallel targets. (qkv excluded here: its tp-blocked layout
    permutes columns, so adapters trained in that layout stay in it.)"""
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    from quintnet_tpu.core import collectives as cc
    from quintnet_tpu.core.mesh import mesh_from_sizes
    from quintnet_tpu.models.gpt2 import (gpt2_forward, gpt2_partition_specs,
                                          gpt2_to_tp_layout)
    from quintnet_tpu.models.lora import lora_merge_blocks
    from quintnet_tpu.parallel.tp import block_specs

    params, ids = base
    lcfg = LoRAConfig(rank=4, alpha=8.0, targets=("proj", "fc"))
    lora = lora_init(jax.random.key(5), params["blocks"], lcfg)
    # make the adapters non-trivial (b is zero-init)
    lora = jax.tree.map(
        lambda l: l + 0.01 * jax.random.normal(jax.random.key(6), l.shape),
        lora)

    ref = gpt2_apply(lora_merge_tree(params, lora, lcfg), ids, CFG)

    mesh = mesh_from_sizes(tp=2)
    specs = gpt2_partition_specs(CFG, tp_axis="tp")
    lspecs = lora_partition_specs(block_specs(tp_axis="tp", stacked=True),
                                  lcfg)
    base_tp = gpt2_to_tp_layout(params, CFG, 2)

    def local_fwd(p, l, ids):
        merged = {**p, "blocks": lora_merge_blocks(p["blocks"], l, lcfg)}
        logits, _ = gpt2_forward(merged, ids, CFG, tp_axis="tp")
        return logits

    fwd = jax.jit(cc.shard_map_fn(
        local_fwd, mesh, in_specs=(specs, lspecs, P()), out_specs=P()))
    out = fwd(base_tp, lora, jnp.asarray(ids))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=1e-5)


def test_lora_on_llama_family():
    """The adapter walker is name-based, so the same LoRA machinery
    trains Llama blocks (q/v targets, classic LoRA) untouched."""
    from quintnet_tpu.models.llama import (LlamaConfig, llama_apply,
                                           llama_init)
    from quintnet_tpu.models.lora import LLAMA_ATTN_TARGETS

    lcfg = LlamaConfig.tiny()
    params = llama_init(jax.random.key(0), lcfg)
    lora_cfg = LoRAConfig(rank=2, alpha=4.0, targets=LLAMA_ATTN_TARGETS)
    lora = lora_init(jax.random.key(1), params["blocks"], lora_cfg)
    assert set(lora["attn"]) == {"q", "v"}

    ids = jnp.asarray(np.random.default_rng(0).integers(
        0, lcfg.vocab_size, (2, 8), dtype=np.int32))
    merged = lora_merge_tree(params, lora, lora_cfg)
    np.testing.assert_allclose(  # b zero-init -> identity
        np.asarray(llama_apply(merged, ids, lcfg)),
        np.asarray(llama_apply(params, ids, lcfg)), rtol=1e-6, atol=1e-6)

    import optax

    fwd = lora_wrap(lambda p, i: llama_apply(p, i, lcfg), params, lora_cfg)
    from quintnet_tpu.models.gpt2 import clm_loss

    opt = optax.adam(1e-2)
    state = opt.init(lora)

    @jax.jit
    def step(lora, state):
        loss, g = jax.value_and_grad(
            lambda l: clm_loss(fwd(l, ids), ids))(lora)
        up, state = opt.update(g, state, lora)
        return optax.apply_updates(lora, up), state, loss

    l0 = None
    for _ in range(8):
        lora, state, loss = step(lora, state)
        l0 = l0 if l0 is not None else float(loss)
    assert float(loss) < l0


def test_lora_on_vit():
    """Third family: the same adapters train ViT blocks (qkv/proj/fc
    names match) — one LoRA implementation, every model."""
    from quintnet_tpu.models.vit import (ViTConfig, cross_entropy_loss,
                                         vit_apply, vit_init)

    vcfg = ViTConfig(image_size=14, patch_size=7, in_channels=1,
                     hidden_dim=16, depth=2, num_heads=2, num_classes=10)
    params = vit_init(jax.random.key(0), vcfg)
    lcfg = LoRAConfig(rank=2, alpha=4.0)
    lora = lora_init(jax.random.key(1), params["blocks"], lcfg)
    x = jnp.asarray(np.random.default_rng(0).normal(
        size=(4, 14, 14, 1)), jnp.float32)
    merged = lora_merge_tree(params, lora, lcfg)
    np.testing.assert_allclose(  # zero-init identity
        np.asarray(vit_apply(merged, x, vcfg)),
        np.asarray(vit_apply(params, x, vcfg)), rtol=1e-6, atol=1e-6)


def test_sharded_lora_training_matches_single_device(base):
    """make_lora_train_step on a dp x tp mesh: 3 adapter-only steps
    must match single-device LoRA training (same data, same init)."""
    import optax
    from jax.sharding import PartitionSpec as P

    from quintnet_tpu.core.mesh import mesh_from_sizes
    from quintnet_tpu.models.gpt2 import (clm_loss, gpt2_forward,
                                          gpt2_partition_specs,
                                          gpt2_to_tp_layout)
    from quintnet_tpu.models.lora import (lora_merge_blocks,
                                          make_lora_train_step)
    from quintnet_tpu.parallel.tp import block_specs
    from quintnet_tpu.parallel.train_step import shard_pytree

    params, ids = base
    lcfg = LoRAConfig(rank=4, alpha=8.0, targets=("proj", "fc"))
    lora0 = lora_init(jax.random.key(11), params["blocks"], lcfg)
    opt = optax.adam(1e-2)
    ids_j = jnp.asarray(ids)

    # single-device reference
    fwd = lora_wrap(lambda p, i: gpt2_apply(p, i, CFG), params, lcfg)
    lo, st = jax.tree.map(jnp.array, lora0), None
    st = opt.init(lo)

    @jax.jit
    def ref_step(lo, st):
        loss, g = jax.value_and_grad(
            lambda l: clm_loss(fwd(l, ids_j), ids_j))(lo)
        up, st = opt.update(g, st, lo)
        return optax.apply_updates(lo, up), st, loss

    ref_losses = []
    for _ in range(3):
        lo, st, loss = ref_step(lo, st)
        ref_losses.append(float(loss))

    # dp2 x tp2 sharded
    mesh = mesh_from_sizes(dp=2, tp=2)
    bspecs = block_specs(tp_axis="tp", stacked=True)
    lspecs = lora_partition_specs(bspecs, lcfg)
    base_specs = gpt2_partition_specs(CFG, tp_axis="tp")
    base_tp = shard_pytree(mesh, gpt2_to_tp_layout(params, CFG, 2),
                           base_specs)
    lora_s = shard_pytree(mesh, jax.tree.map(jnp.array, lora0), lspecs)
    opt_s = opt.init(lora_s)

    def merged_loss(base, lora, batch):
        merged = {**base,
                  "blocks": lora_merge_blocks(base["blocks"], lora, lcfg)}
        logits, _ = gpt2_forward(merged, batch[0], CFG, tp_axis="tp")
        return clm_loss(logits, batch[1])

    step = make_lora_train_step(mesh, merged_loss, opt,
                                base_specs=base_specs, lora_specs=lspecs)
    losses = []
    for _ in range(3):
        lora_s, opt_s, loss = step(base_tp, lora_s, opt_s,
                                   (ids_j, ids_j))
        losses.append(float(loss))

    np.testing.assert_allclose(losses, ref_losses, rtol=1e-5)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-4, atol=1e-5),
        lora_s, lo)

"""Beam-search decode contracts: beams=1 == greedy, beam-K never scores
below greedy under teacher-forced log-prob, EOS padding convention.
(The reference decodes greedy-only, utils/metrics.py:74-149.)
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from quintnet_tpu.models.gpt2 import GPT2Config, gpt2_apply, gpt2_init
from quintnet_tpu.models.gpt2_generate import gpt2_beam_search, gpt2_generate

pytestmark = pytest.mark.fast

CFG = GPT2Config.tiny()


@pytest.fixture(scope="module")
def setup():
    params = gpt2_init(jax.random.key(0), CFG)
    ids = np.asarray(
        np.random.default_rng(0).integers(0, CFG.vocab_size, (2, 6)),
        np.int32)
    return params, ids


def _seq_logprob(params, full, t0):
    """Teacher-forced log-prob of the generated suffix."""
    logits = gpt2_apply(params, jnp.asarray(full), CFG)
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    tgt = full[:, 1:]
    tok_lp = np.take_along_axis(np.asarray(logp[:, :-1]),
                                tgt[:, :, None], axis=2)[:, :, 0]
    return tok_lp[:, t0 - 1:].sum(axis=1)


def test_beam1_equals_greedy(setup):
    params, ids = setup
    greedy = gpt2_generate(params, ids, CFG, max_new_tokens=6)
    beam = gpt2_beam_search(params, ids, CFG, beams=1, max_new_tokens=6)
    np.testing.assert_array_equal(greedy, beam)


def test_beam_scores_at_least_greedy(setup):
    params, ids = setup
    greedy = gpt2_generate(params, ids, CFG, max_new_tokens=6)
    beam = gpt2_beam_search(params, ids, CFG, beams=4, max_new_tokens=6)
    lp_g = _seq_logprob(params, greedy, ids.shape[1])
    lp_b = _seq_logprob(params, beam, ids.shape[1])
    assert (lp_b >= lp_g - 1e-4).all(), (lp_b, lp_g)


def test_beam_eos_pads_tail(setup):
    params, ids = setup
    eos = 7
    out = gpt2_beam_search(params, ids, CFG, beams=3, max_new_tokens=8,
                           eos_token_id=eos)
    assert out.shape == (2, 14)
    new = out[:, 6:]
    for row in new:
        hits = np.where(row == eos)[0]
        if hits.size:
            assert (row[hits[0]:] == eos).all()


def test_beam_shape_without_eos(setup):
    params, ids = setup
    out = gpt2_beam_search(params, ids, CFG, beams=2, max_new_tokens=1)
    assert out.shape == (2, 7)


def test_evaluate_generation_with_beams(setup):
    from quintnet_tpu.data import ByteTokenizer
    from quintnet_tpu.train.metrics import evaluate_generation

    params, _ = setup
    tok = ByteTokenizer()
    prompts = [([1, 2, 3, 4], "some reference"),
               ([5, 6, 7, 8], "other reference")]
    scores = evaluate_generation(params, CFG, prompts, tok,
                                 max_new_tokens=4, batch_size=2,
                                 beams=3)
    assert set(scores) == {"rouge1", "rouge2", "rougeL", "bleu"}

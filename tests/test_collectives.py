"""Golden tests for collective primitives vs single-device math, including
the gradient relationships the reference hand-codes in its autograd
Functions (core/communication.py:46-600). Mirrors the methodology of
reference tests/test_tensor_parallel.py (allclose vs unsharded)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from quintnet_tpu.core import collectives as cc
from quintnet_tpu.core.mesh import mesh_from_sizes


@pytest.fixture(scope="module")
def mesh4():
    return mesh_from_sizes(x=4)


def _smap(mesh, fn, in_specs, out_specs):
    return cc.shard_map_fn(fn, mesh, in_specs, out_specs)


def test_all_reduce_sum(mesh4):
    x = jnp.arange(8.0).reshape(4, 2)  # shard rows over x
    out = _smap(mesh4, lambda v: cc.all_reduce(v, "x"), (P("x"),), P("x"))(x)
    # every shard holds the sum of all rows
    expected = np.tile(np.asarray(x).sum(0, keepdims=True), (4, 1))
    np.testing.assert_allclose(out, expected)


def test_all_reduce_backward_is_identity(mesh4):
    # reference All_Reduce backward returns grad unchanged
    # (communication.py:521-535)
    x = jnp.ones((4, 2))

    def loss(v):
        y = _smap(mesh4, lambda u: cc.all_reduce(u, "x"), (P("x"),), P("x"))(v)
        return jnp.sum(y * jnp.arange(8.0).reshape(4, 2))

    g = jax.grad(loss)(x)
    # d/dx_i sum_j c_j * (sum_k x_k) per column: each shard's grad = psum of
    # cotangents = identity routing of the summed cotangent
    expected = np.tile(np.asarray(jnp.arange(8.0).reshape(4, 2)).sum(0, keepdims=True), (4, 1))
    np.testing.assert_allclose(g, expected)


def test_all_gather_concat(mesh4):
    x = jnp.arange(8.0).reshape(4, 2)
    out = _smap(
        mesh4,
        lambda v: cc.all_gather(v, "x", gather_dim=-1),
        (P("x", None),),
        P("x", None),
    )(x)
    # each shard (1,2) -> gathered (1,8); global result (4,8)
    assert out.shape == (4, 8)
    row = np.asarray(x).reshape(-1)
    for r in range(4):
        np.testing.assert_allclose(out[r], row)


def test_all_gather_backward_is_slice(mesh4):
    # reference All_Gather backward mode="slice": each rank takes its own
    # chunk of the incoming grad (communication.py:447-455)
    x = jnp.ones((4, 2))

    def loss(v):
        y = _smap(
            mesh4,
            lambda u: cc.all_gather(u, "x", gather_dim=-1),
            (P("x", None),),
            P("x", None),
        )(v)
        w = jnp.arange(y.size, dtype=jnp.float32).reshape(y.shape)
        return jnp.sum(y * w)

    g = jax.grad(loss)(x)
    w = np.arange(32, dtype=np.float32).reshape(4, 8)
    # shard r holds columns [2r:2r+2] of its gathered row; grads route back
    expected = np.stack([w[:, 2 * r : 2 * r + 2].sum(0) for r in range(4)])
    # tiled all_gather over rows: each row r of x is chunk r of every
    # gathered copy; cotangent sums over the 4 copies (rows of w)
    np.testing.assert_allclose(g, expected)


def test_reduce_scatter(mesh4):
    # reference ReduceScatter forward (communication.py:565-580)
    x = jnp.ones((4, 8))

    out = _smap(
        mesh4,
        lambda v: cc.reduce_scatter(v, "x", scatter_dim=-1),
        (P("x", None),),
        P("x", None),
    )(x)
    # each shard contributes ones(1,8); sum over 4 shards = 4s; each keeps
    # a (1,2) chunk
    assert out.shape == (4, 2)
    np.testing.assert_allclose(out, np.full((4, 2), 4.0))


def test_ppermute_shift_forward_boundary(mesh4):
    x = jnp.arange(4.0).reshape(4, 1) + 1.0  # device i holds i+1

    out = _smap(
        mesh4,
        lambda v: cc.send_forward(v, "x"),
        (P("x"),),
        P("x"),
    )(x)
    # device 0 gets zeros (boundary no-op, communication.py:219-226),
    # device i gets value from i-1
    np.testing.assert_allclose(np.asarray(out).ravel(), [0.0, 1.0, 2.0, 3.0])


def test_ppermute_grad_flows_reverse(mesh4):
    # reference Send backward receives grad from the destination
    # (communication.py:96-126)
    x = jnp.arange(4.0).reshape(4, 1)

    def loss(v):
        y = _smap(mesh4, lambda u: cc.send_forward(u, "x"), (P("x"),), P("x"))(v)
        w = jnp.asarray([[0.0], [10.0], [20.0], [30.0]])
        return jnp.sum(y * w)

    g = jax.grad(loss)(x)
    # grad at device i = cotangent that arrived at device i+1
    np.testing.assert_allclose(np.asarray(g).ravel(), [10.0, 20.0, 30.0, 0.0])


def test_broadcast_from(mesh4):
    x = jnp.arange(4.0).reshape(4, 1)
    out = _smap(mesh4, lambda v: cc.broadcast_from(v, "x", src=2), (P("x"),), P("x"))(x)
    np.testing.assert_allclose(np.asarray(out).ravel(), [2.0] * 4)


def test_tree_all_reduce_mean(mesh4):
    tree = {"a": jnp.arange(4.0).reshape(4, 1), "b": jnp.ones((4, 3))}
    out = _smap(
        mesh4,
        lambda t: cc.tree_all_reduce_mean(t, "x"),
        ({"a": P("x"), "b": P("x")},),
        {"a": P("x"), "b": P("x")},
    )(tree)
    np.testing.assert_allclose(np.asarray(out["a"]).ravel(), [1.5] * 4)
    np.testing.assert_allclose(out["b"], np.ones((4, 3)))


def test_mean_of_sharded_grads_matches_global_batch_grad(mesh4):
    """The DP contract the reference *intends* (tests/test_data_parallel.py:92-117):
    mean of per-shard grads == grad over the concatenated global batch."""
    w = jnp.asarray([[0.5, -1.0], [2.0, 0.25]])
    xs = jnp.arange(16.0).reshape(8, 2) / 10.0

    def local_loss(w_, x_):
        return jnp.mean(jnp.sum((x_ @ w_) ** 2, -1))

    def dp_grads(w_, x_):
        g = jax.grad(local_loss)(w_, x_)
        return cc.all_reduce_mean(g, "x")

    g_dp = _smap(mesh4, dp_grads, (P(None, None), P("x", None)), P(None, None))(w, xs)
    g_ref = jax.grad(local_loss)(w, xs)
    np.testing.assert_allclose(g_dp, g_ref, rtol=1e-6)

"""Pytest setup: run every test on a simulated 8-device CPU mesh.

The reference's test story needs real GPUs + torchrun per rank and skips
on world-size mismatch (reference: tests/conftest.py:48-135). JAX gives
multi-device simulation for free: 8 virtual CPU devices in one process,
so the full DPxTPxPP matrix runs in CI with no hardware.

NOTE: this environment's sitecustomize pins JAX_PLATFORMS=axon (real TPU
tunnel); ``jax.config.update('jax_platforms', 'cpu')`` after import
overrides it, and XLA_FLAGS must be set before first backend use.
"""

import os

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", False)

import numpy as np
import pytest


@pytest.fixture(scope="session", autouse=True)
def _devices():
    devs = jax.devices()
    assert len(devs) >= 8, f"expected 8 simulated devices, got {len(devs)}"
    return devs


@pytest.fixture
def rng():
    return np.random.default_rng(0)

"""Pytest setup: run every test on a simulated 8-device CPU mesh.

The reference's test story needs real GPUs + torchrun per rank and skips
on world-size mismatch (reference: tests/conftest.py:48-135). JAX gives
multi-device simulation for free: 8 virtual CPU devices in one process,
so the full DPxTPxPP matrix runs in CI with no hardware.

NOTE: this environment's sitecustomize pins JAX_PLATFORMS=axon (real TPU
tunnel); ``jax.config.update('jax_platforms', 'cpu')`` after import
overrides it, and XLA_FLAGS must be set before first backend use.
"""

import os

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", False)

import numpy as np
import pytest

# Whole files whose tests are multi-minute on one CPU core (subprocess
# meshes, full-matrix parity, long schedules). Everything else is
# auto-marked ``fast`` — `pytest -m fast` stays green in <5 min
# single-core; `-m slow` (or no -m) runs the rest. Individual tests can
# still carry an explicit @pytest.mark.slow inside fast files.
SLOW_FILES = {
    "test_5d.py",         # 32-device 5D subprocess run (~9 min budget)
    "test_multihost.py",  # real 2-process jax.distributed rendezvous
    "test_launcher.py",   # spawns multi-process demos
    "test_sp.py",         # ring/zigzag/ulysses golden matrix (~4 min)
    "test_vp.py",         # vocab-parallel loss/embedding matrix (~2 min)
    "test_train.py",      # multi-epoch trainer runs + resume
    "test_generate.py",   # KV-cache + tp decode goldens (~4 min)
    "test_moe.py",        # MoE routing/dispatch matrix (~4 min)
    "test_dropout.py",    # seed-discipline matrix across strategies (~5 min)
    "test_gpt2.py",       # 3D training goldens + HF import (~2 min)
    "test_dp.py",         # replica-identity/grad-accum goldens (~1.5 min)
    "test_strategy.py",   # full strategy x schedule matrix (~2 min)
    "test_flash.py",      # pallas interpret-mode kernels (~1.5 min)
    "test_llama.py",      # HF goldens + strategy matrix (~3 min; the
                          # HF-logits golden is promoted fast)
    "test_lora.py",       # adapter goldens (~1.5 min; identity +
                          # save/load promoted fast)
    "test_beam.py",       # beam-search goldens (~1 min)
    "test_remat_knobs.py",  # remat policy matrix (~1.5 min; plain
                            # policy goldens promoted fast)
    "test_segments.py",   # packed-segment matrix incl. sp modes (~3 min;
                          # sdpa/host-helper goldens promoted fast)
    "test_fsdp.py",       # ZeRO-3 golden matrix (~4 min; spec-transform
                          # + guard tests promoted fast)
}


def pytest_collection_modifyitems(config, items):
    for item in items:
        fname = os.path.basename(str(item.fspath))
        explicit_slow = item.get_closest_marker("slow") is not None
        # an explicit @pytest.mark.fast inside a slow FILE promotes that
        # test into the smoke subset
        explicit_fast = item.get_closest_marker("fast") is not None
        if explicit_slow or (fname in SLOW_FILES and not explicit_fast):
            item.add_marker(pytest.mark.slow)
        else:
            item.add_marker(pytest.mark.fast)


@pytest.fixture(scope="session", autouse=True)
def _devices():
    devs = jax.devices()
    assert len(devs) >= 8, f"expected 8 simulated devices, got {len(devs)}"
    return devs


@pytest.fixture
def rng():
    return np.random.default_rng(0)

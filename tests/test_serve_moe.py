"""MoE serving goldens (expert parallelism through the paged engine).

THE contracts, in order of strength:

- **ep=1 == dense replication**: an engine on a size-1 ``ep`` mesh (or
  no mesh at all) builds the dense-replicated MoE programs — its
  committed token streams are identical to each other, greedy AND
  sampled.
- **ep=2 == ep=1**: sharding the experts over two ranks moves WHERE
  each expert FFN runs (two all_to_alls per MoE layer, census pinned
  in tests/test_qtcheck.py), never WHAT is computed — token-identical
  streams, greedy AND sampled, composing with the prefix cache,
  chunked prefill, int8 KV and speculative decoding.
- **Composition rules at construction**: ep x tp is allowed
  (nn/moe.py moe_specs), ep x sp and ep x adapters raise
  NotImplementedError, and MoEArgs misconfigurations raise actionable
  ValueErrors — all at ``ServeEngine(...)``, never inside the first
  serving step's trace.
- **Honest routing telemetry**: per-expert routed demand
  (pre-capacity-cut), capacity-drop counts, and router entropy flow
  from the programs' replicated routing masks into ServeMetrics,
  aggregate(), the Prometheus exposition and the StepRecorder ring —
  and a DENSE engine's summary/exposition is byte-identical to what
  it was before MoE serving existed.
"""

import jax
import numpy as np
import pytest
from jax.sharding import Mesh

from quintnet_tpu.models.gpt2 import GPT2Config, gpt2_init
from quintnet_tpu.serve import ServeEngine, SpecConfig, gpt2_family

CFG = GPT2Config.tiny(n_layer=2, n_experts=4, expert_top_k=2)


@pytest.fixture(scope="module")
def params():
    return gpt2_init(jax.random.key(0), CFG)


def _engine(params, cfg=CFG, **kw):
    kw.setdefault("max_slots", 3)
    kw.setdefault("block_size", 4)
    kw.setdefault("num_blocks", 36)
    kw.setdefault("max_seq_len", 48)
    return ServeEngine(gpt2_family(cfg), params, **kw)


def _ep_mesh(n):
    return Mesh(np.array(jax.devices()[:n]).reshape(n), ("ep",))


def _run_trace(eng, *, lengths=(7, 3, 5), max_new=6, seed=0):
    """Submit a deterministic staggered trace, run to drain, return
    the committed streams in submission order."""
    rng = np.random.default_rng(seed)
    prompts = [np.asarray(rng.integers(0, CFG.vocab_size, (n,)),
                          np.int32) for n in lengths]
    rids = [eng.submit(p, max_new, key=jax.random.key(100 + i))
            for i, p in enumerate(prompts)]
    steps = 0
    while eng.has_work:
        eng.step()
        steps += 1
        assert steps < 500, "engine failed to drain"
    return [eng.result(r) for r in rids]


# ---------------------------------------------------------------------
# construction-time composition rules + MoEArgs validation
# ---------------------------------------------------------------------

class TestConstruction:
    def test_ep_requires_moe_family(self, params):
        dense = GPT2Config.tiny(n_layer=2)
        with pytest.raises(ValueError, match="requires an MoE family"):
            _engine(gpt2_init(jax.random.key(0), dense), cfg=dense,
                    mesh=_ep_mesh(2), ep_axis="ep")

    def test_ep_axis_must_be_on_mesh(self, params):
        with pytest.raises(ValueError, match="not an axis of the mesh"):
            _engine(params, ep_axis="ep")  # no mesh at all
        with pytest.raises(ValueError, match="not an axis of the mesh"):
            _engine(params, ep_axis="ep",
                    mesh=Mesh(np.array(jax.devices()[:2]), ("tp",)))

    def test_n_experts_must_divide_over_ep(self, params):
        with pytest.raises(ValueError, match="divisible by"):
            _engine(params, mesh=_ep_mesh(3), ep_axis="ep")

    def test_nonpositive_capacity_rejected(self):
        cfg = GPT2Config.tiny(n_layer=2, n_experts=4,
                              expert_capacity=0)
        with pytest.raises(ValueError, match="capacity"):
            _engine(gpt2_init(jax.random.key(0), cfg), cfg=cfg)

    def test_nonpositive_capacity_factor_rejected(self):
        cfg = GPT2Config.tiny(n_layer=2, n_experts=4,
                              capacity_factor=0.0)
        with pytest.raises(ValueError, match="capacity_factor"):
            _engine(gpt2_init(jax.random.key(0), cfg), cfg=cfg)

    def test_bad_top_k_rejected(self):
        cfg = GPT2Config.tiny(n_layer=2, n_experts=4, expert_top_k=5)
        with pytest.raises(ValueError, match="top_k"):
            _engine(gpt2_init(jax.random.key(0), cfg), cfg=cfg)

    def test_moe_rejects_sp(self, params):
        mesh = Mesh(np.array(jax.devices()[:2]), ("sp",))
        with pytest.raises(NotImplementedError, match="MoE"):
            _engine(params, mesh=mesh, sp_axis="sp")

    def test_ep_rejects_adapters(self, params):
        from quintnet_tpu.serve import AdapterRegistry

        with pytest.raises(NotImplementedError, match="adapters"):
            _engine(params, mesh=_ep_mesh(2), ep_axis="ep",
                    adapters=AdapterRegistry())

    def test_ep1_mesh_nulls_ep_axis(self, params):
        eng = _engine(params, mesh=_ep_mesh(1), ep_axis="ep")
        assert eng.ep_axis is None
        eng2 = _engine(params, mesh=_ep_mesh(2), ep_axis="ep")
        assert eng2.ep_axis == "ep"


# ---------------------------------------------------------------------
# the identity contracts: ep=1 == dense replication, ep=2 == ep=1
# ---------------------------------------------------------------------

class TestEpParity:
    @pytest.mark.parametrize("sample_kw", [
        {},                                       # greedy
        {"temperature": 0.8, "top_k": 16},        # sampled
    ], ids=["greedy", "sampled"])
    def test_ep1_identical_to_dense_replication(self, params,
                                                sample_kw):
        base = _run_trace(_engine(params, **sample_kw))
        ep1 = _run_trace(_engine(params, mesh=_ep_mesh(1),
                                 ep_axis="ep", **sample_kw))
        for a, b in zip(base, ep1):
            assert np.array_equal(a, b)

    @pytest.mark.parametrize("sample_kw", [
        {},
        {"temperature": 0.8, "top_k": 16},
    ], ids=["greedy", "sampled"])
    def test_ep2_token_identical_to_ep1(self, params, sample_kw):
        ep1 = _run_trace(_engine(params, mesh=_ep_mesh(1),
                                 ep_axis="ep", **sample_kw))
        ep2 = _run_trace(_engine(params, mesh=_ep_mesh(2),
                                 ep_axis="ep", **sample_kw))
        for a, b in zip(ep1, ep2):
            assert np.array_equal(a, b)

    @pytest.mark.parametrize("feature_kw", [
        {"kv_dtype": "int8"},
        {"spec": SpecConfig()},
        {"chunked_prefill": True, "prefill_chunk_budget": 8},
    ], ids=["int8_kv", "spec_decode", "chunked_prefill"])
    def test_ep2_parity_composes_with_engine_features(self, params,
                                                      feature_kw):
        """ep=2 stays token-identical to the dense-replicated engine
        under each engine feature it must compose with — the feature's
        own dense goldens (test_kv_quant / test_spec / test_longctx)
        carry the rest of the equivalence chain."""
        base = _run_trace(_engine(params, **feature_kw),
                          lengths=(12, 5, 9), max_new=5)
        ep2 = _run_trace(_engine(params, mesh=_ep_mesh(2),
                                 ep_axis="ep", **feature_kw),
                         lengths=(12, 5, 9), max_new=5)
        for a, b in zip(base, ep2):
            assert np.array_equal(a, b)

    def test_ep2_parity_with_prefix_cache_reuse(self, params):
        """A shared-prefix second request admits through the prefix
        cache (hit tokens > 0) and STILL matches the dense-replicated
        engine token-for-token — the COW + cached-chain path neither
        skips nor double-runs any MoE layer."""
        rng = np.random.default_rng(3)
        prefix = np.asarray(rng.integers(0, CFG.vocab_size, (9,)),
                            np.int32)
        tail = np.asarray(rng.integers(0, CFG.vocab_size, (4,)),
                          np.int32)
        outs = {}
        for name, kw in (("base", {}),
                         ("ep2", {"mesh": _ep_mesh(2),
                                  "ep_axis": "ep"})):
            eng = _engine(params, **kw)
            r1 = eng.submit(prefix, 4, key=jax.random.key(1))
            while eng.has_work:
                eng.step()
            r2 = eng.submit(np.concatenate([prefix, tail]), 4,
                            key=jax.random.key(2))
            while eng.has_work:
                eng.step()
            assert eng.metrics.prefix_hit_tokens > 0
            outs[name] = (eng.result(r1), eng.result(r2))
        for a, b in zip(outs["base"], outs["ep2"]):
            assert np.array_equal(a, b)

    def test_ep_times_tp_parity(self, params):
        """ep x tp == tp: sharding the experts over ep on top of a
        tp-sharded engine changes no committed token. (The reference
        is the tp-ONLY engine, not the dense one: tp splits the FFN
        contraction and reassociates float sums — a pre-existing tp
        property, identical for dense and MoE FFNs — while ep moves
        whole expert FFNs between ranks without touching any
        reduction order.)"""
        if len(jax.devices()) < 4:
            pytest.skip("needs 4 devices")
        mesh = Mesh(np.array(jax.devices()[:4]).reshape(2, 2),
                    ("ep", "tp"))
        tp = _run_trace(_engine(
            params, mesh=Mesh(np.array(jax.devices()[:2]), ("tp",))))
        eptp = _run_trace(_engine(params, mesh=mesh, ep_axis="ep"))
        for a, b in zip(tp, eptp):
            assert np.array_equal(a, b)

    def test_compile_counts_unchanged_by_ep(self, params):
        """ep changes the programs' internals, never the program
        ladder: one compiled prefill per bucket + one decode, exactly
        like a dense engine (RecompileSentinel max_compiles=1)."""
        eng = _engine(params, mesh=_ep_mesh(2), ep_axis="ep")
        _run_trace(eng)
        assert eng.compile_stats() == {"prefill": 1, "decode": 1}
        eng.assert_compile_count()


# ---------------------------------------------------------------------
# routing telemetry: metrics -> aggregate -> prom -> recorder
# ---------------------------------------------------------------------

class TestRoutingStats:
    def test_dense_summary_has_no_moe_keys(self, params):
        dense = GPT2Config.tiny(n_layer=2)
        eng = _engine(gpt2_init(jax.random.key(0), dense), cfg=dense)
        _run_trace(eng)
        assert not any(k.startswith("moe") for k in
                       eng.metrics.summary())

    def test_summary_reports_real_routed_demand(self, params):
        eng = _engine(params)
        _run_trace(eng)
        s = eng.metrics.summary()
        assert s["moe_routed_tokens"] > 0
        # per-expert demand sums to the total routed demand (both are
        # PRE-capacity-cut): the ledger reads the programs' own
        # routing masks, it does not re-derive anything host-side
        assert (sum(s["moe_expert_tokens"].values())
                == s["moe_routed_tokens"])
        assert s["moe_expert_skew"] >= 1.0
        assert 0.0 <= s["moe_drop_rate"] <= 1.0
        assert s["moe_router_entropy"] > 0.0

    def test_capacity_drops_are_counted(self, params):
        """An explicit capacity of 1 token per expert under top_k=2
        routing MUST drop assignments — the drop ledger reads real
        program outputs, so it cannot be zero."""
        cfg = GPT2Config.tiny(n_layer=2, n_experts=4, expert_top_k=2,
                              expert_capacity=1)
        eng = _engine(gpt2_init(jax.random.key(0), cfg), cfg=cfg)
        _run_trace(eng)
        s = eng.metrics.summary()
        assert s["moe_dropped_tokens"] > 0
        assert s["moe_drop_rate"] > 0.0

    def test_ep2_and_dense_report_identical_routing(self, params):
        """The routing masks are replicated — sharding the experts
        must not change a single routed/dropped count."""
        a = _engine(params)
        b = _engine(params, mesh=_ep_mesh(2), ep_axis="ep")
        _run_trace(a)
        _run_trace(b)
        sa, sb = a.metrics.summary(), b.metrics.summary()
        for k in ("moe_routed_tokens", "moe_dropped_tokens",
                  "moe_expert_tokens"):
            assert sa[k] == sb[k], k

    def test_aggregate_sums_moe_ledgers(self, params):
        from quintnet_tpu.serve.metrics import aggregate

        a = _engine(params)
        b = _engine(params)
        _run_trace(a)
        _run_trace(b, seed=1)
        agg = aggregate([a.metrics, b.metrics])
        sa, sb = a.metrics.summary(), b.metrics.summary()
        assert agg["moe_routed_tokens"] == (sa["moe_routed_tokens"]
                                            + sb["moe_routed_tokens"])
        assert agg["moe_dropped_tokens"] == (
            sa["moe_dropped_tokens"] + sb["moe_dropped_tokens"])
        for e in agg["moe_expert_tokens"]:
            assert agg["moe_expert_tokens"][e] == (
                sa["moe_expert_tokens"][e] + sb["moe_expert_tokens"][e])
        # a dense fleet's aggregate stays moe-free
        dense = GPT2Config.tiny(n_layer=2)
        d = _engine(gpt2_init(jax.random.key(0), dense), cfg=dense)
        _run_trace(d)
        assert not any(k.startswith("moe")
                       for k in aggregate([d.metrics]))

    def test_prom_exposition_moe_families(self, params):
        from quintnet_tpu.obs.prom import (iter_samples,
                                           parse_exposition,
                                           render_exposition, sample)

        eng = _engine(params)
        _run_trace(eng)
        s = eng.metrics.summary()
        text = render_exposition({}, {"r0": s})
        parsed = parse_exposition(text)
        assert sample(parsed, "quintnet_engine_moe_routed_tokens",
                      replica="r0") == s["moe_routed_tokens"]
        assert sample(parsed, "quintnet_engine_moe_drop_rate",
                      replica="r0") == pytest.approx(
                          s["moe_drop_rate"])
        # one expert-labeled series per expert
        per_expert = dict(iter_samples(
            parsed, "quintnet_engine_moe_expert_tokens"))
        assert len(per_expert) == CFG.n_experts
        for labels, v in per_expert.items():
            eid = dict(labels)["expert"]
            assert v == s["moe_expert_tokens"][eid]
        # counters are TYPEd as counters
        assert ("# TYPE quintnet_engine_moe_routed_tokens counter"
                in text)
        # a dense engine's exposition carries no moe families
        dense = GPT2Config.tiny(n_layer=2)
        deng = _engine(gpt2_init(jax.random.key(0), dense), cfg=dense)
        _run_trace(deng)
        dtext = render_exposition({}, {"r0": deng.metrics.summary()})
        assert "moe" not in dtext

    def test_recorder_attrs_carry_step_routing(self, params):
        from quintnet_tpu.obs.recorder import StepRecorder

        eng = _engine(params)
        eng.recorder = StepRecorder(capacity=64, clock=eng.clock)
        _run_trace(eng)
        recs = eng.recorder.snapshot()
        moe_recs = [r for r in recs if r["attrs"]]
        assert moe_recs, "no step carried routing attrs"
        attrs = moe_recs[0]["attrs"]
        assert attrs["moe_routed_tokens"] > 0
        assert len(attrs["moe_expert_tokens"]) == CFG.n_experts
        # the ring's attrs sum to the metrics ledger (every step's
        # drain landed in exactly one record)
        assert sum(r["attrs"].get("moe_routed_tokens", 0)
                   for r in recs) == eng.metrics.moe_routed_tokens

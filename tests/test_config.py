"""Config loading tests, including loading the reference's shipped YAML
schema unmodified (reference: examples/config.yaml, core/config.py:96-120)."""

import textwrap

import pytest

from quintnet_tpu.core.config import Config, load_config, merge_configs


REFERENCE_STYLE_YAML = textwrap.dedent(
    """
    model:
      image_size: 28
      patch_size: 7
      in_channels: 1
      hidden_dim: 64
      depth: 8
      num_heads: 4
      num_classes: 10

    mesh_dim: [2, 2, 2]
    mesh_name: ['dp', 'tp', 'pp']

    training:
      batch_size: 32
      epochs: 10
      learning_rate: 0.0003
      gradient_accumulation_steps: 2
      schedule: '1f1b'
    """
)


def test_load_reference_style_yaml(tmp_path):
    p = tmp_path / "config.yaml"
    p.write_text(REFERENCE_STYLE_YAML)
    cfg = load_config(str(p))
    assert cfg.mesh.mesh_dim == [2, 2, 2]
    assert cfg.dp_size == 2 and cfg.tp_size == 2 and cfg.pp_size == 2
    assert cfg.model.hidden_dim == 64 and cfg.model.depth == 8
    assert cfg.training.schedule == "1f1b"
    # micro = batch // (grad_acc * dp) — trainer.py:99-146
    assert cfg.micro_batch_size_resolved() == 32 // (2 * 2)


def test_nested_mesh_schema():
    cfg = Config.from_dict({"mesh": {"mesh_dim": [4], "mesh_name": ["dp"]}})
    assert cfg.dp_size == 4 and cfg.tp_size == 1


def test_defaults():
    cfg = Config.from_dict({})
    assert cfg.mesh.world_size == 1
    assert cfg.training.optimizer == "adam"


def test_merge_configs():
    # reference merge_configs is a TODO stub (core/config.py:123-130)
    base = Config.from_dict({"training": {"batch_size": 32}})
    out = merge_configs(base, {"training": {"batch_size": 64}})
    assert out.training.batch_size == 64


def test_unknown_model_keys_go_to_extra():
    cfg = Config.from_dict({"model": {"hidden_dim": 8, "exotic_knob": 3}})
    assert cfg.model.extra["exotic_knob"] == 3


def test_bad_micro_batch():
    cfg = Config.from_dict(
        {"mesh_dim": [3], "mesh_name": ["dp"], "training": {"batch_size": 32}}
    )
    with pytest.raises(ValueError):
        cfg.micro_batch_size_resolved()


def test_remat_mode_resolution():
    """remat_mode folds (remat, remat_policy) into the model-spec arg."""
    mk = lambda **t: Config.from_dict({"training": t}).training
    assert mk().remat_mode is False
    assert mk(remat=True).remat_mode is True
    assert mk(remat=True, remat_policy="dots").remat_mode == "dots"
    # policy without remat stays off
    assert mk(remat=False, remat_policy="dots").remat_mode is False
    assert mk(scan_unroll=4).scan_unroll == 4

"""Fused paged-attention Pallas kernels (ops/paged_attention.py) vs
the XLA gathered-view oracle.

The contract ladder:

1. **Kernel parity matrix** — the real nn/attention entry points
   (``mha_decode`` / ``mha_verify_paged`` / ``mha_prefill_paged``) run
   once per backend from identical pool state, across every
   ``kv_layout_policies`` entry x verify bucket widths x chunked
   prefill offsets, in CPU interpret mode: outputs BIT-exact for
   f32/fake_quant, within the pinned tolerance for bf16/int8 (the
   observed diff is 0.0 — the kernel mirrors the oracle's op
   sequence — but only the passthrough-f32 and identity-scale cases
   are *guaranteed* exact by construction, so the quantized dtypes pin
   a bound instead of a bit pattern), and POOL BYTES + SCALES exactly
   equal everywhere (the write paths are one math).
2. **GQA** — the same matrix through the llama blocks (4 query heads
   on 2 kv heads): the kernel resolves the repeat in its index maps.
3. **Engine goldens** — ``ServeEngine(attn_kernel="pallas")`` serves
   prefix-cache, speculative-decode, chunked-prefill, preemption and
   tp=2 traffic TOKEN-IDENTICAL to ``attn_kernel="xla"``, greedy and
   sampled, f32 and int8, gpt2 and llama.
4. **Structural win** — the jaxpr auditor
   (analysis.gathered_view_gathers) proves the pallas programs issue
   ZERO full-row block-table gathers where the xla ones issue 2-4 per
   layer; compile counts and sentinels are unchanged per backend.
"""

import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from quintnet_tpu.analysis import gathered_view_gathers
from quintnet_tpu.analysis.specs import attn_kernels, kv_layout_policies
from quintnet_tpu.models.gpt2 import GPT2Config, gpt2_init
from quintnet_tpu.serve import ServeEngine, SpecConfig, gpt2_family
from quintnet_tpu.serve.kv_quant import make_policy

CFG = GPT2Config.tiny(n_layer=2)

# quantized-dtype tolerance: the kernel mirrors the oracle op for op,
# so the OBSERVED diff is 0.0; the pin leaves headroom only for
# platform-lowering drift in ops that are not exact by construction
QUANT_ATOL = 1e-6


@pytest.fixture(scope="module")
def params():
    return gpt2_init(jax.random.key(0), CFG)


# ---------------------------------------------------------------------
# 1. kernel parity matrix through the real mha entry points
# ---------------------------------------------------------------------

H, D, BS, M, NB = 2, 8, 4, 6, 20        # geometry: M collides with no
S = 3                                   # other dim (auditor contract)


def _mha_params(key):
    from quintnet_tpu.nn.attention import mha_init

    return mha_init(key, H * D)


def _pool(policy):
    k = jnp.zeros((NB * BS, H, D), policy.store_dtype)
    v = jnp.zeros((NB * BS, H, D), policy.store_dtype)
    if policy.scaled:
        return [k, v, jnp.ones((NB, H), jnp.float32),
                jnp.ones((NB, H), jnp.float32)]
    return [k, v, None, None]


def _scales(pool):
    return (pool[2], pool[3]) if pool[2] is not None else None


def _tables():
    # disjoint per-row tables; block 0 stays the null block
    return jnp.asarray([[1 + s * M + m for m in range(M)]
                        for s in range(S)], jnp.int32)


def _assert_pools_match(pa, pb, policy, tables):
    """Pool bytes + scales bit-equal on every REAL block (the null
    block legitimately collects both backends' masked-pad scatters)."""
    real = np.asarray(tables).reshape(-1)
    for a, b in zip(pa[:2], pb[:2]):
        ra = np.asarray(a).reshape(NB, BS, H, D)[real]
        rb = np.asarray(b).reshape(NB, BS, H, D)[real]
        np.testing.assert_array_equal(ra, rb)
    if policy.scaled:
        for a, b in zip(pa[2:], pb[2:]):
            np.testing.assert_array_equal(np.asarray(a)[real],
                                          np.asarray(b)[real])


def _assert_out(ya, yb, policy):
    ya, yb = np.asarray(ya), np.asarray(yb)
    if policy.name in ("f32", "fake_quant"):
        np.testing.assert_array_equal(ya, yb)
    else:
        np.testing.assert_allclose(ya, yb, atol=QUANT_ATOL, rtol=0)


class TestMhaParityMatrix:
    """Each scenario runs the SAME op sequence per backend from the
    same initial pool, twice back to back (history accumulates across
    the calls, covering requant-on-top-of-requant)."""

    @pytest.fixture(scope="class")
    def attn(self):
        return _mha_params(jax.random.key(1))

    def _run_verify(self, attn, policy, kernel, P, steps=2):
        from quintnet_tpu.nn.attention import mha_verify_paged

        rng = np.random.default_rng(7)
        pool = _pool(policy)
        tables = _tables()
        starts = np.asarray([5, 0, 11], np.int32)
        outs = []
        for it in range(steps):
            x = jnp.asarray(rng.standard_normal((S, P, H * D)),
                            jnp.float32)
            positions = jnp.asarray(starts)[:, None] + jnp.arange(
                P, dtype=jnp.int32)[None, :]
            tail_lens = jnp.asarray([P, max(P - 1, 1), P], jnp.int32)
            kv = _scales(pool)
            out = jax.jit(
                lambda x, kp, vp, ks, vs: mha_verify_paged(
                    attn, x, kp, vp, positions, tail_lens,
                    num_heads=H, block_tables=tables, block_size=BS,
                    kv_scales=(ks, vs) if ks is not None else None,
                    policy=policy if kv is not None else None,
                    attn_kernel=kernel)
            )(x, pool[0], pool[1], pool[2], pool[3])
            outs.append(out[0])
            pool = list(out[1:]) + ([None, None] if kv is None else [])
            starts = starts + np.asarray(tail_lens)
        return outs, pool

    @pytest.mark.parametrize("policy_name", kv_layout_policies())
    @pytest.mark.parametrize("P", (1, 3, 5))
    def test_verify_and_decode_widths(self, attn, policy_name, P):
        """P=1 IS the decode shape; 3/5 are the verify buckets + 1."""
        policy = make_policy(policy_name)
        ya, pa = self._run_verify(attn, policy, "xla", P)
        yb, pb = self._run_verify(attn, policy, "pallas", P)
        for a, b in zip(ya, yb):
            _assert_out(a, b, policy)
        _assert_pools_match(pa, pb, policy, _tables())

    def _run_prefill(self, attn, policy, kernel):
        """Chunked prefill: one row, two chunks at dynamic offsets
        (start 0 then 8) through the SAME bucket width — the
        prefix-cache tail shape."""
        from quintnet_tpu.nn.attention import mha_prefill_paged

        rng = np.random.default_rng(9)
        pool = _pool(policy)
        tables = _tables()[0]
        P = 8
        outs = []
        for start, tail in ((0, 8), (8, 5)):
            x = jnp.asarray(rng.standard_normal((1, P, H * D)),
                            jnp.float32)
            positions = start + jnp.arange(P, dtype=jnp.int32)
            kv = _scales(pool)
            out = jax.jit(
                lambda x, kp, vp, ks, vs: mha_prefill_paged(
                    attn, x, kp, vp, positions, jnp.int32(tail),
                    num_heads=H, block_tables=tables, block_size=BS,
                    kv_scales=(ks, vs) if ks is not None else None,
                    policy=policy if kv is not None else None,
                    attn_kernel=kernel)
            )(x, pool[0], pool[1], pool[2], pool[3])
            outs.append(out[0])
            pool = list(out[1:]) + ([None, None] if kv is None else [])
        return outs, pool

    @pytest.mark.parametrize("policy_name", kv_layout_policies())
    def test_chunked_prefill_offsets(self, attn, policy_name):
        policy = make_policy(policy_name)
        ya, pa = self._run_prefill(attn, policy, "xla")
        yb, pb = self._run_prefill(attn, policy, "pallas")
        for a, b in zip(ya, yb):
            _assert_out(a, b, policy)
        _assert_pools_match(pa, pb, policy, _tables())


# ---------------------------------------------------------------------
# 2. GQA through the llama block (4 query heads on 2 kv heads)
# ---------------------------------------------------------------------

class TestGQAParity:
    @pytest.mark.parametrize("policy_name", ("f32", "int8"))
    @pytest.mark.parametrize("P", (1, 3))
    def test_llama_verify_gqa(self, policy_name, P):
        from quintnet_tpu.models.llama import (LlamaConfig, llama_init,
                                               llama_block_verify_paged,
                                               llama_rope_tables)

        cfg = LlamaConfig.tiny()
        assert cfg.n_heads != cfg.n_kv_heads  # the point of this test
        policy = make_policy(policy_name)
        params = llama_init(jax.random.key(2), cfg)
        blk = jax.tree.map(lambda a: a[0], params["blocks"])
        hkv, hd = cfg.n_kv_heads, cfg.head_dim
        pool = [jnp.zeros((NB * BS, hkv, hd), policy.store_dtype),
                jnp.zeros((NB * BS, hkv, hd), policy.store_dtype)]
        if policy.scaled:
            pool += [jnp.ones((NB, hkv), jnp.float32),
                     jnp.ones((NB, hkv), jnp.float32)]
        else:
            pool += [None, None]
        tables = _tables()
        rng = np.random.default_rng(3)
        starts = np.asarray([5, 0, 11], np.int32)
        results = {}
        for kernel in attn_kernels():
            p = [jnp.array(a) if a is not None else None for a in pool]
            outs = []
            st = starts.copy()
            rng2 = np.random.default_rng(3)
            for it in range(2):
                x = jnp.asarray(rng2.standard_normal((S, P, cfg.dim)),
                                jnp.float32)
                positions = (jnp.asarray(st)[:, None]
                             + jnp.arange(P, dtype=jnp.int32)[None, :])
                tails = jnp.asarray([P, max(P - 1, 1), P], jnp.int32)
                cos, sin = llama_rope_tables(positions, cfg)
                cos, sin = cos[:, None], sin[:, None]
                kv = (p[2], p[3]) if p[2] is not None else None
                out = jax.jit(
                    lambda x, kp, vp, ks, vs: llama_block_verify_paged(
                        blk, x, kp, vp, positions, tails, cfg, cos,
                        sin, block_tables=tables, block_size=BS,
                        kv_scales=(ks, vs) if ks is not None else None,
                        policy=policy if kv is not None else None,
                        attn_kernel=kernel)
                )(x, p[0], p[1], p[2], p[3])
                outs.append(out[0])
                p = list(out[1]) + ([None, None] if kv is None else [])
                st = st + np.asarray(tails)
            results[kernel] = (outs, p)
        (ya, pa), (yb, pb) = results["xla"], results["pallas"]
        for a, b in zip(ya, yb):
            _assert_out(a, b, policy)
        real = np.asarray(tables).reshape(-1)
        for a, b in zip(pa[:2], pb[:2]):
            np.testing.assert_array_equal(
                np.asarray(a).reshape(NB, BS, hkv, hd)[real],
                np.asarray(b).reshape(NB, BS, hkv, hd)[real])


# ---------------------------------------------------------------------
# 3. engine goldens: pallas serves token-identical to xla
# ---------------------------------------------------------------------

def _engine(params, kernel, family=None, fam_params=None, **kw):
    kw.setdefault("max_slots", 3)
    kw.setdefault("block_size", 4)
    kw.setdefault("num_blocks", 48)
    kw.setdefault("max_seq_len", 32)
    return ServeEngine(family or gpt2_family(CFG),
                       fam_params if fam_params is not None else params,
                       attn_kernel=kernel, **kw)


def _serve(eng, prompts, max_new, *, arrivals=None):
    arrivals = arrivals or [0] * len(prompts)
    keys = [jax.random.key(100 + i) for i in range(len(prompts))]
    rids, submitted, step = {}, 0, 0
    while submitted < len(prompts) or eng.has_work:
        while (submitted < len(prompts)
               and arrivals[submitted] <= step):
            rids[submitted] = eng.submit(prompts[submitted], max_new,
                                         key=keys[submitted])
            submitted += 1
        eng.step()
        step += 1
        assert step < 1000
    return [np.asarray(eng.result(rids[i])) for i in range(len(prompts))]


def _ab(params, prompts, max_new, *, arrivals=None, **kw):
    a = _serve(_engine(params, "xla", **kw), prompts, max_new,
               arrivals=arrivals)
    b = _serve(_engine(params, "pallas", **kw), prompts, max_new,
               arrivals=arrivals)
    for x, y in zip(a, b):
        np.testing.assert_array_equal(x, y)
    return a


class TestEngineGoldens:
    @pytest.fixture(scope="class")
    def prompts(self):
        rng = np.random.default_rng(11)
        shared = rng.integers(0, CFG.vocab_size, (9,)).astype(np.int32)
        mixed = [np.asarray(rng.integers(0, CFG.vocab_size, (n,)),
                            np.int32) for n in (5, 12, 3)]
        shared_tails = [np.concatenate(
            [shared, rng.integers(0, CFG.vocab_size, (t,)
                                  ).astype(np.int32)]) for t in (3, 5)]
        return mixed + shared_tails

    def test_greedy_prefix_cache_f32(self, params, prompts):
        _ab(params, prompts, 8, arrivals=[0, 1, 2, 4, 6])

    def test_sampled_spec_int8(self, params, prompts):
        _ab(params, prompts, 8, arrivals=[0, 0, 2, 3, 5],
            kv_dtype="int8", temperature=0.8,
            spec=SpecConfig(max_draft=4))

    def test_chunked_prefill_fake_quant(self, params):
        rng = np.random.default_rng(13)
        long = np.asarray(rng.integers(0, CFG.vocab_size, (20,)),
                          np.int32)
        short = np.asarray(rng.integers(0, CFG.vocab_size, (4,)),
                           np.int32)
        _ab(params, [long, short], 6, kv_dtype="fake_quant",
            prefill_len=8, chunked_prefill=True, prefill_chunk_budget=8,
            max_seq_len=32)

    def test_preemption_pressure_int8(self, params, prompts):
        # pool sized to force growth + preemption mid-trace
        _ab(params, prompts, 8, arrivals=[0, 0, 0, 1, 1],
            kv_dtype="int8", num_blocks=14, max_slots=3)

    def test_llama_gqa_engine_int8(self):
        from quintnet_tpu.models.llama import LlamaConfig, llama_init
        from quintnet_tpu.serve import llama_family

        cfg = LlamaConfig.tiny()
        lp = llama_init(jax.random.key(4), cfg)
        rng = np.random.default_rng(17)
        prompts = [np.asarray(rng.integers(0, cfg.vocab_size, (n,)),
                              np.int32) for n in (5, 9)]
        _ab(None, prompts, 6, family=llama_family(cfg), fam_params=lp,
            kv_dtype="int8", max_slots=2)

    def test_tp2_fake_quant(self, params, prompts):
        from jax.sharding import Mesh

        mesh = Mesh(np.array(jax.devices()[:2]), ("tp",))
        _ab(params, prompts[:3], 6, kv_dtype="fake_quant", mesh=mesh,
            max_slots=2)


# ---------------------------------------------------------------------
# 4. structural win + validation + import surface
# ---------------------------------------------------------------------

class TestStructure:
    def _args(self, eng, params, which, bucket=None):
        caches = eng.pool.caches()
        if which == "decode":
            return (params, *caches, jnp.asarray(eng._tok),
                    jnp.asarray(eng._pos), jnp.asarray(eng._tables),
                    jnp.asarray(eng._key_data))
        if which == "verify":
            S = eng.max_slots
            ids = np.zeros((S, bucket + 1), np.int32)
            return (params, *caches, jnp.asarray(ids),
                    jnp.asarray(eng._pos),
                    jnp.asarray(np.ones(S, np.int32)),
                    jnp.asarray(eng._tables), jnp.asarray(eng._key_data))
        ids = np.zeros((1, bucket), np.int32)
        row = np.zeros((eng.table_width,), np.int32)
        return (params, *caches, jnp.asarray(ids), jnp.int32(1),
                jnp.int32(3), jnp.asarray(row), jnp.int32(0),
                jnp.int32(0), jnp.asarray(eng._key_data[0]))

    @pytest.mark.parametrize("kv_dtype", ("f32", "int8"))
    def test_pallas_issues_zero_gathered_view_gathers(self, params,
                                                      kv_dtype):
        """THE structural gate: every xla serving program gathers the
        full block-table row (2 pools, +2 scale arrays when scaled) per
        layer; every pallas program gathers it ZERO times — the walk
        happens inside the kernel. Asserted on decode, the smallest
        prefill bucket (requant span < table width — the auditor's
        caller contract), and a verify bucket."""
        counts = {}
        for kernel in attn_kernels():
            eng = _engine(params, kernel, kv_dtype=kv_dtype,
                          num_blocks=24, spec=SpecConfig(max_draft=4))
            kw = dict(num_blocks=24, table_width=eng.table_width)
            b0 = eng.prefill_buckets[0]
            counts[kernel] = dict(
                decode=gathered_view_gathers(
                    eng._decode.fn, *self._args(eng, params, "decode"),
                    **kw),
                prefill=gathered_view_gathers(
                    eng._prefills[b0].fn,
                    *self._args(eng, params, "prefill", b0), **kw),
                verify=gathered_view_gathers(
                    eng._verifies[2].fn,
                    *self._args(eng, params, "verify", 2), **kw),
            )
        per_layer = 4 if kv_dtype == "int8" else 2
        for which in ("decode", "prefill", "verify"):
            assert counts["xla"][which] == per_layer, counts
            assert counts["pallas"][which] == 0, counts

    def test_compile_counts_unchanged_per_backend(self, params):
        """Same sentinel set, same bounds, either backend — the kernel
        never adds a program."""
        rng = np.random.default_rng(5)
        prompts = [np.asarray(rng.integers(0, CFG.vocab_size, (n,)),
                              np.int32) for n in (3, 7)]
        for kernel in attn_kernels():
            eng = _engine(params, kernel)
            _serve(eng, prompts, 5)
            assert eng.compile_stats() == {"prefill": 1, "decode": 1}
            eng.assert_compile_count()

    def test_unknown_kernel_rejected(self, params):
        with pytest.raises(ValueError, match="attn_kernel"):
            _engine(params, "triton")

    def test_pallas_unavailable_rejected_at_construction(self, params,
                                                         monkeypatch):
        """A jax install without pallas TPU support must fail at
        ServeEngine construction, not deep inside the first serving
        step."""
        import importlib

        # the ops package re-exports the paged_attention FUNCTION, so
        # attribute-style module access resolves to it — go via
        # importlib for the module object
        pa = importlib.import_module(
            "quintnet_tpu.ops.paged_attention")
        monkeypatch.setattr(pa, "_HAVE_PLTPU", False)
        with pytest.raises(RuntimeError, match="pallas"):
            _engine(params, "pallas")

    def test_pallas_sp_rejected(self, params):
        from jax.sharding import Mesh

        mesh = Mesh(np.array(jax.devices()[:2]), ("sp",))
        with pytest.raises(NotImplementedError, match="pallas"):
            _engine(params, "pallas", mesh=mesh, sp_axis="sp",
                    prefill_bucket_sizes=(16, 32))

    def test_dense_path_rejects_pallas(self):
        from quintnet_tpu.nn.attention import mha_decode, mha_init

        p = mha_init(jax.random.key(0), H * D)
        x = jnp.zeros((1, 1, H * D))
        kc = jnp.zeros((1, H, 8, D))
        with pytest.raises(ValueError, match="paged"):
            mha_decode(p, x, kc, kc, jnp.int32(0), num_heads=H,
                       attn_kernel="pallas")

    def test_scaled_kernel_requires_fresh_kv(self):
        from quintnet_tpu.ops.paged_attention import paged_attention

        q = jnp.zeros((1, H, 1, D))
        pool = jnp.zeros((NB * BS, H, D), jnp.int8)
        sc = jnp.ones((NB, H), jnp.float32)
        with pytest.raises(ValueError, match="fresh_kv"):
            paged_attention(q, pool, pool, _tables()[:1],
                            jnp.zeros((1,), jnp.int32), block_size=BS,
                            kv_scales=(sc, sc))


def test_ops_import_surface():
    """ops/ exports its public kernel entry points (the previously
    empty ``__init__`` belied its own docstring)."""
    import quintnet_tpu.ops as ops

    expected = {"flash_attention", "blockwise_attention",
                "pallas_flash_attention", "paged_attention",
                "paged_quant_window_update", "ring_attention",
                "zigzag_ring_attention", "ulysses_attention"}
    assert expected == set(ops.__all__)
    for name in ops.__all__:
        assert callable(getattr(ops, name)), name


def test_attn_kernel_ladder_pinned():
    assert attn_kernels() == ("xla", "pallas")

"""Sequence parallelism / ring attention golden tests (capability absent
from the reference — SURVEY §5.7; validated against full-sequence
attention and single-device training)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

from quintnet_tpu.core import collectives as cc
from quintnet_tpu.core.config import Config
from quintnet_tpu.core.mesh import mesh_from_sizes
from quintnet_tpu.models.gpt2 import (
    GPT2Config,
    clm_loss,
    gpt2_apply,
    gpt2_init,
    gpt2_model_spec,
)
from quintnet_tpu.nn.attention import sdpa
from quintnet_tpu.ops.ring_attention import ring_attention
from quintnet_tpu.parallel.strategy import get_strategy

TINY = GPT2Config.tiny()


@pytest.fixture(scope="module")
def mesh_sp():
    return mesh_from_sizes(sp=4)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_sdpa(mesh_sp, causal):
    b, h, s, d = 2, 2, 32, 8
    q = jax.random.normal(jax.random.key(0), (b, h, s, d))
    k = jax.random.normal(jax.random.key(1), (b, h, s, d))
    v = jax.random.normal(jax.random.key(2), (b, h, s, d))

    ref = sdpa(q, k, v, causal=causal)

    out = cc.shard_map_fn(
        lambda q_, k_, v_: ring_attention(q_, k_, v_, axis="sp",
                                          causal=causal),
        mesh_sp,
        in_specs=(P(None, None, "sp"), P(None, None, "sp"),
                  P(None, None, "sp")),
        out_specs=P(None, None, "sp"),
    )(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)


def test_ring_attention_grads_match(mesh_sp):
    b, h, s, d = 1, 2, 16, 4
    q = jax.random.normal(jax.random.key(0), (b, h, s, d))
    k = jax.random.normal(jax.random.key(1), (b, h, s, d))
    v = jax.random.normal(jax.random.key(2), (b, h, s, d))
    w = jax.random.normal(jax.random.key(3), (b, h, s, d))

    def ref_loss(q_, k_, v_):
        return jnp.sum(sdpa(q_, k_, v_, causal=True) * w)

    g_ref = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)

    def ring_loss(q_, k_, v_, w_):
        # local partial (no psum): per-rank seeds sum to the global loss;
        # transposed ppermutes deliver the cross-rank k/v cotangents
        out = ring_attention(q_, k_, v_, axis="sp", causal=True)
        return jnp.sum(out * w_)

    def local(q_, k_, v_, w_):
        g = jax.grad(lambda a, b_, c: ring_loss(a, b_, c, w_),
                     argnums=(0, 1, 2))(q_, k_, v_)
        return g

    sp_spec = P(None, None, "sp")
    g = cc.shard_map_fn(
        local, mesh_sp,
        in_specs=(sp_spec,) * 4,
        out_specs=(sp_spec,) * 3,
    )(q, k, v, w)
    for a, b_ in zip(g, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=5e-4, atol=1e-5)


def test_gpt2_sp_forward_matches_single_device(mesh_sp):
    params = gpt2_init(jax.random.key(0), TINY)
    ids = jax.random.randint(jax.random.key(1), (2, 32), 0, TINY.vocab_size)

    ref = gpt2_apply(params, ids, TINY)

    out = cc.shard_map_fn(
        lambda p, i: gpt2_apply(p, i, TINY, sp_axis="sp"),
        mesh_sp,
        in_specs=(P(), P(None, "sp")),
        out_specs=P(None, "sp"),
    )(params, ids)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-3, atol=2e-4)


@pytest.mark.parametrize("sp", [1, 2, 4, 8])
def test_zigzag_ring_attention_matches_sdpa(sp):
    """Load-balanced zigzag layout must stay EXACT (relayout + selected
    chunk-pair scheduling is pure bookkeeping) at every ring size,
    including odd-even boundary cases."""
    from quintnet_tpu.ops.ring_attention import zigzag_ring_attention

    b, h, s, d = 2, 2, 32, 8
    q = jax.random.normal(jax.random.key(0), (b, h, s, d))
    k = jax.random.normal(jax.random.key(1), (b, h, s, d))
    v = jax.random.normal(jax.random.key(2), (b, h, s, d))

    ref = sdpa(q, k, v, causal=True)
    mesh = mesh_from_sizes(sp=sp)
    out = cc.shard_map_fn(
        lambda q_, k_, v_: zigzag_ring_attention(q_, k_, v_, axis="sp",
                                                 causal=True),
        mesh,
        in_specs=(P(None, None, "sp"),) * 3,
        out_specs=P(None, None, "sp"),
    )(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)


def test_zigzag_matches_plain_ring(mesh_sp):
    from quintnet_tpu.ops.ring_attention import zigzag_ring_attention

    b, h, s, d = 1, 2, 64, 8
    q = jax.random.normal(jax.random.key(5), (b, h, s, d))
    k = jax.random.normal(jax.random.key(6), (b, h, s, d))
    v = jax.random.normal(jax.random.key(7), (b, h, s, d))

    run = lambda fn: cc.shard_map_fn(
        lambda q_, k_, v_: fn(q_, k_, v_, axis="sp", causal=True),
        mesh_sp,
        in_specs=(P(None, None, "sp"),) * 3,
        out_specs=P(None, None, "sp"),
    )(q, k, v)
    np.testing.assert_allclose(np.asarray(run(zigzag_ring_attention)),
                               np.asarray(run(ring_attention)),
                               rtol=2e-4, atol=2e-5)


def test_zigzag_ring_attention_grads_match(mesh_sp):
    from quintnet_tpu.ops.ring_attention import zigzag_ring_attention

    b, h, s, d = 1, 2, 16, 4
    q = jax.random.normal(jax.random.key(0), (b, h, s, d))
    k = jax.random.normal(jax.random.key(1), (b, h, s, d))
    v = jax.random.normal(jax.random.key(2), (b, h, s, d))
    w = jax.random.normal(jax.random.key(3), (b, h, s, d))

    def ref_loss(q_, k_, v_):
        return jnp.sum(sdpa(q_, k_, v_, causal=True) * w)

    g_ref = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)

    def local(q_, k_, v_, w_):
        def loss(a, b_, c):
            out = zigzag_ring_attention(a, b_, c, axis="sp", causal=True)
            return jnp.sum(out * w_)

        return jax.grad(loss, argnums=(0, 1, 2))(q_, k_, v_)

    sp_spec = P(None, None, "sp")
    g = cc.shard_map_fn(
        local, mesh_sp,
        in_specs=(sp_spec,) * 4,
        out_specs=(sp_spec,) * 3,
    )(q, k, v, w)
    for a, b_ in zip(g, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=5e-4, atol=1e-5)


def test_gpt2_sp_zigzag_forward_matches_single_device(mesh_sp):
    params = gpt2_init(jax.random.key(0), TINY)
    ids = jax.random.randint(jax.random.key(1), (2, 32), 0, TINY.vocab_size)

    ref = gpt2_apply(params, ids, TINY)

    out = cc.shard_map_fn(
        lambda p, i: gpt2_apply(p, i, TINY, sp_axis="sp",
                                sp_mode="zigzag"),
        mesh_sp,
        in_specs=(P(), P(None, "sp")),
        out_specs=P(None, "sp"),
    )(params, ids)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-3, atol=2e-4)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_attention_matches_sdpa(mesh_sp, causal):
    from quintnet_tpu.ops.ulysses_attention import ulysses_attention

    b, h, s, d = 2, 4, 32, 8
    q = jax.random.normal(jax.random.key(0), (b, h, s, d))
    k = jax.random.normal(jax.random.key(1), (b, h, s, d))
    v = jax.random.normal(jax.random.key(2), (b, h, s, d))

    ref = sdpa(q, k, v, causal=causal)

    out = cc.shard_map_fn(
        lambda q_, k_, v_: ulysses_attention(q_, k_, v_, axis="sp",
                                             causal=causal),
        mesh_sp,
        in_specs=(P(None, None, "sp"), P(None, None, "sp"),
                  P(None, None, "sp")),
        out_specs=P(None, None, "sp"),
    )(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)


def test_ulysses_attention_grads_match(mesh_sp):
    from quintnet_tpu.ops.ulysses_attention import ulysses_attention

    b, h, s, d = 1, 4, 16, 4
    q = jax.random.normal(jax.random.key(0), (b, h, s, d))
    k = jax.random.normal(jax.random.key(1), (b, h, s, d))
    v = jax.random.normal(jax.random.key(2), (b, h, s, d))
    w = jax.random.normal(jax.random.key(3), (b, h, s, d))

    def ref_loss(q_, k_, v_):
        return jnp.sum(sdpa(q_, k_, v_, causal=True) * w)

    g_ref = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)

    def local(q_, k_, v_, w_):
        def loss(a, b_, c):
            out = ulysses_attention(a, b_, c, axis="sp", causal=True)
            return jnp.sum(out * w_)

        return jax.grad(loss, argnums=(0, 1, 2))(q_, k_, v_)

    sp_spec = P(None, None, "sp")
    g = cc.shard_map_fn(
        local, mesh_sp,
        in_specs=(sp_spec,) * 4,
        out_specs=(sp_spec,) * 3,
    )(q, k, v, w)
    for a, b_ in zip(g, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=5e-4, atol=1e-5)


def test_ulysses_rejects_indivisible_heads(mesh_sp):
    from quintnet_tpu.ops.ulysses_attention import ulysses_attention

    b, h, s, d = 1, 2, 16, 4  # 2 local heads, sp=4 -> invalid
    q = jax.random.normal(jax.random.key(0), (b, h, s, d))
    with pytest.raises(ValueError, match="divisible"):
        cc.shard_map_fn(
            lambda q_: ulysses_attention(q_, q_, q_, axis="sp"),
            mesh_sp,
            in_specs=(P(None, None, "sp"),),
            out_specs=P(None, None, "sp"),
        )(q)


def test_gpt2_sp_ulysses_forward_matches_single_device(mesh_sp):
    params = gpt2_init(jax.random.key(0), TINY)
    ids = jax.random.randint(jax.random.key(1), (2, 32), 0, TINY.vocab_size)

    ref = gpt2_apply(params, ids, TINY)

    out = cc.shard_map_fn(
        lambda p, i: gpt2_apply(p, i, TINY, sp_axis="sp",
                                sp_mode="ulysses"),
        mesh_sp,
        in_specs=(P(), P(None, "sp")),
        out_specs=P(None, "sp"),
    )(params, ids)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-3, atol=2e-4)


@pytest.mark.parametrize("mesh_dim,mesh_name,schedule,grad_acc,sp_mode", [
    ([4], ["sp"], "afab", 1, "ring"),
    ([4], ["sp"], "afab", 1, "ulysses"),
    ([2, 2], ["dp", "sp"], "afab", 1, "ring"),
    ([2, 2, 2], ["tp", "pp", "sp"], "1f1b", 2, "ring"),
    ([2, 2, 2], ["tp", "pp", "sp"], "1f1b", 2, "ulysses"),
    ([4], ["sp"], "afab", 1, "zigzag"),
    ([2, 2, 2], ["tp", "pp", "sp"], "1f1b", 2, "zigzag"),
])
def test_gpt2_sp_train_step_matches_single_device(mesh_dim, mesh_name,
                                                  schedule, grad_acc,
                                                  sp_mode):
    cfg = Config.from_dict({
        "mesh_dim": mesh_dim, "mesh_name": mesh_name,
        "training": {"batch_size": 4, "gradient_accumulation_steps": grad_acc,
                     "schedule": schedule, "grad_clip_norm": None},
    })
    params = gpt2_init(jax.random.key(0), TINY)
    ids = jax.random.randint(jax.random.key(1), (4, 32), 0, TINY.vocab_size)
    batch = (ids, ids)
    opt = optax.sgd(0.05)

    def ref_loss(p):
        return clm_loss(gpt2_apply(p, ids, TINY), ids)

    loss_ref, g_ref = jax.value_and_grad(ref_loss)(params)
    p_ref = optax.apply_updates(params, opt.update(g_ref, opt.init(params),
                                                   params)[0])

    strat = get_strategy("auto", cfg)
    model = gpt2_model_spec(TINY, sp_mode=sp_mode)
    p = strat.shard_params(model, params)
    s = strat.init_opt_state(model, opt, p)
    b = strat.shard_batch(batch, model)
    step = strat.make_train_step(model, opt)
    p2, _, loss = step(p, s, b)

    np.testing.assert_allclose(float(loss), float(loss_ref), rtol=1e-5)
    from quintnet_tpu.models.gpt2 import gpt2_to_tp_layout

    p_ref_l = gpt2_to_tp_layout(p_ref, TINY, cfg.tp_size)
    flat = jax.tree_util.tree_leaves_with_path(p2)
    ref = dict(jax.tree_util.tree_leaves_with_path(p_ref_l))
    for path, leaf in flat:
        np.testing.assert_allclose(
            np.asarray(jax.device_get(leaf)), np.asarray(ref[path]),
            rtol=5e-4, atol=2e-5, err_msg=f"{path}")

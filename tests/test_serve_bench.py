"""tools/serve_bench.py must never rot unexecuted: the fast suite runs
the CLI end-to-end (CPU, tiny config, 3 steps) and checks the JSON
contract — for the default Poisson trace AND the --prefix-share A/B
mode — and the bench.py staleness scanner (test_bench_stale.py
machinery) must surface the committed serve-bench artifacts the same
way it surfaces training-throughput records. The committed
artifacts/serve_r09.json additionally gates the PR 5 acceptance
numbers: shared-prefix cache-on >= 1.5x cache-off (or an equivalent
TTFT reduction) with a nonzero hit rate, and the cache-off path no
worse than PR 1's serve_r06.json record. artifacts/serve_r10.json
gates speculation the same way: spec-on >= 1.5x spec-off on the
repetitive greedy trace, spec-off no worse than serve_r09's plain
baseline. artifacts/serve_r11.json gates multi-tenant LoRA: one
multi-LoRA engine >= 1.5x the dedicated merged-weight-engine-per-
adapter baseline on the same N-tenants-x-M-adapters trace, with the
noise-free structural gate that each shared decode step replaces > 2
dedicated-engine steps. artifacts/serve_r13.json gates long-context
chunked prefill: concurrent decode tok/s during a long prefill >= 2x
the monolithic (widened-single-bucket) baseline on the same
document + decode-mix trace, plain default trace no worse than r10.
artifacts/serve_r14.json gates the quantized KV pool: at EQUAL POOL
BYTES the int8 side holds >= 1.8x the usable blocks and wins
structurally on the shared-prefix trace — admits more concurrently,
preempts less, evicts no cached chains — with the plain default trace
(f32 policy) no worse than r13. artifacts/obs_r15.json gates the
flight recorder (quintnet_tpu/obs/): observation must be nearly free —
tracing-on tok/s >= 0.95x tracing-off on the same trace (bit-identity
is pinned separately in tests/test_obs.py) with real spans and ring
records behind the numbers, and the obs-off side (the plain default
trace) no worse than r14's plain baseline. artifacts/serve_r18.json
gates the fused paged-attention Pallas kernels: the gates are
STRUCTURAL and wall-noise-free, because off-TPU the kernel runs in the
Pallas interpreter (which prices emulation, not the kernel) — every
request token-identical across backends on the same trace, and the
jaxpr auditor counting ZERO full-row gathered-view gathers in the
pallas decode program where the xla oracle issues 4 (int8: k + v +
both scale arrays); the plain xla record stays within the documented
CPU-noise band of r14's plain baseline. artifacts/serve_r19.json
gates the tiered KV cache (serve/kv_tier.py): on a many-tenant
prefix-churn trace whose prefix set costs 3x the device pool, the
host-tier side must beat the identical evict-only engine on warm hit
rate, TTFT (p50 AND p95), and tok/s, with the structural
decode_blocked_demotions == 0 — demotion copies never ride a decode
dispatch. (The r19 plain record is NOT gated against r14's value:
the box changed between eras — r19's plain gates are structural.)
artifacts/serve_r20.json gates MoE serving: the routing A/B replays a
diverse Poisson trace and a hot-expert (shared tiled pattern) trace
through the same capacity-bounded MoE engine, and the gates are
structural and wall-noise-free — the hot side's expert-utilization
skew exceeds the diverse side's, the routing ledger accounts exactly
(per-expert demand sums to the routed total, drops bounded by it,
drop rate reported for both sides), and the compile bound does not
move (MoE adds zero programs: same prefill ladder, one decode).
artifacts/serve_r21.json gates quantized weights
(serve/weight_quant.py): the --weights-ab record's gates are
structural and wall-noise-free — the int8 side's targeted-node byte
ratio >= 3.5x (per-channel scale overhead included) with a
paged_eval_nll quality delta under the serving gate, both sides
finishing the identical trace — and a second record serves fp8
weights + fp8 KV end-to-end through the default trace (the fp8 pool
bytes/token at exactly 1/4 of f32's). CPU walls are recorded but
never gated.
"""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.join(os.path.dirname(__file__), "..")
sys.path.insert(0, REPO)
import bench  # noqa: E402

SERVE_METRIC = "serve_gpt2_tiny_tokens_per_sec"
PREFIX_METRIC = "serve_gpt2_tiny_prefix_share_tokens_per_sec"
SPEC_METRIC = "serve_gpt2_tiny_spec_tokens_per_sec"
LORA_METRIC = "serve_gpt2_tiny_lora_tokens_per_sec"
LONG_METRIC = "serve_gpt2_tiny_long_tokens_per_sec"
KVCAP_METRIC = "serve_gpt2_tiny_kvcap_tokens_per_sec"
OBS_METRIC = "serve_gpt2_tiny_obs_tokens_per_sec"
KERNEL_METRIC = "serve_gpt2_tiny_kernel_tokens_per_sec"
TIER_METRIC = "serve_gpt2_tiny_tier_tokens_per_sec"
MOE_METRIC = "serve_gpt2_tiny_moe_tokens_per_sec"
WEIGHTS_METRIC = "serve_gpt2_tiny_weights_tokens_per_sec"
R09 = os.path.join(REPO, "artifacts", "serve_r09.json")
R10 = os.path.join(REPO, "artifacts", "serve_r10.json")
R11 = os.path.join(REPO, "artifacts", "serve_r11.json")
R13 = os.path.join(REPO, "artifacts", "serve_r13.json")
R14 = os.path.join(REPO, "artifacts", "serve_r14.json")
R15 = os.path.join(REPO, "artifacts", "obs_r15.json")
R18 = os.path.join(REPO, "artifacts", "serve_r18.json")
R19 = os.path.join(REPO, "artifacts", "serve_r19.json")
R20 = os.path.join(REPO, "artifacts", "serve_r20.json")
R21 = os.path.join(REPO, "artifacts", "serve_r21.json")


@pytest.mark.fast
def test_serve_bench_smoke_cli():
    """`serve_bench.py --steps 3 --synthetic` runs end-to-end on CPU and
    emits one well-formed JSON line with the acceptance fields."""
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "serve_bench.py"),
         "--steps", "3", "--synthetic"],
        capture_output=True, text=True, timeout=300,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert out.returncode == 0, out.stderr[-2000:]
    rec = json.loads(out.stdout.strip().splitlines()[-1])
    assert rec["metric"] == SERVE_METRIC
    assert rec["rc"] == 0
    assert rec["unit"] == "tok/s"
    for k in ("ttft_p50_s", "ttft_p95_s", "peak_kv_utilization",
              "decode_tokens", "prefill_tokens", "gen_tokens",
              "decode_steps", "tokens_per_decode_step"):
        assert k in rec["extras"], k
    assert rec["extras"]["spec"] is False


@pytest.mark.fast
def test_committed_serve_artifact_surfaces_in_staleness_scan():
    """The committed serve artifact is discoverable through the same
    last_known_result scanner the training bench uses, so a dead
    backend can fall back to the last real serving number too."""
    last = bench.last_known_result(metric=SERVE_METRIC)
    assert last is not None
    assert last["stale"] is True
    assert last["metric"] == SERVE_METRIC
    assert last["value"] > 0
    assert last["source"].startswith("artifacts")
    assert last["as_of"]


@pytest.mark.fast
def test_prefix_share_smoke_cli():
    """`serve_bench.py --prefix-share` runs the cache-on/cache-off A/B
    end-to-end on CPU (tiny trace, run to completion so retires happen
    and the cache actually gets hit) and reports the comparison
    fields."""
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "serve_bench.py"),
         "--synthetic", "--prefix-share", "--requests", "6",
         "--rate", "0.15", "--max-new", "4", "--shared-prefix", "24",
         "--min-tail", "2", "--max-tail", "4"],
        capture_output=True, text=True, timeout=300,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert out.returncode == 0, out.stderr[-2000:]
    rec = json.loads(out.stdout.strip().splitlines()[-1])
    assert rec["metric"] == PREFIX_METRIC
    assert rec["rc"] == 0
    e = rec["extras"]
    for k in ("cache_off_tokens_per_sec", "speedup_vs_cache_off",
              "prefix_hit_rate", "prefill_tokens_saved",
              "shared_prefix", "cache_off_ttft_p50_s"):
        assert k in e, k
    assert e["prefix_hit_rate"] > 0        # the cache actually served
    assert e["prefill_tokens_saved"] > 0
    assert e["finished"] == e["submitted"] == 6


@pytest.mark.fast
def test_committed_prefix_share_artifact_meets_acceptance():
    """The committed serve_r09.json is the PR's acceptance evidence:
    cache-on >= 1.5x cache-off tok/s on the shared-prefix trace (or an
    equivalent TTFT reduction), nonzero hit rate, and the cache-off
    plain-trace record no worse than PR 1's serve_r06.json."""
    with open(R09) as f:
        records = json.load(f)
    by_metric = {r["metric"]: r for r in records}

    share = by_metric[PREFIX_METRIC]
    e = share["extras"]
    assert e["prefix_hit_rate"] > 0
    assert e["prefill_tokens_saved"] > 0
    ttft_reduction = (e["cache_off_ttft_p50_s"] / e["ttft_p50_s"]
                      if e["ttft_p50_s"] else 0.0)
    assert (e["speedup_vs_cache_off"] >= 1.5
            or ttft_reduction >= 1.5), (
        f"prefix cache won neither throughput "
        f"({e['speedup_vs_cache_off']}x) nor TTFT ({ttft_reduction}x)")

    # cache-off baseline: the SAME plain synthetic trace as serve_r06,
    # through the new engine with the cache disabled — the bucketed
    # paged-prefill refactor must not regress the cache-off path
    plain = by_metric[SERVE_METRIC]
    assert plain["extras"]["prefix_cache"] is False
    with open(os.path.join(REPO, "artifacts", "serve_r06.json")) as f:
        r06 = [r for r in json.load(f) if r["metric"] == SERVE_METRIC]
    assert plain["value"] >= max(r["value"] for r in r06)


@pytest.mark.fast
def test_spec_smoke_cli():
    """`serve_bench.py --spec-trace` runs the speculation-on vs
    speculation-off A/B end-to-end on CPU (tiny trace, run to
    completion so drafting has history to match) and reports the
    comparison fields; `--spec on` works on the default trace too."""
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "serve_bench.py"),
         "--synthetic", "--spec-trace", "--pattern", "0", "--seed", "1",
         "--requests", "3", "--rate", "0.1", "--max-new", "24",
         "--min-prompt", "6", "--max-prompt", "10", "--slots", "2"],
        capture_output=True, text=True, timeout=300,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert out.returncode == 0, out.stderr[-2000:]
    rec = json.loads(out.stdout.strip().splitlines()[-1])
    assert rec["metric"] == SPEC_METRIC
    assert rec["rc"] == 0
    e = rec["extras"]
    for k in ("spec_off_tokens_per_sec", "speedup_vs_spec_off",
              "draft_acceptance_rate", "accepted_draft_tokens",
              "tokens_per_decode_step", "spec_off_tokens_per_decode_step",
              "decode_steps", "spec_off_decode_steps", "max_draft"):
        assert k in e, k
    assert e["spec"] is True
    assert e["finished"] == e["submitted"] == 3

    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "serve_bench.py"),
         "--steps", "3", "--synthetic", "--spec", "on"],
        capture_output=True, text=True, timeout=300,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert out.returncode == 0, out.stderr[-2000:]
    rec = json.loads(out.stdout.strip().splitlines()[-1])
    assert rec["metric"] == SERVE_METRIC
    assert rec["extras"]["spec"] is True
    assert "draft_acceptance_rate" in rec["extras"]


@pytest.mark.fast
def test_committed_spec_artifact_meets_acceptance():
    """The committed serve_r10.json is the speculation PR's acceptance
    evidence: spec-on >= 1.5x spec-off tok/s on the repetitive greedy
    trace with a real acceptance rate and multi-token decode steps,
    and the spec-off plain-trace record no worse than PR 5's
    serve_r09.json baseline."""
    with open(R10) as f:
        records = json.load(f)
    by_metric = {r["metric"]: r for r in records}

    spec = by_metric[SPEC_METRIC]
    e = spec["extras"]
    assert e["speedup_vs_spec_off"] >= 1.5, (
        f"speculation won only {e['speedup_vs_spec_off']}x")
    assert e["draft_acceptance_rate"] > 0.5
    assert e["accepted_draft_tokens"] > 0
    # the structural win, independent of wall-clock noise: committed
    # tokens per program invocation must be decisively multi-token
    assert e["tokens_per_decode_step"] \
        >= 2 * e["spec_off_tokens_per_decode_step"]

    # spec-off baseline: the plain synthetic trace, speculation and
    # prefix cache off — the verify-path rework must not regress the
    # non-speculating engine
    plain = by_metric[SERVE_METRIC]
    assert plain["extras"]["spec"] is False
    with open(R09) as f:
        r09 = [r for r in json.load(f) if r["metric"] == SERVE_METRIC]
    assert plain["value"] >= max(r["value"] for r in r09)


@pytest.mark.fast
def test_lora_trace_smoke_cli():
    """`serve_bench.py --lora-trace` runs the multi-LoRA vs dedicated
    merged-engines A/B end-to-end on CPU (tiny trace, adapters saved
    through the real safetensors path) and reports the comparison
    fields incl. the per-adapter ledger."""
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "serve_bench.py"),
         "--synthetic", "--lora-trace", "--requests", "6",
         "--adapters", "3", "--rate", "0.3", "--max-new", "4"],
        capture_output=True, text=True, timeout=300,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert out.returncode == 0, out.stderr[-2000:]
    rec = json.loads(out.stdout.strip().splitlines()[-1])
    assert rec["metric"] == LORA_METRIC
    assert rec["rc"] == 0
    e = rec["extras"]
    for k in ("merged_tokens_per_sec", "speedup_vs_merged",
              "decode_step_ratio_vs_merged", "merged_decode_steps",
              "adapters", "lora_rank", "per_adapter"):
        assert k in e, k
    assert e["finished"] == e["submitted"] == 6
    assert len(e["per_adapter"]) == 3          # every tenant served
    assert all(d["gen_tokens"] > 0 for d in e["per_adapter"].values())


@pytest.mark.fast
def test_committed_lora_artifact_meets_acceptance():
    """The committed serve_r11.json is the multi-tenant-LoRA PR's
    acceptance evidence: one multi-LoRA engine serving N tenants x M
    adapters beats the dedicated merged-weight-engine-per-adapter
    baseline >= 1.5x tok/s on the same trace (same-process A/B, so
    wall noise hits both sides), with the noise-free structural signal
    — dedicated-engine decode steps per shared multi-LoRA step —
    decisively > 2, every request finished, and every tenant's
    per-adapter ledger populated."""
    with open(R11) as f:
        records = json.load(f)
    by_metric = {r["metric"]: r for r in records}

    lora = by_metric[LORA_METRIC]
    e = lora["extras"]
    assert e["speedup_vs_merged"] >= 1.5, (
        f"multi-LoRA won only {e['speedup_vs_merged']}x over dedicated "
        f"merged engines")
    assert e["decode_step_ratio_vs_merged"] >= 2, (
        f"shared decode steps replaced only "
        f"{e['decode_step_ratio_vs_merged']}x dedicated steps")
    assert e["finished"] == e["submitted"] == e["requests"]
    assert len(e["per_adapter"]) == e["adapters"]
    assert all(d["requests"] > 0 and d["gen_tokens"] > 0
               for d in e["per_adapter"].values())
    # A/B accounting sanity: both sides generated the same tokens
    assert e["gen_tokens"] == e["merged_gen_tokens"]


@pytest.mark.fast
def test_long_trace_smoke_cli():
    """`serve_bench.py --long-trace` runs the chunked-vs-monolithic
    A/B end-to-end on CPU (tiny trace, document prompts longer than
    the chunked engine's whole prefill window) and reports the
    comparison fields; the chunked side really chunked and both sides
    finished everything."""
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "serve_bench.py"),
         "--synthetic", "--long-trace", "--requests", "4",
         "--rate", "0.3", "--max-new", "8", "--long-prompts", "1",
         "--long-prompt", "160", "--prefill-window", "64"],
        capture_output=True, text=True, timeout=300,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert out.returncode == 0, out.stderr[-2000:]
    rec = json.loads(out.stdout.strip().splitlines()[-1])
    assert rec["metric"] == LONG_METRIC
    assert rec["rc"] == 0
    e = rec["extras"]
    for k in ("decode_tps_during_long_prefill",
              "unchunked_decode_tps_during_long_prefill",
              "decode_tps_ratio_vs_unchunked", "prefill_chunks",
              "chunk_tokens_per_step", "itl_p99_s",
              "unchunked_itl_p99_s", "long_window_wall_s",
              "prefill_window", "chunk_budget", "long_prompt"):
        assert k in e, k
    assert e["long_prompt"] > e["prefill_window"]  # really long-context
    assert e["prefill_chunks"] >= e["long_prompt"] // e["chunk_budget"]
    assert e["finished"] == e["submitted"] == 4 + 1
    assert e["unchunked_finished"] == 5


@pytest.mark.fast
def test_committed_long_artifact_meets_acceptance():
    """The committed serve_r13.json is the long-context PR's
    acceptance evidence: decode tok/s under a concurrent long prefill
    >= 2x the unchunked (stall-prone, widened-single-bucket) baseline
    on the same trace — the measured ratio is committed in the record
    — with real chunk counts, every request finished on both sides,
    and the plain default-trace record (chunked machinery OFF) no
    worse than PR 6's serve_r10.json baseline."""
    with open(R13) as f:
        records = json.load(f)
    by_metric = {r["metric"]: r for r in records}

    rec = by_metric[LONG_METRIC]
    e = rec["extras"]
    assert e["decode_tps_ratio_vs_unchunked"] >= 2.0, (
        f"chunked prefill kept concurrent decode at only "
        f"{e['decode_tps_ratio_vs_unchunked']}x the monolithic "
        f"baseline")
    assert e["long_prompt"] > e["prefill_window"]
    assert e["prefill_chunks"] >= e["long_prompt"] // e["chunk_budget"]
    assert e["chunk_tokens_per_step"] <= e["chunk_budget"]
    assert e["finished"] == e["submitted"]
    assert e["unchunked_finished"] == e["submitted"]

    plain = by_metric[SERVE_METRIC]
    assert plain["extras"]["spec"] is False
    with open(R10) as f:
        r10 = [r for r in json.load(f) if r["metric"] == SERVE_METRIC]
    assert plain["value"] >= max(r["value"] for r in r10)


@pytest.mark.fast
def test_long_artifact_surfaces_in_staleness_scan():
    last = bench.last_known_result(metric=LONG_METRIC)
    assert last is not None
    assert last["metric"] == LONG_METRIC
    assert last["value"] > 0
    assert last["source"].startswith("artifacts")
    assert last["as_of"]


@pytest.mark.fast
def test_lora_artifact_surfaces_in_staleness_scan():
    last = bench.last_known_result(metric=LORA_METRIC)
    assert last is not None
    assert last["metric"] == LORA_METRIC
    assert last["value"] > 0
    assert last["source"].startswith("artifacts")
    assert last["as_of"]


@pytest.mark.fast
def test_spec_artifact_surfaces_in_staleness_scan():
    last = bench.last_known_result(metric=SPEC_METRIC)
    assert last is not None
    assert last["metric"] == SPEC_METRIC
    assert last["value"] > 0
    assert last["source"].startswith("artifacts")
    assert last["as_of"]


@pytest.mark.fast
def test_prefix_share_artifact_surfaces_in_staleness_scan():
    last = bench.last_known_result(metric=PREFIX_METRIC)
    assert last is not None
    assert last["metric"] == PREFIX_METRIC
    assert last["value"] > 0
    assert last["source"].startswith("artifacts")


@pytest.mark.fast
def test_kv_capacity_smoke_cli():
    """`serve_bench.py --kv-capacity` runs the equal-pool-bytes f32 vs
    int8 A/B end-to-end on CPU (tiny trace, run to completion) and
    reports the comparison fields; the quantized side really got more
    blocks for the same bytes and both sides finished everything."""
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "serve_bench.py"),
         "--synthetic", "--kv-capacity", "--requests", "6",
         "--rate", "0.3", "--max-new", "4", "--num-blocks", "10",
         "--shared-prefix", "24", "--min-tail", "2", "--max-tail", "4"],
        capture_output=True, text=True, timeout=300,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert out.returncode == 0, out.stderr[-2000:]
    rec = json.loads(out.stdout.strip().splitlines()[-1])
    assert rec["metric"] == KVCAP_METRIC
    assert rec["rc"] == 0
    e = rec["extras"]
    for k in ("usable_blocks_ratio", "pool_bytes_budget",
              "f32_num_blocks", "q_num_blocks", "f32_pool_bytes",
              "q_pool_bytes", "kv_bytes_per_token", "f32_preempted",
              "q_preempted", "f32_cache_evictions", "q_cache_evictions",
              "f32_tokens_per_sec", "f32_prefix_hit_rate",
              "q_prefix_hit_rate"):
        assert k in e, k
    assert e["kv_dtype"] == "int8"
    # equal bytes really bought more blocks (never exceeding budget)
    assert e["q_num_blocks"] > e["f32_num_blocks"]
    assert e["q_pool_bytes"] <= e["pool_bytes_budget"]
    assert e["usable_blocks_ratio"] >= 1.8
    assert e["finished"] == e["submitted"] == 6
    assert e["f32_finished"] == 6

    # --kv-dtype rides the default trace too (int8 engine end-to-end)
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "serve_bench.py"),
         "--steps", "3", "--synthetic", "--kv-dtype", "int8"],
        capture_output=True, text=True, timeout=300,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert out.returncode == 0, out.stderr[-2000:]
    rec = json.loads(out.stdout.strip().splitlines()[-1])
    assert rec["metric"] == SERVE_METRIC
    assert rec["extras"]["kv_dtype"] == "int8"
    assert rec["extras"]["kv_pool_bytes"] > 0


@pytest.mark.fast
def test_committed_kv_capacity_artifact_meets_acceptance():
    """The committed serve_r14.json is the quantized-KV PR's acceptance
    evidence. The CI gate is STRUCTURAL (wall-noise free): at equal
    pool bytes the int8 side holds >= 1.8x the usable blocks, admits
    more concurrently (peak running), preempts less, and evicts NO
    cached chains where the f32 pool thrashes — plus the throughput
    win that capacity buys. (Raw hit-rate comparisons are confounded
    under pressure — see tools/serve_bench.py — so retention is gated
    on evictions.) And the plain default-trace record (f32 policy,
    the passthrough path through the policy refactor) is no worse
    than PR 9's serve_r13.json baseline."""
    with open(R14) as f:
        records = json.load(f)
    by_metric = {r["metric"]: r for r in records}

    rec = by_metric[KVCAP_METRIC]
    e = rec["extras"]
    assert e["usable_blocks_ratio"] >= 1.8, (
        f"equal bytes bought only {e['usable_blocks_ratio']}x blocks")
    assert e["q_pool_bytes"] <= e["pool_bytes_budget"]
    assert e["f32_pool_bytes"] == e["pool_bytes_budget"]
    # the structural win: more concurrency, less thrash, at equal bytes
    assert e["q_peak_running"] > e["f32_peak_running"], "admits more"
    assert e["q_preempted"] < e["f32_preempted"], "preempts less"
    assert e["q_cache_evictions"] < e["f32_cache_evictions"], \
        "retains the shared chain"
    assert e["q_preempted"] == 0 and e["q_cache_evictions"] == 0
    assert rec["value"] > e["f32_tokens_per_sec"]
    assert e["finished"] == e["submitted"] == e["requests"]
    assert e["f32_finished"] == e["requests"]

    # plain f32 baseline: the policy refactor must not regress the
    # passthrough path (same default trace as every prior serve round)
    plain = by_metric[SERVE_METRIC]
    assert plain["extras"]["kv_dtype"] == "f32"
    with open(R13) as f:
        r13 = [r for r in json.load(f) if r["metric"] == SERVE_METRIC]
    assert plain["value"] >= max(r["value"] for r in r13)


@pytest.mark.fast
def test_kv_capacity_artifact_surfaces_in_staleness_scan():
    last = bench.last_known_result(metric=KVCAP_METRIC)
    assert last is not None
    assert last["metric"] == KVCAP_METRIC
    assert last["value"] > 0
    assert last["source"].startswith("artifacts")
    assert last["as_of"]


@pytest.mark.fast
def test_obs_ab_smoke_cli(tmp_path):
    """`serve_bench.py --obs-ab --trace-out` runs the observability
    overhead A/B end-to-end on CPU and emits both the comparison
    record and a Perfetto-loadable Chrome trace (validated by the real
    validator, not a shape check)."""
    trace_out = str(tmp_path / "trace.json")
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "serve_bench.py"),
         "--synthetic", "--obs-ab", "--requests", "6",
         "--rate", "0.3", "--max-new", "4", "--trace-out", trace_out],
        capture_output=True, text=True, timeout=300,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert out.returncode == 0, out.stderr[-2000:]
    rec = json.loads(out.stdout.strip().splitlines()[-1])
    assert rec["metric"] == OBS_METRIC
    assert rec["rc"] == 0
    e = rec["extras"]
    for k in ("obs_off_tokens_per_sec", "obs_on_ratio", "obs_traces",
              "obs_spans", "obs_ring_steps", "trace_events"):
        assert k in e, k
    assert e["obs_traces"] == 6          # every request traced
    assert e["obs_spans"] > 0 and e["obs_ring_steps"] > 0
    assert e["finished"] == e["submitted"] == 6

    from tools.trace_view import validate_chrome_trace

    with open(trace_out) as f:
        trace = json.load(f)
    assert validate_chrome_trace(trace) == e["trace_events"]
    phases = {ev["ph"] for ev in trace["traceEvents"]}
    assert "X" in phases                 # engine steps as slices
    assert "b" in phases and "e" in phases   # request async spans


@pytest.mark.fast
def test_committed_obs_artifact_meets_acceptance():
    """The committed obs_r15.json is the flight-recorder PR's
    acceptance evidence: observation is nearly free — tracing-on
    >= 0.95x tracing-off tok/s on the same trace (the A/B is
    warm-replay-first, obs-on timed before obs-off, so the ratio is
    conservative) — with real spans/ring behind it, everything
    finished on both sides, and the obs-off side (the plain default
    trace) no worse than r14's plain baseline."""
    with open(R15) as f:
        records = json.load(f)
    rec = {r["metric"]: r for r in records}[OBS_METRIC]
    e = rec["extras"]
    assert e["obs_on_ratio"] >= 0.95, (
        f"observation cost {1 - e['obs_on_ratio']:.1%} of throughput")
    assert rec["vs_baseline"] == e["obs_on_ratio"]
    assert e["obs_traces"] == e["requests"]
    assert e["obs_spans"] > 0
    assert e["obs_ring_steps"] > 0
    assert e["finished"] == e["submitted"] == e["requests"]
    # the obs-off side IS the plain default trace: no regression vs
    # the r14 plain baseline (same trace family, same machine era)
    with open(R14) as f:
        r14 = [r for r in json.load(f) if r["metric"] == SERVE_METRIC]
    assert e["obs_off_tokens_per_sec"] >= max(r["value"] for r in r14)


@pytest.mark.fast
def test_obs_artifact_surfaces_in_staleness_scan():
    last = bench.last_known_result(metric=OBS_METRIC)
    assert last is not None
    assert last["metric"] == OBS_METRIC
    assert last["value"] > 0
    assert last["source"].startswith("artifacts")
    assert last["as_of"]


@pytest.mark.fast
def test_mixed_offset_timestamps_ordered_correctly():
    """ADVICE r5: lexicographic ISO-string comparison picks the wrong
    newest across timezone offsets; the parsed ordering must not."""
    # lexicographically "2026-01-01T09:00:00+09:00" > "2026-01-01T01:30.."
    # but in UTC it is 00:00 vs 01:30 — the +09:00 stamp is OLDER
    a = "2026-01-01T09:00:00+09:00"
    b = "2026-01-01T01:30:00+00:00"
    dt_a, dt_b = bench._parse_as_of(a), bench._parse_as_of(b)
    assert dt_b > dt_a  # parsed ordering disagrees with string ordering
    assert a > b

    # naive stamps (mtime fallback) are treated as local time, not UTC
    naive = bench._parse_as_of("2026-01-01T01:30:00")
    assert naive.tzinfo is not None

    # and unparseable strings lose to any real timestamp
    assert bench._parse_as_of("not-a-date") < dt_a


@pytest.mark.fast
def test_kernel_ab_smoke_cli():
    """`serve_bench.py --kernel-ab` runs the xla-vs-pallas A/B
    end-to-end on CPU (tiny trace, interpret-mode kernel) and reports
    the structural comparison fields; `--kernel pallas` also serves
    the plain default trace."""
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "serve_bench.py"),
         "--synthetic", "--kernel-ab", "--requests", "6",
         "--rate", "0.3", "--max-new", "4"],
        capture_output=True, text=True, timeout=300,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert out.returncode == 0, out.stderr[-2000:]
    rec = json.loads(out.stdout.strip().splitlines()[-1])
    assert rec["metric"] == KERNEL_METRIC
    assert rec["rc"] == 0
    e = rec["extras"]
    for k in ("token_identical", "compared_requests",
              "mismatched_requests", "xla_gathered_view_gathers",
              "pallas_gathered_view_gathers", "xla_tokens_per_sec",
              "cpu_interpret_mode", "speedup_vs_xla"):
        assert k in e, k
    assert e["token_identical"] is True
    assert e["mismatched_requests"] == 0
    assert e["compared_requests"] == 6
    assert e["xla_gathered_view_gathers"] > 0
    assert e["pallas_gathered_view_gathers"] == 0
    assert e["finished"] == e["submitted"] == 6

    # --kernel pallas rides the default trace too (fused engine
    # end-to-end through the stock record shape)
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "serve_bench.py"),
         "--steps", "3", "--synthetic", "--kernel", "pallas"],
        capture_output=True, text=True, timeout=300,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert out.returncode == 0, out.stderr[-2000:]
    rec = json.loads(out.stdout.strip().splitlines()[-1])
    assert rec["metric"] == SERVE_METRIC
    assert rec["extras"]["attn_kernel"] == "pallas"


@pytest.mark.fast
def test_committed_kernel_artifact_meets_acceptance():
    """The committed serve_r18.json is the fused-kernel PR's
    acceptance evidence. Both gates are STRUCTURAL (benches are
    CPU-run, and interpret-mode walls price the Pallas emulator, not
    the kernel — explicitly NOT gated): every finished request's
    token stream identical across backends on the same int8 trace,
    and the auditor-verified no-gathered-view win — the pallas decode
    program issues ZERO full-row block-table gathers where the int8
    xla oracle issues 4 (k + v pools + both scale arrays). The plain
    xla record must stay within the documented CPU-noise band (>= 0.95,
    the obs_r15 convention; PR 6 measured +-20% wall noise on this
    box) of r14's plain baseline."""
    with open(R18) as f:
        records = json.load(f)
    by_metric = {r["metric"]: r for r in records}

    rec = by_metric[KERNEL_METRIC]
    e = rec["extras"]
    assert e["kv_dtype"] == "int8"
    assert e["token_identical"] is True
    assert e["mismatched_requests"] == 0
    assert e["compared_requests"] == e["requests"]
    assert e["finished"] == e["submitted"] == e["requests"]
    assert e["xla_finished"] == e["requests"]
    # THE structural win: the gathered view is never materialized
    assert e["xla_gathered_view_gathers"] == 4
    assert e["pallas_gathered_view_gathers"] == 0
    assert rec["value"] > 0 and e["xla_tokens_per_sec"] > 0

    # plain xla baseline: the kernel-dispatch refactor must not
    # regress the default path (noise-banded vs r14's plain record)
    plain = by_metric[SERVE_METRIC]
    assert plain["extras"]["kv_dtype"] == "f32"
    assert plain["extras"]["attn_kernel"] == "xla"
    with open(R14) as f:
        r14 = [r for r in json.load(f) if r["metric"] == SERVE_METRIC]
    assert plain["value"] >= 0.95 * max(r["value"] for r in r14)


@pytest.mark.fast
def test_kernel_artifact_surfaces_in_staleness_scan():
    last = bench.last_known_result(metric=KERNEL_METRIC)
    assert last is not None
    assert last["metric"] == KERNEL_METRIC
    assert last["value"] > 0
    assert last["source"].startswith("artifacts")
    assert last["as_of"]


@pytest.mark.fast
def test_tier_trace_smoke_cli():
    """`serve_bench.py --tier-trace` runs the tiered-vs-evict-only A/B
    end-to-end on CPU. The tiny sizes still force real churn (4
    prefixes x 2-3 blocks against a 7-usable-block pool), so the
    smoke asserts the tier actually CYCLED — demotions, promotions,
    and host-hit tokens all nonzero — not just that the fields
    exist."""
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "serve_bench.py"),
         "--synthetic", "--tier-trace", "--tier-prefixes", "4",
         "--tier-repeats", "2", "--rate", "0.3", "--max-new", "4",
         "--shared-prefix", "16", "--block-size", "8",
         "--num-blocks", "8", "--slots", "2",
         "--min-tail", "2", "--max-tail", "6"],
        capture_output=True, text=True, timeout=300,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert out.returncode == 0, out.stderr[-2000:]
    rec = json.loads(out.stdout.strip().splitlines()[-1])
    assert rec["metric"] == TIER_METRIC
    assert rec["rc"] == 0
    e = rec["extras"]
    for k in ("warm_hit_rate", "evict_only_hit_rate",
              "evict_only_ttft_p50_s", "evict_only_tokens_per_sec",
              "tier_byte_budget", "host_hit_rate",
              "speedup_vs_evict_only"):
        assert k in e, k
    assert e["kv_demotions"] > 0        # eviction pressure spilled
    assert e["kv_promotions"] > 0       # revisits came back from host
    assert e["host_hit_tokens"] > 0
    assert e["warm_hit_rate"] > e["evict_only_hit_rate"]
    # the tier's latency contract, structurally
    assert e["decode_blocked_demotions"] == 0
    assert e["finished"] == e["submitted"] == 8
    assert e["evict_only_finished"] == 8


@pytest.mark.fast
def test_committed_tier_artifact_meets_acceptance():
    """The committed serve_r19.json is the tiered-KV PR's acceptance
    evidence: on a prefix set costing 3x the device pool, spilling to
    host RAM must beat re-prefilling from scratch — warm hit rate,
    TTFT p50 AND p95, and tok/s all better than the identical
    evict-only engine on the same trace — and the structural latency
    contract holds: zero demotions observed inside a plain decode
    dispatch. The plain record is gated structurally only (finished
    everything, f32 passthrough); the box changed between artifact
    eras, so cross-era wall comparisons would gate noise, not code."""
    with open(R19) as f:
        records = json.load(f)
    by_metric = {r["metric"]: r for r in records}

    rec = by_metric[TIER_METRIC]
    e = rec["extras"]
    assert e["tier_trace"] is True
    assert e["finished"] == e["submitted"] == e["requests"]
    assert e["evict_only_finished"] == e["requests"]
    # the churn actually happened: the prefix set overflowed the
    # device pool, spilled, and came back
    assert e["kv_demotions"] > 0
    assert e["kv_promotions"] > 0
    assert e["host_hit_tokens"] > 0
    # the A/B wins: hit rate, TTFT (both percentiles), throughput
    assert e["warm_hit_rate"] > e["evict_only_hit_rate"]
    assert e["ttft_p50_s"] < e["evict_only_ttft_p50_s"]
    assert e["ttft_p95_s"] < e["evict_only_ttft_p95_s"]
    assert rec["vs_baseline"] > 1.0
    assert rec["value"] > e["evict_only_tokens_per_sec"] > 0
    # THE structural gate: a demotion copy never rides a decode
    # dispatch — promotion is budgeted, demotion is eviction-time
    assert e["decode_blocked_demotions"] == 0

    plain = by_metric[SERVE_METRIC]
    pe = plain["extras"]
    assert pe["kv_dtype"] == "f32"
    assert pe["finished"] == pe["submitted"] == pe["requests"]
    assert plain["value"] > 0


@pytest.mark.fast
def test_tier_artifact_surfaces_in_staleness_scan():
    last = bench.last_known_result(metric=TIER_METRIC)
    assert last is not None
    assert last["metric"] == TIER_METRIC
    assert last["value"] > 0
    assert last["source"].startswith("artifacts")
    assert last["as_of"]


@pytest.mark.fast
def test_moe_trace_smoke_cli():
    """`serve_bench.py --moe-trace` runs the diverse-vs-hot-expert A/B
    end-to-end on CPU through a real MoE engine (the bench's own
    runtime asserts already gate the routing ledger and the compile
    bound — a leak or a recompile exits nonzero). The smoke checks the
    record shape and that routing actually happened on both sides."""
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "serve_bench.py"),
         "--synthetic", "--moe-trace", "--requests", "10",
         "--max-new", "6", "--seed", "3"],
        capture_output=True, text=True, timeout=300,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert out.returncode == 0, out.stderr[-2000:]
    rec = json.loads(out.stdout.strip().splitlines()[-1])
    assert rec["metric"] == MOE_METRIC
    assert rec["rc"] == 0
    e = rec["extras"]
    for k in ("hot_expert_skew", "diverse_expert_skew",
              "hot_drop_rate", "diverse_drop_rate",
              "hot_router_entropy", "hot_expert_tokens",
              "diverse_expert_tokens", "compile_counts"):
        assert k in e, k
    assert e["experts"] == 4 and e["expert_top_k"] == 2
    assert e["hot_routed_tokens"] > 0
    assert e["diverse_routed_tokens"] > 0
    assert len(e["hot_expert_tokens"]) == e["experts"]
    # a max/mean skew is >= 1 by construction; > 1 means the router
    # actually discriminated between experts
    assert e["hot_expert_skew"] >= 1.0
    assert e["finished"] == e["submitted"] == 10
    # MoE adds zero programs to the engine's compile bound
    assert e["compile_counts"]["decode"] == 1


@pytest.mark.fast
def test_committed_moe_artifact_meets_acceptance():
    """The committed serve_r20.json is the MoE-serving PR's acceptance
    evidence, and every gate is structural (wall-noise-free): the
    hot-expert trace concentrates routed demand — its expert skew
    exceeds the diverse trace's — the routing ledger accounts exactly
    on BOTH sides (per-expert demand sums to the routed total, drops
    bounded by it), capacity drops are reported as rates in [0, 1],
    and the compile bound is untouched (one decode program; the
    prefill count is the ladder's, not MoE's). The plain record is
    gated structurally only, per the r19 precedent."""
    with open(R20) as f:
        records = json.load(f)
    by_metric = {r["metric"]: r for r in records}

    rec = by_metric[MOE_METRIC]
    e = rec["extras"]
    assert e["moe_trace"] is True
    assert e["finished"] == e["submitted"] == e["requests"]
    # the A/B's point: skewed traffic shows up in the ledger
    assert e["hot_expert_skew"] > e["diverse_expert_skew"] >= 1.0
    # the ledger accounts exactly, both sides
    for side in ("hot", "diverse"):
        tokens = e[f"{side}_expert_tokens"]
        assert len(tokens) == e["experts"]
        assert sum(tokens.values()) == e[f"{side}_routed_tokens"] > 0
        assert 0 <= e[f"{side}_dropped_tokens"] \
            <= e[f"{side}_routed_tokens"]
        assert 0.0 <= e[f"{side}_drop_rate"] <= 1.0
        assert e[f"{side}_router_entropy"] > 0.0
    # capacity pressure was real on the skewed side
    assert e["hot_dropped_tokens"] > 0
    # compile bound unchanged: MoE added zero programs
    assert e["compile_counts"]["decode"] == 1
    assert rec["value"] > 0
    assert rec["vs_baseline"] > 0

    plain = by_metric[SERVE_METRIC]
    pe = plain["extras"]
    assert pe["kv_dtype"] == "f32"
    assert pe["finished"] == pe["submitted"] == pe["requests"]
    assert plain["value"] > 0


@pytest.mark.fast
def test_moe_artifact_surfaces_in_staleness_scan():
    last = bench.last_known_result(metric=MOE_METRIC)
    assert last is not None
    assert last["metric"] == MOE_METRIC
    assert last["value"] > 0
    assert last["source"].startswith("artifacts")
    assert last["as_of"]


# ---------------------------------------------------------------------
# quantized weights (serve/weight_quant.py, --weights-ab)
# ---------------------------------------------------------------------

@pytest.mark.fast
def test_weights_ab_smoke_cli():
    """`serve_bench.py --weights-ab` runs the f32-vs-int8 weight A/B
    end-to-end on CPU (tiny trace, run to completion): both engines
    finish the identical trace, the packed side really shrinks the
    targeted weight bytes, and the quality delta is reported."""
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "serve_bench.py"),
         "--synthetic", "--weights-ab", "--requests", "6",
         "--rate", "0.3", "--max-new", "4"],
        capture_output=True, text=True, timeout=300,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert out.returncode == 0, out.stderr[-2000:]
    rec = json.loads(out.stdout.strip().splitlines()[-1])
    assert rec["metric"] == WEIGHTS_METRIC
    assert rec["rc"] == 0
    e = rec["extras"]
    for k in ("weight_bytes_ratio", "f32_weight_bytes",
              "q_weight_bytes", "eval_nll_f32", "eval_nll_q",
              "eval_nll_delta", "f32_tokens_per_sec", "f32_wall_s"):
        assert k in e, k
    assert e["weights_dtype"] == "int8"
    assert e["q_weight_bytes"] < e["f32_weight_bytes"]
    assert e["weight_bytes_ratio"] >= 3.5
    assert e["finished"] == e["submitted"] == 6
    assert e["f32_finished"] == 6

    # --weights-dtype rides the default trace too (int8 end-to-end)
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "serve_bench.py"),
         "--steps", "3", "--synthetic", "--weights-dtype", "int8"],
        capture_output=True, text=True, timeout=300,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert out.returncode == 0, out.stderr[-2000:]
    rec = json.loads(out.stdout.strip().splitlines()[-1])
    assert rec["metric"] == SERVE_METRIC
    assert rec["extras"]["weights_dtype"] == "int8"


@pytest.mark.fast
def test_committed_weights_artifact_meets_acceptance():
    """The committed serve_r21.json is the quantized-weights PR's
    acceptance evidence. The CI gates are STRUCTURAL (wall-noise
    free, never a cross-era tok/s comparison): the int8 side's
    targeted-node byte ratio >= 3.5x (the 3.94x raw int8 shrink minus
    the per-channel f32 scale overhead), the paged teacher-forced NLL
    delta under the serving quality gate, both sides finishing the
    identical trace; and the second record serves fp8 weights + fp8
    KV end-to-end with the pool's bytes/token at exactly 1/4 of
    f32's 512."""
    with open(R21) as f:
        records = json.load(f)
    by_metric = {r["metric"]: r for r in records}

    rec = by_metric[WEIGHTS_METRIC]
    e = rec["extras"]
    assert e["weights_ab"] is True
    assert e["weights_dtype"] == "int8"
    # THE structural gate: >= 3.5x fewer bytes on the serving matmul
    # weights (scale overhead included), quality within the gate
    assert e["weight_bytes_ratio"] >= 3.5, (
        f"int8 packed only {e['weight_bytes_ratio']}x")
    assert e["q_weight_bytes"] < e["f32_weight_bytes"]
    assert abs(e["eval_nll_delta"]) < 0.05
    assert e["finished"] == e["submitted"] == e["requests"]
    assert e["f32_finished"] == e["requests"]
    assert rec["value"] > 0  # wall recorded, never gated cross-era

    # fp8 end-to-end: weights AND KV pool in float8 on the default
    # trace — the pool's per-token bytes at exactly f32/4
    fp8 = by_metric[SERVE_METRIC]
    fe = fp8["extras"]
    assert fe["weights_dtype"] == "fp8"
    assert fe["kv_dtype"] == "fp8"
    assert fe["kv_bytes_per_token"] == 128.0
    assert fe["finished"] == fe["submitted"] == fe["requests"]
    assert fp8["value"] > 0


@pytest.mark.fast
def test_weights_artifact_surfaces_in_staleness_scan():
    last = bench.last_known_result(metric=WEIGHTS_METRIC)
    assert last is not None
    assert last["metric"] == WEIGHTS_METRIC
    assert last["value"] > 0
    assert last["source"].startswith("artifacts")
    assert last["as_of"]

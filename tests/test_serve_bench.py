"""tools/serve_bench.py must never rot unexecuted: the fast suite runs
the CLI end-to-end (CPU, tiny config, 3 steps) and checks the JSON
contract, and the bench.py staleness scanner (test_bench_stale.py
machinery) must surface the committed serve-bench artifact the same way
it surfaces training-throughput records.
"""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.join(os.path.dirname(__file__), "..")
sys.path.insert(0, REPO)
import bench  # noqa: E402

SERVE_METRIC = "serve_gpt2_tiny_tokens_per_sec"


@pytest.mark.fast
def test_serve_bench_smoke_cli():
    """`serve_bench.py --steps 3 --synthetic` runs end-to-end on CPU and
    emits one well-formed JSON line with the acceptance fields."""
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "serve_bench.py"),
         "--steps", "3", "--synthetic"],
        capture_output=True, text=True, timeout=300,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert out.returncode == 0, out.stderr[-2000:]
    rec = json.loads(out.stdout.strip().splitlines()[-1])
    assert rec["metric"] == SERVE_METRIC
    assert rec["rc"] == 0
    assert rec["unit"] == "tok/s"
    for k in ("ttft_p50_s", "ttft_p95_s", "peak_kv_utilization",
              "decode_tokens", "prefill_tokens"):
        assert k in rec["extras"], k


@pytest.mark.fast
def test_committed_serve_artifact_surfaces_in_staleness_scan():
    """The committed serve artifact is discoverable through the same
    last_known_result scanner the training bench uses, so a dead
    backend can fall back to the last real serving number too."""
    last = bench.last_known_result(metric=SERVE_METRIC)
    assert last is not None
    assert last["stale"] is True
    assert last["metric"] == SERVE_METRIC
    assert last["value"] > 0
    assert last["source"].startswith("artifacts")
    assert last["as_of"]


@pytest.mark.fast
def test_mixed_offset_timestamps_ordered_correctly():
    """ADVICE r5: lexicographic ISO-string comparison picks the wrong
    newest across timezone offsets; the parsed ordering must not."""
    # lexicographically "2026-01-01T09:00:00+09:00" > "2026-01-01T01:30.."
    # but in UTC it is 00:00 vs 01:30 — the +09:00 stamp is OLDER
    a = "2026-01-01T09:00:00+09:00"
    b = "2026-01-01T01:30:00+00:00"
    dt_a, dt_b = bench._parse_as_of(a), bench._parse_as_of(b)
    assert dt_b > dt_a  # parsed ordering disagrees with string ordering
    assert a > b

    # naive stamps (mtime fallback) are treated as local time, not UTC
    naive = bench._parse_as_of("2026-01-01T01:30:00")
    assert naive.tzinfo is not None

    # and unparseable strings lose to any real timestamp
    assert bench._parse_as_of("not-a-date") < dt_a

"""Disaggregated prefill/decode serving goldens
(quintnet_tpu/fleet/proc.py ``pools=`` + serve/kv_pool.py chain
export/import + fleet/wire.py KV frames).

THE contract, in layers:

- **pool**: an exported chain imports byte-exactly (blocks + scales)
  and becomes a warm prefix hit; a full pool or cache-off import
  returns 0 (the caller re-prefills — the chain is cache, not state);
- **engine**: a ``prefill_only`` request commits + streams its first
  token with the REAL last flag, retires with blocks published, and
  the decode-side continuation — warm via the imported chain or cold
  via local re-prefill — is BIT-identical to a colocated engine
  serving the whole request (greedy AND sampled, f32 AND int8);
- **fleet** (fast smoke + slow chaos tier): a real two-pool
  ProcessFleet serves token-identical to the colocated oracle with
  the KV handoff observable in the metrics, and every handoff fault —
  SIGKILL'd exporter, corrupted frame, stalled receiver — finishes
  every request token-identical via retry or local-prefill fallback,
  with the failure visible in the typed event log;
- **degradation ladder**: prefill pool down -> the decode pool
  absorbs prefill work (still token-identical, /healthz says
  ``degraded``); decode pool hard-down (every breaker tripped) ->
  new work sheds typed ``Overloaded('pool_down')`` while admitted
  work requeues behind the breaker.
"""

import http.client
import json
import os
import time

import jax
import numpy as np
import pytest

from quintnet_tpu.fleet import (ANY_POOL, FrontDoor, Overloaded,
                                ProcessFleet, RetryPolicy, eligible)
from quintnet_tpu.fleet.admission import SHED_REASONS
from quintnet_tpu.models.gpt2 import GPT2Config, gpt2_init
from quintnet_tpu.obs.events import EVENT_KINDS
from quintnet_tpu.serve import ServeEngine, gpt2_family
from quintnet_tpu.serve.kv_pool import KVPool
from quintnet_tpu.serve.scheduler import RequestProgress

CFG = GPT2Config.tiny(n_layer=2)
FACTORY_FILE = os.path.join(os.path.dirname(__file__),
                            "_proc_factories.py")


@pytest.fixture(scope="module")
def params():
    return gpt2_init(jax.random.key(0), CFG)


def _spec(**kw):
    kwargs = {"temperature": 0.8, "top_k": 5, "max_seq_len": 40,
              "num_blocks": 32, "block_size": 4}
    kwargs.update(kw)
    return {"file": FACTORY_FILE, "func": "build_tiny_gpt2",
            "kwargs": kwargs}


def _engine(params, **kw):
    kwargs = dict(max_slots=2, block_size=4, num_blocks=32,
                  max_seq_len=40, temperature=0.8, top_k=5)
    kwargs.update(kw)
    return ServeEngine(gpt2_family(CFG), params, **kwargs)


def _colocated_outputs(params, prompts, keys, max_new=8, **kw):
    """The oracle: ONE engine (same spec) serving each request whole."""
    eng = _engine(params, **kw)
    outs = []
    for p, k in zip(prompts, keys):
        rid = eng.submit(p, max_new, key=k)
        eng.run(max_steps=400)
        outs.append(np.asarray(eng.result(rid)))
    return outs


def _advance(key, n):
    for _ in range(n):
        key = jax.random.split(key, 2)[0]
    return key


def _wait_until(pred, *, timeout=60.0, msg=""):
    t0 = time.monotonic()
    while not pred():
        if time.monotonic() - t0 > timeout:
            raise AssertionError(f"timed out waiting for: {msg}")
        time.sleep(0.02)


# ---------------------------------------------------------------------
# pool layer
# ---------------------------------------------------------------------


class TestPoolChainExportImport:
    def _publish_chain(self, pool, toks):
        blocks = pool.acquire(pool.blocks_for(len(toks)))
        k = pool.k
        for i, b in enumerate(blocks):
            bs = pool.block_size
            k = k.at[:, b * bs:(b + 1) * bs].set(i + 1)
        pool.update(k, pool.v, *(() if not pool.policy.scaled
                                 else (pool.k_scale, pool.v_scale)))
        pool.publish(toks, blocks, len(toks))
        pool.release(blocks)
        return blocks

    def test_missing_chain_exports_none(self):
        pool = KVPool(n_layers=1, n_kv_heads=2, head_dim=4,
                      block_size=4, num_blocks=8)
        assert pool.export_chain(np.arange(6, dtype=np.int32)) is None

    def test_round_trip_is_byte_exact_and_hits(self):
        toks = np.arange(10, dtype=np.int32)
        src = KVPool(n_layers=2, n_kv_heads=2, head_dim=4,
                     block_size=4, num_blocks=8)
        self._publish_chain(src, toks)
        chain = src.export_chain(toks)
        dst = KVPool(n_layers=2, n_kv_heads=2, head_dim=4,
                     block_size=4, num_blocks=8)
        assert dst.import_chain(chain) == 10
        back = dst.export_chain(toks)
        assert back["n_tokens"] == 10
        for a, b in zip(chain["blocks"], back["blocks"]):
            np.testing.assert_array_equal(a["k"], b["k"])
            np.testing.assert_array_equal(a["v"], b["v"])

    def test_full_pool_import_returns_zero_not_raises(self):
        toks = np.arange(10, dtype=np.int32)
        src = KVPool(n_layers=1, n_kv_heads=2, head_dim=4,
                     block_size=4, num_blocks=8)
        self._publish_chain(src, toks)
        chain = src.export_chain(toks)
        dst = KVPool(n_layers=1, n_kv_heads=2, head_dim=4,
                     block_size=4, num_blocks=4)
        held = dst.acquire(3)            # pool fully referenced
        assert held is not None
        assert dst.import_chain(chain) == 0   # fallback, not failure

    def test_cache_off_import_returns_zero(self):
        toks = np.arange(8, dtype=np.int32)
        src = KVPool(n_layers=1, n_kv_heads=2, head_dim=4,
                     block_size=4, num_blocks=8)
        self._publish_chain(src, toks)
        chain = src.export_chain(toks)
        dst = KVPool(n_layers=1, n_kv_heads=2, head_dim=4,
                     block_size=4, num_blocks=8, prefix_cache=False)
        assert dst.import_chain(chain) == 0

    def test_incumbent_chain_survives_duplicate_import(self):
        """A racing local prefill published first: the import must not
        replace the incumbent blocks (publish keeps incumbents), and
        the duplicate's blocks return to the free list."""
        toks = np.arange(8, dtype=np.int32)
        src = KVPool(n_layers=1, n_kv_heads=2, head_dim=4,
                     block_size=4, num_blocks=8)
        self._publish_chain(src, toks)
        chain = src.export_chain(toks)
        dst = KVPool(n_layers=1, n_kv_heads=2, head_dim=4,
                     block_size=4, num_blocks=8)
        incumbent = self._publish_chain(dst, toks)
        free0 = dst.num_free
        dst.import_chain(chain)
        plan = dst.lookup(toks, max_tokens=8)
        assert plan.shared_blocks == incumbent[:len(plan.shared_blocks)]
        assert dst.num_free == free0     # duplicate blocks freed


# ---------------------------------------------------------------------
# engine layer: prefill_only + the disagg golden
# ---------------------------------------------------------------------


class TestPrefillOnly:
    def test_hands_off_with_real_last_flag(self, params, rng):
        eng = _engine(params)
        prompt = np.asarray(rng.integers(0, CFG.vocab_size, (6,)),
                            np.int32)
        seen = []
        rid = eng.submit(prompt, 8, key=jax.random.key(1),
                         on_token=lambda r, t, l: seen.append((t, l)),
                         prefill_only=True)
        eng.run(max_steps=20)
        req = eng.request(rid)
        assert req.handed_off is True
        assert len(req.generated) == 1
        assert seen == [(req.generated[0], False)]   # NOT last: 7 left
        # the chain was published — the handoff payload exists
        assert eng.export_kv_chain(prompt)["n_tokens"] == len(prompt)

    def test_one_token_budget_finishes_normally(self, params, rng):
        eng = _engine(params)
        prompt = np.asarray(rng.integers(0, CFG.vocab_size, (5,)),
                            np.int32)
        seen = []
        rid = eng.submit(prompt, 1, key=jax.random.key(2),
                         on_token=lambda r, t, l: seen.append((t, l)),
                         prefill_only=True)
        eng.run(max_steps=20)
        req = eng.request(rid)
        assert req.handed_off is False    # complete, nothing to move
        assert seen[0][1] is True         # real last flag

    def test_eos_on_first_token_finishes_normally(self, params, rng):
        prompt = np.asarray(rng.integers(0, CFG.vocab_size, (5,)),
                            np.int32)
        greedy = _engine(params, temperature=0.0, top_k=0)
        rid = greedy.submit(prompt, 8, prefill_only=True)
        greedy.run(max_steps=20)
        t0 = greedy.request(rid).generated[0]
        eng = _engine(params, temperature=0.0, top_k=0,
                      eos_token_id=int(t0))
        seen = []
        rid = eng.submit(prompt, 8, prefill_only=True,
                         on_token=lambda r, t, l: seen.append((t, l)))
        eng.run(max_steps=20)
        req = eng.request(rid)
        assert req.handed_off is False    # EOS = genuinely done
        assert seen == [(int(t0), True)]


class TestDisaggGolden:
    """Disaggregated output BIT-identical to colocated — greedy AND
    sampled, prefix-cache-on, f32 AND int8 KV — through the in-process
    engine pair (prefill engine -> exported chain -> decode engine),
    both with the chain transferred (warm) and without (the local
    re-prefill fallback)."""

    @pytest.mark.parametrize("kv,sample", [
        ("f32", False), ("f32", True), ("int8", True), ("int8", False),
    ])
    def test_warm_and_cold_match_colocated(self, params, rng, kv,
                                           sample):
        kw = (dict(kv_dtype=kv) if sample
              else dict(kv_dtype=kv, temperature=0.0, top_k=0))
        prompts = [np.asarray(rng.integers(0, CFG.vocab_size, (n,)),
                              np.int32) for n in (5, 7)]
        keys = [jax.random.key(40 + i) for i in range(2)]
        colocated = _colocated_outputs(params, prompts, keys, **kw)

        for prompt, key, want in zip(prompts, keys, colocated):
            A = _engine(params, **kw)          # prefill replica
            ra = A.submit(prompt, 8, key=key, prefill_only=True)
            A.run(max_steps=50)
            gen = list(A.request(ra).generated)
            chain = A.export_kv_chain(prompt)
            assert chain is not None

            prog = RequestProgress(
                rid=0, prompt=prompt, generated=gen,
                key_data=np.asarray(jax.random.key_data(
                    _advance(key, len(gen)))),
                max_new_tokens=8)

            B = _engine(params, **kw)          # decode replica, warm
            assert B.import_kv_chain(chain) == len(prompt)
            rb = B.restore_progress(prog)
            B.run(max_steps=200)
            np.testing.assert_array_equal(B.result(rb), want)
            assert B.metrics.summary()["prefill_tokens_saved"] > 0

            C = _engine(params, **kw)          # decode replica, cold
            rc = C.restore_progress(RequestProgress(
                rid=0, prompt=prompt, generated=gen,
                key_data=np.asarray(jax.random.key_data(
                    _advance(key, len(gen)))),
                max_new_tokens=8))
            C.run(max_steps=200)
            np.testing.assert_array_equal(C.result(rc), want)


# ---------------------------------------------------------------------
# routing / shedding / health units (no processes)
# ---------------------------------------------------------------------


class _StubReplica:
    def __init__(self, name, pool=ANY_POOL, state="healthy",
                 in_flight=0):
        self.name = name
        self.pool = pool
        self.state = state
        self.paused = False
        self.in_flight = in_flight
        self.max_dispatch = 4
        self.outstanding_tokens = 0

    def adapter_resident(self, adapter_id):
        return False


class TestPoolEligibility:
    def test_pool_filter_matches_pool_and_any(self):
        reps = [_StubReplica("prefill0", "prefill"),
                _StubReplica("decode0", "decode"),
                _StubReplica("c0")]      # colocated, pool "any"
        assert [r.name for r in eligible(reps, pool="prefill")] == \
            ["prefill0", "c0"]
        assert [r.name for r in eligible(reps, pool="decode")] == \
            ["decode0", "c0"]
        # pool=None is the colocated predicate, byte-identical
        assert [r.name for r in eligible(reps)] == \
            ["prefill0", "decode0", "c0"]

    def test_state_and_window_still_apply(self):
        reps = [_StubReplica("prefill0", "prefill", state="dead"),
                _StubReplica("prefill1", "prefill", in_flight=4)]
        assert eligible(reps, pool="prefill") == []

    def test_thread_replicas_without_pool_attr_match_any_pool(self):
        class Bare:
            name = "t0"
            state = "healthy"
            paused = False
            in_flight = 0
            max_dispatch = 2

        bare = Bare()
        assert eligible([bare], pool="decode") == [bare]


class TestTypedSurface:
    def test_pool_down_is_a_known_shed_reason(self):
        assert "pool_down" in SHED_REASONS
        e = Overloaded("pool_down", "decode pool is gone")
        assert e.reason == "pool_down"

    def test_frontdoor_maps_pool_down_to_503_with_retry_after(self):
        fd = FrontDoor(fleet=None)
        status, body, headers = fd._error_response(
            Overloaded("pool_down", "nope"))
        assert status == 503
        assert body["reason"] == "pool_down"
        assert "Retry-After" in headers

    def test_handoff_event_kinds_registered(self):
        assert {"handoff", "handoff_retry", "handoff_fallback",
                "pool_degraded", "pool_recovered"} <= EVENT_KINDS

    def test_pools_spec_validated(self):
        with pytest.raises(ValueError, match="exactly"):
            ProcessFleet({"file": "x", "func": "f"},
                         pools={"prefill": 1})
        with pytest.raises(ValueError, match=">= 1 replica"):
            ProcessFleet({"file": "x", "func": "f"},
                         pools={"prefill": 1, "decode": 0})


class _StubHealthFleet:
    """Just enough fleet for FrontDoor's /healthz."""

    def __init__(self, pools, draining=False):
        self._pools = pools
        self._draining = draining

    def health(self):
        replicas = {}
        for pool, states in self._pools.items():
            for i, st in enumerate(states):
                replicas[f"{pool}{i}"] = {"state": st, "pool": pool}
        return {
            "replicas": replicas,
            "pools": {
                pool: {"replicas": [f"{pool}{i}"
                                    for i in range(len(states))],
                       "healthy": sum(s == "healthy" for s in states),
                       "starting": 0,
                       "state": ("up" if any(s == "healthy"
                                             for s in states)
                                 else "down")}
                for pool, states in self._pools.items()},
            "disaggregated": len(self._pools) > 1,
            "queue_depth": 0, "open_requests": 0,
            "draining": self._draining,
        }


def _get_healthz(fleet):
    with FrontDoor(fleet) as fd:
        conn = http.client.HTTPConnection(fd.host, fd.port, timeout=10)
        conn.request("GET", "/healthz")
        resp = conn.getresponse()
        body = json.loads(resp.read().decode())
        headers = dict(resp.getheaders())
        conn.close()
    return resp.status, body, headers


class TestHealthzPoolMapping:
    """The satellite contract: 200 + status=degraded when one pool is
    down but the ladder still serves; 503 + Retry-After only when
    nothing can serve."""

    def test_all_pools_up_is_200_ok(self):
        status, body, _h = _get_healthz(_StubHealthFleet(
            {"prefill": ["healthy"], "decode": ["healthy", "healthy"]}))
        assert status == 200 and body["status"] == "ok"

    @pytest.mark.parametrize("down_pool", ["prefill", "decode"])
    def test_one_pool_down_is_200_degraded(self, down_pool):
        pools = {"prefill": ["healthy"], "decode": ["healthy"]}
        pools[down_pool] = ["dead"]
        status, body, _h = _get_healthz(_StubHealthFleet(pools))
        assert status == 200
        assert body["status"] == "degraded"
        assert body["pools"][down_pool]["state"] == "down"

    def test_both_pools_down_is_503_with_retry_after(self):
        status, body, headers = _get_healthz(_StubHealthFleet(
            {"prefill": ["dead"], "decode": ["dead", "stalled"]}))
        assert status == 503
        assert body["status"] == "unavailable"
        assert "Retry-After" in headers

    def test_draining_is_503_even_with_pools_up(self):
        status, body, _h = _get_healthz(_StubHealthFleet(
            {"prefill": ["healthy"], "decode": ["healthy"]},
            draining=True))
        assert status == 503 and body["status"] == "unavailable"

    def test_colocated_single_pool_keeps_binary_mapping(self):
        status, body, _h = _get_healthz(_StubHealthFleet(
            {"any": ["healthy", "dead"]}))
        assert status == 200 and body["status"] == "ok"
        status, body, _h = _get_healthz(_StubHealthFleet(
            {"any": ["dead", "dead"]}))
        assert status == 503 and body["status"] == "unavailable"


# ---------------------------------------------------------------------
# the real two-pool process fleet
# ---------------------------------------------------------------------


def test_disagg_process_fleet_token_identical_smoke(params, rng):
    """FAST-tier end-to-end: 1 prefill + 1 decode replica processes,
    int8 KV, sampled traffic — every output BIT-identical to a
    colocated engine of the same spec, every request handed off with
    its chain transferred, the decode replica serving warm hits, and
    /healthz reporting both pools up."""
    prompts = [np.asarray(rng.integers(0, CFG.vocab_size, (n,)),
                          np.int32) for n in (5, 7, 6)]
    keys = [jax.random.key(200 + i) for i in range(3)]
    want = _colocated_outputs(params, prompts, keys, kv_dtype="int8")

    fleet = ProcessFleet(_spec(kv_dtype="int8"),
                         pools={"prefill": 1, "decode": 1},
                         platform="cpu", heartbeat_s=0.05)
    try:
        outs = fleet.generate(prompts, max_new_tokens=8, keys=keys,
                              timeout=300)
        for o, w in zip(outs, want):
            np.testing.assert_array_equal(o, w)
        s = fleet.summary()
        assert s["handoffs"] == 3
        assert s["handoff_transfers"] == 3
        assert s["handoff_fallbacks"] == 0
        assert s["finished"] == s["accepted"] == 3
        # the decode replica really served from the transferred chains
        assert s["engines"]["decode0"]["prefill_tokens_saved"] > 0
        assert s["replicas"]["prefill0"]["pool"] == "prefill"
        h = fleet.health()
        assert h["disaggregated"] is True
        assert h["pools"]["prefill"]["state"] == "up"
        assert h["pools"]["decode"]["state"] == "up"
        fleet.assert_compile_count()
        with FrontDoor(fleet) as fd:
            conn = http.client.HTTPConnection(fd.host, fd.port,
                                              timeout=10)
            conn.request("GET", "/healthz")
            resp = conn.getresponse()
            body = json.loads(resp.read().decode())
            conn.close()
        assert resp.status == 200 and body["status"] == "ok"
    finally:
        fleet.close()


# ---------------------------------------------------------------------
# chaos + degradation ladder (slow tier: multi-process, multi-fleet)
# ---------------------------------------------------------------------


@pytest.mark.slow
@pytest.mark.parametrize("fault,target", [
    ("kill", "prefill0"),      # exporter SIGKILL'd mid-transfer
    ("corrupt", "prefill0"),   # frame damaged after its checksum
    ("stall", "decode0"),      # receiver sits on the frame
])
def test_handoff_chaos_token_identical(params, rng, fault, target):
    """Chaos goldens: whatever the handoff fault, EVERY request
    finishes token-identical to an undisturbed colocated run — via
    retry or the local re-prefill fallback — and the failure is
    visible in the typed event log (and, for the kill, in the crash
    machinery: replica death + restart + pool events)."""
    prompts = [np.asarray(rng.integers(0, CFG.vocab_size, (n,)),
                          np.int32) for n in (5, 7)]
    keys = [jax.random.key(300 + i) for i in range(2)]
    want = _colocated_outputs(params, prompts, keys)

    chaos = {"target": target, "handoff": fault, "rearm": True,
             "handoff_stall_s": 3.0}
    fleet = ProcessFleet(
        _spec(), pools={"prefill": 1, "decode": 2}, platform="cpu",
        heartbeat_s=0.05, chaos=[chaos], obs=True,
        handoff_retry=RetryPolicy(base_s=0.02, cap_s=0.1,
                                  max_attempts=2),
        handoff_timeout_s=1.0)
    try:
        outs = fleet.generate(prompts, max_new_tokens=8, keys=keys,
                              timeout=300)
        for o, w in zip(outs, want):
            np.testing.assert_array_equal(o, w)
        s = fleet.summary()
        assert s["finished"] == s["accepted"] == 2   # nothing lost
        assert s["handoffs"] == 2
        assert s["handoff_fallbacks"] >= 1           # fault engaged
        kinds = {e["kind"] for e in fleet.events.snapshot()}
        assert "handoff_fallback" in kinds
        if fault == "kill":
            assert s["replica_deaths"] >= 1
            assert {"replica_death", "pool_degraded"} <= kinds
    finally:
        fleet.close()


@pytest.mark.slow
def test_prefill_pool_down_decode_absorbs(params, rng):
    """Degradation ladder, first rung: the prefill pool dies
    repeatedly (rearmed kill, breaker tripped) — the decode pool
    absorbs prefill work colocated-style, every request still
    finishes token-identical, /healthz reports 200 degraded, and the
    event log shows the pool transition."""
    prompts = [np.asarray(rng.integers(0, CFG.vocab_size, (n,)),
                          np.int32) for n in (5, 6)]
    keys = [jax.random.key(400 + i) for i in range(2)]
    want = _colocated_outputs(params, prompts, keys)

    fleet = ProcessFleet(
        _spec(), pools={"prefill": 1, "decode": 1}, platform="cpu",
        heartbeat_s=0.05, trip_after=1, breaker_reset_s=300.0,
        obs=True,
        chaos=[{"target": "prefill0", "kill_at_step": 1,
                "mode": "hard", "rearm": True}])
    try:
        outs = fleet.generate(prompts, max_new_tokens=8, keys=keys,
                              timeout=300)
        for o, w in zip(outs, want):
            np.testing.assert_array_equal(o, w)
        s = fleet.summary()
        assert s["finished"] == s["accepted"] == 2
        assert s["replica_deaths"] >= 1
        _wait_until(lambda: fleet.health()["pools"]["prefill"]["state"]
                    == "down", timeout=30,
                    msg="prefill pool marked down")
        kinds = {e["kind"] for e in fleet.events.snapshot()}
        assert "pool_degraded" in kinds
        with FrontDoor(fleet) as fd:
            conn = http.client.HTTPConnection(fd.host, fd.port,
                                              timeout=10)
            conn.request("GET", "/healthz")
            resp = conn.getresponse()
            body = json.loads(resp.read().decode())
            conn.close()
        assert resp.status == 200
        assert body["status"] == "degraded"
    finally:
        fleet.close()


@pytest.mark.slow
def test_cache_off_engines_rejected_at_fleet_startup():
    """A disaggregated fleet built from prefix_cache=False engines
    would fall back on EVERY handoff (nothing is ever published to
    export) — fail fast at construction instead of degrading to
    worse-than-colocated with only per-request events as a clue."""
    with pytest.raises(ValueError, match="prefix_cache=True"):
        ProcessFleet(_spec(prefix_cache=False),
                     pools={"prefill": 1, "decode": 1},
                     platform="cpu", heartbeat_s=0.05)


@pytest.mark.slow
def test_decode_pool_hard_down_sheds_typed(params, rng):
    """Degradation ladder, last rung: the decode pool dies repeatedly
    until its breaker is OPEN — admitted work requeues behind the
    breaker (it is NOT errored), and NEW submits shed with typed
    ``Overloaded('pool_down')``."""
    prompt = np.asarray(rng.integers(0, CFG.vocab_size, (5,)),
                        np.int32)
    fleet = ProcessFleet(
        _spec(), pools={"prefill": 1, "decode": 1}, platform="cpu",
        heartbeat_s=0.05, trip_after=1, breaker_reset_s=300.0,
        handoff_retry=RetryPolicy(base_s=0.02, cap_s=0.1,
                                  max_attempts=2),
        handoff_timeout_s=1.0,
        chaos=[{"target": "decode0", "kill_at_step": 1,
                "mode": "hard", "rearm": True}])
    try:
        fid = fleet.submit(prompt, 8, key=jax.random.key(9))
        _wait_until(lambda: fleet.metrics.replica_deaths >= 1
                    and fleet.breaker("decode0").state == "open",
                    timeout=120, msg="decode breaker tripped")
        # the admitted request is requeued, not failed
        freq = fleet.request(fid)
        assert not freq.event.is_set() or freq.error is None
        with pytest.raises(Overloaded) as ei:
            fleet.submit(prompt, 8)
        assert ei.value.reason == "pool_down"
        assert fleet.metrics.shed_pool_down == 1
    finally:
        fleet.close()

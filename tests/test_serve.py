"""Continuous-batching serving goldens (quintnet_tpu/serve/).

THE contract: the engine's output for every request is token-for-token
identical to an independent ``gpt2_generate``/``llama_generate`` call —
no matter how requests are staggered, packed into slots, grown across
KV blocks, preempted and resumed, or sharded over a tp mesh. Plus the
operational invariants: one compiled decode step per engine (no
recompiles as requests come and go), free-list/pool accounting, FCFS
vs priority admission, EOS retirement, streaming callbacks.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from quintnet_tpu.models.gpt2 import GPT2Config, gpt2_init
from quintnet_tpu.models.gpt2_generate import gpt2_generate
from quintnet_tpu.serve import (KVPool, Request, Scheduler, ServeEngine,
                                generate, generate_stream, gpt2_family)

CFG = GPT2Config.tiny(n_layer=2)


@pytest.fixture(scope="module")
def params():
    return gpt2_init(jax.random.key(0), CFG)


def _prompts(rng, lengths):
    return [np.asarray(rng.integers(0, CFG.vocab_size, (t,)), np.int32)
            for t in lengths]


def _engine(params, **kw):
    kw.setdefault("max_slots", 4)
    kw.setdefault("block_size", 4)
    kw.setdefault("num_blocks", 48)
    kw.setdefault("max_seq_len", 40)
    return ServeEngine(gpt2_family(CFG), params, **kw)


def _run_staggered(eng, prompts, max_new, keys, arrivals):
    """Submit request i when the engine has taken ``arrivals[i]`` steps;
    run to completion; return outputs in submission order."""
    order = np.argsort(np.asarray(arrivals), kind="stable")
    rids = {}
    submitted, step = 0, 0
    while submitted < len(prompts) or eng.has_work:
        while (submitted < len(prompts)
               and arrivals[order[submitted]] <= step):
            i = order[submitted]
            rids[i] = eng.submit(prompts[i], max_new[i], key=keys[i])
            submitted += 1
        eng.step()
        step += 1
        assert step < 2000, "engine failed to drain"
    return [eng.result(rids[i]) for i in range(len(prompts))]


# ---------------------------------------------------------------------
# pool + scheduler units
# ---------------------------------------------------------------------

class TestKVPool:
    def _pool(self, num_blocks=8):
        return KVPool(n_layers=2, n_kv_heads=2, head_dim=4, block_size=4,
                      num_blocks=num_blocks)

    def test_null_block_reserved(self):
        p = self._pool()
        got = p.alloc(p.usable_blocks)
        assert got is not None and 0 not in got
        assert p.alloc(1) is None  # exhausted, never hands out block 0

    def test_alloc_free_roundtrip(self):
        p = self._pool()
        a = p.alloc(3)
        assert p.num_used == 3
        p.free(a)
        assert p.num_used == 0 and p.num_free == p.usable_blocks

    def test_alloc_never_partial(self):
        p = self._pool(num_blocks=4)  # 3 usable
        assert p.alloc(5) is None
        assert p.num_free == 3  # nothing leaked

    def test_double_free_raises(self):
        p = self._pool()
        a = p.alloc(1)
        p.free(a)
        with pytest.raises(ValueError, match="double free"):
            p.free(a)

    def test_blocks_for_and_utilization(self):
        p = self._pool()
        assert p.blocks_for(1) == 1
        assert p.blocks_for(4) == 1
        assert p.blocks_for(5) == 2
        p.alloc(7)
        assert p.utilization == 1.0

    def test_paged_write_gather_roundtrip(self):
        """paged_cache_update + paged_gather give back a position-
        ordered dense view through an arbitrary block table."""
        from quintnet_tpu.nn.attention import (paged_cache_update,
                                               paged_gather)

        bs, nb, H, Dh = 4, 6, 2, 3
        k = jnp.zeros((nb * bs, H, Dh))
        v = jnp.zeros_like(k)
        tables = jnp.asarray([[3, 1, 0], [5, 2, 4]], jnp.int32)
        # write token at position 5 of row 0 (block 1, offset 1) and
        # position 2 of row 1 (block 5, offset 2)
        pos = jnp.asarray([5, 2], jnp.int32)
        kin = jnp.arange(2 * H * Dh, dtype=jnp.float32).reshape(2, H, Dh)
        k, v = paged_cache_update(k, v, kin, kin, pos,
                                  block_tables=tables, block_size=bs)
        view = paged_gather(k, tables, block_size=bs)  # [2, H, 12, Dh]
        np.testing.assert_array_equal(np.asarray(view[0, :, 5]),
                                      np.asarray(kin[0]))
        np.testing.assert_array_equal(np.asarray(view[1, :, 2]),
                                      np.asarray(kin[1]))
        assert float(jnp.abs(view[0, :, :5]).sum()) == 0.0


class TestScheduler:
    def _mk(self, policy="fcfs", num_blocks=16):
        pool = KVPool(n_layers=1, n_kv_heads=1, head_dim=2, block_size=4,
                      num_blocks=num_blocks)
        return Scheduler(pool, policy=policy), pool

    def _req(self, rid, t0=4, arrival=None, priority=0):
        return Request(rid=rid, prompt=np.zeros((t0,), np.int32),
                       max_new_tokens=4, priority=priority,
                       arrival=arrival if arrival is not None else rid)

    def test_fcfs_order(self):
        s, _ = self._mk()
        for i in (0, 1, 2):
            s.submit(self._req(i))
        assert [s.next_admission(1).rid for _ in range(3)] == [0, 1, 2]

    def test_priority_order_with_arrival_tiebreak(self):
        s, _ = self._mk(policy="priority")
        s.submit(self._req(0, priority=5))
        s.submit(self._req(1, priority=0))
        s.submit(self._req(2, priority=0))
        assert [s.next_admission(1).rid for _ in range(3)] == [1, 2, 0]

    def test_admission_budget_head_of_line(self):
        """If the FRONT request does not fit, nothing jumps the queue."""
        s, pool = self._mk(num_blocks=4)  # 3 usable
        pool.alloc(2)                     # only 1 block left
        s.submit(self._req(0, t0=8))      # needs 3 blocks
        s.submit(self._req(1, t0=2))      # would fit, but is behind
        assert s.next_admission(4) is None
        assert len(s.waiting) == 2

    def test_no_free_slots_blocks_admission(self):
        s, _ = self._mk()
        s.submit(self._req(0))
        assert s.next_admission(0) is None

    def test_preempt_victim_is_youngest_admission(self):
        s, _ = self._mk()
        rs = [self._req(i) for i in range(3)]
        for r in rs:
            s.submit(r)
        for _ in range(3):
            s.next_admission(1)
        assert Scheduler.preempt_victim(rs).rid == 2
        # preempted request resumes ahead of younger arrivals
        s.submit(self._req(9, arrival=99))
        s.push_front(rs[2])
        assert s.waiting[0].rid == 2


# ---------------------------------------------------------------------
# golden parity (the acceptance contract)
# ---------------------------------------------------------------------

LENGTHS = (5, 11, 3, 8, 6, 14, 4, 9)
MAX_NEW = (10, 6, 12, 8, 5, 7, 11, 9)
ARRIVALS = (0, 0, 1, 2, 4, 5, 7, 9)


def _oracle(params, prompt, max_new, key, temperature=0.0, top_k=0,
            eos=None):
    return gpt2_generate(params, prompt[None], CFG, max_new_tokens=max_new,
                         temperature=temperature, top_k=top_k,
                         eos_token_id=eos, key=key)[0]


def test_golden_parity_staggered_greedy(params, rng):
    """8 staggered mixed-length requests, greedy: engine output ==
    independent gpt2_generate per request, token for token."""
    prompts = _prompts(rng, LENGTHS)
    keys = [jax.random.key(40 + i) for i in range(len(prompts))]
    eng = _engine(params)
    outs = _run_staggered(eng, prompts, list(MAX_NEW), keys,
                          list(ARRIVALS))
    for p, m, k, o in zip(prompts, MAX_NEW, keys, outs):
        np.testing.assert_array_equal(o, _oracle(params, p, m, k))
    assert eng.metrics.finished == len(prompts)
    assert eng.metrics.peak_running >= 2  # batching actually happened


def test_golden_parity_staggered_sampling(params, rng):
    """Same trace, fixed-seed temperature/top-k sampling."""
    prompts = _prompts(rng, LENGTHS)
    keys = [jax.random.key(70 + i) for i in range(len(prompts))]
    eng = _engine(params, temperature=0.9, top_k=7)
    outs = _run_staggered(eng, prompts, list(MAX_NEW), keys,
                          list(ARRIVALS))
    for p, m, k, o in zip(prompts, MAX_NEW, keys, outs):
        np.testing.assert_array_equal(
            o, _oracle(params, p, m, k, temperature=0.9, top_k=7))


def test_golden_parity_llama(rng):
    """Llama family (GQA cache, rope-at-position decode) through the
    same engine: greedy parity vs llama_generate."""
    from quintnet_tpu.models.llama import LlamaConfig, llama_init
    from quintnet_tpu.models.llama_generate import llama_generate
    from quintnet_tpu.serve import llama_family

    cfg = LlamaConfig.tiny(n_layers=2)
    lparams = llama_init(jax.random.key(1), cfg)
    prompts = [np.asarray(rng.integers(0, cfg.vocab_size, (t,)), np.int32)
               for t in (5, 9, 3, 12)]
    eng = ServeEngine(llama_family(cfg), lparams, max_slots=3,
                      block_size=4, num_blocks=32, max_seq_len=32)
    keys = [jax.random.key(7)] * 4
    outs = _run_staggered(eng, prompts, [8, 6, 10, 5], keys, [0, 1, 1, 3])
    for p, m, o in zip(prompts, [8, 6, 10, 5], outs):
        ref = llama_generate(lparams, p[None], cfg, max_new_tokens=m)[0]
        np.testing.assert_array_equal(o, ref)


# ---------------------------------------------------------------------
# scheduling behaviors
# ---------------------------------------------------------------------

def test_staggered_admission_waits_for_slots(params, rng):
    """More requests than slots: the overflow sits in the waiting
    queue and is admitted FCFS as rows retire."""
    prompts = _prompts(rng, (4, 4, 4, 4, 4, 4))
    eng = _engine(params, max_slots=2)
    rids = [eng.submit(p, 5) for p in prompts]
    eng.step()
    assert eng.metrics.running == 2 and eng.metrics.waiting == 4
    eng.run()
    assert eng.metrics.finished == 6
    # FCFS: admission order must follow submission order
    seqs = [eng.request(r).admit_seq for r in rids]
    assert seqs == sorted(seqs)


def test_pool_exhaustion_preemption_and_resume(params, rng):
    """A pool too small for the working set forces eviction of the
    youngest request; the evicted request resumes and still produces
    golden output (recompute + checkpointed key state)."""
    prompts = _prompts(rng, (6, 6, 6))
    keys = [jax.random.key(90 + i) for i in range(3)]
    # 8 usable blocks of 2 tokens = 16 token slots; three requests
    # need up to 3 * (6 + 8) = 42 slots -> guaranteed pressure
    eng = _engine(params, max_slots=3, block_size=2, num_blocks=9,
                  max_seq_len=16, temperature=0.8, top_k=5)
    outs = generate(eng, prompts, max_new_tokens=8, keys=keys)
    assert eng.metrics.preempted >= 1
    for p, k, o in zip(prompts, keys, outs):
        np.testing.assert_array_equal(
            o, _oracle(params, p, 8, k, temperature=0.8, top_k=5))
    # all blocks returned to the pool at the end
    assert eng.pool.num_used == 0


def test_pool_too_small_for_one_request_rejected_at_submit(params, rng):
    """A request the pool can never hold is rejected up front — were it
    queued, admission would return None forever and run() would spin."""
    eng = _engine(params, max_slots=1, block_size=2, num_blocks=3,
                  max_seq_len=16)  # 2 usable blocks = 4 slots
    with pytest.raises(ValueError, match="KV pool too small"):
        eng.submit(_prompts(rng, (3,))[0], 8)
    assert not eng.has_work  # nothing was queued


def test_resume_overflow_of_prefill_len_rejected_at_submit(params, rng):
    """With prefill_len < max_seq_len, a request whose preemption-resume
    prefill (prompt + generated) could exceed prefill_len is rejected —
    mid-run it would be a shape error inside the engine."""
    eng = _engine(params, max_seq_len=40, prefill_len=16)
    with pytest.raises(ValueError, match="exceeds prefill_len"):
        eng.submit(_prompts(rng, (10,))[0], 8)  # 10 + 8 - 1 > 16
    # the same prompt with a budget that fits runs fine
    out = generate(eng, _prompts(rng, (10,))[0:1], max_new_tokens=7)[0]
    assert len(out) == 17


def test_eos_retirement(params, rng):
    """Rows retire at their first EOS: output is the oracle's row
    truncated at EOS (the oracle pads with EOS to max_new), and the
    engine frees the row's blocks early."""
    prompt = _prompts(rng, (6,))[0]
    key = jax.random.key(5)
    plain = _oracle(params, prompt, 12, key)
    eos = int(plain[len(prompt) + 4])  # forces a mid-stream EOS hit
    ref = _oracle(params, prompt, 12, key, eos=eos)

    eng = _engine(params, eos_token_id=eos)
    out = generate(eng, [prompt], max_new_tokens=12, keys=[key])[0]
    assert len(out) < len(prompt) + 12  # actually retired early
    np.testing.assert_array_equal(out, ref[:len(out)])
    assert (np.asarray(ref[len(out):]) == eos).all()
    assert eng.pool.num_used == 0


def test_priority_policy_jumps_queue(params, rng):
    prompts = _prompts(rng, (4, 4, 4))
    eng = _engine(params, max_slots=1, policy="priority")
    r0 = eng.submit(prompts[0], 3)            # admitted first
    r1 = eng.submit(prompts[1], 3, priority=5)
    r2 = eng.submit(prompts[2], 3, priority=0)
    eng.run()
    assert (eng.request(r2).admit_seq < eng.request(r1).admit_seq)
    assert eng.request(r0).admit_seq == 0


def test_streaming_callback(params, rng):
    prompt = _prompts(rng, (5,))[0]
    got = []
    eng = _engine(params)
    out = generate_stream(eng, prompt, max_new_tokens=6,
                          on_token=lambda rid, tok, last:
                          got.append((tok, last)))
    toks = [t for t, _ in got]
    np.testing.assert_array_equal(out[len(prompt):], toks)
    assert [last for _, last in got] == [False] * 5 + [True]


def test_generate_max_steps_error_names_unfinished(params, rng):
    """Exhausting max_steps raises an ACTIONABLE error naming every
    unfinished request id and its progress, instead of whatever
    engine.result does on an unfinished row."""
    eng = _engine(params)
    prompts = _prompts(rng, (4, 4))
    with pytest.raises(RuntimeError) as ei:
        generate(eng, prompts, max_new_tokens=8, max_steps=2)
    msg = str(ei.value)
    assert "unfinished" in msg and "max_steps=2" in msg
    assert "rid 0" in msg and "rid 1" in msg
    assert "/8 tokens" in msg


def test_stream_preempted_mid_stream_orders_tokens(params, rng):
    """generate_stream under policy='priority' with queued background
    work: the low-urgency streaming request is admitted youngest, gets
    preempted when the pool dries, resumes — and still delivers its
    tokens in order with is_last firing exactly once, nothing
    re-delivered across the preemption."""
    eng = _engine(params, max_slots=2, block_size=2, num_blocks=12,
                  max_seq_len=20, policy="priority")
    # bg0 is LONG: it keeps growing blocks while the stream runs, so
    # the pool dries with the stream as the youngest admission (the
    # eviction victim); bg1 is the queued background work
    bg_prompts = _prompts(rng, (6, 4))
    bg_new = (12, 4)
    bg_keys = [jax.random.key(200 + i) for i in range(2)]
    bg = [eng.submit(p, m, key=k, priority=0)
          for p, m, k in zip(bg_prompts, bg_new, bg_keys)]

    sp = _prompts(rng, (4,))[0]
    skey = jax.random.key(300)
    got = []
    out = generate_stream(
        eng, sp, max_new_tokens=8, key=skey, priority=5,
        on_token=lambda rid, tok, last: got.append((rid, tok, last)))
    srid = got[0][0]
    assert eng.request(srid).preemptions >= 1  # actually preempted
    toks = [t for _, t, _ in got]
    np.testing.assert_array_equal(out[len(sp):], toks)  # in order, once
    lasts = [last for *_, last in got]
    assert lasts.count(True) == 1 and lasts[-1] is True
    np.testing.assert_array_equal(out, _oracle(params, sp, 8, skey))
    # the queued background work is untouched by the streaming detour
    eng.run()
    for p, m, k, r in zip(bg_prompts, bg_new, bg_keys, bg):
        np.testing.assert_array_equal(eng.result(r),
                                      _oracle(params, p, m, k))


# ---------------------------------------------------------------------
# pause / drain / progress export+restore (the migration surface)
# ---------------------------------------------------------------------

def test_export_restore_progress_cross_engine_exact(params, rng):
    """The fleet migration contract at engine level: progress exported
    mid-flight from engine A (running slot: evolved key; waiting row:
    submit-time key) restored on a fresh engine B continues
    token-identically — sampling on."""
    prompts = _prompts(rng, (5, 6))
    keys = [jax.random.key(40 + i) for i in range(2)]
    a = _engine(params, max_slots=1, temperature=0.9, top_k=7)
    rids = [a.submit(p, 8, key=k) for p, k in zip(prompts, keys)]
    for _ in range(3):
        a.step()
    progs = a.export_progress()
    assert [p.rid for p in progs] == rids
    assert len(progs[0].generated) >= 1        # running, mid-flight
    assert progs[1].generated == []            # still waiting

    b = _engine(params, max_slots=2, temperature=0.9, top_k=7)
    new_rids = [b.restore_progress(p) for p in progs]
    b.run()
    for p, k, nr in zip(prompts, keys, new_rids):
        np.testing.assert_array_equal(
            b.result(nr),
            _oracle(params, p, 8, k, temperature=0.9, top_k=7))


def test_restore_progress_validation(params, rng):
    from quintnet_tpu.serve import RequestProgress

    eng = _engine(params)
    prompt = _prompts(rng, (4,))[0]
    key_data = np.asarray(jax.random.key_data(jax.random.key(0)))
    with pytest.raises(ValueError, match="key_data"):
        eng.restore_progress(RequestProgress(
            rid=0, prompt=prompt, generated=[1], key_data=None,
            max_new_tokens=4))
    with pytest.raises(ValueError, match="nothing left"):
        eng.restore_progress(RequestProgress(
            rid=0, prompt=prompt, generated=[1, 2], key_data=key_data,
            max_new_tokens=2))
    with pytest.raises(ValueError, match="exceeds max_seq_len"):
        eng.restore_progress(RequestProgress(
            rid=0, prompt=np.zeros(39, np.int32), generated=[],
            key_data=key_data, max_new_tokens=4))


def test_pause_admissions_and_drain(params, rng):
    """drain() finishes the active slots and leaves the waiting queue
    intact with admissions paused; resume_admissions picks the queue
    back up."""
    eng = _engine(params, max_slots=1)
    p1, p2 = _prompts(rng, (4, 4))
    r1 = eng.submit(p1, 4, key=jax.random.key(1))
    eng.step()                                  # r1 active
    r2 = eng.submit(p2, 4, key=jax.random.key(2))
    finished = eng.drain()
    assert r1 in finished
    assert eng.admissions_paused
    assert eng.request(r2).state == "waiting"   # queued, not dropped
    assert eng.pool.num_used == 0
    eng.resume_admissions()
    eng.run()
    np.testing.assert_array_equal(eng.result(r2),
                                  _oracle(params, p2, 4,
                                          jax.random.key(2)))


def test_submit_validation(params):
    eng = _engine(params)
    with pytest.raises(ValueError, match="exceeds max_seq_len"):
        eng.submit(np.zeros(39, np.int32), 2)
    with pytest.raises(ValueError, match="max_new_tokens"):
        eng.submit(np.zeros(4, np.int32), 0)
    with pytest.raises(ValueError, match="empty"):
        eng.submit(np.zeros(0, np.int32), 2)
    with pytest.raises(ValueError, match="deadline_s"):
        eng.submit(np.zeros(4, np.int32), 2, deadline_s=0)


class _FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def test_deadline_mid_decode_retires_typed_and_publishes(params, rng):
    """A request whose deadline passes MID-GENERATION is retired with a
    typed DeadlineExceeded — not finished late, not silently dropped —
    and its blocks are PUBLISHED: the pool holds no live references
    afterwards and a retry of the same prompt re-prefills almost
    nothing. An unconstrained request in the same batch is untouched."""
    from quintnet_tpu.serve import DeadlineExceeded

    clk = _FakeClock()
    eng = _engine(params, clock=clk)
    p1, p2 = _prompts(rng, (6, 5))
    k2 = jax.random.key(21)
    r1 = eng.submit(p1, 16, key=jax.random.key(20), deadline_s=5.0)
    r2 = eng.submit(p2, 8, key=k2)
    for _ in range(3):
        eng.step()
    got_before = len(eng.request(r1).generated)
    assert 0 < got_before < 16          # genuinely mid-generation
    clk.t = 10.0                        # r1's deadline lapses
    finished = eng.step()
    assert r1 in finished
    with pytest.raises(DeadlineExceeded) as ei:
        eng.result(r1)
    assert ei.value.generated == got_before
    assert eng.metrics.deadline_exceeded == 1
    # the survivor finishes golden
    eng.run()
    np.testing.assert_array_equal(eng.result(r2),
                                  _oracle(params, p2, 8, k2))
    assert eng.pool.num_used == 0       # nothing leaked: published,
    #                                     released, only cached remains
    # the published prefix is live: resubmitting the same prompt hits
    # the cache instead of re-prefilling
    hits0 = eng.metrics.prefix_hit_tokens
    eng.submit(p1, 4, key=jax.random.key(22))
    eng.run()
    assert eng.metrics.prefix_hit_tokens > hits0


def test_deadline_expired_while_waiting_is_typed_too(params, rng):
    """A queued (never admitted) request whose deadline passes is
    failed with DeadlineExceeded(generated=0) at the next step — the
    scheduler does not leak it, and admissions behind it proceed."""
    from quintnet_tpu.serve import DeadlineExceeded

    clk = _FakeClock()
    eng = _engine(params, max_slots=1, clock=clk)
    p1, p2, p3 = _prompts(rng, (4, 4, 5))
    k3 = jax.random.key(32)
    r1 = eng.submit(p1, 8, key=jax.random.key(30))
    r2 = eng.submit(p2, 8, key=jax.random.key(31), deadline_s=5.0)
    r3 = eng.submit(p3, 6, key=k3)
    eng.step()                          # r1 occupies the single slot
    assert eng.request(r2).state == "waiting"
    clk.t = 6.0
    eng.step()
    with pytest.raises(DeadlineExceeded) as ei:
        eng.result(r2)
    assert ei.value.generated == 0
    eng.run()
    np.testing.assert_array_equal(eng.result(r3),
                                  _oracle(params, p3, 6, k3))
    # exported progress carries REMAINING deadline budget for the
    # migration contract (none of the survivors had one here)
    assert eng.result(r1) is not None


# ---------------------------------------------------------------------
# the one-compiled-program invariant
# ---------------------------------------------------------------------

def test_no_recompilation_over_20_step_trace(params, rng):
    """Admitting/retiring/preempting across a 20-step trace must hit
    the SAME two compiled programs: zero backend compiles observed via
    jax.monitoring after warmup, jit cache size stays 1 per program."""
    import jax.monitoring as monitoring

    eng = _engine(params, max_slots=3, block_size=2, num_blocks=12,
                  max_seq_len=16)
    # warmup: one full lifecycle (admission/prefill, decode, retire)
    eng.submit(_prompts(rng, (4,))[0], 3)
    eng.run()
    assert eng.compile_stats() == {"prefill": 1, "decode": 1}

    compiles = []

    def listener(name, **kw):
        if "backend_compile" in name:
            compiles.append(name)

    monitoring.register_event_duration_secs_listener(
        lambda name, dur, **kw: listener(name))
    try:
        prompts = _prompts(rng, (3, 5, 4, 6, 3, 5))
        arrivals = [0, 1, 3, 6, 10, 14]
        submitted, step = 0, 0
        rids = []
        for step in range(20):
            while (submitted < len(prompts)
                   and arrivals[submitted] <= step):
                rids.append(eng.submit(prompts[submitted], 4))
                submitted += 1
            eng.step()
        assert submitted == len(prompts)
        assert eng.metrics.finished >= 4  # retirements happened mid-trace
    finally:
        monitoring.clear_event_listeners()
    assert compiles == []
    assert eng.compile_stats() == {"prefill": 1, "decode": 1}


# ---------------------------------------------------------------------
# TP-sharded engine
# ---------------------------------------------------------------------

@pytest.mark.slow
def test_tp2_engine_matches_single_device(params, rng):
    """The whole engine step under a tp=2 shard_map (head-sharded pool,
    RowParallel psum per cached layer): outputs identical to the
    unsharded engine's — which are themselves golden vs gpt2_generate."""
    from quintnet_tpu.core.mesh import mesh_from_sizes
    from quintnet_tpu.models.gpt2 import gpt2_to_tp_layout

    prompts = _prompts(rng, (5, 9, 3))
    keys = [jax.random.key(50 + i) for i in range(3)]
    mesh = mesh_from_sizes(tp=2)
    tp_params = gpt2_to_tp_layout(params, CFG, 2)
    eng = _engine(tp_params, mesh=mesh)
    outs = generate(eng, prompts, max_new_tokens=[8, 6, 10], keys=keys)
    for p, m, k, o in zip(prompts, (8, 6, 10), keys, outs):
        np.testing.assert_array_equal(o, _oracle(params, p, m, k))

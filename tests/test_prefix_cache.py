"""Prefix-cached, bucketed prefill goldens (quintnet_tpu/serve/).

THE contract: with prefix caching enabled, every request's token stream
is BIT-IDENTICAL to cache-off — which is itself golden against
independent ``gpt2_generate`` calls — for greedy and fixed-seed
sampling, across staggered shared-prefix traffic, preemption-resume,
and cross-engine migration. Plus the sharing-core invariants: refcount
acquire/release, copy-on-write on partial-block reuse, LRU eviction
ordering vs the LIFO free list, double-release rejection, and the
adversarial guarantee that an evicted cached block is never reachable
from any live block table.
"""

import jax
import numpy as np
import pytest

from quintnet_tpu.analysis.recompile import RecompileError
from quintnet_tpu.analysis.specs import prefill_buckets
from quintnet_tpu.models.gpt2 import GPT2Config, gpt2_init
from quintnet_tpu.models.gpt2_generate import gpt2_generate
from quintnet_tpu.serve import KVPool, ServeEngine, generate, gpt2_family

CFG = GPT2Config.tiny(n_layer=2)


@pytest.fixture(scope="module")
def params():
    return gpt2_init(jax.random.key(0), CFG)


def _engine(params, **kw):
    kw.setdefault("max_slots", 4)
    kw.setdefault("block_size", 4)
    kw.setdefault("num_blocks", 48)
    kw.setdefault("max_seq_len", 40)
    return ServeEngine(gpt2_family(CFG), params, **kw)


def _oracle(params, prompt, max_new, key, temperature=0.0, top_k=0):
    return gpt2_generate(params, prompt[None], CFG, max_new_tokens=max_new,
                         temperature=temperature, top_k=top_k, key=key)[0]


# ---------------------------------------------------------------------
# pool sharing core
# ---------------------------------------------------------------------

class TestSharingCore:
    def _pool(self, num_blocks=8, block_size=4):
        return KVPool(n_layers=1, n_kv_heads=1, head_dim=2,
                      block_size=block_size, num_blocks=num_blocks)

    def _toks(self, n, seed=0):
        rng = np.random.default_rng(seed)
        return rng.integers(0, 100, (n,)).astype(np.int32)

    def test_refcount_acquire_release_invariants(self):
        p = self._pool()
        toks = self._toks(8)
        a = p.acquire(2)
        assert [p.refcount(b) for b in a] == [1, 1]
        p.publish(toks, a, 8)
        # a second holder pins the published chain
        p.acquire_cached(a)
        assert [p.refcount(b) for b in a] == [2, 2]
        p.release(a)
        # still referenced: neither free nor cached-retained
        assert p.num_used == 2 and p.num_cached == 0
        p.release(a)
        # refcount zero + published -> retained as cache, NOT freed
        assert p.num_used == 0 and p.num_cached == 2
        assert p.num_free == p.usable_blocks - 2

    def test_double_release_rejected_o1(self):
        p = self._pool()
        a = p.acquire(1)
        p.release(a)
        with pytest.raises(ValueError, match="double free"):
            p.release(a)
        # duplicate ids inside ONE call cannot over-decrement either
        b = p.acquire(1)
        with pytest.raises(ValueError, match="double free"):
            p.release(b + b)
        # membership set (not an O(n) list scan) backs the check
        assert p._free_set == set(p._free)

    def test_release_unpublished_goes_to_free_list(self):
        p = self._pool()
        a = p.acquire(3)
        p.release(a)
        assert p.num_cached == 0 and p.num_free == p.usable_blocks

    def test_acquire_cached_requires_known_block(self):
        p = self._pool()
        with pytest.raises(ValueError, match="neither referenced"):
            p.acquire_cached([3])

    def test_lifo_free_list_preferred_over_cached_eviction(self):
        """Allocation drains the LIFO free list before touching the
        cached retention set; cached blocks are evicted only when the
        free list is dry, in LRU order."""
        p = self._pool(num_blocks=8)   # 7 usable
        toks = self._toks(8, seed=1)
        cached = p.acquire(2)
        p.publish(toks, cached, 8)
        p.release(cached)              # 2 cached, 5 free
        assert (p.num_free, p.num_cached) == (5, 2)
        got = p.acquire(5)
        # free list served first: the cached pair untouched
        assert set(got).isdisjoint(cached)
        assert p.num_cached == 2 and p.num_free == 0
        # now eviction must kick in
        assert p.acquire(1) is not None
        assert p.num_cached == 1 and p.cache_evictions == 1

    def test_lru_eviction_order_is_least_recently_touched(self):
        p = self._pool(num_blocks=8)
        t1, t2 = self._toks(4, seed=2), self._toks(4, seed=3)
        c1 = p.acquire(1)
        p.publish(t1, c1, 4)
        p.release(c1)
        c2 = p.acquire(1)
        p.publish(t2, c2, 4)
        p.release(c2)
        # touch the OLDER chain via a lookup hit + pin/unpin
        plan = p.lookup(np.concatenate([t1, t1[:1]]))
        assert plan.shared_blocks == c1
        p.acquire_cached(c1)
        p.release(c1)
        p.acquire(p.num_free)          # dry the free list
        evicted = p.acquire(1)         # forces one eviction
        assert evicted == c2           # c1 was touched later -> survives
        assert p.lookup(np.concatenate([t2, t2[:1]])).shared_blocks == []

    def test_publish_duplicate_key_keeps_incumbent(self):
        p = self._pool()
        toks = self._toks(4, seed=4)
        a = p.acquire(1)
        p.publish(toks, a, 4)
        b = p.acquire(1)
        p.publish(toks, b, 4)          # identical content, later
        p.release(a)
        p.release(b)
        # incumbent cached; duplicate went back to the free list
        assert p.lookup(np.concatenate([toks, toks[:1]])
                        ).shared_blocks == a
        assert p.num_cached == 1

    def test_lookup_caps_at_len_minus_one(self):
        """A fully-cached prompt still prefills >= 1 token (the logits
        source): plan_admission never returns start == len(tokens)."""
        p = self._pool()
        toks = self._toks(8, seed=5)
        a = p.acquire(2)
        p.publish(toks, a, 8)
        p.release(a)
        plan = p.plan_admission(toks, 9)
        assert plan.cached_tokens == 4        # capped to the first block
        assert plan.shared_blocks == a[:1]
        assert plan.n_new_blocks == 3 - 1

    def test_admission_budget_counts_only_uncached_blocks(self):
        p = self._pool(num_blocks=5)   # 4 usable
        toks = self._toks(8, seed=6)
        a = p.acquire(2)
        p.publish(toks, a, 7)          # 1 full block + partial leaf (3)
        p.release(a)                   # 2 cached, 2 free
        # cache-cold: the full 3 blocks count against the budget
        cold = p.plan_admission(self._toks(8, seed=7), 9)
        assert cold.n_new_blocks == 3
        assert p.can_admit(cold)
        # cache hit: 4 full + 3 COW slots resident, only 2 new blocks
        # needed; the pinned chain is excluded from the evictable count
        hot = p.plan_admission(toks, 9)
        assert hot.cached_tokens == 7
        assert hot.shared_blocks == a[:1]
        assert (hot.cow_src, hot.cow_len) == (a[1], 3)
        assert hot.n_new_blocks == 2
        assert p.can_admit(hot)

    def test_plan_degrades_instead_of_wedging_at_capacity_edge(self):
        """A maximal-chain plan can need more simultaneous blocks than
        the pool holds (pinned chain + transient COW pin + new blocks)
        even on an otherwise idle pool — the plan must degrade (drop
        the COW hit, then the chain) rather than report an
        inadmissible plan forever and head-of-line-block the queue."""
        p = self._pool(num_blocks=6)       # 5 usable
        toks = self._toks(19, seed=9)
        a = p.acquire(3)
        p.publish(toks, a, 11)             # 2 full blocks + leaf (3)
        p.release(a)                       # 3 cached, 2 free
        # request sharing the 11-token prefix, table must cover 19
        # slots = 5 blocks: the maximal plan (2 shared + 3 new + COW
        # pin) needs 6 distinct blocks > 5 usable
        plan = p.plan_admission(toks, 19)
        assert p.can_admit(plan)           # degraded, not wedged
        assert plan.cow_src is None        # the COW hit was dropped
        assert plan.cached_tokens == 8     # full-block chain kept
        assert plan.n_new_blocks == 3
        # and an engine at that exact edge still serves the request
        params = gpt2_init(jax.random.key(0), CFG)
        eng = _engine(params, max_slots=1, block_size=4, num_blocks=6,
                      max_seq_len=20)
        prompt = np.asarray(
            np.random.default_rng(9).integers(0, CFG.vocab_size, (11,)),
            np.int32)
        r1 = eng.submit(prompt, 4, key=jax.random.key(1))
        eng.run(max_steps=50)
        r2 = eng.submit(np.concatenate(
            [eng.result(r1)[:11], prompt[:4]]), 4, key=jax.random.key(2))
        eng.run(max_steps=50)
        assert eng.request(r2).state == "finished"
        np.testing.assert_array_equal(
            eng.result(r2),
            _oracle(params, np.asarray(eng.request(r2).prompt), 4,
                    jax.random.key(2)))

    def test_prefix_cache_off_is_inert(self):
        p = KVPool(n_layers=1, n_kv_heads=1, head_dim=2, block_size=4,
                   num_blocks=8, prefix_cache=False)
        toks = self._toks(8, seed=8)
        a = p.acquire(2)
        p.publish(toks, a, 8)          # no-op
        p.release(a)
        assert p.num_cached == 0 and p.num_free == p.usable_blocks
        assert p.lookup(toks).cached_tokens == 0


# ---------------------------------------------------------------------
# copy-on-write
# ---------------------------------------------------------------------

def test_cow_on_partial_block_divergence(params):
    """Request B extends A's published chain INTO a partially-filled
    cached block and then diverges: B must copy the filled slots into
    a private block (counted as hit tokens), write its own
    continuation there, and leave the cached block's content and index
    entry untouched — while B's output stays golden."""
    rng = np.random.default_rng(3)
    eng = _engine(params, block_size=4)
    pa = np.asarray(rng.integers(0, CFG.vocab_size, (10,)), np.int32)
    ra = eng.submit(pa, 4, key=jax.random.key(1))
    eng.run()
    oa = eng.result(ra)                 # published chain covers 13 toks
    pool = eng.pool
    leaf_key = pool._key(np.asarray(oa[:13], np.int32), 13)
    leaf = pool._index[leaf_key]
    assert pool._block_fill[leaf] == 1  # partially filled (13 % 4)
    k_before = np.asarray(pool.k[:, leaf * 4:(leaf + 1) * 4]).copy()

    # B: A's 13 published tokens + a diverging continuation
    pb = np.concatenate(
        [oa[:13], np.asarray(rng.integers(0, CFG.vocab_size, (5,)),
                             np.int32)])
    rb = eng.submit(pb, 4, key=jax.random.key(2))
    eng.run()
    np.testing.assert_array_equal(
        eng.result(rb), _oracle(params, pb, 4, jax.random.key(2)))
    assert eng.metrics.prefix_hit_tokens == 13   # 12 full + 1 COW slot
    # the cached leaf is untouched and still indexed
    k_after = np.asarray(pool.k[:, leaf * 4:(leaf + 1) * 4])
    np.testing.assert_array_equal(k_before[:, :1], k_after[:, :1])
    assert pool._index[leaf_key] == leaf
    # B's table never referenced the cached leaf (it wrote a copy)
    assert pool.refcount(leaf) == 0


# ---------------------------------------------------------------------
# adversarial eviction
# ---------------------------------------------------------------------

def test_evicted_block_never_reachable_from_live_tables(params):
    """Memory pressure evicts cached blocks while other requests run:
    at every step, every evicted block id must be absent from every
    ACTIVE slot's block table (eviction only ever takes refcount-zero
    blocks)."""
    rng = np.random.default_rng(4)
    eng = _engine(params, max_slots=3, block_size=2, num_blocks=12,
                  max_seq_len=16)

    def live_blocks():
        return {b for s in eng._active_slots()
                for b in eng._slot_blocks[s]}

    # instrument the eviction point: AT THE MOMENT a cached block is
    # evicted it must be unreferenced, absent from every live table,
    # and gone from the index (an evicted block may be legally handed
    # out again afterwards — that is the allocator working)
    orig_evict = eng.pool._evict_lru
    evictions = []

    def checked_evict():
        b = orig_evict()
        assert eng.pool.refcount(b) == 0
        assert b not in live_blocks()
        assert b not in eng.pool._block_key
        assert all(v != b for v in eng.pool._index.values())
        evictions.append(b)
        return b

    eng.pool._evict_lru = checked_evict
    rids = []
    for i in range(8):
        p = np.asarray(rng.integers(0, CFG.vocab_size, (5,)), np.int32)
        rids.append(eng.submit(p, 6, key=jax.random.key(600 + i)))
    while eng.has_work:
        eng.step()
        live = live_blocks()
        # step-end consistency: live tables never overlap the free
        # list or the cached retention set, and hold real references
        assert live.isdisjoint(eng.pool._free_set)
        assert live.isdisjoint(eng.pool._cached_free)
        assert all(eng.pool.refcount(b) >= 1 for b in live)
    assert len(evictions) > 0            # pressure actually evicted
    for r in rids:
        assert eng.request(r).state == "finished"


# ---------------------------------------------------------------------
# golden parity: cache-on == cache-off == oracle
# ---------------------------------------------------------------------

def _shared_prefix_prompts(rng, n, prefix_len=18, tails=(3, 4, 5, 6)):
    shared = np.asarray(rng.integers(0, CFG.vocab_size, (prefix_len,)),
                        np.int32)
    out = []
    for i in range(n):
        t = tails[i % len(tails)]
        tail = np.asarray(rng.integers(0, CFG.vocab_size, (t,)), np.int32)
        out.append(np.concatenate([shared, tail]))
    return out


def _staggered(eng, prompts, max_new, keys, arrivals):
    order = np.argsort(np.asarray(arrivals), kind="stable")
    rids = {}
    submitted, step = 0, 0
    while submitted < len(prompts) or eng.has_work:
        while (submitted < len(prompts)
               and arrivals[order[submitted]] <= step):
            i = order[submitted]
            rids[i] = eng.submit(prompts[i], max_new[i], key=keys[i])
            submitted += 1
        eng.step()
        step += 1
        assert step < 2000, "engine failed to drain"
    return [eng.result(rids[i]) for i in range(len(prompts))]


@pytest.mark.parametrize("temperature,top_k", [(0.0, 0), (0.9, 7)])
def test_cache_on_equals_cache_off_and_oracle(params, temperature, top_k):
    """Staggered shared-prefix trace, greedy AND sampled: the cache-on
    engine's streams equal the cache-off engine's AND the independent
    oracle's, token for token — with a nonzero hit rate proving the
    cache actually served tokens."""
    rng = np.random.default_rng(11)
    prompts = _shared_prefix_prompts(rng, 6)
    keys = [jax.random.key(800 + i) for i in range(6)]
    max_new = [8, 6, 9, 5, 7, 8]
    arrivals = [0, 0, 4, 9, 14, 19]   # late arrivals see a warm cache

    on = _engine(params, temperature=temperature, top_k=top_k)
    outs_on = _staggered(on, prompts, max_new, keys, arrivals)
    off = _engine(params, temperature=temperature, top_k=top_k,
                  prefix_cache=False)
    outs_off = _staggered(off, prompts, max_new, keys, arrivals)

    assert on.metrics.prefix_hit_tokens > 0
    assert off.metrics.prefix_hit_tokens == 0
    for p, m, k, o_on, o_off in zip(prompts, max_new, keys, outs_on,
                                    outs_off):
        np.testing.assert_array_equal(o_on, o_off)
        np.testing.assert_array_equal(
            o_on, _oracle(params, p, m, k, temperature=temperature,
                          top_k=top_k))


def test_preempt_resume_parity_and_nearly_free_resume(params):
    """Preemption under pool pressure with caching on: outputs stay
    golden, and when a preempted request resumes while its published
    chain is still resident the re-prefill is a prefix hit."""
    rng = np.random.default_rng(12)
    prompts = [np.asarray(rng.integers(0, CFG.vocab_size, (6,)), np.int32)
               for _ in range(3)]
    keys = [jax.random.key(900 + i) for i in range(3)]
    eng = _engine(params, max_slots=3, block_size=2, num_blocks=16,
                  max_seq_len=16, temperature=0.8, top_k=5)
    outs = generate(eng, prompts, max_new_tokens=8, keys=keys)
    assert eng.metrics.preempted >= 1
    for p, k, o in zip(prompts, keys, outs):
        np.testing.assert_array_equal(
            o, _oracle(params, p, 8, k, temperature=0.8, top_k=5))
    assert eng.pool.num_used == 0


def test_migration_onto_warm_engine_is_a_cache_hit(params):
    """The fleet's kill-migration path with caching: progress exported
    from engine A mid-flight restores on engine B which has ALREADY
    served the same prompt — B's resume prefill hits its prefix cache
    and the continuation stays token-identical (sampling on)."""
    rng = np.random.default_rng(13)
    prompt = np.asarray(rng.integers(0, CFG.vocab_size, (9,)), np.int32)
    key = jax.random.key(77)
    a = _engine(params, temperature=0.9, top_k=7)
    rid = a.submit(prompt, 10, key=key)
    for _ in range(4):
        a.step()
    progs = a.export_progress()
    assert len(progs) == 1 and len(progs[0].generated) >= 1

    b = _engine(params, temperature=0.9, top_k=7)
    # B has served the identical prompt before (a different sampling
    # key, so only the PROMPT prefix is shared)
    b.submit(prompt, 4, key=jax.random.key(78))
    b.run()
    b.metrics = type(b.metrics)(clock=b.clock)
    new_rid = b.restore_progress(progs[0])
    b.run()
    assert b.metrics.prefix_hit_tokens > 0   # resume rode the cache
    np.testing.assert_array_equal(
        b.result(new_rid),
        _oracle(params, prompt, 10, key, temperature=0.9, top_k=7))
    del rid


# ---------------------------------------------------------------------
# bucketed prefill + the bounded-compile invariant
# ---------------------------------------------------------------------

def test_bucket_ladder_pinned_in_specs():
    assert prefill_buckets(40) == (16, 32, 40)
    assert prefill_buckets(16) == (16,)
    assert prefill_buckets(12) == (12,)
    assert prefill_buckets(100) == (16, 32, 64, 100)


def test_bucket_choice_does_not_change_tokens(params):
    """The same request served through different buckets (alone: big
    tail -> big bucket; after a cache warm-up: small tail -> small
    bucket) produces the identical stream — bucket width is pure
    padding."""
    rng = np.random.default_rng(14)
    prompt = np.asarray(rng.integers(0, CFG.vocab_size, (20,)), np.int32)
    key = jax.random.key(500)
    eng = _engine(params, temperature=0.7, top_k=9)
    assert len(eng.prefill_buckets) >= 2
    r1 = eng.submit(prompt, 6, key=key)   # cold: tail 20 -> bucket 32
    eng.run()
    r2 = eng.submit(prompt, 6, key=key)   # warm: tiny tail -> bucket 16
    eng.run()
    np.testing.assert_array_equal(eng.result(r1), eng.result(r2))
    assert eng.metrics.prefix_hit_tokens > 0
    assert eng.compile_stats()["prefill"] == 2  # two buckets exercised


def test_compile_count_bounded_by_buckets_over_mixed_trace(params, rng):
    """A mixed preempting + shared-prefix trace compiles at most
    n_buckets prefill programs and exactly one decode program —
    asserted via assert_compile_count AND a jax.monitoring listener
    observing zero backend compiles after every bucket is warm."""
    import jax.monitoring as monitoring

    eng = _engine(params, max_slots=3, block_size=2, num_blocks=16,
                  max_seq_len=16)
    assert eng.prefill_buckets == (16,)  # short prefill_len: one bucket
    del eng

    eng = _engine(params)                # prefill_len 40 -> 3 buckets
    shared = _shared_prefix_prompts(rng, 4)
    # warm every bucket: prompts sized into each bucket
    for n in (5, 20, 33):
        eng.submit(np.asarray(rng.integers(0, CFG.vocab_size, (n,)),
                              np.int32), 2)
        eng.run()
    n_buckets = len(eng.prefill_buckets)
    assert eng.compile_stats() == {"prefill": n_buckets, "decode": 1}

    compiles = []
    monitoring.register_event_duration_secs_listener(
        lambda name, dur, **kw: compiles.append(name)
        if "backend_compile" in name else None)
    try:
        for i, p in enumerate(shared):
            eng.submit(p, 5, key=jax.random.key(i))
        eng.run()
    finally:
        monitoring.clear_event_listeners()
    assert compiles == []
    eng.assert_compile_count(prefill=n_buckets, decode=1)
    with pytest.raises(RecompileError, match="expected 1 compiled"):
        eng.assert_compile_count(prefill=1, decode=1)


def test_validation_rejects_uncovering_buckets(params):
    with pytest.raises(ValueError, match="does not cover"):
        _engine(params, prefill_bucket_sizes=(8, 16))  # prefill_len 40

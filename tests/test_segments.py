"""Packed-segment attention masking: sdpa, the blockwise jnp path and
the Pallas kernels (interpret mode) must all agree with a brute-force
masked softmax, forward AND backward — positions in different packed
documents never attend to each other (round-4 verdict item 3: the
kernel previously had no segment support at all).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from quintnet_tpu.data.datasets import segments_from_tokens
from quintnet_tpu.nn.attention import mha_apply, mha_init, sdpa
from quintnet_tpu.ops.flash_attention import blockwise_attention
from quintnet_tpu.ops.pallas_attention import pallas_flash_attention


def _qkv(b=2, h=2, s=64, d=32, keyseed=0):
    ks = jax.random.split(jax.random.key(keyseed), 3)
    return tuple(jax.random.normal(k, (b, h, s, d)) for k in ks)


def _segments(b=2, s=64, keyseed=3, n_docs=3):
    """Random monotone segment ids (packed-document layout)."""
    rng = np.random.default_rng(keyseed)
    out = np.zeros((b, s), np.int32)
    for i in range(b):
        cuts = np.sort(rng.choice(np.arange(1, s), n_docs - 1,
                                  replace=False))
        out[i] = np.searchsorted(cuts, np.arange(s), side="right")
    return jnp.asarray(out)


def _brute(q, k, v, seg, causal):
    """Dense masked softmax oracle."""
    scores = jnp.einsum("bhsd,bhtd->bhst", q, k) / np.sqrt(q.shape[-1])
    mask = (seg[:, None, :, None] == seg[:, None, None, :])
    if causal:
        s = q.shape[2]
        mask = mask & jnp.tril(jnp.ones((s, s), bool))[None, None]
    scores = jnp.where(mask, scores, -1e30)
    return jnp.einsum("bhst,bhtd->bhsd",
                      jax.nn.softmax(scores, axis=-1), v)


@pytest.mark.parametrize("causal", [False, True])
def test_sdpa_segments(causal):
    q, k, v = _qkv()
    seg = _segments()
    ref = _brute(q, k, v, seg, causal)
    out = sdpa(q, k, v, causal=causal, segment_ids=seg)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_blockwise_segments(causal):
    """Segment boundaries intentionally misaligned with the 16-wide
    blocks: interior tiles, crossing tiles and fully-masked tiles all
    occur."""
    q, k, v = _qkv()
    seg = _segments()
    ref = _brute(q, k, v, seg, causal)
    out = blockwise_attention(q, k, v, causal=causal, block_q=16,
                              block_k=16, segment_ids=seg)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)


def test_blockwise_segments_ragged():
    q, k, v = _qkv(s=50)
    seg = _segments(s=50)
    ref = _brute(q, k, v, seg, True)
    out = blockwise_attention(q, k, v, causal=True, block_q=16,
                              block_k=16, segment_ids=seg)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_pallas_kernel_segments_fwd(causal):
    """In-kernel segment masking (interpret mode), including tiles that
    are FULLY segment-masked (the exp-guard path)."""
    q, k, v = _qkv(s=128, d=64)
    seg = _segments(s=128)
    ref = _brute(q, k, v, seg, causal)
    out = pallas_flash_attention(q, k, v, causal, 32, 32, True,
                                 segment_ids=seg)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_pallas_kernel_segments_grads(causal):
    q, k, v = _qkv(s=64, d=32)
    seg = _segments()
    w = jax.random.normal(jax.random.key(9), q.shape)

    def ref_loss(q_, k_, v_):
        return jnp.sum(_brute(q_, k_, v_, seg, causal) * w)

    def fa_loss(q_, k_, v_):
        return jnp.sum(pallas_flash_attention(
            q_, k_, v_, causal, 32, 32, True, segment_ids=seg) * w)

    g_ref = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
    g_fa = jax.grad(fa_loss, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_fa, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=5e-5)


def test_segments_from_tokens():
    eos = 9
    rows = np.asarray([[1, 2, eos, 3, 4, 5, eos, 6],
                       [eos, 1, 2, 3, eos, eos, 4, 5]])
    seg = segments_from_tokens(rows, eos)
    np.testing.assert_array_equal(
        seg, [[0, 0, 0, 1, 1, 1, 1, 2],
              [0, 1, 1, 1, 1, 2, 3, 3]])


def test_mha_apply_segments_match_manual():
    """Threading through the attention module: mha_apply(segment_ids=)
    equals running each document separately."""
    d, h, s = 32, 4, 24
    p = mha_init(jax.random.key(0), d)
    x = jax.random.normal(jax.random.key(1), (1, s, d))
    cut = 10
    seg = jnp.asarray([[0] * cut + [1] * (s - cut)])

    out = mha_apply(p, x, num_heads=h, causal=True, segment_ids=seg)
    out_a = mha_apply(p, x[:, :cut], num_heads=h, causal=True)
    out_b = mha_apply(p, x[:, cut:], num_heads=h, causal=True)
    np.testing.assert_allclose(np.asarray(out[:, :cut]),
                               np.asarray(out_a), rtol=2e-4, atol=2e-5)
    np.testing.assert_allclose(np.asarray(out[:, cut:]),
                               np.asarray(out_b), rtol=2e-4, atol=2e-5)


def test_mha_apply_segments_under_sp_raises():
    from jax.sharding import PartitionSpec as P

    from quintnet_tpu.core import collectives as cc
    from quintnet_tpu.core.mesh import mesh_from_sizes

    d, h, s = 16, 2, 16
    p = mha_init(jax.random.key(0), d)
    x = jax.random.normal(jax.random.key(1), (2, s, d))
    seg = jnp.zeros((2, s), jnp.int32)
    mesh = mesh_from_sizes(sp=2)
    f = cc.shard_map_fn(
        lambda p_, x_, s_: mha_apply(p_, x_, num_heads=h, causal=True,
                                     sp_axis="sp", segment_ids=s_),
        mesh, in_specs=(None, P(None, "sp"), P(None, "sp")),
        out_specs=P(None, "sp"))
    with pytest.raises(NotImplementedError, match="segment_ids"):
        f(p, x, seg)

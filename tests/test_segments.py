"""Packed-segment attention masking: sdpa, the blockwise jnp path and
the Pallas kernels (interpret mode) must all agree with a brute-force
masked softmax, forward AND backward — positions in different packed
documents never attend to each other (round-4 verdict item 3: the
kernel previously had no segment support at all).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from quintnet_tpu.data.datasets import segments_from_tokens
from quintnet_tpu.nn.attention import mha_apply, mha_init, sdpa
from quintnet_tpu.ops.flash_attention import blockwise_attention
from quintnet_tpu.ops.pallas_attention import pallas_flash_attention


def _qkv(b=2, h=2, s=64, d=32, keyseed=0):
    ks = jax.random.split(jax.random.key(keyseed), 3)
    return tuple(jax.random.normal(k, (b, h, s, d)) for k in ks)


def _segments(b=2, s=64, keyseed=3, n_docs=3):
    """Random monotone segment ids (packed-document layout)."""
    rng = np.random.default_rng(keyseed)
    out = np.zeros((b, s), np.int32)
    for i in range(b):
        cuts = np.sort(rng.choice(np.arange(1, s), n_docs - 1,
                                  replace=False))
        out[i] = np.searchsorted(cuts, np.arange(s), side="right")
    return jnp.asarray(out)


def _brute(q, k, v, seg, causal):
    """Dense masked softmax oracle."""
    scores = jnp.einsum("bhsd,bhtd->bhst", q, k) / np.sqrt(q.shape[-1])
    mask = (seg[:, None, :, None] == seg[:, None, None, :])
    if causal:
        s = q.shape[2]
        mask = mask & jnp.tril(jnp.ones((s, s), bool))[None, None]
    scores = jnp.where(mask, scores, -1e30)
    return jnp.einsum("bhst,bhtd->bhsd",
                      jax.nn.softmax(scores, axis=-1), v)


@pytest.mark.fast
@pytest.mark.parametrize("causal", [False, True])
def test_sdpa_segments(causal):
    q, k, v = _qkv()
    seg = _segments()
    ref = _brute(q, k, v, seg, causal)
    out = sdpa(q, k, v, causal=causal, segment_ids=seg)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_blockwise_segments(causal):
    """Segment boundaries intentionally misaligned with the 16-wide
    blocks: interior tiles, crossing tiles and fully-masked tiles all
    occur."""
    q, k, v = _qkv()
    seg = _segments()
    ref = _brute(q, k, v, seg, causal)
    out = blockwise_attention(q, k, v, causal=causal, block_q=16,
                              block_k=16, segment_ids=seg)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)


def test_blockwise_segments_ragged():
    q, k, v = _qkv(s=50)
    seg = _segments(s=50)
    ref = _brute(q, k, v, seg, True)
    out = blockwise_attention(q, k, v, causal=True, block_q=16,
                              block_k=16, segment_ids=seg)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_pallas_kernel_segments_fwd(causal):
    """In-kernel segment masking (interpret mode), including tiles that
    are FULLY segment-masked (the exp-guard path)."""
    q, k, v = _qkv(s=128, d=64)
    seg = _segments(s=128)
    ref = _brute(q, k, v, seg, causal)
    out = pallas_flash_attention(q, k, v, causal, 32, 32, True,
                                 segment_ids=seg)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_pallas_kernel_segments_grads(causal):
    q, k, v = _qkv(s=64, d=32)
    seg = _segments()
    w = jax.random.normal(jax.random.key(9), q.shape)

    def ref_loss(q_, k_, v_):
        return jnp.sum(_brute(q_, k_, v_, seg, causal) * w)

    def fa_loss(q_, k_, v_):
        return jnp.sum(pallas_flash_attention(
            q_, k_, v_, causal, 32, 32, True, segment_ids=seg) * w)

    g_ref = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
    g_fa = jax.grad(fa_loss, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_fa, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=5e-5)


@pytest.mark.fast
def test_segments_from_tokens():
    eos = 9
    rows = np.asarray([[1, 2, eos, 3, 4, 5, eos, 6],
                       [eos, 1, 2, 3, eos, eos, 4, 5]])
    seg = segments_from_tokens(rows, eos)
    np.testing.assert_array_equal(
        seg, [[0, 0, 0, 1, 1, 1, 1, 2],
              [0, 1, 1, 1, 1, 2, 3, 3]])


def test_mha_apply_segments_match_manual():
    """Threading through the attention module: mha_apply(segment_ids=)
    equals running each document separately."""
    d, h, s = 32, 4, 24
    p = mha_init(jax.random.key(0), d)
    x = jax.random.normal(jax.random.key(1), (1, s, d))
    cut = 10
    seg = jnp.asarray([[0] * cut + [1] * (s - cut)])

    out = mha_apply(p, x, num_heads=h, causal=True, segment_ids=seg)
    out_a = mha_apply(p, x[:, :cut], num_heads=h, causal=True)
    out_b = mha_apply(p, x[:, cut:], num_heads=h, causal=True)
    np.testing.assert_allclose(np.asarray(out[:, :cut]),
                               np.asarray(out_a), rtol=2e-4, atol=2e-5)
    np.testing.assert_allclose(np.asarray(out[:, cut:]),
                               np.asarray(out_b), rtol=2e-4, atol=2e-5)


# ---------------------------------------------------------------------------
# sequence-parallel: ring / zigzag / ulysses carry the GLOBAL ids


@pytest.mark.parametrize("sp,mode", [(2, "ring"), (4, "ring"),
                                     (2, "zigzag"), (4, "zigzag"),
                                     (2, "ulysses")])
def test_sp_attention_segments_match_sdpa(sp, mode):
    """Sequence-parallel attention with segment masking == single-device
    masked sdpa on the gathered sequence (ring rotates ids with K/V,
    zigzag relays them through its permuted layout, ulysses all-gathers
    them)."""
    from jax.sharding import PartitionSpec as P

    from quintnet_tpu.core import collectives as cc
    from quintnet_tpu.core.mesh import mesh_from_sizes
    from quintnet_tpu.ops.ring_attention import (ring_attention,
                                                 zigzag_ring_attention)
    from quintnet_tpu.ops.ulysses_attention import ulysses_attention

    b, h, s, d = 2, 2, 32, 16
    q, k, v = _qkv(b=b, h=h, s=s, d=d)
    seg = _segments(b=b, s=s, n_docs=3)
    ref = _brute(q, k, v, seg, True)

    fns = {"ring": ring_attention, "zigzag": zigzag_ring_attention,
           "ulysses": ulysses_attention}
    fn = fns[mode]
    mesh = mesh_from_sizes(sp=sp)
    out = cc.shard_map_fn(
        lambda q_, k_, v_, s_: fn(q_, k_, v_, axis="sp", causal=True,
                                  segment_ids=s_),
        mesh,
        in_specs=(P(None, None, "sp"), P(None, None, "sp"),
                  P(None, None, "sp"), P(None, "sp")),
        out_specs=P(None, None, "sp"))(q, k, v, seg)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)


def test_mha_apply_segments_under_sp_matches_local():
    """mha_apply(sp_axis=..., segment_ids=<local slice of global ids>)
    equals the unsharded call with the full vector."""
    from jax.sharding import PartitionSpec as P

    from quintnet_tpu.core import collectives as cc
    from quintnet_tpu.core.mesh import mesh_from_sizes

    d, h, s = 16, 2, 16
    p = mha_init(jax.random.key(0), d)
    x = jax.random.normal(jax.random.key(1), (2, s, d))
    seg = _segments(b=2, s=s, n_docs=3)
    ref = mha_apply(p, x, num_heads=h, causal=True, segment_ids=seg)
    mesh = mesh_from_sizes(sp=2)
    out = cc.shard_map_fn(
        lambda p_, x_, s_: mha_apply(p_, x_, num_heads=h, causal=True,
                                     sp_axis="sp", segment_ids=s_),
        mesh, in_specs=(None, P(None, "sp"), P(None, "sp")),
        out_specs=P(None, "sp"))(p, x, seg)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)


def test_gpt2_segment_isolation_sp_strategy_golden():
    """Full GPT-2 train-step golden: segment_eos_id on a dp x sp mesh
    (sp-aware GLOBAL id derivation inside the model) == single device."""
    import optax

    from quintnet_tpu.core.config import Config
    from quintnet_tpu.models.gpt2 import (GPT2Config, gpt2_init,
                                          gpt2_model_spec)
    from quintnet_tpu.parallel.strategy import get_strategy

    gcfg = GPT2Config.tiny(segment_eos_id=5)
    model = gpt2_model_spec(gcfg)
    params = gpt2_init(jax.random.key(0), gcfg)
    ids = np.random.default_rng(0).integers(
        0, gcfg.vocab_size, (4, 16)).astype(np.int32)
    ids[:, 5] = 5  # a separator inside every row, off the sp boundary
    batch = (jnp.asarray(ids), jnp.asarray(ids))
    opt = optax.sgd(0.05)

    ref_loss, g = jax.value_and_grad(model.loss_fn)(params, batch)
    up, _ = opt.update(g, opt.init(params), params)
    p_ref = optax.apply_updates(params, up)

    cfg = Config.from_dict({"mesh_dim": [2, 2], "mesh_name": ["dp", "sp"],
                            "training": {"batch_size": 4,
                                         "grad_clip_norm": None}})
    strat = get_strategy("dp_sp", cfg)
    p = strat.shard_params(model, jax.tree.map(jnp.copy, params))
    st = strat.init_opt_state(model, opt, p)
    b = strat.shard_batch(batch, model)
    step = strat.make_train_step(model, opt)
    p, st, loss = step(p, st, b)
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)


# ---------------------------------------------------------------------------
# model-level: GPT2Config/LlamaConfig segment_eos_id


def _iso_case(vocab, eos, s1=7, s2=8, seed=0):
    """Two packed rows sharing doc2 but with DIFFERENT doc1 content of
    the same length. Under isolation, doc2's logits must be identical
    across the rows (doc1 can no longer leak into doc2); without it
    they differ. Position encodings are unaffected (same lengths)."""
    rng = np.random.default_rng(seed)
    doc1a = rng.integers(1, vocab, s1)
    doc1b = rng.integers(1, vocab, s1)
    doc2 = rng.integers(1, vocab, s2)
    row = lambda d1: np.concatenate([d1, [eos], doc2]).astype(np.int32)
    return np.stack([row(doc1a), row(doc1b)]), s1 + 1


@pytest.mark.fast
def test_gpt2_segment_isolation():
    from quintnet_tpu.models.gpt2 import GPT2Config, gpt2_apply, gpt2_init

    eos = 5
    iso = GPT2Config.tiny(segment_eos_id=eos)
    base = GPT2Config.tiny()
    params = gpt2_init(jax.random.key(0), base)
    rows, start2 = _iso_case(base.vocab_size, eos)

    out = gpt2_apply(params, jnp.asarray(rows), iso)
    np.testing.assert_allclose(np.asarray(out[0, start2:]),
                               np.asarray(out[1, start2:]),
                               rtol=1e-5, atol=1e-6)
    leak = gpt2_apply(params, jnp.asarray(rows), base)
    assert not np.allclose(np.asarray(leak[0, start2:]),
                           np.asarray(leak[1, start2:]), atol=1e-4)


def test_llama_segment_isolation():
    import dataclasses

    from quintnet_tpu.models.llama import LlamaConfig, llama_apply, \
        llama_init

    eos = 5
    base = LlamaConfig.tiny()
    iso = dataclasses.replace(base, segment_eos_id=eos)
    params = llama_init(jax.random.key(0), base)
    rows, start2 = _iso_case(base.vocab_size, eos)

    out = llama_apply(params, jnp.asarray(rows), iso)
    np.testing.assert_allclose(np.asarray(out[0, start2:]),
                               np.asarray(out[1, start2:]),
                               rtol=1e-5, atol=1e-5)
    leak = llama_apply(params, jnp.asarray(rows), base)
    assert not np.allclose(np.asarray(leak[0, start2:]),
                           np.asarray(leak[1, start2:]), atol=1e-4)


def test_segment_ids_from_input_matches_host_helper():
    from quintnet_tpu.models.gpt2 import GPT2Config, segment_ids_from_input

    eos = 9
    rows = np.asarray([[1, 2, eos, 3, 4, 5, eos, 6],
                       [eos, 1, 2, 3, eos, eos, 4, 5]], np.int32)
    cfg = GPT2Config.tiny(segment_eos_id=eos)
    dev = segment_ids_from_input(jnp.asarray(rows), cfg)
    np.testing.assert_array_equal(np.asarray(dev),
                                  segments_from_tokens(rows, eos))
    assert segment_ids_from_input(jnp.asarray(rows),
                                  GPT2Config.tiny()) is None


def test_gpt2_segment_isolation_trains_sharded():
    """segment_eos_id survives the full dp x tp shard_map train step."""
    import optax

    from quintnet_tpu.core.config import Config
    from quintnet_tpu.models.gpt2 import GPT2Config, gpt2_model_spec
    from quintnet_tpu.parallel.strategy import get_strategy

    gcfg = GPT2Config.tiny(segment_eos_id=5)
    cfg = Config.from_dict({"mesh_dim": [2, 2], "mesh_name": ["dp", "tp"],
                            "training": {"batch_size": 4,
                                         "grad_clip_norm": None}})
    model = gpt2_model_spec(gcfg)
    strat = get_strategy("dp_tp", cfg)
    opt = optax.adam(1e-3)
    params = strat.shard_params(model, model.init(jax.random.key(0)))
    state = strat.init_opt_state(model, opt, params)
    ids = np.random.default_rng(0).integers(
        0, gcfg.vocab_size, (4, 16)).astype(np.int32)
    step = strat.make_train_step(model, opt)
    params, state, loss = step(params, state,
                               strat.shard_batch((ids, ids), model))
    assert np.isfinite(float(loss))


def test_gpt2_segment_isolation_pp_raises():
    from quintnet_tpu.models.gpt2 import GPT2Config, gpt2_pipeline_fns

    with pytest.raises(NotImplementedError, match="pipeline"):
        gpt2_pipeline_fns(GPT2Config.tiny(segment_eos_id=5))


def test_trainer_packed_isolation_end_to_end():
    """PackedLMDataset -> Trainer with segment_eos_id: the packed
    pretraining loop with document isolation trains and reduces loss
    (the llama_pretrain --isolate-docs path, in-process)."""
    import optax  # noqa: F401  (trainer builds its own optimizer)

    from quintnet_tpu.core.config import Config
    from quintnet_tpu.data.datasets import ByteTokenizer, PackedLMDataset
    from quintnet_tpu.models.gpt2 import GPT2Config, gpt2_model_spec
    from quintnet_tpu.parallel.strategy import get_strategy
    from quintnet_tpu.train.trainer import Trainer

    tok = ByteTokenizer()
    texts = ["the quick brown fox " * 4, "jumps over lazy dogs " * 5,
             "packing sequences tightly " * 3] * 8
    ds = PackedLMDataset.from_texts(texts, tok, seq_len=32)
    gcfg = GPT2Config.tiny(vocab_size=264, n_positions=32,
                           segment_eos_id=tok.eos_token_id)
    cfg = Config.from_dict({
        "mesh_dim": [2], "mesh_name": ["dp"],
        "training": {"batch_size": 8, "epochs": 2, "log_every": 0,
                     "learning_rate": 3e-3, "optimizer": "adamw"}})
    trainer = Trainer(cfg, gpt2_model_spec(gcfg),
                      strategy=get_strategy("dp", cfg), task_type="clm")
    hist = trainer.fit(lambda ep: ds.batches(8, seed=ep))
    assert hist.train_loss[-1] < hist.train_loss[0]

"""Mesh planner invariants: estimates must track the real sharding
rules directionally (exact bytes are heuristic by design).
"""

import pytest

from quintnet_tpu.models.gpt2 import GPT2Config
from quintnet_tpu.tools.plan_mesh import GB, estimate, main, plan

pytestmark = pytest.mark.fast

CFG = GPT2Config.base()
KW = dict(batch=32, seq=1024)


def _mem(mesh, cfg=CFG, **kw):
    return estimate(cfg, mesh, **{**KW, **kw})


def test_tp_shards_blocks_not_embed():
    m1 = _mem({"tp": 1})
    m2 = _mem({"tp": 2})
    assert m2.breakdown["master"] < m1.breakdown["master"]
    # embeddings replicate over tp (no vocab_parallel): the shrink is
    # strictly less than half
    assert m2.breakdown["master"] > m1.breakdown["master"] // 2


def test_vocab_parallel_shards_wte():
    import dataclasses

    vp = dataclasses.replace(CFG, vocab_parallel=True,
                             padded_vocab_size=50304)
    assert (_mem({"tp": 2}, cfg=vp).breakdown["master"]
            < _mem({"tp": 2}).breakdown["master"])
    assert _mem({"tp": 2}, cfg=vp).breakdown["logits"] == 0


def test_zero1_divides_optimizer_by_dp():
    m = _mem({"dp": 4})
    z = _mem({"dp": 4}, zero1=True)
    assert z.breakdown["opt"] * 4 == m.breakdown["opt"]
    assert z.breakdown["master"] == m.breakdown["master"]


def test_sp_shards_activations_and_kills_dense_logits():
    m1, m2 = _mem({"sp": 1}), _mem({"sp": 2})
    assert m2.breakdown["acts"] < m1.breakdown["acts"]
    assert m1.breakdown["logits"] > 0 and m2.breakdown["logits"] == 0


def test_remat_cuts_activation_memory():
    assert (_mem({"dp": 1}, remat=True).breakdown["acts"]
            < _mem({"dp": 1}, remat=False).breakdown["acts"])


def test_plan_rejects_illegal_axes():
    plans = plan(CFG, n_devices=8, **KW)
    for p in plans:
        assert CFG.n_head % p.mesh["tp"] == 0
        assert CFG.n_layer % p.mesh["pp"] == 0
        assert KW["seq"] % p.mesh["sp"] == 0
        size = 1
        for v in p.mesh.values():
            size *= v
        assert size == 8
    # tp=8 is legal for 12 heads? no: 12 % 8 != 0
    assert not any(p.mesh["tp"] == 8 for p in plans)


def test_plan_sorts_fitting_first():
    plans = plan(CFG, n_devices=8, batch=32, seq=1024, hbm_gb=0.9)
    fits = [p.bytes_per_chip <= 0.9 * GB for p in plans]
    assert fits == sorted(fits, reverse=True)


def test_cli_smoke(capsys):
    main(["--model", "gpt2-medium", "--devices", "8", "--batch", "32"])
    out = capsys.readouterr().out
    assert "legal meshes fit" in out and "GiB" in out


def test_zero2_shards_grads_too():
    z1 = _mem({"dp": 4}, zero1=True)
    z2 = _mem({"dp": 4}, zero1=True, zero_stage=2)
    assert z2.breakdown["grads"] * 4 == z1.breakdown["grads"]
    assert z2.breakdown["opt"] == z1.breakdown["opt"]


def test_llama_geometry_gqa_and_tied_head():
    """Llama memory model: GQA shrinks attention params, SwiGLU uses
    intermediate_size, tied embeddings count once / untied twice."""
    import dataclasses

    from quintnet_tpu.models.llama import LlamaConfig
    from quintnet_tpu.tools.plan_mesh import _geometry, estimate

    cfg = LlamaConfig.llama_160m()  # GQA 12/4, tied
    d, L, V, blk, emb, pos, H = _geometry(cfg)
    # q + o full, k + v at kv/heads ratio, SwiGLU 3 matmuls, 2 norms
    r = cfg.n_kv_heads / cfg.n_heads
    assert blk == int(d * d * (2 + 2 * r)) + 3 * d * cfg.intermediate_size + 2 * d
    assert pos == 0 and emb == V * d

    untied = dataclasses.replace(cfg, tie_embeddings=False)
    assert _geometry(untied)[4] == 2 * V * d

    # vp shards the table over tp
    vp = dataclasses.replace(cfg, vocab_parallel=True)
    p_rep = estimate(cfg, {"tp": 4}, batch=8, seq=512)
    p_vp = estimate(vp, {"tp": 4}, batch=8, seq=512)
    assert p_vp.bytes_per_chip < p_rep.bytes_per_chip
    assert p_vp.breakdown["logits"] == 0  # sharded CE, no dense logits


def test_cli_llama_smoke(capsys):
    from quintnet_tpu.tools.plan_mesh import main

    main(["--model", "llama32-1b", "--devices", "8", "--batch", "32",
          "--seq", "2048", "--zero1", "--vocab-parallel"])
    out = capsys.readouterr().out
    assert "llama32-1b" in out and "legal meshes fit" in out


def test_fsdp_divides_block_param_memory():
    """--fsdp: master/opt/grads of the block share divide by dp; a
    dp-heavy fsdp plan needs far less memory than replicated."""
    from quintnet_tpu.tools.plan_mesh import estimate

    from quintnet_tpu.models.gpt2 import GPT2Config

    cfg = GPT2Config.medium()
    rep = estimate(cfg, {"dp": 8}, batch=32, seq=512)
    fs = estimate(cfg, {"dp": 8}, batch=32, seq=512, fsdp=True)
    assert fs.bytes_per_chip < 0.5 * rep.bytes_per_chip
    # embeddings stay replicated: fsdp can't go below the embed share
    assert fs.breakdown["master"] > 0


def test_cli_fsdp_smoke(capsys):
    from quintnet_tpu.tools.plan_mesh import main

    main(["--model", "llama32-1b", "--devices", "16", "--batch", "64",
          "--seq", "2048", "--fsdp", "--vocab-parallel"])
    assert "legal meshes fit" in capsys.readouterr().out

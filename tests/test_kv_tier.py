"""Tiered KV cache goldens (quintnet_tpu/serve/kv_tier.py + the tier
hooks in kv_pool.py / engine.py / fleet/proc.py).

THE contract: spilling the prefix cache to host RAM changes WHAT IS
WARM, never WHAT IS COMPUTED — demote→promote round-trips are
byte-exact (pool bytes AND quantization scales), a tiered engine's
token streams are bit-identical to the tier-off engine and to the
independent ``gpt2_generate`` oracle (greedy and fixed-seed sampling,
f32 and int8), promotion is asynchronous (other slots emit tokens
every step while the queue head is PROMOTING), the host tier is
byte-budgeted with its own LRU, demotion never blocks a decode step,
namespaced (adapter) chains stay isolated across BOTH tiers, and the
fleet's peer lookup ships a warm chain replica→replica instead of
re-prefilling. Plus the satellite invariants: the lazy-deletion
eviction heap agrees with the exhaustive ``min()`` oracle, and
``import_chain`` admits the longest block-aligned prefix that fits
instead of all-or-nothing.
"""

import os

import jax
import numpy as np
import pytest

from quintnet_tpu.fleet import ProcessFleet
from quintnet_tpu.models.gpt2 import GPT2Config, gpt2_init
from quintnet_tpu.models.gpt2_generate import gpt2_generate
from quintnet_tpu.serve import KVPool, ServeEngine, gpt2_family
from quintnet_tpu.serve.kv_tier import HostTier, record_nbytes

CFG = GPT2Config.tiny(n_layer=2)
FACTORY_FILE = os.path.join(os.path.dirname(__file__),
                            "_proc_factories.py")


@pytest.fixture(scope="module")
def params():
    return gpt2_init(jax.random.key(0), CFG)


def _engine(params, **kw):
    kw.setdefault("max_slots", 2)
    kw.setdefault("block_size", 4)
    kw.setdefault("num_blocks", 10)
    kw.setdefault("max_seq_len", 40)
    return ServeEngine(gpt2_family(CFG), params, **kw)


def _oracle(params, prompt, max_new, key=None, temperature=0.0,
            top_k=0):
    return np.asarray(gpt2_generate(
        params, np.asarray(prompt, np.int32)[None], CFG,
        max_new_tokens=max_new, temperature=temperature, top_k=top_k,
        key=key)[0])


def _run_one(eng, prompt, max_new, key=None):
    rid = eng.submit(np.asarray(prompt, np.int32), max_new, key=key)
    while eng.has_work:
        eng.step()
    return np.asarray(eng.result(rid))


# ---------------------------------------------------------------------
# HostTier unit: the byte-budgeted LRU store
# ---------------------------------------------------------------------

def _rec(nbytes, fill=4, seed=0):
    """A synthetic record whose k+v payload is exactly ``nbytes``."""
    rng = np.random.default_rng(seed)
    half = nbytes // 2
    return {"fill": fill,
            "k": rng.integers(0, 100, (half,)).astype(np.uint8),
            "v": rng.integers(0, 100, (nbytes - half,)
                              ).astype(np.uint8)}


class TestHostTier:
    def test_budget_must_be_positive(self):
        with pytest.raises(ValueError, match="byte_budget"):
            HostTier(byte_budget=0)
        with pytest.raises(ValueError, match="byte_budget"):
            HostTier(byte_budget=-1)

    def test_put_get_and_lru_eviction_under_pressure(self):
        t = HostTier(byte_budget=300)
        assert t.put(b"a", _rec(100, seed=1))
        assert t.put(b"b", _rec(100, seed=2))
        assert t.put(b"c", _rec(100, seed=3))
        assert t.bytes_used == 300 and len(t) == 3
        # touch "a" so "b" becomes the LRU victim
        assert t.get(b"a") is not None
        assert t.put(b"d", _rec(100, seed=4))
        assert t.bytes_used <= t.byte_budget
        assert t.contains(b"a") and not t.contains(b"b")
        assert t.evictions == 1 and t.demotions == 4

    def test_contains_does_not_touch_lru(self):
        t = HostTier(byte_budget=200)
        t.put(b"a", _rec(100, seed=1))
        t.put(b"b", _rec(100, seed=2))
        assert t.contains(b"a")      # a probe, not a use
        t.put(b"c", _rec(100, seed=3))
        assert not t.contains(b"a")  # "a" was still the LRU victim

    def test_oversized_record_refused_not_wedged(self):
        t = HostTier(byte_budget=100)
        assert not t.put(b"big", _rec(200))
        assert len(t) == 0 and t.bytes_used == 0
        assert t.put(b"ok", _rec(80))

    def test_same_key_overwrite_replaces_bytes(self):
        t = HostTier(byte_budget=300)
        t.put(b"a", _rec(100, seed=1))
        t.put(b"a", _rec(200, seed=2))
        assert len(t) == 1 and t.bytes_used == 200
        assert t.evictions == 0      # replacement, not pressure

    def test_summary_is_plain_scalars(self):
        t = HostTier(byte_budget=100)
        t.put(b"a", _rec(60))
        s = t.summary()
        assert s["bytes_used"] == 60
        assert s["records"] == 1 and s["demotions"] == 1
        assert all(isinstance(v, int) for v in s.values())


# ---------------------------------------------------------------------
# pool layer: demotion on eviction + byte-exact promotion round-trip
# ---------------------------------------------------------------------

def _tier_pool(num_blocks=4, block_size=4, policy=None,
               byte_budget=1 << 20):
    return KVPool(n_layers=2, n_kv_heads=2, head_dim=4,
                  block_size=block_size, num_blocks=num_blocks,
                  policy=policy,
                  host_tier=HostTier(byte_budget=byte_budget))


def _publish_chain(pool, toks, seed=0):
    """Publish a chain with distinct per-block payloads (and, on a
    scaled policy, distinct per-block scales)."""
    rng = np.random.default_rng(seed)
    blocks = pool.acquire(pool.blocks_for(len(toks)))
    bs = pool.block_size
    k, v = pool.k, pool.v
    ks, vs = pool.k_scale, pool.v_scale
    for b in blocks:
        sl = slice(b * bs, (b + 1) * bs)
        shape = (pool.n_layers, bs, pool.n_kv_heads, pool.head_dim)
        k = k.at[:, sl].set(rng.integers(-50, 50, shape)
                            .astype(pool.k.dtype))
        v = v.at[:, sl].set(rng.integers(-50, 50, shape)
                            .astype(pool.v.dtype))
        if pool.policy.scaled:
            sshape = (pool.n_layers, pool.n_kv_heads)
            ks = ks.at[:, b].set(rng.uniform(0.5, 2.0, sshape)
                                 .astype(np.float32))
            vs = vs.at[:, b].set(rng.uniform(0.5, 2.0, sshape)
                                 .astype(np.float32))
    pool.update(k, v, *(() if not pool.policy.scaled else (ks, vs)))
    pool.publish(toks, blocks, len(toks))
    pool.release(blocks)
    return blocks


def _force_evict_all_cached(pool):
    """Drain the free list, then evict every cached block (demoting
    each to the host tier); the acquired blocks are released back."""
    n = pool.num_free + pool.num_cached
    held = pool.acquire(n)
    assert held is not None
    pool.release(held)


class TestPoolTier:
    @pytest.mark.parametrize("policy", [None, "int8"])
    def test_demote_promote_round_trip_byte_exact(self, policy):
        toks = np.arange(8, dtype=np.int32)
        p = _tier_pool(policy=policy)
        _publish_chain(p, toks, seed=3)
        before = p.export_chain(toks)
        assert before["n_tokens"] == 8

        _force_evict_all_cached(p)
        tier = p.host_tier
        assert tier.demotions == 2 and len(tier) == 2
        assert p.lookup(toks, max_tokens=8).shared_blocks == []
        # snapshot the demoted records to check re-demotion later
        first = {k: {f: np.array(a) for f, a in r.items()
                     if f != "fill"}
                 for k, r in tier._records.items()}

        covered, keys = p.plan_promotion(toks)
        assert covered == 8 and len(keys) == 2
        assert p.promote_chain(keys) == (2, 2)
        assert tier.promotions == 2 and tier.promoted_tokens == 8
        # promoted chain is an ordinary device hit again, byte-exact
        assert p.lookup(toks, max_tokens=8).shared_blocks != []
        after = p.export_chain(toks)
        assert after["n_tokens"] == 8
        for a, b in zip(before["blocks"], after["blocks"]):
            assert a["fill"] == b["fill"]
            for f in a:
                if f == "fill":
                    continue
                assert np.asarray(a[f]).dtype == np.asarray(b[f]).dtype
                np.testing.assert_array_equal(a[f], b[f])

        # re-demote: the overwritten host records are byte-identical
        # to the first demotion's (demote -> promote -> demote is a
        # fixed point)
        _force_evict_all_cached(p)
        for key, snap in first.items():
            rec = tier._records[key]
            for f, arr in snap.items():
                np.testing.assert_array_equal(rec[f], arr)

    def test_plan_promotion_three_outcomes(self):
        toks = np.arange(8, dtype=np.int32)
        p = _tier_pool(num_blocks=8)
        # miss in both tiers
        assert p.plan_promotion(toks) == (0, [])
        _publish_chain(p, toks)
        # pure device hit: covered, nothing to promote
        covered, keys = p.plan_promotion(toks)
        assert covered == 8 and keys == []
        # host hit after demotion
        _force_evict_all_cached(p)
        covered, keys = p.plan_promotion(toks)
        assert covered == 8 and len(keys) == 2
        # tier-off pool reports no third outcome
        off = KVPool(n_layers=2, n_kv_heads=2, head_dim=4,
                     block_size=4, num_blocks=8)
        assert off.plan_promotion(toks) == (0, [])

    def test_promote_respects_block_budget(self):
        toks = np.arange(16, dtype=np.int32)
        p = _tier_pool(num_blocks=6)
        _publish_chain(p, toks)
        _force_evict_all_cached(p)
        _, keys = p.plan_promotion(toks)
        assert len(keys) == 4
        taken, blocks = p.promote_chain(keys, max_blocks=1)
        assert (taken, blocks) == (1, 1)
        # the promoted key is now device-resident: the next feed
        # consumes it for free and promotes the next budget's worth
        taken, blocks = p.promote_chain(keys, max_blocks=2)
        assert (taken, blocks) == (3, 2)
        taken, blocks = p.promote_chain(keys[3:], max_blocks=4)
        assert (taken, blocks) == (1, 1)
        assert p.plan_promotion(toks)[1] == []

    def test_vanished_host_record_truncates_chain(self):
        """A record budget-evicted mid-promotion is terminal for the
        chain: later keys are unreachable past the gap by any device
        walk, so they are consumed unpromoted (admission re-prefills
        from the gap) instead of imported as orphans."""
        toks = np.arange(12, dtype=np.int32)
        p = _tier_pool(num_blocks=6)
        _publish_chain(p, toks)
        _force_evict_all_cached(p)
        _, keys = p.plan_promotion(toks)
        assert len(keys) == 3
        del p.host_tier._records[keys[1]]
        p.host_tier.bytes_used = sum(
            record_nbytes(r) for r in p.host_tier._records.values())
        taken, blocks = p.promote_chain(keys)
        assert taken == 3 and blocks == 1      # only keys[0] landed
        covered, rest = p.plan_promotion(toks)
        assert covered == 4 and rest == []

    def test_namespaced_chains_isolated_across_tiers(self):
        toks = np.arange(8, dtype=np.int32)
        p = _tier_pool(num_blocks=4)
        blocks = p.acquire(2)
        p.publish(toks, blocks, 8, namespace="tenant-a")
        p.release(blocks)
        _force_evict_all_cached(p)
        assert len(p.host_tier) == 2
        # the other namespace (and the namespace-less default) miss
        assert p.plan_promotion(toks, namespace="tenant-b") == (0, [])
        assert p.plan_promotion(toks) == (0, [])
        covered, keys = p.plan_promotion(toks, namespace="tenant-a")
        assert covered == 8 and len(keys) == 2
        p.promote_chain(keys)
        assert p.lookup(toks, max_tokens=8,
                        namespace="tenant-b").shared_blocks == []
        assert p.lookup(toks, max_tokens=8,
                        namespace="tenant-a").shared_blocks != []

    def test_peek_counts_device_plus_host_extension(self):
        toks = np.arange(16, dtype=np.int32)
        p = _tier_pool(num_blocks=6)
        _publish_chain(p, toks)
        assert p.peek_chain_tokens(toks) == 16
        _force_evict_all_cached(p)
        assert p.peek_chain_tokens(toks) == 16       # host-resident
        _, keys = p.plan_promotion(toks)
        p.promote_chain(keys, max_blocks=2)
        assert p.peek_chain_tokens(toks) == 16       # 2 dev + 2 host
        assert p.peek_chain_tokens(toks[:8]) == 8
        assert p.peek_chain_tokens(
            np.arange(100, 108, dtype=np.int32)) == 0


# ---------------------------------------------------------------------
# satellite: partial import_chain (longest block-aligned prefix)
# ---------------------------------------------------------------------

class TestPartialImport:
    def _chain(self, n_tokens):
        src = KVPool(n_layers=1, n_kv_heads=2, head_dim=4,
                     block_size=4, num_blocks=8)
        toks = np.arange(n_tokens, dtype=np.int32)
        blocks = src.acquire(src.blocks_for(n_tokens))
        k = src.k
        for i, b in enumerate(blocks):
            k = k.at[:, b * 4:(b + 1) * 4].set(i + 1)
        src.update(k, src.v)
        src.publish(toks, blocks, n_tokens)
        src.release(blocks)
        return toks, src.export_chain(toks)

    def test_imports_longest_prefix_that_fits(self):
        toks, chain = self._chain(12)                # 3 full blocks
        dst = KVPool(n_layers=1, n_kv_heads=2, head_dim=4,
                     block_size=4, num_blocks=4)     # 3 usable
        held = dst.acquire(1)                        # only 2 left
        assert dst.import_chain(chain) == 8
        plan = dst.lookup(toks, max_tokens=12)
        assert len(plan.shared_blocks) == 2
        # the imported prefix carries the right bytes
        back = dst.export_chain(toks[:8])
        for i, rec in enumerate(back["blocks"]):
            np.testing.assert_array_equal(
                rec["k"], np.full_like(rec["k"], i + 1))
        dst.release(held)

    def test_zero_fit_still_returns_zero(self):
        toks, chain = self._chain(8)
        dst = KVPool(n_layers=1, n_kv_heads=2, head_dim=4,
                     block_size=4, num_blocks=4)
        held = dst.acquire(3)                        # nothing left
        assert dst.import_chain(chain) == 0
        dst.release(held)

    def test_full_fit_unchanged(self):
        toks, chain = self._chain(12)
        dst = KVPool(n_layers=1, n_kv_heads=2, head_dim=4,
                     block_size=4, num_blocks=8)
        assert dst.import_chain(chain) == 12


# ---------------------------------------------------------------------
# satellite: lazy-deletion eviction heap == exhaustive min() oracle
# ---------------------------------------------------------------------

class TestEvictionHeap:
    @pytest.mark.parametrize("tiered", [False, True])
    def test_eviction_order_matches_min_oracle(self, tiered):
        """Random publish/touch traffic, then drain: every forced
        eviction must pick exactly the block the exhaustive
        ``min(_cached_free, key=_lru.get)`` oracle picks — including
        after enough stale heap entries to trigger compaction."""
        p = KVPool(n_layers=1, n_kv_heads=1, head_dim=2,
                   block_size=2, num_blocks=10,
                   host_tier=(HostTier(byte_budget=1 << 20)
                              if tiered else None))
        rng = np.random.default_rng(7)
        next_tok = [0]

        def publish_one():
            blocks = p.acquire(1)
            if blocks is None:
                return
            toks = np.arange(next_tok[0], next_tok[0] + 2,
                             dtype=np.int32)
            next_tok[0] += 2
            p.publish(toks, blocks, 2)
            p.release(blocks)

        for _ in range(4):
            while p.num_free:
                publish_one()
            # touch randomly, enough to force at least one heap
            # compaction (threshold 8 * num_blocks + 64)
            for _ in range(200):
                cached = sorted(p._cached_free)
                b = cached[rng.integers(len(cached))]
                p.acquire_cached([b])
                p.release([b])
            held = []
            while p._cached_free:
                expect = min(p._cached_free, key=p._lru.__getitem__)
                got = p.acquire(1)
                assert got == [expect]
                held.extend(got)
            p.release(held)

    def test_stale_heap_entries_never_evict_a_live_block(self):
        """A block touched after entering the retention set leaves
        stale (stamp, block) pairs in the heap; popping one must not
        evict the block out of LRU order."""
        p = KVPool(n_layers=1, n_kv_heads=1, head_dim=2,
                   block_size=2, num_blocks=4)   # 3 usable
        t1, t2 = (np.arange(2, dtype=np.int32),
                  np.arange(10, 12, dtype=np.int32))
        a = p.acquire(1)
        p.publish(t1, a, 2)
        p.release(a)
        b = p.acquire(1)
        p.publish(t2, b, 2)
        p.release(b)
        # touch the OLDER chain repeatedly: heap now holds many stale
        # entries for ``a`` below ``b``'s stamp
        for _ in range(5):
            p.acquire_cached(a)
            p.release(a)
        p.acquire(p.num_free)
        assert p.acquire(1) == b     # b is LRU despite a's stale spam
        assert p.acquire(1) == a


# ---------------------------------------------------------------------
# engine layer: parity goldens + async promotion
# ---------------------------------------------------------------------

class TestEngineTier:
    def _workload(self, rng, n=4, prefix_len=12, total_len=16):
        base = np.asarray(rng.integers(0, CFG.vocab_size, (prefix_len,)),
                          np.int32)
        prompts = []
        for _ in range(n):
            tail = np.asarray(
                rng.integers(0, CFG.vocab_size, (total_len - prefix_len,)),
                np.int32)
            prompts.append(np.concatenate([base, tail]))
        return prompts

    @pytest.mark.parametrize("kv_dtype", [None, "int8"])
    @pytest.mark.parametrize("temp,topk", [(0.0, 0), (0.8, 5)])
    def test_tiered_on_equals_off_equals_oracle(self, params, rng,
                                                kv_dtype, temp, topk):
        """The acceptance golden: with the pool small enough that
        every admission evicts (and so demotes) the previous chain,
        resubmitted prompts host-hit and promote — and every token
        stream is bit-identical to the tier-off engine AND the
        independent oracle, greedy and fixed-seed sampled, f32 and
        int8."""
        kw = dict(num_blocks=10, kv_dtype=kv_dtype,
                  temperature=temp, top_k=topk)
        on = _engine(params, kv_tier_bytes=1 << 20, **kw)
        off = _engine(params, **kw)
        # total_len=20 puts a chain-SPECIFIC block boundary (@16)
        # inside the admission walk's len-1 cap — the boundary the
        # LRU evicts first and a resubmission must promote back
        prompts = self._workload(rng, total_len=20)
        # distinct chains + resubmissions of evicted ones
        seq = prompts + [prompts[0], prompts[2], prompts[0]]
        for i, prompt in enumerate(seq):
            keys = (None, None) if temp == 0.0 else (
                jax.random.key(100 + i), jax.random.key(100 + i))
            got_on = _run_one(on, prompt, 6, key=keys[0])
            got_off = _run_one(off, prompt, 6, key=keys[1])
            np.testing.assert_array_equal(got_on, got_off)
            np.testing.assert_array_equal(
                got_on, _oracle(params, prompt, 6,
                                key=(None if temp == 0.0
                                     else jax.random.key(100 + i)),
                                temperature=temp, top_k=topk))
        # the workload actually exercised the tier
        tier = on.kv_tier
        assert tier.demotions > 0 and tier.promotions > 0
        assert on._decode_blocked_demotions == 0
        assert on.metrics.summary()["host_hit_tokens"] > 0

    def test_promotion_is_async_other_slots_keep_decoding(self, params,
                                                          rng):
        """Sarathi discipline applied to memcpy: with a 1-block/step
        promotion budget, the queue head sits PROMOTING for several
        steps — and the already-running slot emits a token on every
        one of them."""
        eng = _engine(params, num_blocks=14, max_slots=2,
                      kv_tier_bytes=1 << 20,
                      kv_tier_promote_budget_bytes=1)
        # DISTINCT prompts: shared prefixes would cross-promote during
        # the warm-up and shrink the host chain under test
        prompts = [np.asarray(rng.integers(0, CFG.vocab_size, (16,)),
                              np.int32) for _ in range(3)]
        for prompt in prompts:           # warm, then evict A's chain
            _run_one(eng, prompt, 4)
        assert eng.kv_tier.demotions > 0
        covered, keys = eng.pool.plan_promotion(prompts[0][:16],
                                                max_tokens=15)
        assert len(keys) >= 2            # multi-step promotion ahead

        long_tokens = []
        rid_long = eng.submit(
            np.asarray(rng.integers(0, CFG.vocab_size, (6,)), np.int32),
            16, on_token=lambda r, t, l: long_tokens.append(t))
        eng.step()                       # admit + first token
        rid_a = eng.submit(prompts[0], 4)

        overlap_steps = 0
        while eng.has_work:
            promoting = bool(eng._promoting)
            n0 = len(long_tokens)
            eng.step()
            if promoting and len(long_tokens) > n0:
                overlap_steps += 1
        # the head really was parked PROMOTING while the long request
        # kept streaming, one budgeted block per step
        assert overlap_steps >= 2
        assert eng.kv_tier.promotions >= 2
        assert eng.metrics.summary()["kv_promotions"] >= 2
        np.testing.assert_array_equal(
            np.asarray(eng.result(rid_a)),
            _oracle(params, prompts[0], 4))
        np.testing.assert_array_equal(
            np.asarray(eng.result(rid_long))[6:],
            np.asarray(long_tokens, np.int32))

    def test_host_eviction_racing_promotion_degrades_to_prefill(
            self, params, rng):
        """The record a promotion was counting on vanishes mid-flight
        (host-budget pressure): the promotion force-finishes instead
        of wedging, admission re-prefills the gap, and the output is
        still oracle-identical."""
        eng = _engine(params, num_blocks=14, max_slots=2,
                      kv_tier_bytes=1 << 20,
                      kv_tier_promote_budget_bytes=1)
        prompts = [np.asarray(rng.integers(0, CFG.vocab_size, (16,)),
                              np.int32) for _ in range(3)]
        for prompt in prompts:
            _run_one(eng, prompt, 4)
        bg_prompt = np.asarray(rng.integers(0, CFG.vocab_size, (6,)),
                               np.int32)
        rid_bg = eng.submit(bg_prompt, 12)
        eng.step()
        rid_a = eng.submit(prompts[0], 4)
        # let the promotion start, then yank the rest of the tier out
        # from under it — the budget-eviction race, made deterministic
        for _ in range(50):
            if eng._promoting:
                break
            eng.step()
        assert eng._promoting
        eng.kv_tier._records.clear()
        eng.kv_tier.bytes_used = 0
        while eng.has_work:
            eng.step()
        assert not eng._promoting        # truncated, not wedged
        np.testing.assert_array_equal(
            np.asarray(eng.result(rid_a)), _oracle(params, prompts[0], 4))
        np.testing.assert_array_equal(
            np.asarray(eng.result(rid_bg)), _oracle(params, bg_prompt, 12))

    def test_tier_requires_prefix_cache(self, params):
        with pytest.raises(ValueError, match="prefix_cache"):
            _engine(params, kv_tier_bytes=1 << 20, prefix_cache=False)
        with pytest.raises(ValueError, match="kv_tier_bytes"):
            _engine(params, kv_tier_bytes=-1)

    def test_limits_report_tier(self, params):
        assert _engine(params, kv_tier_bytes=1 << 20
                       ).limits()["kv_tier"] is True
        assert _engine(params).limits()["kv_tier"] is False


# ---------------------------------------------------------------------
# fleet layer: peer lookup ships a warm chain instead of re-prefilling
# ---------------------------------------------------------------------

def test_fleet_peer_lookup_beats_reprefill(params, rng):
    """2 process replicas, round-robin: the first request warms
    replica 0; the identical prompt then dispatches to replica 1,
    whose tier peer lookup probes the fleet (``kv_peek``), finds
    replica 0's chain, and ships it over the existing
    ``kv_export``/``kv_import`` wire before the submit lands — a
    host-hit on ANY replica beats a re-prefill, token-identically."""
    spec = {"file": FACTORY_FILE, "func": "build_tiny_gpt2",
            "kwargs": {"temperature": 0.8, "top_k": 5,
                       "max_seq_len": 40, "num_blocks": 24,
                       "kv_tier_bytes": 1 << 20}}
    fleet = ProcessFleet(spec, n_replicas=2, policy="round_robin",
                         platform="cpu")
    try:
        prompt = np.asarray(rng.integers(0, CFG.vocab_size, (12,)),
                            np.int32)
        k1, k2 = jax.random.key(11), jax.random.key(22)
        out1 = fleet.generate([prompt], max_new_tokens=6, keys=[k1],
                              timeout=300)[0]
        probes0 = fleet.metrics.tier_probes
        out2 = fleet.generate([prompt], max_new_tokens=6, keys=[k2],
                              timeout=300)[0]
        assert fleet.metrics.tier_probes > probes0
        assert fleet.metrics.tier_peer_transfers >= 1
        np.testing.assert_array_equal(
            out1, _oracle(params, prompt, 6, key=k1,
                          temperature=0.8, top_k=5))
        np.testing.assert_array_equal(
            out2, _oracle(params, prompt, 6, key=k2,
                          temperature=0.8, top_k=5))
        s = fleet.summary()
        assert s["tier_peer_transfers"] >= 1
        assert s["tier_peer_fallbacks"] == 0
    finally:
        fleet.close()

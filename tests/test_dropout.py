"""Training-dropout seed discipline (SURVEY.md §7 hard part 5).

The reference trains GPT-2 with embd/attn/resid dropout 0.1
(gpt2_config.yaml:31-33; nn.Dropout in gpt2_embeddings/attention/mlp).
Here dropout is functional: the train step takes a ``seed``, each device
folds its (dp, ep, sp) coordinate — never tp, whose ranks must agree on
replicated-activation masks — and the PP schedules fold (microbatch,
stage) so the 1F1B vjp-recompute reproduces its forward masks.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from quintnet_tpu.core.config import Config
from quintnet_tpu.models.gpt2 import GPT2Config, gpt2_init, gpt2_model_spec
from quintnet_tpu.parallel.strategy import get_strategy

DROP = dict(embd_pdrop=0.1, attn_pdrop=0.1, resid_pdrop=0.1)


def _config(mesh_dim, mesh_name, schedule="afab", grad_acc=1):
    return Config.from_dict({
        "mesh_dim": list(mesh_dim),
        "mesh_name": list(mesh_name),
        "training": {"batch_size": 8, "gradient_accumulation_steps": grad_acc,
                     "schedule": schedule, "grad_clip_norm": None},
    })


def _batch(rng, cfg_model, b=8, t=16):
    ids = np.asarray(rng.integers(0, cfg_model.vocab_size, (b, t)), np.int32)
    return jnp.asarray(ids), jnp.asarray(ids)


def _run(name, cfg, cfg_model, params, batch, seed, steps=1):
    strat = get_strategy(name, cfg)
    model = gpt2_model_spec(cfg_model)
    p = strat.shard_params(model, jax.tree.map(jnp.copy, params))
    opt = optax.sgd(0.05)
    s = strat.init_opt_state(model, opt, p)
    b = strat.shard_batch(batch, model)
    step = strat.make_train_step(model, opt)
    loss = None
    for i in range(steps):
        p, s, loss = step(p, s, b, seed + i)
    return float(loss), p


def _leaves(p):
    return {str(k): np.asarray(jax.device_get(v))
            for k, v in jax.tree_util.tree_leaves_with_path(p)}


def test_dropout_changes_loss_and_is_seed_deterministic(rng):
    cfg_model = GPT2Config.tiny(n_layer=2, **DROP)
    cfg_nodrop = GPT2Config.tiny(n_layer=2)
    params = gpt2_init(jax.random.key(0), cfg_model)
    batch = _batch(rng, cfg_model)
    cfg = _config([1], ["dp"])

    l_det, _ = _run("single", cfg, cfg_nodrop, params, batch, seed=1)
    l_a, p_a = _run("single", cfg, cfg_model, params, batch, seed=1)
    l_a2, p_a2 = _run("single", cfg, cfg_model, params, batch, seed=1)
    l_b, _ = _run("single", cfg, cfg_model, params, batch, seed=2)

    assert l_a != l_det            # dropout actually perturbs the loss
    assert l_a == l_a2             # same seed -> bit-identical
    assert l_a != l_b              # different seed -> different masks
    for (k, x), (k2, y) in zip(sorted(_leaves(p_a).items()),
                               sorted(_leaves(p_a2).items())):
        np.testing.assert_array_equal(x, y, err_msg=str(k))


def test_dropout_tp_matches_single_device(rng):
    """tp-replicated activation masks must agree across tp ranks: with
    attn-prob dropout off (its mask shape is head-sharded) a tp=2 run is
    bit-comparable to single device — same canonical (0,0,0) key."""
    cfg_model = GPT2Config.tiny(n_layer=2, embd_pdrop=0.1, attn_pdrop=0.0,
                                resid_pdrop=0.1)
    params = gpt2_init(jax.random.key(0), cfg_model)
    batch = _batch(rng, cfg_model)

    l_1, _ = _run("single", _config([1], ["dp"]), cfg_model, params, batch,
                  seed=3)
    l_tp, _ = _run("tp", _config([2], ["tp"]), cfg_model, params, batch,
                   seed=3)
    np.testing.assert_allclose(l_tp, l_1, rtol=1e-5)


def test_dropout_pp_schedules_agree(rng):
    """AFAB and 1F1B derive dropout keys from the same (microbatch,
    stage) fold — identical masks, so identical losses and updates."""
    cfg_model = GPT2Config.tiny(n_layer=4, **DROP)
    params = gpt2_init(jax.random.key(0), cfg_model)
    batch = _batch(rng, cfg_model)

    l_afab, p_afab = _run("pp", _config([2], ["pp"], "afab", 2), cfg_model,
                          params, batch, seed=5)
    l_1f1b, p_1f1b = _run("pp", _config([2], ["pp"], "1f1b", 2), cfg_model,
                          params, batch, seed=5)
    np.testing.assert_allclose(l_afab, l_1f1b, rtol=1e-6)
    a, b = _leaves(p_afab), _leaves(p_1f1b)
    for k in a:
        np.testing.assert_allclose(a[k], b[k], rtol=1e-5, atol=1e-6,
                                   err_msg=str(k))


def test_dropout_dp_ranks_get_distinct_masks(rng):
    """dp members fold their coordinate: a dp=2 run must differ from the
    would-be all-ranks-same-mask run. Indirect check: dp=2 loss differs
    from single-device loss on the same global batch (masks differ on
    the second shard) while the no-dropout losses agree."""
    cfg_model = GPT2Config.tiny(n_layer=2, **DROP)
    cfg_nodrop = GPT2Config.tiny(n_layer=2)
    params = gpt2_init(jax.random.key(0), cfg_model)
    batch = _batch(rng, cfg_model)

    l1_nd, _ = _run("single", _config([1], ["dp"]), cfg_nodrop, params,
                    batch, seed=7)
    l2_nd, _ = _run("dp", _config([2], ["dp"]), cfg_nodrop, params, batch,
                    seed=7)
    np.testing.assert_allclose(l1_nd, l2_nd, rtol=1e-5)

    l1, _ = _run("single", _config([1], ["dp"]), cfg_model, params, batch,
                 seed=7)
    l2, _ = _run("dp", _config([2], ["dp"]), cfg_model, params, batch,
                 seed=7)
    assert abs(l1 - l2) > 1e-7


def test_dropout_grad_accum_micro_keys_differ(rng):
    """grad-accum microbatches fold their index — the accumulated run
    must differ from a single-shot run over the same batch (same seed),
    while without dropout they agree."""
    cfg_model = GPT2Config.tiny(n_layer=2, **DROP)
    cfg_nodrop = GPT2Config.tiny(n_layer=2)
    params = gpt2_init(jax.random.key(0), cfg_model)
    batch = _batch(rng, cfg_model)

    lnd_1, _ = _run("single", _config([1], ["dp"], grad_acc=1), cfg_nodrop,
                    params, batch, seed=9)
    lnd_2, _ = _run("single", _config([1], ["dp"], grad_acc=2), cfg_nodrop,
                    params, batch, seed=9)
    np.testing.assert_allclose(lnd_1, lnd_2, rtol=2e-5)

    ld_2a, _ = _run("single", _config([1], ["dp"], grad_acc=2), cfg_model,
                    params, batch, seed=9)
    ld_2b, _ = _run("single", _config([1], ["dp"], grad_acc=2), cfg_model,
                    params, batch, seed=9)
    assert ld_2a == ld_2b  # deterministic under accumulation too


class TestFusedPathAttnDropout:
    """attn_pdrop on the fused attention paths (flash blockwise / ring /
    ulysses). The reference gets prob-dropout everywhere via sdpa's
    dropout_p (gpt2_attention.py:156-161); round 2 silently dropped it
    on fused paths — these goldens pin the round-3 fix."""

    B, H, S, D = 2, 2, 32, 8

    def _qkv(self):
        ks = jax.random.split(jax.random.key(0), 3)
        return [jax.random.normal(k, (self.B, self.H, self.S, self.D))
                for k in ks]

    def test_blockwise_dropout_off_identical_and_on_unbiased(self):
        from quintnet_tpu.nn.attention import sdpa
        from quintnet_tpu.ops.flash_attention import blockwise_attention

        q, k, v = self._qkv()
        ref = sdpa(q, k, v, causal=True)
        # key given but pdrop=0 -> exact
        out0 = blockwise_attention(q, k, v, causal=True, block_q=8,
                                   block_k=8, pdrop=0.0,
                                   key=jax.random.key(1))
        np.testing.assert_allclose(np.asarray(out0), np.asarray(ref),
                                   rtol=1e-5, atol=1e-6)
        # dropout on: deterministic in key, different across keys,
        # unbiased in expectation (matches sdpa-dropout's expectation,
        # which is the undropped output)
        f = jax.jit(lambda key: blockwise_attention(
            q, k, v, causal=True, block_q=8, block_k=8, pdrop=0.3,
            key=key))
        a = f(jax.random.key(2))
        assert np.allclose(np.asarray(a), np.asarray(f(jax.random.key(2))))
        assert not np.allclose(np.asarray(a),
                               np.asarray(f(jax.random.key(3))))
        keys = jax.random.split(jax.random.key(4), 256)
        mean = jnp.mean(jax.vmap(f)(keys), axis=0)
        err = float(jnp.max(jnp.abs(mean - ref)))
        assert err < 0.12, err  # 256-sample MC noise bound

    def test_blockwise_dropout_loss_distribution_matches_sdpa(self):
        """VERDICT round-2 ask: with dropout ON, sdpa-vs-flash loss
        distributions match in expectation."""
        from quintnet_tpu.nn.attention import sdpa
        from quintnet_tpu.ops.flash_attention import blockwise_attention

        q, k, v = self._qkv()
        w = jax.random.normal(jax.random.key(9), q.shape)

        def loss(out):
            return jnp.mean(out * w)

        keys = jax.random.split(jax.random.key(5), 256)
        l_sdpa = jax.vmap(lambda kk: loss(sdpa(
            q, k, v, causal=True, pdrop=0.3, key=kk)))(keys)
        l_blk = jax.vmap(lambda kk: loss(blockwise_attention(
            q, k, v, causal=True, block_q=8, block_k=8, pdrop=0.3,
            key=kk)))(keys)
        m1, m2 = float(jnp.mean(l_sdpa)), float(jnp.mean(l_blk))
        s1, s2 = float(jnp.std(l_sdpa)), float(jnp.std(l_blk))
        assert abs(m1 - m2) < 3 * (s1 + s2) / np.sqrt(len(keys)) + 1e-4, \
            (m1, m2, s1, s2)
        assert 0.5 < (s1 + 1e-8) / (s2 + 1e-8) < 2.0, (s1, s2)

    @pytest.mark.parametrize("sp_mode", ["ring", "ulysses"])
    def test_sp_paths_dropout(self, sp_mode):
        from jax.sharding import PartitionSpec as P

        from quintnet_tpu.core import collectives as cc
        from quintnet_tpu.core.mesh import mesh_from_sizes
        from quintnet_tpu.nn.attention import sdpa
        from quintnet_tpu.ops.ring_attention import ring_attention
        from quintnet_tpu.ops.ulysses_attention import ulysses_attention

        q, k, v = self._qkv()
        mesh = mesh_from_sizes(sp=2)
        sp_spec = P(None, None, "sp")

        # ONE compiled function per pdrop with the key as a traced arg —
        # the previous shape of this test rebuilt the shard_map closure
        # per sampled key and spent 20+ min recompiling 128 times
        # (pdrop stays static: the attention paths branch on it in
        # Python)
        def make_fn(pdrop):
            def local(q_, k_, v_, key_):
                if sp_mode == "ring":
                    return ring_attention(q_, k_, v_, axis="sp",
                                          causal=True, pdrop=pdrop,
                                          key=key_)
                return ulysses_attention(q_, k_, v_, axis="sp",
                                         causal=True, pdrop=pdrop,
                                         key=key_)

            return jax.jit(cc.shard_map_fn(
                local, mesh,
                in_specs=(sp_spec, sp_spec, sp_spec, P()),
                out_specs=sp_spec))

        fns = {0.0: make_fn(0.0), 0.3: make_fn(0.3)}

        def run(pdrop, key):
            return fns[pdrop](q, k, v, key)

        ref = sdpa(q, k, v, causal=True)
        # pdrop=0 with a key stays exact
        np.testing.assert_allclose(np.asarray(run(0.0, jax.random.key(1))),
                                   np.asarray(ref), rtol=2e-4, atol=2e-5)
        # dropout actually perturbs, deterministically per key
        a = run(0.3, jax.random.key(2))
        b = run(0.3, jax.random.key(2))
        c = run(0.3, jax.random.key(3))
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        assert not np.allclose(np.asarray(a), np.asarray(c))
        # unbiased: MC mean over keys approaches the undropped output.
        # Bound: per-element MC std of a pdrop=0.3 prob-dropout output
        # here is ~0.6; max over 2*2*32*8=1024 elements of a 128-sample
        # mean concentrates near 0.6/sqrt(128)*sqrt(2*ln 1024) ~ 0.2 —
        # 0.27 gives ~3-sigma headroom (ulysses measured 0.209, ring
        # 0.19; a hard 0.2 bound was inside the noise band and flaked)
        keys = jax.random.split(jax.random.key(6), 128)
        outs = jnp.stack([run(0.3, kk) for kk in keys])
        err = float(jnp.max(jnp.abs(jnp.mean(outs, 0) - ref)))
        assert err < 0.27, err


class TestViTDropout:
    """ViTConfig.dropout is a wired knob (round-3 verdict flagged it as
    silently ignored): one rate at the embedding/attention/residual
    sites, same seed discipline as GPT-2."""

    from quintnet_tpu.models.vit import ViTConfig

    CFG_D = ViTConfig(image_size=14, patch_size=7, hidden_dim=16, depth=2,
                      num_heads=2, dropout=0.2)
    CFG_ND = ViTConfig(image_size=14, patch_size=7, hidden_dim=16, depth=2,
                       num_heads=2)

    def _batch(self, rng, b=8):
        x = np.asarray(rng.normal(size=(b, 14, 14, 1)), np.float32)
        y = np.asarray(rng.integers(0, 10, (b,)), np.int32)
        return jnp.asarray(x), jnp.asarray(y)

    def _run(self, name, mesh_dim, mesh_name, vcfg, params, batch, seed,
             schedule="afab", grad_acc=1):
        from quintnet_tpu.models.vit import vit_model_spec

        cfg = _config(mesh_dim, mesh_name, schedule, grad_acc)
        strat = get_strategy(name, cfg)
        model = vit_model_spec(vcfg)
        p = strat.shard_params(model, jax.tree.map(jnp.copy, params))
        opt = optax.sgd(0.05)
        s = strat.init_opt_state(model, opt, p)
        b = strat.shard_batch(batch, model)
        step = strat.make_train_step(model, opt)
        p, s, loss = step(p, s, b, seed)
        return float(loss), p

    def test_seed_determinism_and_perturbation(self, rng):
        from quintnet_tpu.models.vit import vit_init

        params = vit_init(jax.random.key(0), self.CFG_D)
        batch = self._batch(rng)
        l_nd, _ = self._run("single", [1], ["dp"], self.CFG_ND, params,
                            batch, seed=1)
        l_a, _ = self._run("single", [1], ["dp"], self.CFG_D, params,
                           batch, seed=1)
        l_a2, _ = self._run("single", [1], ["dp"], self.CFG_D, params,
                            batch, seed=1)
        l_b, _ = self._run("single", [1], ["dp"], self.CFG_D, params,
                           batch, seed=2)
        assert l_a != l_nd          # dropout perturbs the loss
        assert l_a == l_a2          # same seed -> bit-identical
        assert l_a != l_b           # different seed -> different masks

    def test_pp_schedules_agree(self, rng):
        from quintnet_tpu.models.vit import vit_init

        params = vit_init(jax.random.key(0), self.CFG_D)
        batch = self._batch(rng)
        l_afab, p_afab = self._run("pp", [2], ["pp"], self.CFG_D, params,
                                   batch, seed=5, schedule="afab",
                                   grad_acc=2)
        l_1f1b, p_1f1b = self._run("pp", [2], ["pp"], self.CFG_D, params,
                                   batch, seed=5, schedule="1f1b",
                                   grad_acc=2)
        np.testing.assert_allclose(l_afab, l_1f1b, rtol=1e-6)
        a, b = _leaves(p_afab), _leaves(p_1f1b)
        for k in a:
            np.testing.assert_allclose(a[k], b[k], rtol=1e-5, atol=1e-6,
                                       err_msg=str(k))

    def test_eval_deterministic(self, rng):
        from quintnet_tpu.models.vit import vit_init, vit_model_spec

        params = vit_init(jax.random.key(0), self.CFG_D)
        batch = self._batch(rng)
        model = vit_model_spec(self.CFG_D)
        assert float(model.loss_fn(params, batch)) == \
            float(model.loss_fn(params, batch))


def test_eval_has_no_dropout(rng):
    """model.loss_fn without a key is deterministic (the Trainer eval
    path never passes one)."""
    cfg_model = GPT2Config.tiny(n_layer=2, **DROP)
    params = gpt2_init(jax.random.key(0), cfg_model)
    batch = _batch(rng, cfg_model)
    model = gpt2_model_spec(cfg_model)
    l1 = float(model.loss_fn(params, batch))
    l2 = float(model.loss_fn(params, batch))
    assert l1 == l2

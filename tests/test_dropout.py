"""Training-dropout seed discipline (SURVEY.md §7 hard part 5).

The reference trains GPT-2 with embd/attn/resid dropout 0.1
(gpt2_config.yaml:31-33; nn.Dropout in gpt2_embeddings/attention/mlp).
Here dropout is functional: the train step takes a ``seed``, each device
folds its (dp, ep, sp) coordinate — never tp, whose ranks must agree on
replicated-activation masks — and the PP schedules fold (microbatch,
stage) so the 1F1B vjp-recompute reproduces its forward masks.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from quintnet_tpu.core.config import Config
from quintnet_tpu.models.gpt2 import GPT2Config, gpt2_init, gpt2_model_spec
from quintnet_tpu.parallel.strategy import get_strategy

DROP = dict(embd_pdrop=0.1, attn_pdrop=0.1, resid_pdrop=0.1)


def _config(mesh_dim, mesh_name, schedule="afab", grad_acc=1):
    return Config.from_dict({
        "mesh_dim": list(mesh_dim),
        "mesh_name": list(mesh_name),
        "training": {"batch_size": 8, "gradient_accumulation_steps": grad_acc,
                     "schedule": schedule, "grad_clip_norm": None},
    })


def _batch(rng, cfg_model, b=8, t=16):
    ids = np.asarray(rng.integers(0, cfg_model.vocab_size, (b, t)), np.int32)
    return jnp.asarray(ids), jnp.asarray(ids)


def _run(name, cfg, cfg_model, params, batch, seed, steps=1):
    strat = get_strategy(name, cfg)
    model = gpt2_model_spec(cfg_model)
    p = strat.shard_params(model, jax.tree.map(jnp.copy, params))
    opt = optax.sgd(0.05)
    s = strat.init_opt_state(model, opt, p)
    b = strat.shard_batch(batch, model)
    step = strat.make_train_step(model, opt)
    loss = None
    for i in range(steps):
        p, s, loss = step(p, s, b, seed + i)
    return float(loss), p


def _leaves(p):
    return {str(k): np.asarray(jax.device_get(v))
            for k, v in jax.tree_util.tree_leaves_with_path(p)}


def test_dropout_changes_loss_and_is_seed_deterministic(rng):
    cfg_model = GPT2Config.tiny(n_layer=2, **DROP)
    cfg_nodrop = GPT2Config.tiny(n_layer=2)
    params = gpt2_init(jax.random.key(0), cfg_model)
    batch = _batch(rng, cfg_model)
    cfg = _config([1], ["dp"])

    l_det, _ = _run("single", cfg, cfg_nodrop, params, batch, seed=1)
    l_a, p_a = _run("single", cfg, cfg_model, params, batch, seed=1)
    l_a2, p_a2 = _run("single", cfg, cfg_model, params, batch, seed=1)
    l_b, _ = _run("single", cfg, cfg_model, params, batch, seed=2)

    assert l_a != l_det            # dropout actually perturbs the loss
    assert l_a == l_a2             # same seed -> bit-identical
    assert l_a != l_b              # different seed -> different masks
    for (k, x), (k2, y) in zip(sorted(_leaves(p_a).items()),
                               sorted(_leaves(p_a2).items())):
        np.testing.assert_array_equal(x, y, err_msg=str(k))


def test_dropout_tp_matches_single_device(rng):
    """tp-replicated activation masks must agree across tp ranks: with
    attn-prob dropout off (its mask shape is head-sharded) a tp=2 run is
    bit-comparable to single device — same canonical (0,0,0) key."""
    cfg_model = GPT2Config.tiny(n_layer=2, embd_pdrop=0.1, attn_pdrop=0.0,
                                resid_pdrop=0.1)
    params = gpt2_init(jax.random.key(0), cfg_model)
    batch = _batch(rng, cfg_model)

    l_1, _ = _run("single", _config([1], ["dp"]), cfg_model, params, batch,
                  seed=3)
    l_tp, _ = _run("tp", _config([2], ["tp"]), cfg_model, params, batch,
                   seed=3)
    np.testing.assert_allclose(l_tp, l_1, rtol=1e-5)


def test_dropout_pp_schedules_agree(rng):
    """AFAB and 1F1B derive dropout keys from the same (microbatch,
    stage) fold — identical masks, so identical losses and updates."""
    cfg_model = GPT2Config.tiny(n_layer=4, **DROP)
    params = gpt2_init(jax.random.key(0), cfg_model)
    batch = _batch(rng, cfg_model)

    l_afab, p_afab = _run("pp", _config([2], ["pp"], "afab", 2), cfg_model,
                          params, batch, seed=5)
    l_1f1b, p_1f1b = _run("pp", _config([2], ["pp"], "1f1b", 2), cfg_model,
                          params, batch, seed=5)
    np.testing.assert_allclose(l_afab, l_1f1b, rtol=1e-6)
    a, b = _leaves(p_afab), _leaves(p_1f1b)
    for k in a:
        np.testing.assert_allclose(a[k], b[k], rtol=1e-5, atol=1e-6,
                                   err_msg=str(k))


def test_dropout_dp_ranks_get_distinct_masks(rng):
    """dp members fold their coordinate: a dp=2 run must differ from the
    would-be all-ranks-same-mask run. Indirect check: dp=2 loss differs
    from single-device loss on the same global batch (masks differ on
    the second shard) while the no-dropout losses agree."""
    cfg_model = GPT2Config.tiny(n_layer=2, **DROP)
    cfg_nodrop = GPT2Config.tiny(n_layer=2)
    params = gpt2_init(jax.random.key(0), cfg_model)
    batch = _batch(rng, cfg_model)

    l1_nd, _ = _run("single", _config([1], ["dp"]), cfg_nodrop, params,
                    batch, seed=7)
    l2_nd, _ = _run("dp", _config([2], ["dp"]), cfg_nodrop, params, batch,
                    seed=7)
    np.testing.assert_allclose(l1_nd, l2_nd, rtol=1e-5)

    l1, _ = _run("single", _config([1], ["dp"]), cfg_model, params, batch,
                 seed=7)
    l2, _ = _run("dp", _config([2], ["dp"]), cfg_model, params, batch,
                 seed=7)
    assert abs(l1 - l2) > 1e-7


def test_dropout_grad_accum_micro_keys_differ(rng):
    """grad-accum microbatches fold their index — the accumulated run
    must differ from a single-shot run over the same batch (same seed),
    while without dropout they agree."""
    cfg_model = GPT2Config.tiny(n_layer=2, **DROP)
    cfg_nodrop = GPT2Config.tiny(n_layer=2)
    params = gpt2_init(jax.random.key(0), cfg_model)
    batch = _batch(rng, cfg_model)

    lnd_1, _ = _run("single", _config([1], ["dp"], grad_acc=1), cfg_nodrop,
                    params, batch, seed=9)
    lnd_2, _ = _run("single", _config([1], ["dp"], grad_acc=2), cfg_nodrop,
                    params, batch, seed=9)
    np.testing.assert_allclose(lnd_1, lnd_2, rtol=2e-5)

    ld_2a, _ = _run("single", _config([1], ["dp"], grad_acc=2), cfg_model,
                    params, batch, seed=9)
    ld_2b, _ = _run("single", _config([1], ["dp"], grad_acc=2), cfg_model,
                    params, batch, seed=9)
    assert ld_2a == ld_2b  # deterministic under accumulation too


def test_eval_has_no_dropout(rng):
    """model.loss_fn without a key is deterministic (the Trainer eval
    path never passes one)."""
    cfg_model = GPT2Config.tiny(n_layer=2, **DROP)
    params = gpt2_init(jax.random.key(0), cfg_model)
    batch = _batch(rng, cfg_model)
    model = gpt2_model_spec(cfg_model)
    l1 = float(model.loss_fn(params, batch))
    l2 = float(model.loss_fn(params, batch))
    assert l1 == l2

"""Real-data-format readiness: the committed fixtures under
tests/fixtures/ are byte-accurate replicas of the real on-disk formats
(MNIST IDX/gzip as served by yann.lecun.com; CNN/DailyMail CSV schema
with quoted multi-line fields), and these tests run the REAL loader
paths end-to-end with the synthetic fallback DISABLED — if the
real-data path rots, they fail.

Reference: utils/Dataloader.py:38-358 (mnist_transform + CustomDataset
+ SummarizationDataset/Collator). Regenerate fixtures with
tools/make_fixtures.py (deterministic bytes).
"""

import gzip
import os
import struct

import jax
import numpy as np
import optax
import pytest

from quintnet_tpu.data.datasets import (ArrayDataset, ByteTokenizer,
                                        SummarizationDataset, load_mnist,
                                        make_batches)

FIX = os.path.join(os.path.dirname(__file__), "fixtures")
MNIST_DIR = os.path.join(FIX, "mnist")
CSV = os.path.join(FIX, "cnn_dm_tiny.csv")


def _raw_idx(path):
    with gzip.open(path, "rb") as f:
        magic = struct.unpack(">I", f.read(4))[0]
        ndim = magic & 0xFF
        assert (magic >> 8) & 0xFF == 0x08, "IDX dtype code must be ubyte"
        dims = struct.unpack(">" + "I" * ndim, f.read(4 * ndim))
        return np.frombuffer(f.read(), np.uint8).reshape(dims)


def test_mnist_fixture_is_real_idx_format():
    """The fixture files parse as genuine IDX: correct magic (0x0803
    images / 0x0801 labels), big-endian dims, gzip container."""
    img = os.path.join(MNIST_DIR, "train-images-idx3-ubyte.gz")
    lbl = os.path.join(MNIST_DIR, "train-labels-idx1-ubyte.gz")
    with gzip.open(img, "rb") as f:
        assert struct.unpack(">I", f.read(4))[0] == 0x0803
    with gzip.open(lbl, "rb") as f:
        assert struct.unpack(">I", f.read(4))[0] == 0x0801
    assert _raw_idx(img).shape == (24, 28, 28)
    assert _raw_idx(lbl).shape == (24,)


@pytest.mark.parametrize("split,n", [("train", 24), ("test", 8)])
def test_load_mnist_real_path_no_fallback(split, n):
    """load_mnist with synthetic_ok=False reads the IDX files and
    applies the reference's mean/std transform exactly."""
    x, y = load_mnist(MNIST_DIR, split=split, synthetic_ok=False)
    assert x.shape == (n, 28, 28, 1) and x.dtype == np.float32
    assert y.shape == (n,) and y.dtype == np.int32

    raw_name = "train" if split == "train" else "t10k"
    raw = _raw_idx(os.path.join(MNIST_DIR,
                                f"{raw_name}-images-idx3-ubyte.gz"))
    expect = ((raw.astype(np.float32) / 255.0) - 0.1307) / 0.3081
    np.testing.assert_array_equal(x[..., 0], expect)
    np.testing.assert_array_equal(
        y, _raw_idx(os.path.join(
            MNIST_DIR, f"{raw_name}-labels-idx1-ubyte.gz")))


def test_load_mnist_npz_real_path(tmp_path):
    """The mnist.npz branch (keras layout) — same transform, no
    fallback."""
    xtr = _raw_idx(os.path.join(MNIST_DIR, "train-images-idx3-ubyte.gz"))
    ytr = _raw_idx(os.path.join(MNIST_DIR, "train-labels-idx1-ubyte.gz"))
    np.savez(tmp_path / "mnist.npz", x_train=xtr, y_train=ytr,
             x_test=xtr[:4], y_test=ytr[:4])
    x, y = load_mnist(str(tmp_path), split="train", synthetic_ok=False)
    expect = ((xtr.astype(np.float32) / 255.0) - 0.1307) / 0.3081
    np.testing.assert_array_equal(x[..., 0], expect)
    np.testing.assert_array_equal(y, ytr.astype(np.int32))


def test_load_mnist_missing_raises_without_fallback(tmp_path):
    with pytest.raises(FileNotFoundError, match="MNIST not found"):
        load_mnist(str(tmp_path), synthetic_ok=False)


def test_mnist_fixture_trains_vit_end_to_end():
    """Loader -> batches -> sharded train step, real files all the way
    (the drop-in path the reference's MNIST run uses)."""
    from quintnet_tpu.core.config import Config
    from quintnet_tpu.models.vit import ViTConfig, vit_model_spec
    from quintnet_tpu.parallel.strategy import get_strategy

    x, y = load_mnist(MNIST_DIR, split="train", synthetic_ok=False)
    ds = ArrayDataset(x, y)
    cfg = Config.from_dict({"mesh_dim": [2], "mesh_name": ["dp"],
                            "training": {"batch_size": 8,
                                         "grad_clip_norm": None}})
    model = vit_model_spec(ViTConfig(hidden_dim=16, depth=2, num_heads=2))
    strat = get_strategy("dp", cfg)
    opt = optax.adam(1e-3)
    params = strat.shard_params(model, model.init(jax.random.key(0)))
    state = strat.init_opt_state(model, opt, params)
    step = strat.make_train_step(model, opt)
    losses = []
    for bx, by in make_batches(ds, 8, seed=0):
        params, state, loss = step(params, state,
                                   strat.shard_batch((bx, by), model))
        losses.append(float(loss))
    assert len(losses) == 3 and all(np.isfinite(l) for l in losses)


def test_cnn_dm_csv_real_path():
    """from_csv on the CNN/DM-schema fixture: quoted multi-line
    articles survive, prompt positions are -100-masked, summary tokens
    are supervised."""
    tok = ByteTokenizer()
    ds = SummarizationDataset.from_csv(CSV, tok, max_length=192)
    assert len(ds) == 6
    art, summ = ds.rows[0]
    assert "\n" in art and art.startswith("(CNN) -- ")  # multi-line field
    ids, labels = ds.encode_row(art, summ)
    assert ids.shape == (192,) and labels.shape == (192,)
    n_prompt = len(tok.encode(art + ds.PROMPT))
    assert (labels[:n_prompt] == -100).all()
    supervised = labels[labels != -100]
    np.testing.assert_array_equal(supervised, tok.encode(summ))


def test_cnn_dm_csv_trains_gpt2_end_to_end():
    """CSV -> collated CLM batches -> one GPT-2 train step (the
    reference's summarization fine-tune loop, real file format)."""
    from quintnet_tpu.core.config import Config
    from quintnet_tpu.models.gpt2 import GPT2Config, gpt2_model_spec
    from quintnet_tpu.parallel.strategy import get_strategy

    tok = ByteTokenizer()
    ds = SummarizationDataset.from_csv(CSV, tok, max_length=96)
    cfg = Config.from_dict({"mesh_dim": [2], "mesh_name": ["dp"],
                            "training": {"batch_size": 6,
                                         "grad_clip_norm": None}})
    gcfg = GPT2Config.tiny(vocab_size=264, n_positions=96)
    model = gpt2_model_spec(gcfg)
    strat = get_strategy("dp", cfg)
    opt = optax.adam(1e-3)
    params = strat.shard_params(model, model.init(jax.random.key(0)))
    state = strat.init_opt_state(model, opt, params)
    step = strat.make_train_step(model, opt)
    (bx, by), = list(ds.batches(6, shuffle=False))
    params, state, loss = step(params, state,
                               strat.shard_batch((bx, by), model))
    assert np.isfinite(float(loss))


def test_fixture_generator_is_deterministic(tmp_path):
    """Committed fixtures == regenerated fixtures, byte for byte (so
    fixture rot is detectable and regeneration is safe)."""
    import subprocess
    import sys

    import shutil

    tool = os.path.join(os.path.dirname(__file__), "..", "tools",
                        "make_fixtures.py")
    work = tmp_path / "tools"
    work.mkdir()
    shutil.copy(tool, work / "make_fixtures.py")
    subprocess.run([sys.executable, str(work / "make_fixtures.py")],
                   check=True, capture_output=True)
    gen = tmp_path / "tests" / "fixtures"
    for rel in ("cnn_dm_tiny.csv", "mnist/train-images-idx3-ubyte.gz",
                "mnist/train-labels-idx1-ubyte.gz",
                "mnist/t10k-images-idx3-ubyte.gz",
                "mnist/t10k-labels-idx1-ubyte.gz"):
        with open(os.path.join(FIX, rel), "rb") as a, \
                open(gen / rel, "rb") as b:
            assert a.read() == b.read(), f"fixture drift: {rel}"

"""Pipeline parallelism golden tests: AFAB and 1F1B schedules produce the
same loss and gradients as single-device training (the reference only
structurally tests layer distribution + a manual 2-stage send/recv —
tests/test_pipeline_parallel.py:35-168; numeric schedule equivalence is
new here)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

from quintnet_tpu.core import collectives as cc
from quintnet_tpu.core.mesh import mesh_from_sizes
from quintnet_tpu.models.vit import (
    ViTConfig,
    cross_entropy_loss,
    vit_apply,
    vit_init,
    vit_partition_specs,
    vit_pipeline_fns,
)
from quintnet_tpu.parallel.pp import (
    PipelineSpec,
    make_afab_loss_fn,
    make_1f1b_grad_fn,
    validate_pp,
)
from quintnet_tpu.parallel.train_step import make_parallel_train_step, reduce_grads

CFG = ViTConfig(image_size=14, patch_size=7, in_channels=1, hidden_dim=16,
                depth=4, num_heads=2, num_classes=10)
M = 4  # microbatches


@pytest.fixture(scope="module")
def mesh_pp():
    return mesh_from_sizes(pp=4)


def _data(n=8):
    x = jax.random.normal(jax.random.key(1), (n, 14, 14, 1))
    y = jax.random.randint(jax.random.key(2), (n,), 0, 10)
    return x, y


def _ref_loss_and_grads(params, batch):
    def loss_fn(p):
        x, y = batch
        return cross_entropy_loss(vit_apply(p, x, CFG), y)

    return jax.value_and_grad(loss_fn)(params)


def _check_grads(g, g_ref, rtol=1e-4, atol=1e-6):
    flat = jax.tree_util.tree_leaves_with_path(g)
    ref = dict(jax.tree_util.tree_leaves_with_path(g_ref))
    for path, leaf in flat:
        np.testing.assert_allclose(np.asarray(leaf), np.asarray(ref[path]),
                                   rtol=rtol, atol=atol, err_msg=str(path))


def test_validate_pp():
    with pytest.raises(ValueError):
        validate_pp(depth=6, pp_size=4)
    validate_pp(depth=8, pp_size=4)


def test_afab_matches_single_device(mesh_pp):
    params = vit_init(jax.random.key(0), CFG)
    batch = _data()
    loss_ref, g_ref = _ref_loss_and_grads(params, batch)

    embed_fn, stage_fn, head_loss_fn = vit_pipeline_fns(CFG)
    pipe_loss = make_afab_loss_fn(embed_fn, stage_fn, head_loss_fn,
                                  PipelineSpec(n_micro=M))
    specs = vit_partition_specs(CFG, tp_axis=None, pp_axis="pp")

    def local(p, b):
        loss, g = jax.value_and_grad(pipe_loss)(p, b)
        g = reduce_grads(g, specs, data_axes=(), model_axes=(),
                         partial_axes=("pp",))
        return loss, g

    loss, g = cc.shard_map_fn(
        local, mesh_pp,
        in_specs=(specs, (P(), P())),
        out_specs=(P(), specs),
    )(params, batch)

    np.testing.assert_allclose(float(loss), float(loss_ref), rtol=1e-5)
    _check_grads(g, g_ref)


@pytest.mark.parametrize("stored", [False, True],
                         ids=["recompute", "stored"])
def test_1f1b_matches_single_device(mesh_pp, stored):
    params = vit_init(jax.random.key(0), CFG)
    batch = _data()
    loss_ref, g_ref = _ref_loss_and_grads(params, batch)

    embed_fn, stage_fn, head_loss_fn = vit_pipeline_fns(CFG)
    grad_fn = make_1f1b_grad_fn(embed_fn, stage_fn, head_loss_fn,
                                PipelineSpec(n_micro=M),
                                store_activations=stored)
    specs = vit_partition_specs(CFG, tp_axis=None, pp_axis="pp")

    def local(p, b):
        loss, g = grad_fn(p, b)
        g = reduce_grads(g, specs, data_axes=(), model_axes=(),
                         partial_axes=("pp",))
        return loss, g

    loss, g = cc.shard_map_fn(
        local, mesh_pp,
        in_specs=(specs, (P(), P())),
        out_specs=(P(), specs),
    )(params, batch)

    np.testing.assert_allclose(float(loss), float(loss_ref), rtol=1e-5)
    _check_grads(g, g_ref)


def test_1f1b_equals_afab(mesh_pp):
    """The two schedules are different orderings of the same math."""
    params = vit_init(jax.random.key(3), CFG)
    batch = _data()

    embed_fn, stage_fn, head_loss_fn = vit_pipeline_fns(CFG)
    spec = PipelineSpec(n_micro=M)
    specs = vit_partition_specs(CFG, tp_axis=None, pp_axis="pp")

    pipe_loss = make_afab_loss_fn(embed_fn, stage_fn, head_loss_fn, spec)
    grad_fn = make_1f1b_grad_fn(embed_fn, stage_fn, head_loss_fn, spec)

    def afab(p, b):
        return jax.value_and_grad(pipe_loss)(p, b)

    def f1b(p, b):
        return grad_fn(p, b)

    la, ga = cc.shard_map_fn(afab, mesh_pp, in_specs=(specs, (P(), P())),
                             out_specs=(P(), specs))(params, batch)
    lb, gb = cc.shard_map_fn(f1b, mesh_pp, in_specs=(specs, (P(), P())),
                             out_specs=(P(), specs))(params, batch)

    np.testing.assert_allclose(float(la), float(lb), rtol=1e-6)
    for a, b in zip(jax.tree.leaves(ga), jax.tree.leaves(gb)):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-7)


def test_pp_train_step_via_builder(mesh_pp):
    """End-to-end: make_parallel_train_step with the AFAB pipeline loss
    (the integration the reference routes through PipelineTrainer +
    schedules, trainer.py:99-146)."""
    params = vit_init(jax.random.key(0), CFG)
    batch = _data()
    opt = optax.sgd(0.05)

    loss_ref, g_ref = _ref_loss_and_grads(params, batch)
    p_ref = optax.apply_updates(
        params, opt.update(g_ref, opt.init(params), params)[0])

    embed_fn, stage_fn, head_loss_fn = vit_pipeline_fns(CFG)
    pipe_loss = make_afab_loss_fn(embed_fn, stage_fn, head_loss_fn,
                                  PipelineSpec(n_micro=M))
    specs = vit_partition_specs(CFG, tp_axis=None, pp_axis="pp")

    step = make_parallel_train_step(
        mesh_pp, pipe_loss, opt, specs,
        batch_axes=(), model_axes=(), partial_axes=("pp",), donate=False)
    p_pp, _, loss = step(params, opt.init(params), batch)

    np.testing.assert_allclose(float(loss), float(loss_ref), rtol=1e-5)
    _check_grads(p_pp, p_ref)

"""Weight layout policies (quintnet_tpu/serve/weight_quant.py).

THE contract, mirroring tests/test_kv_quant.py on the weights side of
the shared LayoutPolicy protocol: a ``fake_quant``-weights engine —
f32 storage, all-ones per-output-channel scales, the FULL scaled code
path through nn/layers.quantized_matmul — is BIT-identical to the f32
engine across greedy, sampled, prefix-cache reuse, speculation,
chunked prefill, tp=2 and the llama family, which pins the
quantized-matmul seam as numerically inert. int8/fp8 are then gated
by the paged teacher-forced NLL delta (< 0.05 through the serving
path) and the provable per-channel round-trip bounds (int8: <=
scale/2; fp8 e4m3: <= scale * 448 * 2**-4 — one ulp at the binade
top). The policy is baked into the param tree at engine build, so
compile counts are UNCHANGED for every policy (one prefill, one
decode — zero backend compiles observed after warmup), the LoRA
delta path stays full-precision on top (adapter identity preserved
under fake_quant), and ServeMetrics surfaces
weight_bytes/weights_dtype through summary(), aggregate() and the
strict-parser Prometheus exposition.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from quintnet_tpu.models.gpt2 import GPT2Config, gpt2_init
from quintnet_tpu.serve import (ServeEngine, SpecConfig, gpt2_family,
                                make_weight_policy)
from quintnet_tpu.serve.kv_pool import KVPool
from quintnet_tpu.serve.kv_quant import (FLOAT8_DTYPE,
                                         dequant_roundtrip_error,
                                         paged_eval_nll)
from quintnet_tpu.serve.weight_quant import (WeightLayoutPolicy,
                                             present_targets,
                                             quantize_params,
                                             weight_bytes,
                                             weight_policy_names)

CFG = GPT2Config.tiny(n_layer=2)

needs_fp8 = pytest.mark.skipif(FLOAT8_DTYPE is None,
                               reason="no float8_e4m3fn in this jax")


@pytest.fixture(scope="module")
def params():
    return gpt2_init(jax.random.key(0), CFG)


def _prompts(rng, lengths):
    return [np.asarray(rng.integers(0, CFG.vocab_size, (t,)), np.int32)
            for t in lengths]


def _engine(params, weights_dtype, **kw):
    kw.setdefault("max_slots", 3)
    kw.setdefault("block_size", 4)
    kw.setdefault("num_blocks", 48)
    kw.setdefault("max_seq_len", 32)
    return ServeEngine(gpt2_family(CFG), params,
                       weights_dtype=weights_dtype, **kw)


def _serve(eng, prompts, max_new, *, arrivals=None, keys=None):
    """Submit with staggered arrivals, run to completion, return
    outputs in submission order."""
    arrivals = arrivals or [0] * len(prompts)
    keys = keys or [jax.random.key(100 + i) for i in range(len(prompts))]
    rids = {}
    submitted, step = 0, 0
    while submitted < len(prompts) or eng.has_work:
        while (submitted < len(prompts)
               and arrivals[submitted] <= step):
            rids[submitted] = eng.submit(prompts[submitted], max_new,
                                         key=keys[submitted])
            submitted += 1
        eng.step()
        step += 1
        assert step < 1000, "engine failed to drain"
    return [eng.result(rids[i]) for i in range(len(prompts))]


# ---------------------------------------------------------------------
# policy objects: one protocol, two faces
# ---------------------------------------------------------------------

class TestPolicy:
    def test_resolution(self):
        assert make_weight_policy(None).name == "f32"
        assert make_weight_policy("int8").name == "int8"
        assert make_weight_policy(jnp.float32).name == "f32"
        assert make_weight_policy(jnp.bfloat16).name == "bf16"
        p = make_weight_policy("fake_quant")
        assert make_weight_policy(p) is p
        with pytest.raises(ValueError, match="unknown weights_dtype"):
            make_weight_policy("int4")
        with pytest.raises(ValueError, match="no weight policy"):
            make_weight_policy(jnp.int8)  # raw int8 needs the scales

    def test_ladder_pinned_in_specs(self):
        from quintnet_tpu.analysis.specs import weight_layout_policies

        assert weight_policy_names() == weight_layout_policies()

    def test_shared_protocol(self):
        """Weights and KV consume ONE LayoutPolicy contract — the
        weight ladder subclasses the same base the KV ladder does,
        without the two ladders' objects being interchangeable."""
        from quintnet_tpu.serve.kv_quant import (KVLayoutPolicy,
                                                 LayoutPolicy,
                                                 make_policy)

        for name in weight_policy_names():
            if name == "fp8" and FLOAT8_DTYPE is None:
                continue
            pol = make_weight_policy(name)
            assert isinstance(pol, WeightLayoutPolicy)
            assert isinstance(pol, LayoutPolicy)
            assert not isinstance(pol, KVLayoutPolicy)
        assert not isinstance(make_policy("int8"), WeightLayoutPolicy)

    def test_scaled_flags(self):
        assert not make_weight_policy("f32").scaled
        assert not make_weight_policy("bf16").scaled
        assert make_weight_policy("int8").scaled
        assert make_weight_policy("fake_quant").scaled
        assert make_weight_policy("fake_quant").qmax == 0.0

    def test_int8_roundtrip_bound(self, rng):
        # [L, in, out] with per-OUTPUT-channel scales (axes = in dim)
        x = rng.normal(size=(2, 16, 8)).astype(np.float32)
        err, sc = dequant_roundtrip_error(make_weight_policy("int8"), x,
                                          axes=(-2,))
        assert err.shape == sc.shape == (2, 8)
        # the provable absmax bound: <= scale / 2 per element
        assert np.all(np.asarray(err) <= np.asarray(sc) * 0.5 + 1e-6)
        assert np.asarray(err).max() > 0  # rounding really happened
        err0, sc0 = dequant_roundtrip_error(
            make_weight_policy("fake_quant"), x, axes=(-2,))
        assert np.all(np.asarray(err0) == 0.0)
        assert np.all(np.asarray(sc0) == 1.0)

    @needs_fp8
    def test_fp8_roundtrip_bound(self, rng):
        """e4m3's worst relative spacing below qmax is 2**-3 between
        mantissa steps at a binade top; after the absmax prescale the
        provable per-element bound is scale * 448 * 2**-4 (half a
        step). Rounding must really be float-shaped: small values
        survive (no integer truncation to zero)."""
        x = rng.normal(size=(2, 16, 8)).astype(np.float32)
        pol = make_weight_policy("fp8")
        err, sc = dequant_roundtrip_error(pol, x, axes=(-2,))
        bound = np.asarray(sc) * 448.0 * 2.0 ** -4
        assert np.all(np.asarray(err) <= bound + 1e-6)
        assert np.asarray(err).max() > 0
        # fractions survive the narrowing cast (no jnp.round in the
        # float-storage quant path)
        q = pol.quant(jnp.asarray([0.3, -0.7]), jnp.asarray(1.0))
        assert q.dtype == jnp.dtype(FLOAT8_DTYPE)
        assert np.all(np.asarray(pol.dequant(q, jnp.asarray(1.0)))
                      != 0.0)


# ---------------------------------------------------------------------
# tree surgery
# ---------------------------------------------------------------------

class TestPacking:
    def test_quantize_params_targets_only(self, params):
        fam = gpt2_family(CFG)
        targets = present_targets(params, fam.weight_targets)
        assert targets == fam.weight_targets  # dense: all present
        q = quantize_params(params, targets,
                            make_weight_policy("int8"))
        for path in targets:
            node = q["blocks"]
            ref = params["blocks"]
            for k in path:
                node, ref = node[k], ref[k]
            assert node["w"].dtype == jnp.int8
            L, _fin, fout = ref["w"].shape
            assert node["w_scale"].shape == (L, fout)
            assert node["w_scale"].dtype == jnp.float32
            if "b" in ref:                 # bias stays full-precision
                assert node["b"] is ref["b"]
        # untargeted leaves keep their identity (same device buffers)
        assert q["embedding"] is params["embedding"]
        assert q["head"] is params["head"]
        assert q["blocks"]["ln1"] is params["blocks"]["ln1"]
        # the f32 policy is the identity, same OBJECT
        assert quantize_params(params, targets,
                               make_weight_policy("f32")) is params

    def test_present_targets_drop_missing(self, params):
        """An MoE block swaps mlp for moe — the dense-mlp targets must
        drop out instead of KeyError-ing (experts stay f32)."""
        fam = gpt2_family(CFG)
        no_mlp = {**params,
                  "blocks": {k: v for k, v in params["blocks"].items()
                             if k != "mlp"}}
        kept = present_targets(no_mlp, fam.weight_targets)
        assert kept == (("attn", "qkv"), ("attn", "proj"))

    def test_weight_bytes_ratio(self, params):
        fam = gpt2_family(CFG)
        targets = present_targets(params, fam.weight_targets)
        b32 = weight_bytes(params, targets)
        q = quantize_params(params, targets,
                            make_weight_policy("int8"))
        b8 = weight_bytes(q, targets)
        # THE capacity claim: >= 3.5x fewer bytes on the serving
        # matmul weights, per-channel f32 scales included
        assert b32 / b8 >= 3.5
        # and the engine accounts the same numbers
        eng = _engine(params, "int8")
        assert eng.weight_bytes == b8
        assert _engine(params, "f32").weight_bytes == b32


# ---------------------------------------------------------------------
# the identity golden matrix: fake_quant weights == f32, bit for bit
# ---------------------------------------------------------------------

class TestFakeQuantIdentity:
    def _match(self, params, rng, *, kw_a=None, lengths=(5, 9, 3),
               max_new=6, arrivals=None):
        kw_a = kw_a or {}
        prompts = _prompts(rng, lengths)
        keys = [jax.random.key(70 + i) for i in range(len(prompts))]
        out32 = _serve(_engine(params, "f32", **kw_a), prompts, max_new,
                       arrivals=arrivals, keys=keys)
        outfk = _serve(_engine(params, "fake_quant", **kw_a),
                       prompts, max_new, arrivals=arrivals, keys=keys)
        for a, b in zip(out32, outfk):
            np.testing.assert_array_equal(a, b)
        return out32

    def test_greedy(self, params, rng):
        self._match(params, rng)

    def test_sampled(self, params, rng):
        self._match(params, rng, kw_a=dict(temperature=0.9, top_k=7))

    def test_prefix_cache_with_reuse(self, params, rng):
        shared = np.asarray(rng.integers(0, CFG.vocab_size, (10,)),
                            np.int32)
        tails = [np.asarray(rng.integers(0, CFG.vocab_size, (t,)),
                            np.int32) for t in (3, 5, 2, 4)]
        prompts = [np.concatenate([shared, t]) for t in tails]
        keys = [jax.random.key(200 + i) for i in range(4)]
        outs = {}
        for name in ("f32", "fake_quant"):
            eng = _engine(params, name, max_slots=2)
            outs[name] = _serve(eng, prompts, 5,
                                arrivals=[0, 0, 6, 6], keys=keys)
            assert eng.metrics.prefix_hit_tokens > 0  # cache really hit
        for a, b in zip(outs["f32"], outs["fake_quant"]):
            np.testing.assert_array_equal(a, b)

    def test_speculative_sampled(self, params, rng):
        self._match(params, rng,
                    kw_a=dict(spec=SpecConfig(), temperature=0.7),
                    max_new=8)

    def test_chunked_prefill(self, params, rng):
        self._match(params, rng,
                    kw_a=dict(chunked_prefill=True, prefill_len=8,
                              prefill_chunk_budget=4),
                    lengths=(5, 14, 3))

    def test_stacked_with_kv_fake_quant(self, params, rng):
        """Both seams at once: fake_quant WEIGHTS over a fake_quant KV
        pool is still bit-identical to the all-f32 engine."""
        self._match(params, rng, kw_a=dict(kv_dtype="fake_quant"))

    def test_tp2(self, params, rng):
        """Scaled weights under a tp=2 shard_map: w_scale shards like
        the out dim of its weight (augment_weight_specs), outputs
        bit-identical to the single-device f32 engine."""
        from quintnet_tpu.core.mesh import mesh_from_sizes
        from quintnet_tpu.models.gpt2 import gpt2_to_tp_layout

        prompts = _prompts(rng, (5, 9, 3))
        keys = [jax.random.key(50 + i) for i in range(3)]
        out32 = _serve(_engine(params, "f32"), prompts, 6, keys=keys)
        mesh = mesh_from_sizes(tp=2)
        tp_params = gpt2_to_tp_layout(params, CFG, 2)
        outfk = _serve(_engine(tp_params, "fake_quant", mesh=mesh),
                       prompts, 6, keys=keys)
        for a, b in zip(out32, outfk):
            np.testing.assert_array_equal(a, b)

    def test_llama_family(self, rng):
        from quintnet_tpu.models.llama import LlamaConfig, llama_init
        from quintnet_tpu.serve import llama_family

        cfg = LlamaConfig.tiny(n_layers=2)
        lparams = llama_init(jax.random.key(1), cfg)
        prompts = [np.asarray(rng.integers(0, cfg.vocab_size, (t,)),
                   np.int32) for t in (4, 7)]
        keys = [jax.random.key(300 + i) for i in range(2)]
        outs = {}
        for name in ("f32", "fake_quant"):
            eng = ServeEngine(llama_family(cfg), lparams, max_slots=2,
                              block_size=4, num_blocks=32,
                              max_seq_len=24, weights_dtype=name)
            outs[name] = _serve(eng, prompts, 5, keys=keys)
        for a, b in zip(outs["f32"], outs["fake_quant"]):
            np.testing.assert_array_equal(a, b)

    def test_lora_stays_full_precision_on_top(self, params, rng,
                                              tmp_path):
        """The adapter delta rides OVER the scaled dot: a fake_quant
        engine serving a LoRA tenant is bit-identical to the f32
        engine serving the same tenant (and the packed factors never
        inherit the storage dtype)."""
        from quintnet_tpu.models.lora import (LoRAConfig, lora_init,
                                              save_lora)
        from quintnet_tpu.serve import AdapterRegistry

        lcfg = LoRAConfig(rank=4)
        lora = lora_init(jax.random.key(3), params["blocks"], lcfg)
        lora = jax.tree.map(
            lambda l: l + 0.02 * jax.random.normal(
                jax.random.key(103), l.shape), lora)
        path = str(tmp_path / "t.safetensors")
        save_lora(lora, lcfg, path)
        prompts = _prompts(rng, (5, 8))
        keys = [jax.random.key(400 + i) for i in range(2)]
        outs = {}
        for name in ("f32", "fake_quant"):
            reg = AdapterRegistry()
            reg.register("t", path)
            eng = _engine(params, name, adapters=reg, max_seq_len=48)
            rids = [eng.submit(p, 5, key=k, adapter_id="t")
                    for p, k in zip(prompts, keys)]
            eng.run()
            outs[name] = [eng.result(r) for r in rids]
        for a, b in zip(outs["f32"], outs["fake_quant"]):
            np.testing.assert_array_equal(a, b)


# ---------------------------------------------------------------------
# int8/fp8 quality gates + the compile bound
# ---------------------------------------------------------------------

class TestQuality:
    def _nll(self, params, name, rows):
        fam = gpt2_family(CFG)
        qparams = quantize_params(
            params, present_targets(params, fam.weight_targets),
            make_weight_policy(name))
        pool = KVPool(n_layers=CFG.n_layer, n_kv_heads=CFG.n_head,
                      head_dim=CFG.n_embd // CFG.n_head, block_size=4,
                      num_blocks=32)
        return paged_eval_nll(fam, qparams, pool, rows)

    def test_paged_ppl_delta_gate(self, params, rng):
        """Teacher-forced NLL THROUGH the paged serving path under
        packed weights: int8/fp8 quality loss stays under the gate,
        fake_quant's is exactly zero."""
        rows = rng.integers(0, CFG.vocab_size, (4, 24)).astype(np.int32)
        names = ["f32", "fake_quant", "int8"]
        if FLOAT8_DTYPE is not None:
            names.append("fp8")
        nll = {name: self._nll(params, name, rows) for name in names}
        assert nll["fake_quant"] == nll["f32"]  # the identity, again
        for name in names[2:]:
            assert abs(nll[name] - nll["f32"]) < 0.05, (
                f"{name} paged ppl delta too large: "
                f"{nll[name]:.4f} vs {nll['f32']:.4f}")

    @pytest.mark.parametrize("name", ["bf16", "int8", "fake_quant"])
    def test_serves_and_compile_bound_holds(self, params, rng, name):
        """Mixed staggered trace per policy: everything finishes and
        the compile counts are exactly the f32 engine's — one
        prefill, one decode (the policy is baked into the tree, not
        a program)."""
        prompts = _prompts(rng, (3, 5, 4, 6, 3))
        eng = _engine(params, name, max_slots=3, block_size=2,
                      num_blocks=12, max_seq_len=16)
        outs = _serve(eng, prompts, 5, arrivals=[0, 1, 2, 5, 8])
        assert all(len(o) == len(p) + 5
                   for o, p in zip(outs, prompts))
        assert eng.metrics.finished == len(prompts)
        assert eng.compile_stats() == {"prefill": 1, "decode": 1}
        eng.assert_compile_count()

    @needs_fp8
    def test_fp8_serves_and_compile_bound_holds(self, params, rng):
        eng = _engine(params, "fp8")
        outs = _serve(eng, _prompts(rng, (4, 7)), 5)
        assert all(len(o) > 0 for o in outs)
        assert eng.compile_stats() == {"prefill": 1, "decode": 1}
        eng.assert_compile_count()

    def test_zero_backend_compiles_after_warmup(self, params, rng):
        """jax.monitoring sees ZERO backend_compile events across a
        20-step int8 trace after warmup — the quantized tree hits the
        same two compiled programs."""
        import jax.monitoring as monitoring

        eng = _engine(params, "int8", max_slots=3, block_size=2,
                      num_blocks=12, max_seq_len=16)
        eng.submit(_prompts(rng, (4,))[0], 3)
        eng.run()
        assert eng.compile_stats() == {"prefill": 1, "decode": 1}

        compiles = []
        monitoring.register_event_duration_secs_listener(
            lambda name, dur, **kw: compiles.append(name)
            if "backend_compile" in name else None)
        try:
            prompts = _prompts(rng, (3, 5, 4, 6, 3, 5))
            arrivals = [0, 1, 3, 6, 10, 14]
            submitted = 0
            for step in range(20):
                while (submitted < len(prompts)
                       and arrivals[submitted] <= step):
                    eng.submit(prompts[submitted], 4)
                    submitted += 1
                eng.step()
            assert submitted == len(prompts)
        finally:
            monitoring.clear_event_listeners()
        assert compiles == []
        assert eng.compile_stats() == {"prefill": 1, "decode": 1}


# ---------------------------------------------------------------------
# metrics surface
# ---------------------------------------------------------------------

class TestMetrics:
    def test_summary_surfaces_weight_bytes(self, params, rng):
        eng = _engine(params, "int8")
        _serve(eng, _prompts(rng, (4,)), 3)
        s = eng.metrics.summary()
        assert s["weight_bytes"] == eng.weight_bytes > 0
        assert s["weights_dtype"] == "int8"

    def test_aggregate_sums_weight_bytes(self, params, rng):
        from quintnet_tpu.serve.metrics import aggregate

        engines = [_engine(params, d) for d in ("f32", "int8")]
        for eng in engines:
            _serve(eng, _prompts(rng, (4,)), 3)
        agg = aggregate([e.metrics for e in engines])
        assert agg["weight_bytes"] == sum(e.weight_bytes
                                          for e in engines)
        assert agg["weights_dtype"] == "f32,int8"

    def test_prom_exposition_weight_bytes(self, params, rng):
        """weight_bytes rides the strict-parser GET /metrics gate as
        quintnet_engine_weight_bytes (the string-valued weights_dtype
        is correctly NOT a series)."""
        from quintnet_tpu.obs.prom import (parse_exposition,
                                           render_exposition, sample)

        eng = _engine(params, "int8")
        _serve(eng, _prompts(rng, (4,)), 3)
        s = eng.metrics.summary()
        text = render_exposition({}, {"r0": s})
        parsed = parse_exposition(text)
        assert sample(parsed, "quintnet_engine_weight_bytes",
                      replica="r0") == s["weight_bytes"] > 0
        assert "weights_dtype" not in text

"""Llama family: HF-golden logits, strategy parity, sp/rope composition.

The model is the round-4 "another model family" extension (the reference
zoo is ViT + GPT-2 only). The strongest oracle available offline is a
randomly-initialised transformers LlamaForCausalLM with the SAME
weights: logits must match to float tolerance.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from quintnet_tpu.models.llama import (LlamaConfig, llama_apply,
                                       llama_from_hf_state, llama_init,
                                       llama_model_spec)

# fast subset: the HF golden + remat goldens; the strategy matrix and
# shape checks run in the full suite (keeps `-m fast` under 5 min)
CFG = LlamaConfig.tiny()


def _ids(b=2, s=16, seed=0, v=None):
    return np.random.default_rng(seed).integers(
        0, v or CFG.vocab_size, (b, s), dtype=np.int32)


@pytest.mark.fast
def test_logits_match_hf_llama():
    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")

    hf_cfg = transformers.LlamaConfig(
        vocab_size=CFG.vocab_size, hidden_size=CFG.dim,
        intermediate_size=CFG.intermediate_size,
        num_hidden_layers=CFG.n_layers, num_attention_heads=CFG.n_heads,
        num_key_value_heads=CFG.n_kv_heads,
        max_position_embeddings=CFG.n_positions,
        rope_theta=CFG.rope_theta, rms_norm_eps=CFG.rms_eps,
        tie_word_embeddings=CFG.tie_embeddings,
        attention_bias=False, mlp_bias=False)
    torch.manual_seed(0)
    hf = transformers.LlamaForCausalLM(hf_cfg).eval()

    params = llama_from_hf_state(hf.state_dict(), CFG)
    ids = _ids()
    with torch.no_grad():
        ref = hf(torch.from_numpy(ids).long()).logits.numpy()
    got = np.asarray(llama_apply(params, jnp.asarray(ids), CFG))
    np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-4)


@pytest.mark.fast
def test_remat_and_flashpath_match_plain():
    params = llama_init(jax.random.key(0), CFG)
    ids = jnp.asarray(_ids())
    base = llama_apply(params, ids, CFG)
    np.testing.assert_allclose(
        llama_apply(params, ids, CFG, remat="dots"), base,
        rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize(
    "name,mesh_dim,mesh_name",
    [("dp", [4], ["dp"]),
     ("tp", [2], ["tp"]),
     ("dp_tp", [2, 2], ["dp", "tp"]),
     ("sp", [2], ["sp"]),
     ("pp", [2], ["pp"])])
def test_strategy_loss_matches_single_device(name, mesh_dim, mesh_name):
    import optax

    from quintnet_tpu.core.config import Config
    from quintnet_tpu.models.gpt2 import clm_loss
    from quintnet_tpu.parallel.strategy import get_strategy

    cfg = Config.from_dict({
        "mesh_dim": mesh_dim, "mesh_name": mesh_name,
        "training": {"batch_size": 4, "grad_clip_norm": None,
                     "gradient_accumulation_steps": 2
                     if name == "pp" else 1,
                     "schedule": "1f1b"},
    })
    model = llama_model_spec(CFG)
    host = llama_init(jax.random.key(0), CFG)
    ids = _ids(b=4, s=16)

    ref = clm_loss(llama_apply(host, jnp.asarray(ids), CFG),
                   jnp.asarray(ids))

    strat = get_strategy(name, cfg)
    opt = optax.sgd(0.05)
    p = strat.shard_params(model, jax.tree.map(jnp.array, host))
    s = strat.init_opt_state(model, opt, p)
    b = strat.shard_batch((jnp.asarray(ids), jnp.asarray(ids)), model)
    _, _, loss = strat.make_train_step(model, opt)(p, s, b)
    np.testing.assert_allclose(float(loss), float(ref), rtol=1e-5)


def test_gqa_repeat_matches_mha_when_kv_equals_heads():
    """n_kv == n_heads must behave exactly as plain MHA (repeat_kv is
    the identity)."""
    mha = LlamaConfig.tiny(n_kv_heads=4)
    params = llama_init(jax.random.key(0), mha)
    ids = jnp.asarray(_ids())
    out = llama_apply(params, ids, mha)
    assert out.shape == (2, 16, mha.vocab_size)
    assert np.isfinite(np.asarray(out)).all()


def test_tied_embeddings_variant():
    tied = LlamaConfig.tiny(tie_embeddings=True)
    params = llama_init(jax.random.key(0), tied)
    assert "lm" not in params["head"]
    out = llama_apply(params, jnp.asarray(_ids(v=tied.vocab_size)), tied)
    assert out.shape == (2, 16, tied.vocab_size)

"""Llama family: HF-golden logits, strategy parity, sp/rope composition.

The model is the round-4 "another model family" extension (the reference
zoo is ViT + GPT-2 only). The strongest oracle available offline is a
randomly-initialised transformers LlamaForCausalLM with the SAME
weights: logits must match to float tolerance.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from quintnet_tpu.models.llama import (LlamaConfig, llama_apply,
                                       llama_from_hf_state, llama_init,
                                       llama_model_spec)

# fast subset: the HF golden + remat goldens; the strategy matrix and
# shape checks run in the full suite (keeps `-m fast` under 5 min)
CFG = LlamaConfig.tiny()


def _ids(b=2, s=16, seed=0, v=None):
    return np.random.default_rng(seed).integers(
        0, v or CFG.vocab_size, (b, s), dtype=np.int32)


@pytest.mark.fast
def test_logits_match_hf_llama():
    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")

    hf_cfg = transformers.LlamaConfig(
        vocab_size=CFG.vocab_size, hidden_size=CFG.dim,
        intermediate_size=CFG.intermediate_size,
        num_hidden_layers=CFG.n_layers, num_attention_heads=CFG.n_heads,
        num_key_value_heads=CFG.n_kv_heads,
        max_position_embeddings=CFG.n_positions,
        rope_theta=CFG.rope_theta, rms_norm_eps=CFG.rms_eps,
        tie_word_embeddings=CFG.tie_embeddings,
        attention_bias=False, mlp_bias=False)
    torch.manual_seed(0)
    hf = transformers.LlamaForCausalLM(hf_cfg).eval()

    params = llama_from_hf_state(hf.state_dict(), CFG)
    ids = _ids()
    with torch.no_grad():
        ref = hf(torch.from_numpy(ids).long()).logits.numpy()
    got = np.asarray(llama_apply(params, jnp.asarray(ids), CFG))
    np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-4)


def test_remat_and_flashpath_match_plain():
    params = llama_init(jax.random.key(0), CFG)
    ids = jnp.asarray(_ids())
    base = llama_apply(params, ids, CFG)
    np.testing.assert_allclose(
        llama_apply(params, ids, CFG, remat="dots"), base,
        rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize(
    "name,mesh_dim,mesh_name",
    [("dp", [4], ["dp"]),
     ("tp", [2], ["tp"]),
     ("dp_tp", [2, 2], ["dp", "tp"]),
     ("sp", [2], ["sp"]),
     ("pp", [2], ["pp"]),
     ("3d", [2, 2, 2], ["dp", "tp", "pp"])])
def test_strategy_loss_matches_single_device(name, mesh_dim, mesh_name):
    import optax

    from quintnet_tpu.core.config import Config
    from quintnet_tpu.models.gpt2 import clm_loss
    from quintnet_tpu.parallel.strategy import get_strategy

    cfg = Config.from_dict({
        "mesh_dim": mesh_dim, "mesh_name": mesh_name,
        "training": {"batch_size": 4, "grad_clip_norm": None,
                     "gradient_accumulation_steps": 2
                     if name == "pp" else 1,
                     "schedule": "1f1b"},
    })
    model = llama_model_spec(CFG)
    host = llama_init(jax.random.key(0), CFG)
    ids = _ids(b=4, s=16)

    ref = clm_loss(llama_apply(host, jnp.asarray(ids), CFG),
                   jnp.asarray(ids))

    strat = get_strategy(name, cfg)
    opt = optax.sgd(0.05)
    p = strat.shard_params(model, jax.tree.map(jnp.array, host))
    s = strat.init_opt_state(model, opt, p)
    b = strat.shard_batch((jnp.asarray(ids), jnp.asarray(ids)), model)
    _, _, loss = strat.make_train_step(model, opt)(p, s, b)
    np.testing.assert_allclose(float(loss), float(ref), rtol=1e-5)


def test_gqa_equals_mha_with_repeated_kv_weights():
    """A GQA model must equal an MHA model whose k/v projection columns
    are the GQA columns repeated per group — pins repeat_kv's head
    ORDER (group-contiguous, HF convention), not just shapes."""
    import dataclasses

    gqa = CFG  # n_heads=4, n_kv_heads=2
    params = llama_init(jax.random.key(0), gqa)
    rep = gqa.n_heads // gqa.n_kv_heads
    hd = gqa.head_dim

    def widen(w):  # [L, D, n_kv*hd] -> [L, D, n_heads*hd], group order
        L, D, _ = w.shape
        w = w.reshape(L, D, gqa.n_kv_heads, hd)
        w = jnp.repeat(w, rep, axis=2)
        return w.reshape(L, D, gqa.n_heads * hd)

    mha_params = jax.tree.map(lambda x: x, params)
    mha_params["blocks"] = dict(params["blocks"])
    attn = dict(params["blocks"]["attn"])
    attn["k"] = {"w": widen(attn["k"]["w"])}
    attn["v"] = {"w": widen(attn["v"]["w"])}
    mha_params["blocks"]["attn"] = attn

    mha_cfg = dataclasses.replace(gqa, n_kv_heads=gqa.n_heads)
    ids = jnp.asarray(_ids())
    np.testing.assert_allclose(
        np.asarray(llama_apply(params, ids, gqa)),
        np.asarray(llama_apply(mha_params, ids, mha_cfg)),
        rtol=1e-5, atol=1e-5)


def test_rope_scaling_matches_hf():
    """llama3 rope scaling (the thing real 3.1/3.2 checkpoints ship
    with) — logits vs HF with rope_scaling enabled."""
    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")

    from quintnet_tpu.models.llama import LlamaConfig as LC

    hf_cfg = transformers.LlamaConfig(
        vocab_size=CFG.vocab_size, hidden_size=CFG.dim,
        intermediate_size=CFG.intermediate_size,
        num_hidden_layers=CFG.n_layers, num_attention_heads=CFG.n_heads,
        num_key_value_heads=CFG.n_kv_heads,
        max_position_embeddings=64,
        rope_theta=10000.0, rms_norm_eps=CFG.rms_eps,
        tie_word_embeddings=False,
        attention_bias=False, mlp_bias=False,
        rope_scaling={"rope_type": "llama3", "factor": 8.0,
                      "low_freq_factor": 1.0, "high_freq_factor": 4.0,
                      "original_max_position_embeddings": 32})
    torch.manual_seed(1)
    hf = transformers.LlamaForCausalLM(hf_cfg).eval()
    cfg = LC.from_hf_config(hf_cfg)
    assert cfg.rope_scaling == (8.0, 1.0, 4.0, 32)

    params = llama_from_hf_state(hf.state_dict(), cfg)
    ids = _ids(s=48)  # past original_max/2 so scaled lanes matter
    with torch.no_grad():
        ref = hf(torch.from_numpy(ids).long()).logits.numpy()
    got = np.asarray(llama_apply(params, jnp.asarray(ids), cfg))
    np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-4)


def test_tied_embeddings_variant():
    tied = LlamaConfig.tiny(tie_embeddings=True)
    params = llama_init(jax.random.key(0), tied)
    assert "lm" not in params["head"]
    out = llama_apply(params, jnp.asarray(_ids(v=tied.vocab_size)), tied)
    assert out.shape == (2, 16, tied.vocab_size)


def test_llama_generate_matches_full_forward_greedy():
    """KV-cache decode == argmax over a full forward recompute per step
    (the reference-style O(T^2) oracle), token for token."""
    from quintnet_tpu.models.llama_generate import llama_generate

    params = llama_init(jax.random.key(0), CFG)
    ids = _ids(b=2, s=5, seed=3)
    new = 6

    # oracle: full forward each step
    cur = np.asarray(ids)
    for _ in range(new):
        logits = llama_apply(params, jnp.asarray(cur), CFG)
        nxt = np.asarray(jnp.argmax(logits[:, -1], axis=-1),
                         np.int32)[:, None]
        cur = np.concatenate([cur, nxt], axis=1)

    fast = llama_generate(params, ids, CFG, max_new_tokens=new)
    np.testing.assert_array_equal(fast, cur)


def test_llama_generate_eos_and_sampling():
    from quintnet_tpu.models.llama_generate import llama_generate

    params = llama_init(jax.random.key(0), CFG)
    ids = _ids(b=2, s=4, seed=4)
    out = llama_generate(params, ids, CFG, max_new_tokens=5,
                         eos_token_id=3, temperature=0.8, top_p=0.9,
                         key=jax.random.key(1))
    assert out.shape == (2, 9)
    for row in out[:, 4:]:
        hits = np.where(row == 3)[0]
        if hits.size:
            assert (row[hits[0]:] == 3).all()


def test_llama_tp_generate_matches_single_device():
    """tp=2 decode on the training layout == single-device decode,
    token for token (greedy)."""
    from quintnet_tpu.core.mesh import mesh_from_sizes
    from quintnet_tpu.models.llama_generate import (llama_generate,
                                                    llama_generate_tp)
    from quintnet_tpu.parallel.train_step import shard_pytree
    from quintnet_tpu.models.llama import llama_partition_specs

    params = llama_init(jax.random.key(0), CFG)
    ids = _ids(b=2, s=5, seed=7)
    ref = llama_generate(params, ids, CFG, max_new_tokens=5)

    mesh = mesh_from_sizes(tp=2)
    specs = llama_partition_specs(CFG, tp_axis="tp")
    sharded = shard_pytree(mesh, params, specs)
    out = llama_generate_tp(sharded, ids, CFG, mesh=mesh,
                            max_new_tokens=5)
    np.testing.assert_array_equal(out, ref)


def test_llama_moe_one_expert_matches_dense_swiglu():
    """A 1-expert top-1 SwiGLU MoE with capacity >= tokens is exactly a
    dense SwiGLU (gate prob 1 after normalisation) — pins the swiglu
    expert math in nn/moe.py."""
    from quintnet_tpu.nn.layers import swiglu_apply
    from quintnet_tpu.nn.moe import MoEArgs, moe_apply, moe_init

    key = jax.random.key(0)
    p = moe_init(key, 16, 32, 1, expert_type="swiglu")
    x = jax.random.normal(jax.random.key(1), (2, 8, 16))
    args = MoEArgs(n_experts=1, top_k=1, capacity=16, aux_weight=0.0)
    y, aux = moe_apply(p, x, args)
    dense = {"gate": {"w": p["wg"][0]}, "up": {"w": p["wu"][0]},
             "down": {"w": p["wd"][0]}}
    np.testing.assert_allclose(np.asarray(y),
                               np.asarray(swiglu_apply(dense, x)),
                               rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("name,mesh_dim,mesh_name",
                         [("dp_ep", [2, 2], ["dp", "ep"]),
                          ("ep", [2], ["ep"])])
def test_llama_moe_strategy_matches_single_device(name, mesh_dim,
                                                  mesh_name):
    """Mixtral-style Llama-MoE: expert-parallel loss == single device
    (same capacity per token-set; drops identical)."""
    import optax

    from quintnet_tpu.core.config import Config
    from quintnet_tpu.parallel.strategy import get_strategy

    # same convention as the gpt2 moe goldens (tests/test_moe.py TINY):
    # huge capacity so no drops, aux weight 0 (the f*P load statistic is
    # nonlinear, so per-rank aux legitimately differs from global aux)
    mcfg = LlamaConfig.tiny(n_experts=4, expert_top_k=2,
                            expert_capacity=4096, aux_loss_weight=0.0)
    model = llama_model_spec(mcfg)
    host = llama_init(jax.random.key(0), mcfg)
    ids = _ids(b=4, s=16, v=mcfg.vocab_size)

    # single-device reference THROUGH the same loss_fn (incl. aux)
    cfg1 = Config.from_dict({
        "mesh_dim": [1], "mesh_name": ["dp"],
        "training": {"batch_size": 4, "grad_clip_norm": None}})
    s1 = get_strategy("single", cfg1)
    p1 = s1.shard_params(model, jax.tree.map(jnp.array, host))
    st1 = s1.init_opt_state(model, optax.sgd(0.05), p1)
    b1 = s1.shard_batch((jnp.asarray(ids), jnp.asarray(ids)), model)
    _, _, ref = s1.make_train_step(model, optax.sgd(0.05))(p1, st1, b1)

    cfg = Config.from_dict({
        "mesh_dim": mesh_dim, "mesh_name": mesh_name,
        "training": {"batch_size": 4, "grad_clip_norm": None}})
    strat = get_strategy(name, cfg)
    p = strat.shard_params(model, jax.tree.map(jnp.array, host))
    st = strat.init_opt_state(model, optax.sgd(0.05), p)
    b = strat.shard_batch((jnp.asarray(ids), jnp.asarray(ids)), model)
    _, _, loss = strat.make_train_step(model, optax.sgd(0.05))(p, st, b)
    np.testing.assert_allclose(float(loss), float(ref), rtol=2e-4)


def test_llama_generation_eval_harness():
    """The ROUGE/BLEU harness scores a Llama model via generate_fn."""
    from quintnet_tpu.data import ByteTokenizer
    from quintnet_tpu.models.llama_generate import llama_generate
    from quintnet_tpu.train.metrics import evaluate_generation

    params = llama_init(jax.random.key(0), CFG)
    tok = ByteTokenizer()
    prompts = [([1, 2, 3, 4], "ref one"), ([5, 6, 7, 8], "ref two")]
    scores = evaluate_generation(params, CFG, prompts, tok,
                                 max_new_tokens=4, batch_size=2,
                                 generate_fn=llama_generate)
    assert set(scores) == {"rouge1", "rouge2", "rougeL", "bleu"}


def test_tied_embeddings_under_pp():
    """Tied lm head (= tok embedding) under pipeline parallelism: the
    embedding grad (stage 0) and lm-head grad (last stage) are partial
    across pp and must combine via the partial-axes psum — same
    mechanism as GPT-2's tied wte (no manual sync)."""
    import optax

    from quintnet_tpu.core.config import Config
    from quintnet_tpu.models.gpt2 import clm_loss
    from quintnet_tpu.parallel.strategy import get_strategy

    tied = LlamaConfig.tiny(tie_embeddings=True)
    model = llama_model_spec(tied)
    host = llama_init(jax.random.key(0), tied)
    ids = _ids(b=4, s=16, v=tied.vocab_size)

    def ref_loss(p):
        return clm_loss(llama_apply(p, jnp.asarray(ids), tied),
                        jnp.asarray(ids))

    loss_ref, g_ref = jax.value_and_grad(ref_loss)(host)
    p_ref = optax.apply_updates(
        host, optax.sgd(0.05).update(
            g_ref, optax.sgd(0.05).init(host), host)[0])

    cfg = Config.from_dict({
        "mesh_dim": [2], "mesh_name": ["pp"],
        "training": {"batch_size": 4, "grad_clip_norm": None,
                     "gradient_accumulation_steps": 2,
                     "schedule": "1f1b"},
    })
    strat = get_strategy("pp", cfg)
    opt = optax.sgd(0.05)
    p = strat.shard_params(model, jax.tree.map(jnp.array, host))
    s = strat.init_opt_state(model, opt, p)
    b = strat.shard_batch((jnp.asarray(ids), jnp.asarray(ids)), model)
    p2, _, loss = strat.make_train_step(model, opt)(p, s, b)
    np.testing.assert_allclose(float(loss), float(loss_ref), rtol=1e-5)
    # the tied table's update must include BOTH grad contributions
    np.testing.assert_allclose(
        np.asarray(p2["embedding"]["tok"]),
        np.asarray(p_ref["embedding"]["tok"]), rtol=2e-4, atol=1e-5)


def test_llama_moe_pp_matches_single_device():
    """Llama-MoE under pipeline parallelism: the per-stage aux
    accumulation in the shared pp schedules must carry the SwiGLU-MoE
    aux exactly as it does GPT-2's (per-microbatch aux objective —
    compare against the microbatched single-device loss)."""
    import optax

    from quintnet_tpu.core.config import Config
    from quintnet_tpu.parallel.strategy import get_strategy

    mcfg = LlamaConfig.tiny(n_experts=4, expert_top_k=2,
                            expert_capacity=4096, aux_loss_weight=0.0)
    model = llama_model_spec(mcfg)
    host = llama_init(jax.random.key(0), mcfg)
    ids = _ids(b=4, s=16, v=mcfg.vocab_size)

    n_micro = 2
    parts = [model.loss_fn(host, (jnp.asarray(ids[i * 2:(i + 1) * 2]),
                                  jnp.asarray(ids[i * 2:(i + 1) * 2])))
             for i in range(n_micro)]
    ref = jnp.mean(jnp.stack(parts))

    cfg = Config.from_dict({
        "mesh_dim": [2], "mesh_name": ["pp"],
        "training": {"batch_size": 4, "grad_clip_norm": None,
                     "gradient_accumulation_steps": n_micro,
                     "schedule": "1f1b"},
    })
    strat = get_strategy("pp", cfg)
    p = strat.shard_params(model, jax.tree.map(jnp.array, host))
    s = strat.init_opt_state(model, optax.sgd(0.05), p)
    b = strat.shard_batch((jnp.asarray(ids), jnp.asarray(ids)), model)
    _, _, loss = strat.make_train_step(model, optax.sgd(0.05))(p, s, b)
    np.testing.assert_allclose(float(loss), float(ref), rtol=1e-5)


def test_llama_hf_export_roundtrip():
    """export -> HF load_state_dict -> logits must match ours (the
    inverse of the import golden)."""
    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")

    from quintnet_tpu.models.llama import llama_to_hf_state

    params = llama_init(jax.random.key(2), CFG)
    hf_cfg = transformers.LlamaConfig(
        vocab_size=CFG.vocab_size, hidden_size=CFG.dim,
        intermediate_size=CFG.intermediate_size,
        num_hidden_layers=CFG.n_layers, num_attention_heads=CFG.n_heads,
        num_key_value_heads=CFG.n_kv_heads,
        max_position_embeddings=CFG.n_positions,
        rope_theta=CFG.rope_theta, rms_norm_eps=CFG.rms_eps,
        tie_word_embeddings=CFG.tie_embeddings,
        attention_bias=False, mlp_bias=False)
    hf = transformers.LlamaForCausalLM(hf_cfg).eval()
    state = {k: torch.from_numpy(np.ascontiguousarray(v))
             for k, v in llama_to_hf_state(params, CFG).items()}
    missing, unexpected = hf.load_state_dict(state, strict=False)
    assert not unexpected, unexpected
    assert all("rotary" in m or "bias" not in m for m in missing), missing

    ids = _ids(b=2, s=12, seed=9)
    with torch.no_grad():
        ref = hf(torch.from_numpy(ids).long()).logits.numpy()
    got = np.asarray(llama_apply(params, jnp.asarray(ids), CFG))
    np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-4)


def test_llama_upcycle_to_moe_near_identity():
    """Upcycled SwiGLU-MoE starts function-close to the dense model
    (copied experts, near-uniform router; normalize_gates makes top-k
    of identical experts exact up to gate normalisation)."""
    from quintnet_tpu.models.llama import llama_upcycle_to_moe

    dense = LlamaConfig.tiny()
    moe = LlamaConfig.tiny(n_experts=4, expert_top_k=2,
                           expert_capacity=4096)
    params = llama_init(jax.random.key(0), dense)
    up = llama_upcycle_to_moe(params, moe, key=jax.random.key(3))
    assert set(up["blocks"]["moe"]) == {"router", "wg", "wu", "wd"}

    ids = jnp.asarray(_ids(b=2, s=16, v=dense.vocab_size))
    base = llama_apply(params, ids, dense)
    upc = llama_apply(up, ids, moe)
    # identical experts -> combine of normalised gates == dense output
    np.testing.assert_allclose(np.asarray(upc), np.asarray(base),
                               rtol=2e-3, atol=2e-3)


def test_llama_beam1_equals_greedy_and_beam_scores():
    from quintnet_tpu.models.llama_generate import (llama_beam_search,
                                                    llama_generate)

    params = llama_init(jax.random.key(0), CFG)
    ids = _ids(b=2, s=5, seed=12)
    greedy = llama_generate(params, ids, CFG, max_new_tokens=5)
    beam1 = llama_beam_search(params, ids, CFG, beams=1, max_new_tokens=5)
    np.testing.assert_array_equal(greedy, beam1)

    beam4 = llama_beam_search(params, ids, CFG, beams=4, max_new_tokens=5)

    def seq_lp(full):
        logits = llama_apply(params, jnp.asarray(full), CFG)
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        tgt = full[:, 1:]
        tok = np.take_along_axis(np.asarray(logp[:, :-1]),
                                 tgt[:, :, None], axis=2)[:, :, 0]
        return tok[:, 4:].sum(axis=1)

    assert (seq_lp(beam4) >= seq_lp(greedy) - 1e-4).all()


@pytest.mark.parametrize("sp_mode", ["zigzag", "ulysses"])
def test_llama_sp_modes_match_single_device(sp_mode):
    """Ring is covered in the strategy matrix; pin zigzag and ulysses
    too (rope with global positions must compose with both)."""
    import optax

    from quintnet_tpu.core.config import Config
    from quintnet_tpu.models.gpt2 import clm_loss
    from quintnet_tpu.parallel.strategy import get_strategy

    cfg_m = LlamaConfig.tiny()
    model = llama_model_spec(cfg_m, sp_mode=sp_mode)
    host = llama_init(jax.random.key(0), cfg_m)
    ids = _ids(b=4, s=16)

    ref = clm_loss(llama_apply(host, jnp.asarray(ids), cfg_m),
                   jnp.asarray(ids))

    cfg = Config.from_dict({
        "mesh_dim": [2], "mesh_name": ["sp"],
        "training": {"batch_size": 4, "grad_clip_norm": None,
                     "sp_mode": sp_mode},
    })
    strat = get_strategy("sp", cfg)
    opt = optax.sgd(0.05)
    p = strat.shard_params(model, jax.tree.map(jnp.array, host))
    s = strat.init_opt_state(model, opt, p)
    b = strat.shard_batch((jnp.asarray(ids), jnp.asarray(ids)), model)
    _, _, loss = strat.make_train_step(model, opt)(p, s, b)
    np.testing.assert_allclose(float(loss), float(ref), rtol=1e-5)


def test_eval_ppl_llama_hf_checkpoint(tmp_path):
    """tools/eval_ppl --family llama --checkpoint <hf dir>: loads via
    transformers + llama_from_hf_state and reports a finite ppl
    (closes the round-4 guarded hole)."""
    import subprocess
    import sys

    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")

    hf_cfg = transformers.LlamaConfig(
        vocab_size=512, hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=4,
        num_key_value_heads=2, max_position_embeddings=128,
        rope_theta=10000.0, rms_norm_eps=1e-5,
        tie_word_embeddings=False, attention_bias=False, mlp_bias=False)
    torch.manual_seed(0)
    hf = transformers.LlamaForCausalLM(hf_cfg).eval()
    hf_dir = tmp_path / "hf"
    hf.save_pretrained(hf_dir)
    text = tmp_path / "t.txt"
    text.write_text("byte level text for perplexity " * 20)

    import os

    env = dict(os.environ, PYTHONPATH=os.getcwd())
    res = subprocess.run(
        [sys.executable, "-m", "quintnet_tpu.tools.eval_ppl",
         "--text", str(text), "--family", "llama",
         "--checkpoint", str(hf_dir), "--seq", "64", "--batch", "4"],
        capture_output=True, text=True, timeout=420, env=env)
    assert res.returncode == 0, res.stdout + res.stderr
    assert "perplexity" in res.stdout
    ppl = float(res.stdout.strip().split()[-1])
    assert np.isfinite(ppl) and ppl > 0

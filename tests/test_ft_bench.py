"""tools/ft_run.py must never rot unexecuted: the fast suite runs the
supervisor end-to-end (CPU, tiny run, one injected kill + relaunch) and
checks the JSON goodput contract, and the bench.py staleness scanner
must surface the committed ft artifact the same way it surfaces the
serving and training records.
"""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.join(os.path.dirname(__file__), "..")
sys.path.insert(0, REPO)
import bench  # noqa: E402

FT_METRIC = "ft_goodput"


@pytest.mark.fast
def test_ft_run_smoke_survives_injected_kill(tmp_path):
    """One SIGTERM kill mid-run: the supervisor relaunches, the child
    resumes from the emergency snapshot, the run completes, and the
    one-line JSON record carries the acceptance fields."""
    out_file = str(tmp_path / "ft.json")
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "ft_run.py"),
         "--run-dir", str(tmp_path / "run"),
         "--epochs", "2", "--samples", "32", "--batch-size", "16",
         "--save-every", "1", "--kill-at", "3", "--kill-mode", "sigterm",
         "--out", out_file],
        capture_output=True, text=True, timeout=300,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert out.returncode == 0, out.stdout[-2000:] + out.stderr[-2000:]
    rec = json.loads(out.stdout.strip().splitlines()[-1])
    assert rec["metric"] == FT_METRIC
    assert rec["rc"] == 0
    assert rec["unit"] == "fraction"
    ex = rec["extras"]
    assert ex["completed"] is True
    assert ex["restarts"] == 1
    assert ex["faults_survived"] == 1
    # 2 epochs x 2 steps: the graceful kill at step 3 checkpoints step 3,
    # so the relaunch replays only step 4 — no useful work lost
    assert ex["useful_steps"] == 4
    assert ex["lost_steps"] == 0
    assert ex["attempts"] == 2
    assert 0 < rec["value"] <= 1
    # --out appends to an artifacts-style JSON list
    assert json.load(open(out_file)) == [rec]


@pytest.mark.fast
def test_committed_ft_artifact_surfaces_in_staleness_scan():
    """artifacts/ft_r07.json is discoverable through the same
    last_known_result scanner the perf benches use, so the goodput
    evidence survives a dead backend like every other metric."""
    last = bench.last_known_result(metric=FT_METRIC)
    assert last is not None
    assert last["stale"] is True
    assert last["metric"] == FT_METRIC
    assert 0 < last["value"] <= 1
    assert last["source"].startswith("artifacts")
    assert last["as_of"]


@pytest.mark.fast
def test_committed_ft_artifact_proves_acceptance_scenario():
    """The committed record documents the end-to-end acceptance run:
    >= 2 injected kills survived and the run still completed."""
    recs = json.load(open(os.path.join(REPO, "artifacts", "ft_r07.json")))
    rec = [r for r in recs if r.get("metric") == FT_METRIC][-1]
    ex = rec["extras"]
    assert ex["faults_injected"] >= 2
    assert ex["faults_survived"] >= 2
    assert ex["restarts"] >= 2
    assert ex["completed"] is True
    assert rec["rc"] == 0

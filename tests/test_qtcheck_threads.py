"""qtcheck-threads goldens: the static lock-discipline auditor
(analysis/threads.py), its committed baseline gate, and the
instrumented-lock runtime (analysis/lockrt.py) it is twinned with.

Four layers, mirroring tests/test_qtcheck.py's structure for the lint
pass:

- **synthetic rules** — QT201 (lock-order cycles, lexical and
  interprocedural), QT202 (guarded-by inference on thread-reachable
  paths), QT203 (spawn census, BOTH directions), and the
  ``# qtcheck: ok[RULE]`` pragma contract, all over in-memory sources;
- **repo gate** — the committed tools/qtcheck_threads_baseline.json
  matches the live tree EXACTLY (new and stale both fail), every entry
  carries a justifying note, the real lock-order graph is cycle-free,
  and a seeded inverted acquisition IS caught (then reverted);
- **runtime** — LockOrderError on the second edge direction naming
  both stacks, ledgers under an injected clock, the held-too-long
  watchdog, Condition protocol, and an 8-thread AdmissionQueue stress
  behind one InstrumentedLock (the queue's real locking contract: the
  fleet serialises, the queue owns only policy);
- **fleet** — lock_audit=True is INERT: the kill-migration golden
  stays token-identical to the oracle (which the lock_audit=False
  golden in test_fleet.py already pins), zero order violations under
  real chaos, and the quintnet_lock_* families pass the strict
  exposition parser. The process-fleet SIGKILL twin is slow-tier.
"""

import ast
import json
import os
import textwrap
import threading
import time

import numpy as np
import pytest

from quintnet_tpu.analysis.lint import (SourceFile, collect_sources,
                                        compare_baseline, load_baseline,
                                        violations_to_baseline)
from quintnet_tpu.analysis.lockrt import (InstrumentedLock, LockAudit,
                                          LockOrderError)
from quintnet_tpu.analysis.threads import (THREAD_PATHS, audit_parsed,
                                           audit_paths, audit_sources,
                                           load_thread_specs,
                                           thread_spawn_census)
from quintnet_tpu.fleet import AdmissionQueue, Overloaded

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BASELINE = os.path.join(REPO, "tools", "qtcheck_threads_baseline.json")
LINT_BASELINE = os.path.join(REPO, "tools", "qtcheck_baseline.json")


def _src(text):
    return textwrap.dedent(text).strip() + "\n"


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


# ---------------------------------------------------------------------
# QT201: lock-order cycles
# ---------------------------------------------------------------------

_CYCLE = _src("""
    import threading

    class S:
        def __init__(self):
            self._a = threading.Lock()
            self._b = threading.Lock()

        def fwd(self):
            with self._a:
                with self._b:
                    pass

        def rev(self):
            with self._b:
                with self._a:
                    pass
""")


class TestQT201:
    def test_inverted_acquisition_names_both_chains(self):
        vs = audit_sources([("pkg/mod.py", _CYCLE)], rules=["QT201"])
        assert len(vs) == 1
        v = vs[0]
        assert v.rule == "QT201"
        # the finding names BOTH locks and BOTH directions' call chains
        assert "pkg/mod.py:S._a" in v.symbol
        assert "pkg/mod.py:S._b" in v.symbol
        assert " <-> " in v.symbol
        assert v.message.startswith("lock-order cycle (")
        assert "S.fwd" in v.message and "S.rev" in v.message
        assert "->" in v.message

    def test_consistent_order_is_clean(self):
        one_way = _CYCLE.replace("with self._b:\n            with "
                                 "self._a:\n                pass",
                                 "pass")
        vs = audit_sources([("pkg/mod.py", one_way)], rules=["QT201"])
        assert vs == []

    def test_pragma_suppresses_the_edge(self):
        # suppressing the b->a edge at its acquisition site breaks the
        # cycle: pragma honored exactly like the lint rules
        pragmad = _CYCLE.replace(
            "with self._b:\n            with self._a:",
            "with self._b:\n            with self._a:"
            "  # qtcheck: ok[QT201]")
        assert pragmad != _CYCLE
        vs = audit_sources([("pkg/mod.py", pragmad)], rules=["QT201"])
        assert vs == []

    def test_interprocedural_cycle_via_resolved_call(self):
        """Holding B while CALLING a method that acquires A is a B->A
        edge — the bounded call-graph half of the pass."""
        src = _src("""
            import threading

            class S:
                def __init__(self):
                    self._a = threading.Lock()
                    self._b = threading.Lock()

                def fwd(self):
                    with self._a:
                        with self._b:
                            pass

                def rev(self):
                    with self._b:
                        self._grab()

                def _grab(self):
                    with self._a:
                        pass
            """)
        vs = audit_sources([("pkg/mod.py", src)], rules=["QT201"])
        assert len(vs) == 1
        assert "_grab" in vs[0].message    # the chain is readable


# ---------------------------------------------------------------------
# QT202: guarded-by inference
# ---------------------------------------------------------------------

_GUARDED = _src("""
    import threading

    class C:
        def __init__(self):
            self._lock = threading.Lock()
            self._n = 0

        def start(self):
            t = threading.Thread(target=self._loop, daemon=True)
            t.start()

        def bump(self):
            with self._lock:
                self._n += 1

        def _loop(self):
            return self._n
""")


class TestQT202:
    def test_unguarded_read_on_thread_path_flagged(self):
        vs = audit_sources([("pkg/mod.py", _GUARDED)], rules=["QT202"])
        assert len(vs) == 1
        v = vs[0]
        assert v.symbol == "C._loop"
        assert "load of self._n" in v.message
        assert "pkg/mod.py:C._lock" in v.message
        assert "thread-reachable" in v.message

    def test_guarded_read_is_clean(self):
        fixed = _GUARDED.replace(
            "def _loop(self):\n        return self._n",
            "def _loop(self):\n        with self._lock:\n"
            "            return self._n")
        assert fixed != _GUARDED
        vs = audit_sources([("pkg/mod.py", fixed)], rules=["QT202"])
        assert vs == []

    def test_init_is_exempt_both_sides(self):
        # __init__'s unguarded write of _n classifies nothing and
        # triggers nothing: construction happens-before every thread
        vs = audit_sources([("pkg/mod.py", _GUARDED.replace(
            "def _loop(self):\n        return self._n",
            "def _loop(self):\n        pass"))], rules=["QT202"])
        assert vs == []

    def test_pragma_suppresses(self):
        pragmad = _GUARDED.replace(
            "return self._n",
            "return self._n  # qtcheck: ok[QT202]")
        vs = audit_sources([("pkg/mod.py", pragmad)], rules=["QT202"])
        assert vs == []

    def test_ambient_held_makes_locked_convention_clean(self):
        """The repo's ``*_locked`` convention: a method ONLY ever
        called with the lock held inherits it as ambient — no
        annotation needed, no false positive."""
        src = _src("""
            import threading

            class C:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._n = 0

                def start(self):
                    t = threading.Thread(target=self._loop, daemon=True)
                    t.start()

                def _loop(self):
                    with self._lock:
                        self._n += 1
                        self._flush_locked()

                def _flush_locked(self):
                    return self._n
            """)
        vs = audit_sources([("pkg/mod.py", src)], rules=["QT202"])
        assert vs == []


# ---------------------------------------------------------------------
# QT203: thread-spawn census, both directions
# ---------------------------------------------------------------------

_SPAWNER = _src("""
    import threading

    class W:
        def start(self):
            self._t = threading.Thread(target=self._run, daemon=True)
            self._t.start()

        def stop(self):
            self._t.join()

        def _run(self):
            pass
""")

_SPAWN_SPEC = {"pkg/w.py": [{"symbol": "W.start", "target": "self._run",
                             "daemon": True, "joined": True}]}


class TestQT203:
    def test_census_matches_spec_clean(self):
        vs = audit_sources([("pkg/w.py", _SPAWNER)], rules=["QT203"],
                           specs=_SPAWN_SPEC)
        assert vs == []

    def test_unexpected_spawn_fails(self):
        vs = audit_sources([("pkg/w.py", _SPAWNER)], rules=["QT203"],
                           specs={})
        assert len(vs) == 1
        assert vs[0].symbol == "W.start[self._run]"
        assert "unexpected Thread spawn" in vs[0].message
        assert "THREAD_SPAWN_SPECS" in vs[0].message

    def test_stale_spec_entry_fails(self):
        specs = {"pkg/w.py": _SPAWN_SPEC["pkg/w.py"] + [
            {"symbol": "W.start", "target": "self._gone",
             "daemon": True, "joined": True}]}
        vs = audit_sources([("pkg/w.py", _SPAWNER)], rules=["QT203"],
                           specs=specs)
        assert len(vs) == 1
        assert vs[0].symbol == "W.start[self._gone]"
        assert "no longer has it" in vs[0].message

    def test_daemon_flag_mismatch_fails(self):
        specs = {"pkg/w.py": [dict(_SPAWN_SPEC["pkg/w.py"][0],
                                   daemon=False)]}
        vs = audit_sources([("pkg/w.py", _SPAWNER)], rules=["QT203"],
                           specs=specs)
        assert len(vs) == 1
        assert "daemon: spec False, tree True" in vs[0].message

    def test_census_shape(self):
        parsed = [SourceFile("pkg/w.py", _SPAWNER,
                             ast.parse(_SPAWNER))]
        census = thread_spawn_census(parsed)
        assert census == [{"module": "pkg/w.py", "symbol": "W.start",
                           "line": census[0]["line"],
                           "target": "self._run", "daemon": True,
                           "joined": True, "kind": "Thread"}]


# ---------------------------------------------------------------------
# repo gate: committed baseline == live tree, exactly
# ---------------------------------------------------------------------

class TestRepoGate:
    def test_threads_baseline_gate(self):
        """The no-drift contract, both directions: a NEW violation
        (fix it or pragma it with a note) and a STALE entry (you fixed
        one — regenerate with --write-baseline) both fail tier-1."""
        violations = audit_paths(root=REPO)
        new, stale = compare_baseline(violations,
                                      load_baseline(BASELINE))
        assert not new, f"new concurrency violations: {new}"
        assert not stale, f"stale baseline entries: {stale}"

    def test_baseline_entries_all_carry_notes(self):
        """Every grandfathered finding must say WHY it is benign — a
        baseline without justifications is just a mute button."""
        baseline = load_baseline(BASELINE)
        missing = [e for e in baseline["violations"]
                   if not e.get("note")]
        assert not missing, missing

    def test_lock_order_graph_is_cycle_free(self):
        """The acceptance bar for pool actuation: ZERO QT201 findings
        on the real tree — no baseline rides for deadlocks."""
        assert audit_paths(root=REPO, rules=["QT201"]) == []

    def test_spawn_census_matches_spec(self):
        """QT203 clean against the committed THREAD_SPAWN_SPECS — and
        the spec is non-trivial (the fleet really does spawn)."""
        assert audit_paths(root=REPO, rules=["QT203"]) == []
        specs = load_thread_specs()
        assert sum(len(v) for v in specs.values()) >= 8

    def test_seeded_inversion_is_caught_then_reverted(self):
        """Seed an inverted acquisition into the live parse set: the
        gate MUST catch it (this is the whole point of the pass), and
        the unseeded set must stay clean."""
        parsed = list(collect_sources(list(THREAD_PATHS), root=REPO))
        src = _CYCLE
        seed = SourceFile("quintnet_tpu/fleet/_seeded_demo.py", src,
                          ast.parse(src))
        vs = audit_parsed(parsed + [seed], rules=["QT201"])
        assert any(v.rule == "QT201"
                   and "_seeded_demo" in v.symbol for v in vs)
        # reverted: the real tree alone is cycle-free
        assert audit_parsed(parsed, rules=["QT201"]) == []


# ---------------------------------------------------------------------
# CLI: --select / --json / both-direction failures / timed smoke
# ---------------------------------------------------------------------

class TestCLI:
    def test_select_qt2_with_baseline_clean(self):
        from quintnet_tpu.tools.qtcheck import main

        rc = main(["--select", "QT2", "--threads-baseline", BASELINE,
                   "--root", REPO])
        assert rc == 0

    def test_select_single_rule_without_baseline(self, capsys):
        """--select arms the concurrency pass even with no baseline;
        QT203 alone is clean on the real tree, so rc 0."""
        from quintnet_tpu.tools.qtcheck import main

        rc = main(["--select", "QT203", "--root", REPO])
        out = capsys.readouterr().out
        assert rc == 0
        assert "0 violation(s)" in out

    def test_json_gate_output(self, capsys):
        from quintnet_tpu.tools.qtcheck import main

        rc = main(["--select", "QT2", "--threads-baseline", BASELINE,
                   "--root", REPO, "--json"])
        payload = json.loads(capsys.readouterr().out)
        assert rc == 0
        assert payload["new"] == [] and payload["stale"] == []
        assert payload["total"] >= 1   # the baselined benign findings

    def test_json_listing_output(self, capsys):
        from quintnet_tpu.tools.qtcheck import main

        rc = main(["--select", "QT203", "--root", REPO, "--json"])
        payload = json.loads(capsys.readouterr().out)
        assert rc == 0 and payload == []

    def test_new_violation_fails_gate(self, tmp_path, capsys):
        """Direction 1: tree has findings an (empty) baseline lacks."""
        from quintnet_tpu.tools.qtcheck import main

        p = tmp_path / "empty.json"
        p.write_text(json.dumps(violations_to_baseline([])))
        rc = main(["--select", "QT2", "--threads-baseline", str(p),
                   "--root", REPO])
        out = capsys.readouterr().out
        assert rc == 1 and "NEW" in out

    def test_stale_entry_fails_gate(self, tmp_path, capsys):
        """Direction 2: baseline carries an entry the tree no longer
        produces."""
        from quintnet_tpu.tools.qtcheck import main

        base = load_baseline(BASELINE)
        base["violations"] = base["violations"] + [
            {"rule": "QT202", "path": "quintnet_tpu/fleet/fleet.py",
             "symbol": "ServeFleet.fixed_long_ago", "count": 1}]
        p = tmp_path / "stale.json"
        p.write_text(json.dumps(base))
        rc = main(["--select", "QT2", "--threads-baseline", str(p),
                   "--root", REPO])
        out = capsys.readouterr().out
        assert rc == 1 and "STALE" in out

    def test_full_tree_both_passes_timed_smoke(self):
        """Both passes over the whole tree share ONE parse
        (qtcheck.py hoists collect_sources): the combined run is
        bounded — this is the perf regression tripwire for the CLI."""
        from quintnet_tpu.tools.qtcheck import main

        t0 = time.monotonic()
        rc = main(["--baseline", LINT_BASELINE,
                   "--threads-baseline", BASELINE, "--root", REPO])
        elapsed = time.monotonic() - t0
        assert rc == 0
        assert elapsed < 60.0, f"full-tree qtcheck took {elapsed:.1f}s"


# ---------------------------------------------------------------------
# runtime: LockAudit / InstrumentedLock
# ---------------------------------------------------------------------

class TestLockRuntime:
    def test_inversion_raises_typed_with_both_stacks(self):
        audit = LockAudit()
        a, b = audit.lock("A"), audit.lock("B")
        with a:
            with b:
                pass
        seen = []
        audit.on_violation = seen.append
        with b:
            with pytest.raises(LockOrderError) as ei:
                a.acquire()
        err = ei.value
        assert err.first == "A" and err.second == "B"
        assert err.thread == threading.current_thread().name
        assert err.forward_stack and err.reverse_stack
        # the message is the readable deadlock report: both directions
        assert "earlier A -> B" in str(err)
        assert "current B -> A" in str(err)
        # raised BEFORE blocking: B is still cleanly held/releasable,
        # and the callback saw the same info the exception carries
        assert seen and seen[0]["first"] == "A"
        assert seen[0]["second"] == "B"
        assert seen[0]["forward_stack"] == err.forward_stack
        s = audit.summary()
        assert s["order_violations"] == 1
        assert s["order_edges"] == 1       # only A->B was recorded

    def test_consistent_order_records_edges_silently(self):
        audit = LockAudit()
        a, b = audit.lock("A"), audit.lock("B")
        for _ in range(3):
            with a:
                with b:
                    pass
        s = audit.summary()
        assert s["order_edges"] == 1 and s["order_violations"] == 0
        assert s["locks"]["A"]["acquisitions"] == 3

    def test_self_deadlock_on_non_reentrant(self):
        audit = LockAudit()
        a = audit.lock("A")
        with a:
            with pytest.raises(LockOrderError) as ei:
                a.acquire()
        assert "self-deadlock" in ei.value.forward_stack

    def test_rlock_reacquire_is_fine(self):
        audit = LockAudit()
        r = audit.rlock("R")
        with r:
            with r:
                pass
        assert audit.summary()["locks"]["R"]["acquisitions"] == 2
        assert audit.summary()["order_violations"] == 0

    def test_mint_same_name_returns_same_lock(self):
        """Replica restarts and re-armed subsystems re-mint by name:
        same name + same kind is the SAME node (one story per name);
        a kind mismatch is a hard error."""
        audit = LockAudit()
        assert audit.lock("X") is audit.lock("X")
        with pytest.raises(ValueError, match="already minted"):
            audit.rlock("X")

    def test_ledgers_with_injected_clock(self):
        clk = FakeClock()
        audit = LockAudit(clock=clk, hold_budget_s=1.0)
        a = audit.lock("A")
        a.acquire()
        clk.advance(2.5)
        a.release()
        led = audit.summary()["locks"]["A"]
        assert led["hold_s"] == pytest.approx(2.5)
        assert led["max_hold_s"] == pytest.approx(2.5)
        assert led["held_too_long"] == 1   # 2.5s > 1.0s budget

    def test_check_held_watchdog_deterministic(self):
        clk = FakeClock()
        audit = LockAudit(clock=clk, hold_budget_s=1.0)
        a = audit.lock("A")
        a.acquire()
        clk.advance(5.0)
        offenders = audit.check_held()
        assert len(offenders) == 1
        assert offenders[0]["lock"] == "A"
        assert offenders[0]["held_s"] == pytest.approx(5.0)
        assert offenders[0]["holder"] == threading.current_thread().name
        a.release()
        assert audit.check_held() == []

    def test_watchdog_thread_counts_long_holds(self):
        audit = LockAudit(hold_budget_s=0.005,
                          watchdog_interval_s=0.005)
        a = audit.lock("A")
        a.acquire()
        deadline = time.monotonic() + 5.0
        while (audit.summary()["locks"]["A"]["held_too_long"] == 0
               and time.monotonic() < deadline):
            time.sleep(0.005)
        a.release()
        audit.close()
        assert audit.summary()["locks"]["A"]["held_too_long"] >= 1

    def test_contended_acquire_counted(self):
        audit = LockAudit()
        a = audit.lock("A")
        a.acquire()
        started = threading.Event()

        def worker():
            started.set()
            with a:
                pass

        t = threading.Thread(target=worker)
        t.start()
        started.wait(5.0)
        time.sleep(0.05)       # let the worker hit the blocking path
        a.release()
        t.join(5.0)
        assert not t.is_alive()
        assert audit.summary()["locks"]["A"]["contended"] >= 1

    def test_condition_wait_releases_audit_entry(self):
        """Condition over an instrumented RLock: wait() fully releases
        (a sleeping waiter holds NOTHING in the audit's model) and the
        notify/wake handshake works — if _release_save didn't release
        the inner lock, the producer below would deadlock."""
        audit = LockAudit()
        cond = audit.condition("C")
        state = {"flag": False, "done": False}

        def consumer():
            with cond:
                while not state["flag"]:
                    cond.wait(timeout=5.0)
                state["done"] = True

        t = threading.Thread(target=consumer)
        t.start()
        time.sleep(0.05)
        with cond:
            state["flag"] = True
            cond.notify()
        t.join(5.0)
        assert not t.is_alive() and state["done"]
        lk = cond._lock
        assert isinstance(lk, InstrumentedLock)
        assert lk.holder is None           # nothing residually held
        assert audit.summary()["order_violations"] == 0


# ---------------------------------------------------------------------
# satellite 3: AdmissionQueue under an 8-thread barrier stress
# ---------------------------------------------------------------------

class _QItem:
    __slots__ = ("ident", "deadline", "submit_time", "adapter_id")

    def __init__(self, ident, now):
        self.ident = ident
        self.deadline = None
        self.submit_time = now
        self.adapter_id = None


class TestAdmissionStress:
    def test_eight_thread_barrier_stress_under_one_lock(self):
        """The queue's REAL concurrency contract, stressed: it is not
        internally locked — the fleet serialises all access under its
        condition lock. Eight threads (pushers, a migration re-queuer,
        a targeted remover, a popper, a pressure observer) hammer it
        behind ONE InstrumentedLock. Afterwards: no item lost, none
        duplicated, shed items never entered, the audit saw zero order
        violations, and the ledger accounts every acquisition."""
        audit = LockAudit()
        lock = audit.lock("fleet._cv")
        q = AdmissionQueue(max_pending=64)
        barrier = threading.Barrier(8)
        errors = []
        pushed_ok, shed = [], []
        popped, removed = [], []
        push_lists = [[f"p{w}-{i}" for i in range(150)]
                      for w in range(3)]

        def run(fn):
            def wrapped():
                try:
                    barrier.wait(timeout=30.0)
                    fn()
                except Exception as e:      # pragma: no cover
                    errors.append(e)
            return wrapped

        def pusher(idents):
            def go():
                for ident in idents:
                    with lock:
                        it = _QItem(ident, time.monotonic())
                        try:
                            q.push(it)
                            pushed_ok.append(ident)
                        except Overloaded as e:
                            assert e.reason == "queue_full"
                            shed.append(ident)
            return go

        def requeuer():
            # migration path: pop + push_front is ONE atomic re-queue
            # under the fleet lock; net queue membership is unchanged
            for _ in range(300):
                with lock:
                    it = q.pop()
                    if it is not None:
                        q.push_front([it])

        def remover():
            for _ in range(300):
                with lock:
                    items = q.items()
                    if items:
                        it = items[len(items) // 2]
                        q.remove(it)
                        removed.append(it.ident)

        def popper():
            for _ in range(400):
                with lock:
                    it = q.pop()
                    if it is not None:
                        popped.append(it.ident)

        def observer():
            for _ in range(400):
                with lock:
                    depth = len(q)
                    wait_s = q.oldest_wait_s()
                    q.peek_adapter_id()
                    assert depth >= 0 and wait_s >= 0.0

        threads = [threading.Thread(target=run(fn)) for fn in
                   [pusher(push_lists[0]), pusher(push_lists[1]),
                    pusher(push_lists[2]), requeuer, remover, popper,
                    observer,
                    lambda: None]]          # 8th: pure barrier party
        for t in threads:
            t.start()
        for t in threads:
            t.join(60.0)
            assert not t.is_alive()
        assert errors == []

        with lock:
            remaining = [i.ident for i in q.drain_all()]
            assert len(q) == 0

        # conservation: every accepted item is in EXACTLY one place
        consumed = sorted(popped + removed + remaining)
        assert consumed == sorted(pushed_ok)
        assert len(set(consumed)) == len(consumed)   # no duplication
        # shed items never entered the queue
        assert not set(shed) & set(pushed_ok)
        assert len(pushed_ok) + len(shed) == 450
        # the instrumented fleet lock observed a clean discipline
        s = audit.summary()
        assert s["order_violations"] == 0
        assert s["locks"]["fleet._cv"]["acquisitions"] >= 450


# ---------------------------------------------------------------------
# fleet: lock_audit=True is inert (token-identical) and observable
# ---------------------------------------------------------------------

jax = pytest.importorskip("jax")

from quintnet_tpu.fleet import ServeFleet                    # noqa: E402
from quintnet_tpu.ft import ChaosMonkey                      # noqa: E402
from quintnet_tpu.models.gpt2 import GPT2Config, gpt2_init   # noqa: E402
from quintnet_tpu.models.gpt2_generate import gpt2_generate  # noqa: E402
from quintnet_tpu.obs.prom import (parse_exposition,         # noqa: E402
                                   render_exposition, sample)
from quintnet_tpu.serve import ServeEngine, gpt2_family      # noqa: E402

CFG = GPT2Config.tiny(n_layer=2)
TEMP, TOPK = 0.8, 5


@pytest.fixture(scope="module")
def params():
    return gpt2_init(jax.random.key(0), CFG)


@pytest.fixture
def factory(params):
    def make():
        return ServeEngine(gpt2_family(CFG), params, max_slots=2,
                           block_size=4, num_blocks=24, max_seq_len=24,
                           temperature=TEMP, top_k=TOPK)

    return make


def _oracle(params, prompt, max_new, key):
    return np.asarray(gpt2_generate(
        params, prompt[None], CFG, max_new_tokens=max_new,
        temperature=TEMP, top_k=TOPK, key=key)[0])


def _prompts(rng, lengths):
    return [np.asarray(rng.integers(0, CFG.vocab_size, (t,)), np.int32)
            for t in lengths]


class TestFleetLockAudit:
    def test_kill_migration_golden_with_lock_audit(self, factory,
                                                   params, rng):
        """THE inertness proof: the kill-migration golden from
        test_fleet.py rerun with lock_audit=True (+obs). Every request
        is token-identical to the undisturbed oracle — the same oracle
        the lock_audit=False golden pins — so the audited path changes
        no observable byte. And under real chaos (a death, a
        migration, a restart) the instrumented locks saw ZERO order
        violations: the discipline the static pass proves on resolvable
        paths holds dynamically too."""
        prompts = _prompts(rng, (5, 7, 3, 6, 4, 8, 5, 6, 4))
        keys = [jax.random.key(500 + i) for i in range(9)]
        monkey = ChaosMonkey(kill_at_step=3, mode="raise", target="r1")
        fleet = ServeFleet(factory, n_replicas=3, policy="round_robin",
                           chaos=monkey, obs=True, lock_audit=True)
        try:
            fids = [fleet.submit(p, 8, key=k)
                    for p, k in zip(prompts, keys)]
            outs = [fleet.result(f, timeout=300) for f in fids]
            for p, k, o in zip(prompts, keys, outs):
                np.testing.assert_array_equal(
                    o, _oracle(params, p, 8, k))

            m = fleet.metrics
            assert m.replica_deaths == 1 and m.restarts == 1
            assert m.migrations >= 1
            assert m.finished == 9 and m.shed == 0

            s = fleet.lock_audit.summary()
            assert s["order_violations"] == 0
            assert s["locks"]["fleet._cv"]["acquisitions"] > 0
            assert "obs.events" in s["locks"]
            # zero violations -> zero lock_order_violation events
            assert fleet.events.snapshot(
                kind="lock_order_violation") == []
            # the black box carries the ledgers at death
            assert fleet.last_crash is not None
            assert fleet.last_crash["locks"]["order_violations"] == 0
            assert "fleet._cv" in fleet.last_crash["locks"]["locks"]

            # quintnet_lock_* families pass the STRICT parser
            text = render_exposition(fleet.metrics.summary(),
                                     locks=fleet.lock_audit.summary())
            parsed = parse_exposition(text)
            assert sample(parsed,
                          "quintnet_lock_order_violations_total") == 0.0
            assert sample(parsed, "quintnet_lock_order_edges") >= 0.0
            assert sample(parsed, "quintnet_lock_acquisitions_total",
                          lock="fleet._cv") > 0.0
            assert sample(parsed, "quintnet_lock_contended_total",
                          lock="fleet._cv") >= 0.0
            assert sample(parsed, "quintnet_lock_hold_seconds_total",
                          lock="fleet._cv") >= 0.0
        finally:
            fleet.drain(timeout=120)

    def test_violation_wiring_emits_event(self, factory):
        """The on_violation callback the fleet installs turns an
        inversion into a typed lock_order_violation event (the same
        record the crash dump's events section would carry)."""
        fleet = ServeFleet(factory, n_replicas=1, obs=True,
                           lock_audit=True)
        try:
            fleet.lock_audit.on_violation(
                {"first": "A", "second": "B", "thread": "t-demo",
                 "forward_stack": "fwd", "reverse_stack": "rev"})
            evs = fleet.events.snapshot(kind="lock_order_violation")
            assert len(evs) == 1
            assert evs[0]["first"] == "A" and evs[0]["second"] == "B"
            assert evs[0]["thread"] == "t-demo"
        finally:
            fleet.drain(timeout=60)

    def test_off_path_constructs_stock_primitives(self, factory):
        """lock_audit=False (the default): no LockAudit exists and the
        fleet's condition is the stock threading.Condition — the
        off-path really is what it always was."""
        fleet = ServeFleet(factory, n_replicas=1)
        try:
            assert fleet.lock_audit is None
            assert not isinstance(
                getattr(fleet._cv, "_lock", None), InstrumentedLock)
        finally:
            fleet.drain(timeout=60)


# ---------------------------------------------------------------------
# slow tier: the process-fleet SIGKILL golden, audited
# ---------------------------------------------------------------------

@pytest.mark.slow
def test_sigkill_golden_with_lock_audit(params, rng):
    """The cross-process twin: os.kill(SIGKILL) on p1-of-3 mid-stream
    with the parent's locks instrumented. Token identity to the
    undisturbed oracle (pinned for the unaudited path by
    test_fleet_proc.py) plus zero observed order violations across
    death, journal-replay migration and supervised restart."""
    import signal as _signal

    from quintnet_tpu.fleet import Backoff, ProcessFleet

    FACTORY_FILE = os.path.join(os.path.dirname(__file__),
                                "_proc_factories.py")
    spec = {"file": FACTORY_FILE, "func": "build_tiny_gpt2",
            "kwargs": {"temperature": TEMP, "top_k": TOPK,
                       "max_seq_len": 40}}
    fleet = ProcessFleet(spec, n_replicas=3, policy="round_robin",
                         platform="cpu", heartbeat_s=0.05,
                         backoff=Backoff(base_s=0.01, cap_s=0.1),
                         obs=True, lock_audit=True)
    try:
        big = [np.asarray(rng.integers(0, CFG.vocab_size, (t,)),
                          np.int32) for t in (5, 7, 3, 6, 4, 8, 5, 6, 4)]
        keys = [jax.random.key(500 + i) for i in range(9)]
        streamed = []
        fids = []
        for i, (p, k) in enumerate(zip(big, keys)):
            cb = ((lambda fid, tok, last: streamed.append(tok))
                  if i == 1 else None)     # round_robin: i=1 -> p1
            fids.append(fleet.submit(p, 24, key=k, on_token=cb))
        victim = fleet.replica("p1")
        t0 = time.monotonic()
        while len(streamed) < 3:
            if time.monotonic() - t0 > 120:
                raise AssertionError("victim never started streaming")
            time.sleep(0.01)
        os.kill(victim.pid, _signal.SIGKILL)

        outs = [fleet.result(f, timeout=300) for f in fids]
        for p, k, o in zip(big, keys, outs):
            np.testing.assert_array_equal(
                o, np.asarray(gpt2_generate(
                    params, p[None], CFG, max_new_tokens=24,
                    temperature=TEMP, top_k=TOPK, key=k)[0]))

        assert fleet.metrics.replica_deaths == 1
        assert fleet.metrics.migrations >= 1
        assert fleet.metrics.finished == 9 and fleet.metrics.shed == 0

        s = fleet.lock_audit.summary()
        assert s["order_violations"] == 0
        assert s["locks"]["fleet._cv"]["acquisitions"] > 0
        # the victim's per-replica locks joined the same graph
        assert any(name.startswith("proc.p1.") for name in s["locks"])
        assert fleet.events.snapshot(kind="lock_order_violation") == []
        assert fleet.last_crash["locks"]["order_violations"] == 0
    finally:
        fleet.drain(timeout=180)

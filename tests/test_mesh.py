"""Mesh construction + coordinate tests (parity with reference
tests/test_mesh.py:35-141, which asserts 2x2 group membership and 2x2x2
coordinate lookup)."""

import jax
import numpy as np
import pytest

from quintnet_tpu.core.config import MeshConfig
from quintnet_tpu.core.mesh import (
    MeshSpec,
    build_mesh,
    local_axis_index,
    mesh_from_sizes,
)


def test_mesh_spec_sizes():
    spec = MeshSpec.create(dp=2, tp=2, pp=2)
    assert spec.world_size == 8
    assert spec.names == ("dp", "tp", "pp")
    assert spec.size("tp") == 2
    assert spec.size("sp") == 1  # absent axis -> 1


def test_build_mesh_2x2x2():
    mesh = mesh_from_sizes(dp=2, tp=2, pp=2)
    assert mesh.devices.shape == (2, 2, 2)
    assert mesh.axis_names == ("dp", "tp", "pp")


def test_build_mesh_insufficient_devices():
    with pytest.raises(ValueError):
        mesh_from_sizes(dp=4, tp=4)  # 16 > 8


def test_coordinates_cover_grid():
    mesh = mesh_from_sizes(dp=2, tp=2, pp=2)
    seen = set()
    for dev in mesh.devices.flat:
        c = tuple(local_axis_index(mesh, ax, dev) for ax in ("dp", "tp", "pp"))
        seen.add(c)
    assert len(seen) == 8


def test_mesh_from_reference_yaml_schema():
    # the reference's shipped config uses ['dp','tp','pp'] order
    # (examples/config.yaml:21-23)
    cfg = MeshConfig(mesh_dim=[2, 2, 2], mesh_name=["dp", "tp", "pp"])
    mesh = build_mesh(MeshSpec.from_config(cfg))
    assert mesh.axis_names == ("dp", "tp", "pp")
    assert cfg.size("tp") == 2
    assert cfg.world_size == 8


def test_mesh_config_validation():
    with pytest.raises(ValueError):
        MeshConfig(mesh_dim=[2, 2], mesh_name=["dp"])
    with pytest.raises(ValueError):
        MeshConfig(mesh_dim=[2], mesh_name=["bogus"])
    with pytest.raises(ValueError):
        MeshConfig(mesh_dim=[2, 2], mesh_name=["dp", "dp"])


def test_axis_index_inside_shard_map():
    """axis_index inside shard_map matches host-side coordinates."""
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    mesh = mesh_from_sizes(dp=2, tp=2, pp=2)

    def f():
        return (
            jax.lax.axis_index("dp") * 4
            + jax.lax.axis_index("tp") * 2
            + jax.lax.axis_index("pp")
        )[None]

    out = jax.shard_map(f, mesh=mesh, in_specs=(), out_specs=P(("dp", "tp", "pp")))()
    assert sorted(np.asarray(out).tolist()) == list(range(8))

"""bench.py must never leave a round's official record number-free:
when the TPU backend is down, the diagnostic JSON embeds the most
recent committed measurement, clearly labelled stale (VERDICT r4 #8).

These tests exercise the artifact-scanning logic directly (no backend
needed) — the repo's own committed artifacts are the fixture.
"""

import json
import os
import sys

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
import bench  # noqa: E402


@pytest.mark.fast
def test_last_known_from_committed_artifacts():
    """The committed round-4 sweep contains a real headline number; the
    scanner must surface it with provenance."""
    last = bench.last_known_result()
    assert last is not None
    assert last["stale"] is True
    assert last["metric"] == bench.HEADLINE_METRIC
    assert last["value"] > 0
    assert last["source"].startswith("artifacts")
    assert last["as_of"]  # commit date or mtime, never empty


@pytest.mark.fast
def test_last_known_prefers_default_config_record(tmp_path):
    """Among same-age records, the one measured under the committed
    baseline config (extras.baseline set) wins, not the fastest."""
    recs = [
        {"metric": bench.HEADLINE_METRIC, "value": 250.0, "rc": 0,
         "unit": "samples/s/chip", "vs_baseline": 1.0,
         "extras": {"baseline": None, "batch_per_chip": 32}},
        {"metric": bench.HEADLINE_METRIC, "value": 188.0, "rc": 0,
         "unit": "samples/s/chip", "vs_baseline": 1.037,
         "extras": {"baseline": 181.3, "batch_per_chip": 8, "mfu": 0.36}},
    ]
    (tmp_path / "sweep.json").write_text(json.dumps(recs))
    last = bench.last_known_result(art_dir=str(tmp_path))
    assert last["value"] == 188.0
    assert last["mfu"] == 0.36


@pytest.mark.fast
def test_last_known_skips_failed_records(tmp_path):
    recs = [
        {"metric": "backend_unavailable", "value": 0.0, "rc": 0},
        {"metric": bench.HEADLINE_METRIC, "value": 100.0, "rc": 1},
    ]
    (tmp_path / "bad.json").write_text(json.dumps(recs))
    (tmp_path / "junk.json").write_text("not json{")
    assert bench.last_known_result(art_dir=str(tmp_path)) is None


@pytest.mark.fast
def test_unavailable_json_embeds_last_known():
    out = bench._unavailable_json("tunnel hang", retries=5)
    assert out["metric"] == "backend_unavailable"
    assert out["error"] == "tpu_unavailable"
    assert out["retries"] == 5
    assert out["last_known"]["stale"] is True
    assert out["last_known"]["value"] > 0
    json.dumps(out)  # stays one well-formed JSON line

"""Cross-process fleet goldens (quintnet_tpu/fleet/proc.py +
frontdoor.py).

THE contract, upgraded from thread-kill to a real ``os.kill(pid,
SIGKILL)``: a replica PROCESS killed mid-stream takes nothing with it —
every in-flight request finishes on a survivor token-identical to an
undisturbed run (greedy and sampled), reconstructed from the
dispatcher's write-ahead token journal with zero cooperation from the
corpse, streams in order with ``is_last`` exactly once, and the
supervisor restarts the dead replica behind the circuit breaker with
jittered backoff. Plus the wedge path (a stalled replica stops
heartbeating but keeps its socket open — detected and routed around
within the heartbeat budget, distinct from death) and the HTTP front
door's typed 429/503 + Retry-After backpressure mapping.

Fast tier: a 2-process spawn smoke (tiny synthetic config, CPU) so
tier-1 exercises the real spawn/handshake/socket path on every run;
the full 3-replica SIGKILL goldens are slow-tier.
"""

import http.client
import json
import os
import signal
import time

import jax
import numpy as np
import pytest

from quintnet_tpu.fleet import (HEALTHY, Backoff, FrontDoor, Overloaded,
                                ProcessFleet, ServeFleet)
from quintnet_tpu.models.gpt2 import GPT2Config, gpt2_init
from quintnet_tpu.models.gpt2_generate import gpt2_generate
from quintnet_tpu.serve import DeadlineExceeded, ServeEngine, gpt2_family

CFG = GPT2Config.tiny(n_layer=2)
TEMP, TOPK = 0.8, 5
FACTORY_FILE = os.path.join(os.path.dirname(__file__),
                            "_proc_factories.py")


def _spec(**kw):
    kwargs = {"temperature": TEMP, "top_k": TOPK, "max_seq_len": 40}
    kwargs.update(kw)
    return {"file": FACTORY_FILE, "func": "build_tiny_gpt2",
            "kwargs": kwargs}


@pytest.fixture(scope="module")
def params():
    return gpt2_init(jax.random.key(0), CFG)


def _oracle(params, prompt, max_new, key, temperature=TEMP, top_k=TOPK):
    return np.asarray(gpt2_generate(
        params, prompt[None], CFG, max_new_tokens=max_new,
        temperature=temperature, top_k=top_k, key=key)[0])


def _prompts(rng, lengths):
    return [np.asarray(rng.integers(0, CFG.vocab_size, (t,)), np.int32)
            for t in lengths]


def _wait_until(pred, *, timeout=60.0, msg=""):
    t0 = time.monotonic()
    while not pred():
        if time.monotonic() - t0 > timeout:
            raise AssertionError(f"timed out waiting for: {msg}")
        time.sleep(0.01)


# ---------------------------------------------------------------------
# fast tier: the 2-process spawn smoke
# ---------------------------------------------------------------------

def test_two_process_spawn_smoke(params, rng):
    """Spawn 2 replica processes (tiny config, CPU), serve sampled
    requests token-identical to the oracle THROUGH real sockets,
    answer HTTP at the front door, keep the per-process compile
    accounting, reject never-admissible work at the parent, and drain
    cleanly. The one fast-tier test that exercises the whole process
    path end to end."""
    fleet = ProcessFleet(_spec(), n_replicas=2, policy="least_work",
                         platform="cpu")
    try:
        # parent-side admissibility: no replica round-trip for a
        # request no engine could ever run
        with pytest.raises(ValueError, match="exceeds max_seq_len"):
            fleet.submit(np.zeros(39, np.int32), 8)
        with pytest.raises(ValueError, match="empty prompt"):
            fleet.submit(np.zeros(0, np.int32), 4)
        assert fleet.metrics.accepted == 0

        prompts = _prompts(rng, (5, 7, 3, 6))
        keys = [jax.random.key(100 + i) for i in range(4)]
        outs = fleet.generate(prompts, max_new_tokens=8, keys=keys,
                              timeout=300)
        for p, k, o in zip(prompts, keys, outs):
            np.testing.assert_array_equal(o, _oracle(params, p, 8, k))

        # per-process compile accounting over the wire: counts come
        # from the CHILD's sentinels via the stats frame
        fleet.assert_compile_count()
        stats = fleet.replica_stats()
        assert sum(s["admitted"] for s in stats.values()) == 4
        for name, s in stats.items():
            assert sum(v for k, v in s["compile"].items()
                       if k.startswith("prefill[")) >= 1, name

        h = fleet.health()
        assert all(r["state"] == HEALTHY
                   for r in h["replicas"].values())
        assert fleet.summary()["tokens_delivered"] == 32

        # the HTTP front door over the PROCESS fleet: one request
        # end to end + health
        with FrontDoor(fleet) as fd:
            conn = http.client.HTTPConnection(fd.host, fd.port,
                                              timeout=300)
            conn.request("POST", "/v1/generate", json.dumps(
                {"prompt": [int(t) for t in prompts[0]],
                 "max_new_tokens": 6, "seed": 77}), {})
            r = conn.getresponse()
            body = json.loads(r.read())
            assert r.status == 200
            np.testing.assert_array_equal(
                np.asarray(body["output"], np.int32),
                _oracle(params, prompts[0], 6, jax.random.key(77)))
            conn2 = http.client.HTTPConnection(fd.host, fd.port,
                                               timeout=30)
            conn2.request("GET", "/healthz")
            r2 = conn2.getresponse()
            assert r2.status == 200
            assert json.loads(r2.read())["status"] == "ok"
    finally:
        fleet.drain(timeout=120)
    with pytest.raises(Overloaded) as ei:
        fleet.submit(np.ones(4, np.int32), 4)
    assert ei.value.reason == "shutdown"


def test_process_replica_serves_quantized_weights(params, rng):
    """A replica built with ``weights_dtype`` rides the same spawn
    path (serve/weight_quant.py through tests/_proc_factories.py):
    ``fake_quant`` weights are bit-identical to the dense oracle
    ACROSS the socket, and each child's stats frame surfaces the
    weight-bytes accounting."""
    fleet = ProcessFleet(_spec(weights_dtype="fake_quant"),
                         n_replicas=2, policy="round_robin",
                         platform="cpu")
    try:
        prompts = _prompts(rng, (5, 4))
        keys = [jax.random.key(500 + i) for i in range(2)]
        outs = fleet.generate(prompts, max_new_tokens=6, keys=keys,
                              timeout=300)
        for p, k, o in zip(prompts, keys, outs):
            np.testing.assert_array_equal(o, _oracle(params, p, 6, k))
        engines = fleet.summary()["engines"]
        assert engines
        for name, s in engines.items():
            assert s["weights_dtype"] == "fake_quant", name
            assert s["weight_bytes"] > 0, name
    finally:
        fleet.drain(timeout=120)


def test_stalled_replica_detected_and_routed_around(params, rng):
    """The wedge path, distinct from clean death: chaos mode='stall'
    makes p1 stop heartbeating (and stepping) while its SOCKET STAYS
    OPEN — no EOF ever fires. The dispatcher must detect the silence
    within the heartbeat budget, migrate p1's in-flight work via the
    journal, finish everything token-identically on p0, SIGKILL the
    zombie, and restart it with backoff. ``stalls`` counts separately
    from ``replica_deaths``."""
    # budget generous enough that a freshly-RESTARTED child on a
    # loaded CI box (heartbeats starved while its sibling compiles)
    # cannot false-positive a second stall — the strict stalls == 1
    # below depends on only the armed wedge ever tripping it
    budget = 2.0
    fleet = ProcessFleet(_spec(), n_replicas=2, policy="round_robin",
                         platform="cpu", heartbeat_s=0.05,
                         heartbeat_budget_s=budget,
                         backoff=Backoff(base_s=0.01, cap_s=0.1))
    try:
        fleet.arm_chaos("p1", {"kill_at_step": 2, "mode": "stall"})
        prompts = _prompts(rng, (5, 7, 3, 6))
        keys = [jax.random.key(900 + i) for i in range(4)]
        fids = [fleet.submit(p, 16, key=k)
                for p, k in zip(prompts, keys)]
        outs = [fleet.result(f, timeout=300) for f in fids]
        for p, k, o in zip(prompts, keys, outs):
            np.testing.assert_array_equal(o, _oracle(params, p, 16, k))

        m = fleet.metrics
        assert m.stalls == 1
        assert m.replica_deaths == 0      # a wedge is NOT a death
        assert m.migrations >= 1          # p1's work moved over
        assert m.finished == 4 and m.shed == 0
        # detection honored the budget: the stalled replica was out of
        # the candidate set and its work COMPLETED elsewhere — if the
        # dispatcher had waited for an EOF that never comes, result()
        # above would have timed out
        _wait_until(lambda: fleet.metrics.restarts >= 1,
                    msg="breaker-gated restart of the stalled replica")
        _wait_until(lambda: fleet.replica("p1").state == HEALTHY,
                    timeout=180,
                    msg="restarted replica back to healthy")
    finally:
        fleet.drain(timeout=120)


# ---------------------------------------------------------------------
# fast tier: front-door backpressure mapping (thread fleet — the HTTP
# contract is fleet-implementation-agnostic)
# ---------------------------------------------------------------------

@pytest.fixture
def thread_fleet(params):
    def factory():
        return ServeEngine(gpt2_family(CFG), params, max_slots=2,
                           block_size=4, num_blocks=24, max_seq_len=24,
                           temperature=TEMP, top_k=TOPK)

    fleet = ServeFleet(factory, n_replicas=1, max_pending=2)
    yield fleet
    fleet.close()


def _post(fd, payload, timeout=300):
    conn = http.client.HTTPConnection(fd.host, fd.port, timeout=timeout)
    conn.request("POST", "/v1/generate", json.dumps(payload), {})
    r = conn.getresponse()
    return r.status, dict(r.getheaders()), r.read()


def test_frontdoor_overload_maps_to_typed_429_503(thread_fleet,
                                                  params, rng):
    """Overload becomes PROTOCOL, not latency: queue_full -> 429 +
    Retry-After, expired deadline at submit -> 503 + Retry-After,
    draining fleet -> 503 + Retry-After; a never-admissible request
    -> 400; the bounded queue never grows past max_pending."""
    fleet = thread_fleet
    with FrontDoor(fleet, retry_after_s=2.0) as fd:
        fleet.pause_all()           # nothing dispatches: queue fills
        body = {"prompt": [1, 2, 3], "max_new_tokens": 4}
        # fill the bounded queue out-of-band so every HTTP probe below
        # is an IMMEDIATE typed rejection (a 200-path request would
        # block on its stream until the fleet resumes)
        fleet.submit([1, 2, 3], 4)
        fleet.submit([1, 2, 3], 4)
        for _ in range(2):
            status, headers, raw = _post(fd, body, timeout=30)
            assert status == 429
            assert headers.get("Retry-After") == "2"
            payload = json.loads(raw)
            assert payload["error"] == "overloaded"
            assert payload["reason"] == "queue_full"
        assert len(fleet._queue) <= 2        # the bound held

        # expired-at-submit deadline -> 503 (typed reason rides along)
        status, headers, raw = _post(
            fd, dict(body, deadline_s=0), timeout=30)
        assert status == 503
        assert headers.get("Retry-After") == "2"
        assert json.loads(raw)["reason"] == "deadline"

        # never admissible -> 400, not a 5xx
        status, _h, raw = _post(
            fd, {"prompt": [1] * 23, "max_new_tokens": 8}, timeout=30)
        assert status == 400
        assert "max_seq_len" in json.loads(raw)["message"]

        fleet.resume_all()
        _wait_until(lambda: fleet.metrics.finished
                    >= fleet.metrics.accepted, timeout=300,
                    msg="queued work finishes after resume")

        fleet.drain(timeout=120)
        status, headers, raw = _post(fd, body, timeout=30)
        assert status == 503
        assert json.loads(raw)["reason"] == "shutdown"
        assert headers.get("Retry-After") == "2"


def test_frontdoor_stream_and_error_mapping_units(thread_fleet):
    """SSE streaming delivers every token exactly once then a done
    event; the typed-error -> status table is pinned as a unit
    (DeadlineExceeded -> 504 is hard to schedule over real HTTP
    without wall-clock flake; the mapping is what matters)."""
    fleet = thread_fleet
    fd = FrontDoor(fleet, retry_after_s=1.0)
    status, payload, headers = fd._error_response(
        Overloaded("queue_full", "full"))
    assert (status, headers["Retry-After"]) == (429, "1")
    status, payload, _ = fd._error_response(
        DeadlineExceeded("late", rid=1, generated=3))
    assert status == 504 and payload["generated"] == 3
    status, _p, headers = fd._error_response(
        Overloaded("shutdown", "bye"))
    assert status == 503 and headers["Retry-After"] == "1"
    status, _p, _h = fd._error_response(TimeoutError("slow"))
    assert status == 504
    status, _p, _h = fd._error_response(ValueError("bad"))
    assert status == 400

    with FrontDoor(fleet) as live:
        conn = http.client.HTTPConnection(live.host, live.port,
                                          timeout=300)
        conn.request("POST", "/v1/generate", json.dumps(
            {"prompt": [4, 5, 6], "max_new_tokens": 5, "seed": 9,
             "stream": True}), {})
        r = conn.getresponse()
        assert r.status == 200
        assert r.getheader("Content-Type") == "text/event-stream"
        events = [e for e in r.read().decode().split("\n\n")
                  if e.strip()]
        toks = [json.loads(e.split("data: ", 1)[1])
                for e in events if e.startswith("data: ")]
        done = [e for e in events if e.startswith("event: done")]
        assert len(done) == 1
        final = json.loads(done[0].split("data: ", 1)[1])
        assert [t["token"] for t in toks] == final["output"][3:]
        assert [t["last"] for t in toks].count(True) == 1
        assert toks[-1]["last"] is True


# ---------------------------------------------------------------------
# slow tier: THE process-kill goldens (real SIGKILL, 3 replicas)
# ---------------------------------------------------------------------

@pytest.mark.slow
@pytest.mark.parametrize("temperature,top_k", [(0.0, 0), (TEMP, TOPK)],
                         ids=["greedy", "sampled"])
def test_sigkill_one_of_three_migrates_token_identically(
        params, rng, temperature, top_k):
    """THE golden, now across real process boundaries: ``os.kill(pid,
    SIGKILL)`` on replica p1-of-3 while its requests are mid-stream.
    No export, no exit handler, no flush — the dispatcher reconstructs
    every in-flight request from its write-ahead journal (prompt +
    committed tokens + host-side key replay) and survivors finish them
    token-identical to the undisturbed oracle, greedy AND sampled.
    The streamed request delivers in order with is_last exactly once
    and nothing re-delivered; the supervisor restarts the corpse."""
    fleet = ProcessFleet(
        _spec(temperature=temperature, top_k=top_k), n_replicas=3,
        policy="round_robin", platform="cpu", heartbeat_s=0.05,
        backoff=Backoff(base_s=0.01, cap_s=0.1))
    try:
        prompts = _prompts(rng, (5, 7, 3, 6, 4, 8, 5, 6, 4))
        keys = [jax.random.key(500 + i) for i in range(9)]
        streamed = []
        fids = []
        for i, (p, k) in enumerate(zip(prompts, keys)):
            cb = ((lambda fid, tok, last:
                   streamed.append((tok, last)))
                  if i == 1 else None)  # round_robin: i=1 -> p1
            fids.append(fleet.submit(p, 24, key=k, on_token=cb))
        victim = fleet.replica("p1")
        # kill MID-STREAM, deterministically: after the p1-routed
        # request has produced some tokens but before it can finish
        _wait_until(lambda: len(streamed) >= 3, timeout=120,
                    msg="victim replica streaming")
        assert len(streamed) < 24
        os.kill(victim.pid, signal.SIGKILL)

        outs = [fleet.result(f, timeout=300) for f in fids]
        for p, k, o in zip(prompts, keys, outs):
            np.testing.assert_array_equal(
                o, _oracle(params, p, 24, k, temperature=temperature,
                           top_k=top_k))

        m = fleet.metrics
        assert m.replica_deaths == 1
        assert m.migrations >= 1          # in-flight work moved over
        assert m.finished == 9 and m.shed == 0
        # the streamed request survived the SIGKILL with an intact,
        # in-order, exactly-once token stream
        toks = [t for t, _ in streamed]
        np.testing.assert_array_equal(
            np.asarray(toks, np.int32), outs[1][len(prompts[1]):])
        assert [last for _, last in streamed].count(True) == 1
        assert streamed[-1][1] is True
        _wait_until(lambda: fleet.metrics.restarts >= 1,
                    msg="supervisor restart of the killed replica")
        # survivors kept the bounded-compile promise
        fleet.assert_compile_count()
    finally:
        fleet.drain(timeout=180)


@pytest.mark.slow
def test_repeated_kills_trip_breaker_and_backoff_spaces_restarts(
        params, rng):
    """Chaos armed with rearm: every restarted p0 dies again at its
    2nd step (mode='hard' — the process exits with no cleanup, exactly
    like the ft_run supervisor's kill story). After trip_after
    consecutive deaths the breaker opens: restarts STOP, the fleet
    keeps serving on p1, and every accepted request still finishes
    golden."""
    fleet = ProcessFleet(
        _spec(), n_replicas=2, policy="round_robin", platform="cpu",
        heartbeat_s=0.05, trip_after=2, breaker_reset_s=3600.0,
        backoff=Backoff(base_s=0.01, cap_s=0.05),
        chaos={"target": "p0", "kill_at_step": 2, "mode": "hard",
               "rearm": True})
    try:
        # sustained traffic: each request outlives kill_at_step, so
        # whenever the rearmed p0 is back up and receives work it dies
        # again — two consecutive deaths trip the breaker
        served = 0
        deadline = time.monotonic() + 240.0
        while (fleet.breaker("p0").state != "open"
               and time.monotonic() < deadline):
            p = _prompts(rng, (5,))[0]
            k = jax.random.key(700 + served)
            fid = fleet.submit(p, 8, key=k)
            np.testing.assert_array_equal(
                fleet.result(fid, timeout=300),
                _oracle(params, p, 8, k))
            served += 1
            time.sleep(0.05)
        assert fleet.breaker("p0").state == "open", \
            f"breaker never tripped after {served} requests"
        assert fleet.metrics.replica_deaths >= 2
        assert fleet.metrics.restarts >= 1   # 2nd death tripped instead
        assert fleet.metrics.finished == served
        assert fleet.replica("p1").state == HEALTHY
        # open breaker: p0 stays down, the fleet keeps serving on p1
        p = _prompts(rng, (6,))[0]
        k = jax.random.key(999)
        np.testing.assert_array_equal(
            fleet.generate([p], max_new_tokens=6, keys=[k],
                           timeout=300)[0],
            _oracle(params, p, 6, k))
    finally:
        fleet.drain(timeout=180)

"""Shared retry policy units (quintnet_tpu/fleet/retry.py).

THE contract: attempt ``n`` (1-based) waits ``min(base * 2^(n-1),
cap) * u`` with ``u`` uniform in ``[1, 1+jitter]`` — the envelope is
pinned at both edges and the delays are deterministic under a seeded
RNG; :meth:`RetryPolicy.run` retries ONLY the declared exception
types, stops on attempt count or wall-clock budget, re-raises the
LAST retryable error on exhaustion, and the legacy ``Backoff``
(fleet/health.py) is the same class wearing its old constructor."""

import random

import pytest

from quintnet_tpu.fleet import Backoff, RetryPolicy


class TestDelayEnvelope:
    def test_zero_jitter_is_exact_exponential_with_cap(self):
        p = RetryPolicy(base_s=0.1, cap_s=0.5, jitter=0.0)
        assert p.delay_s(1) == pytest.approx(0.1)
        assert p.delay_s(2) == pytest.approx(0.2)
        assert p.delay_s(3) == pytest.approx(0.4)
        assert p.delay_s(4) == pytest.approx(0.5)   # capped
        assert p.delay_s(9) == pytest.approx(0.5)   # stays capped

    @pytest.mark.parametrize("attempt", [1, 2, 3, 5, 8])
    def test_jitter_envelope_pinned_both_edges(self, attempt):
        lo = RetryPolicy(base_s=0.05, cap_s=5.0, jitter=0.25,
                         rand=lambda: 0.0)
        hi = RetryPolicy(base_s=0.05, cap_s=5.0, jitter=0.25,
                         rand=lambda: 1.0)
        raw = min(0.05 * 2 ** (attempt - 1), 5.0)
        assert lo.delay_s(attempt) == pytest.approx(raw)
        assert hi.delay_s(attempt) == pytest.approx(raw * 1.25)
        # any rand value lands inside the envelope
        mid = RetryPolicy(base_s=0.05, cap_s=5.0, jitter=0.25,
                          rand=lambda: 0.37)
        assert raw <= mid.delay_s(attempt) <= raw * 1.25

    def test_deterministic_under_seeded_rng(self):
        a = RetryPolicy(rand=random.Random(7).random)
        b = RetryPolicy(rand=random.Random(7).random)
        assert [a.delay_s(n) for n in range(1, 8)] == \
            [b.delay_s(n) for n in range(1, 8)]

    def test_invalid_params_rejected(self):
        with pytest.raises(ValueError, match="max_attempts"):
            RetryPolicy(max_attempts=0)
        with pytest.raises(ValueError, match="jitter"):
            RetryPolicy(jitter=-0.1)
        with pytest.raises(ValueError, match="base_s"):
            RetryPolicy(base_s=-1.0)


class TestRun:
    def _policy(self, **kw):
        slept = []
        kw.setdefault("max_attempts", 3)
        kw.setdefault("base_s", 0.1)
        kw.setdefault("jitter", 0.0)
        p = RetryPolicy(sleep=slept.append, **kw)
        return p, slept

    def test_succeeds_after_transient_failures(self):
        p, slept = self._policy()
        seen = []

        def fn(attempt):
            seen.append(attempt)
            if attempt < 3:
                raise OSError("transient")
            return "done"

        assert p.run(fn, retry_on=(OSError,)) == "done"
        assert seen == [1, 2, 3]
        assert slept == pytest.approx([0.1, 0.2])  # between attempts

    def test_exhaustion_reraises_last_error(self):
        p, slept = self._policy()

        def fn(attempt):
            raise OSError(f"boom {attempt}")

        with pytest.raises(OSError, match="boom 3"):
            p.run(fn, retry_on=(OSError,))
        assert len(slept) == 2   # no sleep after the final failure

    def test_non_retryable_type_propagates_immediately(self):
        p, slept = self._policy()
        calls = []

        def fn(attempt):
            calls.append(attempt)
            raise KeyError("programming error")

        with pytest.raises(KeyError):
            p.run(fn, retry_on=(OSError,))
        assert calls == [1] and slept == []

    def test_on_retry_hook_sees_attempt_and_error(self):
        p, _slept = self._policy()
        hooks = []

        def fn(attempt):
            if attempt == 1:
                raise ValueError("first")
            return attempt

        assert p.run(fn, retry_on=(ValueError,),
                     on_retry=lambda n, e: hooks.append((n, str(e)))) == 2
        assert hooks == [(1, "first")]

    def test_wall_clock_budget_stops_retrying(self):
        now = [0.0]

        def clock():
            return now[0]

        def sleep(s):
            now[0] += s

        p = RetryPolicy(base_s=1.0, jitter=0.0, max_attempts=100,
                        timeout_s=2.5, clock=clock, sleep=sleep)
        calls = []

        def fn(attempt):
            calls.append(attempt)
            now[0] += 1.0           # each attempt costs 1s
            raise OSError("slow failure")

        with pytest.raises(OSError):
            p.run(fn, retry_on=(OSError,))
        # attempt 1 (t=1) -> sleep 1 (t=2) -> attempt 2 (t=3) is past
        # the 2.5s budget -> give up; attempts are bounded by TIME
        # here, not by max_attempts=100
        assert calls == [1, 2]

    def test_bounded_tightens_the_wall_clock_budget(self):
        now = [0.0]

        def clock():
            return now[0]

        def sleep(s):
            now[0] += s

        base = RetryPolicy(base_s=1.0, jitter=0.0, max_attempts=100,
                           timeout_s=60.0, clock=clock, sleep=sleep)
        # the handoff path: a request with 2.5s of deadline left must
        # bound the transfer by ITS budget, not the policy's 60s
        p = base.bounded(2.5)
        assert p.timeout_s == 2.5
        assert p.max_attempts == base.max_attempts
        assert p.clock is clock and p.sleep is sleep
        calls = []

        def fn(attempt):
            calls.append(attempt)
            now[0] += 1.0
            raise OSError("slow failure")

        with pytest.raises(OSError):
            p.run(fn, retry_on=(OSError,))
        assert calls == [1, 2]
        # bounded() never LOOSENS an existing budget
        assert base.bounded(90.0).timeout_s == 60.0
        # and the original policy is untouched
        assert base.timeout_s == 60.0


class TestBackoffAlias:
    def test_backoff_is_a_retry_policy(self):
        b = Backoff(base_s=0.05, cap_s=5.0, jitter=0.25,
                    rand=lambda: 0.0)
        assert isinstance(b, RetryPolicy)
        assert b.delay_s(3) == pytest.approx(0.2)

    def test_backoff_keeps_its_legacy_constructor(self):
        # the restart sites construct Backoff(base_s=..., cap_s=...)
        # with no retry-loop arguments — that surface must keep working
        b = Backoff(base_s=0.02, cap_s=0.5)
        assert 0.02 <= b.delay_s(1) <= 0.025

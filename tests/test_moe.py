"""MoE + expert parallelism golden tests.

The reference has no MoE/EP at all (SURVEY.md §2.2: "EP / expert
parallel — Absent"); these tests hold the new capability to the same
golden-model standard as every other axis: expert-parallel execution
over the ``ep`` mesh axis must reproduce single-device MoE math exactly
(capacity chosen so no tokens drop), and full GPT-2-MoE training steps
must match unsharded training.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

from quintnet_tpu.core import collectives as cc
from quintnet_tpu.core.config import Config
from quintnet_tpu.core.mesh import mesh_from_sizes
from quintnet_tpu.models.gpt2 import GPT2Config, gpt2_init, gpt2_model_spec
from quintnet_tpu.nn.moe import MoEArgs, moe_apply, moe_init, moe_specs
from quintnet_tpu.parallel.strategy import get_strategy

D, H, E = 16, 32, 8


def _x(rng, b, t):
    return jnp.asarray(rng.normal(size=(b, t, D)), jnp.float32)


# ---------------------------------------------------------------------------
# layer-level goldens


def test_moe_ep_matches_single_device(rng):
    """ep=4-sharded layer == unsharded layer on the same tokens (capacity
    ample on both sides so routing drops nothing)."""
    B, T = 8, 4
    params = moe_init(jax.random.key(0), D, H, E)
    x = _x(rng, B, T)

    args_1 = MoEArgs(n_experts=E, top_k=2, capacity=B * T * 2)
    y_ref, _ = moe_apply(params, x, args_1)

    ep = 4
    args_n = MoEArgs(n_experts=E, top_k=2, capacity=(B // ep) * T * 2)
    mesh = mesh_from_sizes(ep=ep)
    f = cc.shard_map_fn(
        lambda p, xx: moe_apply(p, xx, args_n, ep_axis="ep")[0],
        mesh,
        in_specs=(moe_specs(ep_axis="ep"), P("ep")),
        out_specs=P("ep"),
    )
    y = jax.jit(f)(params, x)
    np.testing.assert_allclose(np.asarray(y), np.asarray(y_ref),
                               rtol=1e-5, atol=1e-5)


def test_moe_ep_tp_matches_single_device(rng):
    """Experts sharded over ep=2 AND column/row sharded over tp=2."""
    B, T = 8, 4
    params = moe_init(jax.random.key(0), D, H, E)
    x = _x(rng, B, T)

    args_1 = MoEArgs(n_experts=E, top_k=2, capacity=B * T * 2)
    y_ref, _ = moe_apply(params, x, args_1)

    args_n = MoEArgs(n_experts=E, top_k=2, capacity=(B // 2) * T * 2)
    mesh = mesh_from_sizes(ep=2, tp=2)
    f = cc.shard_map_fn(
        lambda p, xx: moe_apply(p, xx, args_n, ep_axis="ep",
                                tp_axis="tp")[0],
        mesh,
        in_specs=(moe_specs(ep_axis="ep", tp_axis="tp"), P("ep")),
        out_specs=P("ep"),
    )
    y = jax.jit(f)(params, x)
    np.testing.assert_allclose(np.asarray(y), np.asarray(y_ref),
                               rtol=1e-5, atol=1e-5)


def test_moe_capacity_drops_are_safe(rng):
    """Tiny capacity forces drops: output stays finite and the dropped
    tokens fall back to zero (residual path in the block keeps them)."""
    params = moe_init(jax.random.key(0), D, H, E)
    x = _x(rng, 4, 4)
    args = MoEArgs(n_experts=E, top_k=2, capacity=1)
    y, aux = moe_apply(params, x, args)
    assert np.isfinite(np.asarray(y)).all()
    assert np.isfinite(float(aux))


def test_moe_aux_loss_positive_and_differentiable(rng):
    params = moe_init(jax.random.key(0), D, H, E)
    x = _x(rng, 4, 4)
    args = MoEArgs(n_experts=E, top_k=2, aux_weight=1e-2, z_weight=1e-3)

    def aux_of(p):
        return moe_apply(p, x, args)[1]

    aux, g = jax.value_and_grad(aux_of)(params)
    assert float(aux) > 0.0
    gr = np.asarray(g["router"]["w"])
    assert np.isfinite(gr).all() and np.abs(gr).sum() > 0.0


# ---------------------------------------------------------------------------
# full-model goldens (strategy plumbing, grad reduction over ep)

TINY = GPT2Config.tiny(n_layer=2, n_experts=4, expert_top_k=2,
                       expert_capacity=4096, aux_loss_weight=0.0)


def _gpt2_batch(rng, b=8, t=16):
    ids = jnp.asarray(rng.integers(0, TINY.vocab_size, (b, t)), jnp.int32)
    return ids, ids


def _config(mesh_dim, mesh_name, schedule="afab", grad_acc=1):
    return Config.from_dict({
        "mesh_dim": list(mesh_dim),
        "mesh_name": list(mesh_name),
        "training": {
            "batch_size": 8,
            "gradient_accumulation_steps": grad_acc,
            "schedule": schedule,
            "grad_clip_norm": None,
        },
    })


def _reference_update(cfg_model, params, batch, opt, steps=2):
    model = gpt2_model_spec(cfg_model)

    losses = []
    state = opt.init(params)
    for _ in range(steps):
        loss, g = jax.value_and_grad(model.loss_fn)(params, batch)
        up, state = opt.update(g, state, params)
        params = optax.apply_updates(params, up)
        losses.append(float(loss))
    return losses, params


def _strategy_update(name, cfg, cfg_model, params, batch, opt, steps=2):
    strat = get_strategy(name, cfg)
    model = gpt2_model_spec(cfg_model)
    # copy: device_put may alias host buffers and the donating train step
    # would delete them (see Strategy.shard_params docstring)
    p = strat.shard_params(model, jax.tree.map(jnp.copy, params))
    s = strat.init_opt_state(model, opt, p)
    b = strat.shard_batch(batch, model)
    step = strat.make_train_step(model, opt)
    losses = []
    for _ in range(steps):
        p, s, loss = step(p, s, b)
        losses.append(float(loss))
    return losses, p


def _assert_trees_close(p2, p_ref, rtol=2e-4, atol=1e-5):
    flat = jax.tree_util.tree_leaves_with_path(p2)
    ref = dict(jax.tree_util.tree_leaves_with_path(p_ref))
    for path, leaf in flat:
        np.testing.assert_allclose(
            np.asarray(jax.device_get(leaf)), np.asarray(ref[path]),
            rtol=rtol, atol=atol, err_msg=str(path))


@pytest.mark.parametrize(
    "name,mesh_dim,mesh_name",
    [
        ("ep", [4], ["ep"]),
        ("dp_ep", [2, 2], ["dp", "ep"]),
        ("ep_tp", [2, 2], ["ep", "tp"]),
    ],
)
def test_gpt2_moe_strategy_matches_single_device(rng, name, mesh_dim,
                                                 mesh_name):
    cfg = _config(mesh_dim, mesh_name)
    params = gpt2_init(jax.random.key(0), TINY)
    batch = _gpt2_batch(rng)
    opt = optax.sgd(0.05)

    losses_ref, p_ref = _reference_update(TINY, params, batch, opt)
    losses, p2 = _strategy_update(name, cfg, TINY, params, batch, opt)

    np.testing.assert_allclose(losses, losses_ref, rtol=1e-5)
    from quintnet_tpu.models.gpt2 import gpt2_to_tp_layout

    _assert_trees_close(p2, gpt2_to_tp_layout(p_ref, TINY, cfg.tp_size))


def _reference_update_micro(cfg_model, params, batch, opt, n_micro):
    """Single-device step with the loss averaged over microbatches —
    the objective PP schedules optimise (aux stats are per-microbatch,
    so a full-batch reference would differ in the nonlinear f*P term)."""
    model = gpt2_model_spec(cfg_model)

    def loss_fn(p):
        x, y = batch
        m = n_micro
        parts = [
            model.loss_fn(p, (x[i * (len(x) // m):(i + 1) * (len(x) // m)],
                              y[i * (len(y) // m):(i + 1) * (len(y) // m)]))
            for i in range(m)
        ]
        return jnp.mean(jnp.stack(parts))

    loss, g = jax.value_and_grad(loss_fn)(params)
    up, _ = opt.update(g, opt.init(params), params)
    return [float(loss)], optax.apply_updates(params, up)


@pytest.mark.parametrize("schedule", ["afab", "1f1b"])
def test_gpt2_moe_pp_aux_matches_single_device(rng, schedule):
    """PP with MoE aux ENABLED: per-stage aux accumulation in both
    schedules must reproduce a single-device run with the same
    microbatching (no ep axis, so every stage sees all tokens and
    local-aux == global-aux)."""
    cfg_model = GPT2Config.tiny(n_layer=4, n_experts=4, expert_top_k=2,
                                expert_capacity=4096,
                                aux_loss_weight=1e-2)
    cfg = _config([2], ["pp"], schedule=schedule, grad_acc=2)
    params = gpt2_init(jax.random.key(0), cfg_model)
    batch = _gpt2_batch(rng)
    opt = optax.sgd(0.05)

    losses_ref, p_ref = _reference_update_micro(cfg_model, params, batch,
                                                opt, n_micro=2)
    losses, p2 = _strategy_update("pp", cfg, cfg_model, params, batch,
                                  opt, steps=1)

    np.testing.assert_allclose(losses, losses_ref, rtol=1e-5)
    _assert_trees_close(p2, p_ref)


@pytest.mark.parametrize("schedule", ["afab", "1f1b"])
def test_gpt2_moe_ep_pp_matches_single_device(rng, schedule):
    """EP x PP composition (aux off for cross-sharding exactness)."""
    cfg_model = GPT2Config.tiny(n_layer=4, n_experts=4, expert_top_k=2,
                                expert_capacity=4096,
                                aux_loss_weight=0.0)
    cfg = _config([2, 2], ["ep", "pp"], schedule=schedule, grad_acc=2)
    params = gpt2_init(jax.random.key(0), cfg_model)
    batch = _gpt2_batch(rng)
    opt = optax.sgd(0.05)

    losses_ref, p_ref = _reference_update(cfg_model, params, batch, opt,
                                          steps=1)
    losses, p2 = _strategy_update("ep_pp", cfg, cfg_model, params, batch,
                                  opt, steps=1)

    np.testing.assert_allclose(losses, losses_ref, rtol=1e-5)
    _assert_trees_close(p2, p_ref)


def test_trainer_fit_eval_moe_ep(rng):
    """Trainer.fit + evaluate on a dp x ep mesh with a MoE model —
    regression for the eval builder dropping ep_axis (experts would
    stay unsharded inside shard_map and shape-error)."""
    from quintnet_tpu.train.trainer import Trainer

    cfg = Config.from_dict({
        "mesh_dim": [2, 2], "mesh_name": ["dp", "ep"],
        "training": {"batch_size": 8, "gradient_accumulation_steps": 1,
                     "schedule": "afab", "optimizer": "adamw",
                     "learning_rate": 1e-3, "epochs": 1, "log_every": 0},
    })
    gcfg = GPT2Config.tiny(n_layer=2, n_experts=4)
    model = gpt2_model_spec(gcfg)
    strat = get_strategy("dp_ep", cfg)
    trainer = Trainer(cfg, model, strategy=strat, task_type="clm")

    ids = np.asarray(rng.integers(0, gcfg.vocab_size, (8, 16)), np.int32)
    hist = trainer.fit(lambda _e: [(ids, ids)], epochs=1,
                       val_batches_fn=lambda _e: [(ids, ids)])
    assert np.isfinite(hist.train_loss[0])
    assert np.isfinite(hist.val_loss[0])


def test_gpt2_moe_zero1_dp_ep(rng):
    """ZeRO-1 AdamW over dp composes with ep-sharded experts.

    Param comparison is against PLAIN AdamW on the same mesh: AdamW is
    elementwise, so the chunked (ZeRO) update must match the replicated
    one near-exactly. (A single-device reference only gets a loss-level
    check — Adam's g/sqrt(v) amplifies reduction-order noise on
    near-zero grads far beyond any sensible parameter tolerance.)"""
    def cfgd(optname):
        return Config.from_dict({
            "mesh_dim": [2, 2], "mesh_name": ["dp", "ep"],
            "training": {"batch_size": 8,
                         "gradient_accumulation_steps": 1,
                         "schedule": "afab", "optimizer": optname,
                         "grad_clip_norm": None},
        })

    params = gpt2_init(jax.random.key(0), TINY)
    batch = _gpt2_batch(rng)
    opt = optax.adamw(1e-3)

    losses_ref, _ = _reference_update(TINY, params, batch, opt, steps=1)
    losses_plain, p_plain = _strategy_update("dp_ep", cfgd("adamw"), TINY,
                                             params, batch, opt, steps=1)
    losses_z, p_z = _strategy_update("dp_ep", cfgd("zero1_adamw"), TINY,
                                     params, batch, opt, steps=1)
    np.testing.assert_allclose(losses_z, losses_ref, rtol=1e-5)
    np.testing.assert_allclose(losses_z, losses_plain, rtol=1e-6)
    _assert_trees_close(p_z, p_plain, rtol=1e-6, atol=1e-7)


# -- expert-choice routing ---------------------------------------------------

def test_expert_choice_one_expert_full_capacity_is_weighted_dense(rng):
    """E=1, C=S: the expert takes every token; softmax over one expert
    gives affinity 1.0, so EC == dense FFN exactly."""
    from quintnet_tpu.nn.layers import mlp_apply

    key = jax.random.key(0)
    p = moe_init(key, 16, 32, 1)
    x = jnp.asarray(rng.normal(size=(2, 8, 16)), jnp.float32)
    args = MoEArgs(n_experts=1, top_k=1, capacity=16,
                   router="expert_choice", aux_weight=0.0)
    y, aux = moe_apply(p, x, args)
    dense = {"fc": {"w": p["w1"][0], "b": p["b1"][0]},
             "proj": {"w": p["w2"][0], "b": p["b2"][0]}}
    np.testing.assert_allclose(np.asarray(y),
                               np.asarray(mlp_apply(dense, x)),
                               rtol=1e-5, atol=1e-6)
    assert float(aux) == 0.0  # EC needs no load-balance loss


def test_expert_choice_ep_matches_single_device(rng):
    """EC dispatch over an ep mesh == single-device EC (deterministic
    expert-side top-C)."""
    from quintnet_tpu.core.mesh import mesh_from_sizes
    from quintnet_tpu.core import collectives as cc
    from jax.sharding import PartitionSpec as P

    E, D, H, C = 4, 16, 32, 8
    p = moe_init(jax.random.key(1), D, H, E)
    x = jnp.asarray(rng.normal(size=(2, 16, D)), jnp.float32)
    args = MoEArgs(n_experts=E, top_k=2, capacity=C,
                   router="expert_choice", aux_weight=0.0)
    ref, _ = moe_apply(p, x, args)

    mesh = mesh_from_sizes(ep=2)
    specs = {"router": {"w": P()},
             "w1": P("ep"), "b1": P("ep"), "w2": P("ep"), "b2": P("ep")}

    def local(p, x):
        y, aux = moe_apply(p, x, args, ep_axis="ep")
        return y

    fn = jax.jit(cc.shard_map_fn(local, mesh, in_specs=(specs, P()),
                                 out_specs=P()))
    from quintnet_tpu.parallel.train_step import shard_pytree

    ps = shard_pytree(mesh, p, specs)
    np.testing.assert_allclose(np.asarray(fn(ps, x)), np.asarray(ref),
                               rtol=2e-5, atol=1e-6)


def test_expert_choice_trains(rng):
    """Gradients flow through the EC gather/scatter + gates (nn-level:
    the causal LM configs REJECT expert_choice — see below)."""
    import optax

    E, D, H = 4, 16, 32
    p = moe_init(jax.random.key(0), D, H, E)
    x = jnp.asarray(rng.normal(size=(4, 8, D)), jnp.float32)
    target = jnp.asarray(rng.normal(size=(4, 8, D)), jnp.float32)
    args = MoEArgs(n_experts=E, top_k=2, router="expert_choice",
                   aux_weight=0.0)
    opt = optax.adam(1e-2)
    state = opt.init(p)

    @jax.jit
    def step(p, state):
        def loss_fn(p):
            y, aux = moe_apply(p, x, args)
            return jnp.mean(jnp.square(y - target)) + aux

        loss, g = jax.value_and_grad(loss_fn)(p)
        up, state = opt.update(g, state, p)
        return optax.apply_updates(p, up), state, loss

    l0 = None
    for _ in range(15):
        p, state, loss = step(p, state)
        l0 = l0 if l0 is not None else float(loss)
    assert float(loss) < l0


def test_expert_choice_rejected_by_causal_configs():
    """EC selection is non-causal (runs over the whole flattened token
    set) — both causal LM configs must refuse it loudly."""
    from quintnet_tpu.models.gpt2 import GPT2Config
    from quintnet_tpu.models.llama import LlamaConfig

    for cfg in (GPT2Config.tiny(n_experts=4,
                                router_type="expert_choice"),
                LlamaConfig.tiny(n_experts=4,
                                 router_type="expert_choice")):
        with pytest.raises(ValueError, match="non-causal"):
            cfg.moe_args


@pytest.mark.parametrize("schedule", ["afab", "1f1b"])
def test_vit_moe_pp_matches_single_device(rng, schedule):
    """ViT-MoE under PIPELINE parallelism (aux enabled): per-stage aux
    accumulation through the ViT stage fns must reproduce a
    single-device run with the same microbatching — the family x axis
    combination that was a guarded hole before round 5."""
    from quintnet_tpu.models.vit import ViTConfig, vit_init, vit_model_spec

    vcfg = ViTConfig(image_size=14, patch_size=7, in_channels=1,
                     hidden_dim=16, depth=4, num_heads=2, num_classes=10,
                     n_experts=4, expert_top_k=2, expert_capacity=4096,
                     aux_loss_weight=1e-2)
    model = vit_model_spec(vcfg)
    params = vit_init(jax.random.key(0), vcfg)
    x = jnp.asarray(rng.normal(size=(8, 14, 14, 1)), jnp.float32)
    y = jnp.asarray(rng.integers(0, 10, (8,)), jnp.int32)
    opt = optax.sgd(0.05)

    # single-device reference with the SAME microbatching (aux stats are
    # per-microbatch; the f*P term is nonlinear in the batch split)
    def loss_ref(p):
        parts = [model.loss_fn(p, (x[i * 4:(i + 1) * 4],
                                   y[i * 4:(i + 1) * 4]))
                 for i in range(2)]
        return jnp.mean(jnp.stack(parts))

    ref_loss, g = jax.value_and_grad(loss_ref)(params)
    up, _ = opt.update(g, opt.init(params), params)
    p_ref = optax.apply_updates(params, up)

    cfg = _config([2], ["pp"], schedule=schedule, grad_acc=2)
    strat = get_strategy("pp", cfg)
    p = strat.shard_params(model, jax.tree.map(jnp.copy, params))
    s = strat.init_opt_state(model, opt, p)
    b = strat.shard_batch((x, y), model)
    step = strat.make_train_step(model, opt)
    p, s, loss = step(p, s, b)

    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
    _assert_trees_close(p, p_ref)


def test_vit_moe_expert_choice_trains_and_shards(rng):
    """ViT-MoE with EXPERT-CHOICE routing (legal: non-causal encoder) —
    dp x ep strategy loss == single device, and training reduces it."""
    import optax

    from quintnet_tpu.core.config import Config
    from quintnet_tpu.models.vit import (ViTConfig, vit_init,
                                         vit_model_spec)
    from quintnet_tpu.parallel.strategy import get_strategy

    vcfg = ViTConfig(image_size=14, patch_size=7, in_channels=1,
                     hidden_dim=16, depth=2, num_heads=2, num_classes=10,
                     n_experts=4, router_type="expert_choice",
                     expert_capacity=4096, aux_loss_weight=0.0)
    model = vit_model_spec(vcfg)
    host = vit_init(jax.random.key(0), vcfg)
    x = jnp.asarray(rng.normal(size=(8, 14, 14, 1)), jnp.float32)
    y = jnp.asarray(rng.integers(0, 10, (8,)), jnp.int32)

    ref = model.loss_fn(host, (x, y))

    cfg = Config.from_dict({
        "mesh_dim": [2, 2], "mesh_name": ["dp", "ep"],
        "training": {"batch_size": 8, "grad_clip_norm": None}})
    strat = get_strategy("dp_ep", cfg)
    opt = optax.adam(1e-2)
    p = strat.shard_params(model, jax.tree.map(jnp.array, host))
    s = strat.init_opt_state(model, opt, p)
    b = strat.shard_batch((x, y), model)
    step = strat.make_train_step(model, opt)
    p, s, loss = step(p, s, b)
    np.testing.assert_allclose(float(loss), float(ref), rtol=2e-4)
    for _ in range(9):
        p, s, loss = step(p, s, b)
    assert float(loss) < float(ref)

"""Multi-tenant LoRA serving goldens (quintnet_tpu/serve/adapters.py).

THE contract: a heterogeneous-adapter batch — different tenants'
adapters plus base-model requests sharing one decode step — produces,
per request, output token-identical to a DEDICATED engine serving that
adapter's ``lora_merge_tree`` merged weights, greedy AND sampled,
including with the prefix cache on, speculation on, under preemption,
and across fleet kill-migration onto a replica that has never seen the
adapter. Plus the operational invariants: the registry's LRU never
evicts a pinned adapter, the prefix index is namespaced per adapter
(identical tokens under different adapters can never alias KV), and
the bounded-compile promise extends to <= prefill buckets + verify
buckets + one decode per ``analysis/specs.lora_rank_buckets`` bucket —
adapters registering/evicting mid-trace trigger ZERO recompiles.
"""

import os

import jax
import numpy as np
import pytest

from quintnet_tpu.analysis.specs import lora_rank_buckets
from quintnet_tpu.models.gpt2 import GPT2Config, gpt2_init
from quintnet_tpu.models.lora import (LoRAConfig, lora_init,
                                      lora_merge_tree, save_lora)
from quintnet_tpu.serve import (AdapterRegistry, KVPool, ServeEngine,
                                SpecConfig, generate, gpt2_family)

CFG = GPT2Config.tiny(n_layer=2, n_positions=128)


@pytest.fixture(scope="module")
def params():
    return gpt2_init(jax.random.key(0), CFG)


def _adapter(params, seed, rank, alpha=None, targets=None):
    """A non-trivial adapter (b moved off its zero init so deltas are
    real) + its config."""
    kw = {"targets": tuple(targets)} if targets else {}
    cfg = LoRAConfig(rank=rank, alpha=alpha or 2.0 * rank, **kw)
    lora = lora_init(jax.random.key(seed), params["blocks"], cfg)
    lora = jax.tree.map(
        lambda l: l + 0.02 * jax.random.normal(
            jax.random.key(seed + 100), l.shape), lora)
    return lora, cfg


@pytest.fixture(scope="module")
def tenants(params, tmp_path_factory):
    """Two tenants of different ranks, saved through the real
    safetensors path the registry consumes."""
    root = tmp_path_factory.mktemp("adapters")
    out = {}
    for aid, seed, rank in (("tenant-a", 1, 4), ("tenant-b", 2, 8)):
        lora, cfg = _adapter(params, seed, rank)
        path = str(root / f"{aid}.safetensors")
        save_lora(lora, cfg, path)
        out[aid] = (lora, cfg, path)
    return out


def _registry(tenants):
    reg = AdapterRegistry()
    for aid, (_l, _c, path) in tenants.items():
        reg.register(aid, path)
    return reg


def _engine(params, adapters=None, **kw):
    kw.setdefault("max_slots", 4)
    kw.setdefault("block_size", 8)
    kw.setdefault("num_blocks", 32)
    kw.setdefault("max_seq_len", 64)
    return ServeEngine(gpt2_family(CFG), params, adapters=adapters, **kw)


def _dedicated(params, tenants, aid, prompt, max_new, key, **kw):
    """The golden reference: a dedicated engine serving the adapter's
    lora_merge_tree merged weights (or the plain base for aid=None)."""
    merged = (params if aid is None
              else lora_merge_tree(params, tenants[aid][0],
                                   tenants[aid][1]))
    eng = _engine(merged, max_slots=1, **kw)
    return generate(eng, [prompt], max_new_tokens=max_new, keys=[key])[0]


def _prompts(rng, lens):
    return [rng.integers(0, CFG.vocab_size, (n,)).astype(np.int32)
            for n in lens]


# ---------------------------------------------------------------------
# registry units
# ---------------------------------------------------------------------

class TestRegistry:
    def test_register_load_evict_reload(self, tenants):
        reg = _registry(tenants)
        assert reg.adapter_ids == ["tenant-a", "tenant-b"]
        assert reg.is_resident("tenant-a")
        reg.evict("tenant-a")
        assert not reg.is_resident("tenant-a")
        assert reg.is_registered("tenant-a")   # registration survives
        entry = reg.acquire("tenant-a")        # reloads from source
        assert entry.resident and entry.loads == 2
        reg.release("tenant-a")

    def test_pinned_adapter_cannot_evict(self, tenants):
        reg = _registry(tenants)
        reg.acquire("tenant-a")
        with pytest.raises(ValueError, match="pinned"):
            reg.evict("tenant-a")
        with pytest.raises(ValueError, match="pinned"):
            reg.unregister("tenant-a")
        reg.release("tenant-a")
        reg.evict("tenant-a")                  # unpinned: fine

    def test_byte_budget_lru_eviction(self, tenants):
        _, _, path_a = tenants["tenant-a"]
        _, _, path_b = tenants["tenant-b"]
        one = AdapterRegistry().register("x", path_a).nbytes
        t = [0.0]
        # rank-8 t1 is 2x the bytes of rank-4 t0/t2: all three resident
        # would be 4x one; a 3.2x budget forces exactly the LRU out
        reg = AdapterRegistry(byte_budget=int(one * 3.2),
                              clock=lambda: t[0])
        for i, p in enumerate([path_a, path_b, path_a]):
            t[0] = float(i)
            reg.register(f"t{i}", p)
        assert not reg.is_resident("t0")       # least-recently-used
        assert reg.is_resident("t1") and reg.is_resident("t2")
        assert reg.evictions == 1
        # touching t1 then loading t0 back evicts t2 (now the LRU)
        t[0] = 3.0
        reg.ensure_resident("t1")
        t[0] = 4.0
        reg.acquire("t0")
        assert not reg.is_resident("t2")
        # a PINNED working set may exceed the budget rather than fail
        t[0] = 5.0
        reg.acquire("t1")
        reg.acquire("t2")
        assert reg.bytes_resident > reg.byte_budget
        assert reg.stats()["pinned"] == 3

    def test_in_memory_entries_never_lru_evicted(self, params, tenants):
        lora, cfg = _adapter(params, 9, 4)
        reg = AdapterRegistry(byte_budget=1)   # absurdly small
        reg.register("mem", tree=lora, cfg=cfg)
        reg.register("f1", tenants["tenant-a"][2])
        reg.register("f2", tenants["tenant-b"][2])
        # way over budget: only file-backed entries are eviction
        # candidates, and the newest registrant is protected — so f1
        # went while the sourceless tree and the fresh file survive
        assert reg.is_resident("mem")
        assert not reg.is_resident("f1")
        assert reg.is_resident("f2")
        with pytest.raises(ValueError, match="in-memory"):
            reg.evict("mem")

    def test_register_validation(self, params, tenants):
        reg = _registry(tenants)
        with pytest.raises(ValueError, match="already registered"):
            reg.register("tenant-a", tenants["tenant-a"][2])
        with pytest.raises(ValueError, match="invalid adapter id"):
            reg.register("", tenants["tenant-a"][2])
        with pytest.raises(ValueError, match="source path"):
            AdapterRegistry().register("x")
        with pytest.raises(KeyError, match="unknown adapter"):
            reg.acquire("nope")
        with pytest.raises(ValueError, match="released more"):
            reg.release("tenant-a")


# ---------------------------------------------------------------------
# engine-side validation
# ---------------------------------------------------------------------

class TestEngineValidation:
    def test_adapter_blind_engine_rejects_adapter_id(self, params):
        eng = _engine(params)
        with pytest.raises(ValueError, match="without adapters"):
            eng.submit(np.zeros((4,), np.int32), 2, adapter_id="a")

    def test_unknown_and_overrank_adapters_fail_at_submit(
            self, params, tenants):
        reg = _registry(tenants)
        lora, cfg = _adapter(params, 11, 16)   # above the default top
        reg.register("huge", tree=lora, cfg=cfg)
        eng = _engine(params, adapters=reg)    # ladder tops out at 8
        with pytest.raises(KeyError, match="unknown adapter"):
            eng.submit(np.zeros((4,), np.int32), 2, adapter_id="ghost")
        with pytest.raises(ValueError, match="rank 16"):
            eng.submit(np.zeros((4,), np.int32), 2, adapter_id="huge")
        # the failed pin was rolled back
        assert reg.entry("huge").refs == 0

    def test_unserved_target_rejected_not_dropped(self, params, tenants):
        """An adapter training targets the engine is NOT configured to
        pack must be rejected — silently dropping a trained factor
        would diverge from the adapter's merged-weights golden."""
        reg = _registry(tenants)   # tenants train qkv/proj/fc
        eng = _engine(params, adapters=reg,
                      lora_targets=("qkv", "proj"))   # no fc packing
        with pytest.raises(ValueError, match="mlp.fc"):
            eng.submit(np.zeros((4,), np.int32), 2,
                       adapter_id="tenant-a")
        assert reg.entry("tenant-a").refs == 0   # pin rolled back

    def test_changed_on_disk_reload_rejected(self, params, tmp_path):
        """A source file rewritten with a different config (same rank,
        new alpha) must fail the reload — serving new factors under
        the stale registered scale would be neither adapter."""
        lora, cfg = _adapter(params, 21, 4, alpha=8.0)
        path = str(tmp_path / "mut.safetensors")
        save_lora(lora, cfg, path)
        reg = AdapterRegistry()
        reg.register("mut", path)
        reg.evict("mut")
        save_lora(lora, LoRAConfig(rank=4, alpha=32.0), path)
        with pytest.raises(ValueError, match="changed on disk"):
            reg.ensure_resident("mut")

    def test_shape_mismatch_fails_the_request_only(self, params, tenants):
        reg = _registry(tenants)
        other = gpt2_init(jax.random.key(9),
                          GPT2Config.tiny(n_layer=2, n_embd=48, n_head=2))
        wrong, wcfg = _adapter(other, 12, 4)
        reg.register("wrong-dims", tree=wrong, cfg=wcfg)
        eng = _engine(params, adapters=reg)
        with pytest.raises(ValueError, match="do not match"):
            eng.submit(np.zeros((4,), np.int32), 2,
                       adapter_id="wrong-dims")
        # the engine itself is fine: a good request still runs
        rid = eng.submit(np.zeros((4,), np.int32), 2,
                         adapter_id="tenant-a")
        eng.run(max_steps=50)
        assert eng.result(rid).shape == (6,)


# ---------------------------------------------------------------------
# parity goldens vs dedicated merged-weight engines
# ---------------------------------------------------------------------

def test_heterogeneous_batch_matches_dedicated_greedy(params, tenants):
    """Mixed adapters + base-model slots in ONE decode step, staggered
    arrivals: every request equals its dedicated merged-weight engine."""
    reg = _registry(tenants)
    eng = _engine(params, adapters=reg)
    rng = np.random.default_rng(0)
    prompts = _prompts(rng, (5, 7, 6, 4))
    keys = [jax.random.key(10 + i) for i in range(4)]
    aids = ["tenant-a", "tenant-b", None, "tenant-a"]
    arrivals = [0, 0, 1, 3]
    rids, submitted, step = {}, 0, 0
    while submitted < len(prompts) or eng.has_work:
        while submitted < len(prompts) and arrivals[submitted] <= step:
            rids[submitted] = eng.submit(
                prompts[submitted], 8, key=keys[submitted],
                adapter_id=aids[submitted])
            submitted += 1
        eng.step()
        step += 1
        assert step < 500
    assert eng.metrics.peak_running >= 3   # tenants truly shared steps
    for i in range(4):
        ref = _dedicated(params, tenants, aids[i], prompts[i], 8, keys[i])
        np.testing.assert_array_equal(eng.result(rids[i]), ref)
    # per-adapter ledgers saw the traffic
    per = eng.metrics.summary()["adapters"]
    assert per["tenant-a"]["requests"] == 2
    assert per["tenant-b"]["gen_tokens"] == 8
    # every retire released its pin
    assert all(reg.entry(a).refs == 0 for a in reg.adapter_ids)


def test_heterogeneous_batch_matches_dedicated_sampled(params, tenants):
    reg = _registry(tenants)
    kw = dict(temperature=0.8, top_k=20)
    eng = _engine(params, adapters=reg, **kw)
    rng = np.random.default_rng(1)
    prompts = _prompts(rng, (5, 7, 6))
    keys = [jax.random.key(20 + i) for i in range(3)]
    aids = ["tenant-a", "tenant-b", None]
    rids = [eng.submit(p, 8, key=k, adapter_id=a)
            for p, k, a in zip(prompts, keys, aids)]
    eng.run(max_steps=200)
    for i in range(3):
        ref = _dedicated(params, tenants, aids[i], prompts[i], 8,
                         keys[i], **kw)
        np.testing.assert_array_equal(eng.result(rids[i]), ref)


def test_parity_with_prefix_cache_and_namespacing(params, tenants):
    """The same prompt served under tenant-a, tenant-b AND the base
    model: per-adapter chains hit within a tenant (second wave
    re-prefills almost nothing) while IDENTICAL token prefixes under
    other adapters never alias — the namespaced-index guarantee."""
    reg = _registry(tenants)
    eng = _engine(params, adapters=reg)
    rng = np.random.default_rng(2)
    shared = rng.integers(0, CFG.vocab_size, (16,)).astype(np.int32)
    aids = ["tenant-a", "tenant-b", None]
    keys = [jax.random.key(30 + i) for i in range(6)]
    # wave 1: one request per namespace, identical prompt
    w1 = [eng.submit(shared, 6, key=keys[i], adapter_id=aids[i])
          for i in range(3)]
    eng.run(max_steps=200)
    hits_w1 = eng.metrics.prefix_hit_tokens
    # wave 2: same prompt again per namespace -> intra-namespace hits
    w2 = [eng.submit(shared, 6, key=keys[3 + i], adapter_id=aids[i])
          for i in range(3)]
    eng.run(max_steps=200)
    assert eng.metrics.prefix_hit_tokens > hits_w1
    for i in range(3):
        for rid, key in ((w1[i], keys[i]), (w2[i], keys[3 + i])):
            ref = _dedicated(params, tenants, aids[i], shared, 6, key)
            np.testing.assert_array_equal(eng.result(rid), ref)


def test_pool_prefix_index_is_namespaced():
    """KVPool unit for the same guarantee: a chain published under one
    adapter id is invisible to other namespaces and to the base."""
    pool = KVPool(n_layers=1, n_kv_heads=1, head_dim=4, block_size=4,
                  num_blocks=8)
    toks = np.arange(8, dtype=np.int32)
    blocks = pool.acquire(2)
    pool.publish(toks, blocks, 8, namespace="tenant-a")
    hit = pool.lookup(toks, namespace="tenant-a")
    assert hit.cached_tokens == 8 and hit.shared_blocks == blocks
    assert pool.lookup(toks, namespace="tenant-b").cached_tokens == 0
    assert pool.lookup(toks).cached_tokens == 0
    base_blocks = pool.acquire(2)
    pool.publish(toks, base_blocks, 8)          # base namespace
    assert pool.lookup(toks).shared_blocks == base_blocks
    assert pool.lookup(toks,
                       namespace="tenant-a").shared_blocks == blocks
    # adversarial byte collision: 'abc' + NUL == the little-endian
    # bytes of token 0x00636261, so without the base-key NUL prefix a
    # base prompt opening with that token could alias namespace 'abc'
    abc = KVPool(n_layers=1, n_kv_heads=1, head_dim=4, block_size=1,
                 num_blocks=8)
    t = np.asarray([7], np.int32)
    blk = abc.acquire(1)
    abc.publish(t, blk, 1, namespace="abc")
    crafted = np.asarray([0x00636261, 7], np.int32)
    assert abc.lookup(crafted).cached_tokens == 0


def test_parity_under_preemption(params, tenants):
    """A pool too small for the batch forces preempt-resume; adapter
    bindings survive eviction (unbound at preempt, re-bound at resume)
    and outputs stay token-identical."""
    reg = _registry(tenants)
    eng = _engine(params, adapters=reg, max_slots=3, block_size=4,
                  num_blocks=14, max_seq_len=40)
    rng = np.random.default_rng(3)
    prompts = _prompts(rng, (8, 9, 7))
    keys = [jax.random.key(40 + i) for i in range(3)]
    aids = ["tenant-a", "tenant-b", "tenant-a"]
    rids = [eng.submit(p, 12, key=k, adapter_id=a)
            for p, k, a in zip(prompts, keys, aids)]
    eng.run(max_steps=500)
    assert eng.metrics.preempted > 0
    for i in range(3):
        ref = _dedicated(params, tenants, aids[i], prompts[i], 12,
                         keys[i], block_size=4, num_blocks=14,
                         max_seq_len=40)
        np.testing.assert_array_equal(eng.result(rids[i]), ref)


def test_parity_with_speculation(params, tenants):
    """Spec-on + adapters: repetitive prompts draft and commit
    multi-token runs; committed output equals the dedicated merged
    engine (which is itself spec-off — speculation is bit-exact)."""
    reg = _registry(tenants)
    eng = _engine(params, adapters=reg, max_slots=3, max_seq_len=96,
                  spec=SpecConfig())
    rng = np.random.default_rng(4)
    pat = rng.integers(0, CFG.vocab_size, (4,)).astype(np.int32)
    rp = np.tile(pat, 5)[:18]
    keys = [jax.random.key(50), jax.random.key(51)]
    rid_a = eng.submit(rp, 30, key=keys[0], adapter_id="tenant-a")
    rid_b = eng.submit(rp[:10], 10, key=keys[1], adapter_id="tenant-b")
    eng.run(max_steps=300)
    assert eng.metrics.spec_steps > 0      # speculation actually ran
    ref_a = _dedicated(params, tenants, "tenant-a", rp, 30, keys[0],
                       max_seq_len=96)
    ref_b = _dedicated(params, tenants, "tenant-b", rp[:10], 10, keys[1],
                       max_seq_len=96)
    np.testing.assert_array_equal(eng.result(rid_a), ref_a)
    np.testing.assert_array_equal(eng.result(rid_b), ref_b)


def test_llama_family_parity(tenants):
    """Same contract through the llama family (separate q/k/v/o +
    SwiGLU targets, GQA pool)."""
    from quintnet_tpu.models.llama import LlamaConfig, llama_init
    from quintnet_tpu.models.lora import LLAMA_TARGETS
    from quintnet_tpu.serve import llama_family

    lcfg_m = LlamaConfig.tiny()
    lp = llama_init(jax.random.key(0), lcfg_m)
    lora, cfg = _adapter(lp, 5, 4, targets=LLAMA_TARGETS)
    reg = AdapterRegistry()
    reg.register("t", tree=lora, cfg=cfg)
    fam = llama_family(lcfg_m)
    eng = ServeEngine(fam, lp, max_slots=2, block_size=8, num_blocks=32,
                      max_seq_len=64, adapters=reg)
    rng = np.random.default_rng(5)
    p = rng.integers(0, lcfg_m.vocab_size, (6,)).astype(np.int32)
    k = jax.random.key(42)
    rid = eng.submit(p, 8, key=k, adapter_id="t")
    rid_base = eng.submit(p, 8, key=k)     # same prompt, base slot
    eng.run(max_steps=100)
    merged = lora_merge_tree(lp, lora, cfg)
    for ref_params, rid_ in ((merged, rid), (lp, rid_base)):
        ded = ServeEngine(fam, ref_params, max_slots=1, block_size=8,
                          num_blocks=32, max_seq_len=64)
        ref = generate(ded, [p], max_new_tokens=8, keys=[k])[0]
        np.testing.assert_array_equal(eng.result(rid_), ref)


# ---------------------------------------------------------------------
# fleet: affinity routing + kill-migration onto a cold replica
# ---------------------------------------------------------------------

class _StubReplica:
    def __init__(self, name, tokens, resident):
        self.name = name
        self.outstanding_tokens = tokens
        self._resident = set(resident)

    def adapter_resident(self, aid):
        return aid in self._resident


def test_router_adapter_affinity_prefilter():
    from quintnet_tpu.fleet.router import Router

    cold = _StubReplica("r0", 0, ())
    warm = _StubReplica("r1", 100, ("a",))
    r = Router("least_work")
    # least_work alone would pick the idle cold replica...
    assert r.pick([cold, warm]) is cold
    # ...but adapter affinity narrows to the warm one first
    assert r.pick([cold, warm], adapter_id="a") is warm
    # no warm candidate -> the full list stands (soft preference)
    assert r.pick([cold, warm], adapter_id="zzz") is cold


def test_fleet_kill_migration_onto_cold_replica(params, tenants):
    """r0 (adapter-warm) dies mid-flight with its breaker held open;
    every in-flight adapter request resumes on r1 — whose registry has
    NEVER held the adapter resident — token-identical to the dedicated
    merged engine. The cold replica warms itself from the safetensors
    source on demand."""
    from quintnet_tpu.fleet.fleet import ServeFleet
    from quintnet_tpu.ft import ChaosMonkey

    paths = {aid: t[2] for aid, t in tenants.items()}

    def factory():
        reg = AdapterRegistry()
        for aid, path in paths.items():
            reg.register(aid, path)
        return _engine(params, adapters=reg, max_slots=2)

    monkey = ChaosMonkey(kill_at_step=6, mode="raise", target="r0")
    # trip_after=1 + long reset: r0 stays down, so migration MUST land
    # on the cold replica instead of a warm restart
    fleet = ServeFleet(factory, n_replicas=2, chaos=monkey,
                       trip_after=1, breaker_reset_s=1e9)
    try:
        for aid in paths:
            fleet.replicas[1].engine.adapters.evict(aid)
        assert not fleet.replicas[1].adapter_resident("tenant-a")
        rng = np.random.default_rng(6)
        prompts = _prompts(rng, (6, 5, 7))
        keys = [jax.random.key(60 + i) for i in range(3)]
        aids = ["tenant-a", "tenant-b", "tenant-a"]
        fids = [fleet.submit(p, 16, key=k, adapter_id=a)
                for p, k, a in zip(prompts, keys, aids)]
        outs = [fleet.result(f, timeout=120) for f in fids]
        assert fleet.metrics.replica_deaths >= 1
        assert fleet.metrics.migrations >= 1
        for i in range(3):
            ref = _dedicated(params, tenants, aids[i], prompts[i], 16,
                             keys[i])
            np.testing.assert_array_equal(outs[i], ref)
        # the cold replica loaded what it was handed
        assert fleet.replicas[1].adapter_resident("tenant-a")
        # fleet-wide compile accounting handles decode[r*] sentinels
        fleet.assert_compile_count()
        agg = fleet.engine_summary()["adapters"]
        assert agg["tenant-a"]["requests"] == 2
        assert agg["tenant-b"]["requests"] == 1
    finally:
        fleet.close()


# ---------------------------------------------------------------------
# the zero-recompile invariant
# ---------------------------------------------------------------------

def test_zero_recompiles_as_adapters_join_and_leave(params, tenants,
                                                    tmp_path):
    """Mixed trace with adapters REGISTERED AND EVICTED mid-flight:
    after warmup, zero backend compiles (jax.monitoring), compile
    counts pinned at the sentinel bound derived from
    analysis/specs.lora_rank_buckets."""
    import jax.monitoring as monitoring

    reg = _registry(tenants)
    eng = _engine(params, adapters=reg)
    assert eng.lora_rank_buckets == lora_rank_buckets(8)
    eng.warmup()   # every prefill bucket, decode rank bucket, (verify)
    stats0 = eng.compile_stats()
    assert stats0 == {"prefill": len(eng.prefill_buckets),
                      "decode": len(eng.lora_rank_buckets)}
    # one full lifecycle primes submit-path helpers outside sentinels
    eng.submit(np.zeros((3,), np.int32), 2)
    eng.run(max_steps=50)

    rng = np.random.default_rng(7)
    new_lora, new_cfg = _adapter(params, 30, 2)   # third rank class
    new_path = str(tmp_path / "c.safetensors")
    save_lora(new_lora, new_cfg, new_path)

    compiles = []
    monitoring.register_event_duration_secs_listener(
        lambda name, dur, **kw: compiles.append(name)
        if "backend_compile" in name else None)
    try:
        plan = [("tenant-a", 9), (None, 6), ("tenant-b", 7)]
        rids = [eng.submit(rng.integers(0, CFG.vocab_size, (n,))
                           .astype(np.int32), 6, adapter_id=a)
                for a, n in plan]
        eng.run(max_steps=200)
        # JOIN: a brand-new tenant registers and serves mid-session
        reg.register("tenant-c", new_path)
        rid_c = eng.submit(rng.integers(0, CFG.vocab_size, (5,))
                           .astype(np.int32), 6, adapter_id="tenant-c")
        # LEAVE: an idle tenant's weights evict; traffic continues
        reg.evict("tenant-a")
        rid_a = eng.submit(rng.integers(0, CFG.vocab_size, (4,))
                           .astype(np.int32), 6, adapter_id="tenant-a")
        eng.run(max_steps=200)
        assert all(eng.request(r).state == "finished"
                   for r in rids + [rid_c, rid_a])
    finally:
        monitoring.clear_event_listeners()
    assert compiles == []
    assert eng.compile_stats() == stats0       # nothing new compiled
    eng.assert_compile_count(prefill=stats0["prefill"],
                             decode=stats0["decode"])


def test_rank_bucket_selection(params, tenants):
    """The decode step runs in the smallest ladder bucket covering the
    batch's largest bound rank (base-only batches use the floor)."""
    reg = _registry(tenants)
    eng = _engine(params, adapters=reg)
    assert eng._decode_rank_bucket() == eng.lora_rank_buckets[0]
    rid = eng.submit(np.zeros((4,), np.int32), 4, adapter_id="tenant-a")
    eng.step()
    assert eng._decode_rank_bucket() == 4      # rank-4 adapter bound
    rid_b = eng.submit(np.zeros((5,), np.int32), 4,
                       adapter_id="tenant-b")
    eng.step()
    assert eng._decode_rank_bucket() == 8      # rank-8 joined the batch
    eng.run(max_steps=100)
    assert eng._decode_rank_bucket() == eng.lora_rank_buckets[0]
    assert {eng.request(r).state for r in (rid, rid_b)} == {"finished"}


def test_adapter_blind_engine_surface_unchanged(params):
    """An adapters=None engine exposes the pre-adapter compile surface
    byte-for-byte: single `decode` sentinel, no rank buckets — fleets
    mixing adapter-on and adapter-off replicas account each
    correctly."""
    eng = _engine(params)
    eng.submit(np.zeros((4,), np.int32), 3)
    eng.run(max_steps=50)
    assert eng.compile_stats() == {"prefill": 1, "decode": 1}
    assert "decode" in eng.compile_sentinels()
    assert not any(k.startswith("decode[")
                   for k in eng.compile_sentinels())
    eng.assert_compile_count()


# ---------------------------------------------------------------------
# tp-sharded engine (slow tier, like the other tp serve goldens)
# ---------------------------------------------------------------------

@pytest.mark.slow
def test_tp2_adapter_parity(params, tenants):
    """The whole multi-LoRA step under a tp=2 shard_map: packed factors
    sharded per-target like their weights (a in-sharded, b out-sharded,
    gpt2's fused qkv re-blocked by the family layout hook), outputs
    identical to the dedicated merged engines."""
    from quintnet_tpu.core.mesh import mesh_from_sizes
    from quintnet_tpu.models.gpt2 import gpt2_to_tp_layout

    reg = _registry(tenants)
    mesh = mesh_from_sizes(tp=2)
    tp_params = gpt2_to_tp_layout(params, CFG, 2)
    eng = _engine(tp_params, adapters=reg, mesh=mesh)
    rng = np.random.default_rng(8)
    prompts = _prompts(rng, (6, 5, 7))
    keys = [jax.random.key(70 + i) for i in range(3)]
    aids = ["tenant-a", "tenant-b", None]
    rids = [eng.submit(p, 8, key=k, adapter_id=a)
            for p, k, a in zip(prompts, keys, aids)]
    eng.run(max_steps=100)
    for i in range(3):
        ref = _dedicated(params, tenants, aids[i], prompts[i], 8,
                         keys[i])
        np.testing.assert_array_equal(eng.result(rids[i]), ref)

"""Trainer / data / metrics tests (reference analogues: trainer loops in
trainer.py + GPT2_Trainer.py, dataset plumbing utils/Dataloader.py,
metrics utils/metrics.py)."""

import numpy as np
import pytest

import jax

from quintnet_tpu.core.config import Config
from quintnet_tpu.data import (
    ArrayDataset,
    ByteTokenizer,
    SummarizationDataset,
    load_mnist,
    make_batches,
)
from quintnet_tpu.models.vit import ViTConfig, vit_model_spec
from quintnet_tpu.train import metrics as M
from quintnet_tpu.train.trainer import Trainer

CFG = ViTConfig(image_size=28, patch_size=7, in_channels=1, hidden_dim=16,
                depth=4, num_heads=2, num_classes=10)


def test_synthetic_mnist_learnable_and_split_consistent():
    xtr, ytr = load_mnist(split="train", synthetic_size=2048)
    xte, yte = load_mnist(split="test", synthetic_size=512)
    assert xtr.shape == (2048, 28, 28, 1) and ytr.shape == (2048,)
    # same class prototypes across splits: same-class means correlate.
    # The task is deliberately low-SNR (Bayes acc ~94%, see
    # synthetic_mnist docstring) so the correlation needs enough samples
    # per class to emerge from the noise.
    m_tr = xtr[ytr == 3].mean(0).ravel()
    m_te = xte[yte == 3].mean(0).ravel()
    corr = np.corrcoef(m_tr, m_te)[0, 1]
    assert corr > 0.35, corr


def test_make_batches_shapes():
    ds = ArrayDataset(np.zeros((10, 2)), np.arange(10))
    bs = list(make_batches(ds, 4, shuffle=False))
    assert len(bs) == 2 and bs[0][0].shape == (4, 2)


def test_summarization_encoding_masks_prompt():
    tok = ByteTokenizer()
    ds = SummarizationDataset([("hello world", "hi")], tok, max_length=32)
    ids, labels = ds.encode_row("hello world", "hi")
    assert ids.shape == (32,) and labels.shape == (32,)
    n_prompt = len(tok.encode("hello world" + ds.PROMPT))
    assert (labels[:n_prompt] == -100).all()
    assert (labels[n_prompt:n_prompt + 2] == ids[n_prompt:n_prompt + 2]).all()
    assert (labels[n_prompt + 2:] == -100).all()  # padding masked


def test_summarization_encoding_keeps_summary_on_overflow():
    """Long articles must left-truncate so the summary labels survive
    (right-truncation silently masks every label -> zero loss)."""
    tok = ByteTokenizer()
    long_article = "x" * 100
    ds = SummarizationDataset([(long_article, "hi")], tok, max_length=32)
    ids, labels = ds.encode_row(long_article, "hi")
    assert ids.shape == (32,)
    n_valid = int((labels != -100).sum())
    assert n_valid == len(tok.encode("hi"))
    # the TL;DR marker at the prompt tail survives the left-truncation
    marker = tok.encode(ds.PROMPT)
    assert list(ids[32 - n_valid - len(marker):32 - n_valid]) == list(marker)


def test_rouge_bleu():
    r = M.rouge_scores("the cat sat", "the cat sat")
    assert r["rouge1"] == r["rouge2"] == r["rougeL"] == 1.0
    r2 = M.rouge_scores("the cat", "the dog")
    assert 0 < r2["rouge1"] < 1 and r2["rouge2"] == 0.0
    assert M.bleu_score("the cat sat on the mat", ["the cat sat on the mat"]) \
        == pytest.approx(1.0)
    agg = M.compute_rouge_bleu(["a b c"], ["a b d"])
    assert set(agg) == {"rouge1", "rouge2", "rougeL", "bleu"}


def test_trainer_fit_reduces_loss_dp():
    cfg = Config.from_dict({
        "mesh_dim": [4], "mesh_name": ["dp"],
        "training": {"batch_size": 32, "epochs": 3, "learning_rate": 1e-3,
                     "optimizer": "adam", "log_every": 0},
    })
    model = vit_model_spec(CFG)
    x, y = load_mnist(split="train", synthetic_size=128)
    ds = ArrayDataset(x, y)
    trainer = Trainer(cfg, model, task_type="classification",
                      log_fn=lambda s: None)
    hist = trainer.fit(
        lambda ep: make_batches(ds, 32, seed=ep),
        val_batches_fn=lambda ep: make_batches(ds, 32, shuffle=False),
    )
    assert hist.train_loss[-1] < hist.train_loss[0]
    assert len(hist.val_loss) == 3


def test_trainer_resume(tmp_path):
    cfg = Config.from_dict({
        "mesh_dim": [2], "mesh_name": ["dp"],
        "training": {"batch_size": 16, "epochs": 2, "optimizer": "adam",
                     "log_every": 0},
    })
    model = vit_model_spec(CFG)
    x, y = load_mnist(split="train", synthetic_size=64)
    ds = ArrayDataset(x, y)
    ck = str(tmp_path / "ck")

    t1 = Trainer(cfg, model, task_type="classification", checkpoint_dir=ck,
                 log_fn=lambda s: None)
    t1.fit(lambda ep: make_batches(ds, 16, seed=ep), epochs=1)

    t2 = Trainer(cfg, model, task_type="classification", checkpoint_dir=ck,
                 log_fn=lambda s: None)
    params, opt_state, start = t2.resume_or_init()
    assert start == 1  # resumes after epoch 0


def test_history_to_jsonl(tmp_path):
    import json

    from quintnet_tpu.train.trainer import History

    h = History(train_loss=[2.0, 1.5], val_loss=[1.8],
                val_metric=[0.5], wall_time_s=3.2,
                best_val_loss=1.8, best_epoch=0)
    p = str(tmp_path / "hist.jsonl")
    h.to_jsonl(p)
    rows = [json.loads(l) for l in open(p)]
    assert rows[0] == {"epoch": 0, "train_loss": 2.0, "val_loss": 1.8,
                       "val_metric": 0.5}
    assert rows[1] == {"epoch": 1, "train_loss": 1.5}
    assert rows[-1]["best_epoch"] == 0 and rows[-1]["wall_time_s"] == 3.2


def test_parity_report_flags_stale_legs(tmp_path, monkeypatch):
    import json

    from quintnet_tpu.tools import parity_run

    art = tmp_path / "parity"
    art.mkdir()
    base = {"epochs": 1, "train_loss": [1.0], "val_accuracy": [0.5],
            "val_perplexity": [3.0], "wall_time_s": 1.0}
    for task in ("vit", "gpt2"):
        mkey = "val_accuracy" if task == "vit" else "val_perplexity"
        single = {**base, "task": task, "mode": "single", "data_fp": "aaa"}
        three = {**base, "task": task, "mode": "3d",
                 "data_fp": "aaa" if task == "gpt2" else "bbb"}
        for r in (single, three):
            (art / f"{task}_{r['mode']}.json").write_text(json.dumps(r))
    monkeypatch.setattr(parity_run, "ART_DIR", str(art))
    md = parity_run.report()
    assert "INCOMPARABLE" in md           # vit legs differ -> flagged
    assert "GPT2 (1 epochs)" in md        # gpt2 legs match -> compared
    assert md.count("PASS") == 1


def test_compilation_cache_helper(tmp_path):
    from quintnet_tpu.core import runtime

    d = runtime.enable_compilation_cache(str(tmp_path / "xla"),
                                         min_compile_time_secs=0.0)
    import os

    import jax.numpy as jnp

    @jax.jit
    def f(x):
        return jnp.cos(x) @ x.T

    f(jnp.ones((128, 128))).block_until_ready()
    assert sum(len(fs) for _, _, fs in os.walk(d)) > 0
    # restore defaults for the rest of the session
    jax.config.update("jax_compilation_cache_dir", None)

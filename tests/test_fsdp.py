"""ZeRO-3 / FSDP golden tests (training.fsdp).

Block params are STORED dp-sharded (parallel/tp.py fsdp_shard_specs)
and all-gathered per layer inside the scan body
(nn/transformer.py stacked_blocks_apply) — the all_gather's vjp is a
reduce-scatter, so gradients and the optimizer state live sharded too.
The reference's ZeRO file is an empty stub (optimizers/zero.py); this
is the stage-3 capability on top of the round-4 ZeRO-1/2.

Golden bar: same as every other axis — loss AND updated parameters
must match single-device training exactly (up to float reassociation).
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

from quintnet_tpu.core.config import Config
from quintnet_tpu.models.gpt2 import (GPT2Config, gpt2_init,
                                      gpt2_model_spec,
                                      gpt2_partition_specs, gpt2_to_tp_layout)
from quintnet_tpu.parallel.strategy import get_strategy
from quintnet_tpu.parallel.tp import fsdp_gather_dims, fsdp_shard_specs

VOCAB = 128
TINY = GPT2Config.tiny(vocab_size=VOCAB)


def _config(mesh_dim, mesh_name, fsdp=True, optimizer="adamw"):
    return Config.from_dict({
        "mesh_dim": list(mesh_dim), "mesh_name": list(mesh_name),
        "training": {"batch_size": 8, "fsdp": fsdp,
                     "optimizer": optimizer, "grad_clip_norm": 1.0},
    })


def _data(n=8, t=16, seed=3):
    ids = jax.random.randint(jax.random.key(seed), (n, t), 0, VOCAB)
    return ids, ids


@pytest.mark.fast
def test_fsdp_spec_transform():
    """First free dim >= 1 gets the axis; full specs stay untouched."""
    specs = gpt2_partition_specs(TINY, tp_axis="tp", fsdp_axis="dp")
    b = specs["blocks"]
    assert b["attn"]["qkv"]["w"] == P(None, "dp", "tp")
    assert b["attn"]["proj"]["w"] == P(None, "tp", "dp")
    assert b["ln1"]["scale"] == P(None, "dp")
    # column bias [L, 3d/tp] has no free dim -> stays as-is
    assert "dp" not in (b["attn"]["qkv"]["b"] or ())
    # embedding/head replicate (vp is the knob for those)
    assert specs["embedding"]["wte"] == P()

    dims = fsdp_gather_dims(b, "dp")
    assert dims["attn"]["qkv"]["w"] == 0   # per-layer dim 0
    assert dims["attn"]["proj"]["w"] == 1  # per-layer dim 1 (tp on 0)
    assert dims["ln1"]["scale"] == 0
    assert dims["attn"]["qkv"]["b"] == -1  # not sharded, no gather


def _reference_update(params, batch, opt, steps=2):
    model = gpt2_model_spec(TINY)
    losses, state = [], opt.init(params)
    for _ in range(steps):
        loss, g = jax.value_and_grad(model.loss_fn)(params, batch)
        g, _ = optax.clip_by_global_norm(1.0).update(g, None)
        up, state = opt.update(g, state, params)
        params = optax.apply_updates(params, up)
        losses.append(float(loss))
    return losses, params


@pytest.mark.parametrize("mesh_dim,mesh_name,name", [
    ([2], ["dp"], "dp"),
    ([4], ["dp"], "dp"),
    ([2, 2], ["dp", "tp"], "dp_tp"),
    ([2, 2], ["dp", "sp"], "dp_sp"),
])
def test_fsdp_matches_single_device(mesh_dim, mesh_name, name):
    """FSDP training == single-device training: loss and params.

    SGD for the parameter-exactness bar: FSDP grads arrive through a
    reduce-scatter whose summation order differs from the single-device
    sum, and Adam's g/sqrt(v) amplifies that reassociation noise on
    near-zero grads beyond any sensible tolerance (same reasoning as
    tests/test_zero.py); Adam coverage is the trainer/opt-state tests.
    """
    cfg = _config(mesh_dim, mesh_name)
    params = gpt2_init(jax.random.key(0), TINY)
    batch = _data()
    opt = optax.sgd(0.05)

    losses_ref, p_ref = _reference_update(params, batch, opt)

    strat = get_strategy(name, cfg)
    assert strat.fsdp_axis == "dp"
    model = gpt2_model_spec(TINY)
    p = strat.shard_params(model, jax.tree.map(jnp.copy, params))
    s = strat.init_opt_state(model, opt, p)
    b = strat.shard_batch(batch, model)
    step = strat.make_train_step(model, opt)
    losses = []
    for _ in range(2):
        p, s, loss = step(p, s, b)
        losses.append(float(loss))

    np.testing.assert_allclose(losses, losses_ref, rtol=1e-4)
    tp = strat.mesh.shape.get("tp", 1)
    ref = dict(jax.tree_util.tree_leaves_with_path(
        gpt2_to_tp_layout(p_ref, TINY, tp)))
    for path, leaf in jax.tree_util.tree_leaves_with_path(p):
        np.testing.assert_allclose(
            np.asarray(jax.device_get(leaf)), np.asarray(ref[path]),
            rtol=2e-4, atol=1e-5,
            err_msg=f"{name}:{jax.tree_util.keystr(path)}")


def test_fsdp_params_and_opt_state_are_sharded():
    """The whole point: resident block params AND adam m/v hold 1/dp of
    the fsdp-sharded leaves per device."""
    cfg = _config([2], ["dp"])
    strat = get_strategy("dp", cfg)
    model = gpt2_model_spec(TINY)
    params = strat.shard_params(model, gpt2_init(jax.random.key(0), TINY))
    opt = optax.adamw(1e-3)
    state = strat.init_opt_state(model, opt, params)

    w = params["blocks"]["attn"]["qkv"]["w"]       # [L, d, 3d]
    shard = w.sharding.shard_shape(w.shape)
    assert shard[1] == w.shape[1] // 2             # dp=2 shards dim 1
    mu = state[0].mu["blocks"]["attn"]["qkv"]["w"]
    assert mu.sharding.shard_shape(mu.shape)[1] == mu.shape[1] // 2


def test_fsdp_trainer_fit_eval():
    """Trainer.fit + evaluate under fsdp (eval path gathers too)."""
    from quintnet_tpu.train.trainer import Trainer

    cfg = Config.from_dict({
        "mesh_dim": [2], "mesh_name": ["dp"],
        "training": {"batch_size": 8, "fsdp": True, "optimizer": "adamw",
                     "learning_rate": 1e-3, "epochs": 1, "log_every": 0},
    })
    strat = get_strategy("dp", cfg)
    trainer = Trainer(cfg, gpt2_model_spec(TINY), strategy=strat,
                      task_type="clm")
    ids = np.asarray(_data()[0])
    hist = trainer.fit(lambda _e: [(ids, ids)], epochs=1,
                       val_batches_fn=lambda _e: [(ids, ids)])
    assert np.isfinite(hist.train_loss[0])
    assert np.isfinite(hist.val_loss[0])


def test_fsdp_llama_and_vit_match_single_device():
    """The other two families run the same scan machinery."""
    from quintnet_tpu.models.llama import (LlamaConfig, llama_init,
                                           llama_model_spec)
    from quintnet_tpu.models.vit import ViTConfig, vit_init, vit_model_spec

    cfg = _config([2], ["dp"])
    opt = optax.sgd(0.05)

    lcfg = LlamaConfig.tiny(vocab_size=VOCAB)
    lmodel = llama_model_spec(lcfg)
    lparams = llama_init(jax.random.key(0), lcfg)
    batch = _data()
    ref = lmodel.loss_fn(lparams, batch)

    strat = get_strategy("dp", cfg)
    p = strat.shard_params(lmodel, jax.tree.map(jnp.copy, lparams))
    s = strat.init_opt_state(lmodel, opt, p)
    b = strat.shard_batch(batch, lmodel)
    _, _, loss = strat.make_train_step(lmodel, opt)(p, s, b)
    np.testing.assert_allclose(float(loss), float(ref), rtol=1e-5)

    vcfg = ViTConfig(image_size=14, patch_size=7, hidden_dim=16, depth=2,
                     num_heads=2)
    vmodel = vit_model_spec(vcfg)
    vparams = vit_init(jax.random.key(0), vcfg)
    x = jax.random.normal(jax.random.key(1), (8, 14, 14, 1))
    y = jax.random.randint(jax.random.key(2), (8,), 0, 10)
    vref = vmodel.loss_fn(vparams, (x, y))
    p = strat.shard_params(vmodel, jax.tree.map(jnp.copy, vparams))
    s = strat.init_opt_state(vmodel, opt, p)
    b = strat.shard_batch((x, y), vmodel)
    _, _, loss = strat.make_train_step(vmodel, opt)(p, s, b)
    np.testing.assert_allclose(float(loss), float(vref), rtol=1e-5)


@pytest.mark.fast
def test_fsdp_guards():
    """pp + fsdp and zero-optimizer + fsdp are refused loudly."""
    model = gpt2_model_spec(TINY)
    opt = optax.adamw(1e-3)

    cfg = Config.from_dict({
        "mesh_dim": [2, 2], "mesh_name": ["dp", "pp"],
        "training": {"batch_size": 8, "fsdp": True,
                     "gradient_accumulation_steps": 2}})
    with pytest.raises(NotImplementedError, match="fsdp under pipeline"):
        get_strategy("dp_pp", cfg).make_train_step(model, opt)

    cfg = _config([2], ["dp"], optimizer="zero1_adamw")
    with pytest.raises(ValueError, match="subsumes"):
        get_strategy("dp", cfg).make_train_step(model, opt)


@pytest.mark.fast
def test_fsdp_without_dp_axis_raises():
    model = gpt2_model_spec(TINY)
    cfg = _config([2], ["tp"])
    with pytest.raises(ValueError, match="requires a dp mesh axis"):
        get_strategy("tp", cfg).make_train_step(model,
                                                optax.adamw(1e-3))


def test_fsdp_checkpoint_save_resume(tmp_path):
    """Orbax save under fsdp sharding + Trainer resume: the dp-sharded
    params/opt-state round-trip, and a run resumed from epoch 0's
    checkpoint continues from the same state (loss parity with an
    uninterrupted 2-epoch run)."""
    from quintnet_tpu.train.trainer import Trainer

    def make_trainer(ckpt):
        cfg = Config.from_dict({
            "mesh_dim": [2], "mesh_name": ["dp"],
            "training": {"batch_size": 8, "fsdp": True,
                         "optimizer": "adamw", "learning_rate": 1e-3,
                         "log_every": 0}})
        return Trainer(cfg, gpt2_model_spec(TINY),
                       strategy=get_strategy("dp", cfg), task_type="clm",
                       checkpoint_dir=str(ckpt), log_fn=lambda s: None)

    ids = np.asarray(_data()[0])
    batches = lambda _e: [(ids, ids)]  # noqa: E731

    full = make_trainer(tmp_path / "a").fit(batches, epochs=2)

    t1 = make_trainer(tmp_path / "b")
    t1.fit(batches, epochs=1)
    t2 = make_trainer(tmp_path / "b")   # fresh instance -> resume path
    resumed = t2.fit(batches, epochs=2)  # continues at epoch 1

    np.testing.assert_allclose(resumed.train_loss[-1],
                               full.train_loss[-1], rtol=1e-5)


def test_fsdp_grad_accumulation_matches_single_device():
    """Microbatch accumulation happens in SHARD space under fsdp; the
    accumulated update must still equal the single-device full-batch
    mean-of-microbatches objective."""
    cfg = Config.from_dict({
        "mesh_dim": [2], "mesh_name": ["dp"],
        "training": {"batch_size": 8, "fsdp": True, "optimizer": "adamw",
                     "gradient_accumulation_steps": 2,
                     "grad_clip_norm": None}})
    params = gpt2_init(jax.random.key(0), TINY)
    batch = _data()
    opt = optax.sgd(0.05)
    model = gpt2_model_spec(TINY)

    def loss_ref(p):
        x, y = batch
        parts = [model.loss_fn(p, (x[i * 4:(i + 1) * 4],
                                   y[i * 4:(i + 1) * 4]))
                 for i in range(2)]
        return jnp.mean(jnp.stack(parts))

    ref_loss, g = jax.value_and_grad(loss_ref)(params)
    up, _ = opt.update(g, opt.init(params), params)
    p_ref = optax.apply_updates(params, up)

    strat = get_strategy("dp", cfg)
    p = strat.shard_params(model, jax.tree.map(jnp.copy, params))
    s = strat.init_opt_state(model, opt, p)
    b = strat.shard_batch(batch, model)
    p, s, loss = strat.make_train_step(model, opt)(p, s, b)
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
    ref = dict(jax.tree_util.tree_leaves_with_path(p_ref))
    for path, leaf in jax.tree_util.tree_leaves_with_path(p):
        np.testing.assert_allclose(
            np.asarray(jax.device_get(leaf)), np.asarray(ref[path]),
            rtol=2e-4, atol=1e-5, err_msg=jax.tree_util.keystr(path))


def test_fsdp_moe_ep_matches_single_device():
    """fsdp composes with expert parallelism: MoE expert leaves carry
    ep AND an fsdp dim; loss golden vs single device."""
    moe_cfg = dataclasses.replace(TINY, n_experts=4, expert_top_k=2,
                                  expert_capacity=4096,
                                  aux_loss_weight=0.0)
    model = gpt2_model_spec(moe_cfg)
    params = gpt2_init(jax.random.key(0), moe_cfg)
    batch = _data()
    ref = model.loss_fn(params, batch)

    cfg = Config.from_dict({
        "mesh_dim": [2, 2], "mesh_name": ["dp", "ep"],
        "training": {"batch_size": 8, "fsdp": True, "optimizer": "adamw",
                     "grad_clip_norm": None}})
    strat = get_strategy("dp_ep", cfg)
    opt = optax.sgd(0.05)
    p = strat.shard_params(model, jax.tree.map(jnp.copy, params))
    s = strat.init_opt_state(model, opt, p)
    b = strat.shard_batch(batch, model)
    _, _, loss = strat.make_train_step(model, opt)(p, s, b)
    np.testing.assert_allclose(float(loss), float(ref), rtol=1e-4)


@pytest.mark.parametrize("remat", [True, "dots"])
def test_fsdp_remat_matches_plain(remat):
    """The per-layer gather sits INSIDE the checkpoint boundary —
    backward re-gathers. Loss under remat must equal the plain fsdp
    path exactly."""
    cfg = _config([2], ["dp"])
    params = gpt2_init(jax.random.key(0), TINY)
    batch = _data()
    opt = optax.sgd(0.05)

    def run(model):
        strat = get_strategy("dp", cfg)
        p = strat.shard_params(model, jax.tree.map(jnp.copy, params))
        s = strat.init_opt_state(model, opt, p)
        b = strat.shard_batch(batch, model)
        p, s, loss = strat.make_train_step(model, opt)(p, s, b)
        return float(loss), p

    loss_plain, p_plain = run(gpt2_model_spec(TINY))
    loss_remat, p_remat = run(gpt2_model_spec(TINY, remat=remat))
    np.testing.assert_allclose(loss_remat, loss_plain, rtol=1e-6)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(jax.device_get(a)), np.asarray(jax.device_get(b)),
            rtol=1e-5, atol=1e-6),
        p_remat, p_plain)

"""ZeRO-1 tests: dp-sharded optimizer state produces bit-for-bit the same
updates as replicated AdamW, at 1/dp the state footprint (the reference's
optimizers/zero.py is an empty stub)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from quintnet_tpu.core.config import Config
from quintnet_tpu.models.vit import ViTConfig, vit_init, vit_model_spec
from quintnet_tpu.parallel.strategy import get_strategy

CFG = ViTConfig(image_size=14, patch_size=7, in_channels=1, hidden_dim=16,
                depth=4, num_heads=2, num_classes=10)


def _config(optimizer, mesh_dim, mesh_name, schedule="afab", grad_acc=1):
    return Config.from_dict({
        "mesh_dim": list(mesh_dim),
        "mesh_name": list(mesh_name),
        "training": {
            "batch_size": 16,
            "gradient_accumulation_steps": grad_acc,
            "schedule": schedule,
            "optimizer": optimizer,
            "grad_clip_norm": 1.0,
        },
    })


def _data(n=16):
    x = jax.random.normal(jax.random.key(1), (n, 14, 14, 1))
    y = jax.random.randint(jax.random.key(2), (n,), 0, 10)
    return x, y


def _run(optimizer_name, mesh_dim, mesh_name, n_steps=3, **kw):
    cfg = _config(optimizer_name, mesh_dim, mesh_name, **kw)
    strat = get_strategy("auto", cfg)
    model = vit_model_spec(CFG)
    opt = optax.adamw(1e-3, weight_decay=0.01)
    params = strat.shard_params(model, vit_init(jax.random.key(0), CFG))
    state = strat.init_opt_state(model, opt, params)
    batch = strat.shard_batch(_data())
    step = strat.make_train_step(model, opt)
    losses = []
    for _ in range(n_steps):
        params, state, loss = step(params, state, batch)
        losses.append(float(loss))
    return params, state, losses


def test_zero1_matches_replicated_adamw_exactly_one_step():
    """A single step is bit-identical (verified: chunked flat AdamW ==
    leaf-wise AdamW elementwise)."""
    p_ref, _, _ = _run("adamw", [4], ["dp"], n_steps=1)
    p_z, _, _ = _run("zero1_adamw", [4], ["dp"], n_steps=1)
    for a, b in zip(jax.tree.leaves(p_z), jax.tree.leaves(p_ref)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_zero1_matches_replicated_adamw_multistep():
    """Over steps, ulp-level fusion differences get amplified by Adam's
    rsqrt — allow float-noise tolerance."""
    p_ref, _, l_ref = _run("adamw", [4], ["dp"])
    p_z, state_z, l_z = _run("zero1_adamw", [4], ["dp"])

    np.testing.assert_allclose(l_z, l_ref, rtol=1e-5)
    for a, b in zip(jax.tree.leaves(p_z), jax.tree.leaves(p_ref)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-2, atol=2e-4)


def test_zero1_state_is_sharded():
    """Adam m/v live as dp-sharded chunks: total state elements ~= param
    count (x2), not x2 per replica."""
    cfg = _config("zero1_adamw", [4], ["dp"])
    strat = get_strategy("auto", cfg)
    model = vit_model_spec(CFG)
    opt = optax.adamw(1e-3)
    params = strat.shard_params(model, vit_init(jax.random.key(0), CFG))
    state = strat.init_opt_state(model, opt, params)

    n_params = sum(x.size for x in jax.tree.leaves(params))
    arr_leaves = [x for x in jax.tree.leaves(state) if hasattr(x, "size")]
    n_state = sum(x.size for x in arr_leaves if x.ndim > 0)
    # mu + nu, padded to dp multiple
    assert n_state <= 2 * (n_params + 4 * 4), (n_state, n_params)
    # and each device holds only 1/dp of it
    chunk = [x for x in arr_leaves if x.ndim == 1][0]
    local = chunk.addressable_shards[0].data
    assert local.shape[0] * 4 == chunk.shape[0]


def test_zero1_composes_with_3d():
    p_ref, _, l_ref = _run("adamw", [2, 2, 2], ["dp", "tp", "pp"],
                           schedule="1f1b", grad_acc=2, n_steps=1)
    p_z, _, l_z = _run("zero1_adamw", [2, 2, 2], ["dp", "tp", "pp"],
                       schedule="1f1b", grad_acc=2, n_steps=1)
    np.testing.assert_allclose(l_z, l_ref, rtol=1e-5)
    for a, b in zip(jax.tree.leaves(p_z), jax.tree.leaves(p_ref)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_zero2_matches_replicated_adamw_one_step():
    """ZeRO-2 (reduce-scatter grads + chunk-space weighted clip) must
    reproduce the replicated update: same math, different comm."""
    p_ref, _, _ = _run("adamw", [4], ["dp"], n_steps=1)
    p_z, _, _ = _run("zero2_adamw", [4], ["dp"], n_steps=1)
    for a, b in zip(jax.tree.leaves(p_z), jax.tree.leaves(p_ref)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-6, atol=1e-7)


@pytest.mark.slow
def test_zero2_matches_zero1_under_dp_tp():
    """dp x tp exercises the replication-weighted chunk-space norm: LN
    grads are replicated over tp and must count ONCE in the clip norm
    (grad_weights), or the clip scale — and every update — drifts."""
    p_1, _, l_1 = _run("zero1_adamw", [2, 2], ["dp", "tp"], n_steps=2)
    p_2, _, l_2 = _run("zero2_adamw", [2, 2], ["dp", "tp"], n_steps=2)
    np.testing.assert_allclose(l_2, l_1, rtol=1e-5)
    for a, b in zip(jax.tree.leaves(p_2), jax.tree.leaves(p_1)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-6)


@pytest.mark.slow
def test_zero2_composes_with_3d():
    p_1, _, l_1 = _run("zero1_adamw", [2, 2, 2], ["dp", "tp", "pp"],
                       n_steps=2, schedule="1f1b", grad_acc=4)
    p_2, _, l_2 = _run("zero2_adamw", [2, 2, 2], ["dp", "tp", "pp"],
                       n_steps=2, schedule="1f1b", grad_acc=4)
    np.testing.assert_allclose(l_2, l_1, rtol=1e-5)
    for a, b in zip(jax.tree.leaves(p_2), jax.tree.leaves(p_1)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-6)


@pytest.mark.slow
def test_zero2_chunk_accumulation_matches_zero1():
    """grad_accum > 1 routes ZeRO-2 through chunk-space accumulation
    (the full grad buffer never materialises across microbatches) —
    updates must match the ZeRO-1 full-tree path. One step compares
    tightly; multi-step tolerance is loose for the same reason as
    test_zero1_matches_replicated_adamw_multistep: the scatter-then-sum
    reassociation's ulp noise is amplified by Adam's rsqrt."""
    p_1, _, l_1 = _run("zero1_adamw", [4], ["dp"], n_steps=1, grad_acc=4)
    p_2, _, l_2 = _run("zero2_adamw", [4], ["dp"], n_steps=1, grad_acc=4)
    np.testing.assert_allclose(l_2, l_1, rtol=1e-5)
    for a, b in zip(jax.tree.leaves(p_2), jax.tree.leaves(p_1)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-3, atol=2e-5)

    p_1, _, l_1 = _run("zero1_adamw", [4], ["dp"], n_steps=3, grad_acc=4)
    p_2, _, l_2 = _run("zero2_adamw", [4], ["dp"], n_steps=3, grad_acc=4)
    np.testing.assert_allclose(l_2, l_1, rtol=1e-4)
    for a, b in zip(jax.tree.leaves(p_2), jax.tree.leaves(p_1)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-2, atol=2e-4)


@pytest.mark.slow
def test_zero2_chunk_accumulation_under_dp_tp():
    """Chunk accumulation + tp: per-microbatch model-axis psums must
    reproduce the accumulate-then-reduce ordering (linearity; same
    rsqrt-amplified tolerance as above)."""
    p_1, _, l_1 = _run("zero1_adamw", [2, 2], ["dp", "tp"], n_steps=1,
                       grad_acc=2)
    p_2, _, l_2 = _run("zero2_adamw", [2, 2], ["dp", "tp"], n_steps=1,
                       grad_acc=2)
    np.testing.assert_allclose(l_2, l_1, rtol=1e-5)
    for a, b in zip(jax.tree.leaves(p_2), jax.tree.leaves(p_1)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-3, atol=2e-5)

"""tools/fleet_bench.py must never rot unexecuted: the fast suite runs
the CLI end-to-end (CPU, tiny config, one replica kill) and checks the
JSON contract — in BOTH modes: thread replicas (artifacts/
fleet_r08.json) and ``--process`` replicas (fleet/proc.py, artifacts/
fleet_r12.json, where the kill is an abrupt process exit and the
migration runs off the dispatcher's write-ahead journal) — and the
bench.py staleness scanner must surface both committed artifacts the
same way it surfaces the serving/training/ft records.
"""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.join(os.path.dirname(__file__), "..")
sys.path.insert(0, REPO)
import bench  # noqa: E402

FLEET_METRIC = "fleet_gpt2_tiny_tokens_per_sec"
PROC_METRIC = "fleet_proc_gpt2_tiny_tokens_per_sec"


@pytest.mark.fast
def test_fleet_bench_smoke_cli():
    """A tiny replay — 2 replicas, burst > capacity, r0 killed at its
    2nd step — runs end-to-end on CPU and emits one well-formed JSON
    line per policy with the acceptance fields."""
    # capacity an instant burst can absorb = max_pending (2) +
    # replicas * max_dispatch (2*2) = 6 < 8 requests -> >= 2 shed,
    # deterministically, whatever the dispatcher's timing
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "fleet_bench.py"),
         "--synthetic", "--requests", "8", "--replicas", "2",
         "--policies", "least_work", "--max-new", "4",
         "--max-pending", "2", "--max-dispatch", "2",
         "--kill-at-step", "2",
         "--kill-replica", "r0", "--timeout-s", "240"],
        capture_output=True, text=True, timeout=500,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert out.returncode == 0, out.stderr[-2000:]
    rec = json.loads(out.stdout.strip().splitlines()[-1])
    assert rec["metric"] == FLEET_METRIC
    assert rec["rc"] == 0
    assert rec["unit"] == "tok/s"
    ex = rec["extras"]
    for k in ("policy", "ttft_p50_s", "ttft_p99_s", "shed_rate",
              "migrations", "replica_deaths", "restarts", "finished",
              "latency_p99_s"):
        assert k in ex, k
    # the injected kill really happened and its work still finished
    assert ex["replica_deaths"] == 1
    assert ex["migrations"] >= 1
    assert ex["finished"] == ex["accepted"]
    # the burst overflowed the bounded queue -> typed shedding, and
    # accounting is consistent
    assert ex["shed"] == ex["submitted"] - ex["accepted"]
    assert ex["shed"] >= 1


@pytest.mark.fast
def test_fleet_bench_process_smoke_cli():
    """The same tiny replay through the CROSS-PROCESS fleet: 2 spawned
    replica engines, burst > capacity, r0's process exits abruptly
    (mode='hard' chaos — no cleanup, the SIGKILL story) at its 2nd
    step. The journal migrates its in-flight work, so finished ==
    accepted even though an engine died mid-run."""
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "fleet_bench.py"),
         "--synthetic", "--process", "--requests", "8",
         "--replicas", "2", "--policies", "least_work",
         "--max-new", "4", "--max-pending", "2", "--max-dispatch", "2",
         "--kill-at-step", "2", "--kill-replica", "r0",
         "--timeout-s", "240"],
        capture_output=True, text=True, timeout=500,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert out.returncode == 0, out.stderr[-2000:]
    rec = json.loads(out.stdout.strip().splitlines()[-1])
    assert rec["metric"] == PROC_METRIC
    assert rec["rc"] == 0 and rec["unit"] == "tok/s"
    ex = rec["extras"]
    assert ex["process"] is True
    # the process really died and none of its work was lost
    assert ex["replica_deaths"] == 1
    assert ex["migrations"] >= 1
    assert ex["restarts"] >= 1
    assert ex["finished"] == ex["accepted"]
    # typed shedding under the burst, bounded queue
    assert ex["shed"] == ex["submitted"] - ex["accepted"]
    assert ex["shed"] >= 1
    # tokens are counted from the dispatcher's journal, which survives
    # the death — a live-engines-only count would undercount
    assert ex["gen_tokens"] == ex["finished"] * 4


@pytest.mark.fast
def test_committed_fleet_artifact_surfaces_in_staleness_scan():
    """The committed fleet artifact is discoverable through the same
    last_known_result scanner every other bench uses."""
    last = bench.last_known_result(metric=FLEET_METRIC)
    assert last is not None
    assert last["stale"] is True
    assert last["metric"] == FLEET_METRIC
    assert last["value"] > 0
    assert last["source"].startswith("artifacts")
    assert last["as_of"]


@pytest.mark.fast
def test_committed_fleet_artifact_proves_acceptance_scenario():
    """artifacts/fleet_r08.json documents the acceptance run PER
    POLICY: 1 of 3 replicas killed mid-trace with its work migrated
    and finished, a >capacity burst shed (typed, bounded queue), and
    p50/p99 TTFT + tok/s + shed rate + migration count reported."""
    recs = json.load(open(os.path.join(REPO, "artifacts",
                                       "fleet_r08.json")))
    by_policy = {r["extras"]["policy"]: r for r in recs
                 if r.get("metric") == FLEET_METRIC}
    assert {"least_work", "round_robin"} <= set(by_policy)
    for policy, rec in by_policy.items():
        ex = rec["extras"]
        assert rec["rc"] == 0 and rec["value"] > 0
        assert ex["replicas"] == 3
        assert ex["replica_deaths"] >= 1, policy     # chaos kill fired
        assert ex["migrations"] >= 1, policy         # work moved over
        assert ex["finished"] == ex["accepted"], policy  # none lost
        assert ex["shed"] >= 1, policy               # burst shed
        assert 0 < ex["shed_rate"] < 1, policy
        assert ex["ttft_p50_s"] > 0 and ex["ttft_p99_s"] > 0, policy
        assert ex["ttft_p99_s"] >= ex["ttft_p50_s"], policy


@pytest.mark.fast
def test_committed_process_artifact_surfaces_in_staleness_scan():
    last = bench.last_known_result(metric=PROC_METRIC)
    assert last is not None
    assert last["stale"] is True
    assert last["metric"] == PROC_METRIC
    assert last["value"] > 0
    assert last["source"].startswith("artifacts")
    assert last["as_of"]


@pytest.mark.fast
def test_committed_process_artifact_proves_acceptance_scenario():
    """artifacts/fleet_r12.json documents the PROCESS-fleet acceptance
    run per policy: 1 of 3 replica PROCESSES dead mid-trace (abrupt
    exit), its in-flight work migrated off the dispatcher's journal
    and finished (finished == accepted), the dead process restarted by
    the supervisor, and the >capacity burst shed typed — with
    shed_rate / migrations / restarts reported."""
    recs = json.load(open(os.path.join(REPO, "artifacts",
                                       "fleet_r12.json")))
    by_policy = {r["extras"]["policy"]: r for r in recs
                 if r.get("metric") == PROC_METRIC}
    assert {"least_work", "round_robin"} <= set(by_policy)
    for policy, rec in by_policy.items():
        ex = rec["extras"]
        assert rec["rc"] == 0 and rec["value"] > 0
        assert ex["process"] is True and ex["replicas"] == 3
        assert ex["replica_deaths"] >= 1, policy     # process died
        assert ex["migrations"] >= 1, policy         # journal migration
        assert ex["restarts"] >= 1, policy           # supervisor acted
        assert ex["finished"] == ex["accepted"], policy  # none lost
        assert ex["shed"] >= 1, policy
        assert 0 < ex["shed_rate"] < 1, policy
        assert ex["ttft_p99_s"] >= ex["ttft_p50_s"] > 0, policy

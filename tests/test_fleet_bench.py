"""tools/fleet_bench.py must never rot unexecuted: the fast suite runs
the CLI end-to-end (CPU, tiny config, one replica kill) and checks the
JSON contract — in BOTH modes: thread replicas (artifacts/
fleet_r08.json) and ``--process`` replicas (fleet/proc.py, artifacts/
fleet_r12.json, where the kill is an abrupt process exit and the
migration runs off the dispatcher's write-ahead journal) — and the
bench.py staleness scanner must surface both committed artifacts the
same way it surfaces the serving/training/ft records.
"""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.join(os.path.dirname(__file__), "..")
sys.path.insert(0, REPO)
import bench  # noqa: E402

FLEET_METRIC = "fleet_gpt2_tiny_tokens_per_sec"
PROC_METRIC = "fleet_proc_gpt2_tiny_tokens_per_sec"
DISAGG_METRIC = "fleet_disagg_gpt2_tiny_itl_interference"
SLO_METRIC = "fleet_slo_gpt2_tiny_burst_burn_peak"


@pytest.mark.fast
def test_fleet_bench_smoke_cli():
    """A tiny replay — 2 replicas, burst > capacity, r0 killed at its
    2nd step — runs end-to-end on CPU and emits one well-formed JSON
    line per policy with the acceptance fields."""
    # capacity an instant burst can absorb = max_pending (2) +
    # replicas * max_dispatch (2*2) = 6 < 8 requests -> >= 2 shed,
    # deterministically, whatever the dispatcher's timing
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "fleet_bench.py"),
         "--synthetic", "--requests", "8", "--replicas", "2",
         "--policies", "least_work", "--max-new", "4",
         "--max-pending", "2", "--max-dispatch", "2",
         "--kill-at-step", "2",
         "--kill-replica", "r0", "--timeout-s", "240"],
        capture_output=True, text=True, timeout=500,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert out.returncode == 0, out.stderr[-2000:]
    rec = json.loads(out.stdout.strip().splitlines()[-1])
    assert rec["metric"] == FLEET_METRIC
    assert rec["rc"] == 0
    assert rec["unit"] == "tok/s"
    ex = rec["extras"]
    for k in ("policy", "ttft_p50_s", "ttft_p99_s", "shed_rate",
              "migrations", "replica_deaths", "restarts", "finished",
              "latency_p99_s"):
        assert k in ex, k
    # the injected kill really happened and its work still finished
    assert ex["replica_deaths"] == 1
    assert ex["migrations"] >= 1
    assert ex["finished"] == ex["accepted"]
    # the burst overflowed the bounded queue -> typed shedding, and
    # accounting is consistent
    assert ex["shed"] == ex["submitted"] - ex["accepted"]
    assert ex["shed"] >= 1


@pytest.mark.fast
def test_fleet_bench_process_smoke_cli():
    """The same tiny replay through the CROSS-PROCESS fleet: 2 spawned
    replica engines, burst > capacity, r0's process exits abruptly
    (mode='hard' chaos — no cleanup, the SIGKILL story) at its 2nd
    step. The journal migrates its in-flight work, so finished ==
    accepted even though an engine died mid-run."""
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "fleet_bench.py"),
         "--synthetic", "--process", "--requests", "8",
         "--replicas", "2", "--policies", "least_work",
         "--max-new", "4", "--max-pending", "2", "--max-dispatch", "2",
         "--kill-at-step", "2", "--kill-replica", "r0",
         "--timeout-s", "240"],
        capture_output=True, text=True, timeout=500,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert out.returncode == 0, out.stderr[-2000:]
    rec = json.loads(out.stdout.strip().splitlines()[-1])
    assert rec["metric"] == PROC_METRIC
    assert rec["rc"] == 0 and rec["unit"] == "tok/s"
    ex = rec["extras"]
    assert ex["process"] is True
    # the process really died and none of its work was lost
    assert ex["replica_deaths"] == 1
    assert ex["migrations"] >= 1
    assert ex["restarts"] >= 1
    assert ex["finished"] == ex["accepted"]
    # typed shedding under the burst, bounded queue
    assert ex["shed"] == ex["submitted"] - ex["accepted"]
    assert ex["shed"] >= 1
    # tokens are counted from the dispatcher's journal, which survives
    # the death — a live-engines-only count would undercount
    assert ex["gen_tokens"] == ex["finished"] * 4


@pytest.mark.fast
def test_fleet_bench_disagg_smoke_cli():
    """A tiny --disagg replay — 1 prefill + 1 decode process vs a
    2-replica colocated fleet, one long-prefill burst probe — runs
    end-to-end on CPU and emits a well-formed interference record.
    Wall-clock ratios are NOT asserted here (2-core CI noise); the
    deterministic structural signal is: the decode pool prefilled
    warm tails only while every long prefill ran on the prefill
    pool, all via transferred (checksummed) KV chains."""
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "fleet_bench.py"),
         "--synthetic", "--disagg", "--prefill-replicas", "1",
         "--decode-replicas", "1", "--slots", "4", "--steady", "2",
         "--steady-gap-s", "0.05", "--burst-prompts", "1",
         "--burst-prompt-len", "24", "--max-new", "6",
         "--num-blocks", "64", "--block-size", "8",
         "--timeout-s", "240"],
        capture_output=True, text=True, timeout=560,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert out.returncode == 0, out.stderr[-2000:]
    rec = json.loads(out.stdout.strip().splitlines()[-1])
    assert rec["metric"] == DISAGG_METRIC
    assert rec["rc"] == 0 and rec["unit"] == "ratio"
    ex = rec["extras"]
    for k in ("colocated_interference", "disagg_itl_p99_burst_s",
              "colocated_itl_p99_burst_s", "handoffs",
              "handoff_transfers", "handoff_fallbacks",
              "disagg_pool_prefill_tokens"):
        assert k in ex, k
    # nothing lost, every steady request handed off with its chain
    assert ex["finished"] == ex["accepted"]
    assert ex["colocated_finished"] == ex["colocated_accepted"]
    assert ex["handoff_transfers"] == ex["handoffs"] == 2
    assert ex["handoff_fallbacks"] == 0
    # structural isolation: the burst's long prefill ran on the
    # prefill pool; the decode pool prefilled warm-hit tails only
    pool_tokens = ex["disagg_pool_prefill_tokens"]
    assert pool_tokens["decode"] <= 2 * ex["accepted"]
    assert pool_tokens["prefill"] >= 24


@pytest.mark.fast
def test_committed_fleet_artifact_surfaces_in_staleness_scan():
    """The committed fleet artifact is discoverable through the same
    last_known_result scanner every other bench uses."""
    last = bench.last_known_result(metric=FLEET_METRIC)
    assert last is not None
    assert last["stale"] is True
    assert last["metric"] == FLEET_METRIC
    assert last["value"] > 0
    assert last["source"].startswith("artifacts")
    assert last["as_of"]


@pytest.mark.fast
def test_committed_fleet_artifact_proves_acceptance_scenario():
    """artifacts/fleet_r08.json documents the acceptance run PER
    POLICY: 1 of 3 replicas killed mid-trace with its work migrated
    and finished, a >capacity burst shed (typed, bounded queue), and
    p50/p99 TTFT + tok/s + shed rate + migration count reported."""
    recs = json.load(open(os.path.join(REPO, "artifacts",
                                       "fleet_r08.json")))
    by_policy = {r["extras"]["policy"]: r for r in recs
                 if r.get("metric") == FLEET_METRIC}
    assert {"least_work", "round_robin"} <= set(by_policy)
    for policy, rec in by_policy.items():
        ex = rec["extras"]
        assert rec["rc"] == 0 and rec["value"] > 0
        assert ex["replicas"] == 3
        assert ex["replica_deaths"] >= 1, policy     # chaos kill fired
        assert ex["migrations"] >= 1, policy         # work moved over
        assert ex["finished"] == ex["accepted"], policy  # none lost
        assert ex["shed"] >= 1, policy               # burst shed
        assert 0 < ex["shed_rate"] < 1, policy
        assert ex["ttft_p50_s"] > 0 and ex["ttft_p99_s"] > 0, policy
        assert ex["ttft_p99_s"] >= ex["ttft_p50_s"], policy


@pytest.mark.fast
def test_committed_process_artifact_surfaces_in_staleness_scan():
    last = bench.last_known_result(metric=PROC_METRIC)
    assert last is not None
    assert last["stale"] is True
    assert last["metric"] == PROC_METRIC
    assert last["value"] > 0
    assert last["source"].startswith("artifacts")
    assert last["as_of"]


@pytest.mark.fast
def test_committed_disagg_artifact_surfaces_in_staleness_scan():
    last = bench.last_known_result(metric=DISAGG_METRIC)
    assert last is not None
    assert last["stale"] is True
    assert last["metric"] == DISAGG_METRIC
    assert last["value"] > 0
    assert last["source"].startswith("artifacts")
    assert last["as_of"]


@pytest.mark.fast
def test_committed_disagg_artifact_proves_acceptance_scenario():
    """artifacts/fleet_r16.json documents the interference A/B at
    matched load: on the disaggregated side a long-prefill burst
    moves decode ITL p99 by at most the pinned bound over its own
    no-burst baseline AND the burst-time decode ITL p99 beats the
    colocated fleet's under the same burst on the same box (the
    matched-load interference comparison — the self-ratios are not
    comparable across modes on shared cores because disaggregation
    also improves the NO-burst baseline, see run_disagg);
    structurally, every long prefill ran on the prefill pool (int8
    chains transferred, zero fallbacks, nothing lost)."""
    recs = json.load(open(os.path.join(REPO, "artifacts",
                                       "fleet_r16.json")))
    rec = next(r for r in recs if r.get("metric") == DISAGG_METRIC)
    ex = rec["extras"]
    assert rec["rc"] == 0
    # pinned interference bound on the disaggregated side
    assert 0 < rec["value"] <= 2.5
    # the matched-load win: under the SAME burst, decode ITL p99 is
    # lower on the disaggregated side — and its clean-baseline p99 is
    # no worse either
    assert ex["burst_itl_p99_vs_colocated"] < 1.0
    assert (ex["disagg_itl_p99_burst_s"]
            < ex["colocated_itl_p99_burst_s"])
    assert ex["baseline_itl_p99_vs_colocated"] <= 1.0
    # fault-tolerant handoff did its job: every steady request's
    # chain transferred (int8 — 4x smaller frames), zero fallbacks,
    # nothing lost on either side
    assert ex["kv_dtype"] == "int8"
    assert ex["handoff_transfers"] == ex["handoffs"] == ex["steady"]
    assert ex["handoff_fallbacks"] == 0
    assert ex["finished"] == ex["accepted"]
    assert ex["colocated_finished"] == ex["colocated_accepted"]
    # structural isolation: decode pool prefilled warm tails only;
    # the burst's long prefills all landed on the prefill pool
    pool_tokens = ex["disagg_pool_prefill_tokens"]
    assert pool_tokens["decode"] <= 2 * ex["accepted"]
    assert (pool_tokens["prefill"]
            >= ex["burst_prompts"] * ex["burst_prompt_len"])


@pytest.mark.fast
def test_committed_process_artifact_proves_acceptance_scenario():
    """artifacts/fleet_r12.json documents the PROCESS-fleet acceptance
    run per policy: 1 of 3 replica PROCESSES dead mid-trace (abrupt
    exit), its in-flight work migrated off the dispatcher's journal
    and finished (finished == accepted), the dead process restarted by
    the supervisor, and the >capacity burst shed typed — with
    shed_rate / migrations / restarts reported."""
    recs = json.load(open(os.path.join(REPO, "artifacts",
                                       "fleet_r12.json")))
    by_policy = {r["extras"]["policy"]: r for r in recs
                 if r.get("metric") == PROC_METRIC}
    assert {"least_work", "round_robin"} <= set(by_policy)
    for policy, rec in by_policy.items():
        ex = rec["extras"]
        assert rec["rc"] == 0 and rec["value"] > 0
        assert ex["process"] is True and ex["replicas"] == 3
        assert ex["replica_deaths"] >= 1, policy     # process died
        assert ex["migrations"] >= 1, policy         # journal migration
        assert ex["restarts"] >= 1, policy           # supervisor acted
        assert ex["finished"] == ex["accepted"], policy  # none lost
        assert ex["shed"] >= 1, policy
        assert 0 < ex["shed_rate"] < 1, policy
        assert ex["ttft_p99_s"] >= ex["ttft_p50_s"] > 0, policy


@pytest.mark.fast
def test_fleet_bench_slo_smoke_cli():
    """A tiny --slo replay — 1 prefill + 1 decode process vs a
    2-replica colocated fleet, objectives calibrated off the clean
    replays, burst replayed under the armed SLO engine + signal bus —
    runs end-to-end on CPU and emits a well-formed judgment record.
    Breaches are NOT asserted here (at smoke scale the burst rarely
    outruns the calibrated targets on a quiet box); the contract under
    test is the machinery: calibration happened, the engine evaluated
    without a NaN or a crash, the planner ledger is present, and
    nothing was lost."""
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "fleet_bench.py"),
         "--synthetic", "--slo", "--prefill-replicas", "1",
         "--decode-replicas", "1", "--slots", "4", "--steady", "2",
         "--steady-gap-s", "0.05", "--burst-prompts", "1",
         "--burst-prompt-len", "24", "--max-new", "6",
         "--num-blocks", "64", "--block-size", "8",
         "--slo-recovery-wait", "2", "--timeout-s", "240"],
        capture_output=True, text=True, timeout=560,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert out.returncode == 0, out.stderr[-2000:]
    rec = json.loads(out.stdout.strip().splitlines()[-1])
    assert rec["metric"] == SLO_METRIC
    assert rec["rc"] == 0 and rec["unit"] == "x"
    ex = rec["extras"]
    for k in ("targets", "burn_threshold", "disagg_breached",
              "disagg_breach_pools", "disagg_burn_fast_peak",
              "colocated_breached", "colocated_burn_fast_peak",
              "recommendations", "disagg_baseline_ttft_p99_s",
              "colocated_baseline_itl_p99_s"):
        assert k in ex, k
    # the calibrated contract is real numbers, not NaN at low traffic
    assert ex["targets"]["ttft_p99_s"] > 0
    assert ex["targets"]["itl_p99_s"] > 0
    for peak in (ex["disagg_burn_fast_peak"],
                 ex["colocated_burn_fast_peak"]):
        for v in peak.values():
            assert v == v and v >= 0.0          # NaN-free, bounded below
    # judged, not perturbed: nothing lost on either side
    assert ex["finished"] == ex["accepted"]
    assert ex["colocated_finished"] == ex["colocated_accepted"]
    assert ex["handoff_fallbacks"] == 0


@pytest.mark.fast
def test_committed_slo_artifact_surfaces_in_staleness_scan():
    last = bench.last_known_result(metric=SLO_METRIC)
    assert last is not None
    assert last["stale"] is True
    assert last["metric"] == SLO_METRIC
    assert last["value"] > 0
    assert last["source"].startswith("artifacts")
    assert last["as_of"]


@pytest.mark.fast
def test_committed_slo_artifact_proves_acceptance_scenario():
    """artifacts/slo_r17.json documents the judgment-layer acceptance
    replay (ISSUE 13): one objective set calibrated off the clean
    replays, then the fleet_r16 interference burst under the armed SLO
    engine. On the disaggregated side the burst trips the fast+slow
    TTFT burn windows (both >= threshold — the SRE multi-window gate),
    the breach event names the PREFILL pool, the observe-only planner
    recommends converting a decode replica to prefill during the
    breach and recommends the revert after, and the objective recovers
    cleanly. The colocated fleet, judged against the SAME contract,
    burns the ITL budget the disaggregated fleet holds — monolithic
    prefills stall decode, the DistServe goodput argument as typed
    events."""
    recs = json.load(open(os.path.join(REPO, "artifacts",
                                       "slo_r17.json")))
    rec = next(r for r in recs if r.get("metric") == SLO_METRIC)
    ex = rec["extras"]
    assert rec["rc"] == 0
    thresh = ex["burn_threshold"]
    # the burst tripped the disaggregated TTFT objective: fast AND
    # slow windows at/above threshold (the value is the fast peak)
    assert rec["value"] >= thresh
    assert "ttft_p99" in ex["disagg_breached"]
    for burn in ex["disagg_breach_burns"]:
        assert burn["burn_fast"] >= thresh
        assert burn["burn_slow"] >= thresh
    # attribution: a TTFT breach names the prefill pool
    assert ex["disagg_breach_pools"]["ttft_p99"] == "prefill"
    # the breach recovered once the burst drained (fast window clear)
    assert "ttft_p99" in ex["disagg_recovered"]
    assert ex["disagg_still_breaching"] == []
    # the observe-only planner: decode->prefill during the breach,
    # the revert after recovery — recommendations, no actuation
    recs_ = ex["recommendations"]
    assert any(r["direction"] == "decode_to_prefill"
               and not r["revert"] for r in recs_)
    assert any(r["revert"] for r in recs_)
    # the DistServe verdict: judged against the SAME objective set,
    # the colocated fleet breaches ITL where the disaggregated one
    # holds (the dedicated decode pool never runs a monolithic
    # prefill)
    assert "itl_p99" in ex["colocated_breached"]
    assert "itl_p99" not in ex["disagg_breached"]
    assert (ex["colocated_burn_fast_peak"]["itl_p99"] >= thresh)
    # judged, not perturbed: the replay itself lost nothing
    assert ex["finished"] == ex["accepted"]
    assert ex["colocated_finished"] == ex["colocated_accepted"]
    assert ex["handoffs"] == ex["steady"]
    assert ex["handoff_fallbacks"] == 0

"""5D parallelism EXECUTION test (not just a claim): GPT-2-MoE trained
with all five axes active — dp x tp x pp x sp x ep = 2x2x2x2x2 — to
golden parity with single-device math.

Needs 32 virtual devices, so it runs in its own subprocess (the main
suite's conftest pins 8); the worker does the asserts and writes a JSON
marker on success. The reference's "Towards 5D Parallelism" docstring
ships 3 axes (SURVEY.md §2.2); this runs five.
"""

import json
import os
import subprocess
import sys

import pytest


def test_5d_gpt2_moe_1f1b_matches_single_device(tmp_path):
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # worker sets its own 32-device flag
    env.pop("JAX_PLATFORMS", None)
    env["PYTHONPATH"] = os.getcwd()

    worker = os.path.join(os.path.dirname(__file__), "_worker_5d.py")
    out = str(tmp_path / "w5d.json")
    try:
        res = subprocess.run(
            [sys.executable, worker, out],
            env=env, capture_output=True, timeout=540)
    except subprocess.TimeoutExpired:
        pytest.fail("5d worker timed out")
    assert res.returncode == 0, (
        f"5d worker failed:\n{res.stdout.decode(errors='replace')[-2000:]}"
        f"\n{res.stderr.decode(errors='replace')[-4000:]}")
    with open(out) as f:
        assert json.load(f)["ok"]

"""DP golden tests: sharded training step == single-device step on the
global batch (the contract the reference's test_data_parallel.py:45-126
states but cannot actually run — SURVEY §2.2/§4)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from quintnet_tpu.core.mesh import mesh_from_sizes
from quintnet_tpu.models.vit import ViTConfig, cross_entropy_loss, vit_apply, vit_init
from quintnet_tpu.parallel.dp import accumulate_grads, make_dp_train_step

CFG = ViTConfig(image_size=14, patch_size=7, in_channels=1, hidden_dim=16,
                depth=2, num_heads=2, num_classes=10)


def _data(n=16):
    x = jax.random.normal(jax.random.key(1), (n, 14, 14, 1))
    y = jax.random.randint(jax.random.key(2), (n,), 0, 10)
    return x, y


def _loss_fn(params, batch):
    x, y = batch
    return cross_entropy_loss(vit_apply(params, x, CFG), y)


def test_dp_step_matches_single_device():
    mesh = mesh_from_sizes(dp=4)
    params = vit_init(jax.random.key(0), CFG)
    # SGD so the param comparison reflects grad equality directly (Adam's
    # first step is ~sign(g), which amplifies float reduction-order noise)
    opt = optax.sgd(0.1)
    opt_state = opt.init(params)
    batch = _data(16)

    # single-device reference on the full global batch (computed first:
    # the dp step donates its inputs)
    loss_ref, g = jax.value_and_grad(_loss_fn)(params, batch)
    updates, s_ref = opt.update(g, opt.init(params), params)
    p_ref = optax.apply_updates(params, updates)

    dp_step = make_dp_train_step(mesh, _loss_fn, opt)
    p_dp, s_dp, loss_dp = dp_step(params, opt_state, batch)

    np.testing.assert_allclose(float(loss_dp), float(loss_ref), rtol=1e-5)
    for a, b in zip(jax.tree.leaves(p_dp), jax.tree.leaves(p_ref)):
        np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-6)


def test_dp_with_grad_accumulation_matches():
    """grad_acc=2: average over micro-batches then step — the intended
    reference semantics (step at accumulation end, not mid-way)."""
    mesh = mesh_from_sizes(dp=2)
    params = vit_init(jax.random.key(0), CFG)
    opt = optax.sgd(0.1)
    batch = _data(16)

    loss_ref, g = jax.value_and_grad(_loss_fn)(params, batch)
    p_ref = optax.apply_updates(params, opt.update(g, opt.init(params), params)[0])

    step = make_dp_train_step(mesh, _loss_fn, opt, grad_accum_steps=2)
    p_dp, _, loss_dp = step(params, opt.init(params), batch)

    np.testing.assert_allclose(float(loss_dp), float(loss_ref), rtol=1e-5)
    for a, b in zip(jax.tree.leaves(p_dp), jax.tree.leaves(p_ref)):
        np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-6)


def test_accumulate_grads_equals_full_batch():
    params = vit_init(jax.random.key(0), CFG)
    batch = _data(8)
    loss1, g1 = jax.value_and_grad(_loss_fn)(params, batch)
    loss2, g2 = accumulate_grads(_loss_fn, params, batch, n_micro=4)
    np.testing.assert_allclose(float(loss1), float(loss2), rtol=1e-5)
    for a, b in zip(jax.tree.leaves(g1), jax.tree.leaves(g2)):
        np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-6)


def test_dp_grads_identical_across_replicas():
    """Cross-rank parameter identity after a step (reference
    test_data_parallel.py cross-rank grad identity check)."""
    from jax.sharding import PartitionSpec as P
    from quintnet_tpu.core import collectives as cc

    mesh = mesh_from_sizes(dp=4)
    params = vit_init(jax.random.key(0), CFG)
    batch = _data(16)

    def per_device_grads(p, b):
        g = jax.grad(_loss_fn)(p, b)
        g = cc.tree_all_reduce_mean(g, "dp")
        # return the dp-local copy stacked so we can compare across ranks
        return jax.tree.map(lambda x: x[None], g)

    g = cc.shard_map_fn(per_device_grads, mesh,
                        in_specs=(P(), P("dp")),
                        out_specs=P("dp"))(params, batch)
    for leaf in jax.tree.leaves(g):
        for i in range(1, 4):
            np.testing.assert_allclose(leaf[0], leaf[i], rtol=1e-6)

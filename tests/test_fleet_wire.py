"""Wire-serialization goldens (quintnet_tpu/fleet/wire.py).

THE contract: everything a cross-process migration needs round-trips
through versioned JSON payloads BIT-exactly — prompt/generated tokens,
the evolved PRNG key (raw dtype bytes, not a float detour), the
adapter binding, the remaining deadline — and a payload from a future
(or corrupt) version is rejected with an actionable error naming both
versions, never a KeyError three fields deep. Plus the framing layer
(length-prefixed JSON over a socket) and the end-to-end golden: an
engine's exported progress serialized to JSON, parsed back, and
restored on a second engine continues token-identically.
"""

import json
import socket
import threading

import jax
import numpy as np
import pytest

from quintnet_tpu.fleet import Overloaded
from quintnet_tpu.fleet import wire
from quintnet_tpu.models.gpt2 import GPT2Config, gpt2_init
from quintnet_tpu.models.gpt2_generate import gpt2_generate
from quintnet_tpu.serve import (DeadlineExceeded, ServeEngine,
                                SpecConfig, gpt2_family)
from quintnet_tpu.serve.scheduler import Request, RequestProgress

CFG = GPT2Config.tiny(n_layer=2)


@pytest.fixture(scope="module")
def params():
    return gpt2_init(jax.random.key(0), CFG)


def _progress(**over):
    base = dict(
        rid=7, prompt=np.asarray([3, 1, 4, 1, 5], np.int32),
        generated=[9, 2, 6], key_data=np.asarray(
            jax.random.key_data(jax.random.key(11))),
        max_new_tokens=12, priority=2, preemptions=1,
        adapter_id="tenant-a", deadline_s=3.25)
    base.update(over)
    return RequestProgress(**base)


class TestProgressRoundTrip:
    def test_all_fields_survive_json(self):
        p = _progress()
        # through actual JSON text — what the socket carries
        q = wire.progress_from_wire(
            json.loads(json.dumps(wire.progress_to_wire(p))))
        assert q.rid == 7 and q.max_new_tokens == 12
        assert q.priority == 2 and q.preemptions == 1
        assert q.adapter_id == "tenant-a"
        assert q.deadline_s == pytest.approx(3.25)
        assert q.generated == [9, 2, 6]
        np.testing.assert_array_equal(q.prompt, p.prompt)
        assert q.prompt.dtype == np.int32
        # the PRNG key is BIT-exact, dtype preserved (b64 raw bytes)
        np.testing.assert_array_equal(q.key_data, p.key_data)
        assert q.key_data.dtype == p.key_data.dtype

    def test_optional_fields_none(self):
        p = _progress(adapter_id=None, deadline_s=None, key_data=None)
        q = wire.progress_from_wire(wire.progress_to_wire(p))
        assert q.adapter_id is None and q.deadline_s is None
        assert q.key_data is None

    def test_unknown_version_rejected_actionably(self):
        payload = wire.progress_to_wire(_progress())
        payload["v"] = 99
        with pytest.raises(wire.WireVersionError,
                           match="version 99.*not supported.*upgrade"):
            wire.progress_from_wire(payload)

    def test_missing_version_rejected(self):
        payload = wire.progress_to_wire(_progress())
        del payload["v"]
        with pytest.raises(wire.WireVersionError, match="None"):
            wire.progress_from_wire(payload)

    def test_wrong_kind_rejected(self):
        payload = wire.progress_to_wire(_progress())
        payload["kind"] = "request"
        with pytest.raises(wire.WireError, match="wrong decoder"):
            wire.progress_from_wire(payload)

    def test_missing_field_named_not_keyerror(self):
        payload = wire.progress_to_wire(_progress())
        del payload["key_data"]
        with pytest.raises(wire.WireError,
                           match=r"missing required field.*key_data"):
            wire.progress_from_wire(payload)

    def test_malformed_array_payload(self):
        payload = wire.progress_to_wire(_progress())
        payload["prompt"] = {"dtype": "int32", "b64": "!!!"}
        with pytest.raises(wire.WireError, match="malformed array"):
            wire.progress_from_wire(payload)


class TestRequestRoundTrip:
    def test_submit_payload_survives(self):
        req = Request(rid=4, prompt=np.asarray([5, 6, 7], np.int32),
                      max_new_tokens=9, priority=1,
                      adapter_id="tenant-b")
        req.key_data = np.asarray(
            jax.random.key_data(jax.random.key(3)))
        req.generated = [11, 12]
        out, deadline_s = wire.request_from_wire(json.loads(
            json.dumps(wire.request_to_wire(req, deadline_s=1.5))))
        assert out.rid == 4 and out.max_new_tokens == 9
        assert out.priority == 1 and out.adapter_id == "tenant-b"
        assert out.generated == [11, 12]
        assert deadline_s == pytest.approx(1.5)
        np.testing.assert_array_equal(out.prompt, req.prompt)
        np.testing.assert_array_equal(out.key_data, req.key_data)

    def test_version_gate(self):
        req = Request(rid=0, prompt=np.asarray([1], np.int32),
                      max_new_tokens=1)
        payload = wire.request_to_wire(req)
        payload["v"] = 2
        with pytest.raises(wire.WireVersionError):
            wire.request_from_wire(payload)


class TestErrorRoundTrip:
    @pytest.mark.parametrize("reason", ["queue_full", "deadline",
                                        "shutdown"])
    def test_overloaded_keeps_reason(self, reason):
        e = wire.error_from_wire(json.loads(json.dumps(
            wire.error_to_wire(Overloaded(reason, "nope")))))
        assert isinstance(e, Overloaded)
        assert e.reason == reason and "nope" in str(e)

    def test_deadline_exceeded_keeps_progress_count(self):
        e = wire.error_from_wire(wire.error_to_wire(
            DeadlineExceeded("late", rid=5, generated=7)))
        assert isinstance(e, DeadlineExceeded)
        assert e.generated == 7 and "late" in str(e)

    def test_value_and_key_errors(self):
        assert isinstance(
            wire.error_from_wire(wire.error_to_wire(
                ValueError("bad prompt"))), ValueError)
        assert isinstance(
            wire.error_from_wire(wire.error_to_wire(
                KeyError("tenant-z"))), KeyError)

    def test_wire_error_keeps_its_type(self):
        """A WireError must NOT degrade to plain ValueError across the
        RPC reply: the handoff retry loop treats WireError (damaged
        frame — transient, re-export) differently from ValueError
        (geometry mismatch / evicted chain — permanent, straight to
        the local-re-prefill fallback)."""
        e = wire.error_from_wire(json.loads(json.dumps(
            wire.error_to_wire(wire.WireError("checksum mismatch")))))
        assert isinstance(e, wire.WireError)
        assert "checksum" in str(e)


class TestKVChainFrames:
    """The disaggregated handoff payload: a published chain's blocks
    (+ per-block scales under int8) round-trip bit-exactly through
    JSON text, and EVERY corruption mode — flipped payload bits,
    damaged geometry, missing fields — surfaces as a typed
    :class:`WireError`, never wrong KV silently cached."""

    def _chain(self, policy="int8"):
        from quintnet_tpu.serve.kv_pool import KVPool

        pool = KVPool(n_layers=2, n_kv_heads=2, head_dim=4,
                      block_size=4, num_blocks=8, policy=policy)
        toks = np.arange(10, dtype=np.int32)
        blocks = pool.acquire(3)
        k = pool.k
        for i, b in enumerate(blocks):
            k = k.at[:, b * 4:(b + 1) * 4].set(i + 1)
        if pool.policy.scaled:
            ks = pool.k_scale
            for i, b in enumerate(blocks):
                ks = ks.at[:, b].set(0.25 * (i + 1))
            pool.update(k, pool.v, ks, pool.v_scale)
        else:
            pool.update(k, pool.v)
        pool.publish(toks, blocks, 10)
        pool.release(blocks)
        return pool, pool.export_chain(toks), toks

    def test_round_trip_through_json_bit_exact(self):
        from quintnet_tpu.serve.kv_pool import KVPool

        _pool, chain, toks = self._chain("int8")
        payload = json.loads(json.dumps(
            wire.kv_chain_to_wire(chain, namespace="tenant-a")))
        got, ns = wire.kv_chain_from_wire(payload)
        assert ns == "tenant-a"
        assert got["n_tokens"] == 10 and got["policy"] == "int8"
        np.testing.assert_array_equal(got["tokens"], toks)
        for a, b in zip(chain["blocks"], got["blocks"]):
            assert a["fill"] == b["fill"]
            np.testing.assert_array_equal(a["k"], b["k"])
            assert b["k"].dtype == np.int8   # int8 ships as int8
            np.testing.assert_array_equal(a["k_scale"], b["k_scale"])
        # and the decoded chain actually imports + hits
        dst = KVPool(n_layers=2, n_kv_heads=2, head_dim=4,
                     block_size=4, num_blocks=8, policy="int8")
        assert dst.import_chain(got, namespace=ns) == 10
        assert dst.lookup(toks, max_tokens=8,
                          namespace=ns).cached_tokens == 8

    def test_flipped_payload_bit_fails_checksum_typed(self):
        _pool, chain, _toks = self._chain()
        payload = wire.kv_chain_to_wire(chain)
        b64 = payload["blocks"][1]["v"]["b64"]
        flip = "A" if b64[0] != "A" else "B"
        payload["blocks"][1]["v"]["b64"] = flip + b64[1:]
        with pytest.raises(wire.WireError, match="checksum mismatch"):
            wire.kv_chain_from_wire(payload)

    def test_flipped_geometry_fails_checksum_typed(self):
        _pool, chain, _toks = self._chain()
        payload = wire.kv_chain_to_wire(chain)
        payload["n_kv_heads"] = 7
        with pytest.raises(wire.WireError, match="checksum mismatch"):
            wire.kv_chain_from_wire(payload)

    def test_missing_field_named_not_keyerror(self):
        _pool, chain, _toks = self._chain()
        payload = wire.kv_chain_to_wire(chain)
        del payload["n_tokens"]
        with pytest.raises(wire.WireError, match="n_tokens"):
            wire.kv_chain_from_wire(payload)

    def test_null_fill_is_typed_not_typeerror(self):
        """A buggy peer's null fill checksums CONSISTENTLY on its side
        (it hashed the same null), so the frame reaches the walk — it
        must surface as a typed WireError, never a TypeError that
        escapes the import handler and reads as a replica death."""
        _pool, chain, _toks = self._chain()
        payload = wire.kv_chain_to_wire(chain)
        payload["blocks"][0]["fill"] = None
        with pytest.raises(wire.WireError, match="fill"):
            wire.kv_chain_from_wire(payload)
        # string fill is the sibling case (ValueError path)
        payload["blocks"][0]["fill"] = "x"
        with pytest.raises(wire.WireError, match="fill"):
            wire.kv_chain_from_wire(payload)

    def test_null_geometry_is_typed_not_typeerror(self):
        """Same vector on the header ints: the peer's checksum covers
        its own null, so int(None) is reachable post-verification."""
        _pool, chain, _toks = self._chain()
        payload = wire.kv_chain_to_wire(chain)
        payload["n_tokens"] = None
        payload["crc32"] = wire.kv_chain_checksum(payload)
        with pytest.raises(wire.WireError, match="geometry"):
            wire.kv_chain_from_wire(payload)

    def test_wire_size_estimate_is_conservative(self):
        """The exporter's pre-ship size check must OVER-estimate: a
        frame it approves can never trip the receiver's
        MAX_FRAME_BYTES guard (which would read as a dead connection
        and kill a healthy replica)."""
        _pool, chain, _toks = self._chain("int8")
        payload = wire.kv_chain_to_wire(chain, namespace="tenant-a")
        actual = len(json.dumps(payload,
                                separators=(",", ":")).encode())
        assert wire.kv_chain_wire_size(payload) >= actual
        assert wire.kv_chain_fits(payload)   # tiny chain fits

    def test_geometry_mismatch_rejected_at_import(self):
        from quintnet_tpu.serve.kv_pool import KVPool

        _pool, chain, _toks = self._chain("f32")
        dst = KVPool(n_layers=2, n_kv_heads=2, head_dim=4,
                     block_size=4, num_blocks=8, policy="int8")
        with pytest.raises(ValueError, match="does not match this pool"):
            dst.import_chain(chain)


class TestWireFaultsAreReplicaDeathNotFleetDeath:
    """Satellite contract: a truncated frame mid-body, flipped-bit
    payload bytes and an oversized length prefix all surface as typed
    ``ConnectionClosed``/``WireError`` WITH THE PEER NAMED — never a
    raw ``struct.error``/``KeyError`` — and the dispatcher's reader
    treats them as the death of THAT replica, not of the fleet."""

    def test_truncated_frame_mid_body_names_peer(self):
        a, b = socket.socketpair()
        try:
            data = json.dumps({"t": "hb"}).encode()
            a.sendall(len(data).to_bytes(4, "big") + data[:3])
            a.close()
            with pytest.raises(wire.ConnectionClosed,
                               match=r"'decode0'.*mid-frame"):
                wire.recv_frame(b, peer="decode0")
        finally:
            b.close()

    def test_oversized_length_prefix_names_peer(self):
        a, b = socket.socketpair()
        try:
            a.sendall((wire.MAX_FRAME_BYTES + 1).to_bytes(4, "big"))
            with pytest.raises(wire.WireError,
                               match=r"'prefill0'.*MAX_FRAME_BYTES"):
                wire.recv_frame(b, peer="prefill0")
        finally:
            a.close()
            b.close()

    def test_flipped_bits_in_body_are_typed_not_decode_crash(self):
        a, b = socket.socketpair()
        try:
            garbage = b"\xff\xfe{not json"
            a.sendall(len(garbage).to_bytes(4, "big") + garbage)
            with pytest.raises(wire.WireError,
                               match=r"'p1'.*not valid JSON"):
                wire.recv_frame(b, peer="p1")
        finally:
            a.close()
            b.close()

    def test_reader_thread_turns_wire_fault_into_replica_death(self):
        """Drive the REAL ``ProcReplica._read_loop`` over a socketpair
        feeding garbage: the loop must swallow the typed fault, abort
        pending RPCs, and report the replica's death to the fleet —
        the dispatcher thread never sees the exception."""
        from quintnet_tpu.fleet.proc import ProcReplica

        class FakeFleet:
            def __init__(self):
                self.dead = []
                self.frames = []

            def _on_frame(self, rep, frame):
                self.frames.append(frame)

            def _on_conn_lost(self, rep):
                self.dead.append(rep.name)

        a, b = socket.socketpair()
        rep = ProcReplica.__new__(ProcReplica)   # no spawn
        rep.name = "decode1"
        rep.fleet = FakeFleet()
        rep.sock = b
        rep._pending = {}
        rep._send_lock = threading.Lock()
        ev = threading.Event()
        rep._pending[1] = (ev, {})               # an in-flight RPC
        try:
            t = threading.Thread(target=rep._read_loop, daemon=True)
            t.start()
            # one good frame, then flipped-bit garbage
            wire.send_frame(a, {"t": "hb", "steps": 1})
            garbage = b"\x00garbage\xff"
            a.sendall(len(garbage).to_bytes(4, "big") + garbage)
            t.join(timeout=10.0)
            assert not t.is_alive(), "reader wedged on a wire fault"
            # the good frame was processed, the fault became a DEATH
            assert rep.fleet.frames == [{"t": "hb", "steps": 1}]
            assert rep.fleet.dead == ["decode1"]
            # pending RPCs were aborted, not left to time out
            assert ev.is_set() and rep._pending == {}
        finally:
            a.close()
            b.close()


class TestFraming:
    def test_frames_round_trip_over_a_socket(self):
        a, b = socket.socketpair()
        try:
            frames = [{"t": "hb", "steps": 3},
                      {"t": "submit",
                       "progress": wire.progress_to_wire(_progress())}]

            def sender():
                for f in frames:
                    wire.send_frame(a, f)
                a.close()

            t = threading.Thread(target=sender)
            t.start()
            got = [wire.recv_frame(b), wire.recv_frame(b)]
            assert got[0] == {"t": "hb", "steps": 3}
            q = wire.progress_from_wire(got[1]["progress"])
            assert q.adapter_id == "tenant-a"
            with pytest.raises(wire.ConnectionClosed):
                wire.recv_frame(b)      # peer gone == EOF, typed
            t.join()
        finally:
            b.close()

    def test_corrupt_length_prefix_rejected(self):
        a, b = socket.socketpair()
        try:
            a.sendall((wire.MAX_FRAME_BYTES + 1).to_bytes(4, "big"))
            with pytest.raises(wire.WireError, match="length"):
                wire.recv_frame(b)
        finally:
            a.close()
            b.close()


class TestCrossEngineWireGolden:
    """The payload actually does its job: progress exported from one
    engine, pushed through JSON text, restored on a FRESH engine,
    continues token-identically — sampled traffic, spec-enabled
    exporter (whose progress must carry committed tokens only), and
    the deadline budget re-anchored on the restorer's clock."""

    def test_export_json_restore_token_identical(self, params, rng):
        def make(spec=None):
            return ServeEngine(gpt2_family(CFG), params, max_slots=2,
                               block_size=4, num_blocks=32,
                               max_seq_len=40, temperature=0.8,
                               top_k=5, spec=spec)

        src = make(spec=SpecConfig())   # exporter speculates
        prompts = [np.asarray(rng.integers(0, CFG.vocab_size, (n,)),
                              np.int32) for n in (5, 7)]
        keys = [jax.random.key(40 + i) for i in range(2)]
        rids = [src.submit(p, 16, key=k, deadline_s=120.0)
                for p, k in zip(prompts, keys)]
        for _ in range(5):
            src.step()
        payloads = [json.loads(json.dumps(wire.progress_to_wire(p)))
                    for p in src.export_progress()]
        assert payloads, "exporter finished too fast to export"
        dst = make()
        out = {}
        for payload in payloads:
            prog = wire.progress_from_wire(payload)
            # spec drafts never leak: committed tokens only
            assert len(prog.generated) < prog.max_new_tokens
            assert prog.deadline_s is not None
            assert 0 < prog.deadline_s <= 120.0
            out[prog.rid] = dst.restore_progress(prog)
        dst.run(max_steps=500)
        for rid, p, k in zip(rids, prompts, keys):
            oracle = np.asarray(gpt2_generate(
                params, p[None], CFG, max_new_tokens=16,
                temperature=0.8, top_k=5, key=k)[0])
            if rid in out:
                np.testing.assert_array_equal(dst.result(out[rid]),
                                              oracle)
            else:   # finished before the export — still golden
                np.testing.assert_array_equal(src.result(rid), oracle)

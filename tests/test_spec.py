"""Speculative-decoding goldens (quintnet_tpu/serve/spec.py).

THE contract: speculation is a pure latency optimization — spec-on
output is BIT-identical to spec-off output for every request, greedy
AND sampled, under preemption, with the prefix cache on, across
migration, for both model families. Plus the operational invariants:
tentative blocks are committed-or-rolled-back within the step that
acquired them (published chains never observe draft slots), the PRNG
split chain advances once per COMMITTED token only, and the bounded-
compile promise extends to <= prefill buckets + verify buckets + 1
decode program.
"""

import jax
import numpy as np
import pytest

from quintnet_tpu.analysis.specs import verify_buckets
from quintnet_tpu.models.gpt2 import GPT2Config, gpt2_init
from quintnet_tpu.models.gpt2_generate import gpt2_generate
from quintnet_tpu.serve import (KVPool, NgramDrafter, ServeEngine,
                                SpecConfig, gpt2_family)

CFG = GPT2Config.tiny(n_layer=2)


@pytest.fixture(scope="module")
def params():
    return gpt2_init(jax.random.key(0), CFG)


# params whose greedy dynamics settle into long repetitive runs (so
# acceptance-dependent assertions have something to accept) — verified
# behaviour of this (init key, n_positions) pair, cf. serve_r10 notes
CFG_REP = GPT2Config.tiny(n_layer=2, n_positions=256)


@pytest.fixture(scope="module")
def rep_params():
    return gpt2_init(jax.random.key(1), CFG_REP)


def _engine(params, cfg=CFG, spec=None, **kw):
    kw.setdefault("max_slots", 3)
    kw.setdefault("block_size", 4)
    kw.setdefault("num_blocks", 48)
    kw.setdefault("max_seq_len", 40)
    return ServeEngine(gpt2_family(cfg), params, spec=spec, **kw)


def _oracle(params, prompt, max_new, key, temperature=0.0, top_k=0,
            cfg=CFG):
    return np.asarray(gpt2_generate(
        params, prompt[None], cfg, max_new_tokens=max_new,
        temperature=temperature, top_k=top_k, key=key)[0])


def _run_staggered(eng, prompts, max_new, keys, arrivals):
    order = np.argsort(np.asarray(arrivals), kind="stable")
    rids = {}
    submitted, step = 0, 0
    while submitted < len(prompts) or eng.has_work:
        while (submitted < len(prompts)
               and arrivals[order[submitted]] <= step):
            i = order[submitted]
            rids[i] = eng.submit(prompts[i], max_new[i], key=keys[i])
            submitted += 1
        eng.step()
        step += 1
        assert step < 2000, "engine failed to drain"
    return [eng.result(rids[i]) for i in range(len(prompts))]


# ---------------------------------------------------------------------
# drafter + config units
# ---------------------------------------------------------------------

class TestDrafter:
    def _d(self, **kw):
        return NgramDrafter(SpecConfig(**kw))

    def test_run_prediction(self):
        # a token run predicts itself: [..., 7,7,7,7] -> draft 7s
        ctx = np.array([3, 1, 7, 7, 7, 7, 7, 7], np.int32)
        d = self._d().draft(ctx, 4)
        np.testing.assert_array_equal(d, [7, 7, 7, 7])

    def test_periodic_prediction(self):
        # period-3 cycle: the suffix matched one period back predicts
        # the whole next period
        ctx = np.tile(np.array([5, 9, 2], np.int32), 4)
        d = self._d().draft(ctx, 6)
        np.testing.assert_array_equal(d, [5, 9, 2, 5, 9, 2])

    def test_periodic_extension_past_buffer_end(self):
        # the most recent match's literal continuation is 1 token (it
        # butts against the end of the buffer); periodic extension
        # must still fill the whole draft budget
        ctx = np.array([4, 4, 4, 4, 4, 4, 4, 4, 4, 4], np.int32)
        np.testing.assert_array_equal(self._d().draft(ctx, 6), [4] * 6)

    def test_no_match_is_empty(self):
        ctx = np.arange(10, dtype=np.int32)  # all tokens distinct
        assert self._d().draft(ctx, 8).size == 0

    def test_cap_and_max_draft(self):
        ctx = np.tile(np.array([5, 9], np.int32), 8)
        assert len(self._d().draft(ctx, 3)) == 3
        assert len(self._d(max_draft=4).draft(ctx, 99)) == 4
        assert self._d().draft(ctx, 0).size == 0

    def test_ngram_min_gate(self):
        # unigram match exists but bigram does not -> ngram_min=2
        # drafts nothing
        ctx = np.array([8, 1, 2, 3, 9, 4, 5, 9], np.int32)
        assert self._d(ngram_min=2).draft(ctx, 4).size == 0
        assert self._d().draft(ctx, 2).size > 0


class TestSpecConfig:
    def test_bucket_ladder_pinned_in_specs(self):
        assert SpecConfig().buckets == verify_buckets(8) == (2, 4, 8)
        assert SpecConfig(max_draft=6).buckets == (2, 4, 6)
        assert SpecConfig(max_draft=2).buckets == (2,)

    def test_bucket_for_smallest_cover(self):
        c = SpecConfig()
        assert c.bucket_for(1) == 2
        assert c.bucket_for(2) == 2
        assert c.bucket_for(3) == 4
        assert c.bucket_for(8) == 8

    def test_validation(self):
        with pytest.raises(ValueError, match="max_draft"):
            SpecConfig(max_draft=0)
        with pytest.raises(ValueError, match="min_draft"):
            SpecConfig(min_draft=0)
        with pytest.raises(ValueError, match="ngram_min"):
            SpecConfig(ngram_min=3, ngram_max=2)
        with pytest.raises(ValueError, match="end at"):
            SpecConfig(max_draft=8, buckets=(2, 4))
        # min_draft clamps to max_draft: the default 2 must not make
        # max_draft=1 (1 draft + bonus) unconstructible
        assert SpecConfig(max_draft=1).min_draft == 1
        assert SpecConfig(min_draft=9).min_draft == 8


# ---------------------------------------------------------------------
# KVPool tentative (speculative-tail) accounting
# ---------------------------------------------------------------------

class TestTentativePool:
    def _pool(self, num_blocks=8):
        return KVPool(n_layers=2, n_kv_heads=2, head_dim=4, block_size=4,
                      num_blocks=num_blocks)

    def test_acquire_commit_becomes_private(self):
        p = self._pool()
        t = p.tentative_acquire(2)
        assert all(p.is_tentative(b) and p.refcount(b) == 1 for b in t)
        p.commit_tentative(t)
        assert not any(p.is_tentative(b) for b in t)
        p.release(t)
        assert p.num_free == p.usable_blocks

    def test_rollback_returns_to_free_list(self):
        p = self._pool()
        t = p.tentative_acquire(3)
        assert p.num_used == 3 and p.num_tentative == 3
        p.rollback_tentative(t)
        assert p.num_used == 0 and p.num_tentative == 0
        assert p.num_free == p.usable_blocks

    def test_publish_refuses_tentative_blocks(self):
        p = self._pool()
        t = p.tentative_acquire(1)
        tokens = np.arange(4, dtype=np.int32)
        with pytest.raises(ValueError, match="tentative"):
            p.publish(tokens, t, 4)
        # after commit the same publish succeeds
        p.commit_tentative(t)
        p.publish(tokens, t, 4)
        assert p.is_cached(t[0])

    def test_commit_unknown_block_raises(self):
        p = self._pool()
        a = p.acquire(1)
        with pytest.raises(ValueError, match="not tentative"):
            p.commit_tentative(a)
        with pytest.raises(ValueError, match="not tentative"):
            p.rollback_tentative(a)

    def test_never_partial_and_null_block_respected(self):
        p = self._pool(num_blocks=4)  # 3 usable
        assert p.tentative_acquire(5) is None
        assert p.num_tentative == 0
        got = p.tentative_acquire(3)
        assert 0 not in got


# ---------------------------------------------------------------------
# the golden contract: spec-on == spec-off == oracle
# ---------------------------------------------------------------------

@pytest.mark.parametrize("temperature,top_k", [(0.0, 0), (0.8, 5)])
def test_spec_on_equals_spec_off_and_oracle(params, temperature, top_k):
    """Staggered multi-request traffic through a spec-on engine matches
    a spec-off engine AND the independent one-shot oracle per request,
    token for token — greedy and sampled. Sampling is the strong half
    of the claim: candidate tokens are sampled with exactly the keys
    plain decode would consume, so acceptance preserves bits, not just
    the distribution."""
    rng = np.random.default_rng(3)
    pat = rng.integers(0, CFG.vocab_size, (5,)).astype(np.int32)
    prompts = [np.tile(pat, 3),
               rng.integers(0, CFG.vocab_size, (7,)).astype(np.int32),
               np.tile(pat, 2),
               rng.integers(0, CFG.vocab_size, (4,)).astype(np.int32)]
    keys = [jax.random.key(100 + i) for i in range(len(prompts))]
    max_new = [18, 14, 16, 12]
    arrivals = [0, 1, 3, 6]

    outs = {}
    for name, spec in (("off", None), ("on", SpecConfig())):
        eng = _engine(params, spec=spec, temperature=temperature,
                      top_k=top_k)
        outs[name] = _run_staggered(eng, prompts, max_new, keys, arrivals)
    for a, b in zip(outs["off"], outs["on"]):
        np.testing.assert_array_equal(a, b)
    for p, k, n, o in zip(prompts, keys, max_new, outs["on"]):
        np.testing.assert_array_equal(
            o, _oracle(params, p, n, k, temperature, top_k))


def test_spec_parity_under_preemption(params):
    """A pool too small for the whole working set forces preemptions
    mid-speculation; evicted requests resume bit-identically (sampled
    traffic — the checkpointed key after a verify step must equal the
    key plain decode would have evolved)."""
    rng = np.random.default_rng(11)
    shared = rng.integers(0, CFG.vocab_size, (8,)).astype(np.int32)
    prompts = [np.concatenate([
        shared, rng.integers(0, CFG.vocab_size, (t,)).astype(np.int32)])
        for t in (3, 4, 5, 6)]
    keys = [jax.random.key(40 + i) for i in range(4)]
    max_new = [14, 14, 14, 14]
    arrivals = [0, 0, 1, 2]

    outs = {}
    preempted = {}
    for name, spec in (("off", None), ("on", SpecConfig())):
        eng = _engine(params, spec=spec, num_blocks=13, max_slots=3,
                      temperature=0.7, top_k=6)
        outs[name] = _run_staggered(eng, prompts, max_new, keys, arrivals)
        preempted[name] = eng.metrics.preempted
    assert preempted["on"] > 0  # the scenario actually preempts
    for a, b in zip(outs["off"], outs["on"]):
        np.testing.assert_array_equal(a, b)


def test_spec_parity_with_prefix_cache_and_hits(params):
    """Prefix-cache-on + speculation: shared-prompt traffic still
    matches spec-off output exactly, the cache still hits (speculation
    must not poison the index — published chains carry committed
    tokens only), and tentative blocks are all resolved at drain."""
    rng = np.random.default_rng(21)
    shared = rng.integers(0, CFG.vocab_size, (12,)).astype(np.int32)
    prompts = [np.concatenate([
        shared, rng.integers(0, CFG.vocab_size, (t,)).astype(np.int32)])
        for t in (2, 3, 4)]
    keys = [jax.random.key(60 + i) for i in range(3)]
    max_new = [12, 12, 12]
    arrivals = [0, 6, 12]   # staggered so retires publish before hits

    outs = {}
    for name, spec in (("off", None), ("on", SpecConfig())):
        eng = _engine(params, spec=spec, prefix_cache=True)
        outs[name] = _run_staggered(eng, prompts, max_new, keys, arrivals)
        assert eng.metrics.prefix_hit_tokens > 0
        assert eng.pool.num_tentative == 0
    for a, b in zip(outs["off"], outs["on"]):
        np.testing.assert_array_equal(a, b)


def test_spec_parity_llama():
    from quintnet_tpu.models.llama import LlamaConfig, llama_init
    from quintnet_tpu.serve import llama_family

    cfg = LlamaConfig.tiny(n_layers=2)
    lparams = llama_init(jax.random.key(0), cfg)
    rng = np.random.default_rng(2)
    prompts = [rng.integers(0, cfg.vocab_size, (n,)).astype(np.int32)
               for n in (5, 8)]
    keys = [jax.random.key(9 + i) for i in range(2)]
    outs = {}
    for name, spec in (("off", None), ("on", SpecConfig())):
        eng = ServeEngine(llama_family(cfg), lparams, max_slots=2,
                          block_size=4, num_blocks=32,
                          max_seq_len=min(48, cfg.n_positions), spec=spec)
        outs[name] = _run_staggered(eng, prompts, [24, 24], keys, [0, 1])
    for a, b in zip(outs["off"], outs["on"]):
        np.testing.assert_array_equal(a, b)


# ---------------------------------------------------------------------
# speculation actually speculates (and the win is observable)
# ---------------------------------------------------------------------

def test_accepts_drafts_and_fewer_steps(rep_params):
    """On repetition-prone traffic the verify path must actually commit
    multi-token steps: accepted drafts > 0, tokens_per_decode_step > 1,
    and the spec-on engine takes FEWER engine steps than spec-off for
    bit-identical output."""
    rng = np.random.default_rng(5)
    prompt = rng.integers(0, CFG_REP.vocab_size, (12,)).astype(np.int32)
    steps = {}
    outs = {}
    for name, spec in (("off", None), ("on", SpecConfig())):
        eng = ServeEngine(gpt2_family(CFG_REP), rep_params, max_slots=2,
                          block_size=8, num_blocks=32, max_seq_len=100,
                          spec=spec)
        rid = eng.submit(prompt, 60, key=jax.random.key(1))
        eng.run(max_steps=500)
        outs[name] = eng.result(rid)
        steps[name] = eng.metrics.steps
        if name == "on":
            s = eng.metrics.summary()
            assert s["accepted_draft_tokens"] > 10
            assert s["tokens_per_decode_step"] > 1.5
            assert s["spec_steps"] > 0
            assert s["draft_acceptance_rate"] > 0.5
    np.testing.assert_array_equal(outs["off"], outs["on"])
    assert steps["on"] < steps["off"] / 2


def test_eos_mid_draft_truncates_commit(rep_params):
    """An EOS inside the accepted draft retires the request at the EOS
    — tokens past it are never committed (same semantics as plain
    decode hitting EOS)."""
    rng = np.random.default_rng(5)
    prompt = rng.integers(0, CFG_REP.vocab_size, (12,)).astype(np.int32)
    # find the dominant repeated token of the plain continuation
    eng0 = ServeEngine(gpt2_family(CFG_REP), rep_params, max_slots=1,
                       block_size=8, num_blocks=32, max_seq_len=100)
    rid0 = eng0.submit(prompt, 40, key=jax.random.key(1))
    eng0.run(max_steps=300)
    gen = eng0.result(rid0)[len(prompt):]
    eos = int(np.bincount(gen).argmax())  # appears in a long run
    outs = {}
    for name, spec in (("off", None), ("on", SpecConfig())):
        eng = ServeEngine(gpt2_family(CFG_REP), rep_params, max_slots=1,
                          block_size=8, num_blocks=32, max_seq_len=100,
                          eos_token_id=eos, spec=spec)
        rid = eng.submit(prompt, 40, key=jax.random.key(1))
        eng.run(max_steps=300)
        outs[name] = eng.result(rid)
    np.testing.assert_array_equal(outs["off"], outs["on"])
    gen_on = outs["on"][len(prompt):]
    assert eos in gen_on and int(gen_on[-1]) == eos  # stopped AT the EOS


def test_export_mid_speculation_carries_committed_only(rep_params,
                                                       params):
    """Export progress while drafts are being accepted: the payload's
    generated tokens are a prefix of the oracle output (no draft ever
    leaks), and restoring on a SPEC-OFF engine finishes the request
    token-identically — migration across heterogeneous spec configs."""
    rng = np.random.default_rng(5)
    prompt = rng.integers(0, CFG_REP.vocab_size, (12,)).astype(np.int32)
    key = jax.random.key(1)
    oracle = _oracle(rep_params, prompt, 60, key, cfg=CFG_REP)

    eng = ServeEngine(gpt2_family(CFG_REP), rep_params, max_slots=1,
                      block_size=8, num_blocks=32, max_seq_len=100,
                      spec=SpecConfig())
    eng.submit(prompt, 60, key=key)
    for _ in range(60):
        eng.step()
        if eng.metrics.accepted_draft_tokens > 0:
            break   # export while speculation is in flight
    assert eng.metrics.accepted_draft_tokens > 0  # mid-speculation
    assert eng.has_work  # and the request is not finished yet
    progress = eng.export_progress()
    assert len(progress) == 1
    got = np.asarray(progress[0].generated, np.int32)
    assert 0 < len(got) < 60
    np.testing.assert_array_equal(
        got, oracle[len(prompt):len(prompt) + len(got)])

    dest = ServeEngine(gpt2_family(CFG_REP), rep_params, max_slots=1,
                       block_size=8, num_blocks=32, max_seq_len=100)
    rid = dest.restore_progress(progress[0])
    dest.run(max_steps=300)
    np.testing.assert_array_equal(dest.result(rid), oracle)


# ---------------------------------------------------------------------
# bounded-compile invariant with verify buckets
# ---------------------------------------------------------------------

def test_compile_count_bounded_over_mixed_spec_trace(rep_params):
    """Mixed speculating/non-speculating traffic (repetition-prone AND
    novel prompts, staggered, preempting) compiles at most
    len(prefill_buckets) prefill + len(verify_buckets) verify + 1
    decode programs — the no-recompile invariant extended to the
    verify family, enforced by assert_compile_count."""
    import jax.monitoring as monitoring

    rng = np.random.default_rng(5)
    eng = ServeEngine(gpt2_family(CFG_REP), rep_params, max_slots=3,
                      block_size=8, num_blocks=24, max_seq_len=100,
                      spec=SpecConfig())
    eng.warmup()   # compiles every bucket up front
    stats0 = eng.compile_stats()
    assert stats0 == {"prefill": len(eng.prefill_buckets),
                      "decode": 1,
                      "verify": len(eng.spec.buckets)}
    # one full request lifecycle primes the submit-path helpers
    # (fold_in etc.) that compile once outside the sentinels
    eng.submit(np.zeros((3,), np.int32), 2)
    eng.run(max_steps=50)

    compiles = []
    monitoring.register_event_duration_secs_listener(
        lambda name, dur, **kw: compiles.append(name)
        if "backend_compile" in name else None)
    try:
        prompts = [rng.integers(0, CFG_REP.vocab_size,
                                (n,)).astype(np.int32)
                   for n in (12, 7, 9, 5)]
        arrivals = [0, 2, 5, 9]
        submitted, step = 0, 0
        while submitted < len(prompts) or eng.has_work:
            while (submitted < len(prompts)
                   and arrivals[submitted] <= step):
                eng.submit(prompts[submitted], 40)
                submitted += 1
            eng.step()
            step += 1
            assert step < 1000
    finally:
        monitoring.clear_event_listeners()
    assert compiles == []
    assert eng.metrics.spec_steps > 0          # speculation happened
    assert eng.metrics.decode_steps > eng.metrics.spec_steps  # mixed
    assert eng.compile_stats() == stats0       # nothing new compiled
    eng.assert_compile_count(prefill=stats0["prefill"], decode=1,
                             verify=stats0["verify"])


def test_spec_off_engine_unchanged_surface(params):
    """A spec-off engine exposes the pre-speculation compile surface:
    no verify key in compile_stats, no verify sentinels — fleets mixing
    spec-on and spec-off replicas account each correctly."""
    eng = _engine(params)
    rng = np.random.default_rng(0)
    eng.submit(rng.integers(0, CFG.vocab_size, (5,)).astype(np.int32), 4)
    eng.run(max_steps=50)
    assert eng.compile_stats() == {"prefill": 1, "decode": 1}
    assert "decode" in eng.compile_sentinels()
    assert not any(k.startswith("verify[")
                   for k in eng.compile_sentinels())
    eng.assert_compile_count()  # verify default: nothing to check

"""Long-context serving goldens (serve/longctx.py + chunked engine).

THE contract, in two halves:

- **chunked prefill** — a prompt of ANY length the pool can hold is
  admitted whole and streamed through the existing bucket programs
  under a per-step token budget; the output is BIT-identical to the
  same tokens forced through a widened single-window engine (greedy
  AND sampled), including prefix-cache-on, preempt-resume mid-prefill,
  and fleet kill-migration mid-prefill — while concurrent decode slots
  keep emitting a token EVERY step (the Sarathi no-starvation
  property) and the compile count stays at the pinned bucket ladder;
- **sequence-parallel prefill** — the same programs over an ``sp``
  mesh run the chunk's attention ring-sharded
  (nn/attention.ring_paged_prefill) and produce the same tokens as the
  single-device engine (the collective census golden lives in
  tests/test_qtcheck.py).
"""

import jax
import numpy as np
import pytest
from jax.sharding import Mesh

from quintnet_tpu.models.gpt2 import GPT2Config, gpt2_init
from quintnet_tpu.serve import (ServeEngine, check_admissible, generate,
                                gpt2_family, plan_chunks)

CFG = GPT2Config.tiny(n_layer=2, n_positions=256)


@pytest.fixture(scope="module")
def params():
    return gpt2_init(jax.random.key(0), CFG)


@pytest.fixture(scope="module")
def family():
    return gpt2_family(CFG)


def _engine(family, params, **kw):
    kw.setdefault("max_slots", 2)
    kw.setdefault("block_size", 8)
    kw.setdefault("num_blocks", 40)
    kw.setdefault("max_seq_len", 200)
    return ServeEngine(family, params, **kw)


def _prompt(rng, n):
    return np.asarray(rng.integers(0, CFG.vocab_size, (n,)), np.int32)


# ---------------------------------------------------------------------
# planning units
# ---------------------------------------------------------------------

class TestPlanChunks:
    def test_budget_and_bucket_cap(self):
        chunks = plan_chunks(100, buckets=(16, 32), budget=24)
        assert chunks == [(0, 24), (24, 24), (48, 24), (72, 24),
                          (96, 4)]
        # budget above the top bucket: the bucket caps the chunk
        assert plan_chunks(70, buckets=(16, 32), budget=999) == \
            [(0, 32), (32, 32), (64, 6)]
        assert plan_chunks(0, buckets=(16,), budget=4) == []

    def test_validation(self):
        with pytest.raises(ValueError, match="budget"):
            plan_chunks(10, buckets=(16,), budget=0)


# ---------------------------------------------------------------------
# admissibility: the escape hatch
# ---------------------------------------------------------------------

class TestAdmissibility:
    def test_overlength_rejection_names_chunked_prefill(self):
        with pytest.raises(ValueError) as ei:
            check_admissible(100, 8, max_seq_len=200, prefill_len=32,
                             usable_blocks=64, block_size=8)
        msg = str(ei.value)
        assert "chunked_prefill=True" in msg
        assert "docs/serving.md" in msg

    def test_chunked_lifts_only_the_prefill_window(self):
        # same request is admissible with the flag...
        check_admissible(100, 8, max_seq_len=200, prefill_len=32,
                         usable_blocks=64, block_size=8,
                         chunked_prefill=True)
        # ...but max_seq_len and pool capacity still bound it
        with pytest.raises(ValueError, match="max_seq_len"):
            check_admissible(300, 8, max_seq_len=200, prefill_len=32,
                             usable_blocks=64, block_size=8,
                             chunked_prefill=True)
        with pytest.raises(ValueError, match="KV pool too small"):
            check_admissible(100, 8, max_seq_len=200, prefill_len=32,
                             usable_blocks=4, block_size=8,
                             chunked_prefill=True)

    def test_engine_limits_carry_the_flag(self, family, params):
        eng = _engine(family, params, chunked_prefill=True,
                      prefill_len=32)
        assert eng.limits()["chunked_prefill"] is True
        # the limits dict splats straight into check_admissible — the
        # process fleet's parent-side validation admits long prompts
        # against a chunked replica's hello
        check_admissible(150, 8, **eng.limits())

    def test_frontdoor_maps_overlength_to_400_naming_the_hatch(self):
        from quintnet_tpu.fleet.frontdoor import FrontDoor

        try:
            check_admissible(100, 8, max_seq_len=200, prefill_len=32,
                             usable_blocks=64, block_size=8)
        except ValueError as e:
            status, body, _ = FrontDoor._error_response(
                object.__new__(FrontDoor), e)
        assert status == 400
        assert body["error"] == "bad_request"
        assert "chunked_prefill=True" in body["message"]


# ---------------------------------------------------------------------
# the golden contract: chunked == single-shot, bit for bit
# ---------------------------------------------------------------------

class TestChunkedParity:
    @pytest.mark.parametrize("sampling", ["greedy", "sampled"])
    def test_short_prompt_forced_into_chunks(self, family, params, rng,
                                             sampling):
        """Provable even on prompts that fit one bucket: a budget
        smaller than the prompt forces multiple chunks through the
        same programs — output must not move by a bit."""
        kw = (dict(temperature=0.8, top_k=5) if sampling == "sampled"
              else {})
        prompt = _prompt(rng, 40)
        key = jax.random.key(11)
        plain = _engine(family, params, **kw)
        want = generate(plain, [prompt], max_new_tokens=6, keys=[key])[0]
        chunked = _engine(family, params, chunked_prefill=True,
                          prefill_chunk_budget=12, **kw)
        got = generate(chunked, [prompt], max_new_tokens=6, keys=[key],
                       max_steps=100)[0]
        np.testing.assert_array_equal(want, got)
        assert chunked.metrics.prefill_chunks >= 4  # really chunked

    @pytest.mark.parametrize("sampling", ["greedy", "sampled"])
    def test_long_prompt_vs_widened_single_bucket_engine(
            self, family, params, rng, sampling):
        """THE acceptance golden: a prompt LONGER than the chunked
        engine's top prefill bucket is served end to end, bit-identical
        to the same tokens forced through an engine whose single
        prefill window was widened to fit them."""
        kw = (dict(temperature=0.8, top_k=5) if sampling == "sampled"
              else {})
        prompt = _prompt(rng, 150)
        key = jax.random.key(7)
        wide = _engine(family, params, prefill_len=200, **kw)
        want = generate(wide, [prompt], max_new_tokens=8, keys=[key])[0]
        chunked = _engine(family, params, prefill_len=32,
                          chunked_prefill=True, prefill_chunk_budget=32,
                          **kw)
        assert len(prompt) > chunked.prefill_buckets[-1]
        got = generate(chunked, [prompt], max_new_tokens=8, keys=[key],
                       max_steps=100)[0]
        np.testing.assert_array_equal(want, got)
        # no per-length programs: the pinned bucket ladder bounds it
        assert (chunked.compile_stats()["prefill"]
                <= len(chunked.prefill_buckets))
        chunked.assert_compile_count(prefill=1)

    def test_prefix_cache_composes_with_chunks(self, family, params,
                                               rng):
        """Two requests sharing a long prompt: the second's chunks are
        served from the published chain of the first (prefill work
        collapses), output identical either way."""
        prompt = _prompt(rng, 120)
        key1, key2 = jax.random.key(21), jax.random.key(22)
        wide = _engine(family, params, prefill_len=200)
        want1 = generate(wide, [prompt], max_new_tokens=4,
                         keys=[key1])[0]
        wide2 = _engine(family, params, prefill_len=200)
        want2 = generate(wide2, [prompt], max_new_tokens=4,
                         keys=[key2])[0]

        eng = _engine(family, params, prefill_len=32,
                      chunked_prefill=True, prefill_chunk_budget=32)
        got1 = generate(eng, [prompt], max_new_tokens=4, keys=[key1],
                        max_steps=100)[0]
        before = eng.metrics.prefill_tokens
        got2 = generate(eng, [prompt], max_new_tokens=4, keys=[key2],
                        max_steps=100)[0]
        after = eng.metrics.prefill_tokens
        np.testing.assert_array_equal(want1, got1)
        np.testing.assert_array_equal(want2, got2)
        assert eng.metrics.prefix_hit_tokens > 100  # chunks reused
        # the second request's prefill barely computed anything
        assert after - before < len(prompt) // 2

    def test_cache_on_equals_cache_off(self, family, params, rng):
        prompt = _prompt(rng, 100)
        key = jax.random.key(33)
        outs = []
        for pc in (True, False):
            eng = _engine(family, params, prefill_len=32,
                          chunked_prefill=True, prefix_cache=pc,
                          temperature=0.8, top_k=5)
            outs.append(generate(eng, [prompt], max_new_tokens=6,
                                 keys=[key], max_steps=100)[0])
        np.testing.assert_array_equal(outs[0], outs[1])


# ---------------------------------------------------------------------
# the Sarathi property: decode never starves behind a long prefill
# ---------------------------------------------------------------------

class TestDecodeStarvation:
    def test_concurrent_decodes_emit_every_step(self, family, params,
                                                rng):
        """With a 150-token prefill in flight under a 16-token budget,
        a generating request commits >= 1 token on EVERY engine step —
        the monolithic engine's whole-prompt stall cannot happen by
        construction — and the chunk ledger lands in ServeMetrics."""
        eng = _engine(family, params, max_slots=3, prefill_len=32,
                      chunked_prefill=True, prefill_chunk_budget=16)
        short = _prompt(rng, 6)
        longp = _prompt(rng, 150)
        r1 = eng.submit(short, 40)
        eng.step()  # short admitted + first token
        r2 = eng.submit(longp, 4)
        per_step = []
        while eng.request(r2).state != "finished":
            d0 = eng.metrics.decode_tokens
            eng.step()
            per_step.append(eng.metrics.decode_tokens - d0)
            assert len(per_step) < 200
        # every step with the long prefill in flight still decoded
        assert min(per_step) >= 1
        m = eng.metrics
        assert m.prefill_chunks >= 150 // 16
        assert 0 < m.chunk_tokens_per_step <= 16
        s = m.summary()
        for k in ("prefill_chunks", "chunk_steps", "chunk_tokens",
                  "chunk_tokens_per_step", "itl_s"):
            assert k in s, k
        assert s["itl_s"]["p95"] >= 0.0
        assert s["chunk_tokens_per_step"] <= 16

    def test_budget_caps_chunk_tokens_per_step(self, family, params,
                                               rng):
        eng = _engine(family, params, prefill_len=32,
                      chunked_prefill=True, prefill_chunk_budget=8)
        eng.submit(_prompt(rng, 90), 2)
        while eng.has_work:
            before = eng.metrics.chunk_tokens
            eng.step()
            assert eng.metrics.chunk_tokens - before <= 8
            assert eng.metrics.steps < 200


# ---------------------------------------------------------------------
# preemption / migration mid-prefill
# ---------------------------------------------------------------------

class TestMidPrefillLifecycle:
    def test_preempt_mid_prefill_resumes_bit_identically(
            self, family, params, rng):
        """A pool sized so the older request's decode growth preempts
        the long request MID-PREFILL: its completed chunks are
        published (the resume re-prefills almost nothing) and both
        outputs match undisturbed single-shot references exactly."""
        p_old, p_long = _prompt(rng, 10), _prompt(rng, 80)
        eng = _engine(family, params, block_size=8, num_blocks=14,
                      max_seq_len=96, prefill_len=32,
                      chunked_prefill=True, prefill_chunk_budget=4)
        ra = eng.submit(p_old, 60)
        rb = eng.submit(p_long, 4)
        saw_mid_prefill_preempt = False
        steps = 0
        while eng.has_work and steps < 500:
            pre = eng.metrics.preempted
            mid = any(st is not None for st in eng._slot_chunk)
            eng.step()
            if eng.metrics.preempted > pre and mid:
                saw_mid_prefill_preempt = True
            steps += 1
        assert not eng.has_work
        assert saw_mid_prefill_preempt  # the scenario actually ran
        ka = jax.random.fold_in(jax.random.key(0), ra)
        kb = jax.random.fold_in(jax.random.key(0), rb)
        wide = _engine(family, params, num_blocks=40, max_seq_len=96,
                       prefill_len=96)
        np.testing.assert_array_equal(
            eng.result(ra),
            generate(wide, [p_old], max_new_tokens=60, keys=[ka])[0])
        wide2 = _engine(family, params, num_blocks=40, max_seq_len=96,
                        prefill_len=96)
        np.testing.assert_array_equal(
            eng.result(rb),
            generate(wide2, [p_long], max_new_tokens=4, keys=[kb])[0])
        assert eng.metrics.prefix_hit_tokens > 0  # published chunks hit

    def test_export_mid_prefill_carries_prefilled_and_restores(
            self, family, params, rng):
        """Kill-migration surface: a request exported MID-PREFILL has
        generated=[] and the submit key (sampling happens once, on the
        final chunk), carries its chunk high-water mark, survives the
        wire, and the restoring engine re-chunks to a token-identical
        stream."""
        from quintnet_tpu.fleet.wire import (progress_from_wire,
                                             progress_to_wire)

        prompt = _prompt(rng, 80)
        src = _engine(family, params, prefill_len=32,
                      chunked_prefill=True, prefill_chunk_budget=8,
                      temperature=0.8, top_k=5)
        rid = src.submit(prompt, 4, key=jax.random.key(9))
        src.step()
        src.step()
        progs = src.export_progress()
        assert len(progs) == 1
        p = progs[0]
        assert p.generated == [] and 0 < p.prefilled < len(prompt)
        p2 = progress_from_wire(progress_to_wire(p))
        assert p2.prefilled == p.prefilled
        np.testing.assert_array_equal(p2.key_data, p.key_data)

        dst = _engine(family, params, prefill_len=32,
                      chunked_prefill=True, prefill_chunk_budget=8,
                      temperature=0.8, top_k=5)
        rid2 = dst.restore_progress(p2)
        dst.run(max_steps=100)
        wide = _engine(family, params, prefill_len=200,
                       temperature=0.8, top_k=5)
        want = generate(wide, [prompt], max_new_tokens=4,
                        keys=[jax.random.key(9)])[0]
        np.testing.assert_array_equal(dst.result(rid2), want)

    def test_fleet_kill_migration_mid_prefill(self, params, rng):
        """A replica killed while a long prompt is MID-PREFILL: the
        fleet resumes it elsewhere and the stream is token-identical
        to an undisturbed engine (sampled params — the strictest
        form)."""
        from quintnet_tpu.fleet import ServeFleet
        from quintnet_tpu.ft import ChaosMonkey

        fam = gpt2_family(CFG)

        def factory():
            return ServeEngine(fam, params, max_slots=2, block_size=8,
                               num_blocks=40, max_seq_len=200,
                               prefill_len=32, chunked_prefill=True,
                               prefill_chunk_budget=8,
                               temperature=0.8, top_k=5)

        longp = _prompt(rng, 100)
        shorts = [_prompt(rng, n) for n in (5, 7)]
        keys = [jax.random.key(800 + i) for i in range(3)]
        # 100 tokens at 8/step needs ~13 chunk steps: a kill at step 4
        # lands mid-prefill with certainty
        monkey = ChaosMonkey(kill_at_step=4, mode="raise", target="r0")
        fleet = ServeFleet(factory, n_replicas=2, policy="round_robin",
                           chaos=monkey)
        try:
            fids = [fleet.submit(longp, 6, key=keys[0])]
            fids += [fleet.submit(p, 6, key=k)
                     for p, k in zip(shorts, keys[1:])]
            outs = [fleet.result(f, timeout=300) for f in fids]
            assert fleet.metrics.replica_deaths == 1
            assert fleet.metrics.migrations >= 1
            for p, k, o in zip([longp] + shorts, keys, outs):
                wide = _engine(gpt2_family(CFG), params,
                               prefill_len=200, temperature=0.8,
                               top_k=5)
                np.testing.assert_array_equal(
                    o, generate(wide, [p], max_new_tokens=6,
                                keys=[k])[0])
        finally:
            fleet.drain(timeout=120)


# ---------------------------------------------------------------------
# compile bound over a chunked trace
# ---------------------------------------------------------------------

class TestCompileBound:
    def test_zero_backend_compiles_after_warmup(self, family, params,
                                                rng):
        """Mixed chunked traffic — long + short prompts, retires,
        prefix hits — runs ZERO XLA compiles after warmup: prompt
        length stopped being a compile-ladder input."""
        eng = _engine(family, params, prefill_len=32,
                      chunked_prefill=True, prefill_chunk_budget=16)
        eng.warmup()
        compiles = []
        jax.monitoring.register_event_listener(
            lambda ev, **kw: compiles.append(ev)
            if ev == "/jax/backend_compile" else None)
        base = len(compiles)
        for n, mn in ((150, 4), (9, 3), (120, 2), (40, 5)):
            eng.submit(_prompt(rng, n), mn)
        eng.run(max_steps=300)
        assert not eng.has_work
        assert len(compiles) == base, "recompiled after warmup"
        assert (eng.compile_stats()["prefill"]
                <= len(eng.prefill_buckets))


# ---------------------------------------------------------------------
# sequence-parallel prefill (ring attention over the sp axis)
# ---------------------------------------------------------------------

class TestSpPrefill:
    @pytest.mark.parametrize("sp", [2, 4])
    def test_sp_engine_matches_single_device_tokens(self, family,
                                                    params, rng, sp):
        """The sp engine's generated tokens equal the single-device
        engine's — ring attention is exact (online softmax), and
        decode runs replicated so the whole stream matches."""
        prompt = _prompt(rng, 40)
        key = jax.random.key(5)
        plain = _engine(family, params)
        want = generate(plain, [prompt], max_new_tokens=6, keys=[key])[0]
        mesh = Mesh(np.array(jax.devices()[:sp]), ("sp",))
        eng = _engine(family, params, mesh=mesh, sp_axis="sp")
        got = generate(eng, [prompt], max_new_tokens=6, keys=[key])[0]
        np.testing.assert_array_equal(want, got)

    def test_sp_composes_with_chunked_prefill(self, family, params,
                                              rng):
        """Long prompt, chunked, each chunk ring-sharded over sp=2:
        still token-identical to the widened single-device engine."""
        prompt = _prompt(rng, 150)
        key = jax.random.key(6)
        wide = _engine(family, params, prefill_len=200)
        want = generate(wide, [prompt], max_new_tokens=6, keys=[key])[0]
        mesh = Mesh(np.array(jax.devices()[:2]), ("sp",))
        eng = _engine(family, params, mesh=mesh, sp_axis="sp",
                      prefill_len=32, chunked_prefill=True,
                      prefill_chunk_budget=32)
        got = generate(eng, [prompt], max_new_tokens=6, keys=[key],
                       max_steps=100)[0]
        np.testing.assert_array_equal(want, got)
        assert eng.metrics.prefill_chunks >= 4

    def test_sp_llama_matches_single_device(self, rng):
        from quintnet_tpu.models.llama import LlamaConfig, llama_init
        from quintnet_tpu.serve import llama_family

        lcfg = LlamaConfig.tiny(n_layers=2, n_positions=256)
        lp = llama_init(jax.random.key(1), lcfg)
        fam = llama_family(lcfg)
        prompt = np.asarray(rng.integers(0, lcfg.vocab_size, (40,)),
                            np.int32)
        key = jax.random.key(4)
        plain = ServeEngine(fam, lp, max_slots=2, block_size=8,
                            num_blocks=40, max_seq_len=200)
        want = generate(plain, [prompt], max_new_tokens=6, keys=[key])[0]
        mesh = Mesh(np.array(jax.devices()[:2]), ("sp",))
        eng = ServeEngine(fam, lp, max_slots=2, block_size=8,
                          num_blocks=40, max_seq_len=200, mesh=mesh,
                          sp_axis="sp")
        got = generate(eng, [prompt], max_new_tokens=6, keys=[key])[0]
        np.testing.assert_array_equal(want, got)

    def test_sp_one_builds_the_plain_programs(self, family, params):
        """engine(sp=1) must be byte-identical to today's programs —
        the sp path is not even built."""
        mesh = Mesh(np.array(jax.devices()[:1]), ("sp",))
        eng = _engine(family, params, mesh=mesh, sp_axis="sp")
        assert eng.sp_axis is None

    def test_indivisible_buckets_rejected_with_fix(self, family,
                                                   params):
        mesh = Mesh(np.array(jax.devices()[:4]), ("sp",))
        with pytest.raises(ValueError, match="divisible by sp=4"):
            _engine(family, params, mesh=mesh, sp_axis="sp",
                    prefill_bucket_sizes=(16, 18), prefill_len=18,
                    max_seq_len=24)

    def test_misconfigured_sp_axis_raises(self, family, params):
        """An sp_axis the mesh does not carry is a misconfiguration —
        silently running replicated would burn N devices for nothing
        (size 1 falling back to the plain programs is the documented
        degenerate case; a MISSING axis is not)."""
        mesh = Mesh(np.array(jax.devices()[:2]), ("sp",))
        with pytest.raises(ValueError, match="not an axis of the mesh"):
            _engine(family, params, mesh=mesh, sp_axis="spp")
        with pytest.raises(ValueError, match="not an axis of the mesh"):
            _engine(family, params, sp_axis="sp")  # no mesh at all

    def test_zero_chunk_budget_rejected(self, family, params):
        with pytest.raises(ValueError, match="prefill_chunk_budget"):
            _engine(family, params, chunked_prefill=True,
                    prefill_chunk_budget=0)

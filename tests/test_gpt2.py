"""GPT-2 tests: forward golden vs HF transformers (torch CPU), checkpoint
import/export roundtrips, tied-weight grads, and 3D-parallel training
equivalence (the reference verifies its distributed GPT-2 against a
single-GPU HF reload — test.py:28-113; same idea, automated here)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax

from quintnet_tpu.core.config import Config
from quintnet_tpu.models.gpt2 import (
    GPT2Config,
    clm_loss,
    gpt2_apply,
    gpt2_init,
    gpt2_model_spec,
    gpt2_to_tp_layout,
    perplexity,
)
from quintnet_tpu.models.gpt2_io import load_hf_gpt2, save_hf_gpt2
from quintnet_tpu.parallel.strategy import get_strategy
from quintnet_tpu.utils import safetensors_io as st

TINY = GPT2Config.tiny()


def test_safetensors_roundtrip(tmp_path):
    import ml_dtypes

    tensors = {
        "a": np.arange(12, dtype=np.float32).reshape(3, 4),
        "b": np.random.default_rng(0).normal(size=(5,)).astype(np.float16),
        "c": np.arange(4, dtype=np.int64),
        "d": np.ones((2, 2), dtype=ml_dtypes.bfloat16),
    }
    p = str(tmp_path / "x.safetensors")
    st.save_file(tensors, p, metadata={"who": "test"})
    with st.SafeTensorFile(p) as f:
        assert set(f.keys()) == set(tensors)
        assert f.metadata["who"] == "test"
        for k, v in tensors.items():
            np.testing.assert_array_equal(f.tensor(k), v)
        # lazy slicing returns views without materialising the tensor
        np.testing.assert_array_equal(f["a"][1:, :2],
                                      tensors["a"][1:, :2])


@pytest.fixture(scope="module")
def hf_model_file(tmp_path_factory):
    """Small random HF GPT2LMHeadModel saved as safetensors."""
    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")

    hf_cfg = transformers.GPT2Config(
        vocab_size=TINY.vocab_size, n_positions=TINY.n_positions,
        n_embd=TINY.n_embd, n_layer=TINY.n_layer, n_head=TINY.n_head,
        resid_pdrop=0.0, embd_pdrop=0.0, attn_pdrop=0.0)
    torch.manual_seed(0)
    model = transformers.GPT2LMHeadModel(hf_cfg).eval()
    d = tmp_path_factory.mktemp("hf")
    model.save_pretrained(str(d), safe_serialization=True)
    return model, str(d / "model.safetensors")


def test_hf_import_logits_match(hf_model_file):
    """Forward parity with transformers on the same weights — the golden
    check behind every convergence claim."""
    import torch

    model, path = hf_model_file
    params, cfg = load_hf_gpt2(path)
    assert cfg.n_layer == TINY.n_layer and cfg.n_embd == TINY.n_embd
    cfg = TINY  # n_head heuristic can't know tiny's head count

    ids = np.array([[1, 5, 9, 2, 77, 31, 4, 8]], dtype=np.int32)
    with torch.no_grad():
        ref = model(torch.tensor(ids, dtype=torch.long)).logits.numpy()
    out = np.asarray(gpt2_apply(params, jnp.asarray(ids), cfg))
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=2e-4)


def test_hf_export_roundtrip(hf_model_file, tmp_path):
    import torch
    import transformers

    _, path = hf_model_file
    params, _ = load_hf_gpt2(path)
    out_path = str(tmp_path / "exported.safetensors")
    save_hf_gpt2(params, TINY, out_path)

    params2, _ = load_hf_gpt2(out_path)
    for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(params2)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_clm_loss_ignore_index():
    logits = jnp.zeros((2, 4, 8))
    labels = jnp.array([[1, 2, -100, -100], [3, -100, -100, -100]])
    # uniform logits -> loss = log(8) over the 2 valid (shifted) targets
    loss = clm_loss(logits, labels)
    np.testing.assert_allclose(float(loss), np.log(8), rtol=1e-6)
    assert float(perplexity(jnp.asarray(25.0))) == pytest.approx(np.exp(20.0))


def test_tied_weights_grad():
    """wte grad includes both embedding and lm-head contributions (the
    reference syncs these by hand across pp stages,
    gpt2_stage.py:112-141)."""
    params = gpt2_init(jax.random.key(0), TINY)
    ids = jax.random.randint(jax.random.key(1), (2, 16), 0, TINY.vocab_size)
    labels = jnp.where(ids % 3 == 0, -100, ids)

    def loss_fn(p):
        return clm_loss(gpt2_apply(p, ids, TINY), labels)

    g = jax.grad(loss_fn)(params)
    # untied head-only grad: zero out embedding path by freezing embed use
    assert float(jnp.abs(g["embedding"]["wte"]).sum()) > 0


def _data(batch=8, seq=16):
    ids = jax.random.randint(jax.random.key(1), (batch, seq), 0,
                             TINY.vocab_size)
    # all tokens valid: pipeline microbatch mean-of-means == global mean
    # exactly (with ragged masking they differ slightly; the reference's
    # schedule has the same micro-averaging semantics, schedule.py:236-246)
    return ids, ids


@pytest.mark.parametrize("mesh_dim,mesh_name,schedule", [
    ([2, 2, 2], ["dp", "tp", "pp"], "1f1b"),
    ([2, 2, 2], ["dp", "tp", "pp"], "afab"),
])
def test_gpt2_3d_training_matches_single_device(mesh_dim, mesh_name, schedule):
    cfg = Config.from_dict({
        "mesh_dim": mesh_dim, "mesh_name": mesh_name,
        "training": {"batch_size": 8, "gradient_accumulation_steps": 2,
                     "schedule": schedule, "grad_clip_norm": None},
    })
    params = gpt2_init(jax.random.key(0), TINY)
    batch = _data()
    opt = optax.sgd(0.05)

    def ref_loss(p):
        return clm_loss(gpt2_apply(p, batch[0], TINY), batch[1])

    loss_ref, g_ref = jax.value_and_grad(ref_loss)(params)
    p_ref = optax.apply_updates(params, opt.update(g_ref, opt.init(params),
                                                   params)[0])

    strat = get_strategy("auto", cfg)
    model = gpt2_model_spec(TINY)
    p = strat.shard_params(model, params)
    s = strat.init_opt_state(model, opt, p)
    b = strat.shard_batch(batch)
    step = strat.make_train_step(model, opt)
    p2, _, loss = step(p, s, b)

    np.testing.assert_allclose(float(loss), float(loss_ref), rtol=1e-5)

    p_ref_l = gpt2_to_tp_layout(p_ref, TINY, cfg.tp_size)
    flat = jax.tree_util.tree_leaves_with_path(p2)
    ref = dict(jax.tree_util.tree_leaves_with_path(p_ref_l))
    for path, leaf in flat:
        np.testing.assert_allclose(
            np.asarray(jax.device_get(leaf)), np.asarray(ref[path]),
            rtol=2e-4, atol=1e-5, err_msg=f"{path}")


def test_bf16_compute_keeps_f32_master_params():
    """Mixed precision: bf16 compute, f32 param storage + grads."""
    model = gpt2_model_spec(TINY, compute_dtype=jnp.bfloat16)
    params = model.init(jax.random.key(0))
    ids, labels = _data(4, 16)

    loss_bf16 = model.loss_fn(params, (ids, labels))
    loss_f32 = gpt2_model_spec(TINY).loss_fn(params, (ids, labels))
    # same math at bf16 precision
    np.testing.assert_allclose(float(loss_bf16), float(loss_f32),
                               rtol=2e-2)
    g = jax.grad(lambda p: model.loss_fn(p, (ids, labels)))(params)
    for leaf in jax.tree.leaves(g):
        assert leaf.dtype == jnp.float32


def test_chunked_ce_matches_plain_loss_and_grads():
    """clm_loss_chunked == clm_loss (value AND grads) — same math,
    chunked so full [B, S, V] logits never materialise."""
    import numpy as np

    from quintnet_tpu.models.gpt2 import gpt2_init, gpt2_model_spec

    cfg_plain = GPT2Config.tiny(n_layer=2)
    cfg_chunk = GPT2Config.tiny(n_layer=2, loss_chunk=16)
    params = gpt2_init(jax.random.key(0), cfg_plain)
    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(0, cfg_plain.vocab_size, (2, 48)),
                      jnp.int32)
    labels = ids.at[:, :7].set(-100)  # exercise IGNORE_INDEX masking
    batch = (ids, labels)

    m_plain = gpt2_model_spec(cfg_plain)
    m_chunk = gpt2_model_spec(cfg_chunk)
    l1, g1 = jax.value_and_grad(lambda p: m_plain.loss_fn(p, batch))(params)
    l2, g2 = jax.value_and_grad(lambda p: m_chunk.loss_fn(p, batch))(params)
    np.testing.assert_allclose(float(l1), float(l2), rtol=1e-6)
    for a, b in zip(jax.tree.leaves(g1), jax.tree.leaves(g2)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-5, atol=1e-6)


def test_chunked_ce_nondivisible_seq():
    """Padded tail chunk contributes nothing (padding targets are
    IGNORE_INDEX)."""
    import numpy as np

    from quintnet_tpu.models.gpt2 import gpt2_init, gpt2_model_spec

    cfg_plain = GPT2Config.tiny(n_layer=2)
    cfg_chunk = GPT2Config.tiny(n_layer=2, loss_chunk=16)
    params = gpt2_init(jax.random.key(0), cfg_plain)
    rng = np.random.default_rng(1)
    ids = jnp.asarray(rng.integers(0, cfg_plain.vocab_size, (2, 37)),
                      jnp.int32)  # 36 targets: 2 chunks of 16 + pad
    batch = (ids, ids)
    l1 = float(gpt2_model_spec(cfg_plain).loss_fn(params, batch))
    l2 = float(gpt2_model_spec(cfg_chunk).loss_fn(params, batch))
    np.testing.assert_allclose(l1, l2, rtol=1e-6)

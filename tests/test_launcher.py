"""tools/launch_multihost.py: the torchrun-role launcher (reference
README.md:93-97) spawns N processes that rendezvous into one mesh."""

import io
import sys
import textwrap

import pytest


def _worker_script(tmp_path):
    """A minimal entry accepting the appended coordinator flags, doing a
    cross-process psum, and writing its result."""
    p = tmp_path / "worker.py"
    p.write_text(textwrap.dedent("""
        import argparse
        ap = argparse.ArgumentParser()
        ap.add_argument("--outdir")
        ap.add_argument("--coordinator")
        ap.add_argument("--num-processes", type=int)
        ap.add_argument("--process-id", type=int)
        a = ap.parse_args()

        from quintnet_tpu.core import runtime
        runtime.initialize(coordinator_address=a.coordinator,
                           num_processes=a.num_processes,
                           process_id=a.process_id,
                           local_device_count=2, platform="cpu")
        import jax, jax.numpy as jnp
        from jax.sharding import PartitionSpec as P
        from quintnet_tpu.core import collectives as cc
        from quintnet_tpu.core.mesh import mesh_from_sizes

        assert jax.device_count() == 2 * a.num_processes
        mesh = mesh_from_sizes(dp=jax.device_count())
        total = cc.shard_map_fn(
            lambda x: jax.lax.psum(x, "dp"), mesh,
            in_specs=P("dp"), out_specs=P())(
                jnp.arange(jax.device_count(), dtype=jnp.float32))
        print("psum", float(total[0] if total.ndim else total), flush=True)
        with open(f"{a.outdir}/rank{a.process_id}.txt", "w") as f:
            f.write(str(float(total[0] if total.ndim else total)))
    """))
    return str(p)


@pytest.mark.slow
def test_launcher_two_process_psum(tmp_path, monkeypatch):
    import os

    from quintnet_tpu.tools.launch_multihost import launch

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    monkeypatch.setenv("PYTHONPATH", repo + os.pathsep
                       + os.environ.get("PYTHONPATH", ""))
    worker = _worker_script(tmp_path)
    out = io.StringIO()
    rc = launch([sys.executable, worker, "--outdir", str(tmp_path)],
                nproc=2, out=out)
    assert rc == 0, out.getvalue()
    # 4 global devices, psum over arange(4) = 6.0, seen by both ranks
    for r in range(2):
        assert (tmp_path / f"rank{r}.txt").read_text() == "6.0"
    text = out.getvalue()
    assert "[rank 0]" in text and "[rank 1]" in text


@pytest.mark.slow
def test_launcher_propagates_failure(tmp_path):
    from quintnet_tpu.tools.launch_multihost import launch

    bad = tmp_path / "bad.py"
    bad.write_text("import sys; sys.exit(3)\n")
    rc = launch([sys.executable, str(bad)], nproc=2, out=io.StringIO())
    assert rc == 3

"""Observability goldens (quintnet_tpu/obs/ + the threaded hooks).

THE contract is inertness: arming the flight recorder — per-request
Tracer spans, per-step StepRecorder ring — changes NOTHING about what
the engine computes. Tracing on is token-BIT-identical to tracing off
(greedy and sampled) with prefix cache, speculation, chunked prefill
and int8 KV composed, and the compiled-program census is unchanged.
On top of that: the fleet's black box — a replica death produces a
crash dump carrying the corpse's last-known step ring and the affected
requests' spans, and those spans CONTINUE on the destination replica
under the same trace id (thread fleet in-process; process fleet across
a real SIGKILL with zero cooperation from the corpse). The Prometheus
exposition and Chrome trace-event exports are gated by actual parsers,
not shape squints. Satellites ride along: reservoir-bounded percentile
sources, zero-traffic aggregation without NaN, the per-logger
log_once fix, and trace-id round-trip over the wire.
"""

import json
import logging
import os
import signal
import time
import warnings

import jax
import numpy as np
import pytest

from quintnet_tpu.fleet import ProcessFleet, ServeFleet, Backoff, FrontDoor
from quintnet_tpu.fleet import wire
from quintnet_tpu.fleet.fleet import FleetMetrics
from quintnet_tpu.ft.chaos import ChaosMonkey
from quintnet_tpu.models.gpt2 import GPT2Config, gpt2_init
from quintnet_tpu.obs import (SPAN_NAMES, EventLog, StepRecorder,
                              Tracer, load_crash_dump,
                              parse_exposition, render_exposition,
                              write_crash_dump)
from quintnet_tpu.obs.prom import sample
from quintnet_tpu.serve import ServeEngine, gpt2_family
from quintnet_tpu.serve import metrics as serve_metrics
from quintnet_tpu.serve.metrics import Reservoir, ServeMetrics
from quintnet_tpu.serve.scheduler import RequestProgress

CFG = GPT2Config.tiny(n_layer=2)
FACTORY_FILE = os.path.join(os.path.dirname(__file__),
                            "_proc_factories.py")


@pytest.fixture(scope="module")
def params():
    return gpt2_init(jax.random.key(0), CFG)


def _engine(params, *, obs=False, **kw):
    kwargs = dict(max_slots=2, block_size=4, num_blocks=32,
                  max_seq_len=48)
    kwargs.update(kw)
    eng = ServeEngine(gpt2_family(CFG), params, **kwargs)
    if obs:
        eng.tracer = Tracer(clock=eng.clock)
        eng.recorder = StepRecorder(capacity=64, clock=eng.clock)
    return eng


def _wait_until(pred, *, timeout=120.0, msg=""):
    t0 = time.monotonic()
    while not pred():
        if time.monotonic() - t0 > timeout:
            raise AssertionError(f"timed out waiting for: {msg}")
        time.sleep(0.01)


# ---------------------------------------------------------------------
# THE inertness golden: observed == unobserved, bit for bit
# ---------------------------------------------------------------------

@pytest.mark.parametrize("combo", [
    dict(spec=True, kv_dtype="int8", temperature=0.8, top_k=5),
    dict(chunked_prefill=True, prefill_len=16, temperature=0.8,
         top_k=5),
    dict(lora=True, kv_dtype="int8", temperature=0.8, top_k=5),
], ids=["spec+int8+sampled", "chunked+sampled", "lora+int8+sampled"])
def test_tracing_is_token_bit_identical(params, rng, combo):
    """Same params, same trace, same keys — one engine with the full
    flight recorder armed, one without. Every output array must be
    bit-identical and the compile census unchanged (observation adds
    zero programs). Sampled, with prefix cache on and the combo's
    feature stack composed — the inertness acceptance gate."""
    from quintnet_tpu.models.lora import LoRAConfig, lora_init
    from quintnet_tpu.serve import AdapterRegistry

    combo = dict(combo)
    lora = combo.pop("lora", False)
    lens = (5, 9, 3, 7, 30 if combo.get("chunked_prefill") else 12)
    prompts = [np.asarray(rng.integers(0, CFG.vocab_size, (t,)),
                          np.int32) for t in lens]
    keys = [jax.random.key(100 + i) for i in range(len(prompts))]
    adapter_ids = [None] * len(prompts)

    outs = {}
    stats = {}
    obs_engine = None
    for obs in (False, True):
        kw = dict(combo)
        if lora:
            lcfg = LoRAConfig(rank=4, alpha=8.0)
            tree = lora_init(jax.random.key(77), params["blocks"],
                             lcfg)
            reg = AdapterRegistry()
            reg.register("tenantA", tree=tree, cfg=lcfg)
            kw["adapters"] = reg
            adapter_ids = ["tenantA" if i % 2 == 0 else None
                           for i in range(len(prompts))]
        eng = _engine(params, obs=obs, prefix_cache=True, **kw)
        rids = [eng.submit(p, 8, key=k, adapter_id=a)
                for p, k, a in zip(prompts, keys, adapter_ids)]
        eng.run()
        outs[obs] = [eng.result(r) for r in rids]
        stats[obs] = eng.compile_stats()
        if obs:
            obs_engine = eng
    for a, b in zip(outs[False], outs[True]):
        np.testing.assert_array_equal(a, b)
    assert stats[False] == stats[True]
    # and the observer actually observed
    assert len(obs_engine.recorder) > 0
    tids = obs_engine.tracer.trace_ids()
    assert len(tids) == len(prompts)
    names = {s.name for t in tids for s in obs_engine.tracer.spans(t)}
    assert {"submit", "queue", "admit", "finish"} <= names
    if combo.get("chunked_prefill"):
        assert "prefill_chunk" in names
    if combo.get("spec"):
        assert "verify" in names or "decode" in names
    # every emitted name is in the SPAN_NAMES registry (obs/trace.py)
    # — the registry is advisory at runtime, but it must not drift
    # from what the engine actually records
    assert names <= SPAN_NAMES, names - SPAN_NAMES


def test_tracing_inert_across_preemption(params, rng):
    """Preemption pressure (tiny pool) with tracing on vs off: same
    outputs, and the traced side recorded the preempt/resume arc."""
    prompts = [np.asarray(rng.integers(0, CFG.vocab_size, (t,)),
                          np.int32) for t in (6, 7, 6)]
    keys = [jax.random.key(7 + i) for i in range(3)]
    outs = {}
    traced = None
    for obs in (False, True):
        eng = _engine(params, obs=obs, num_blocks=8, max_seq_len=20,
                      temperature=0.7, top_k=4)
        rids = [eng.submit(p, 10, key=k)
                for p, k in zip(prompts, keys)]
        eng.run()
        outs[obs] = [eng.result(r) for r in rids]
        if obs:
            traced = eng
    for a, b in zip(outs[False], outs[True]):
        np.testing.assert_array_equal(a, b)
    assert traced.metrics.preempted > 0      # pressure actually hit
    names = [s.name for t in traced.tracer.trace_ids()
             for s in traced.tracer.spans(t)]
    assert "preempt" in names


def test_fleet_tracing_inert(params, rng):
    """Thread fleet with obs on vs off, chaos kill included: outputs
    identical (the migration path is also observation-inert)."""
    def factory():
        return ServeEngine(gpt2_family(CFG), params, max_slots=2,
                           block_size=4, num_blocks=24, max_seq_len=40,
                           temperature=0.8, top_k=5)

    prompts = [np.asarray(rng.integers(0, CFG.vocab_size, (5,)),
                          np.int32) for _ in range(4)]
    keys = [jax.random.key(40 + i) for i in range(4)]
    outs = {}
    for obs in (False, True):
        fleet = ServeFleet(
            factory, n_replicas=2, obs=obs,
            chaos=ChaosMonkey(kill_at_step=3, mode="raise",
                              target="r0"))
        try:
            fids = [fleet.submit(p, 12, key=k)
                    for p, k in zip(prompts, keys)]
            outs[obs] = [fleet.result(f, timeout=300) for f in fids]
            assert fleet.metrics.replica_deaths == 1
        finally:
            fleet.close()
    for a, b in zip(outs[False], outs[True]):
        np.testing.assert_array_equal(a, b)


# ---------------------------------------------------------------------
# crash-dump forensics
# ---------------------------------------------------------------------

def test_thread_fleet_crash_dump(params, rng, tmp_path):
    """A chaos-killed thread replica leaves a black box: the dump file
    carries its step ring and the migrated requests' spans, and those
    requests' timelines CONTINUE (restore -> finish) under the same
    trace id after migration."""
    def factory():
        return ServeEngine(gpt2_family(CFG), params, max_slots=2,
                           block_size=4, num_blocks=24, max_seq_len=40)

    fleet = ServeFleet(
        factory, n_replicas=2, obs=True, crash_dir=str(tmp_path),
        chaos=ChaosMonkey(kill_at_step=3, mode="raise", target="r0"))
    try:
        prompts = [np.asarray(rng.integers(0, CFG.vocab_size, (5,)),
                              np.int32) for _ in range(4)]
        fids = [fleet.submit(p, 12) for p in prompts]
        [fleet.result(f, timeout=300) for f in fids]
        assert fleet.metrics.replica_deaths == 1
        # the dump file is written by the dispatcher OUTSIDE the fleet
        # lock — wait for the flush, don't race it
        _wait_until(lambda: len(fleet.crash_dumps) == 1,
                    msg="crash dump flushed")
        dump = load_crash_dump(fleet.crash_dumps[0])
        assert dump["replica"] == "r0"
        assert dump["reason"] == "death"
        assert len(dump["ring"]) >= 1            # the corpse's steps
        assert dump["requests"], "affected requests recorded"
        for r in dump["requests"]:
            assert r["trace_id"] in dump["traces"]
            assert dump["traces"][r["trace_id"]]
            # continuation: the SAME id later carries the restore on
            # the survivor and the finish
            names = [s.name
                     for s in fleet.tracer.spans(r["trace_id"])]
            assert "migration" in names
            assert "restore" in names
            assert names.index("restore") > names.index("migration")
            assert "finish" in names
        kinds = [e["kind"] for e in fleet.events.snapshot()]
        assert "replica_death" in kinds
        assert "migration" in kinds
        assert "crash_dump" in kinds
        assert "replica_restart" in kinds or "breaker" in kinds
    finally:
        fleet.close()


def test_process_fleet_sigkill_crash_dump(params, rng, tmp_path):
    """THE acceptance golden on the PR 8 harness: a real
    ``os.kill(pid, SIGKILL)`` mid-stream produces a crash dump
    containing the dead replica's (heartbeat-mirrored) step ring and
    the migrated requests' spans — assembled with zero cooperation
    from the corpse — and the migrated requests' spans CONTINUE on the
    destination replica under the same trace id, while every output
    stays token-identical to the undisturbed oracle."""
    from quintnet_tpu.models.gpt2_generate import gpt2_generate

    max_new = 64       # a tiny model decodes in a burst; the stream
    #                    must outlive a few heartbeats so the mirror
    #                    is non-empty when the kill lands mid-flight
    spec = {"file": FACTORY_FILE, "func": "build_tiny_gpt2",
            "kwargs": {"max_seq_len": 110, "n_positions": 128,
                       "num_blocks": 64}}
    # heartbeat_budget_s generous on purpose: the default (1s) lets a
    # freshly-RESTARTED child on a loaded CI box false-trip the stall
    # detector and write a SECOND dump, which is not what this golden
    # probes (the stall path has its own test in test_fleet_proc.py)
    fleet = ProcessFleet(spec, n_replicas=2, policy="round_robin",
                         platform="cpu", heartbeat_s=0.005,
                         heartbeat_budget_s=5.0,
                         backoff=Backoff(base_s=0.01, cap_s=0.1),
                         obs=True, crash_dir=str(tmp_path))
    try:
        prompts = [np.asarray(rng.integers(0, CFG.vocab_size, (t,)),
                              np.int32) for t in (5, 7, 3, 6)]
        keys = [jax.random.key(500 + i) for i in range(4)]
        streamed = []
        fids = []
        for i, (p, k) in enumerate(zip(prompts, keys)):
            cb = ((lambda fid, tok, last:
                   streamed.append(tok)) if i == 1 else None)
            fids.append(fleet.submit(p, max_new, key=k, on_token=cb))
        victim = fleet.replica("p1")     # round_robin: i=1 -> p1
        # kill mid-stream AND after at least one heartbeat shipped
        # step records — the mirror is "last-known", and last-known
        # must be non-empty for the dump to mean anything
        _wait_until(lambda: len(streamed) >= 2 and len(victim.ring) > 0,
                    msg="victim streaming with a mirrored ring")
        assert len(streamed) < max_new
        os.kill(victim.pid, signal.SIGKILL)

        outs = [fleet.result(f, timeout=300) for f in fids]
        cfg_128 = GPT2Config.tiny(n_layer=2, n_positions=128)
        params_128 = gpt2_init(jax.random.key(0), cfg_128)
        for p, k, o in zip(prompts, keys, outs):
            np.testing.assert_array_equal(
                o, np.asarray(gpt2_generate(
                    params_128, p[None], cfg_128,
                    max_new_tokens=max_new,
                    temperature=0.0, key=k)[0]))
        assert fleet.metrics.replica_deaths == 1
        assert fleet.metrics.migrations >= 1

        # >= 1, first dump: a later incidental event (e.g. a
        # load-starved restarted child) must not deadlock the wait —
        # the DEATH dump this golden is about is always the first
        _wait_until(lambda: len(fleet.crash_dumps) >= 1,
                    msg="crash dump flushed")
        dump = load_crash_dump(fleet.crash_dumps[0])
        assert dump["replica"] == "p1"
        assert dump["reason"] == "death"
        assert len(dump["ring"]) >= 1        # the corpse's last-known
        assert all("step" in r and "t0" in r and "t1" in r
                   for r in dump["ring"])
        assert dump["requests"]
        migrated_tids = [r["trace_id"] for r in dump["requests"]]
        for tid in migrated_tids:
            assert dump["traces"].get(tid), \
                f"no spans for migrated {tid} in the dump"

        # continuation on the DESTINATION replica, same trace id: the
        # survivor's engine recorded restore -> decode -> finish under
        # the id the journal carried over the wire
        dest = fleet.replica_traces("p0", migrated_tids)
        for tid in migrated_tids:
            names = [s["name"] for s in dest.get(tid, [])]
            assert "restore" in names, (tid, names)
            assert "finish" in names, (tid, names)
        kinds = [e["kind"] for e in fleet.events.snapshot()]
        assert "replica_death" in kinds
        assert "migration" in kinds
        assert "crash_dump" in kinds
    finally:
        fleet.drain(timeout=180)


def test_crash_dump_file_roundtrip(tmp_path):
    path = write_crash_dump(
        str(tmp_path), replica="rX", reason="stall", error="wedged",
        ring=[{"step": 1, "t0": 0.0, "t1": 0.1}],
        traces={"f0": [{"trace_id": "f0", "name": "queue",
                        "t0": 0.0, "t1": 0.2, "attrs": {}}]},
        events=[{"ts": 0.0, "seq": 1, "kind": "replica_stall"}],
        requests=[{"fid": 0, "trace_id": "f0", "committed": 3}])
    dump = load_crash_dump(path)
    assert dump["replica"] == "rX" and dump["reason"] == "stall"
    assert dump["ring"] and dump["traces"]["f0"]
    # two dumps in the same second must not collide
    path2 = write_crash_dump(str(tmp_path), replica="rX",
                             reason="death")
    assert path2 != path
    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps({"kind": "crash_dump", "v": 999}))
    with pytest.raises(ValueError, match="version"):
        load_crash_dump(str(bad))


# ---------------------------------------------------------------------
# obs primitives
# ---------------------------------------------------------------------

def test_tracer_bounds_and_merge():
    clk = [0.0]
    tr = Tracer(clock=lambda: clk[0], max_traces=2,
                max_spans_per_trace=8)
    for i in range(20):
        clk[0] = float(i)
        tr.add("a", f"s{i}")
    spans = tr.spans("a")
    assert len(spans) == 8                      # bounded
    assert spans[0].name == "s0"                # first kept (anchor)
    assert spans[-1].name == "s19"              # latest kept
    assert tr.dropped("a") == 12
    tr.add("b", "x")
    tr.add("c", "y")                            # evicts oldest trace
    assert "a" not in tr.trace_ids()
    # merge: another tracer's snapshot folds in under the same ids
    other = Tracer()
    other.add("b", "remote", t0=1.0, t1=2.0, replica="p1")
    tr.merge(other.snapshot())
    assert [s.name for s in tr.spans("b")] == ["x", "remote"]
    # None trace_id is a no-op, not an error
    tr.add(None, "ignored")


def test_recorder_ring_and_drain():
    from quintnet_tpu.obs.recorder import StepRecord

    rec = StepRecorder(capacity=4)
    for i in range(3):
        rec.record(StepRecord(step=i + 1, t0=float(i),
                              t1=float(i) + 0.5))
    assert [r["step"] for r in rec.drain_new()] == [1, 2, 3]
    assert rec.drain_new() == []                # cursor advanced
    for i in range(3, 10):                      # overflow the ring
        rec.record(StepRecord(step=i + 1, t0=float(i),
                              t1=float(i) + 0.5))
    assert len(rec) == 4 and rec.total == 10
    # records that scrolled off before a drain are lost, not
    # re-shipped: only the surviving window arrives, exactly once
    drained = rec.drain_new()
    assert [r["step"] for r in drained] == [7, 8, 9, 10]
    assert rec.drain_new() == []
    # max_records caps one drain; the rest comes next call
    for i in range(10, 14):
        rec.record(StepRecord(step=i + 1, t0=float(i),
                              t1=float(i) + 0.5))
    assert len(rec.drain_new(max_records=3)) == 3
    assert [r["step"] for r in rec.drain_new()] == [14]


def test_event_log_typed_and_jsonl(tmp_path):
    path = tmp_path / "events.jsonl"
    log = EventLog(path=str(path), capacity=4)
    log.emit("replica_death", replica="p0", error="boom")
    log.emit("migration", fid=3)
    with pytest.raises(ValueError, match="unknown event kind"):
        log.emit("oops")
    assert [e["kind"] for e in log.snapshot()] == ["replica_death",
                                                   "migration"]
    assert log.snapshot(kind="migration")[0]["fid"] == 3
    log.close()
    lines = [json.loads(ln) for ln in
             path.read_text().strip().splitlines()]
    assert [ln["kind"] for ln in lines] == ["replica_death",
                                            "migration"]
    assert lines[0]["seq"] == 1 and lines[1]["seq"] == 2


def test_prometheus_render_and_parse(params, rng):
    """render_exposition over REAL ledgers parses with the strict
    parser; samples are addressable by name + labels; malformed text
    is rejected."""
    eng = _engine(params)
    rids = [eng.submit(np.asarray(rng.integers(0, CFG.vocab_size, (5,)),
                                  np.int32), 6) for _ in range(2)]
    eng.run()
    fm = FleetMetrics()
    fm.submitted = 2
    fm.finished = 2
    text = render_exposition(
        fm.summary(), {"r0": eng.metrics.summary()},
        health={"replicas": {"r0": {"state": "healthy"}},
                "queue_depth": 0, "open_requests": 0})
    parsed = parse_exposition(text)
    assert sample(parsed, "quintnet_fleet_finished") == 2.0
    assert sample(parsed, "quintnet_engine_finished",
                  replica="r0") == 2.0
    assert sample(parsed, "quintnet_engine_ttft_s", replica="r0",
                  quantile="p50") >= 0.0
    assert sample(parsed, "quintnet_engine_ttft_s_count",
                  replica="r0") == 2.0
    assert sample(parsed, "quintnet_replica_up", replica="r0") == 1.0
    # one TYPE header per metric name (the format's requirement)
    types = [ln for ln in text.splitlines()
             if ln.startswith("# TYPE")]
    assert len(types) == len({ln.split()[2] for ln in types})
    with pytest.raises(ValueError):
        parse_exposition("this is not { exposition\n")
    assert rids


def test_trace_view_chrome_export(params, rng, tmp_path):
    """The Perfetto export validates as Chrome trace-event JSON (the
    acceptance parser, not a shape squint), covers steps AND request
    spans, and the CLI round-trips a crash dump."""
    from tools.trace_view import chrome_trace, validate_chrome_trace
    import tools.trace_view as trace_view

    eng = _engine(params, obs=True, chunked_prefill=True,
                  prefill_len=16)
    rid = eng.submit(np.asarray(rng.integers(0, CFG.vocab_size, (30,)),
                                np.int32), 6)
    eng.run()
    trace = chrome_trace(eng.recorder.snapshot(),
                         eng.tracer.snapshot())
    n = validate_chrome_trace(trace)
    assert n > 0
    # json-serializable end to end
    reparsed = json.loads(json.dumps(trace))
    assert validate_chrome_trace(reparsed) == n
    phases = {e["ph"] for e in trace["traceEvents"]}
    assert {"M", "X", "i"} <= phases             # steps + instants
    assert "b" in phases and "e" in phases       # async request spans
    x = [e for e in trace["traceEvents"] if e["ph"] == "X"]
    assert all(e["dur"] >= 0 for e in x)
    assert any(e["args"].get("prefill_chunks", 0) > 0 for e in x)
    # unbalanced async must be rejected
    bad = {"traceEvents": [
        {"name": "q", "ph": "e", "ts": 0, "pid": 1, "cat": "r",
         "id": "f0"}]}
    with pytest.raises(ValueError, match="without begin"):
        validate_chrome_trace(bad)
    # the CLI path over a crash-dump-shaped file
    dump_path = tmp_path / "dump.json"
    dump_path.write_text(json.dumps(
        {"ring": eng.recorder.snapshot(),
         "traces": eng.tracer.snapshot()}))
    out_path = tmp_path / "trace.json"
    assert trace_view.main([str(dump_path), "-o", str(out_path)]) == 0
    validate_chrome_trace(json.loads(out_path.read_text()))
    assert rid == 0


def test_frontdoor_metrics_endpoints(params, rng):
    """GET /metrics parses as Prometheus text exposition (acceptance)
    and GET /v1/metrics is explicit application/json carrying the
    per-replica engine_summary."""
    import http.client

    def factory():
        return ServeEngine(gpt2_family(CFG), params, max_slots=2,
                           block_size=4, num_blocks=24, max_seq_len=24)

    fleet = ServeFleet(factory, n_replicas=2, obs=True)
    try:
        fleet.generate([np.asarray(rng.integers(0, CFG.vocab_size,
                                                (5,)), np.int32)],
                       max_new_tokens=6, timeout=300)
        with FrontDoor(fleet) as fd:
            conn = http.client.HTTPConnection(fd.host, fd.port,
                                              timeout=60)
            conn.request("GET", "/metrics")
            r = conn.getresponse()
            assert r.status == 200
            assert r.getheader("Content-Type").startswith(
                "text/plain; version=0.0.4")
            parsed = parse_exposition(r.read().decode())
            assert sample(parsed, "quintnet_fleet_finished") == 1.0
            ups = [v for (name, _l), v in parsed.items()
                   if name == "quintnet_replica_up"]
            assert len(ups) == 2 and all(v == 1.0 for v in ups)
            assert any(name == "quintnet_engine_gen_tokens"
                       for name, _l in parsed)

            conn2 = http.client.HTTPConnection(fd.host, fd.port,
                                               timeout=60)
            conn2.request("GET", "/v1/metrics")
            r2 = conn2.getresponse()
            assert r2.status == 200
            assert r2.getheader("Content-Type") == "application/json"
            body = json.loads(r2.read())
            assert body["frontdoor"]["finished"] == 1
            assert set(body["engine_summary"]) == {"r0", "r1"}
            assert all("gen_tokens" in s
                       for s in body["engine_summary"].values())
    finally:
        fleet.close()


# ---------------------------------------------------------------------
# satellites
# ---------------------------------------------------------------------

def test_reservoir_bounds_percentile_sources():
    r = Reservoir(cap=8, seed=1)
    for x in range(5):
        r.append(float(x))
    assert r.n == 5 and len(r) == 5             # exact below the cap
    assert sorted(r) == [0.0, 1.0, 2.0, 3.0, 4.0]
    for x in range(5, 1000):
        r.append(float(x))
    assert r.n == 1000 and len(r) == 8          # bounded above it
    assert all(0.0 <= x < 1000.0 for x in r)
    # deterministic: same seed, same stream -> same retained sample
    r2 = Reservoir(cap=8, seed=1)
    r2.extend(float(x) for x in range(1000))
    assert r.to_list() == r2.to_list()


def test_serve_metrics_reservoir_and_count_surfaced():
    clk = [0.0]
    m = ServeMetrics(clock=lambda: clk[0])
    m.ttfts = Reservoir(cap=16)
    for i in range(100):
        m.record_first_token(i / 100.0, adapter_id="t0")
        m.record_finish(i / 10.0, adapter_id="t0")
        m.record_itl(0.01)
    s = m.summary()
    assert s["ttft_s"]["n"] == 100              # TRUE count surfaced
    assert s["latency_s"]["n"] == 100
    assert s["itl_s"]["n"] == 100
    assert len(m.ttfts) == 16                   # storage bounded
    assert s["adapters"]["t0"]["ttft_s"]["n"] == 100
    assert len(m.per_adapter["t0"]["ttfts"]) <= \
        serve_metrics.RESERVOIR_CAP
    # aggregate pools retained samples and SUMS true counts
    m2 = ServeMetrics(clock=lambda: clk[0])
    m2.record_first_token(0.5, adapter_id="t0")
    agg = serve_metrics.aggregate([m, m2])
    assert agg["ttft_s"]["n"] == 101
    assert agg["adapters"]["t0"]["ttft_s"]["n"] == 101


def test_aggregate_weights_capped_reservoirs_by_true_count():
    """A busy replica whose reservoir hit its cap must not be
    out-voted by a quiet one: pooling weights each retained sample by
    the observations it represents, so fleet percentiles track the
    TRUE traffic mix (naive equal-weight pooling would report the
    quiet replica's tail as the fleet median)."""
    busy = ServeMetrics()
    busy.ttfts = Reservoir(cap=64)
    for _ in range(10000):
        busy.ttfts.append(0.01)          # 10k fast requests, sampled
    quiet = ServeMetrics()
    for _ in range(100):
        quiet.ttfts.append(1.0)          # 100 slow requests, exact
    agg = serve_metrics.aggregate([busy, quiet])
    assert agg["ttft_s"]["n"] == 10100
    # true mix is ~99% fast: every reported percentile up to p99 must
    # be the fast value (equal-weight pooling of 64 vs 100 samples
    # would have said 1.0 at p50)
    assert agg["ttft_s"]["p50"] == 0.01
    assert agg["ttft_s"]["p95"] == 0.01
    # below every cap the pooled result stays the plain exact pooling
    a, b = ServeMetrics(), ServeMetrics()
    a.ttfts.extend([0.1, 0.2, 0.3])
    b.ttfts.extend([0.4])
    exact = serve_metrics.aggregate([a, b])
    assert exact["ttft_s"]["n"] == 4
    assert exact["ttft_s"]["p50"] == float(
        np.percentile([0.1, 0.2, 0.3, 0.4], 50))


def test_zero_traffic_aggregation_no_nan():
    """aggregate() and FleetMetrics.summary() over zero-step engines:
    zeroed dicts, finite floats, NO RuntimeWarning (the StepTimer fix
    from PR 4, applied one layer up)."""
    def _all_finite(obj):
        if isinstance(obj, dict):
            return all(_all_finite(v) for v in obj.values())
        if isinstance(obj, (int, float)):
            return np.isfinite(obj)
        return True

    with warnings.catch_warnings():
        warnings.simplefilter("error")
        empty = serve_metrics.aggregate([])
        assert empty["replicas"] == 0
        assert empty["tokens_per_sec"] == 0.0
        assert empty["ttft_s"] == {"p50": 0.0, "p95": 0.0,
                                   "p99": 0.0, "n": 0}
        assert _all_finite(empty)

        fresh = serve_metrics.aggregate([ServeMetrics(),
                                         ServeMetrics()])
        assert fresh["replicas"] == 2
        assert fresh["steps"] == 0
        assert fresh["prefix_hit_rate"] == 0.0
        assert fresh["tokens_per_decode_step"] == 0.0
        assert _all_finite(fresh)

        fm = FleetMetrics().summary()
        assert fm["finished"] == 0 and fm["shed_rate"] == 0.0
        assert fm["ttft_s"]["n"] == 0
        assert _all_finite(fm)

        one = ServeMetrics().summary()
        assert one["tokens_per_sec"] == 0.0
        assert _all_finite(one)


def test_log_once_keyed_by_logger(capsys):
    from quintnet_tpu.utils.logger import log_once, setup_logging

    a = setup_logging(name="qt-test-a")
    b = setup_logging(name="qt-test-b")
    msg = "unique-warning-xyz"
    log_once(a, msg)
    log_once(b, msg)       # a DIFFERENT logger must not be deduped
    log_once(a, msg)       # the same one must
    log_once(b, msg)
    out = capsys.readouterr().out
    assert out.count(msg) == 2


def test_exposition_label_escaping_round_trips():
    """Label values carrying the format's three special characters —
    backslash, double quote, newline — render as ONE well-formed line
    each and parse back to the ORIGINAL value (backslash first in the
    escaper, or it would re-escape the others)."""
    nasty = {
        "q": 'say "hi"',
        "b": "back\\slash",
        "n": "two\nlines",
        "all": 'a\\b"c\nd',
    }
    fm = FleetMetrics()
    fm.finished = 1
    text = render_exposition(
        fm.summary(),
        {name: {"finished": 1} for name in nasty.values()})
    for line in text.splitlines():
        assert "\n" not in line                  # one line per sample
    parsed = parse_exposition(text)
    for raw in nasty.values():
        assert sample(parsed, "quintnet_engine_finished",
                      replica=raw) == 1.0        # round-tripped exact


def test_exposition_parser_rejects_invalid_escape():
    with pytest.raises(ValueError, match="invalid escape"):
        parse_exposition('m{l="bad\\t"} 1\n')
    # the three legal escapes parse
    parsed = parse_exposition('m{l="a\\\\b\\"c\\nd"} 1\n')
    assert sample(parsed, "m", l='a\\b"c\nd') == 1.0


def test_exposition_drops_non_finite_and_parser_rejects_them():
    """The renderer NEVER serves NaN/Inf (an absent sample is honest;
    a NaN poisons every rate() downstream) — and the strict parser
    treats a non-finite sample in OUR exposition as proof a second,
    unguarded accounting path leaked in."""
    fm = FleetMetrics()
    fm.finished = 3
    text = render_exposition(
        fm.summary(),
        {"r0": {"finished": 2.0, "bad_nan": float("nan"),
                "bad_inf": float("inf"),
                "bad_ninf": float("-inf")}})
    parsed = parse_exposition(text)              # strict gate passes
    assert sample(parsed, "quintnet_engine_finished", replica="r0") == 2.0
    for name, _labels in parsed:
        assert "bad_nan" not in name and "bad_inf" not in name
    # the format ALLOWS NaN/Inf tokens; our parser rejects each form
    for tok in ("NaN", "nan", "+Inf", "-Inf", "inf"):
        with pytest.raises(ValueError, match="non-finite"):
            parse_exposition(f"leaked_metric {tok}\n")


def test_exposition_single_series_per_queue_gauge():
    """summary() and health() both know the queue gauges since the
    signal plane landed; the renderer must emit each series ONCE
    (duplicate name+labels lines are off the format — Prometheus
    rejects the whole scrape) and the strict parser is the gate that
    catches a second accounting path leaking in."""
    fm = FleetMetrics()
    fm._queue_probe = lambda: (3, 1.5)
    health = {"replicas": {}, "queue_depth": 4,
              "queue_oldest_wait_s": 9.9, "open_requests": 2}
    text = render_exposition(fm.summary(), health=health)
    parsed = parse_exposition(text)              # raises on duplicates
    # summary won: one series, the summary's value
    assert sample(parsed, "quintnet_fleet_queue_depth") == 3.0
    assert sample(parsed, "quintnet_fleet_queue_oldest_wait_s") == 1.5
    # keys only health carries still render (the fallback)
    assert sample(parsed, "quintnet_fleet_open_requests") == 2.0
    # and the parser really does reject a duplicate series
    with pytest.raises(ValueError, match="duplicate sample"):
        parse_exposition("m 1\nm 2\n")
    parse_exposition('m{a="x"} 1\nm{a="y"} 2\n')  # labels differ: fine


def test_crash_dir_bounded_keeps_newest(tmp_path):
    """A flapping replica must not grow crash_dir without limit: after
    each write only the newest ``keep`` dumps survive (and keep=None
    disables pruning)."""
    paths = []
    for i in range(7):
        paths.append(write_crash_dump(
            str(tmp_path), replica=f"p{i}", reason="death", keep=4))
        os.utime(paths[-1], (i + 1.0, i + 1.0))  # monotone mtimes
    names = sorted(os.listdir(tmp_path))
    assert len(names) == 4
    kept = {os.path.basename(p) for p in paths[-4:]}
    assert set(names) == kept
    # the newest dumps are the ones still loadable
    for p in paths[-4:]:
        assert load_crash_dump(p)["replica"] in {"p3", "p4", "p5", "p6"}
    # keep=None: no pruning
    for i in range(3):
        write_crash_dump(str(tmp_path), replica="x", reason="stall",
                         keep=None)
    assert len(os.listdir(tmp_path)) == 7
    # an invalid keep is rejected BEFORE the dump is written — a
    # post-write raise would leave the dir growing un-pruned forever
    with pytest.raises(ValueError, match="keep"):
        write_crash_dump(str(tmp_path), replica="x", reason="stall",
                         keep=0)
    assert len(os.listdir(tmp_path)) == 7        # nothing landed


def test_trace_view_renders_slo_events_as_global_markers(tmp_path):
    """slo_breach / slo_recovered / rebalance_recommended lifecycle
    events become instant markers on the "fleet events" track —
    SLO-judgment kinds FULL-HEIGHT (scope "g") so they line up against
    every other track, ordinary kinds thread-local ticks — and the CLI
    renders a dump whose only payload is events."""
    from tools.trace_view import chrome_trace, validate_chrome_trace
    import tools.trace_view as trace_view

    events = [
        {"ts": 10.0, "seq": 1, "kind": "slo_breach",
         "objective": "ttft_p99", "pool": "prefill",
         "burn_fast": 4.2, "burn_slow": 3.0},
        {"ts": 10.5, "seq": 2, "kind": "rebalance_recommended",
         "direction": "decode_to_prefill", "revert": False},
        {"ts": 12.0, "seq": 3, "kind": "slo_recovered",
         "objective": "ttft_p99", "pool": "prefill"},
        {"ts": 12.5, "seq": 4, "kind": "rebalance_recommended",
         "direction": "prefill_to_decode", "revert": True},
        {"ts": 11.0, "seq": 5, "kind": "replica_death",
         "replica": "p1"},
        {"not_an_event": True},                  # skipped, not guessed
    ]
    trace = chrome_trace(fleet_events=events)
    assert validate_chrome_trace(trace) > 0
    inst = {e["name"]: e for e in trace["traceEvents"]
            if e["ph"] == "i"}
    breach = inst["slo_breach ttft_p99 [prefill] 4.2x"]
    assert breach["s"] == "g"                    # full-height marker
    assert breach["args"]["burn_fast"] == 4.2
    assert inst["rebalance decode_to_prefill"]["s"] == "g"
    assert inst["rebalance prefill_to_decode (revert)"]["s"] == "g"
    assert inst["slo_recovered"]["s"] == "g"
    assert inst["replica_death"]["s"] == "t"     # ordinary tick
    # timestamps re-based to the earliest event (t=10.0 -> 0us)
    assert breach["ts"] == 0.0
    assert inst["replica_death"]["ts"] == pytest.approx(1e6)
    # the CLI path over an events-only dump (crash dumps embed the
    # ring+traces too; a bare event ring must still render)
    dump = tmp_path / "events.json"
    dump.write_text(json.dumps({"events": events}))
    out = tmp_path / "trace.json"
    assert trace_view.main([str(dump), "-o", str(out)]) == 0
    rendered = json.loads(out.read_text())
    assert validate_chrome_trace(rendered) > 0
    assert any(e.get("name", "").startswith("slo_breach")
               for e in rendered["traceEvents"])


def test_trace_id_rides_the_wire():
    p = RequestProgress(
        rid=1, prompt=np.arange(3, dtype=np.int32), generated=[7],
        key_data=np.zeros((4,), np.uint32), max_new_tokens=4,
        trace_id="f42")
    back = wire.progress_from_wire(wire.progress_to_wire(p))
    assert back.trace_id == "f42"
    # pre-obs payloads (no field) decode to None, not KeyError
    payload = wire.progress_to_wire(p)
    del payload["trace_id"]
    assert wire.progress_from_wire(payload).trace_id is None

"""SLO engine + pool-pressure signal plane goldens (obs/slo.py,
obs/signals.py + the fleet threading).

The judgment layer's contract, pinned here:

- **burn-rate math**: latency objectives burn at
  ``frac(obs > target) / (1 - quantile)``, rate objectives at
  ``mean / target``; empty windows burn 0.0 — zero traffic is
  compliant, never NaN;
- **multi-window breach state machine**: a breach requires BOTH the
  fast and slow window at/over the threshold (a fast-only spike is
  noise, a slow-only tail is old news); recovery is the FAST window
  dropping back under — with typed ``slo_breach``/``slo_recovered``
  events carrying per-pool attribution (TTFT -> prefill, ITL ->
  decode), all under an injectable clock so no test sleeps;
- **signal bus**: EWMA smoothing decays on CLOCK time (half-life),
  history is bounded, ``gauges()``/``snapshot()`` are JSON-able;
- **planner**: observe-only — recommendations fire only with a
  one-pool breach + donor headroom, once per direction (hysteresis),
  past the cooldown, and the recovery path recommends the REVERT;
  it holds no fleet references and mutates nothing;
- **inertness** (THE acceptance gate): a fleet with the SLO engine +
  signal bus armed produces BIT-identical output to one without —
  sampled, int8 KV, chunked prefill, under a chaos kill — with the
  compile census unchanged;
- the satellites: ``AdmissionQueue.oldest_wait_s`` (and its surfacing
  in ``FleetMetrics.summary()`` + the front door's 429 Retry-After
  hint), ``/healthz`` degraded-on-breach naming the objectives, and
  ``GET /metrics`` serving ``quintnet_slo_*`` +
  ``quintnet_pool_pressure_*`` through the strict-parser gate.
"""

import json
import time
import types

import jax
import numpy as np
import pytest

from quintnet_tpu.fleet import FrontDoor, ServeFleet
from quintnet_tpu.fleet.admission import AdmissionQueue
from quintnet_tpu.fleet.fleet import FleetMetrics
from quintnet_tpu.ft.chaos import ChaosMonkey
from quintnet_tpu.models.gpt2 import GPT2Config, gpt2_init
from quintnet_tpu.obs import (EventLog, Objective, PoolRebalancePlanner,
                              SignalBus, SLOConfig, SLOEngine,
                              parse_exposition, render_exposition)
from quintnet_tpu.obs.prom import sample
from quintnet_tpu.obs.signals import Ewma
from quintnet_tpu.obs.slo import LATENCY, RATE, burn_rate
from quintnet_tpu.serve import ServeEngine, gpt2_family

CFG = GPT2Config.tiny(n_layer=2)


@pytest.fixture(scope="module")
def params():
    return gpt2_init(jax.random.key(0), CFG)


class _Clock:
    def __init__(self, t=0.0):
        self.t = float(t)

    def __call__(self):
        return self.t

    def tick(self, dt):
        self.t += dt
        return self.t


def _config(**kw):
    kwargs = dict(fast_window_s=10.0, slow_window_s=60.0,
                  burn_threshold=2.0)
    kwargs.update(kw)
    return SLOConfig.serving(ttft_p99_s=0.5, itl_p99_s=0.1,
                             error_rate=0.01, shed_rate=0.05, **kwargs)


# ---------------------------------------------------------------------
# objective / config validation
# ---------------------------------------------------------------------

class TestDeclarations:
    def test_serving_preset_attribution(self):
        cfg = _config()
        by = {o.name: o for o in cfg.objectives}
        assert by["ttft_p99"].pool == "prefill"     # DistServe axes
        assert by["itl_p99"].pool == "decode"
        assert by["error_rate"].pool == "any"
        assert by["shed_rate"].kind == RATE
        assert by["ttft_p99"].kind == LATENCY
        # pass only what you promise
        one = SLOConfig.serving(ttft_p99_s=1.0)
        assert [o.name for o in one.objectives] == ["ttft_p99"]

    def test_objective_validation(self):
        with pytest.raises(ValueError, match="kind"):
            Objective("x", stream="s", kind="latencey", target=1.0)
        with pytest.raises(ValueError, match="target"):
            Objective("x", stream="s", kind=LATENCY, target=0.0)
        with pytest.raises(ValueError, match="fraction"):
            Objective("x", stream="s", kind=RATE, target=1.5)
        with pytest.raises(ValueError, match="quantile"):
            Objective("x", stream="s", kind=LATENCY, target=1.0,
                      quantile=1.0)
        with pytest.raises(ValueError, match="burn_threshold"):
            Objective("x", stream="s", kind=LATENCY, target=1.0,
                      burn_threshold=-1.0)

    def test_config_validation(self):
        ok = Objective("x", stream="s", kind=LATENCY, target=1.0)
        with pytest.raises(ValueError, match="at least one"):
            SLOConfig(objectives=())
        with pytest.raises(ValueError, match="duplicate"):
            SLOConfig(objectives=(ok, ok))
        with pytest.raises(ValueError, match="fast_window_s"):
            SLOConfig(objectives=(ok,), fast_window_s=60.0,
                      slow_window_s=60.0)
        with pytest.raises(ValueError, match="max_samples"):
            SLOConfig(objectives=(ok,), max_samples=2)


# ---------------------------------------------------------------------
# burn-rate math
# ---------------------------------------------------------------------

class TestBurnRate:
    def test_latency_burn_is_bad_fraction_over_budget(self):
        o = Objective("ttft", stream="ttft", kind=LATENCY, target=1.0,
                      quantile=0.99)
        # 2 of 10 over target: frac 0.2 against a 0.01 budget = 20x
        vals = [0.5] * 8 + [2.0, 3.0]
        assert burn_rate(o, vals) == pytest.approx(20.0)
        # exactly at target is NOT a violation (promise is <=)
        assert burn_rate(o, [1.0] * 10) == 0.0
        # all good burns 0, all bad burns 1/(1-q)
        assert burn_rate(o, [0.1]) == 0.0
        assert burn_rate(o, [9.0]) == pytest.approx(100.0)

    def test_rate_burn_is_mean_over_target(self):
        o = Objective("err", stream="error", kind=RATE, target=0.01)
        assert burn_rate(o, [0.0] * 99 + [1.0]) == pytest.approx(1.0)
        assert burn_rate(o, [1.0, 0.0, 0.0, 0.0]) == pytest.approx(25.0)
        assert burn_rate(o, [0.0] * 10) == 0.0

    def test_empty_window_burns_zero_never_nan(self):
        for o in _config().objectives:
            b = burn_rate(o, [])
            assert b == 0.0 and np.isfinite(b)


# ---------------------------------------------------------------------
# the multi-window breach state machine (injectable clock, no sleeps)
# ---------------------------------------------------------------------

class TestBreachStateMachine:
    def _engine(self, **kw):
        clk = _Clock()
        log = EventLog(clock=clk)
        eng = SLOEngine(_config(**kw), clock=clk, events=log)
        return eng, clk, log

    def test_fast_spike_alone_is_not_a_breach(self):
        eng, clk, log = self._engine()
        # old good traffic fills the slow window; then a fresh spike
        for _ in range(50):
            eng.observe("ttft", 0.1)
            eng.observe("ttft", 0.1)
            clk.tick(1.0)
        eng.observe("ttft", 5.0)                 # one fresh bad obs
        st = eng.evaluate()
        ttft = st["objectives"]["ttft_p99"]
        assert ttft["burn_fast"] >= 2.0          # fast window IS hot
        assert ttft["burn_slow"] < 2.0           # slow window is not
        assert not ttft["breaching"]
        assert log.snapshot(kind="slo_breach") == []

    def test_breach_needs_both_windows_and_recovery_is_fast_window(self):
        eng, clk, log = self._engine()
        # sustained bad traffic: both windows burn -> breach edge
        for _ in range(20):
            eng.observe("ttft", 5.0)
            clk.tick(0.2)
        st = eng.evaluate()
        ttft = st["objectives"]["ttft_p99"]
        assert ttft["breaching"]
        assert ttft["burn_fast"] >= 2.0 and ttft["burn_slow"] >= 2.0
        assert st["breaching"] == ["ttft_p99"]
        breaches = log.snapshot(kind="slo_breach")
        assert len(breaches) == 1                # ONE edge, no re-spam
        assert breaches[0]["objective"] == "ttft_p99"
        assert breaches[0]["pool"] == "prefill"  # attribution
        assert breaches[0]["burn_fast"] >= 2.0
        assert breaches[0]["burn_slow"] >= 2.0

        # still breaching while the fast window holds the bad samples
        assert eng.evaluate()["objectives"]["ttft_p99"]["breaching"]
        assert len(log.snapshot(kind="slo_breach")) == 1

        # slide PAST the fast window: fast empties (burns 0) while the
        # slow window still remembers -> recovery, attributed the same
        clk.tick(11.0)
        st = eng.evaluate()
        ttft = st["objectives"]["ttft_p99"]
        assert not ttft["breaching"]
        assert ttft["burn_fast"] == 0.0
        assert ttft["burn_slow"] >= 2.0          # memory, not judgment
        rec = log.snapshot(kind="slo_recovered")
        assert len(rec) == 1 and rec[0]["pool"] == "prefill"
        assert ttft["breaches_total"] == 1
        # peak fast burn survives recovery (the bench reports it)
        assert ttft["burn_fast_peak"] >= 2.0

    def test_itl_breach_names_the_decode_pool(self):
        eng, clk, log = self._engine()
        for _ in range(20):
            eng.observe("itl", 1.0)
            clk.tick(0.2)
        eng.evaluate()
        b = log.snapshot(kind="slo_breach")
        assert [e["pool"] for e in b] == ["decode"]

    def test_rate_objective_breach_and_per_objective_threshold(self):
        clk = _Clock()
        cfg = SLOConfig(objectives=(
            Objective("shed_rate", stream="shed", kind=RATE,
                      target=0.05, burn_threshold=4.0),),
            fast_window_s=10.0, slow_window_s=60.0, burn_threshold=2.0)
        eng = SLOEngine(cfg, clock=clk)
        # mean 0.5 against target 0.05 = 10x: over the 4.0 override
        for v in [1.0, 0.0] * 10:
            eng.observe("shed", v)
            clk.tick(0.3)
        st = eng.evaluate()["objectives"]["shed_rate"]
        assert st["burn_threshold"] == 4.0
        assert st["breaching"]

    def test_zero_traffic_is_compliant_and_nan_free(self):
        eng, clk, _log = self._engine()
        for _ in range(3):
            clk.tick(100.0)
            st = eng.evaluate()
            assert st["breaching"] == []
            for o in st["objectives"].values():
                assert o["burn_fast"] == 0.0 and o["burn_slow"] == 0.0
                assert np.isfinite(o["burn_fast"])
        json.dumps(st)                           # JSON-able as-is

    def test_unbound_stream_ignored_and_memory_bounded(self):
        eng, clk, _log = self._engine(max_samples=16)
        eng.observe("no_such_stream", 1.0)       # no objective binds it
        for _ in range(1000):
            eng.observe("ttft", 0.1)
        st = eng.evaluate()
        assert st["objectives"]["ttft_p99"]["n_slow"] <= 16
        assert clk.t == 0.0


# ---------------------------------------------------------------------
# signal plane primitives
# ---------------------------------------------------------------------

class TestSignalBus:
    def test_ewma_halflife_decays_on_clock_time(self):
        e = Ewma(halflife_s=2.0)
        assert e.update(0.0, 10.0) == 10.0       # first sample seeds
        # one half-life later the old value keeps HALF its weight
        assert e.update(2.0, 0.0) == pytest.approx(5.0)
        # zero elapsed clock = zero decay: the new sample has no weight
        assert e.update(2.0, 100.0) == pytest.approx(5.0)
        with pytest.raises(ValueError, match="halflife_s"):
            Ewma(halflife_s=0.0)

    def test_bus_smoothing_history_and_pools(self):
        clk = _Clock()
        bus = SignalBus(clock=clk, halflife_s=1.0, history=4)
        assert bus.value("occupancy") is None    # never invents
        bus.sample("occupancy", 1.0, pool="prefill")
        clk.tick(1.0)
        bus.sample("occupancy", 0.0, pool="prefill")
        assert bus.value("occupancy", "prefill") == pytest.approx(0.5)
        assert bus.value("occupancy", "prefill",
                         smoothed=False) == 0.0
        # pools are independent series
        bus.sample("occupancy", 0.25, pool="decode")
        assert bus.value("occupancy", "decode") == 0.25
        # bounded history
        for i in range(10):
            clk.tick(1.0)
            bus.sample("queue_depth", float(i))
        assert len(bus.history("queue_depth")) == 4
        g = bus.gauges()
        assert g["occupancy"]["prefill"]["n"] == 2
        assert g["queue_depth"]["fleet"]["last"] == 9.0
        json.dumps(bus.snapshot())               # crash-dump payload

    def test_bus_validation(self):
        with pytest.raises(ValueError, match="history"):
            SignalBus(history=0)


class TestPlanner:
    def _setup(self, *, occupancy=0.2, cooldown_s=5.0, **kw):
        clk = _Clock(100.0)
        log = EventLog(clock=clk)
        bus = SignalBus(clock=clk)
        bus.sample("occupancy", occupancy, pool="decode")
        bus.sample("occupancy", 0.9, pool="prefill")
        planner = PoolRebalancePlanner(clock=clk, events=log,
                                       cooldown_s=cooldown_s, **kw)
        return planner, clk, log, bus

    @staticmethod
    def _status(breach=(), fast_window=60.0):
        objectives = {
            "ttft_p99": {"pool": "prefill", "breaching":
                         "ttft_p99" in breach, "burn_fast": 4.2,
                         "burn_slow": 3.0},
            "itl_p99": {"pool": "decode", "breaching":
                        "itl_p99" in breach, "burn_fast": 2.5,
                        "burn_slow": 2.1},
        }
        return {"objectives": objectives,
                "breaching": sorted(breach),
                "fast_window_s": fast_window}

    def test_prefill_breach_recommends_decode_to_prefill(self):
        planner, _clk, log, bus = self._setup(occupancy=0.2)
        rec = planner.plan(self._status(breach=("ttft_p99",)), bus)
        assert rec is not None and not rec["revert"]
        assert rec["direction"] == "decode_to_prefill"
        assert rec["from_pool"] == "decode" and rec["to_pool"] == "prefill"
        assert rec["objective"] == "ttft_p99"
        assert rec["burn_fast"] == 4.2
        assert rec["donor_occupancy"] == pytest.approx(0.2)
        # the reason reads like the issue's example: direction, burn,
        # donor headroom, duration hint
        assert "decode replica to prefill" in rec["reason"]
        assert "4.2x" in rec["reason"]
        ev = log.snapshot(kind="rebalance_recommended")
        assert len(ev) == 1 and ev[0]["direction"] == "decode_to_prefill"
        assert planner.outstanding == "decode_to_prefill"

    def test_hysteresis_one_outstanding_direction(self):
        planner, clk, log, bus = self._setup(cooldown_s=0.0)
        st = self._status(breach=("ttft_p99",))
        assert planner.plan(st, bus) is not None
        for _ in range(5):                       # sustained breach
            clk.tick(10.0)
            assert planner.plan(st, bus) is None
        assert len(log.snapshot(kind="rebalance_recommended")) == 1

    def test_no_recommendation_without_donor_headroom(self):
        planner, _clk, log, bus = self._setup(occupancy=0.9)
        assert planner.plan(self._status(breach=("ttft_p99",)),
                            bus) is None
        # an unsampled donor gauge is also NOT headroom
        planner2, _c, _l, _b = self._setup()
        empty = SignalBus()
        assert planner2.plan(self._status(breach=("ttft_p99",)),
                             empty) is None
        assert log.snapshot(kind="rebalance_recommended") == []

    def test_both_pools_breaching_recommends_nothing(self):
        planner, _clk, _log, bus = self._setup(occupancy=0.1)
        assert planner.plan(
            self._status(breach=("ttft_p99", "itl_p99")), bus) is None

    def test_decode_breach_recommends_prefill_to_decode(self):
        planner, clk, _log, bus = self._setup()
        clk.tick(20.0)          # let the busy-prefill EWMA decay out
        bus.sample("occupancy", 0.1, pool="prefill")
        rec = planner.plan(self._status(breach=("itl_p99",)), bus)
        assert rec["direction"] == "prefill_to_decode"
        assert rec["objective"] == "itl_p99"

    def test_cooldown_gates_the_next_recommendation(self):
        planner, clk, _log, bus = self._setup(cooldown_s=5.0)
        assert planner.plan(self._status(breach=("ttft_p99",)),
                            bus) is not None
        clk.tick(1.0)                            # breach recovered fast
        assert planner.plan(self._status(), bus) is None  # cooling
        clk.tick(5.0)
        rec = planner.plan(self._status(), bus)  # now the revert fires
        assert rec["revert"] is True

    def test_recovery_recommends_the_revert_exactly_once(self):
        planner, clk, log, bus = self._setup(cooldown_s=0.0)
        planner.plan(self._status(breach=("ttft_p99",)), bus)
        clk.tick(1.0)
        rec = planner.plan(self._status(), bus)
        assert rec["revert"] is True
        assert rec["direction"] == "prefill_to_decode"  # put it back
        assert rec["objective"] is None
        assert "revert" in rec["reason"]
        assert planner.outstanding is None
        # nothing outstanding -> quiet from here on
        clk.tick(1.0)
        assert planner.plan(self._status(), bus) is None
        ev = log.snapshot(kind="rebalance_recommended")
        assert [e["revert"] for e in ev] == [False, True]
        # bounded ledger
        assert len(planner.recommendations) == 2
        json.dumps(list(planner.recommendations))

    def test_opposite_direction_nets_out_no_double_revert(self):
        """A conversion in force, then the OTHER pool breaches before
        recovery: the reverse recommendation nets the ledger back to
        baseline — no separate revert follows once both pools clear
        (otherwise a replaying autoscaler ends lopsided)."""
        planner, clk, log, bus = self._setup(cooldown_s=0.0,
                                             occupancy=0.2)
        planner.plan(self._status(breach=("ttft_p99",)), bus)
        assert planner.outstanding == "decode_to_prefill"
        clk.tick(20.0)          # let the busy-prefill EWMA decay out
        bus.sample("occupancy", 0.1, pool="prefill")
        rec = planner.plan(self._status(breach=("itl_p99",)), bus)
        assert rec is not None and rec["revert"] is False
        assert rec["direction"] == "prefill_to_decode"
        assert planner.outstanding is None       # netted to baseline
        clk.tick(20.0)
        assert planner.plan(self._status(), bus) is None  # no revert
        dirs = [(e["direction"], e["revert"])
                for e in log.snapshot(kind="rebalance_recommended")]
        assert dirs == [("decode_to_prefill", False),
                        ("prefill_to_decode", False)]

    def test_planner_validation(self):
        with pytest.raises(ValueError, match="cooldown_s"):
            PoolRebalancePlanner(cooldown_s=-1.0)
        with pytest.raises(ValueError, match="donor_occupancy_below"):
            PoolRebalancePlanner(donor_occupancy_below=0.0)


# ---------------------------------------------------------------------
# satellites: queue wait age -> summary() + Retry-After
# ---------------------------------------------------------------------

class TestQueueWaitAge:
    def test_oldest_wait_scans_past_push_front(self):
        clk = _Clock(10.0)
        q = AdmissionQueue(8, clock=clk)
        assert q.oldest_wait_s() == 0.0          # empty -> 0, not NaN
        q.push(types.SimpleNamespace(submit_time=10.0, deadline=None))
        clk.tick(5.0)
        q.push(types.SimpleNamespace(submit_time=15.0, deadline=None))
        assert q.oldest_wait_s() == pytest.approx(5.0)
        # a migration re-queue can put YOUNGER work at the head — the
        # age scans submit_time, it does not trust FIFO order
        q.push_front([types.SimpleNamespace(submit_time=14.0,
                                            deadline=None)])
        assert q.oldest_wait_s() == pytest.approx(5.0)

    def test_fleet_metrics_summary_carries_queue_gauges(self):
        fm = FleetMetrics()
        s = fm.summary()                         # probe-less: zeros
        assert s["queue_depth"] == 0
        assert s["queue_oldest_wait_s"] == 0.0
        fm._queue_probe = lambda: (3, 1.25)
        s = fm.summary()
        assert s["queue_depth"] == 3
        assert s["queue_oldest_wait_s"] == 1.25

    def test_retry_after_raised_to_oldest_wait(self):
        fleet = types.SimpleNamespace(
            queue_oldest_wait_s=lambda: 7.3)
        fd = FrontDoor(fleet, retry_after_s=1.0)
        assert fd._retry_after() == "8"          # ceil(7.3) > floor
        fleet.queue_oldest_wait_s = lambda: 0.0
        assert fd._retry_after() == "1"          # floor holds
        # fleets without the probe keep the configured floor
        fd2 = FrontDoor(types.SimpleNamespace(), retry_after_s=2.0)
        assert fd2._retry_after() == "2"


# ---------------------------------------------------------------------
# Prometheus families through the strict-parser gate
# ---------------------------------------------------------------------

class TestExposition:
    def test_slo_and_pressure_families_parse_strict(self):
        clk = _Clock()
        eng = SLOEngine(_config(), clock=clk)
        for _ in range(20):
            eng.observe("ttft", 5.0)
            clk.tick(0.2)
        bus = SignalBus(clock=clk)
        bus.sample("queue_depth", 3.0)
        bus.sample("occupancy", 0.5, pool="decode")
        text = render_exposition(FleetMetrics().summary(),
                                 slo=eng.evaluate(),
                                 pressure=bus.gauges())
        parsed = parse_exposition(text)
        assert sample(parsed, "quintnet_slo_burn_rate",
                      objective="ttft_p99", pool="prefill",
                      window="fast") >= 2.0
        assert sample(parsed, "quintnet_slo_breaching",
                      objective="ttft_p99", pool="prefill") == 1.0
        assert sample(parsed, "quintnet_slo_breaching",
                      objective="itl_p99", pool="decode") == 0.0
        assert sample(parsed, "quintnet_slo_target",
                      objective="ttft_p99", pool="prefill") == 0.5
        assert sample(parsed, "quintnet_slo_breaches_total",
                      objective="ttft_p99", pool="prefill") == 1.0
        assert sample(parsed, "quintnet_pool_pressure_queue_depth",
                      pool="fleet", stat="ewma") == 3.0
        assert sample(parsed, "quintnet_pool_pressure_occupancy",
                      pool="decode", stat="last") == 0.5

    def test_heartbeat_and_breaker_gauges(self):
        """The invisible-today satellite: HeartbeatMonitor.age_s and
        breaker state render as per-replica gauges (breaker one-hot,
        the Prometheus enum idiom)."""
        health = {"replicas": {
            "p0": {"state": "healthy", "heartbeat_age_s": 0.04,
                   "breaker": "closed"},
            "p1": {"state": "dead", "heartbeat_age_s": 9.5,
                   "breaker": "open"},
        }, "queue_depth": 2, "queue_oldest_wait_s": 1.5,
            "open_requests": 1}
        parsed = parse_exposition(render_exposition(
            FleetMetrics().summary(), health=health))
        assert sample(parsed, "quintnet_replica_heartbeat_age_s",
                      replica="p0") == 0.04
        assert sample(parsed, "quintnet_replica_heartbeat_age_s",
                      replica="p1") == 9.5
        assert sample(parsed, "quintnet_replica_breaker_state",
                      replica="p0", state="closed") == 1.0
        assert sample(parsed, "quintnet_replica_breaker_state",
                      replica="p0", state="open") == 0.0
        assert sample(parsed, "quintnet_replica_breaker_state",
                      replica="p1", state="open") == 1.0
        assert sample(parsed, "quintnet_replica_breaker_state",
                      replica="p1", state="half_open") == 0.0
        # the queue gauges render from summary() (single series; the
        # health copy is only a fallback for summaries without them)
        fm = FleetMetrics()
        fm._queue_probe = lambda: (2, 1.5)
        parsed = parse_exposition(render_exposition(
            fm.summary(), health=health))
        assert sample(parsed,
                      "quintnet_fleet_queue_oldest_wait_s") == 1.5


# ---------------------------------------------------------------------
# the armed thread fleet: observation, surfaces, inertness
# ---------------------------------------------------------------------

def _factory(params, **kw):
    kwargs = dict(max_slots=2, block_size=4, num_blocks=24,
                  max_seq_len=40)
    kwargs.update(kw)

    def factory():
        return ServeEngine(gpt2_family(CFG), params, **kwargs)

    return factory


def _wait_until(pred, *, timeout=60.0, msg=""):
    t0 = time.monotonic()
    while not pred():
        if time.monotonic() - t0 > timeout:
            raise AssertionError(f"timed out waiting for: {msg}")
        time.sleep(0.01)


class TestArmedFleet:
    def test_fleet_observes_and_samples(self, params, rng):
        """A thread fleet with ``slo=`` at the constructor: TTFT/ITL
        observed at token delivery, shed/error at the edges, the bus
        sampled on the dispatcher thread, and ``summary()`` carries
        the judgment."""
        cfg = SLOConfig.serving(ttft_p99_s=60.0, itl_p99_s=60.0,
                                error_rate=0.5, shed_rate=0.5,
                                eval_interval_s=0.01)
        fleet = ServeFleet(_factory(params), n_replicas=2, slo=cfg)
        try:
            assert fleet.slo is not None and fleet.signals is not None
            assert fleet.planner is None         # no pools to move
            prompts = [np.asarray(rng.integers(0, CFG.vocab_size, (5,)),
                                  np.int32) for _ in range(3)]
            fids = [fleet.submit(p, 8) for p in prompts]
            [fleet.result(f, timeout=300) for f in fids]
            st = fleet.slo.status()
            obj = st["objectives"]
            assert obj["ttft_p99"]["n_slow"] == 3    # one per request
            # per request: token 1 anchors (ttft), tokens 2..8 are gaps
            assert obj["itl_p99"]["n_slow"] == 3 * (8 - 1)
            assert obj["error_rate"]["n_slow"] == 3  # finishes, 0.0
            assert obj["shed_rate"]["n_slow"] == 3   # accepts, 0.0
            assert st["breaching"] == []
            # the dispatcher sampled the bus (eval_interval 10ms)
            _wait_until(lambda: fleet.signals.value("queue_depth")
                        is not None, msg="bus sampled")
            assert fleet.signals.value("occupancy") is not None
            assert fleet.signals.value("kv_pressure") is not None
            assert fleet.signals.value("breakers_open") == 0.0
            assert fleet.summary()["slo"]["breaching"] == []
        finally:
            fleet.close()

    def test_itl_not_polluted_by_migration(self, params, rng):
        """A chaos kill mid-decode: the cross-replica gap is a fault
        cost, not a decode-cadence reading — the ITL stream must not
        breach a tight objective because of the migration stall."""
        cfg = SLOConfig.serving(itl_p99_s=60.0, eval_interval_s=0.01)
        fleet = ServeFleet(
            _factory(params), n_replicas=2, slo=cfg,
            chaos=ChaosMonkey(kill_at_step=3, mode="raise",
                              target="r0"))
        try:
            prompts = [np.asarray(rng.integers(0, CFG.vocab_size, (5,)),
                                  np.int32) for _ in range(4)]
            fids = [fleet.submit(p, 12) for p in prompts]
            [fleet.result(f, timeout=300) for f in fids]
            assert fleet.metrics.replica_deaths == 1
            m = fleet.metrics.migrations
            assert m >= 1
            # every delivered token fed EITHER ttft or itl — except
            # each migrated request's post-migration re-anchor token
            # (a request migrated before its first token re-anchors
            # nothing: its first survivor token is still TTFT)
            st = fleet.slo.status()["objectives"]["itl_p99"]
            assert 4 * 12 - 4 - m <= st["n_slow"] <= 4 * 12 - 4
            # and the migration stall never read as a decode gap
            assert st["breaching"] is False
        finally:
            fleet.close()

    def test_healthz_degraded_names_breaching_objectives(self, params,
                                                         rng):
        """/healthz with an armed engine: 200 "ok" while compliant; a
        breach downgrades to 200 "degraded" with the objectives named
        (a latency slip must NOT pull the node from the LB); /metrics
        serves the families through the strict parser."""
        import http.client

        cfg = SLOConfig.serving(ttft_p99_s=0.001, eval_interval_s=0.01)
        fleet = ServeFleet(_factory(params), n_replicas=1, slo=cfg)
        try:
            fleet.generate(
                [np.asarray(rng.integers(0, CFG.vocab_size, (5,)),
                            np.int32)], max_new_tokens=4, timeout=300)
            with FrontDoor(fleet) as fd:
                def get(path):
                    conn = http.client.HTTPConnection(
                        fd.host, fd.port, timeout=60)
                    conn.request("GET", path)
                    r = conn.getresponse()
                    body = r.read()
                    conn.close()
                    return r, body

                # the 1ms TTFT objective is already breached by the
                # real request above (sustained: fast AND slow window)
                _wait_until(lambda: fleet.slo.breaching(),
                            msg="ttft breach")
                r, body = get("/healthz")
                h = json.loads(body)
                assert r.status == 200           # still serving!
                assert h["status"] == "degraded"
                assert h["slo"]["breaching"] == ["ttft_p99"]
                assert h["slo"]["objectives"]["ttft_p99"]["pool"] == \
                    "prefill"

                r, body = get("/metrics")
                parsed = parse_exposition(body.decode())
                assert sample(parsed, "quintnet_slo_breaching",
                              objective="ttft_p99",
                              pool="prefill") == 1.0
                assert any(n.startswith("quintnet_pool_pressure_")
                           for n, _l in parsed)
        finally:
            fleet.close()


class TestInertness:
    @pytest.mark.parametrize("combo", [
        dict(spec=True, kv_dtype="int8", temperature=0.8, top_k=5),
        dict(chunked_prefill=True, prefill_len=16, kv_dtype="int8",
             temperature=0.8, top_k=5),
    ], ids=["spec+int8+sampled", "chunked+int8+sampled"])
    def test_slo_armed_fleet_is_bit_identical_census_unchanged(
            self, params, rng, combo):
        """THE acceptance golden, half one: SLO engine + signal bus
        armed vs nothing armed — sampled, int8 KV, with speculation
        and chunked prefill each composed — every output
        bit-identical AND the per-replica compile census unchanged
        (judgment adds zero programs)."""
        prompts = [np.asarray(rng.integers(0, CFG.vocab_size, (t,)),
                              np.int32) for t in (5, 7, 30, 6)]
        keys = [jax.random.key(60 + i) for i in range(4)]
        outs, census = {}, {}
        for armed in (False, True):
            slo = (SLOConfig.serving(ttft_p99_s=0.001, itl_p99_s=0.001,
                                     shed_rate=0.01,
                                     eval_interval_s=0.005)
                   if armed else None)           # breach-hot on purpose
            fleet = ServeFleet(
                _factory(params, **combo),
                n_replicas=2, policy="round_robin", slo=slo)
            try:
                fids = [fleet.submit(p, 10, key=k)
                        for p, k in zip(prompts, keys)]
                outs[armed] = [fleet.result(f, timeout=300)
                               for f in fids]
                census[armed] = sorted(
                    tuple(sorted(r.engine.compile_stats().items()))
                    for r in fleet.replicas)
                if armed:                        # it really judged
                    assert fleet.slo.breaching()
            finally:
                fleet.close()
        for a, b in zip(outs[False], outs[True]):
            np.testing.assert_array_equal(a, b)
        assert census[False] == census[True]

    def test_slo_armed_fleet_inert_under_chaos_kill(self, params, rng):
        """Half two: the same contract under a mid-run chaos kill —
        the migration path with a breach-hot engine judging throughout
        is still bit-identical to the unarmed fleet."""
        prompts = [np.asarray(rng.integers(0, CFG.vocab_size, (5,)),
                              np.int32) for _ in range(4)]
        keys = [jax.random.key(80 + i) for i in range(4)]
        outs = {}
        for armed in (False, True):
            slo = (SLOConfig.serving(ttft_p99_s=0.001, itl_p99_s=0.001,
                                     eval_interval_s=0.005)
                   if armed else None)
            fleet = ServeFleet(
                _factory(params, kv_dtype="int8", temperature=0.8,
                         top_k=5),
                n_replicas=2, slo=slo,
                chaos=ChaosMonkey(kill_at_step=3, mode="raise",
                                  target="r0"))
            try:
                fids = [fleet.submit(p, 12, key=k)
                        for p, k in zip(prompts, keys)]
                outs[armed] = [fleet.result(f, timeout=300)
                               for f in fids]
                assert fleet.metrics.replica_deaths == 1
                if armed:
                    assert fleet.slo.breaching()
                    assert fleet.signals.value("occupancy") is not None
            finally:
                fleet.close()
        for a, b in zip(outs[False], outs[True]):
            np.testing.assert_array_equal(a, b)

    def test_crash_dump_carries_signal_snapshot(self, params, rng,
                                                tmp_path):
        """The black box gains the bus: a chaos-killed replica's dump
        file embeds the dispatcher's last pool-pressure snapshot."""
        from quintnet_tpu.obs import load_crash_dump

        cfg = SLOConfig.serving(ttft_p99_s=60.0, eval_interval_s=0.005)
        fleet = ServeFleet(
            _factory(params), n_replicas=2, slo=cfg,
            crash_dir=str(tmp_path),
            chaos=ChaosMonkey(kill_at_step=3, mode="raise",
                              target="r0"))
        try:
            prompts = [np.asarray(rng.integers(0, CFG.vocab_size, (5,)),
                                  np.int32) for _ in range(4)]
            fids = [fleet.submit(p, 12) for p in prompts]
            [fleet.result(f, timeout=300) for f in fids]
            _wait_until(lambda: len(fleet.crash_dumps) == 1,
                        msg="crash dump flushed")
            dump = load_crash_dump(fleet.crash_dumps[0])
            sig = dump["signals"]
            assert sig, "signal snapshot missing from the dump"
            assert "gauges" in sig and "sampled_at" in sig
            assert "queue_depth" in sig["gauges"]
        finally:
            fleet.close()

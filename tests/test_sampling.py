"""top-k / top-p sampling filters (sample_logits) and the bf16
first-moment optimizer option.

The reference's generation is greedy-only (utils/metrics.py:74-149) and
its optimizers are all-f32; both knobs here are upgrades whose contracts
are pinned by these tests.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from quintnet_tpu.models.gpt2_generate import sample_logits


def _logits():
    # strongly ordered distribution over 8 tokens
    return jnp.asarray([[8.0, 6.0, 5.0, 2.0, 1.0, 0.5, 0.2, 0.1]])


@pytest.mark.fast
def test_greedy_ignores_filters():
    out = sample_logits(_logits(), jax.random.key(0), temperature=0.0,
                        top_k=3, top_p=0.5)
    assert int(out[0]) == 0


@pytest.mark.fast
def test_top_k_restricts_support():
    ks = jax.random.split(jax.random.key(1), 200)
    toks = {int(sample_logits(_logits(), k, temperature=5.0, top_k=3)[0])
            for k in ks}
    assert toks <= {0, 1, 2} and len(toks) > 1  # hot temp still samples


@pytest.mark.fast
def test_top_k_one_is_argmax():
    for i in range(5):
        out = sample_logits(_logits(), jax.random.key(i),
                            temperature=1.0, top_k=1)
        assert int(out[0]) == 0


@pytest.mark.fast
def test_top_p_keeps_first_crossing_token():
    # probs ~ softmax: p0 dominates; tiny top_p must still keep token 0
    for i in range(5):
        out = sample_logits(_logits(), jax.random.key(i),
                            temperature=1.0, top_p=1e-6)
        assert int(out[0]) == 0


@pytest.mark.fast
def test_top_p_restricts_support():
    logits = jnp.log(jnp.asarray([[0.5, 0.3, 0.15, 0.05]]))
    ks = jax.random.split(jax.random.key(2), 300)
    toks = {int(sample_logits(logits, k, temperature=1.0, top_p=0.8)[0])
            for k in ks}
    # cumulative: 0.5, 0.8, 0.95 -> token 1 crosses 0.8 and is kept,
    # tokens 2/3 dropped
    assert toks == {0, 1}


@pytest.mark.fast
def test_unsort_is_correct_per_row():
    # two rows with different orderings; same filter must track each row
    logits = jnp.asarray([[1.0, 9.0, 2.0, 0.0],
                          [0.0, 2.0, 9.0, 1.0]])
    ks = jax.random.split(jax.random.key(3), 100)
    for k in ks[:50]:
        out = sample_logits(logits, k, temperature=1.0, top_k=1)
        assert int(out[0]) == 1 and int(out[1]) == 2


def test_generate_with_filters_runs():
    from quintnet_tpu.models.gpt2 import GPT2Config, gpt2_init
    from quintnet_tpu.models.gpt2_generate import gpt2_generate

    cfg = GPT2Config.tiny()
    params = gpt2_init(jax.random.key(0), cfg)
    ids = np.zeros((2, 4), np.int32)
    out = gpt2_generate(params, ids, cfg, max_new_tokens=3,
                        temperature=0.8, top_k=10, top_p=0.9,
                        key=jax.random.key(7))
    assert out.shape == (2, 7)
    assert (out[:, :4] == ids).all()


@pytest.mark.fast
def test_adam_mu_dtype_bf16():
    import optax

    from quintnet_tpu.core.config import Config
    from quintnet_tpu.train.trainer import make_optimizer

    cfg = Config.from_dict(
        {"training": {"optimizer": "adamw", "adam_mu_dtype": "bfloat16"}})
    opt = make_optimizer(cfg)
    params = {"w": jnp.ones((4, 4)), "b": jnp.ones((4,))}
    state = opt.init(params)
    mu = state[0].mu  # scale_by_adam state in the chain
    assert all(l.dtype == jnp.bfloat16 for l in jax.tree.leaves(mu))
    nu = state[0].nu
    assert all(l.dtype == jnp.float32 for l in jax.tree.leaves(nu))
    # an update step still works and returns param-dtype updates
    g = jax.tree.map(jnp.ones_like, params)
    up, _ = opt.update(g, state, params)
    assert jax.tree.leaves(up)[0].dtype == jnp.float32

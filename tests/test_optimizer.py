"""make_optimizer / make_lr_schedule: schedules and decay masking.

The reference trains at constant lr everywhere (trainer.py:89,
GPT2_Trainer.py:100-104) and decays every parameter; here warmup+cosine/
linear schedules are config fields and AdamW skips LN scales and biases
(standard practice), including under ZeRO-1 where the mask must be
elementwise on the flat chunk (parallel/zero.py).
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from quintnet_tpu.core.config import Config
from quintnet_tpu.models.vit import ViTConfig, vit_init, vit_model_spec
from quintnet_tpu.parallel.strategy import get_strategy
from quintnet_tpu.train.trainer import make_lr_schedule, make_optimizer


def _cfg(**training):
    return Config.from_dict({"training": training})


# -- lr trajectories ---------------------------------------------------------

def test_constant_schedule_is_plain_float():
    assert make_lr_schedule(_cfg(learning_rate=3e-4)) == 3e-4


def test_warmup_constant_trajectory():
    sched = make_lr_schedule(_cfg(learning_rate=1.0, warmup_steps=10))
    np.testing.assert_allclose(sched(0), 0.0)
    np.testing.assert_allclose(sched(5), 0.5)
    np.testing.assert_allclose(sched(10), 1.0)
    np.testing.assert_allclose(sched(1000), 1.0)


def test_warmup_cosine_trajectory():
    sched = make_lr_schedule(_cfg(
        learning_rate=1.0, lr_schedule="cosine", warmup_steps=10,
        decay_steps=110, min_lr_ratio=0.1))
    np.testing.assert_allclose(sched(0), 0.0)
    np.testing.assert_allclose(sched(10), 1.0, rtol=1e-6)
    # cosine midpoint: halfway between peak and floor
    np.testing.assert_allclose(sched(60), 0.55, rtol=1e-5)
    np.testing.assert_allclose(sched(110), 0.1, rtol=1e-5)
    np.testing.assert_allclose(sched(10_000), 0.1, rtol=1e-5)


def test_linear_decay_trajectory():
    sched = make_lr_schedule(_cfg(
        learning_rate=1.0, lr_schedule="linear", warmup_steps=0,
        decay_steps=100, min_lr_ratio=0.0))
    np.testing.assert_allclose(sched(0), 1.0)
    np.testing.assert_allclose(sched(50), 0.5, rtol=1e-6)
    np.testing.assert_allclose(sched(100), 0.0, atol=1e-7)


def test_decaying_schedule_requires_decay_steps():
    with pytest.raises(ValueError, match="decay_steps"):
        make_lr_schedule(_cfg(lr_schedule="cosine"))


# -- weight-decay masking ----------------------------------------------------

def test_adamw_skips_bias_and_ln_decay():
    """With zero grads Adam's direction is exactly 0, so the update is
    pure decoupled decay: -lr*wd*p on matrices, 0 on 1-D leaves."""
    lr, wd = 0.1, 0.5
    opt = make_optimizer(_cfg(optimizer="adamw", learning_rate=lr,
                              weight_decay=wd))
    params = {"w": jnp.ones((4, 4)), "b": jnp.ones((4,)),
              "ln_scale": jnp.ones((4,))}
    state = opt.init(params)
    grads = jax.tree.map(jnp.zeros_like, params)
    updates, _ = opt.update(grads, state, params)
    np.testing.assert_allclose(updates["w"], -lr * wd * params["w"],
                               rtol=1e-6)
    np.testing.assert_array_equal(updates["b"], jnp.zeros((4,)))
    np.testing.assert_array_equal(updates["ln_scale"], jnp.zeros((4,)))


def test_adamw_matches_optax_adamw_on_matrices():
    """On an all-matrix tree the chain reproduces optax.adamw exactly."""
    opt = make_optimizer(_cfg(optimizer="adamw", learning_rate=1e-3,
                              weight_decay=0.01))
    ref = optax.adamw(1e-3, weight_decay=0.01)
    params = {"w": jax.random.normal(jax.random.key(0), (8, 8))}
    grads = {"w": jax.random.normal(jax.random.key(1), (8, 8))}
    u1, _ = opt.update(grads, opt.init(params), params)
    u2, _ = ref.update(grads, ref.init(params), params)
    np.testing.assert_array_equal(u1["w"], u2["w"])


# -- zero1 path carries the mask elementwise ---------------------------------

CFG = ViTConfig(image_size=14, patch_size=7, in_channels=1, hidden_dim=16,
                depth=2, num_heads=2, num_classes=10)


def _train(optimizer_name, n_steps=2):
    cfg = Config.from_dict({
        "mesh_dim": [4], "mesh_name": ["dp"],
        "training": {"batch_size": 16, "optimizer": optimizer_name,
                     "learning_rate": 1e-3, "weight_decay": 0.1,
                     "lr_schedule": "cosine", "warmup_steps": 1,
                     "decay_steps": 4, "grad_clip_norm": 1.0},
    })
    strat = get_strategy("auto", cfg)
    model = vit_model_spec(CFG)
    opt = make_optimizer(cfg)
    params = strat.shard_params(model, vit_init(jax.random.key(0), CFG))
    state = strat.init_opt_state(model, opt, params)
    x = jax.random.normal(jax.random.key(1), (16, 14, 14, 1))
    y = jax.random.randint(jax.random.key(2), (16,), 0, 10)
    batch = strat.shard_batch((x, y))
    step = strat.make_train_step(model, opt)
    for _ in range(n_steps):
        params, state, loss = step(params, state, batch)
    return params


def test_zero1_masked_decay_matches_replicated():
    """ZeRO-1 with schedule + masked decay is bit-identical to the
    replicated path after one step (the elementwise chunk mask must
    reproduce the per-leaf ndim>1 mask exactly)."""
    p_ref = _train("adamw", n_steps=1)
    p_z = _train("zero1_adamw", n_steps=1)
    for a, b in zip(jax.tree.leaves(p_z), jax.tree.leaves(p_ref)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_decay_mask_skips_stacked_biases_and_norms():
    """Round-4 review regression: stacked-block leaves (leading depth
    dim) made biases/LN ndim-2, so the old ndim>1 mask decayed them.
    The name-based mask must not."""
    from quintnet_tpu.core.pytree import decay_mask
    from quintnet_tpu.models.gpt2 import GPT2Config, gpt2_init

    params = gpt2_init(jax.random.key(0), GPT2Config.tiny())
    mask = decay_mask(params)
    blocks = mask["blocks"]
    assert bool(blocks["attn"]["qkv"]["w"].all())        # [L, D, 3D]
    assert not bool(blocks["attn"]["qkv"]["b"].any())    # [L, 3D] bias!
    assert not bool(blocks["ln1"]["scale"].any())        # [L, D] LN!
    assert not bool(blocks["ln1"]["bias"].any())
    assert bool(mask["embedding"]["wte"].all())
    assert not bool(mask["head"]["ln_f"]["scale"].any())

    # end-to-end: zero grads -> update is pure decay; stacked biases
    # and LN leaves must receive exactly zero update
    lr, wd = 0.1, 0.5
    opt = make_optimizer(_cfg(optimizer="adamw", learning_rate=lr,
                              weight_decay=wd))
    grads = jax.tree.map(jnp.zeros_like, params)
    updates, _ = opt.update(grads, opt.init(params), params)
    np.testing.assert_array_equal(updates["blocks"]["attn"]["qkv"]["b"],
                                  jnp.zeros_like(params["blocks"]["attn"]["qkv"]["b"]))
    np.testing.assert_array_equal(updates["blocks"]["ln1"]["scale"],
                                  jnp.zeros_like(params["blocks"]["ln1"]["scale"]))
    np.testing.assert_allclose(
        updates["blocks"]["attn"]["qkv"]["w"],
        -lr * wd * params["blocks"]["attn"]["qkv"]["w"], rtol=1e-6)

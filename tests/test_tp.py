"""TP golden tests: sharded layers and the full TP model match the
unsharded computation (methodology of reference
tests/test_tensor_parallel.py:40-153, extended to full-model and
train-step equivalence which the reference lacks)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

from quintnet_tpu.core import collectives as cc
from quintnet_tpu.core.mesh import mesh_from_sizes
from quintnet_tpu.models.vit import (
    ViTConfig,
    cross_entropy_loss,
    vit_apply,
    vit_init,
    vit_partition_specs,
    vit_to_tp_layout,
)
from quintnet_tpu.parallel import tp as tpl
from quintnet_tpu.parallel.train_step import (
    make_parallel_train_step,
    opt_state_specs,
    reduce_grads,
)


@pytest.fixture(scope="module")
def mesh2():
    return mesh_from_sizes(tp=2)


def test_column_parallel_gather_matches_dense(mesh2):
    key = jax.random.key(0)
    w = jax.random.normal(key, (8, 12))
    b = jax.random.normal(jax.random.key(1), (12,))
    x = jax.random.normal(jax.random.key(2), (4, 8))

    dense = x @ w + b

    fn = cc.shard_map_fn(
        lambda p, x_: tpl.column_parallel_linear(p, x_, gather_output=True),
        mesh2,
        in_specs=({"w": P(None, "tp"), "b": P("tp")}, P()),
        out_specs=P(),
    )
    out = fn({"w": w, "b": b}, x)
    np.testing.assert_allclose(out, dense, rtol=1e-5)


def test_row_parallel_matches_dense(mesh2):
    w = jax.random.normal(jax.random.key(0), (8, 6))
    b = jax.random.normal(jax.random.key(1), (6,))
    x = jax.random.normal(jax.random.key(2), (4, 8))
    dense = x @ w + b

    # input_is_parallel=False: replicated input self-sliced per rank
    # (reference layers.py:185-199)
    fn = cc.shard_map_fn(
        lambda p, x_: tpl.row_parallel_linear(p, x_, input_is_parallel=False),
        mesh2,
        in_specs=({"w": P("tp", None), "b": P()}, P()),
        out_specs=P(),
    )
    out = fn({"w": w, "b": b}, x)
    np.testing.assert_allclose(out, dense, rtol=1e-5)


def test_column_then_row_fused(mesh2):
    """The Megatron pair: column (no gather) -> row (input parallel), one
    psum total — the reference's MLP pattern (gpt2_mlp.py:98-125)."""
    w1 = jax.random.normal(jax.random.key(0), (8, 16))
    w2 = jax.random.normal(jax.random.key(1), (16, 8))
    x = jax.random.normal(jax.random.key(2), (4, 8))
    dense = jnp.maximum(x @ w1, 0) @ w2

    def local(p, x_):
        h = tpl.column_parallel_linear(p["c"], x_, gather_output=False)
        h = jnp.maximum(h, 0)
        return tpl.row_parallel_linear(p["r"], h, input_is_parallel=True)

    fn = cc.shard_map_fn(
        local,
        mesh2,
        in_specs=({"c": {"w": P(None, "tp")}, "r": {"w": P("tp", None)}}, P()),
        out_specs=P(),
    )
    out = fn({"c": {"w": w1}, "r": {"w": w2}}, x)
    np.testing.assert_allclose(out, dense, rtol=1e-4)


def test_vocab_parallel_embedding(mesh2):
    table = jax.random.normal(jax.random.key(0), (10, 4))
    ids = jnp.array([[0, 3, 9], [5, 4, 2]])
    dense = jnp.take(table, ids, axis=0)

    fn = cc.shard_map_fn(
        lambda p, i: tpl.vocab_parallel_embedding(p, i),
        mesh2,
        in_specs=({"table": P("tp", None)}, P()),
        out_specs=P(),
    )
    out = fn({"table": table}, ids)
    np.testing.assert_allclose(out, dense, rtol=1e-6)


def test_qkv_layout_roundtrip():
    w = jax.random.normal(jax.random.key(0), (8, 24))
    b = tpl.qkv_blocked_from_standard(w, num_heads=4, tp=2)
    back = tpl.qkv_standard_from_blocked(b, num_heads=4, tp=2)
    np.testing.assert_array_equal(w, back)
    # tp=1 is identity
    np.testing.assert_array_equal(tpl.qkv_blocked_from_standard(w, 4, 1), w)


CFG = ViTConfig(image_size=14, patch_size=7, in_channels=1, hidden_dim=16,
                depth=2, num_heads=4, num_classes=10)


def _vit_tp_forward(mesh, params_blocked, x, tp_axis="tp"):
    specs = vit_partition_specs(CFG, tp_axis=tp_axis)
    fn = cc.shard_map_fn(
        lambda p, x_: vit_apply(p, x_, CFG, tp_axis=tp_axis),
        mesh,
        in_specs=(specs, P()),
        out_specs=P(),
    )
    return fn(params_blocked, x)


def test_vit_tp_forward_matches_single_device(mesh2):
    params = vit_init(jax.random.key(0), CFG)
    x = jax.random.normal(jax.random.key(1), (4, 14, 14, 1))

    ref = vit_apply(params, x, CFG)
    out = _vit_tp_forward(mesh2, vit_to_tp_layout(params, CFG, 2), x)
    np.testing.assert_allclose(out, ref, rtol=2e-4, atol=1e-5)


def test_vit_tp_train_step_matches_single_device(mesh2):
    """Full TP train step — incl. the psum of replicated-param (LN) grads
    over tp that the reference omits."""
    params = vit_init(jax.random.key(0), CFG)
    x = jax.random.normal(jax.random.key(1), (8, 14, 14, 1))
    y = jax.random.randint(jax.random.key(2), (8,), 0, 10)
    opt = optax.sgd(0.05)

    def ref_loss(p):
        return cross_entropy_loss(vit_apply(p, x, CFG), y)

    loss_ref, g_ref = jax.value_and_grad(ref_loss)(params)
    p_ref = optax.apply_updates(params, opt.update(g_ref, opt.init(params), params)[0])

    def tp_loss(p, batch):
        xb, yb = batch
        return cross_entropy_loss(
            vit_apply(p, xb, CFG, tp_axis="tp"), yb)

    specs = vit_partition_specs(CFG)
    step = make_parallel_train_step(mesh2, tp_loss, opt, specs,
                                    batch_axes=(), model_axes=("tp",),
                                    donate=False)
    params_b = vit_to_tp_layout(params, CFG, 2)
    opt_state = opt.init(params_b)
    p_tp, _, loss_tp = step(params_b, opt_state, (x, y))

    np.testing.assert_allclose(float(loss_tp), float(loss_ref), rtol=1e-5)
    # compare in the same layout
    p_ref_b = vit_to_tp_layout(p_ref, CFG, 2)
    flat_tp = jax.tree_util.tree_leaves_with_path(p_tp)
    flat_ref = dict(jax.tree_util.tree_leaves_with_path(p_ref_b))
    for path, leaf in flat_tp:
        np.testing.assert_allclose(
            np.asarray(leaf), np.asarray(flat_ref[path]),
            rtol=2e-4, atol=1e-5, err_msg=str(path))


def test_opt_state_specs_adam():
    params = {"a": jnp.zeros((4, 4)), "b": jnp.zeros((4,))}
    specs = {"a": P(None, "tp"), "b": P()}
    opt = optax.adam(1e-3)
    s = opt_state_specs(opt, params, specs)
    leaves = jax.tree.leaves(s, is_leaf=lambda x: isinstance(x, P))
    assert all(isinstance(l, P) for l in leaves)
    # mu/nu inherit param specs; count replicated
    flat = jax.tree_util.tree_leaves_with_path(s, is_leaf=lambda x: isinstance(x, P))
    spec_strs = {str(p): s_ for p, s_ in flat}
    assert any(s_ == P(None, "tp") for s_ in spec_strs.values())
    assert any(s_ == P() for s_ in spec_strs.values())


def test_reduce_grads_rule(mesh2):
    """Replicated-leaf grads are psummed over tp then de-redundancy-scaled
    (psum/tp = mean); sharded-leaf grads only get the 1/tp scale."""
    specs = {"rep": P(), "shard": P("tp", None)}

    def f(g):
        return reduce_grads(g, specs, data_axes=(), model_axes=("tp",))

    g = {"rep": jnp.ones((2, 2)), "shard": jnp.ones((2, 2))}
    out = cc.shard_map_fn(
        f, mesh2,
        in_specs=({"rep": P(), "shard": P("tp", None)},),
        out_specs={"rep": P(), "shard": P("tp", None)},
    )(g)
    np.testing.assert_allclose(out["rep"], np.ones((2, 2)))        # psum/2
    np.testing.assert_allclose(out["shard"], 0.5 * np.ones((2, 2)))  # /2

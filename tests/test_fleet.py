"""Multi-replica serving goldens (quintnet_tpu/fleet/).

THE contract: a fleet of N replica engines serves every request
token-for-token identically to an independent ``gpt2_generate`` call —
including requests whose replica is KILLED mid-flight and migrated
(the exported prompt+generated+key progress resumes elsewhere). Plus
the operational invariants: typed load shedding under a >capacity
burst (bounded queue, deadline expiry), circuit-breaker-gated
restarts with a timed half-open probe, graceful drain, per-replica
one-prefill+one-decode compile counts via analysis.assert_compile_count.
"""

import threading

import jax
import numpy as np
import pytest

from quintnet_tpu.fleet import (DEAD, HALF_OPEN, HEALTHY, OPEN,
                                AdmissionQueue, CircuitBreaker,
                                Overloaded, Router, ServeFleet)
from quintnet_tpu.ft import ChaosMonkey
from quintnet_tpu.models.gpt2 import GPT2Config, gpt2_init
from quintnet_tpu.models.gpt2_generate import gpt2_generate
from quintnet_tpu.serve import ServeEngine, gpt2_family
from quintnet_tpu.serve.metrics import ServeMetrics, aggregate

CFG = GPT2Config.tiny(n_layer=2)
TEMP, TOPK = 0.8, 5


@pytest.fixture(scope="module")
def params():
    return gpt2_init(jax.random.key(0), CFG)


@pytest.fixture
def factory(params):
    def make():
        return ServeEngine(gpt2_family(CFG), params, max_slots=2,
                           block_size=4, num_blocks=24, max_seq_len=24,
                           temperature=TEMP, top_k=TOPK)

    return make


def _oracle(params, prompt, max_new, key):
    return np.asarray(gpt2_generate(
        params, prompt[None], CFG, max_new_tokens=max_new,
        temperature=TEMP, top_k=TOPK, key=key)[0])


def _prompts(rng, lengths):
    return [np.asarray(rng.integers(0, CFG.vocab_size, (t,)), np.int32)
            for t in lengths]


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def _wait_until(pred, *, timeout=30.0, msg=""):
    done = threading.Event()
    import time
    t0 = time.monotonic()
    while not pred():
        if time.monotonic() - t0 > timeout:
            raise AssertionError(f"timed out waiting for: {msg}")
        done.wait(0.01)


# ---------------------------------------------------------------------
# policy units (no engines)
# ---------------------------------------------------------------------

class TestCircuitBreaker:
    def test_trips_after_consecutive_failures_only(self):
        clk = FakeClock()
        br = CircuitBreaker(trip_after=3, reset_s=10.0, clock=clk)
        br.record_failure()
        br.record_failure()
        assert br.allow_restart()          # still closed
        br.record_success()                # resets the streak
        br.record_failure()
        br.record_failure()
        assert br.state == "closed"
        br.record_failure()                # third consecutive
        assert br.state == OPEN
        assert not br.allow_restart()

    def test_half_open_probe_once_then_success_closes(self):
        clk = FakeClock()
        br = CircuitBreaker(trip_after=1, reset_s=10.0, clock=clk)
        br.record_failure()
        assert br.state == OPEN and not br.allow_restart()
        clk.advance(10.0)
        assert br.allow_restart()          # the single probe
        assert br.state == HALF_OPEN
        assert not br.allow_restart()      # no second probe
        br.record_success()
        assert br.state == "closed" and br.consecutive_failures == 0

    def test_half_open_failure_reopens_for_full_reset(self):
        clk = FakeClock()
        br = CircuitBreaker(trip_after=1, reset_s=10.0, clock=clk)
        br.record_failure()
        clk.advance(10.0)
        assert br.allow_restart()
        br.record_failure()                # probe died
        assert br.state == OPEN
        clk.advance(9.0)
        assert not br.allow_restart()      # full reset_s again
        clk.advance(1.0)
        assert br.allow_restart()


class _Item:
    def __init__(self, deadline=None):
        self.deadline = deadline


class TestAdmissionQueue:
    def test_bound_sheds_typed(self):
        q = AdmissionQueue(2, clock=FakeClock())
        q.push(_Item())
        q.push(_Item())
        with pytest.raises(Overloaded) as ei:
            q.push(_Item())
        assert ei.value.reason == "queue_full"
        assert len(q) == 2                 # the queue did NOT grow

    def test_deadline_shedding(self):
        clk = FakeClock()
        q = AdmissionQueue(8, clock=clk)
        live, dead = _Item(), _Item(deadline=5.0)
        q.push(live)
        q.push(dead)
        assert q.shed_expired() == []
        clk.advance(6.0)
        assert q.shed_expired() == [dead]
        assert q.pop() is live and q.pop() is None

    def test_migration_requeue_bypasses_bound(self):
        q = AdmissionQueue(1, clock=FakeClock())
        q.push(_Item())
        migrated = _Item()
        q.push_front([migrated])           # no Overloaded
        assert len(q) == 2 and q.pop() is migrated


class TestRouter:
    class _Rep:
        def __init__(self, name, load):
            self.name, self.outstanding_tokens = name, load

    def test_least_work_picks_min_tokens(self):
        r = Router("least_work")
        reps = [self._Rep("r0", 30), self._Rep("r1", 10),
                self._Rep("r2", 20)]
        assert r.pick(reps).name == "r1"
        # tie breaks on name: reproducible
        reps[0].outstanding_tokens = 10
        assert r.pick(reps).name == "r0"

    def test_round_robin_cycles(self):
        r = Router("round_robin")
        reps = [self._Rep(n, 0) for n in ("r0", "r1", "r2")]
        assert [r.pick(reps).name for _ in range(4)] == \
            ["r0", "r1", "r2", "r0"]

    def test_unknown_policy_rejected(self):
        with pytest.raises(ValueError, match="unknown routing policy"):
            Router("fastest")


def test_metrics_aggregate_pools_counters_and_tails():
    clk = FakeClock()
    a, b = ServeMetrics(clock=clk), ServeMetrics(clock=clk)
    a.record_step(running=1, waiting=0, kv_blocks_used=2,
                  kv_blocks_total=4, prefill_tokens=5, decode_tokens=1)
    clk.advance(2.0)
    b.record_step(running=2, waiting=1, kv_blocks_used=4,
                  kv_blocks_total=4, prefill_tokens=7, decode_tokens=2)
    a.record_admit()
    a.record_first_token(0.1)
    b.record_first_token(0.9)
    b.record_finish(1.5)
    agg = aggregate([a, b])
    assert agg["replicas"] == 2 and agg["steps"] == 2
    assert agg["prefill_tokens"] == 12 and agg["decode_tokens"] == 3
    assert agg["gen_tokens"] == 4       # decode 3 + 1 admission sample
    assert agg["wall_s"] == 2.0         # earliest t0 -> latest t_end
    assert agg["finished"] == 1
    # pooled percentiles see BOTH replicas' ttfts
    assert agg["ttft_s"]["p50"] == pytest.approx(0.5)
    assert "p99" in agg["ttft_s"]
    assert agg["peak_kv_utilization"] == 1.0


# ---------------------------------------------------------------------
# fleet integration (real engines)
# ---------------------------------------------------------------------

def test_fleet_parity_and_graceful_drain(factory, params, rng):
    """No faults: outputs across 2 replicas == independent oracle per
    request; per-replica compile counts are exactly 1 prefill + 1
    decode (analysis.assert_compile_count); drain refuses new work."""
    prompts = _prompts(rng, (5, 7, 3, 6, 4, 8))
    keys = [jax.random.key(100 + i) for i in range(6)]
    fleet = ServeFleet(factory, n_replicas=2, policy="least_work")
    try:
        outs = fleet.generate(prompts, max_new_tokens=8, keys=keys,
                              timeout=300)
        for p, k, o in zip(prompts, keys, outs):
            np.testing.assert_array_equal(o, _oracle(params, p, 8, k))
        fleet.assert_compile_count(include_idle=True)
        s = fleet.summary()
        assert s["finished"] == 6 and s["engine"]["finished"] == 6
        assert s["shed"] == 0 and s["migrations"] == 0
        assert all(v["compile_stats"] == {"prefill": 1, "decode": 1}
                   for v in s["replicas"].values())
    finally:
        fleet.drain(timeout=60)
    with pytest.raises(Overloaded) as ei:
        fleet.submit(prompts[0], 4)
    assert ei.value.reason == "shutdown"


def test_never_admissible_request_rejected_at_submit(factory, params,
                                                     rng):
    """A request no engine in the fleet could ever run (prompt+budget
    over max_seq_len) fails fast at fleet.submit — it must NOT be
    dispatched to bounce off (or kill) a replica worker."""
    fleet = ServeFleet(factory, n_replicas=1)
    try:
        with pytest.raises(ValueError, match="exceeds max_seq_len"):
            fleet.submit(np.zeros(23, np.int32), 8)
        with pytest.raises(ValueError, match="empty prompt"):
            fleet.submit(np.zeros(0, np.int32), 4)
        assert fleet.metrics.accepted == 0
        # the fleet still serves fine afterwards
        p = _prompts(rng, (5,))[0]
        k = jax.random.key(60)
        np.testing.assert_array_equal(
            fleet.generate([p], max_new_tokens=4, keys=[k],
                           timeout=300)[0],
            _oracle(params, p, 4, k))
        assert all(r.state == HEALTHY for r in fleet.replicas)
    finally:
        fleet.drain(timeout=60)


def test_kill_one_of_three_migrates_token_identically(factory, params,
                                                      rng):
    """THE chaos demo: replica r1 of 3 is killed (ft.ChaosMonkey,
    mode='raise') after its 3rd step with requests mid-flight. Every
    request still completes, token-identical to the undisturbed
    oracle — including a STREAMING request that migrates mid-stream
    (tokens in order, is_last exactly once, nothing re-delivered)."""
    prompts = _prompts(rng, (5, 7, 3, 6, 4, 8, 5, 6, 4))
    keys = [jax.random.key(500 + i) for i in range(9)]
    monkey = ChaosMonkey(kill_at_step=3, mode="raise", target="r1")
    fleet = ServeFleet(factory, n_replicas=3, policy="round_robin",
                       chaos=monkey)
    try:
        streamed = []
        fids = []
        for i, (p, k) in enumerate(zip(prompts, keys)):
            on_token = ((lambda fid, tok, last:
                         streamed.append((tok, last)))
                        if i == 1 else None)   # round_robin: i=1 -> r1
            fids.append(fleet.submit(p, 8, key=k, on_token=on_token))
        outs = [fleet.result(f, timeout=300) for f in fids]
        for p, k, o in zip(prompts, keys, outs):
            np.testing.assert_array_equal(o, _oracle(params, p, 8, k))

        m = fleet.metrics
        assert m.replica_deaths == 1
        assert m.migrations >= 1           # in-flight work moved over
        assert m.restarts == 1             # breaker closed -> restart
        assert m.finished == 9 and m.shed == 0
        # the streaming request survived migration with an intact,
        # in-order, exactly-once token stream
        toks = [t for t, _ in streamed]
        np.testing.assert_array_equal(
            np.asarray(toks, np.int32), outs[1][len(prompts[1]):])
        assert [last for _, last in streamed].count(True) == 1
        assert streamed[-1][1] is True
        # every replica that served kept the one-prefill+one-decode
        # promise (idle just-restarted engines are skipped)
        fleet.assert_compile_count()
    finally:
        fleet.drain(timeout=120)


def test_burst_sheds_typed_and_deadline_expiry(factory, params, rng):
    """Over-capacity burst: the bounded queue rejects with
    Overloaded('queue_full') instead of growing; a queued request whose
    deadline lapses is shed with Overloaded('deadline'); everything
    accepted still completes golden."""
    clk = FakeClock()
    prompts = _prompts(rng, (5, 6, 4, 7, 5, 6))
    keys = [jax.random.key(700 + i) for i in range(6)]
    fleet = ServeFleet(factory, n_replicas=1, max_pending=4, clock=clk)
    try:
        fleet.pause_all()                  # freeze: nothing dispatches
        ok = [fleet.submit(prompts[0], 6, key=keys[0])]
        fid_dead = fleet.submit(prompts[1], 6, key=keys[1], deadline_s=5)
        ok += [fleet.submit(prompts[2], 6, key=keys[2]),
               fleet.submit(prompts[3], 6, key=keys[3])]
        with pytest.raises(Overloaded) as ei:
            fleet.submit(prompts[4], 6, key=keys[4])   # queue full
        assert ei.value.reason == "queue_full"
        with pytest.raises(Overloaded) as ei:
            fleet.submit(prompts[5], 6, key=keys[5], deadline_s=0)
        assert ei.value.reason == "deadline"
        assert len(fleet._queue) <= 4      # bound held under the burst

        clk.advance(10.0)                  # fid_dead's deadline lapses
        _wait_until(lambda: fleet.request(fid_dead).event.is_set(),
                    msg="deadline shed")
        with pytest.raises(Overloaded) as ei:
            fleet.result(fid_dead)
        assert ei.value.reason == "deadline"

        fleet.resume_all()
        for fid, i in zip(ok, (0, 2, 3)):
            np.testing.assert_array_equal(
                fleet.result(fid, timeout=300),
                _oracle(params, prompts[i], 6, keys[i]))
        m = fleet.metrics
        assert m.shed_queue_full == 1 and m.shed_deadline == 2
        assert m.submitted == 6 and m.accepted == 4 and m.finished == 3
        assert m.shed_rate == pytest.approx(0.5)
    finally:
        fleet.drain(timeout=120)


def test_breaker_trips_then_half_open_probe_recovers(factory, params,
                                                     rng):
    """Repeated kills of r0 (rearmed chaos) trip its breaker after 2
    consecutive failures: no more restarts, work migrates to r1,
    everything completes. After reset_s the breaker grants ONE probe
    restart; the probe completing a request closes the breaker."""
    clk = FakeClock()
    prompts = _prompts(rng, (5, 6, 4, 7))
    keys = [jax.random.key(900 + i) for i in range(4)]
    monkey = ChaosMonkey(kill_at_step=1, mode="raise", target="r0",
                         rearm=True)
    fleet = ServeFleet(factory, n_replicas=2, policy="round_robin",
                       trip_after=2, breaker_reset_s=30.0, chaos=monkey,
                       clock=clk)
    try:
        fids = [fleet.submit(p, 6, key=k)
                for p, k in zip(prompts, keys)]
        for fid, p, k in zip(fids, prompts, keys):
            np.testing.assert_array_equal(
                fleet.result(fid, timeout=300),
                _oracle(params, p, 6, k))
        _wait_until(lambda: fleet.breaker("r0").state == OPEN,
                    msg="breaker open after repeated kills")
        assert fleet.metrics.replica_deaths == 2
        assert fleet.metrics.restarts == 1   # 2nd death tripped instead
        assert fleet.metrics.migrations >= 2

        # recovery: disarm the fault, let the cool-down elapse -> the
        # dispatcher spawns exactly one half-open probe
        monkey.kill_at_step = None
        clk.advance(31.0)
        _wait_until(lambda: fleet.metrics.restarts == 2,
                    msg="half-open probe restart")
        assert fleet.breaker("r0").state == HALF_OPEN
        probe_keys = [jax.random.key(950 + i) for i in range(2)]
        probe_prompts = _prompts(rng, (5, 6))
        outs = fleet.generate(probe_prompts, max_new_tokens=4,
                              keys=probe_keys, timeout=300)
        for p, k, o in zip(probe_prompts, probe_keys, outs):
            np.testing.assert_array_equal(o, _oracle(params, p, 4, k))
        _wait_until(lambda: fleet.breaker("r0").state == "closed",
                    msg="probe success closes the breaker")
        assert all(r.state == HEALTHY for r in fleet.replicas)
    finally:
        fleet.drain(timeout=120)


# ---------------------------------------------------------------------
# speculative decoding x migration (quintnet_tpu/serve/spec.py)
# ---------------------------------------------------------------------

def test_kill_mid_speculation_migrates_token_identically(rng):
    """Replica r1 of 2 is killed while its requests have in-flight
    speculative drafts (spec-enabled engines on repetition-prone
    traffic — drafts are being accepted when the chaos fires). The
    migrated RequestProgress carries COMMITTED tokens only: every
    request resumes on the healthy replica token-identical to the
    undisturbed greedy oracle, drafts never leak into exported
    progress, and the per-replica compile bound now includes the
    verify buckets."""
    from quintnet_tpu.serve import SpecConfig

    cfg = GPT2Config.tiny(n_layer=2, n_positions=256)
    sparams = gpt2_init(jax.random.key(1), cfg)  # repetition-prone init

    def spec_factory():
        return ServeEngine(gpt2_family(cfg), sparams, max_slots=2,
                           block_size=8, num_blocks=32, max_seq_len=100,
                           spec=SpecConfig())

    def oracle(prompt, max_new, key):
        return np.asarray(gpt2_generate(
            sparams, prompt[None], cfg, max_new_tokens=max_new,
            temperature=0.0, key=key)[0])

    prompts = [np.asarray(rng.integers(0, cfg.vocab_size, (n,)),
                          np.int32) for n in (12, 9, 11, 8)]
    keys = [jax.random.key(1300 + i) for i in range(4)]
    monkey = ChaosMonkey(kill_at_step=6, mode="raise", target="r1")
    fleet = ServeFleet(spec_factory, n_replicas=2, policy="round_robin",
                       chaos=monkey)
    try:
        fids = [fleet.submit(p, 60, key=k)
                for p, k in zip(prompts, keys)]
        outs = [fleet.result(f, timeout=300) for f in fids]
        for p, k, o in zip(prompts, keys, outs):
            np.testing.assert_array_equal(o, oracle(p, 60, k))

        m = fleet.metrics
        assert m.replica_deaths == 1
        assert m.migrations >= 1
        assert m.finished == 4 and m.shed == 0
        # speculation was actually in flight fleet-wide (accepted
        # drafts recorded before AND independent of the kill)
        eng = fleet.summary()["engine"]
        assert eng["accepted_draft_tokens"] > 0
        assert eng["spec_steps"] > 0
        # no replica leaked a tentative block past its step
        assert all(r.engine.pool.num_tentative == 0
                   for r in fleet.replicas)
        fleet.assert_compile_count()
    finally:
        fleet.drain(timeout=120)

"""Sequence packing (concat-and-chunk): zero padding waste, EOS
separators, exact row reconstruction. The reference right-pads every
row instead (utils/Dataloader.py:263-319) — packing is an upgrade, so
the contract is pinned here.
"""

import numpy as np
import pytest

from quintnet_tpu.data import ByteTokenizer, PackedLMDataset, pack_documents

pytestmark = pytest.mark.fast

EOS = 256


def test_pack_documents_layout():
    docs = [[1, 2, 3], [4, 5], [6]]
    rows = pack_documents(docs, 4, eos_id=EOS)
    # stream: 1 2 3 E 4 5 E 6 E  -> two full rows of 4, tail dropped
    flat = [1, 2, 3, EOS, 4, 5, EOS, 6, EOS]
    assert rows.shape == (2, 4)
    np.testing.assert_array_equal(rows.ravel(), flat[:8])


def test_pack_keep_remainder_pads_with_eos():
    rows = pack_documents([[1, 2, 3]], 4, eos_id=EOS, drop_remainder=False)
    np.testing.assert_array_equal(rows, [[1, 2, 3, EOS]])
    rows = pack_documents([[1, 2, 3, 4]], 4, eos_id=EOS,
                          drop_remainder=False)
    # 5-token stream (ids + eos) -> row 2 is eos-padded
    np.testing.assert_array_equal(rows, [[1, 2, 3, 4],
                                         [EOS, EOS, EOS, EOS]])


def test_packed_dataset_batches_are_label_identical():
    tok = ByteTokenizer()
    ds = PackedLMDataset.from_texts(["hello world"] * 8, tok, seq_len=16)
    assert len(ds) >= 1
    got = 0
    for x, y in ds.batches(1, shuffle=False):
        np.testing.assert_array_equal(x, y)
        assert x.dtype == np.int32 and x.shape == (1, 16)
        got += 1
    assert got == len(ds)


def test_packed_rows_contain_no_pad_waste():
    tok = ByteTokenizer()
    ds = PackedLMDataset.from_texts(["ab", "cd", "ef"], tok, seq_len=3)
    # stream: a b E c d E e f E = 9 bytes -> 3 rows, every position real
    assert ds.rows.shape == (3, 3)
    assert (ds.rows >= 0).all()


def test_prefetch_batches_order_and_exception():
    from quintnet_tpu.data import prefetch_batches

    assert list(prefetch_batches(iter(range(7)), n=2)) == list(range(7))

    def boom():
        yield 1
        raise ValueError("host pipeline died")

    it = prefetch_batches(boom(), n=2)
    assert next(it) == 1
    with pytest.raises(ValueError, match="host pipeline died"):
        next(it)

"""HF datasets/arrow reader (reference CustomDataset,
utils/Dataloader.py:38-141): save_to_disk dirs, DatasetDict splits,
bare .arrow files, and the summarization/MNIST bridges."""

import numpy as np
import pytest

datasets = pytest.importorskip("datasets")

from quintnet_tpu.data.datasets import (
    ByteTokenizer,
    load_hf_dataset,
    mnist_from_hf,
    summarization_from_hf,
)


@pytest.fixture
def summ_dir(tmp_path):
    ds = datasets.DatasetDict({
        "train": datasets.Dataset.from_dict({
            "article": [f"article number {i} with several words" for i in range(6)],
            "highlights": [f"summary {i}" for i in range(6)],
        }),
        "validation": datasets.Dataset.from_dict({
            "article": ["val article"], "highlights": ["val summary"],
        }),
    })
    p = tmp_path / "summ"
    ds.save_to_disk(str(p))
    return str(p)


def test_load_dir_with_splits(summ_dir):
    train = load_hf_dataset(summ_dir, "train")
    assert len(train) == 6
    val = load_hf_dataset(summ_dir, "validation")
    assert val[0]["article"] == "val article"


def test_unknown_split_lists_available(summ_dir):
    with pytest.raises(ValueError, match="train"):
        load_hf_dataset(summ_dir, "test")


def test_load_single_dataset_dir(tmp_path):
    ds = datasets.Dataset.from_dict({"a": [1, 2, 3]})
    p = tmp_path / "single"
    ds.save_to_disk(str(p))
    # split is ignored for a split-less save (reference behavior)
    assert len(load_hf_dataset(str(p), "train")) == 3


def test_load_bare_arrow_file(tmp_path, summ_dir):
    import glob

    arrow = glob.glob(f"{summ_dir}/train/*.arrow")[0]
    ds = load_hf_dataset(arrow)
    assert len(ds) == 6


def test_missing_path_raises():
    with pytest.raises(FileNotFoundError):
        load_hf_dataset("/nonexistent/nowhere")


def test_summarization_bridge(summ_dir):
    sd = summarization_from_hf(summ_dir, ByteTokenizer(), max_length=64,
                               limit=4)
    assert len(sd) == 4
    ids, labels = next(sd.batches(2, shuffle=False))
    assert ids.shape == (2, 64) and labels.shape == (2, 64)
    # prompt region masked to -100, summary region supervised
    assert (labels[0] == -100).any() and (labels[0] != -100).any()


def test_mnist_bridge(tmp_path):
    rng = np.random.default_rng(0)
    imgs = rng.integers(0, 256, (10, 28, 28), dtype=np.uint8)
    ds = datasets.Dataset.from_dict({
        "image": [im.tolist() for im in imgs],
        "label": list(range(10)),
    })
    p = tmp_path / "mnist"
    ds.save_to_disk(str(p))
    x, y = mnist_from_hf(str(p))
    assert x.shape == (10, 28, 28, 1) and x.dtype == np.float32
    np.testing.assert_array_equal(y, np.arange(10))
    # normalisation matches load_mnist's mean/std
    np.testing.assert_allclose(
        x[0, 0, 0, 0], (imgs[0, 0, 0] / 255.0 - 0.1307) / 0.3081, rtol=1e-5)

"""Worker for tests/test_5d.py — runs GPT-2-MoE 1F1B training on a full
five-axis dp x tp x pp x sp x ep = 2x2x2x2x2 mesh (32 virtual CPU
devices, own process so the device count doesn't clash with the main
suite's 8) and asserts golden parity with single-device math in-process.
Writes a result JSON as its last act so the parent can distinguish
"asserts passed" from "crashed".
"""

import json
import os
import sys

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count=32")

import jax

jax.config.update("jax_platforms", "cpu")

import dataclasses

import jax.numpy as jnp
import numpy as np
import optax

from quintnet_tpu.core.config import Config
from quintnet_tpu.models.gpt2 import (
    GPT2Config,
    clm_loss,
    gpt2_forward,
    gpt2_init,
    gpt2_model_spec,
    gpt2_to_tp_layout,
)
from quintnet_tpu.parallel.strategy import get_strategy


def main():
    outfile = sys.argv[1]
    assert jax.device_count() == 32, jax.device_count()

    gcfg = GPT2Config.tiny(
        vocab_size=128, n_positions=32, n_layer=2, n_head=4,
        n_experts=4, expert_top_k=2, expert_capacity=4096,
        aux_loss_weight=0.0)  # no drops, no aux: exact golden parity
    cfg = Config.from_dict({
        "mesh_dim": [2, 2, 2, 2, 2],
        "mesh_name": ["dp", "tp", "pp", "sp", "ep"],
        "training": {
            "batch_size": 8,
            "gradient_accumulation_steps": 2,
            "schedule": "1f1b",
            "grad_clip_norm": None,
        },
    })

    ids = np.asarray(jax.random.randint(jax.random.key(1), (8, 16), 0,
                                        gcfg.vocab_size), np.int32)
    params0 = gpt2_init(jax.random.key(0), gcfg)
    opt = optax.sgd(0.05)

    # single-device reference
    def ref_loss(p):
        logits, _aux = gpt2_forward(p, jnp.asarray(ids), gcfg)
        return clm_loss(logits, jnp.asarray(ids))

    p_ref = params0
    state = opt.init(p_ref)
    ref_losses = []
    for _ in range(2):
        loss, g = jax.value_and_grad(ref_loss)(p_ref)
        upd, state = opt.update(g, state, p_ref)
        p_ref = optax.apply_updates(p_ref, upd)
        ref_losses.append(float(loss))

    # 5D run
    strat = get_strategy("5d", cfg)
    assert dict(strat.mesh.shape) == {"dp": 2, "tp": 2, "pp": 2,
                                      "sp": 2, "ep": 2}
    model = gpt2_model_spec(gcfg)
    p = strat.shard_params(model, jax.tree.map(jnp.copy, params0))
    s = strat.init_opt_state(model, opt, p)
    b = strat.shard_batch((jnp.asarray(ids), jnp.asarray(ids)), model)
    step = strat.make_train_step(model, opt)
    losses = []
    for _ in range(2):
        p, s, loss = step(p, s, b)
        losses.append(float(loss))

    np.testing.assert_allclose(losses, ref_losses, rtol=1e-4)

    p_ref_layout = gpt2_to_tp_layout(p_ref, gcfg, 2)
    ref = dict(jax.tree_util.tree_leaves_with_path(p_ref_layout))
    for path, leaf in jax.tree_util.tree_leaves_with_path(p):
        np.testing.assert_allclose(
            np.asarray(jax.device_get(leaf)), np.asarray(ref[path]),
            rtol=5e-4, atol=1e-5,
            err_msg=jax.tree_util.keystr(path))

    with open(outfile, "w") as f:
        json.dump({"losses": losses, "ref_losses": ref_losses,
                   "ok": True}, f)
    print("5d worker done", flush=True)


if __name__ == "__main__":
    main()

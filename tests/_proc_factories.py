"""Engine builders for PROCESS-fleet tests (quintnet_tpu/fleet/proc.py).

Replica processes load this module by FILE PATH (the fleet's engine
spec: ``{"file": __file__, "func": "build_tiny_gpt2", "kwargs":
{...}}``) and call the named builder — a spawn child cannot unpickle a
test's closure, and must construct its own engine anyway: that is what
guarantees every replica holds the same (family, params), the
precondition of the migration contract. Builders are DETERMINISTIC in
their kwargs (params come from ``gpt2_init(jax.random.key(seed))``),
so the parent test can build the byte-identical oracle engine/params
in its own process.
"""

import jax


def build_tiny_gpt2(*, seed: int = 0, n_layer: int = 2, max_slots: int = 2,
                    block_size: int = 4, num_blocks: int = 24,
                    max_seq_len: int = 24, temperature: float = 0.0,
                    top_k: int = 0, eos_token_id=None,
                    n_positions=None, prefill_len=None,
                    chunked_prefill: bool = False,
                    prefill_chunk_budget=None,
                    kv_dtype=None, weights_dtype=None,
                    prefix_cache: bool = True,
                    attn_kernel: str = "xla",
                    kv_tier_bytes: int = 0,
                    n_experts: int = 0, expert_top_k: int = 2,
                    expert_capacity=None):
    from quintnet_tpu.models.gpt2 import GPT2Config, gpt2_init
    from quintnet_tpu.serve import ServeEngine, gpt2_family

    # n_experts > 0 makes the replica an MoE engine (dense-replicated:
    # a fleet replica process owns no ep mesh) — its routing ledger
    # rides the stats frame like every other ServeMetrics field
    cfg = GPT2Config.tiny(n_layer=n_layer, n_experts=n_experts,
                          expert_top_k=expert_top_k,
                          expert_capacity=expert_capacity,
                          **({} if n_positions is None
                             else {"n_positions": n_positions}))
    params = gpt2_init(jax.random.key(seed), cfg)
    return ServeEngine(gpt2_family(cfg), params, max_slots=max_slots,
                       block_size=block_size, num_blocks=num_blocks,
                       max_seq_len=max_seq_len, prefill_len=prefill_len,
                       chunked_prefill=chunked_prefill,
                       prefill_chunk_budget=prefill_chunk_budget,
                       kv_dtype=kv_dtype, weights_dtype=weights_dtype,
                       prefix_cache=prefix_cache,
                       attn_kernel=attn_kernel, temperature=temperature,
                       top_k=top_k, eos_token_id=eos_token_id,
                       kv_tier_bytes=kv_tier_bytes)

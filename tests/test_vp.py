"""Vocab-parallel GPT-2 golden tests.

The reference defines VocabParallelEmbedding but never uses it
(tensor_parallel/layers.py:224-297 — GPT-2 replicates embeddings,
gpt2_embeddings.py:8-9). Here vocab parallelism is a first-class GPT-2
option (models/gpt2.py GPT2Config.vocab_parallel): wte sharded over tp,
embedding via masked-lookup + psum, and a sharded cross-entropy that
never materialises full [B, T, V] logits. These tests pin it to the
replicated/single-device math.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from quintnet_tpu.core import collectives as cc
from quintnet_tpu.core.config import Config
from quintnet_tpu.models.gpt2 import (
    GPT2Config,
    clm_loss,
    clm_loss_vp,
    gpt2_apply,
    gpt2_init,
    gpt2_model_spec,
    gpt2_to_tp_layout,
)
from quintnet_tpu.parallel.strategy import get_strategy

VOCAB = 128
CFG = GPT2Config.tiny(vocab_size=VOCAB)
VP_CFG = dataclasses.replace(CFG, vocab_parallel=True)


def _mesh(shape, names):
    devs = np.array(jax.devices()[: int(np.prod(shape))]).reshape(shape)
    return Mesh(devs, names)


def _data(n=8, t=16, seed=3):
    k1 = jax.random.key(seed)
    ids = jax.random.randint(k1, (n, t), 0, VOCAB)
    # mask a fixed PREFIX per row (prompt masking, reference collator
    # semantics): identical valid counts per dp shard, so the dp
    # mean-of-shard-means equals the global mean exactly and the golden
    # comparison is tight
    col = jnp.arange(t)
    labels = jnp.where(col[None, :] < 3, -100, ids)
    return ids, labels


def test_clm_loss_vp_matches_dense():
    """Sharded CE == dense CE on the same (column-sharded) logits."""
    mesh = _mesh((2,), ("tp",))
    logits = jax.random.normal(jax.random.key(0), (4, 12, VOCAB))
    _, labels = _data(4, 12)

    dense = clm_loss(logits, labels)

    fn = cc.shard_map_fn(
        lambda lg, lb: clm_loss_vp(lg, lb, tp_axis="tp"),
        mesh,
        in_specs=(P(None, None, "tp"), P()),
        out_specs=P(),
    )
    sharded = jax.jit(fn)(logits, labels)
    np.testing.assert_allclose(float(sharded), float(dense), rtol=1e-6)


def _config(mesh_dim, mesh_name, schedule="afab", grad_acc=1):
    return Config.from_dict({
        "mesh_dim": list(mesh_dim),
        "mesh_name": list(mesh_name),
        "training": {
            "batch_size": 8,
            "gradient_accumulation_steps": grad_acc,
            "schedule": schedule,
            "grad_clip_norm": None,
        },
    })


def _reference_update(params, batch, opt, cfg=CFG, steps=2):
    ids, labels = batch

    def loss_fn(p):
        return clm_loss(gpt2_apply(p, ids, cfg), labels)

    state = opt.init(params)
    losses = []
    for _ in range(steps):
        loss, g = jax.value_and_grad(loss_fn)(params)
        upd, state = opt.update(g, state, params)
        params = optax.apply_updates(params, upd)
        losses.append(float(loss))
    return losses, params


def _run_strategy(name, cfg, model_cfg, params, batch, steps=2):
    strat = get_strategy(name, cfg)
    model = gpt2_model_spec(model_cfg)
    opt = optax.sgd(0.05)
    p = strat.shard_params(model, jax.tree.map(jnp.copy, params))
    s = strat.init_opt_state(model, opt, p)
    b = strat.shard_batch(batch, model)
    step = strat.make_train_step(model, opt)
    losses = []
    for _ in range(steps):
        p, s, loss = step(p, s, b)
        losses.append(float(loss))
    return losses, p


@pytest.mark.parametrize(
    "name,mesh_dim,mesh_name,schedule,grad_acc",
    [
        ("tp", [2], ["tp"], "afab", 1),
        ("dp_tp", [2, 2], ["dp", "tp"], "afab", 1),
        ("3d", [2, 2, 2], ["dp", "tp", "pp"], "afab", 2),
        ("3d", [2, 2, 2], ["dp", "tp", "pp"], "1f1b", 2),
        # tp x sp x pp: vp loss composed with the sequence-sharded CE
        ("auto", [2, 2, 2], ["tp", "sp", "pp"], "1f1b", 2),
    ],
)
def test_vp_matches_single_device(name, mesh_dim, mesh_name, schedule,
                                  grad_acc):
    cfg = _config(mesh_dim, mesh_name, schedule, grad_acc)
    params = gpt2_init(jax.random.key(0), CFG)
    batch = _data()
    opt = optax.sgd(0.05)

    ref_losses, p_ref = _reference_update(params, batch, opt)
    losses, p2 = _run_strategy(name, cfg, VP_CFG, params, batch)

    np.testing.assert_allclose(losses, ref_losses, rtol=1e-4)

    p_ref_layout = gpt2_to_tp_layout(p_ref, CFG, cfg.tp_size)
    ref = dict(jax.tree_util.tree_leaves_with_path(p_ref_layout))
    for path, leaf in jax.tree_util.tree_leaves_with_path(p2):
        np.testing.assert_allclose(
            np.asarray(jax.device_get(leaf)), np.asarray(ref[path]),
            rtol=2e-4, atol=1e-5, err_msg=f"{name}:{jax.tree_util.keystr(path)}")


def test_vp_padded_vocab_masks_pad_columns():
    """padded_vocab_size: loss identical to the unpadded model and the
    padded wte rows receive exactly zero gradient."""
    real_v = 123  # not divisible by tp=2
    base = GPT2Config.tiny(vocab_size=real_v)
    padded = dataclasses.replace(base, vocab_parallel=True,
                                 padded_vocab_size=128)

    params = gpt2_init(jax.random.key(0), base)
    k1, k2 = jax.random.split(jax.random.key(7))
    ids = jax.random.randint(k1, (8, 16), 0, real_v)
    labels = jnp.where(jax.random.uniform(k2, (8, 16)) < 0.1, -100, ids)
    opt = optax.sgd(0.05)

    ref_losses, p_ref = _reference_update(params, (ids, labels), opt,
                                          cfg=base)

    # pad wte rows with garbage (not zeros) to prove masking works
    pad = jnp.full((128 - real_v, base.n_embd), 3.7, jnp.float32)
    p_padded = jax.tree.map(jnp.copy, params)
    p_padded["embedding"]["wte"] = jnp.concatenate(
        [p_padded["embedding"]["wte"], pad], axis=0)

    cfg = _config([2], ["tp"])
    losses, p2 = _run_strategy("tp", cfg, padded, p_padded, (ids, labels))

    np.testing.assert_allclose(losses, ref_losses, rtol=2e-5)
    wte2 = np.asarray(jax.device_get(p2["embedding"]["wte"]))
    # padded rows: zero grad -> unchanged under sgd
    np.testing.assert_array_equal(wte2[real_v:], np.asarray(pad))
    np.testing.assert_allclose(
        wte2[:real_v], np.asarray(p_ref["embedding"]["wte"]),
        rtol=2e-4, atol=1e-5)


def test_padded_vocab_masked_without_tp():
    """A vocab_parallel+padded config run with NO tp axis (single-device
    fallback, generation) must still mask the padded columns: loss equals
    the unpadded model and argmax can never pick an id >= vocab_size."""
    from quintnet_tpu.models.gpt2 import gpt2_apply

    real_v = 123
    base = GPT2Config.tiny(vocab_size=real_v)
    padded = dataclasses.replace(base, vocab_parallel=True,
                                 padded_vocab_size=128)
    params = gpt2_init(jax.random.key(0), base)
    p_padded = jax.tree.map(jnp.copy, params)
    p_padded["embedding"]["wte"] = jnp.concatenate(
        [p_padded["embedding"]["wte"],
         jnp.full((128 - real_v, base.n_embd), 9.9, jnp.float32)], axis=0)

    ids = jax.random.randint(jax.random.key(5), (2, 12), 0, real_v)
    logits_base = gpt2_apply(params, ids, base)
    logits_pad = gpt2_apply(p_padded, ids, padded)
    # real columns identical; padded columns -inf -> never argmax'd,
    # zero softmax mass
    np.testing.assert_allclose(np.asarray(logits_pad[..., :real_v]),
                               np.asarray(logits_base), rtol=1e-6)
    assert np.all(np.asarray(jnp.argmax(logits_pad, -1)) < real_v)
    np.testing.assert_allclose(
        float(clm_loss(logits_pad, ids)), float(clm_loss(logits_base, ids)),
        rtol=1e-6)


def test_vp_requires_divisible_vocab():
    bad = dataclasses.replace(GPT2Config.tiny(vocab_size=123),
                              vocab_parallel=True)
    with pytest.raises(ValueError, match="vocab_parallel"):
        gpt2_to_tp_layout(gpt2_init(jax.random.key(0), bad), bad, tp=2)


# ---------------------------------------------------------------------------
# Llama vocab parallelism (models/llama.py LlamaConfig.vocab_parallel) —
# at Llama-3's 128k vocab the replicated table is the largest tensor, so
# vp matters most for this family


def _llama_cfgs(padded=False):
    from quintnet_tpu.models.llama import LlamaConfig

    base = LlamaConfig.tiny(vocab_size=VOCAB)
    kw = dict(vocab_parallel=True)
    if padded:
        base = LlamaConfig.tiny(vocab_size=VOCAB - 6)
        kw["padded_vocab_size"] = VOCAB
    return base, dataclasses.replace(base, **kw)


@pytest.mark.parametrize(
    "name,mesh_dim,mesh_name,schedule,grad_acc,tie",
    [
        ("tp", [2], ["tp"], "afab", 1, True),
        ("tp", [2], ["tp"], "afab", 1, False),
        ("dp_tp", [2, 2], ["dp", "tp"], "afab", 1, True),
        ("3d", [2, 2, 2], ["dp", "tp", "pp"], "1f1b", 2, True),
        ("auto", [2, 2, 2], ["tp", "sp", "pp"], "1f1b", 2, True),
    ],
)
def test_llama_vp_matches_single_device(name, mesh_dim, mesh_name,
                                        schedule, grad_acc, tie):
    from quintnet_tpu.models.llama import (LlamaConfig, llama_init,
                                           llama_model_spec)

    base = LlamaConfig.tiny(vocab_size=VOCAB, tie_embeddings=tie)
    vp_cfg = dataclasses.replace(base, vocab_parallel=True)
    cfg = _config(mesh_dim, mesh_name, schedule, grad_acc)
    params = llama_init(jax.random.key(0), base)
    batch = _data()
    opt = optax.sgd(0.05)

    # single-device reference
    model_ref = llama_model_spec(base)
    losses_ref, p_ref = [], params
    state = opt.init(params)
    for _ in range(2):
        loss, g = jax.value_and_grad(model_ref.loss_fn)(p_ref, batch)
        up, state = opt.update(g, state, p_ref)
        p_ref = optax.apply_updates(p_ref, up)
        losses_ref.append(float(loss))

    strat = get_strategy(name, cfg)
    model = llama_model_spec(vp_cfg)
    p = strat.shard_params(model, jax.tree.map(jnp.copy, params))
    s = strat.init_opt_state(model, opt, p)
    b = strat.shard_batch(batch, model)
    step = strat.make_train_step(model, opt)
    losses = []
    for _ in range(2):
        p, s, loss = step(p, s, b)
        losses.append(float(loss))

    np.testing.assert_allclose(losses, losses_ref, rtol=1e-4)
    ref = dict(jax.tree_util.tree_leaves_with_path(p_ref))
    for path, leaf in jax.tree_util.tree_leaves_with_path(p):
        np.testing.assert_allclose(
            np.asarray(jax.device_get(leaf)), np.asarray(ref[path]),
            rtol=2e-4, atol=1e-5,
            err_msg=f"{name}:{jax.tree_util.keystr(path)}")


def test_llama_vp_padded_vocab_matches_unpadded():
    """padded_vocab_size under vp: loss equals the unpadded single-
    device model. TIED embeddings + GARBAGE pad rows make the masking
    load-bearing: the pad rows feed the lm head as logit columns, so
    deleting the vocab_size mask in clm_loss_vp fails this test."""
    import dataclasses as _dc

    from quintnet_tpu.models.llama import (LlamaConfig, llama_init,
                                           llama_model_spec)

    base = LlamaConfig.tiny(vocab_size=VOCAB - 6, tie_embeddings=True)
    vp_pad = _dc.replace(base, vocab_parallel=True,
                         padded_vocab_size=VOCAB)
    params = llama_init(jax.random.key(0), base)
    ids = jax.random.randint(jax.random.key(3), (4, 16), 0,
                             base.vocab_size)
    batch = (ids, ids)

    ref = llama_model_spec(base).loss_fn(params, batch)

    pad_rows = vp_pad.table_vocab_size - base.vocab_size
    padded = jax.tree.map(jnp.copy, params)
    padded["embedding"]["tok"] = jnp.pad(
        padded["embedding"]["tok"], ((0, pad_rows), (0, 0)),
        constant_values=3.7)  # garbage: only the mask hides it

    cfg = _config([2], ["tp"])
    strat = get_strategy("tp", cfg)
    model = llama_model_spec(vp_pad)
    p = strat.shard_params(model, padded)
    b = strat.shard_batch(batch, model)
    opt = optax.sgd(0.05)
    s = strat.init_opt_state(model, opt, p)
    step = strat.make_train_step(model, opt)
    p2, _, loss = step(p, s, b)
    np.testing.assert_allclose(float(loss), float(ref), rtol=1e-5)
    # pad rows must receive ZERO gradient (still exactly 3.7 after sgd)
    tok2 = np.asarray(jax.device_get(p2["embedding"]["tok"]))
    np.testing.assert_array_equal(tok2[base.vocab_size:],
                                  np.float32(3.7))


def test_llama_vp_requires_divisible_vocab():
    from quintnet_tpu.models.llama import LlamaConfig, llama_init, \
        llama_model_spec

    bad = LlamaConfig.tiny(vocab_size=127, vocab_parallel=True)
    cfg = _config([2], ["tp"])
    strat = get_strategy("tp", cfg)
    model = llama_model_spec(bad)
    with pytest.raises(ValueError, match="vocab_parallel"):
        strat.shard_params(model, llama_init(jax.random.key(0), bad))


def test_llama_vp_tp_generate_matches_single_device():
    """vp-trained layout decode: llama_generate_tp with vocab_parallel
    (sharded table, padded vocab, garbage pad rows) == single-device
    decode on the unpadded model, token for token (greedy)."""
    import dataclasses as _dc

    from quintnet_tpu.core.mesh import mesh_from_sizes
    from quintnet_tpu.models.llama import (LlamaConfig, llama_init,
                                           llama_partition_specs)
    from quintnet_tpu.models.llama_generate import (llama_generate,
                                                    llama_generate_tp)
    from quintnet_tpu.parallel.train_step import shard_pytree

    base = LlamaConfig.tiny(vocab_size=VOCAB - 6, tie_embeddings=True)
    vp_pad = _dc.replace(base, vocab_parallel=True,
                         padded_vocab_size=VOCAB)
    params = llama_init(jax.random.key(0), base)
    ids = jax.random.randint(jax.random.key(7), (2, 5), 0,
                             base.vocab_size)
    ref = llama_generate(params, ids, base, max_new_tokens=5)

    pad_rows = vp_pad.table_vocab_size - base.vocab_size
    padded = jax.tree.map(jnp.copy, params)
    padded["embedding"]["tok"] = jnp.pad(
        padded["embedding"]["tok"], ((0, pad_rows), (0, 0)),
        constant_values=3.7)  # decode must never surface these columns

    mesh = mesh_from_sizes(tp=2)
    specs = llama_partition_specs(vp_pad, tp_axis="tp")
    sharded = shard_pytree(mesh, padded, specs)
    out = llama_generate_tp(sharded, ids, vp_pad, mesh=mesh,
                            max_new_tokens=5)
    np.testing.assert_array_equal(out, ref)


def test_llama_vp_sp_segments_moe_composition():
    """Capstone composition: Llama-MoE with vocab_parallel AND
    packed-document isolation on a tp x sp x ep mesh — sharded table,
    sharded CE, sp-aware global segment ids and expert dispatch in ONE
    step, loss golden vs single device."""
    import dataclasses as _dc

    from quintnet_tpu.models.llama import (LlamaConfig, llama_init,
                                           llama_model_spec)

    base = LlamaConfig.tiny(vocab_size=VOCAB, tie_embeddings=True,
                            n_experts=4, expert_top_k=2,
                            expert_capacity=4096, aux_loss_weight=0.0,
                            segment_eos_id=5)
    vp_cfg = _dc.replace(base, vocab_parallel=True)
    params = llama_init(jax.random.key(0), base)
    ids = np.array(jax.random.randint(jax.random.key(3), (4, 16), 0,
                                      VOCAB), np.int32)  # writable copy
    ids[:, 6] = 5  # separator inside every row, off the sp boundary
    batch = (jnp.asarray(ids), jnp.asarray(ids))

    ref = llama_model_spec(base).loss_fn(params, batch)

    cfg = _config([2, 2, 2], ["tp", "sp", "ep"])
    strat = get_strategy("auto", cfg)
    model = llama_model_spec(vp_cfg)
    opt = optax.sgd(0.05)
    p = strat.shard_params(model, jax.tree.map(jnp.copy, params))
    s = strat.init_opt_state(model, opt, p)
    b = strat.shard_batch(batch, model)
    step = strat.make_train_step(model, opt)
    _, _, loss = step(p, s, b)
    np.testing.assert_allclose(float(loss), float(ref), rtol=1e-4)


def test_gpt2_vp_sp_segments_composition():
    """GPT-2 twin of the capstone: vocab_parallel + segment isolation
    on tp x sp, loss golden vs single device."""
    import dataclasses as _dc

    gcfg = GPT2Config.tiny(vocab_size=VOCAB, segment_eos_id=5)
    vp_cfg = _dc.replace(gcfg, vocab_parallel=True)
    params = gpt2_init(jax.random.key(0), gcfg)
    ids = np.array(jax.random.randint(jax.random.key(3), (4, 16), 0,
                                      VOCAB), np.int32)
    ids[:, 6] = 5
    batch = (jnp.asarray(ids), jnp.asarray(ids))

    ref = gpt2_model_spec(gcfg).loss_fn(params, batch)

    cfg = _config([2, 2], ["tp", "sp"])
    strat = get_strategy("auto", cfg)
    model = gpt2_model_spec(vp_cfg)
    opt = optax.sgd(0.05)
    p = strat.shard_params(model, jax.tree.map(jnp.copy, params))
    s = strat.init_opt_state(model, opt, p)
    b = strat.shard_batch(batch, model)
    step = strat.make_train_step(model, opt)
    _, _, loss = step(p, s, b)
    np.testing.assert_allclose(float(loss), float(ref), rtol=1e-4)

"""Checkpoint tests: orbax save/restore of sharded train state incl.
resume-latest and cross-mesh restore (capabilities absent from the
reference, whose checkpointing is save-only — SURVEY §5.4)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax

from quintnet_tpu.core.config import Config
from quintnet_tpu.models.vit import ViTConfig, vit_init, vit_model_spec
from quintnet_tpu.parallel.strategy import get_strategy
from quintnet_tpu.train.checkpoint import (
    CheckpointManager,
    load_pytree,
    save_pytree,
)

CFG = ViTConfig(image_size=14, patch_size=7, in_channels=1, hidden_dim=16,
                depth=4, num_heads=2, num_classes=10)


def test_save_pytree_roundtrip(tmp_path):
    tree = {"a": jnp.arange(6.0).reshape(2, 3), "b": {"c": jnp.ones((4,))}}
    p = str(tmp_path / "t.safetensors")
    save_pytree(p, tree)
    out = load_pytree(p, tree)
    for x, y in zip(jax.tree.leaves(tree), jax.tree.leaves(out)):
        np.testing.assert_array_equal(np.asarray(x), y)


def test_orbax_roundtrip_sharded(tmp_path):
    cfg = Config.from_dict({"mesh_dim": [2, 2, 2],
                            "mesh_name": ["dp", "tp", "pp"]})
    strat = get_strategy("auto", cfg)
    model = vit_model_spec(CFG)
    params = strat.shard_params(model, vit_init(jax.random.key(0), CFG))
    opt = optax.adam(1e-3)
    state = strat.init_opt_state(model, opt, params)

    mgr = CheckpointManager(str(tmp_path / "ckpt"), max_to_keep=2)
    mgr.save(0, {"params": params, "opt": state, "step": 0})
    mgr.save(5, {"params": params, "opt": state, "step": 5})
    assert mgr.latest_step() == 5

    template = jax.tree.map(lambda x: x, {"params": params, "opt": state,
                                          "step": 0})
    restored = mgr.restore(template)
    assert int(restored["step"]) == 5
    for a, b in zip(jax.tree.leaves(restored["params"]),
                    jax.tree.leaves(params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # restored arrays keep their sharding
    leaf = restored["params"]["blocks"]["attn"]["qkv"]["w"]
    assert leaf.sharding == params["blocks"]["attn"]["qkv"]["w"].sharding
    mgr.close()


def test_verify_vit_reload_matches_trainer_eval(tmp_path):
    """Train sharded (3D) with checkpointing, then reload single-device
    with NO mesh code (tools/verify_vit.py) and re-compute accuracy —
    the reference's examples/verify_model.py:23-60 acceptance loop. The
    reloaded accuracy must match the trainer's reported val accuracy."""
    from quintnet_tpu.data.datasets import synthetic_mnist
    from quintnet_tpu.data import ArrayDataset, make_batches
    from quintnet_tpu.tools.verify_vit import verify_vit
    from quintnet_tpu.train.trainer import Trainer

    cfg = Config.from_dict({
        "mesh_dim": [2, 2, 2], "mesh_name": ["dp", "tp", "pp"],
        "training": {"batch_size": 32, "gradient_accumulation_steps": 2,
                     "schedule": "1f1b", "optimizer": "adam",
                     "learning_rate": 1e-3, "grad_clip_norm": None,
                     "epochs": 1, "log_every": 0},
    })
    model = vit_model_spec(CFG)
    xtr, ytr = synthetic_mnist(256, seed=0)
    xte, yte = synthetic_mnist(128, seed=1)
    xtr, xte = xtr[:, 7:21, 7:21, :], xte[:, 7:21, 7:21, :]  # 14x14 CFG
    train = ArrayDataset(xtr, ytr)

    ckpt = str(tmp_path / "ckpt")
    trainer = Trainer(cfg, model, task_type="classification",
                      checkpoint_dir=ckpt, log_fn=lambda s: None)
    hist = trainer.fit(
        lambda ep: make_batches(train, 32, seed=ep),
        val_batches_fn=lambda ep: make_batches(
            ArrayDataset(xte, yte), 32, shuffle=False),
    )
    reported = hist.val_metric[-1]

    res = verify_vit(ckpt, CFG, tp=2, data=(xte[:128], yte[:128]),
                     batch_size=32)
    assert res["epoch"] == 0
    assert abs(res["accuracy"] - reported) <= 0.01, (res, reported)


def test_orbax_cross_mesh_restore(tmp_path):
    """Save under 3D sharding, restore replicated on a dp-only mesh — the
    online version of the reference's offline merge_checkpoints.py."""
    cfg3d = Config.from_dict({"mesh_dim": [2, 2, 2],
                              "mesh_name": ["dp", "tp", "pp"]})
    strat = get_strategy("auto", cfg3d)
    model = vit_model_spec(CFG)
    host_params = vit_init(jax.random.key(0), CFG)
    params = strat.shard_params(model, host_params)

    mgr = CheckpointManager(str(tmp_path / "ckpt"))
    mgr.save(1, {"params": params})

    template = {"params": jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), params)}
    restored = mgr.restore(template)["params"]
    # tp=2 sharded save restores to full (host) arrays; contents equal the
    # tp-blocked layout of the original host tree
    from quintnet_tpu.models.vit import vit_to_tp_layout

    expect = vit_to_tp_layout(host_params, CFG, 2)
    for a, b in zip(jax.tree.leaves(restored), jax.tree.leaves(expect)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    mgr.close()

"""Quantized KV pool goldens (serve/kv_quant.py).

The contract ladder:

1. **Identity proof** — an engine on the ``fake_quant`` policy (f32
   storage, all-ones scales, FULL scaled code path: gather -> dequant
   -> insert -> requant -> scatter) is BIT-IDENTICAL to the f32
   engine, across greedy + sampled decoding, prefix-cache sharing,
   speculative decoding, chunked prefill and a tp=2 mesh. This pins
   the restructured kernels as numerically inert, so the int8
   rounding itself is the only quality variable.
2. **int8 quality gates** — the paged-ppl delta (teacher-forced NLL
   through the quantized pool vs the f32 pool) stays under a
   threshold, and the per-block max-abs dequant error respects the
   provable absmax bound (<= scale / 2 per element after a single
   quantization pass).
3. **Operational invariants** — compile counts are UNCHANGED per
   policy (the policy widens the pool operand list inside the SAME
   sentinel set), and the capacity metrics (`bytes_per_block`,
   `pool_bytes`, `kv_pool_bytes`/`kv_bytes_per_token` in
   summary/aggregate) report the ~4x equal-bytes win int8 buys.
4. **fp8 passthrough** — the ``fp8`` rung stores blocks as UNSCALED
   ``float8_e4m3fn`` (narrow on scatter, upcast on gather — no scale
   arrays at all), buying int8's exact 4x byte ratio WITHOUT the
   per-block scale overhead; gated by the same paged-ppl delta, and
   explicitly rejected by the pallas kernel path until a float8 tile
   lands.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from quintnet_tpu.models.gpt2 import GPT2Config, gpt2_init
from quintnet_tpu.serve import (KVLayoutPolicy, KVPool, ServeEngine,
                                SpecConfig, gpt2_family, make_policy)
from quintnet_tpu.serve.kv_quant import (FLOAT8_DTYPE,
                                         dequant_roundtrip_error,
                                         paged_eval_nll)

CFG = GPT2Config.tiny(n_layer=2)

needs_fp8 = pytest.mark.skipif(FLOAT8_DTYPE is None,
                               reason="no float8_e4m3fn in this jax")


@pytest.fixture(scope="module")
def params():
    return gpt2_init(jax.random.key(0), CFG)


def _prompts(rng, lengths):
    return [np.asarray(rng.integers(0, CFG.vocab_size, (t,)), np.int32)
            for t in lengths]


def _engine(params, kv_dtype, **kw):
    kw.setdefault("max_slots", 3)
    kw.setdefault("block_size", 4)
    kw.setdefault("num_blocks", 48)
    kw.setdefault("max_seq_len", 32)
    return ServeEngine(gpt2_family(CFG), params, kv_dtype=kv_dtype, **kw)


def _serve(eng, prompts, max_new, *, arrivals=None, keys=None):
    """Submit with staggered arrivals, run to completion, return
    outputs in submission order."""
    arrivals = arrivals or [0] * len(prompts)
    keys = keys or [jax.random.key(100 + i) for i in range(len(prompts))]
    rids = {}
    submitted, step = 0, 0
    while submitted < len(prompts) or eng.has_work:
        while (submitted < len(prompts)
               and arrivals[submitted] <= step):
            rids[submitted] = eng.submit(prompts[submitted], max_new,
                                         key=keys[submitted])
            submitted += 1
        eng.step()
        step += 1
        assert step < 1000, "engine failed to drain"
    return [eng.result(rids[i]) for i in range(len(prompts))]


# ---------------------------------------------------------------------
# policy object + capacity math
# ---------------------------------------------------------------------

class TestPolicy:
    def test_resolution(self):
        assert make_policy(None).name == "f32"
        assert make_policy("int8").name == "int8"
        assert make_policy(jnp.float32).name == "f32"
        assert make_policy(jnp.bfloat16).name == "bf16"
        p = make_policy("fake_quant")
        assert make_policy(p) is p
        with pytest.raises(ValueError, match="unknown kv_dtype"):
            make_policy("int4")
        with pytest.raises(ValueError, match="no passthrough policy"):
            make_policy(jnp.int8)  # raw int8 needs the scaled policy

    def test_ladder_pinned_in_specs(self):
        from quintnet_tpu.analysis.specs import kv_layout_policies
        from quintnet_tpu.serve.kv_quant import policy_names

        assert policy_names() == kv_layout_policies()

    def test_scaled_flags(self):
        assert not make_policy("f32").scaled
        assert not make_policy("bf16").scaled
        assert make_policy("int8").scaled
        assert make_policy("fake_quant").scaled
        assert isinstance(make_policy("int8"), KVLayoutPolicy)

    @needs_fp8
    def test_fp8_resolution_and_capacity(self):
        """fp8 is UNSCALED passthrough: raw float8 dtype resolves to
        the policy, no scale arrays, and a block costs exactly 1/4 of
        f32's bytes (int8's data shrink without its scale tax)."""
        pol = make_policy("fp8")
        assert pol.name == "fp8" and not pol.scaled
        assert make_policy(FLOAT8_DTYPE) is pol
        kw = dict(n_layers=2, n_kv_heads=4, head_dim=8, block_size=16)
        f32 = make_policy("f32").bytes_per_block(**kw)
        fp8 = pol.bytes_per_block(**kw)
        assert fp8 * 4 == f32
        assert fp8 < make_policy("int8").bytes_per_block(**kw)
        pool = KVPool(n_layers=2, n_kv_heads=2, head_dim=4,
                      block_size=4, num_blocks=8, policy="fp8")
        assert len(pool.caches()) == 2     # passthrough: no scales
        assert pool.k.dtype == jnp.dtype(FLOAT8_DTYPE)

    def test_bytes_per_block_capacity_math(self):
        kw = dict(n_layers=2, n_kv_heads=4, head_dim=8, block_size=16)
        f32 = make_policy("f32").bytes_per_block(**kw)
        int8 = make_policy("int8").bytes_per_block(**kw)
        # k+v slot data: 2 * L * bs * H * Dh * itemsize
        assert f32 == 2 * 2 * 16 * 4 * 8 * 4
        # int8 adds 2 * L * H f32 scales per block
        assert int8 == 2 * 2 * 16 * 4 * 8 * 1 + 2 * 2 * 4 * 4
        # THE capacity claim: equal pool bytes hold >= 1.8x the blocks
        assert f32 / int8 >= 1.8

    def test_pool_exposes_policy_aware_bytes(self):
        def pool(policy):
            return KVPool(n_layers=2, n_kv_heads=2, head_dim=4,
                          block_size=4, num_blocks=8, policy=policy)

        p32, p8 = pool("f32"), pool("int8")
        assert p32.pool_bytes == 8 * p32.bytes_per_block
        assert p32.bytes_per_token == p32.bytes_per_block / 4
        assert p8.bytes_per_block < p32.bytes_per_block
        # scaled pools carry 4 device buffers, passthrough 2
        assert len(p8.caches()) == 4
        assert len(p32.caches()) == 2
        with pytest.raises(ValueError, match="scale arrays"):
            p8.update(p8.k, p8.v)

    def test_dequant_roundtrip_error_bound(self, rng):
        # [blocks, heads, slots, dh] — per-block-per-head scales
        x = rng.normal(size=(6, 4, 16, 8)).astype(np.float32)
        err, sc = dequant_roundtrip_error(make_policy("int8"), x,
                                          axes=(-2, -1))
        assert err.shape == sc.shape == (6, 4)
        # the provable absmax bound: <= scale / 2 per element
        assert np.all(np.asarray(err) <= np.asarray(sc) * 0.5 + 1e-6)
        assert np.asarray(err).max() > 0  # rounding really happened
        # identity policy: exactly zero error, scales exactly one
        err0, sc0 = dequant_roundtrip_error(make_policy("fake_quant"), x,
                                            axes=(-2, -1))
        assert np.all(np.asarray(err0) == 0.0)
        assert np.all(np.asarray(sc0) == 1.0)

    def test_quant_storage_dtype(self, rng):
        pol = make_policy("int8")
        x = jnp.asarray(rng.normal(size=(4, 8)).astype(np.float32))
        sc = pol.compute_scale(x, axes=(1,))
        q = pol.quant(x, sc[:, None])
        assert q.dtype == jnp.int8
        assert pol.dequant(q, sc[:, None]).dtype == jnp.float32


# ---------------------------------------------------------------------
# the identity golden matrix: fake_quant == f32, bit for bit
# ---------------------------------------------------------------------

class TestFakeQuantIdentity:
    def _match(self, params, rng, *, kw_a=None, kw_b=None, lengths=(5, 9, 3),
               max_new=6, arrivals=None):
        kw_a = kw_a or {}
        prompts = _prompts(rng, lengths)
        keys = [jax.random.key(70 + i) for i in range(len(prompts))]
        out32 = _serve(_engine(params, "f32", **kw_a), prompts, max_new,
                       arrivals=arrivals, keys=keys)
        outfk = _serve(_engine(params, "fake_quant", **(kw_b or kw_a)),
                       prompts, max_new, arrivals=arrivals, keys=keys)
        for a, b in zip(out32, outfk):
            np.testing.assert_array_equal(a, b)
        return out32

    def test_greedy(self, params, rng):
        self._match(params, rng)

    def test_sampled(self, params, rng):
        self._match(params, rng,
                    kw_a=dict(temperature=0.9, top_k=7))

    def test_prefix_cache_with_reuse(self, params, rng):
        """Shared-prefix prompts in two waves: the second wave hits the
        published chain (COW + scale copy on the scaled side)."""
        shared = np.asarray(rng.integers(0, CFG.vocab_size, (10,)),
                            np.int32)
        tails = [np.asarray(rng.integers(0, CFG.vocab_size, (t,)),
                            np.int32) for t in (3, 5, 2, 4)]
        prompts = [np.concatenate([shared, t]) for t in tails]
        keys = [jax.random.key(200 + i) for i in range(4)]
        outs = {}
        for name in ("f32", "fake_quant"):
            eng = _engine(params, name, max_slots=2)
            outs[name] = _serve(eng, prompts, 5,
                                arrivals=[0, 0, 6, 6], keys=keys)
            assert eng.metrics.prefix_hit_tokens > 0  # cache really hit
        for a, b in zip(outs["f32"], outs["fake_quant"]):
            np.testing.assert_array_equal(a, b)

    def test_speculative_sampled(self, params, rng):
        self._match(params, rng,
                    kw_a=dict(spec=SpecConfig(), temperature=0.7),
                    max_new=8)

    def test_chunked_prefill(self, params, rng):
        self._match(params, rng,
                    kw_a=dict(chunked_prefill=True, prefill_len=8,
                              prefill_chunk_budget=4),
                    lengths=(5, 14, 3))

    def test_tp2(self, params, rng):
        from quintnet_tpu.core.mesh import mesh_from_sizes
        from quintnet_tpu.models.gpt2 import gpt2_to_tp_layout

        prompts = _prompts(rng, (5, 9, 3))
        keys = [jax.random.key(50 + i) for i in range(3)]
        out32 = _serve(_engine(params, "f32"), prompts, 6, keys=keys)
        mesh = mesh_from_sizes(tp=2)
        tp_params = gpt2_to_tp_layout(params, CFG, 2)
        outfk = _serve(_engine(tp_params, "fake_quant", mesh=mesh),
                       prompts, 6, keys=keys)
        for a, b in zip(out32, outfk):
            np.testing.assert_array_equal(a, b)

    def test_llama_family(self, rng):
        from quintnet_tpu.models.llama import LlamaConfig, llama_init
        from quintnet_tpu.serve import llama_family

        cfg = LlamaConfig.tiny(n_layers=2)
        lparams = llama_init(jax.random.key(1), cfg)
        prompts = [np.asarray(rng.integers(0, cfg.vocab_size, (t,)),
                   np.int32) for t in (4, 7)]
        keys = [jax.random.key(300 + i) for i in range(2)]
        outs = {}
        for name in ("f32", "fake_quant"):
            eng = ServeEngine(llama_family(cfg), lparams, max_slots=2,
                              block_size=4, num_blocks=32,
                              max_seq_len=24, kv_dtype=name)
            outs[name] = _serve(eng, prompts, 5, keys=keys)
        for a, b in zip(outs["f32"], outs["fake_quant"]):
            np.testing.assert_array_equal(a, b)


# ---------------------------------------------------------------------
# int8 quality gates
# ---------------------------------------------------------------------

class TestInt8Quality:
    def _pool(self, kv_dtype, num_blocks=32):
        return KVPool(n_layers=CFG.n_layer, n_kv_heads=CFG.n_head,
                      head_dim=CFG.n_embd // CFG.n_head, block_size=4,
                      num_blocks=num_blocks, policy=kv_dtype)

    def test_paged_ppl_delta_gate(self, params, rng):
        """Teacher-forced NLL THROUGH the paged pool: the int8 engine's
        quality loss vs the f32 pool stays under the gate (and the
        fake-quant policy's is exactly zero)."""
        fam = gpt2_family(CFG)
        rows = rng.integers(0, CFG.vocab_size, (4, 24)).astype(np.int32)
        nll = {name: paged_eval_nll(fam, params, self._pool(name), rows)
               for name in ("f32", "fake_quant", "int8")}
        assert nll["fake_quant"] == nll["f32"]  # the identity, again
        assert abs(nll["int8"] - nll["f32"]) < 0.05, (
            f"int8 paged ppl delta too large: "
            f"{nll['int8']:.4f} vs {nll['f32']:.4f}")

    def test_per_block_dequant_error_bounded(self, params, rng):
        """Serve the SAME single prompt through an f32 and an int8
        engine (identical deterministic block allocation) and check
        every written block's dequantized content against the f32
        truth: after the single prefill quantization pass the max-abs
        error per block-head is <= scale / 2."""
        prompt = np.asarray(rng.integers(0, CFG.vocab_size, (14,)),
                            np.int32)
        pools = {}
        for name in ("f32", "int8"):
            eng = _engine(params, name, max_slots=1, num_blocks=16)
            _serve(eng, [prompt], 1, keys=[jax.random.key(7)])
            pools[name] = eng.pool
        p32, p8 = pools["f32"], pools["int8"]
        bs = p8.block_size
        nb = p8.num_blocks
        for ref, q, sc in ((p32.k, p8.k, p8.k_scale),
                           (p32.v, p8.v, p8.v_scale)):
            # [L, nb, bs, H, Dh] block views; scales [L, nb, H]
            refb = np.asarray(ref).reshape(CFG.n_layer, nb, bs,
                                           CFG.n_head, -1)
            dq = (np.asarray(q, np.float32).reshape(refb.shape)
                  * np.asarray(sc)[:, :, None, :, None])
            err = np.abs(dq - refb).max(axis=(2, 4))      # [L, nb, H]
            bound = np.asarray(sc) * 0.5 + 1e-5
            written = np.abs(refb).max(axis=(2, 4)) > 0
            # block 0 is the reserved NULL block — scratch memory the
            # two layouts use differently (f32 scatters pad columns
            # into it, the scaled path zero-fills it); nobody reads it
            written[:, 0, :] = False
            assert np.all(err[written] <= bound[written]), (
                f"per-block dequant error exceeds scale/2: "
                f"max excess {(err - bound)[written].max()}")
            assert written.any()  # the comparison saw real blocks

    def test_recycled_block_scale_not_inflated(self):
        """A freed block's stale bytes (a previous owner's large
        values, still in storage under their old scale — the allocator
        never scrubs) must NOT leak into the absmax when the block is
        recycled: the requant masks slots beyond the new owner's last
        written position, so the fresh scale reflects only real
        tokens. Without the mask a 50-absmax ghost coarsens a
        0.5-absmax newcomer's quantization ~100x."""
        from quintnet_tpu.nn.attention import (paged_gather_dequant,
                                               paged_quant_update)

        policy = make_policy("int8")
        bs, H, Dh, nb = 4, 2, 4, 3
        cache = jnp.zeros((nb * bs, H, Dh), jnp.int8)
        scales = jnp.ones((nb, H), jnp.float32)
        table = jnp.asarray([[1, 0]], jnp.int32)
        # first owner fills pool block 1 with large values
        row = paged_gather_dequant(policy, cache, scales, table,
                                   block_size=bs)
        cache, scales, _ = paged_quant_update(
            policy, cache, scales, row, jnp.full((1, H, bs, Dh), 50.0),
            jnp.arange(bs, dtype=jnp.int32)[None, :],
            jnp.asarray([bs], jnp.int32),
            block_tables=table, block_size=bs, max_blocks=2)
        assert float(scales[1].max()) > 0.3          # ~50/127
        # block 1 recycled: new owner writes ONE small token at pos 0
        row2 = paged_gather_dequant(policy, cache, scales, table,
                                    block_size=bs)
        cache, scales, view = paged_quant_update(
            policy, cache, scales, row2, jnp.full((1, H, 1, Dh), 0.5),
            jnp.zeros((1, 1), jnp.int32), jnp.asarray([1], jnp.int32),
            block_tables=table, block_size=bs, max_blocks=1)
        sc = np.asarray(scales[1])
        assert np.all(sc <= 0.5 / 127 + 1e-6), (
            f"stale bytes inflated the recycled block's scale: {sc}")
        got = np.asarray(policy.dequant(
            cache.reshape(nb, bs, H, Dh)[1, 0], sc[:, None]))
        assert np.all(np.abs(got - 0.5) <= sc.max() * 0.5 + 1e-6)

    def test_int8_serves_and_compile_bound_holds(self, params, rng):
        """Mixed staggered trace on int8: everything finishes, with
        preemption pressure, and the compile counts are exactly the
        f32 engine's — one prefill total, one decode (the policy is
        not a program)."""
        prompts = _prompts(rng, (3, 5, 4, 6, 3))
        eng = _engine(params, "int8", max_slots=3, block_size=2,
                      num_blocks=12, max_seq_len=16)
        outs = _serve(eng, prompts, 5, arrivals=[0, 1, 2, 5, 8])
        assert all(len(o) == len(p) + 5
                   for o, p in zip(outs, prompts))
        assert eng.metrics.finished == len(prompts)
        assert eng.compile_stats() == {"prefill": 1, "decode": 1}
        eng.assert_compile_count()

    def test_int8_spec_compile_bound(self, params, rng):
        eng = _engine(params, "int8", spec=SpecConfig())
        prompts = _prompts(rng, (6, 6))
        _serve(eng, prompts, 8)
        stats = eng.compile_stats()
        assert stats["prefill"] == 1 and stats["decode"] == 1
        assert stats["verify"] <= len(eng.spec.buckets)
        eng.assert_compile_count()

    @needs_fp8
    def test_fp8_ppl_delta_gate(self, params, rng):
        """The unscaled fp8 pool passes the same serving quality gate
        the int8 pool does."""
        fam = gpt2_family(CFG)
        rows = rng.integers(0, CFG.vocab_size, (4, 24)).astype(np.int32)
        nll32 = paged_eval_nll(fam, params, self._pool("f32"), rows)
        nll8 = paged_eval_nll(fam, params, self._pool("fp8"), rows)
        assert abs(nll8 - nll32) < 0.05, (
            f"fp8 paged ppl delta too large: {nll8:.4f} vs {nll32:.4f}")

    @needs_fp8
    def test_fp8_serves_and_compile_bound_holds(self, params, rng):
        """Mixed staggered trace on the fp8 pool: everything finishes
        and the compile counts are exactly the f32 engine's."""
        prompts = _prompts(rng, (3, 5, 4))
        eng = _engine(params, "fp8")
        outs = _serve(eng, prompts, 5, arrivals=[0, 1, 2])
        assert all(len(o) == len(p) + 5
                   for o, p in zip(outs, prompts))
        assert eng.compile_stats() == {"prefill": 1, "decode": 1}
        eng.assert_compile_count()

    @needs_fp8
    def test_fp8_pallas_rejected(self, params):
        """The fused pallas kernels have no float8 tile yet — the
        combination must fail loudly at build, not mis-serve."""
        with pytest.raises(NotImplementedError, match="fp8"):
            _engine(params, "fp8", attn_kernel="pallas")


# ---------------------------------------------------------------------
# capacity metrics surface
# ---------------------------------------------------------------------

class TestCapacityMetrics:
    def test_summary_surfaces_pool_bytes(self, params, rng):
        eng = _engine(params, "int8")
        _serve(eng, _prompts(rng, (4,)), 3)
        s = eng.metrics.summary()
        assert s["kv_pool_bytes"] == eng.pool.pool_bytes > 0
        assert s["kv_bytes_per_token"] == pytest.approx(
            eng.pool.bytes_per_token)

    def test_aggregate_inherits_capacity(self, params, rng):
        """fleet.engine_summary goes through metrics.aggregate: pool
        bytes SUM across replicas, bytes/token reports the heaviest."""
        from quintnet_tpu.serve.metrics import aggregate

        engines = [_engine(params, d) for d in ("f32", "int8")]
        for eng in engines:
            _serve(eng, _prompts(rng, (4,)), 3)
        agg = aggregate([e.metrics for e in engines])
        assert agg["kv_pool_bytes"] == sum(e.pool.pool_bytes
                                           for e in engines)
        assert agg["kv_bytes_per_token"] == pytest.approx(
            max(e.pool.bytes_per_token for e in engines))

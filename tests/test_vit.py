"""ViT model tests: shapes, determinism, and a short single-device training
run that must reduce loss (the reference's acceptance style: convergence
behavior, README.md:199-216)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from quintnet_tpu.models.vit import (
    ViTConfig,
    accuracy,
    cross_entropy_loss,
    vit_apply,
    vit_init,
)

CFG = ViTConfig(image_size=28, patch_size=7, in_channels=1, hidden_dim=32,
                depth=2, num_heads=4, num_classes=10)


def test_init_shapes():
    params = vit_init(jax.random.key(0), CFG)
    assert params["embedding"]["patch"]["w"].shape == (49, 32)
    assert params["embedding"]["pos"].shape == (1, 17, 32)
    # blocks stacked along depth
    assert params["blocks"]["attn"]["qkv"]["w"].shape == (2, 32, 96)
    assert params["head"]["fc"]["w"].shape == (32, 10)


def test_forward_shape_and_nchw_autodetect():
    params = vit_init(jax.random.key(0), CFG)
    x_nhwc = jnp.ones((4, 28, 28, 1))
    x_nchw = jnp.ones((4, 1, 28, 28))
    out1 = vit_apply(params, x_nhwc, CFG)
    out2 = vit_apply(params, x_nchw, CFG)
    assert out1.shape == (4, 10)
    np.testing.assert_allclose(out1, out2, rtol=1e-6)


def test_forward_deterministic():
    params = vit_init(jax.random.key(0), CFG)
    x = jax.random.normal(jax.random.key(1), (2, 28, 28, 1))
    np.testing.assert_array_equal(vit_apply(params, x, CFG),
                                  vit_apply(params, x, CFG))


def test_remat_matches_no_remat():
    params = vit_init(jax.random.key(0), CFG)
    x = jax.random.normal(jax.random.key(1), (2, 28, 28, 1))
    y = jax.random.randint(jax.random.key(2), (2,), 0, 10)

    def loss(p, remat):
        return cross_entropy_loss(vit_apply(p, x, CFG, remat=remat), y)

    g1 = jax.grad(lambda p: loss(p, False))(params)
    g2 = jax.grad(lambda p: loss(p, True))(params)
    for a, b in zip(jax.tree.leaves(g1), jax.tree.leaves(g2)):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)


def test_single_device_training_reduces_loss():
    key = jax.random.key(0)
    params = vit_init(key, CFG)
    x = jax.random.normal(jax.random.key(1), (32, 28, 28, 1))
    y = jax.random.randint(jax.random.key(2), (32,), 0, 10)

    opt = optax.adam(1e-3)
    state = opt.init(params)

    @jax.jit
    def step(p, s):
        def loss_fn(p_):
            return cross_entropy_loss(vit_apply(p_, x, CFG), y)

        loss, g = jax.value_and_grad(loss_fn)(p)
        updates, s = opt.update(g, s, p)
        return optax.apply_updates(p, updates), s, loss

    losses = []
    for _ in range(30):
        params, state, loss = step(params, state)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.5, losses
    logits = vit_apply(params, x, CFG)
    assert float(accuracy(logits, y)) > 0.5

"""KV-cache generation goldens.

The cached decode path (models/gpt2_generate.py) must reproduce the
full-forward greedy loop (train/metrics.py greedy_generate — the
reference's strategy, utils/metrics.py:74-149) token for token.
"""

import jax
import jax.numpy as jnp
import numpy as np

from quintnet_tpu.models.gpt2 import GPT2Config, gpt2_apply, gpt2_init
from quintnet_tpu.models.gpt2_generate import (
    gpt2_decode_step,
    gpt2_generate,
    gpt2_prefill,
)
CFG = GPT2Config.tiny(n_layer=2)


def greedy_generate(apply_fn, params, input_ids, *, max_new_tokens,
                    eos_token_id=None):
    """Test-only golden oracle: full forward per token (the reference's
    generation strategy, utils/metrics.py:74-149). O(T^2)/token — kept
    here purely to check the KV-cache decoder against independent math."""
    ids = jnp.asarray(input_ids)

    @jax.jit
    def next_token(p, cur):
        logits = apply_fn(p, cur)
        return jnp.argmax(logits[:, -1, :], axis=-1)

    done = np.zeros((ids.shape[0],), bool)
    for _ in range(max_new_tokens):
        nxt = np.asarray(next_token(params, ids))
        if eos_token_id is not None:
            nxt = np.where(done, eos_token_id, nxt)
            done |= nxt == eos_token_id
        ids = jnp.concatenate([ids, jnp.asarray(nxt)[:, None]], axis=1)
        if eos_token_id is not None and done.all():
            break
    return np.asarray(ids)


def _params():
    return gpt2_init(jax.random.key(0), CFG)


def _prompt(rng, b=2, t=8):
    return np.asarray(rng.integers(0, CFG.vocab_size, (b, t)), np.int32)


def test_prefill_logits_match_full_forward(rng):
    params = _params()
    ids = _prompt(rng)
    full = gpt2_apply(params, jnp.asarray(ids), CFG)[:, -1, :]
    pre, _ = gpt2_prefill(params, jnp.asarray(ids), CFG, cache_len=16)
    np.testing.assert_allclose(np.asarray(pre), np.asarray(full),
                               rtol=1e-5, atol=1e-5)


def test_decode_step_matches_full_forward(rng):
    """Logits for position T under cached decode == full forward over
    [B, T+1]."""
    params = _params()
    ids = _prompt(rng, t=8)
    nxt = np.asarray(rng.integers(0, CFG.vocab_size, (2,)), np.int32)

    _, caches = gpt2_prefill(params, jnp.asarray(ids), CFG, cache_len=16)
    dec, _ = gpt2_decode_step(params, jnp.asarray(nxt), jnp.int32(8),
                              caches, CFG)

    full_ids = np.concatenate([ids, nxt[:, None]], axis=1)
    full = gpt2_apply(params, jnp.asarray(full_ids), CFG)[:, -1, :]
    np.testing.assert_allclose(np.asarray(dec), np.asarray(full),
                               rtol=1e-5, atol=1e-5)


def test_cached_generate_matches_full_forward_greedy(rng):
    params = _params()
    ids = _prompt(rng)

    ref = greedy_generate(
        lambda p, cur: gpt2_apply(p, cur, CFG), params, ids,
        max_new_tokens=12)
    out = gpt2_generate(params, ids, CFG, max_new_tokens=12)
    np.testing.assert_array_equal(out, ref)


def test_generate_eos_padding(rng):
    """Rows that hit EOS keep emitting EOS (reference early-exit
    semantics with static shapes)."""
    params = _params()
    ids = _prompt(rng)
    out = gpt2_generate(params, ids, CFG, max_new_tokens=8,
                        eos_token_id=0)
    new = out[:, ids.shape[1]:]
    for row in new:
        hits = np.where(row == 0)[0]
        if hits.size:
            assert (row[hits[0]:] == 0).all()


def test_generate_moe_smoke(rng):
    # ample capacity: capacity DROPS are not causally consistent between
    # full-forward and per-step decode (later tokens change earlier
    # tokens' drop fate in the full forward — inherent to capacity MoE)
    cfg = GPT2Config.tiny(n_layer=2, n_experts=4, expert_capacity=4096)
    params = gpt2_init(jax.random.key(0), cfg)
    ids = _prompt(rng)
    ref = greedy_generate(
        lambda p, cur: gpt2_apply(p, cur, cfg), params, ids,
        max_new_tokens=6)
    out = gpt2_generate(params, ids, cfg, max_new_tokens=6)
    np.testing.assert_array_equal(out, ref)


def test_generate_sampling_runs(rng):
    params = _params()
    ids = _prompt(rng)
    out = gpt2_generate(params, ids, CFG, max_new_tokens=5,
                        temperature=1.0, key=jax.random.key(7))
    assert out.shape == (2, ids.shape[1] + 5)
    assert (out[:, :ids.shape[1]] == ids).all()


class TestTPGenerate:
    """TP-sharded decode goldens: tp=2 generation == single-device
    generation, token for token (the reference skips generation under
    any parallelism, GPT2_Trainer.py:509-555)."""

    def _mesh(self):
        from quintnet_tpu.core.mesh import mesh_from_sizes

        return mesh_from_sizes(tp=2)

    def test_tp2_matches_single_device(self, rng):
        from quintnet_tpu.models.gpt2 import gpt2_to_tp_layout
        from quintnet_tpu.models.gpt2_generate import gpt2_generate_tp

        params = _params()
        ids = _prompt(rng)
        ref = gpt2_generate(params, ids, CFG, max_new_tokens=10,
                            eos_token_id=0)
        tp_params = gpt2_to_tp_layout(params, CFG, 2)
        out = gpt2_generate_tp(tp_params, ids, CFG, mesh=self._mesh(),
                               max_new_tokens=10, eos_token_id=0)
        np.testing.assert_array_equal(out, ref)

    def test_tp2_vocab_parallel_matches_single_device(self, rng):
        """Vocab-parallel decode: sharded wte lookup (psum) + vocab
        all-gather on the logits; padded columns never win argmax."""
        from quintnet_tpu.models.gpt2 import gpt2_to_tp_layout
        from quintnet_tpu.models.gpt2_generate import gpt2_generate_tp

        cfg = GPT2Config.tiny(n_layer=2, vocab_parallel=True,
                              padded_vocab_size=260)
        params = gpt2_init(jax.random.key(0), cfg)
        ids = np.asarray(rng.integers(0, cfg.vocab_size, (2, 8)), np.int32)
        ref = gpt2_generate(params, ids, cfg, max_new_tokens=8)
        assert (ref < cfg.vocab_size).all()
        tp_params = gpt2_to_tp_layout(params, cfg, 2)
        out = gpt2_generate_tp(tp_params, ids, cfg, mesh=self._mesh(),
                               max_new_tokens=8)
        np.testing.assert_array_equal(out, ref)
        assert (out < cfg.vocab_size).all()

    def test_tp2_sampling_deterministic_across_ranks(self, rng):
        """Temperature sampling under tp must stay rank-consistent (same
        key everywhere) and reproducible."""
        from quintnet_tpu.models.gpt2 import gpt2_to_tp_layout
        from quintnet_tpu.models.gpt2_generate import gpt2_generate_tp

        params = gpt2_to_tp_layout(_params(), CFG, 2)
        ids = _prompt(rng)
        a = gpt2_generate_tp(params, ids, CFG, mesh=self._mesh(),
                             max_new_tokens=6, temperature=1.0,
                             key=jax.random.key(3))
        b = gpt2_generate_tp(params, ids, CFG, mesh=self._mesh(),
                             max_new_tokens=6, temperature=1.0,
                             key=jax.random.key(3))
        np.testing.assert_array_equal(a, b)
        assert (a[:, :ids.shape[1]] == ids).all()


def test_evaluate_generation_tp_mesh(rng):
    """evaluate_generation(mesh=...) routes through the tp-sharded
    decoder with params in training layout."""
    from quintnet_tpu.core.mesh import mesh_from_sizes
    from quintnet_tpu.data.datasets import ByteTokenizer, SummarizationDataset
    from quintnet_tpu.models.gpt2 import gpt2_to_tp_layout
    from quintnet_tpu.train.metrics import evaluate_generation

    tok = ByteTokenizer()
    cfg = GPT2Config.tiny(n_layer=2, vocab_size=264)
    params = gpt2_to_tp_layout(gpt2_init(jax.random.key(0), cfg), cfg, 2)
    ds = SummarizationDataset.synthetic(4, tok, max_length=48)
    prompts = ds.eval_prompts(max_prompt_len=16, limit=4)
    scores = evaluate_generation(params, cfg, prompts, tok,
                                 max_new_tokens=6, batch_size=4,
                                 mesh=mesh_from_sizes(tp=2))
    assert set(scores) == {"rouge1", "rouge2", "rougeL", "bleu"}


def test_evaluate_generation_pipeline(rng):
    """Dataset eval_prompts -> KV-cache generate -> ROUGE/BLEU wiring
    (reference evaluate_generation, utils/metrics.py:152-206)."""
    from quintnet_tpu.data.datasets import ByteTokenizer, SummarizationDataset
    from quintnet_tpu.train.metrics import evaluate_generation

    tok = ByteTokenizer()
    cfg = GPT2Config.tiny(n_layer=2, vocab_size=264)
    params = gpt2_init(jax.random.key(0), cfg)
    ds = SummarizationDataset.synthetic(6, tok, max_length=48)
    prompts = ds.eval_prompts(max_prompt_len=24, limit=6)
    assert len(prompts) == 6
    assert all(len(p) % 8 == 0 or len(p) < 8 for p, _ in prompts)

    scores = evaluate_generation(params, cfg, prompts, tok,
                                 max_new_tokens=8, batch_size=4)
    assert set(scores) == {"rouge1", "rouge2", "rougeL", "bleu"}
    assert all(0.0 <= v <= 1.0 for v in scores.values())

"""utils/profiling.py units: StepTimer must degrade cleanly."""

import warnings

import pytest

from quintnet_tpu.utils.profiling import StepTimer


@pytest.mark.fast
def test_steptimer_zero_steps_is_zeroed_not_nan():
    """A timer that never recorded a step (a run that died before its
    first stop(), an idle serving replica) reports a zeroed summary —
    no NaNs, no NumPy empty-reduction RuntimeWarning."""
    t = StepTimer()
    with warnings.catch_warnings():
        warnings.simplefilter("error")   # any warning -> test failure
        s = t.summary()
    assert s == {"steps": 0, "mean_s": 0.0, "p50_s": 0.0, "p99_s": 0.0}


@pytest.mark.fast
def test_steptimer_single_step_summary():
    """One recorded step: the compile-step drop falls back to using it
    (times[1:] is empty), and the numbers are finite."""
    t = StepTimer()
    t.start()
    t.stop()
    s = t.summary()
    assert s["steps"] == 1
    assert s["mean_s"] >= 0.0 and s["p50_s"] >= 0.0 and s["p99_s"] >= 0.0
    assert s["mean_s"] == s["mean_s"]    # not NaN

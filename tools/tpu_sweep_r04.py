#!/usr/bin/env python
"""Round-4 TPU measurement driver (VERDICT r03 items 1-3).

Runs bench.py across the requested grid on the real chip and writes:
  artifacts/sweep_r04.json  — bs {8,16,32} x remat {0,1} x seq {512,1024}
  artifacts/flash_r04.json  — flash-attn vs sdpa at seq {2048,4096,8192}
                              plus a block-size mini-sweep at 8192
  artifacts/trace_r04/      — jax.profiler trace of the default config

Each entry is bench.py's own JSON line plus the argv that produced it.
Run from the repo root when the TPU tunnel is up:  python tools/tpu_sweep_r04.py
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_bench(argv, timeout=1200):
    cmd = [sys.executable, os.path.join(REPO, "bench.py")] + argv
    print("::", " ".join(argv), flush=True)
    r = subprocess.run(cmd, capture_output=True, text=True, cwd=REPO,
                       timeout=timeout)
    line = r.stdout.strip().splitlines()[-1] if r.stdout.strip() else "{}"
    try:
        d = json.loads(line)
    except json.JSONDecodeError:
        d = {"error": "unparseable", "stdout": r.stdout[-300:],
             "stderr": r.stderr[-300:]}
    d["argv"] = argv
    d["rc"] = r.returncode
    print("  ->", json.dumps({k: d.get(k) for k in
                              ("metric", "value", "vs_baseline", "error")}),
          flush=True)
    return d


def main():
    os.makedirs(os.path.join(REPO, "artifacts"), exist_ok=True)

    # 1. throughput sweep (VERDICT item 2)
    sweep = []
    for seq in (512, 1024):
        for bs in (8, 16, 32):
            for remat in (1, 0):
                sweep.append(run_bench([
                    "--batch", str(bs), "--seq", str(seq),
                    "--remat", str(remat), "--steps", "20"]))
                with open(os.path.join(REPO, "artifacts/sweep_r04.json"),
                          "w") as f:
                    json.dump(sweep, f, indent=1)

    # 2. flash kernel (VERDICT item 3)
    flash = []
    for seq in (2048, 4096, 8192):
        flash.append(run_bench(["--model", "flash-attn", "--seq", str(seq),
                                "--steps", "30"]))
        with open(os.path.join(REPO, "artifacts/flash_r04.json"), "w") as f:
            json.dump(flash, f, indent=1)
    for bq, bk in ((256, 256), (256, 512), (512, 512), (128, 512)):
        flash.append(run_bench(["--model", "flash-attn", "--seq", "8192",
                                "--block-q", str(bq), "--block-k", str(bk),
                                "--steps", "30"]))
        with open(os.path.join(REPO, "artifacts/flash_r04.json"), "w") as f:
            json.dump(flash, f, indent=1)

    # 3. profiler trace of the best default (VERDICT items 1-2)
    run_bench(["--steps", "10",
               "--trace", os.path.join(REPO, "artifacts/trace_r04")])

    print("sweep done; artifacts written", flush=True)


if __name__ == "__main__":
    main()

"""Fleet benchmark: replay a bursty request trace against a
multi-replica serving fleet (quintnet_tpu/fleet/) once per routing
policy, with a mid-trace replica kill and an over-capacity burst, and
report one JSON line per policy:

  {"metric": "fleet_gpt2_tiny_tokens_per_sec", "value": N,
   "unit": "tok/s", "rc": 0, "extras": {"policy": "least_work",
   "ttft_p50_s": .., "ttft_p99_s": .., "shed_rate": ..,
   "migrations": .., ...}}

The trace front-loads ``--burst`` requests in one instantaneous spike
(what sheds: the fleet absorbs queue + dispatch windows and REJECTS
the rest with a typed Overloaded — the queue never grows past
``--max-pending``), then Poisson arrivals (inter-arrival ~
Exp(rate) seconds) for the remainder. ``--kill-at-step K`` arms an
``ft.ChaosMonkey`` (mode='raise') against ``--kill-replica`` AFTER
warmup, so the victim dies at its K-th replay step and its in-flight
requests migrate — finished counts include them, token-identical
(tests/test_fleet.py holds the identity; here we count).

``--process`` replays the SAME trace through the cross-process fleet
(quintnet_tpu/fleet/proc.py): each replica is its own spawned OS
process behind the wire protocol, the armed kill is a mode='hard'
``os._exit`` — the process vanishes mid-run with no cleanup, the
SIGKILL story — and the dispatcher's write-ahead journal migrates the
victim's in-flight requests to survivors (finished == accepted).
Reported tokens come from the dispatcher's journal
(``tokens_delivered``), which survives replica deaths; the metric name
gains a ``proc`` tag so the thread and process records never alias.

Modes:
  python tools/fleet_bench.py --synthetic                # tiny, CPU-ok
  python tools/fleet_bench.py --synthetic --requests 6 \
      --policies least_work                              # CI smoke
  python tools/fleet_bench.py --synthetic --out artifacts/fleet_r08.json
  python tools/fleet_bench.py --synthetic --process \
      --out artifacts/fleet_r12.json                     # process fleet

``--out FILE`` appends the records to an artifacts JSON list
(bench.last_known_result scans them — same staleness story as the
serve/train benches).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def model_setup(model: str, synthetic: bool, seed: int):
    """THE single source of the benched model: (family, params). Both
    modes — the thread factory and the process children, each in their
    own interpreter — construct the model HERE from the same seed, so
    they cannot drift apart and every replica holds identical
    (family, params), the migration-contract precondition."""
    import jax

    from quintnet_tpu.serve import gpt2_family, llama_family

    if model == "gpt2":
        from quintnet_tpu.models.gpt2 import GPT2Config, gpt2_init

        cfg = GPT2Config.tiny(n_layer=2) if synthetic else GPT2Config.base()
        return gpt2_family(cfg), gpt2_init(jax.random.key(seed), cfg)
    if model == "llama":
        from quintnet_tpu.models.llama import LlamaConfig, llama_init

        cfg = (LlamaConfig.tiny(n_layers=2) if synthetic
               else LlamaConfig())
        return llama_family(cfg), llama_init(jax.random.key(seed), cfg)
    raise SystemExit(f"unknown --model {model}")


def build_engine(*, model="gpt2", synthetic=True, seed=0, slots=2,
                 block_size=16, num_blocks=64, max_seq_len=40,
                 eos=None, temperature=0.0):
    """One replica engine, DETERMINISTIC in its kwargs — the builder
    the process fleet's spawn children load by file path."""
    from quintnet_tpu.serve import ServeEngine

    family, params = model_setup(model, synthetic, seed)
    return ServeEngine(
        family, params, max_slots=slots, block_size=block_size,
        num_blocks=num_blocks,
        max_seq_len=min(max_seq_len, family.max_positions),
        eos_token_id=eos, temperature=temperature)


def engine_kwargs(args) -> dict:
    return {"model": args.model, "synthetic": bool(args.synthetic),
            "seed": args.seed, "slots": args.slots,
            "block_size": args.block_size,
            "num_blocks": args.num_blocks,
            "max_seq_len": args.max_prompt + args.max_new,
            "eos": args.eos, "temperature": args.temperature}


def vocab_size(args) -> int:
    """Vocab for trace generation WITHOUT materializing params (the
    process mode's parent never builds a model)."""
    if args.model == "gpt2":
        from quintnet_tpu.models.gpt2 import GPT2Config

        return (GPT2Config.tiny(n_layer=2) if args.synthetic
                else GPT2Config.base()).vocab_size
    from quintnet_tpu.models.llama import LlamaConfig

    return (LlamaConfig.tiny(n_layers=2) if args.synthetic
            else LlamaConfig()).vocab_size


def build_factory(args):
    """Thread-mode factory: model_setup() called ONCE, params shared
    by every replica engine in this process (the process mode cannot
    share — each child runs the same model_setup from the same seed,
    which is the point)."""
    from quintnet_tpu.serve import ServeEngine

    family, params = model_setup(args.model, bool(args.synthetic),
                                 args.seed)
    max_seq = min(args.max_prompt + args.max_new, family.max_positions)

    def factory():
        return ServeEngine(
            family, params, max_slots=args.slots,
            block_size=args.block_size, num_blocks=args.num_blocks,
            max_seq_len=max_seq, eos_token_id=args.eos,
            temperature=args.temperature)

    return factory, family.cfg.vocab_size


def make_trace(args, vocab_size: int):
    """[(delay_s_before_submit, prompt, max_new)]: the first ``burst``
    arrivals are instantaneous (delay 0 — the shedding spike), the rest
    Poisson-spaced."""
    import numpy as np

    rng = np.random.default_rng(args.seed)
    trace = []
    for i in range(args.requests):
        delay = 0.0 if i < args.burst else rng.exponential(1.0 / args.rate)
        n = int(rng.integers(args.min_prompt, args.max_prompt + 1))
        prompt = rng.integers(0, vocab_size, (n,)).astype(np.int32)
        trace.append((delay, prompt, args.max_new))
    return trace


def run_policy(args, policy: str, factory, vocab_size: int) -> dict:
    import time

    import numpy as np

    import jax

    from quintnet_tpu.fleet import Overloaded, ServeFleet
    from quintnet_tpu.ft import ChaosMonkey

    fleet = ServeFleet(
        factory, n_replicas=args.replicas, policy=policy,
        max_pending=args.max_pending, max_dispatch=args.max_dispatch,
        trip_after=args.trip_after)
    # warmup: compile every replica's prefill+decode OUTSIDE the timed
    # window — one full request lifecycle per replica, routed there
    # deterministically by pausing the others — then reset all ledgers
    for rep in fleet.replicas:
        for other in fleet.replicas:
            other.resume() if other is rep else other.pause()
        fleet.generate([np.ones((args.min_prompt,), "int32")],
                       max_new_tokens=2, timeout=600)
    fleet.resume_all()
    fleet.reset_metrics()

    monkey = None
    if args.kill_at_step is not None:
        monkey = ChaosMonkey(kill_at_step=args.kill_at_step, mode="raise",
                             target=args.kill_replica)
        fleet.arm_chaos(monkey)

    trace = make_trace(args, vocab_size)
    fids = []
    t0 = time.perf_counter()
    for delay, prompt, max_new in trace:
        if delay:
            time.sleep(delay)
        try:
            fids.append(fleet.submit(prompt, max_new))
        except Overloaded:
            pass                       # counted in fleet.summary()
    for fid in fids:
        try:
            fleet.result(fid, timeout=args.timeout_s)
        except Overloaded:
            pass
    jax.block_until_ready(
        [rep.engine.pool.caches() for rep in fleet.replicas])
    wall = time.perf_counter() - t0

    s = fleet.summary()
    fleet.drain(timeout=args.timeout_s)
    eng = s["engine"]
    gen_tokens = eng["gen_tokens"]
    tag = "tiny" if args.synthetic else "full"
    return {
        "metric": f"fleet_{args.model}_{tag}_tokens_per_sec",
        "value": round(gen_tokens / wall, 2) if wall > 0 else 0.0,
        "unit": "tok/s",
        "vs_baseline": 1.0,
        "rc": 0,
        "extras": {
            "policy": policy,
            "replicas": args.replicas,
            "requests": args.requests,
            "submitted": s["submitted"],
            "accepted": s["accepted"],
            "finished": s["finished"],
            "shed": s["shed"],
            "shed_rate": s["shed_rate"],
            "migrations": s["migrations"],
            "replica_deaths": s["replica_deaths"],
            "restarts": s["restarts"],
            "ttft_p50_s": s["ttft_s"]["p50"],
            "ttft_p99_s": s["ttft_s"]["p99"],
            "latency_p50_s": s["latency_s"]["p50"],
            "latency_p99_s": s["latency_s"]["p99"],
            "gen_tokens": gen_tokens,
            "engine_steps": eng["steps"],
            "preempted": eng["preempted"],
            "wall_s": round(wall, 4),
            "kill_at_step": args.kill_at_step,
            "kill_replica": args.kill_replica,
            "burst": args.burst,
            "max_pending": args.max_pending,
            "rate": args.rate,
            "slots": args.slots,
            "model": args.model,
            "synthetic": bool(args.synthetic),
        },
    }


def run_policy_process(args, policy: str) -> dict:
    """One replay through the CROSS-PROCESS fleet: spawn --replicas
    engine processes, warm every compiled program over the wire, arm a
    mode='hard' chaos kill (abrupt process exit, no cleanup — the
    SIGKILL story) in the target child, replay the same bursty trace,
    and report from the dispatcher's journal — which is why
    finished == accepted survives the kill."""
    import time

    from quintnet_tpu.fleet import Overloaded, ProcessFleet
    from quintnet_tpu.fleet.health import Backoff

    spec = {"file": os.path.abspath(__file__), "func": "build_engine",
            "kwargs": engine_kwargs(args)}
    fleet = ProcessFleet(
        spec, n_replicas=args.replicas, policy=policy,
        max_pending=args.max_pending, max_dispatch=args.max_dispatch,
        trip_after=args.trip_after, heartbeat_s=0.05,
        backoff=Backoff(base_s=0.02, cap_s=0.5), name_prefix="r")
    try:
        # compile every child's full program set OUTSIDE the timed
        # window (one warmup RPC per replica), then fresh ledgers
        fleet.warmup()
        fleet.reset_metrics()
        if args.kill_at_step is not None:
            fleet.arm_chaos(args.kill_replica,
                            {"kill_at_step": args.kill_at_step,
                             "mode": "hard"})

        trace = make_trace(args, vocab_size(args))
        fids = []
        t0 = time.perf_counter()
        for delay, prompt, max_new in trace:
            if delay:
                time.sleep(delay)
            try:
                fids.append(fleet.submit(prompt, max_new))
            except Overloaded:
                pass                   # counted in fleet.summary()
        for fid in fids:
            try:
                fleet.result(fid, timeout=args.timeout_s)
            except Overloaded:
                pass
        # no device lives in THIS process: every token in the journal
        # was already streamed over a socket by a child whose step
        # completed — the wall delta is true end-to-end serving time
        wall = time.perf_counter() - t0  # qtcheck: ok[QT106]

        s = fleet.summary()
    finally:
        fleet.drain(timeout=args.timeout_s)
    gen_tokens = s["tokens_delivered"]
    engines = s.get("engines", {})
    tag = "tiny" if args.synthetic else "full"
    return {
        "metric": f"fleet_proc_{args.model}_{tag}_tokens_per_sec",
        "value": round(gen_tokens / wall, 2) if wall > 0 else 0.0,
        "unit": "tok/s",
        "vs_baseline": 1.0,
        "rc": 0,
        "extras": {
            "policy": policy,
            "process": True,
            "replicas": args.replicas,
            "requests": args.requests,
            "submitted": s["submitted"],
            "accepted": s["accepted"],
            "finished": s["finished"],
            "shed": s["shed"],
            "shed_rate": s["shed_rate"],
            "migrations": s["migrations"],
            "replica_deaths": s["replica_deaths"],
            "stalls": s["stalls"],
            "restarts": s["restarts"],
            "ttft_p50_s": s["ttft_s"]["p50"],
            "ttft_p99_s": s["ttft_s"]["p99"],
            "latency_p50_s": s["latency_s"]["p50"],
            "latency_p99_s": s["latency_s"]["p99"],
            "gen_tokens": gen_tokens,
            "live_engine_steps": sum(e["steps"]
                                     for e in engines.values()),
            "engines_reporting": len(engines),
            "wall_s": round(wall, 4),
            "kill_at_step": args.kill_at_step,
            "kill_replica": args.kill_replica,
            "burst": args.burst,
            "max_pending": args.max_pending,
            "rate": args.rate,
            "slots": args.slots,
            "model": args.model,
            "synthetic": bool(args.synthetic),
        },
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="gpt2", choices=("gpt2", "llama"))
    ap.add_argument("--synthetic", action="store_true",
                    help="tiny random-init config (CPU-testable)")
    ap.add_argument("--policies", default="least_work,round_robin",
                    help="comma-separated routing policies to replay")
    ap.add_argument("--replicas", type=int, default=3)
    ap.add_argument("--requests", type=int, default=32)
    ap.add_argument("--burst", type=int, default=None,
                    help="arrivals submitted instantaneously at t=0 "
                         "(default: all of them)")
    ap.add_argument("--rate", type=float, default=100.0,
                    help="Poisson arrival rate for post-burst requests "
                         "(requests per second)")
    ap.add_argument("--max-pending", type=int, default=8)
    ap.add_argument("--max-dispatch", type=int, default=None,
                    help="per-replica dispatch window (default "
                         "2*slots). An instant burst sheds at least "
                         "requests - max_pending - replicas*window")
    ap.add_argument("--slots", type=int, default=2)
    ap.add_argument("--block-size", type=int, default=16)
    ap.add_argument("--num-blocks", type=int, default=64)
    ap.add_argument("--min-prompt", type=int, default=4)
    ap.add_argument("--max-prompt", type=int, default=24)
    ap.add_argument("--max-new", type=int, default=16)
    ap.add_argument("--eos", type=int, default=None)
    ap.add_argument("--temperature", type=float, default=0.0)
    ap.add_argument("--trip-after", type=int, default=3)
    ap.add_argument("--kill-at-step", type=int, default=None,
                    help="arm a mode='raise' ChaosMonkey: the target "
                         "replica dies after its K-th replay step")
    ap.add_argument("--kill-replica", default="r1")
    ap.add_argument("--process", action="store_true",
                    help="replicas as spawned OS processes "
                         "(fleet/proc.py) instead of threads; the "
                         "armed kill becomes an abrupt process exit "
                         "and migration runs off the dispatcher's "
                         "write-ahead journal")
    ap.add_argument("--timeout-s", type=float, default=600.0)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--out", default=None,
                    help="append the records to this artifacts JSON file")
    args = ap.parse_args()
    if args.burst is None:
        args.burst = args.requests

    records = []
    if args.process:
        for policy in [p for p in args.policies.split(",") if p]:
            records.append(run_policy_process(args, policy))
            print(json.dumps(records[-1]))
    else:
        factory, vocab = build_factory(args)
        for policy in [p for p in args.policies.split(",") if p]:
            records.append(run_policy(args, policy, factory, vocab))
            print(json.dumps(records[-1]))

    if args.out:
        prev = []
        if os.path.exists(args.out):
            try:
                with open(args.out) as f:
                    loaded = json.load(f)
                prev = loaded if isinstance(loaded, list) else [loaded]
            except (OSError, json.JSONDecodeError):
                prev = []
        with open(args.out, "w") as f:
            json.dump(prev + records, f, indent=1)


if __name__ == "__main__":
    main()

"""Fleet benchmark: replay a bursty request trace against a
multi-replica serving fleet (quintnet_tpu/fleet/) once per routing
policy, with a mid-trace replica kill and an over-capacity burst, and
report one JSON line per policy:

  {"metric": "fleet_gpt2_tiny_tokens_per_sec", "value": N,
   "unit": "tok/s", "rc": 0, "extras": {"policy": "least_work",
   "ttft_p50_s": .., "ttft_p99_s": .., "shed_rate": ..,
   "migrations": .., ...}}

The trace front-loads ``--burst`` requests in one instantaneous spike
(what sheds: the fleet absorbs queue + dispatch windows and REJECTS
the rest with a typed Overloaded — the queue never grows past
``--max-pending``), then Poisson arrivals (inter-arrival ~
Exp(rate) seconds) for the remainder. ``--kill-at-step K`` arms an
``ft.ChaosMonkey`` (mode='raise') against ``--kill-replica`` AFTER
warmup, so the victim dies at its K-th replay step and its in-flight
requests migrate — finished counts include them, token-identical
(tests/test_fleet.py holds the identity; here we count).

``--process`` replays the SAME trace through the cross-process fleet
(quintnet_tpu/fleet/proc.py): each replica is its own spawned OS
process behind the wire protocol, the armed kill is a mode='hard'
``os._exit`` — the process vanishes mid-run with no cleanup, the
SIGKILL story — and the dispatcher's write-ahead journal migrates the
victim's in-flight requests to survivors (finished == accepted).
Reported tokens come from the dispatcher's journal
(``tokens_delivered``), which survives replica deaths; the metric name
gains a ``proc`` tag so the thread and process records never alias.

``--disagg`` runs the disaggregation A/B (quintnet_tpu/fleet/proc.py
``pools=``): the same steady-decode trace + long-prefill burst through
a disaggregated prefill/decode fleet AND a colocated fleet of equal
size, each also replayed without the burst. The reported value is the
disaggregated side's SELF-interference (decode ITL p99, burst /
no-burst — the "burst must not move decode ITL" bound); the
matched-load comparison vs colocated is ``burst_itl_p99_vs_colocated``
(< 1 = the dedicated prefill pool wins under the same burst on the
same box; see run_disagg for why the two modes' self-ratios are not
directly comparable on shared cores). Structural isolation —
``disagg_pool_prefill_tokens`` — is the noise-free signal: every long
prefill must land on the prefill pool (DistServe/Splitwise;
artifacts/fleet_r16.json).

``--slo`` replays the SAME interference trace with the judgment layer
armed (quintnet_tpu/obs/slo.py + signals.py): one shared objective
set is CALIBRATED off the clean no-burst replays — each signal's BEST
baseline across the two modes, x mult (TTFT p99 <= mult x baseline;
relative, so the contract travels across machines) — then both modes
replay the burst under the armed SLO engine + signal bus (+ the
observe-only rebalance planner on the disaggregated side). The record
is the typed-event story: the burst trips the fast+slow TTFT burn
windows, the breach names the prefill pool, the planner recommends
decode→prefill and the revert after recovery — and the colocated
fleet ALSO burns the ITL budget the disaggregated one holds, which is
the DistServe goodput argument as events instead of a human reading
fleet_r16.json (artifacts/slo_r17.json).

Modes:
  python tools/fleet_bench.py --synthetic                # tiny, CPU-ok
  python tools/fleet_bench.py --synthetic --requests 6 \
      --policies least_work                              # CI smoke
  python tools/fleet_bench.py --synthetic --out artifacts/fleet_r08.json
  python tools/fleet_bench.py --synthetic --process \
      --out artifacts/fleet_r12.json                     # process fleet
  python tools/fleet_bench.py --synthetic --disagg \
      --out artifacts/fleet_r16.json                     # interference A/B
  python tools/fleet_bench.py --synthetic --slo \
      --out artifacts/slo_r17.json                       # SLO replay

``--out FILE`` appends the records to an artifacts JSON list
(bench.last_known_result scans them — same staleness story as the
serve/train benches).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def model_setup(model: str, synthetic: bool, seed: int,
                n_positions=None, n_embd=None):
    """THE single source of the benched model: (family, params). Both
    modes — the thread factory and the process children, each in their
    own interpreter — construct the model HERE from the same seed, so
    they cannot drift apart and every replica holds identical
    (family, params), the migration-contract precondition.
    ``n_positions`` widens the synthetic gpt2 context (the --disagg
    trace needs prompts long enough for a prefill burst to hurt)."""
    import jax

    from quintnet_tpu.serve import gpt2_family, llama_family

    if model == "gpt2":
        from quintnet_tpu.models.gpt2 import GPT2Config, gpt2_init

        if synthetic:
            kw = {}
            if n_positions is not None:
                kw["n_positions"] = int(n_positions)
            if n_embd is not None:
                # the --disagg interference probe needs a prefill that
                # actually costs something; width is the cheapest lever
                kw.update(n_embd=int(n_embd),
                          n_head=max(2, int(n_embd) // 64))
            cfg = GPT2Config.tiny(n_layer=2, **kw)
        else:
            cfg = GPT2Config.base()
        return gpt2_family(cfg), gpt2_init(jax.random.key(seed), cfg)
    if model == "llama":
        from quintnet_tpu.models.llama import LlamaConfig, llama_init

        cfg = (LlamaConfig.tiny(n_layers=2) if synthetic
               else LlamaConfig())
        return llama_family(cfg), llama_init(jax.random.key(seed), cfg)
    raise SystemExit(f"unknown --model {model}")


def build_engine(*, model="gpt2", synthetic=True, seed=0, slots=2,
                 block_size=16, num_blocks=64, max_seq_len=40,
                 eos=None, temperature=0.0, n_positions=None,
                 n_embd=None, kv_dtype=None):
    """One replica engine, DETERMINISTIC in its kwargs — the builder
    the process fleet's spawn children load by file path."""
    from quintnet_tpu.serve import ServeEngine

    family, params = model_setup(model, synthetic, seed,
                                 n_positions=n_positions, n_embd=n_embd)
    return ServeEngine(
        family, params, max_slots=slots, block_size=block_size,
        num_blocks=num_blocks,
        max_seq_len=min(max_seq_len, family.max_positions),
        kv_dtype=kv_dtype, eos_token_id=eos, temperature=temperature)


def engine_kwargs(args) -> dict:
    return {"model": args.model, "synthetic": bool(args.synthetic),
            "seed": args.seed, "slots": args.slots,
            "block_size": args.block_size,
            "num_blocks": args.num_blocks,
            "max_seq_len": args.max_prompt + args.max_new,
            "eos": args.eos, "temperature": args.temperature}


def vocab_size(args) -> int:
    """Vocab for trace generation WITHOUT materializing params (the
    process mode's parent never builds a model)."""
    if args.model == "gpt2":
        from quintnet_tpu.models.gpt2 import GPT2Config

        return (GPT2Config.tiny(n_layer=2) if args.synthetic
                else GPT2Config.base()).vocab_size
    from quintnet_tpu.models.llama import LlamaConfig

    return (LlamaConfig.tiny(n_layers=2) if args.synthetic
            else LlamaConfig()).vocab_size


def build_factory(args):
    """Thread-mode factory: model_setup() called ONCE, params shared
    by every replica engine in this process (the process mode cannot
    share — each child runs the same model_setup from the same seed,
    which is the point)."""
    from quintnet_tpu.serve import ServeEngine

    family, params = model_setup(args.model, bool(args.synthetic),
                                 args.seed)
    max_seq = min(args.max_prompt + args.max_new, family.max_positions)

    def factory():
        return ServeEngine(
            family, params, max_slots=args.slots,
            block_size=args.block_size, num_blocks=args.num_blocks,
            max_seq_len=max_seq, eos_token_id=args.eos,
            temperature=args.temperature)

    return factory, family.cfg.vocab_size


def make_trace(args, vocab_size: int):
    """[(delay_s_before_submit, prompt, max_new)]: the first ``burst``
    arrivals are instantaneous (delay 0 — the shedding spike), the rest
    Poisson-spaced."""
    import numpy as np

    rng = np.random.default_rng(args.seed)
    trace = []
    for i in range(args.requests):
        delay = 0.0 if i < args.burst else rng.exponential(1.0 / args.rate)
        n = int(rng.integers(args.min_prompt, args.max_prompt + 1))
        prompt = rng.integers(0, vocab_size, (n,)).astype(np.int32)
        trace.append((delay, prompt, args.max_new))
    return trace


def run_policy(args, policy: str, factory, vocab_size: int) -> dict:
    import time

    import numpy as np

    import jax

    from quintnet_tpu.fleet import Overloaded, ServeFleet
    from quintnet_tpu.ft import ChaosMonkey

    fleet = ServeFleet(
        factory, n_replicas=args.replicas, policy=policy,
        max_pending=args.max_pending, max_dispatch=args.max_dispatch,
        trip_after=args.trip_after)
    # warmup: compile every replica's prefill+decode OUTSIDE the timed
    # window — one full request lifecycle per replica, routed there
    # deterministically by pausing the others — then reset all ledgers
    for rep in fleet.replicas:
        for other in fleet.replicas:
            other.resume() if other is rep else other.pause()
        fleet.generate([np.ones((args.min_prompt,), "int32")],
                       max_new_tokens=2, timeout=600)
    fleet.resume_all()
    fleet.reset_metrics()

    monkey = None
    if args.kill_at_step is not None:
        monkey = ChaosMonkey(kill_at_step=args.kill_at_step, mode="raise",
                             target=args.kill_replica)
        fleet.arm_chaos(monkey)

    trace = make_trace(args, vocab_size)
    fids = []
    t0 = time.perf_counter()
    for delay, prompt, max_new in trace:
        if delay:
            time.sleep(delay)
        try:
            fids.append(fleet.submit(prompt, max_new))
        except Overloaded:
            pass                       # counted in fleet.summary()
    for fid in fids:
        try:
            fleet.result(fid, timeout=args.timeout_s)
        except Overloaded:
            pass
    jax.block_until_ready(
        [rep.engine.pool.caches() for rep in fleet.replicas])
    wall = time.perf_counter() - t0

    s = fleet.summary()
    fleet.drain(timeout=args.timeout_s)
    eng = s["engine"]
    gen_tokens = eng["gen_tokens"]
    tag = "tiny" if args.synthetic else "full"
    return {
        "metric": f"fleet_{args.model}_{tag}_tokens_per_sec",
        "value": round(gen_tokens / wall, 2) if wall > 0 else 0.0,
        "unit": "tok/s",
        "vs_baseline": 1.0,
        "rc": 0,
        "extras": {
            "policy": policy,
            "replicas": args.replicas,
            "requests": args.requests,
            "submitted": s["submitted"],
            "accepted": s["accepted"],
            "finished": s["finished"],
            "shed": s["shed"],
            "shed_rate": s["shed_rate"],
            "migrations": s["migrations"],
            "replica_deaths": s["replica_deaths"],
            "restarts": s["restarts"],
            "ttft_p50_s": s["ttft_s"]["p50"],
            "ttft_p99_s": s["ttft_s"]["p99"],
            "latency_p50_s": s["latency_s"]["p50"],
            "latency_p99_s": s["latency_s"]["p99"],
            "gen_tokens": gen_tokens,
            "engine_steps": eng["steps"],
            "preempted": eng["preempted"],
            "wall_s": round(wall, 4),
            "kill_at_step": args.kill_at_step,
            "kill_replica": args.kill_replica,
            "burst": args.burst,
            "max_pending": args.max_pending,
            "rate": args.rate,
            "slots": args.slots,
            "model": args.model,
            "synthetic": bool(args.synthetic),
        },
    }


def run_policy_process(args, policy: str) -> dict:
    """One replay through the CROSS-PROCESS fleet: spawn --replicas
    engine processes, warm every compiled program over the wire, arm a
    mode='hard' chaos kill (abrupt process exit, no cleanup — the
    SIGKILL story) in the target child, replay the same bursty trace,
    and report from the dispatcher's journal — which is why
    finished == accepted survives the kill."""
    import time

    from quintnet_tpu.fleet import Overloaded, ProcessFleet
    from quintnet_tpu.fleet.health import Backoff

    spec = {"file": os.path.abspath(__file__), "func": "build_engine",
            "kwargs": engine_kwargs(args)}
    fleet = ProcessFleet(
        spec, n_replicas=args.replicas, policy=policy,
        max_pending=args.max_pending, max_dispatch=args.max_dispatch,
        trip_after=args.trip_after, heartbeat_s=0.05,
        backoff=Backoff(base_s=0.02, cap_s=0.5), name_prefix="r")
    try:
        # compile every child's full program set OUTSIDE the timed
        # window (one warmup RPC per replica), then fresh ledgers
        fleet.warmup()
        fleet.reset_metrics()
        if args.kill_at_step is not None:
            fleet.arm_chaos(args.kill_replica,
                            {"kill_at_step": args.kill_at_step,
                             "mode": "hard"})

        trace = make_trace(args, vocab_size(args))
        fids = []
        t0 = time.perf_counter()
        for delay, prompt, max_new in trace:
            if delay:
                time.sleep(delay)
            try:
                fids.append(fleet.submit(prompt, max_new))
            except Overloaded:
                pass                   # counted in fleet.summary()
        for fid in fids:
            try:
                fleet.result(fid, timeout=args.timeout_s)
            except Overloaded:
                pass
        # no device lives in THIS process: every token in the journal
        # was already streamed over a socket by a child whose step
        # completed — the wall delta is true end-to-end serving time
        wall = time.perf_counter() - t0  # qtcheck: ok[QT106]

        s = fleet.summary()
    finally:
        fleet.drain(timeout=args.timeout_s)
    gen_tokens = s["tokens_delivered"]
    engines = s.get("engines", {})
    tag = "tiny" if args.synthetic else "full"
    return {
        "metric": f"fleet_proc_{args.model}_{tag}_tokens_per_sec",
        "value": round(gen_tokens / wall, 2) if wall > 0 else 0.0,
        "unit": "tok/s",
        "vs_baseline": 1.0,
        "rc": 0,
        "extras": {
            "policy": policy,
            "process": True,
            "replicas": args.replicas,
            "requests": args.requests,
            "submitted": s["submitted"],
            "accepted": s["accepted"],
            "finished": s["finished"],
            "shed": s["shed"],
            "shed_rate": s["shed_rate"],
            "migrations": s["migrations"],
            "replica_deaths": s["replica_deaths"],
            "stalls": s["stalls"],
            "restarts": s["restarts"],
            "ttft_p50_s": s["ttft_s"]["p50"],
            "ttft_p99_s": s["ttft_s"]["p99"],
            "latency_p50_s": s["latency_s"]["p50"],
            "latency_p99_s": s["latency_s"]["p99"],
            "gen_tokens": gen_tokens,
            "live_engine_steps": sum(e["steps"]
                                     for e in engines.values()),
            "engines_reporting": len(engines),
            "wall_s": round(wall, 4),
            "kill_at_step": args.kill_at_step,
            "kill_replica": args.kill_replica,
            "burst": args.burst,
            "max_pending": args.max_pending,
            "rate": args.rate,
            "slots": args.slots,
            "model": args.model,
            "synthetic": bool(args.synthetic),
        },
    }


# ---------------------------------------------------------------------------
# --disagg: TTFT-vs-ITL interference A/B (disaggregated vs colocated)
# ---------------------------------------------------------------------------


def _disagg_engine_kwargs(args) -> dict:
    """Engine spec for the interference A/B: context wide enough for
    the long-prefill burst, pool sized so nothing preempts."""
    # the window must hold BOTH trace populations: long burst prompts
    # AND the steady prompts (which --max-prompt can size past the
    # burst length)
    max_seq = max(args.burst_prompt_len, args.max_prompt) + args.max_new
    return {"model": args.model, "synthetic": bool(args.synthetic),
            "seed": args.seed, "slots": args.slots,
            "block_size": args.block_size,
            "num_blocks": args.num_blocks,
            "max_seq_len": max_seq, "n_positions": max_seq,
            "n_embd": args.disagg_n_embd,
            "kv_dtype": args.kv_dtype,
            "eos": args.eos, "temperature": args.temperature}


def _replay_itl(args, fleet, vocab: int, *, burst: bool,
                seed: int) -> dict:
    """One replay against an ALREADY-WARM fleet: ``--steady`` short
    decode-heavy requests submitted at t=0, then (burst replays only)
    ``--burst-prompts`` long-prefill requests mid-decode. Inter-token
    gaps are timestamped AT THE DISPATCHER as tokens stream in — the
    client-visible ITL, which is exactly what a monolithic prefill on
    a colocated replica inflates and a dedicated prefill pool must
    not."""
    import threading
    import time

    import numpy as np

    from quintnet_tpu.fleet import Overloaded

    rng = np.random.default_rng(seed)
    marks = {}          # steady fid -> token arrival timestamps
    lock = threading.Lock()

    def on_token(fid, tok, last):  # appends only; contractually quick
        with lock:
            # setdefault: a first token can land before the submit
            # call returns and the fid is registered below — a plain
            # KeyError here would be SWALLOWED by FleetRequest.deliver
            # (client callbacks must not read as replica faults) and
            # silently drop timestamps, shifting the per[:2]
            # admission-gap trim onto steady-state gaps
            marks.setdefault(fid, []).append(time.perf_counter())

    fleet.reset_metrics()
    fids, burst_fids = [], []
    for i in range(args.steady):
        # staggered arrivals: the prefill pool (and the handoff path)
        # stays periodically busy through BOTH replays, so the
        # no-burst baseline carries the same steady-state load as the
        # burst replay and the ratio isolates the BURST, not the
        # difference between an idle and a working prefill pool
        if i:
            time.sleep(args.steady_gap_s)
        n = int(rng.integers(args.min_prompt, args.max_prompt + 1))
        prompt = rng.integers(0, vocab, (n,)).astype(np.int32)
        fid = fleet.submit(prompt, args.max_new, on_token=on_token)
        with lock:
            marks.setdefault(fid, [])
        fids.append(fid)
    if burst:
        time.sleep(args.burst_delay_s)
        for _ in range(args.burst_prompts):
            # the burst is TTFT-bound prefill work (max_new=1): on a
            # disaggregated fleet it lives and dies in the prefill
            # pool — which is the isolation claim under test. The
            # steady requests above exercise the full handoff path
            # (prefill pool -> KV transfer -> decode pool) either way.
            prompt = rng.integers(
                0, vocab, (args.burst_prompt_len,)).astype(np.int32)
            try:
                burst_fids.append(fleet.submit(prompt, 1))
            except Overloaded:
                pass
    for fid in fids + burst_fids:
        fleet.result(fid, timeout=args.timeout_s)
    gaps, first_gaps = [], []
    with lock:
        for ts in marks.values():
            per = [b - a for a, b in zip(ts, ts[1:])]
            # the first two gaps straddle the admission boundary —
            # on a disaggregated fleet that includes the one-time KV
            # handoff (a TTFT-class cost, reported separately below),
            # on any fleet the admission prefill of the cohort itself.
            # Steady-state decode ITL — the thing a prefill burst must
            # not disturb — is everything after
            first_gaps.extend(per[:2])
            gaps.extend(per[2:])
    gaps.sort()
    s = fleet.summary()
    # the NOISE-FREE structural signal: where did prefill compute
    # actually run? On a disaggregated fleet the decode pool's
    # engines prefill only warm-hit tails (~1 token per handed-off
    # request) — the burst's long prefills must all land on the
    # prefill pool. Wall-clock ITL wobbles on a loaded CPU box; token
    # accounting does not.
    pool_of = {r.name: r.pool for r in fleet.replicas}
    pool_prefill = {}
    for name, eng in s.get("engines", {}).items():
        pool = pool_of.get(name, "any")
        pool_prefill[pool] = (pool_prefill.get(pool, 0)
                              + int(eng.get("prefill_tokens", 0)))
    return {
        "pool_prefill_tokens": pool_prefill,
        "itl_p99_s": (round(float(np.percentile(gaps, 99)), 5)
                      if gaps else 0.0),
        "itl_p50_s": (round(float(np.percentile(gaps, 50)), 5)
                      if gaps else 0.0),
        "ttft_p99_s": s["ttft_s"]["p99"],
        "ttft_p50_s": s["ttft_s"]["p50"],
        "first_gap_max_s": (round(max(first_gaps), 5)
                            if first_gaps else 0.0),
        "gaps": len(gaps),
        "finished": s["finished"],
        "accepted": s["accepted"],
        "handoffs": s["handoffs"],
        "handoff_transfers": s["handoff_transfers"],
        "handoff_fallbacks": s["handoff_fallbacks"],
    }


def run_disagg(args) -> dict:
    """The disaggregation A/B at matched load: the SAME steady trace +
    long-prefill burst replayed through (a) a disaggregated fleet —
    dedicated prefill pool absorbing the burst, decode pool streaming
    undisturbed, KV chains handed off over the wire — and (b) a
    colocated fleet of the same total replica count, where the burst's
    monolithic prefills stall whichever replicas take them. Each mode
    also replays WITHOUT the burst for its own baseline, so the
    reported signal is the interference RATIO (burst ITL p99 /
    no-burst ITL p99) — self-normalized per mode, which is what makes
    it comparable on a noisy CPU box."""
    import time

    from quintnet_tpu.fleet import ProcessFleet
    from quintnet_tpu.fleet.retry import RetryPolicy

    vocab = vocab_size(args)
    spec = {"file": os.path.abspath(__file__), "func": "build_engine",
            "kwargs": _disagg_engine_kwargs(args)}
    n_total = args.prefill_replicas + args.decode_replicas
    results = {}
    for mode in ("disagg", "colocated"):
        kw = (dict(pools={"prefill": args.prefill_replicas,
                          "decode": args.decode_replicas})
              if mode == "disagg" else dict(n_replicas=n_total))
        fleet = ProcessFleet(
            spec, policy="least_work", max_pending=args.max_pending,
            max_dispatch=args.max_dispatch, heartbeat_s=0.05,
            handoff_retry=RetryPolicy(base_s=0.02, cap_s=0.5,
                                      max_attempts=3),
            name_prefix="r", **kw)
        try:
            fleet.warmup()
            # throwaway warm replay: first-use costs that are not the
            # steady-state story (KV-import scatter compiles on the
            # decode replicas, allocator warm-up) must not land inside
            # a measured window — same discipline as serve_bench's
            # warm-lifecycle-first A/B
            import argparse as _ap

            # capped at the run's own --max-new: the engines are sized
            # for THAT window, and a longer warm request would be
            # rejected as inadmissible (prompt+max_new > max_seq_len)
            warm = _ap.Namespace(**{**vars(args), "steady": 2,
                                    "max_new": min(4, args.max_new)})
            _replay_itl(warm, fleet, vocab, burst=False,
                        seed=args.seed + 7919)
            for burst in (False, True):
                results[(mode, burst)] = _replay_itl(
                    args, fleet, vocab, burst=burst,
                    seed=args.seed + (1 if burst else 0))
        finally:
            fleet.drain(timeout=args.timeout_s)

    def ratio(mode):
        base = results[(mode, False)]["itl_p99_s"]
        loud = results[(mode, True)]["itl_p99_s"]
        return round(loud / base, 4) if base > 0 else 0.0

    def vs_colocated(burst):
        d = results[("disagg", burst)]["itl_p99_s"]
        c = results[("colocated", burst)]["itl_p99_s"]
        return round(d / c, 4) if c > 0 else 0.0

    tag = "tiny" if args.synthetic else "full"
    d_burst, c_burst = results[("disagg", True)], \
        results[("colocated", True)]
    # Two complementary signals. The headline value is the
    # disaggregated side's SELF-interference (burst p99 / its own
    # no-burst p99) — the "burst must not move decode ITL" bound.
    # The matched-load comparison vs colocated is the ABSOLUTE
    # burst-time p99 ratio (burst_itl_p99_vs_colocated < 1 = win):
    # on a shared-core box the self-ratios are not comparable across
    # modes, because disaggregation also cleans up the NO-burst
    # baseline (the prefill pool idles when nobody bursts —
    # baseline_itl_p99_vs_colocated reports that win), which deflates
    # the colocated ratio's denominator asymmetrically.
    return {
        "metric": f"fleet_disagg_{args.model}_{tag}_itl_interference",
        "value": ratio("disagg"),
        "unit": "ratio",
        "vs_baseline": 1.0,
        "rc": 0,
        "extras": {
            "colocated_interference": ratio("colocated"),
            "burst_itl_p99_vs_colocated": vs_colocated(True),
            "baseline_itl_p99_vs_colocated": vs_colocated(False),
            "disagg_itl_p99_no_burst_s":
                results[("disagg", False)]["itl_p99_s"],
            "disagg_itl_p99_burst_s": d_burst["itl_p99_s"],
            "colocated_itl_p99_no_burst_s":
                results[("colocated", False)]["itl_p99_s"],
            "colocated_itl_p99_burst_s": c_burst["itl_p99_s"],
            "disagg_itl_p50_burst_s": d_burst["itl_p50_s"],
            "colocated_itl_p50_burst_s": c_burst["itl_p50_s"],
            "handoffs": d_burst["handoffs"],
            "handoff_transfers": d_burst["handoff_transfers"],
            "handoff_fallbacks": d_burst["handoff_fallbacks"],
            "finished": d_burst["finished"],
            "accepted": d_burst["accepted"],
            # structural isolation (deterministic, CI-gated): every
            # long prefill of the burst ran on the prefill pool; the
            # decode pool prefilled warm-hit tails only
            "disagg_pool_prefill_tokens":
                d_burst["pool_prefill_tokens"],
            "colocated_pool_prefill_tokens":
                c_burst["pool_prefill_tokens"],
            "kv_dtype": args.kv_dtype,
            "colocated_finished": c_burst["finished"],
            "colocated_accepted": c_burst["accepted"],
            "prefill_replicas": args.prefill_replicas,
            "decode_replicas": args.decode_replicas,
            "steady": args.steady,
            "burst_prompts": args.burst_prompts,
            "burst_prompt_len": args.burst_prompt_len,
            "max_new": args.max_new,
            "slots": args.slots,
            "model": args.model,
            "synthetic": bool(args.synthetic),
        },
    }


# ---------------------------------------------------------------------------
# --slo: the judgment layer replayed over the fleet_r16 interference trace
# ---------------------------------------------------------------------------


def _slo_capture(fleet) -> dict:
    """One mode's SLO story after an armed replay: which objectives
    breached / recovered (from the typed event stream — edges, not
    polling), the burn peaks, and the planner's recommendation ledger
    (disaggregated fleets only)."""
    status = fleet.slo.status()
    events = fleet.events.snapshot()

    def of_kind(kind):
        return [e for e in events if e["kind"] == kind]

    breaches = of_kind("slo_breach")
    out = {
        "breached": sorted({e["objective"] for e in breaches}),
        "breach_pools": {e["objective"]: e["pool"] for e in breaches},
        "recovered": sorted({e["objective"]
                             for e in of_kind("slo_recovered")}),
        "burn_fast_peak": {name: st["burn_fast_peak"]
                           for name, st in status["objectives"].items()},
        "breach_burns": [{"objective": e["objective"],
                          "burn_fast": e["burn_fast"],
                          "burn_slow": e["burn_slow"]}
                         for e in breaches],
        "still_breaching": status["breaching"],
    }
    if fleet.planner is not None:
        out["recommendations"] = [
            {k: r.get(k) for k in ("direction", "from_pool", "to_pool",
                                   "revert", "objective", "reason")}
            for r in fleet.planner.recommendations]
    return out


def run_slo(args) -> dict:
    """The SLO engine + signal plane over the SAME interference trace
    as --disagg (fleet_r16): each mode first replays WITHOUT the burst
    unarmed, then WITH the burst under the armed engine. The clean
    replays calibrate ONE shared objective set — each signal's BEST
    clean baseline across the two modes, x mult (absolute targets
    would bake in one machine's speed) — the tightest contract this
    box can promise at all; both modes are then judged against the
    SAME promise, which is the DistServe goodput framing.

    The acceptance story this records: on the DISAGGREGATED side the
    long-prefill burst trips the fast+slow TTFT burn windows, the
    breach names the prefill pool, the observe-only planner recommends
    converting a decode replica to prefill while the breach holds and
    recommends the REVERT after it recovers; ITL holds — the decode
    pool never runs a monolithic prefill. On the COLOCATED side the
    same burst ALSO burns the ITL budget — the monolithic prefills
    stall decode, a breach no rebalance can fix — which is the
    DistServe goodput argument as a typed event stream instead of a
    human reading fleet_r16.json."""
    import time

    from quintnet_tpu.fleet import ProcessFleet
    from quintnet_tpu.fleet.retry import RetryPolicy
    from quintnet_tpu.obs import SLOConfig

    if args.max_new < 4:
        # the ITL ledger excludes each request's first 2 gaps (handoff
        # transient) — shorter runs leave NO steady gaps, calibrate an
        # itl_p99 target of 0.0, and Objective rejects target <= 0
        raise SystemExit("--slo needs --max-new >= 4: shorter runs "
                         "record no steady ITL gaps to calibrate the "
                         "itl_p99 objective from")
    vocab = vocab_size(args)
    spec = {"file": os.path.abspath(__file__), "func": "build_engine",
            "kwargs": _disagg_engine_kwargs(args)}
    n_total = args.prefill_replicas + args.decode_replicas
    results = {}
    fleets = {}
    try:
        # phase 1 — both fleets up, warm, and replayed WITHOUT the
        # burst, unarmed: the clean baselines. The shared objective
        # set takes each signal's BEST clean baseline across the two
        # modes (x mult) — the tightest contract this box can promise
        # at all. That is what makes the verdict meaningful: TTFT
        # calibrates off the colocated side (no handoff in the first
        # token's path), ITL off the disaggregated side (a dedicated
        # decode pool nothing ever prefills on), and the burst replay
        # then shows which deployment can HOLD the combined promise.
        for mode in ("disagg", "colocated"):
            kw = (dict(pools={"prefill": args.prefill_replicas,
                              "decode": args.decode_replicas})
                  if mode == "disagg" else dict(n_replicas=n_total))
            fleet = fleets[mode] = ProcessFleet(
                spec, policy="least_work", max_pending=args.max_pending,
                max_dispatch=args.max_dispatch, heartbeat_s=0.05,
                handoff_retry=RetryPolicy(base_s=0.02, cap_s=0.5,
                                          max_attempts=3),
                name_prefix="r", obs=True, **kw)
            fleet.warmup()
            import argparse as _ap

            warm = _ap.Namespace(**{**vars(args), "steady": 2,
                                    "max_new": min(4, args.max_new)})
            _replay_itl(warm, fleet, vocab, burst=False,
                        seed=args.seed + 7919)
            base = _replay_itl(args, fleet, vocab, burst=False,
                               seed=args.seed)
            results[mode] = {"baseline": base}
        targets = {
            "ttft_p99_s": round(args.slo_ttft_mult * min(
                results[m]["baseline"]["ttft_p99_s"]
                for m in results), 5),
            "itl_p99_s": round(args.slo_itl_mult * min(
                results[m]["baseline"]["itl_p99_s"]
                for m in results), 5),
        }
        bad = {k: v for k, v in targets.items() if v <= 0}
        if bad:
            raise SystemExit(f"clean-replay calibration produced "
                             f"non-positive targets {bad} — the "
                             f"baseline recorded no samples for "
                             f"these signals; raise --steady/--max-new")
        # phase 2 — arm the SAME objectives on both fleets and replay
        # WITH the burst (the idle fleet just heartbeats while the
        # other replays; replays stay sequential so the two modes
        # never compete for cores mid-measurement)
        for mode in ("disagg", "colocated"):
            fleet = fleets[mode]
            fleet.arm_slo(
                SLOConfig.serving(
                    ttft_p99_s=targets["ttft_p99_s"],
                    itl_p99_s=targets["itl_p99_s"],
                    fast_window_s=args.slo_fast_window,
                    slow_window_s=args.slo_slow_window,
                    burn_threshold=args.slo_burn_threshold,
                    eval_interval_s=args.slo_eval_interval),
                cooldown_s=args.slo_cooldown,
                donor_occupancy_below=args.slo_donor_occ)
            burst = _replay_itl(args, fleet, vocab, burst=True,
                                seed=args.seed + 1)
            # post-burst: the dispatcher keeps evaluating on its own
            # tick — wait for the fast window to clear (recovery) and,
            # on the disaggregated side, for the planner's revert
            deadline = time.monotonic() + args.slo_recovery_wait
            while time.monotonic() < deadline:  # qtcheck: ok[QT106]
                recovered = not fleet.slo.status()["breaching"]
                reverted = (fleet.planner is None
                            or any(r["revert"] for r in
                                   fleet.planner.recommendations))
                if recovered and reverted:
                    break
                time.sleep(0.05)
            results[mode].update(burst=burst, slo=_slo_capture(fleet))
    finally:
        for fleet in fleets.values():
            fleet.drain(timeout=args.timeout_s)

    d, c = results["disagg"], results["colocated"]
    recs = d["slo"]["recommendations"]
    tag = "tiny" if args.synthetic else "full"
    # the headline value: how hard the burst burned the TTFT budget on
    # the disaggregated side's fast window (>= threshold = tripped)
    return {
        "metric": f"fleet_slo_{args.model}_{tag}_burst_burn_peak",
        "value": d["slo"]["burn_fast_peak"].get("ttft_p99", 0.0),
        "unit": "x",
        "vs_baseline": 1.0,
        "rc": 0,
        "extras": {
            "targets": targets,
            "burn_threshold": args.slo_burn_threshold,
            "fast_window_s": args.slo_fast_window,
            "slow_window_s": args.slo_slow_window,
            "disagg_baseline_ttft_p99_s": d["baseline"]["ttft_p99_s"],
            "disagg_baseline_itl_p99_s": d["baseline"]["itl_p99_s"],
            "colocated_baseline_ttft_p99_s":
                c["baseline"]["ttft_p99_s"],
            "colocated_baseline_itl_p99_s": c["baseline"]["itl_p99_s"],
            "disagg_breached": d["slo"]["breached"],
            "disagg_breach_pools": d["slo"]["breach_pools"],
            "disagg_recovered": d["slo"]["recovered"],
            "disagg_still_breaching": d["slo"]["still_breaching"],
            "disagg_breach_burns": d["slo"]["breach_burns"],
            "disagg_burn_fast_peak": d["slo"]["burn_fast_peak"],
            "recommendations": recs,
            "colocated_breached": c["slo"]["breached"],
            "colocated_breach_pools": c["slo"]["breach_pools"],
            "colocated_burn_fast_peak": c["slo"]["burn_fast_peak"],
            "disagg_itl_p99_burst_s": d["burst"]["itl_p99_s"],
            "colocated_itl_p99_burst_s": c["burst"]["itl_p99_s"],
            "disagg_ttft_p99_burst_s": d["burst"]["ttft_p99_s"],
            "colocated_ttft_p99_burst_s": c["burst"]["ttft_p99_s"],
            "handoffs": d["burst"]["handoffs"],
            "handoff_fallbacks": d["burst"]["handoff_fallbacks"],
            "finished": d["burst"]["finished"],
            "accepted": d["burst"]["accepted"],
            "colocated_finished": c["burst"]["finished"],
            "colocated_accepted": c["burst"]["accepted"],
            "ttft_mult": args.slo_ttft_mult,
            "itl_mult": args.slo_itl_mult,
            "donor_occupancy_below": args.slo_donor_occ,
            "cooldown_s": args.slo_cooldown,
            "kv_dtype": args.kv_dtype,
            "n_embd": args.disagg_n_embd,
            "prefill_replicas": args.prefill_replicas,
            "decode_replicas": args.decode_replicas,
            "steady": args.steady,
            "burst_prompts": args.burst_prompts,
            "burst_prompt_len": args.burst_prompt_len,
            "max_new": args.max_new,
            "slots": args.slots,
            "model": args.model,
            "synthetic": bool(args.synthetic),
        },
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="gpt2", choices=("gpt2", "llama"))
    ap.add_argument("--synthetic", action="store_true",
                    help="tiny random-init config (CPU-testable)")
    ap.add_argument("--policies", default="least_work,round_robin",
                    help="comma-separated routing policies to replay")
    ap.add_argument("--replicas", type=int, default=3)
    ap.add_argument("--requests", type=int, default=32)
    ap.add_argument("--burst", type=int, default=None,
                    help="arrivals submitted instantaneously at t=0 "
                         "(default: all of them)")
    ap.add_argument("--rate", type=float, default=100.0,
                    help="Poisson arrival rate for post-burst requests "
                         "(requests per second)")
    ap.add_argument("--max-pending", type=int, default=8)
    ap.add_argument("--max-dispatch", type=int, default=None,
                    help="per-replica dispatch window (default "
                         "2*slots). An instant burst sheds at least "
                         "requests - max_pending - replicas*window")
    ap.add_argument("--slots", type=int, default=2)
    ap.add_argument("--block-size", type=int, default=16)
    ap.add_argument("--num-blocks", type=int, default=64)
    ap.add_argument("--min-prompt", type=int, default=4)
    ap.add_argument("--max-prompt", type=int, default=24)
    ap.add_argument("--max-new", type=int, default=16)
    ap.add_argument("--eos", type=int, default=None)
    ap.add_argument("--temperature", type=float, default=0.0)
    ap.add_argument("--trip-after", type=int, default=3)
    ap.add_argument("--kill-at-step", type=int, default=None,
                    help="arm a mode='raise' ChaosMonkey: the target "
                         "replica dies after its K-th replay step")
    ap.add_argument("--kill-replica", default="r1")
    ap.add_argument("--process", action="store_true",
                    help="replicas as spawned OS processes "
                         "(fleet/proc.py) instead of threads; the "
                         "armed kill becomes an abrupt process exit "
                         "and migration runs off the dispatcher's "
                         "write-ahead journal")
    ap.add_argument("--disagg", action="store_true",
                    help="TTFT-vs-ITL interference A/B: a "
                         "disaggregated prefill/decode process fleet "
                         "vs a colocated one of the same size, each "
                         "replayed with and without a long-prefill "
                         "burst; reports the decode-ITL-p99 "
                         "interference ratio per mode")
    ap.add_argument("--prefill-replicas", type=int, default=1)
    ap.add_argument("--decode-replicas", type=int, default=2)
    ap.add_argument("--steady", type=int, default=6,
                    help="steady short-prompt decode requests per "
                         "--disagg replay (the ITL probe population)")
    ap.add_argument("--burst-prompts", type=int, default=3,
                    help="long-prefill requests injected mid-decode "
                         "on --disagg burst replays")
    ap.add_argument("--burst-prompt-len", type=int, default=96)
    ap.add_argument("--disagg-n-embd", type=int, default=None,
                    help="widen the synthetic gpt2 for --disagg so a "
                         "long prefill costs enough to measure")
    ap.add_argument("--kv-dtype", default="int8",
                    help="KV layout policy for the --disagg engines "
                         "(int8 makes each handed-off chain ~4x "
                         "smaller on the wire — PR 10's layout is "
                         "half of what makes disaggregation cheap)")
    ap.add_argument("--slo", action="store_true",
                    help="replay the --disagg interference trace with "
                         "the SLO engine + signal plane armed "
                         "(obs/slo.py, obs/signals.py): objectives "
                         "calibrated off the best clean no-burst "
                         "baseline, burn windows + breach events + "
                         "observe-only rebalance recommendations "
                         "recorded for BOTH modes")
    ap.add_argument("--slo-ttft-mult", type=float, default=3.0,
                    help="TTFT p99 objective = mult x the best "
                         "mode's no-burst baseline p99 (relative, so "
                         "the contract travels across machines)")
    ap.add_argument("--slo-itl-mult", type=float, default=1.5,
                    help="ITL p99 objective = mult x the best "
                         "mode's no-burst baseline p99")
    ap.add_argument("--slo-fast-window", type=float, default=1.5,
                    help="fast burn window (responsiveness + recovery)")
    ap.add_argument("--slo-slow-window", type=float, default=6.0,
                    help="slow burn window (the anti-flap gate)")
    ap.add_argument("--slo-burn-threshold", type=float, default=2.0)
    ap.add_argument("--slo-eval-interval", type=float, default=0.05)
    ap.add_argument("--slo-cooldown", type=float, default=1.0,
                    help="planner cooldown between recommendations")
    ap.add_argument("--slo-donor-occ", type=float, default=0.85,
                    help="planner donor-occupancy gate: only recommend "
                         "taking a replica from a pool whose EWMA "
                         "occupancy is below this")
    ap.add_argument("--slo-recovery-wait", type=float, default=15.0,
                    help="post-burst grace for the fast window to "
                         "clear and the planner to recommend the "
                         "revert")
    ap.add_argument("--steady-gap-s", type=float, default=0.1,
                    help="spacing between --disagg steady arrivals "
                         "(keeps the prefill pool periodically busy "
                         "in burst AND no-burst replays)")
    ap.add_argument("--burst-delay-s", type=float, default=0.1,
                    help="seconds into the steady decode at which the "
                         "--disagg burst lands (early enough that the "
                         "steady requests are still decoding)")
    ap.add_argument("--timeout-s", type=float, default=600.0)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--out", default=None,
                    help="append the records to this artifacts JSON file")
    args = ap.parse_args()
    if args.burst is None:
        args.burst = args.requests

    records = []
    if args.slo:
        records.append(run_slo(args))
        print(json.dumps(records[-1]))
    elif args.disagg:
        records.append(run_disagg(args))
        print(json.dumps(records[-1]))
    elif args.process:
        for policy in [p for p in args.policies.split(",") if p]:
            records.append(run_policy_process(args, policy))
            print(json.dumps(records[-1]))
    else:
        factory, vocab = build_factory(args)
        for policy in [p for p in args.policies.split(",") if p]:
            records.append(run_policy(args, policy, factory, vocab))
            print(json.dumps(records[-1]))

    if args.out:
        prev = []
        if os.path.exists(args.out):
            try:
                with open(args.out) as f:
                    loaded = json.load(f)
                prev = loaded if isinstance(loaded, list) else [loaded]
            except (OSError, json.JSONDecodeError):
                prev = []
        with open(args.out, "w") as f:
            json.dump(prev + records, f, indent=1)


if __name__ == "__main__":
    main()

"""Serving benchmark: replay a synthetic request trace through the
continuous-batching engine (quintnet_tpu/serve/) and report
throughput + latency as ONE JSON line:

  {"metric": "serve_gpt2_tiny_tokens_per_sec", "value": N,
   "unit": "tok/s", "rc": 0, "extras": {"ttft_p50_s": ..,
   "ttft_p95_s": .., "peak_kv_utilization": .., ...}}

Arrivals are a Poisson process in ENGINE-STEP time (inter-arrival ~
Exp(rate)). Two trace shapes:

- default: prompt lengths uniform in [min_prompt, max_prompt] — the
  mixed-length staggered workload the one-shot batch decoders
  (models/gpt2_generate.py) cannot serve without padding everything to
  the longest request;
- ``--prefix-share``: N users x ONE shared system prompt
  (``--shared-prefix`` tokens) + short unique tails — the
  real-traffic shape (system prompts, few-shot templates) the prefix
  cache exists for. This mode replays the SAME trace through a
  cache-ON and a cache-OFF engine and reports both: the record's value
  is cache-on tok/s, ``extras`` carries the cache-off numbers, the
  speedup, and the hit rate;
- ``--spec-trace``: repetitive prompts (each a short random pattern
  tiled to length — templated/greedy-friendly text) where n-gram
  self-drafting should accept long drafts. Replays the SAME trace
  through a speculation-ON and a speculation-OFF engine (both greedy)
  and reports both: the record's value is spec-on tok/s, ``extras``
  carries the spec-off numbers, the speedup, the draft acceptance
  rate and ``tokens_per_decode_step`` — the committed-tokens-per-
  program-invocation number that makes the speculation win legible
  without reading raw metrics;
- ``--long-trace``: the short Poisson mix PLUS ``--long-prompts``
  document-length prompts (``--long-prompt`` tokens — longer than the
  chunked engine's whole prefill window) arriving mid-decode. Replays
  the SAME trace through a chunked-prefill engine
  (``chunked_prefill=True``, per-step ``--chunk-budget``) and the
  stall-prone monolithic baseline (prefill window widened to swallow
  the prompt in one program call). The headline comparison is decode
  tok/s DURING the long-prefill window — how fast everyone else's
  streams move while a document is read in — plus inter-token-latency
  tails (a monolithic prefill appears as one giant gap in every
  concurrent stream);
- ``--kv-capacity``: the EQUAL-POOL-BYTES capacity A/B (quantized KV,
  serve/kv_quant.py) over the ``--prefix-share`` trace shape: side A
  is an f32 pool at ``--num-blocks``; side B is the ``--kv-dtype``
  (int8 unless set otherwise) pool given exactly the SAME byte
  budget — which buys it ~4x the blocks. Capacity is concurrency:
  the record's value is the quantized side's tok/s, ``vs_baseline``
  the usable-blocks ratio at equal bytes, and extras carry the
  structural evidence (preemptions, cache evictions, hit rates, peak
  utilization, both pools' bytes);
- ``--tier-trace``: the tiered-KV A/B (serve/kv_tier.py) over a
  MANY-TENANT prefix set sized ``--tier-prefix-ratio`` x the usable
  device pool (``--tier-prefixes`` distinct system prompts visited
  round-robin with unique tails, ``--tier-repeats`` visits each): by
  the time a prefix is revisited its chain has been LRU-evicted from
  the device pool, so side A (host tier armed, ``--tier-bytes``)
  demotes on eviction and re-promotes on the host-hit while side B
  (evict-only: the identical engine, tier off) re-prefills from
  scratch. The record's value is tiered tok/s, ``vs_baseline`` the
  tok/s ratio, and extras carry the gates: warm hit rate vs the
  evict-only hit rate, TTFT both sides, the tier ledger
  (demotions/promotions/host bytes/host evictions), and the
  structural ``decode_blocked_demotions == 0`` — demotion copies
  never stall a decode step;
- ``--lora-trace``: N tenants spread round-robin over ``--adapters``
  LoRA adapters (trained variants of one base model, saved through
  the real safetensors path) — the multi-tenant scenario
  serve/adapters.py exists for. The A side serves the WHOLE mixed
  trace through ONE multi-LoRA engine (heterogeneous adapters batched
  into shared decode steps); the B side is the merged-weight
  baseline: one DEDICATED engine per adapter serving only its
  tenant's requests, walls summed — what multi-tenancy costs without
  adapter batching. The record's value is multi-LoRA tok/s; extras
  carry the merged totals, the speedup, and the structural signal
  ``merged_decode_steps / decode_steps`` (shared steps do the work of
  many dedicated ones, independent of wall-clock noise).

Every mode's extras carry ``decode_steps`` and
``tokens_per_decode_step`` (decode_tokens / decode_steps).

- ``--obs-ab``: the observability overhead A/B (quintnet_tpu/obs/):
  the SAME default Poisson trace replayed through an engine with the
  flight recorder armed (per-request Tracer + per-step StepRecorder)
  and one without. Observation is contractually inert on tokens
  (bit-identity is pinned in tests/test_obs.py); this mode prices the
  host-side overhead — the record's value is obs-on tok/s,
  ``vs_baseline`` the on/off ratio (the committed artifact gates it
  >= 0.95), and extras carry the trace summary (spans, ring depth).
  ``--trace-out FILE`` additionally writes the obs-on replay's ring +
  spans as Chrome trace-event JSON loadable in Perfetto
  (tools/trace_view.py renders; also accepted standalone with the
  default trace).

Modes:
  python tools/serve_bench.py --synthetic              # tiny cfg, CPU-ok
  python tools/serve_bench.py --synthetic --model llama
  python tools/serve_bench.py --synthetic --prefix-share
  python tools/serve_bench.py --synthetic --prefix-cache off   # A/B
  python tools/serve_bench.py --synthetic --spec-trace         # A/B
  python tools/serve_bench.py --synthetic --long-trace         # A/B
  python tools/serve_bench.py --synthetic --spec on    # default trace
  python tools/serve_bench.py --model gpt2             # 124M random init
  python tools/serve_bench.py --synthetic --steps 3    # smoke (CI runs
      this — tests/test_serve_bench.py — so the CLI can never rot)

``--steps N`` caps the engine-step budget (unfinished requests are
reported, not an error); default runs the trace to completion.
``--out FILE`` appends the record to an artifacts JSON list the same
way bench.py artifacts are kept (bench.last_known_result scans them —
the serve bench gets the same staleness story as the training bench).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def build_model(args, params=None):
    """(family, params) for the bench config — separate from
    build_engine so the --lora-trace branch can materialise the base
    params ONCE (for adapter construction and merged baselines)
    without allocating a throwaway engine's KV pool."""
    import jax

    from quintnet_tpu.serve import gpt2_family, llama_family

    # synthetic-config overrides (--n-layer & co): the default tiny
    # model is too small for prefill compute to matter — the
    # prefix-share acceptance run uses a taller/wider synthetic config
    # so the cached-vs-recomputed prefill difference is the signal
    syn_kw = {k: v for k, v in (
        ("n_layer", args.n_layer), ("n_embd", args.n_embd),
        ("n_head", args.n_head), ("n_positions", args.n_positions),
        ("vocab_size", args.vocab_size)) if v is not None}
    if getattr(args, "experts", 0):
        # --experts N makes the synthetic config an MoE one (both
        # config families carry the same field names)
        syn_kw.update(n_experts=args.experts,
                      expert_top_k=args.expert_top_k,
                      capacity_factor=args.capacity_factor,
                      expert_capacity=args.expert_capacity)
    if args.model == "gpt2":
        from quintnet_tpu.models.gpt2 import GPT2Config, gpt2_init

        cfg = (GPT2Config.tiny(**{"n_layer": 2, **syn_kw})
               if args.synthetic else GPT2Config.base())
        if params is None:
            params = gpt2_init(jax.random.key(args.seed), cfg)
        family = gpt2_family(cfg)
    elif args.model == "llama":
        from quintnet_tpu.models.llama import LlamaConfig, llama_init

        lkw = {{"n_layer": "n_layers", "n_embd": "dim",
                "n_head": "n_heads", "n_positions": "n_positions",
                "vocab_size": "vocab_size",
                "n_experts": "n_experts", "expert_top_k": "expert_top_k",
                "capacity_factor": "capacity_factor",
                "expert_capacity": "expert_capacity"}[k]: v
               for k, v in syn_kw.items()}
        cfg = (LlamaConfig.tiny(**{"n_layers": 2, **lkw})
               if args.synthetic else LlamaConfig())
        if params is None:
            params = llama_init(jax.random.key(args.seed), cfg)
        family = llama_family(cfg)
    else:
        raise SystemExit(f"unknown --model {args.model}")
    return family, params


def build_engine(args, *, prefix_cache: bool, spec: bool = False,
                 params=None, adapters=None, max_seq=None,
                 prefill_len=None, chunked_prefill: bool = False,
                 prefill_chunk_budget=None, kv_dtype=None,
                 num_blocks=None, attn_kernel=None,
                 kv_tier_bytes: int = 0,
                 kv_tier_promote_budget_bytes=None,
                 weights_dtype=None):
    from quintnet_tpu.serve import ServeEngine, SpecConfig

    family, params = build_model(args, params=params)
    max_prompt = (args.shared_prefix + args.max_tail
                  if args.prefix_share or args.kv_capacity
                  or args.tier_trace
                  else args.max_prompt)
    if max_seq is None:
        max_seq = min(max_prompt + args.max_new, family.max_positions)
    return ServeEngine(
        family, params, max_slots=args.slots, block_size=args.block_size,
        num_blocks=(num_blocks if num_blocks is not None
                    else args.num_blocks),
        max_seq_len=max_seq,
        prefill_len=prefill_len, chunked_prefill=chunked_prefill,
        prefill_chunk_budget=prefill_chunk_budget,
        eos_token_id=args.eos, temperature=args.temperature,
        policy=args.policy, prefix_cache=prefix_cache,
        kv_dtype=kv_dtype if kv_dtype is not None else args.kv_dtype,
        weights_dtype=(weights_dtype if weights_dtype is not None
                       else args.weights_dtype),
        attn_kernel=(attn_kernel if attn_kernel is not None
                     else args.kernel),
        spec=SpecConfig(max_draft=args.max_draft) if spec else None,
        adapters=adapters, lora_max_rank=args.lora_rank,
        kv_tier_bytes=kv_tier_bytes,
        kv_tier_promote_budget_bytes=kv_tier_promote_budget_bytes)


def poisson_arrivals(rng, n: int, rate: float):
    t, out = 0.0, []
    for _ in range(n):
        t += rng.exponential(1.0 / rate)
        out.append(int(t))
    return out


def poisson_trace(args, vocab_size: int):
    """[(arrival_step, prompt, max_new)] sorted by arrival."""
    import numpy as np

    rng = np.random.default_rng(args.seed)
    arrivals = poisson_arrivals(rng, args.requests, args.rate)
    trace = []
    for t in arrivals:
        n = int(rng.integers(args.min_prompt, args.max_prompt + 1))
        prompt = rng.integers(0, vocab_size, (n,)).astype(np.int32)
        trace.append((t, prompt, args.max_new))
    return trace


def repetitive_trace(args, vocab_size: int):
    """Greedy-friendly prompts for the speculation A/B. ``--pattern N``
    tiles a short random per-request pattern to the sampled prompt
    length (templated/repetitive text); ``--pattern 0`` keeps prompts
    random — with greedy sampling the draftable repetition then comes
    from the CONTINUATIONS (greedy decoding settles into repetitive
    runs/cycles, which is exactly the structure prompt-lookup drafts
    from — long ``--max-new`` lets that phase dominate)."""
    import numpy as np

    rng = np.random.default_rng(args.seed)
    arrivals = poisson_arrivals(rng, args.requests, args.rate)
    trace = []
    for t in arrivals:
        n = int(rng.integers(args.min_prompt, args.max_prompt + 1))
        if args.pattern > 0:
            pat = rng.integers(0, vocab_size,
                               (args.pattern,)).astype(np.int32)
            prompt = np.tile(pat, -(-n // args.pattern))[:n]
        else:
            prompt = rng.integers(0, vocab_size, (n,)).astype(np.int32)
        trace.append((t, prompt, args.max_new))
    return trace


def prefix_share_trace(args, vocab_size: int):
    """N users x one shared system prompt + short unique tails."""
    import numpy as np

    rng = np.random.default_rng(args.seed)
    shared = rng.integers(0, vocab_size,
                          (args.shared_prefix,)).astype(np.int32)
    arrivals = poisson_arrivals(rng, args.requests, args.rate)
    trace = []
    for t in arrivals:
        n = int(rng.integers(args.min_tail, args.max_tail + 1))
        tail = rng.integers(0, vocab_size, (n,)).astype(np.int32)
        trace.append((t, np.concatenate([shared, tail]), args.max_new))
    return trace


def tier_trace_gen(args, vocab_size: int):
    """MANY-TENANT prefix churn for the tiered-KV A/B: P distinct
    system prompts visited round-robin with unique tails,
    ``--tier-repeats`` visits each. P is sized so the prefix set
    costs ``--tier-prefix-ratio`` x the usable device pool (or pinned
    by ``--tier-prefixes``) — the revisit gap is P whole prefixes, so
    by the time prefix j comes around again the device LRU has
    destroyed its chain: the tiered engine serves the revisit from
    host RAM, the evict-only engine re-prefills from scratch.
    Resolves ``args.tier_prefixes`` to the chosen P as a side effect
    so the run() branch can report it. [(t, prompt, max_new)]"""
    import numpy as np

    rng = np.random.default_rng(args.seed)
    blocks_per_prefix = -(-args.shared_prefix // args.block_size)
    usable = max(args.num_blocks - 1, 1)  # minus the reserved null
    if not args.tier_prefixes:
        args.tier_prefixes = max(2, round(
            args.tier_prefix_ratio * usable / blocks_per_prefix))
    prefixes = [rng.integers(0, vocab_size,
                             (args.shared_prefix,)).astype(np.int32)
                for _ in range(args.tier_prefixes)]
    n_requests = args.tier_repeats * args.tier_prefixes
    arrivals = poisson_arrivals(rng, n_requests, args.rate)
    trace = []
    for j, t in enumerate(arrivals):
        n = int(rng.integers(args.min_tail, args.max_tail + 1))
        tail = rng.integers(0, vocab_size, (n,)).astype(np.int32)
        trace.append(
            (t, np.concatenate([prefixes[j % args.tier_prefixes], tail]),
             args.max_new))
    return trace


def hot_expert_trace(args, vocab_size: int):
    """Skewed-routing traffic for the ``--moe-trace`` A/B: every
    request tiles the SAME short token pattern to its sampled prompt
    length, so the router scores the same few hidden states over and
    over — routed demand concentrates on that pattern's favourite
    experts (and the greedy continuations settle into repetitive
    cycles, concentrating decode-time routing the same way). The
    diverse side of the A/B is the plain Poisson trace: random
    prompts spread demand across the expert set."""
    import numpy as np

    rng = np.random.default_rng(args.seed)
    pat = rng.integers(0, vocab_size,
                       (max(args.pattern, 1),)).astype(np.int32)
    arrivals = poisson_arrivals(rng, args.requests, args.rate)
    trace = []
    for t in arrivals:
        n = int(rng.integers(args.min_prompt, args.max_prompt + 1))
        trace.append((t, np.tile(pat, -(-n // len(pat)))[:n],
                      args.max_new))
    return trace


def long_trace(args, vocab_size: int):
    """The decode-starvation workload: the default short Poisson mix
    PLUS ``--long-prompts`` document-length prompts arriving while the
    shorts are mid-decode. Entries are (t, prompt, max_new, is_long) —
    the replayer uses the flag to carve out the window during which a
    long prompt is being prefilled (that window is where a monolithic
    prefill stalls every concurrent stream and a chunked one does
    not)."""
    import numpy as np

    rng = np.random.default_rng(args.seed)
    trace = [(t, p, m, False)
             for (t, p, m) in poisson_trace(args, vocab_size)]
    for i in range(args.long_prompts):
        t = 2 + i * args.long_spacing
        p = rng.integers(0, vocab_size,
                         (args.long_prompt,)).astype(np.int32)
        trace.append((t, p, args.max_new, True))
    return sorted(trace, key=lambda e: e[0])


def replay_long(engine, trace, args) -> dict:
    """Like :func:`replay`, but per-step instrumented: wall time and
    decode tokens are additionally accumulated over the steps during
    which some long prompt is submitted but has not yet produced its
    first token — the long-prefill window. ``decode tokens / window
    wall`` is the number the chunked-vs-monolithic A/B is about:
    how fast everyone ELSE's streams move while a document is being
    read in. Each step blocks on the pool before reading the clock so
    the per-step wall measures device work, not dispatch."""
    import time

    import jax

    engine.warmup()
    engine.metrics = type(engine.metrics)(clock=engine.clock)

    submitted = 0
    step = 0
    long_rids = []
    win_wall = 0.0
    win_decode = 0
    t0 = time.perf_counter()
    while submitted < len(trace) or engine.has_work:
        if args.steps is not None and step >= args.steps:
            break
        while submitted < len(trace) and trace[submitted][0] <= step:
            _, prompt, max_new, is_long = trace[submitted]
            rid = engine.submit(prompt, max_new)
            if is_long:
                long_rids.append(rid)
            submitted += 1
        in_window = any(
            engine.request(r).first_token_time is None
            for r in long_rids)
        d0 = engine.metrics.decode_tokens
        s0 = time.perf_counter()
        engine.step()
        jax.block_until_ready(engine.pool.caches())
        dt = time.perf_counter() - s0
        if in_window:
            win_wall += dt
            win_decode += engine.metrics.decode_tokens - d0
        step += 1
    # every step blocked on the pool above; drain once more so the
    # whole-replay wall measures device work, not dispatch (QT106)
    jax.block_until_ready(engine.pool.caches())
    wall = time.perf_counter() - t0

    s = engine.metrics.summary()
    s["wall_s"] = round(wall, 4)
    s["tokens_per_sec"] = (round(s["gen_tokens"] / wall, 2) if wall > 0
                           else 0.0)
    s["submitted"] = submitted
    s["long_window_wall_s"] = round(win_wall, 4)
    s["long_window_decode_tokens"] = win_decode
    s["decode_tps_during_long_prefill"] = (
        round(win_decode / win_wall, 2) if win_wall > 0 else 0.0)
    return s


def lora_trace(args, vocab_size: int):
    """The default Poisson trace with each request bound round-robin
    to one of ``--adapters`` tenants: [(t, prompt, max_new, aid)]."""
    trace = poisson_trace(args, vocab_size)
    return [(t, p, m, f"tenant-{i % args.adapters}")
            for i, (t, p, m) in enumerate(trace)]


def make_adapters(args, params, tmpdir: str):
    """--adapters trained LoRA variants of the base model, each saved
    through the real safetensors path (the registry's input contract).
    Returns {adapter_id: (merged_params, path)} — merged weights feed
    the dedicated-baseline engines."""
    import os

    import jax
    import numpy as np

    from quintnet_tpu.models.lora import (LoRAConfig, lora_init,
                                          lora_merge_tree, save_lora)

    out = {}
    for i in range(args.adapters):
        cfg = LoRAConfig(rank=args.lora_rank, alpha=2.0 * args.lora_rank)
        lora = lora_init(jax.random.key(1000 + i), params["blocks"], cfg)
        lora = jax.tree.map(
            lambda l, s=i: l + 0.02 * jax.random.normal(
                jax.random.key(2000 + s), l.shape), lora)
        path = os.path.join(tmpdir, f"tenant-{i}.safetensors")
        save_lora(lora, cfg, path)
        out[f"tenant-{i}"] = (lora_merge_tree(params, lora, cfg), path)
    return out


def replay(engine, trace, args) -> dict:
    """Warm up (compile EVERY prefill bucket + the decode step OUTSIDE
    the timed window — engine.warmup() invokes each program against
    the null block directly, so no bucket can be missed), reset the
    ledgers, replay the trace, return the summary with a
    device-drained wall clock."""
    import time

    import jax

    engine.warmup()
    engine.metrics = type(engine.metrics)(clock=engine.clock)

    submitted = 0
    step = 0
    t0 = time.perf_counter()
    while submitted < len(trace) or engine.has_work:
        if args.steps is not None and step >= args.steps:
            break
        while submitted < len(trace) and trace[submitted][0] <= step:
            _, prompt, max_new, *rest = trace[submitted]
            # --lora-trace entries carry the tenant binding as a 4th
            # element (None rides the base model)
            engine.submit(prompt, max_new,
                          adapter_id=rest[0] if rest else None)
            submitted += 1
        engine.step()
        step += 1
    # the throughput wall clock must cover DEVICE work, not dispatch:
    # drain the in-flight pool writes before reading the clock (the
    # metrics' own wall starts at the first step's completion, which
    # also silently excluded the first prefill+decode from the window)
    jax.block_until_ready(engine.pool.caches())
    wall = time.perf_counter() - t0

    s = engine.metrics.summary()
    s["wall_s"] = round(wall, 4)
    s["tokens_per_sec"] = (round(s["gen_tokens"] / wall, 2) if wall > 0
                           else 0.0)
    s["submitted"] = submitted
    return s


def _common_extras(args, s: dict) -> dict:
    return {
        "ttft_p50_s": s["ttft_s"]["p50"],
        "ttft_p95_s": s["ttft_s"]["p95"],
        "latency_p50_s": s["latency_s"]["p50"],
        "latency_p95_s": s["latency_s"]["p95"],
        "peak_kv_utilization": s["peak_kv_utilization"],
        "kv_pool_bytes": s["kv_pool_bytes"],
        "kv_bytes_per_token": s["kv_bytes_per_token"],
        "peak_running": s["peak_running"],
        "steps": s["steps"],
        "requests": args.requests,
        "submitted": s["submitted"],
        "finished": s["finished"],
        "preempted": s["preempted"],
        "decode_tokens": s["decode_tokens"],
        "prefill_tokens": s["prefill_tokens"],
        "prefix_hit_tokens": s["prefix_hit_tokens"],
        "prefill_tokens_saved": s["prefill_tokens_saved"],
        "prefix_hit_rate": s["prefix_hit_rate"],
        "gen_tokens": s["gen_tokens"],
        "decode_steps": s["decode_steps"],
        "tokens_per_decode_step": s["tokens_per_decode_step"],
        "wall_s": s["wall_s"],
        "model": args.model,
        "synthetic": bool(args.synthetic),
        "slots": args.slots,
        "block_size": args.block_size,
        "num_blocks": args.num_blocks,
        "rate": args.rate,
    }


def _arm_obs(engine, ring_capacity: int = 4096):
    """Attach the flight recorder to a bench engine; returns
    (tracer, recorder)."""
    from quintnet_tpu.obs import StepRecorder, Tracer

    engine.tracer = Tracer(clock=engine.clock, max_traces=4096)
    engine.recorder = StepRecorder(capacity=ring_capacity,
                                   clock=engine.clock)
    return engine.tracer, engine.recorder


def _write_trace_out(path: str, tracer, recorder) -> dict:
    """Write the replay's ring + spans as validated Chrome trace-event
    JSON (Perfetto-loadable); returns the trace summary extras."""
    import json as _json

    from tools.trace_view import chrome_trace, validate_chrome_trace

    ring = recorder.snapshot()
    traces = tracer.snapshot()
    trace = chrome_trace(ring, traces, label="serve_bench")
    n_events = validate_chrome_trace(trace)
    with open(path, "w") as f:
        _json.dump(trace, f)
    return {"trace_out": path, "trace_events": n_events}


def _obs_summary(tracer, recorder) -> dict:
    snap = tracer.snapshot()
    return {
        "obs_traces": len(snap),
        "obs_spans": sum(len(v) for v in snap.values()),
        "obs_ring_steps": len(recorder),
        "obs_ring_total": recorder.total,
    }


def run(args) -> dict:
    tag = "tiny" if args.synthetic else "full"

    if args.obs_ab:
        # observability overhead A/B over the SAME default trace:
        # flight recorder armed vs off. Tokens are contractually
        # bit-identical either way (tests/test_obs.py); what this
        # prices is the host-side span/ring bookkeeping.
        prefix_cache = args.prefix_cache == "on"
        # a throwaway UNTIMED replay first: process-level warm-up
        # (first-touch jit plumbing, allocator growth) is several
        # times the effect being measured and would otherwise be
        # charged entirely to whichever side runs first. After it,
        # obs-on is timed before obs-off — any residual ordering
        # advantage goes to the OFF side, keeping the committed
        # >= 0.95 ratio conservative.
        eng_warm = build_engine(args, prefix_cache=prefix_cache)
        trace = poisson_trace(args, eng_warm.family.cfg.vocab_size)
        replay(eng_warm, trace, args)
        del eng_warm
        eng_on = build_engine(args, prefix_cache=prefix_cache)
        tracer, recorder = _arm_obs(eng_on)
        s_on = replay(eng_on, trace, args)
        eng_off = build_engine(args, prefix_cache=prefix_cache)
        s_off = replay(eng_off, trace, args)
        extras = _common_extras(args, s_on)
        extras.update(_obs_summary(tracer, recorder))
        ratio = (round(s_on["tokens_per_sec"]
                       / s_off["tokens_per_sec"], 3)
                 if s_off["tokens_per_sec"] else 0.0)
        extras.update({
            "obs_ab": True,
            "obs_off_tokens_per_sec": s_off["tokens_per_sec"],
            "obs_off_wall_s": s_off["wall_s"],
            "obs_off_gen_tokens": s_off["gen_tokens"],
            # the overhead gate: obs-on throughput / obs-off (the
            # committed artifact pins >= 0.95)
            "obs_on_ratio": ratio,
        })
        if args.trace_out:
            extras.update(_write_trace_out(args.trace_out, tracer,
                                           recorder))
        return {
            "metric": f"serve_{args.model}_{tag}_obs_tokens_per_sec",
            "value": s_on["tokens_per_sec"],
            "unit": "tok/s",
            "vs_baseline": ratio,
            "rc": 0,
            "extras": extras,
        }

    if args.kernel_ab:
        # fused-kernel A/B over the SAME default trace. Two committed
        # signals, both wall-noise-free: (1) every request's token
        # stream is IDENTICAL across backends (the kernel is
        # bit-parity-pinned against the gathered-view oracle), and
        # (2) the jaxpr auditor proves the pallas programs issue ZERO
        # full-row block-table gathers where the xla ones issue 2 (4
        # under a scaled KV policy) per layer — the structural
        # HBM-traffic win the kernel exists for. CPU wall clocks ride
        # along for the record but are NOT gated: off-TPU the kernel
        # runs in the Pallas interpreter, which prices emulation.
        import jax as _jax
        import jax.numpy as _jnp

        from quintnet_tpu.analysis import gathered_view_gathers

        prefix_cache = args.prefix_cache == "on"
        spec = args.spec == "on"
        eng_warm = build_engine(args, prefix_cache=prefix_cache,
                                spec=spec, attn_kernel="xla")
        trace = poisson_trace(args, eng_warm.family.cfg.vocab_size)
        replay(eng_warm, trace, args)   # process warm-up, untimed
        del eng_warm
        eng_p = build_engine(args, prefix_cache=prefix_cache,
                             spec=spec, attn_kernel="pallas")
        s_p = replay(eng_p, trace, args)
        eng_x = build_engine(args, prefix_cache=prefix_cache,
                             spec=spec, attn_kernel="xla")
        s_x = replay(eng_x, trace, args)
        # token-identity is THE signal this mode exists to report, so
        # a divergence (different lengths, an unfinished or errored
        # request on one side) must come back as token_identical=false
        # with a count — never a traceback
        n = min(s_p["finished"], s_x["finished"])
        mismatched = 0
        for r in range(n):
            try:
                a, b = eng_p.result(r), eng_x.result(r)
            except Exception:
                mismatched += 1
                continue
            if a.shape != b.shape or not (a == b).all():
                mismatched += 1
        token_identical = (n == len(trace) and mismatched == 0)

        def _gathers(eng):
            caches = eng.pool.caches()
            dargs = (eng.params, *caches, _jnp.asarray(eng._tok),
                     _jnp.asarray(eng._pos), _jnp.asarray(eng._tables),
                     _jnp.asarray(eng._key_data))
            return gathered_view_gathers(
                eng._decode.fn, *dargs,
                num_blocks=eng.pool.num_blocks,
                table_width=eng.table_width)

        gx, gp = _gathers(eng_x), _gathers(eng_p)
        extras = _common_extras(args, s_p)
        ratio = (round(s_p["tokens_per_sec"] / s_x["tokens_per_sec"], 3)
                 if s_x["tokens_per_sec"] else 0.0)
        extras.update({
            "kernel_ab": True,
            "attn_kernel": "pallas",
            "kv_dtype": args.kv_dtype,
            "token_identical": bool(token_identical),
            "compared_requests": int(n),
            "mismatched_requests": int(mismatched),
            # THE structural gate (CI-pinned): full-row block-table
            # gathers per decode program
            "xla_gathered_view_gathers": int(gx),
            "pallas_gathered_view_gathers": int(gp),
            "xla_tokens_per_sec": s_x["tokens_per_sec"],
            "xla_wall_s": s_x["wall_s"],
            "xla_finished": s_x["finished"],
            "cpu_interpret_mode": _jax.default_backend() != "tpu",
            "speedup_vs_xla": ratio,
        })
        return {
            "metric": f"serve_{args.model}_{tag}_kernel_tokens_per_sec",
            "value": s_p["tokens_per_sec"],
            "unit": "tok/s",
            "vs_baseline": ratio,
            "rc": 0,
            "extras": extras,
        }

    if args.kv_capacity:
        # equal-pool-BYTES capacity A/B over the shared-prefix trace
        # (quantized KV, serve/kv_quant.py): the f32 reference keeps
        # --num-blocks; the --kv-dtype side gets every block the SAME
        # byte budget buys (int8 blocks cost ~1/4, so ~4x blocks).
        # Capacity is concurrency: at equal bytes the quantized pool
        # should admit without preempting and retain the shared-prefix
        # chain (higher hit rate) where the f32 pool thrashes.
        from quintnet_tpu.serve.kv_quant import make_policy

        family, params = build_model(args)
        dims = dict(n_layers=family.n_layers,
                    n_kv_heads=family.n_kv_heads,
                    head_dim=family.head_dim, block_size=args.block_size)
        q_name = args.kv_dtype if args.kv_dtype != "f32" else "int8"
        byte_budget = args.num_blocks * make_policy(
            "f32").bytes_per_block(**dims)
        q_blocks = byte_budget // make_policy(q_name).bytes_per_block(
            **dims)
        eng_f = build_engine(args, prefix_cache=True, params=params,
                             kv_dtype="f32")
        trace = prefix_share_trace(args, eng_f.family.cfg.vocab_size)
        s_f = replay(eng_f, trace, args)
        eng_q = build_engine(args, prefix_cache=True, params=params,
                             kv_dtype=q_name, num_blocks=int(q_blocks))
        s_q = replay(eng_q, trace, args)
        extras = _common_extras(args, s_q)
        ratio = round((q_blocks - 1) / max(args.num_blocks - 1, 1), 3)
        extras.update({
            "kv_capacity": True,
            "kv_dtype": q_name,
            "shared_prefix": args.shared_prefix,
            "pool_bytes_budget": int(byte_budget),
            "f32_num_blocks": args.num_blocks,
            "q_num_blocks": int(q_blocks),
            "f32_usable_blocks": args.num_blocks - 1,
            "q_usable_blocks": int(q_blocks) - 1,
            # THE equal-bytes capacity signal (usable = minus the
            # reserved null block)
            "usable_blocks_ratio": ratio,
            "f32_pool_bytes": s_f["kv_pool_bytes"],
            "q_pool_bytes": s_q["kv_pool_bytes"],
            "q_kv_bytes_per_token": s_q["kv_bytes_per_token"],
            "f32_kv_bytes_per_token": s_f["kv_bytes_per_token"],
            "f32_tokens_per_sec": s_f["tokens_per_sec"],
            "f32_wall_s": s_f["wall_s"],
            # the structural win at equal bytes: fewer preemptions,
            # fewer cache evictions, higher hit rate, lower peak
            # pressure — concurrency the f32 pool could not hold
            "f32_preempted": s_f["preempted"],
            "q_preempted": s_q["preempted"],
            # NOTE hit-rate/prefill comparisons are confounded under
            # pressure, in BOTH directions: an f32 preemption-resume
            # re-prefills through its own published chain (extra
            # booked hits), and the starved f32 queue serializes
            # admissions until retired requests have PUBLISHED the
            # shared chain (late admission sees a warmer cache, while
            # the quantized side's higher concurrency admits before
            # the first publish). The unconfounded cache-retention
            # signal is the EVICTION count: evicted chains are future
            # hits destroyed, and only the starved pool evicts.
            "f32_prefix_hit_rate": s_f["prefix_hit_rate"],
            "q_prefix_hit_rate": s_q["prefix_hit_rate"],
            "f32_prefill_tokens": s_f["prefill_tokens"],
            "f32_prefix_hit_tokens": s_f["prefix_hit_tokens"],
            "f32_cache_evictions": eng_f.pool.cache_evictions,
            "q_cache_evictions": eng_q.pool.cache_evictions,
            "f32_peak_kv_utilization": s_f["peak_kv_utilization"],
            "q_peak_kv_utilization": s_q["peak_kv_utilization"],
            "f32_peak_running": s_f["peak_running"],
            "q_peak_running": s_q["peak_running"],
            "f32_finished": s_f["finished"],
        })
        return {
            "metric": f"serve_{args.model}_{tag}_kvcap_tokens_per_sec",
            "value": s_q["tokens_per_sec"],
            "unit": "tok/s",
            "vs_baseline": ratio,
            "rc": 0,
            "extras": extras,
        }

    if args.weights_ab:
        # weight-quant A/B (serve/weight_quant.py) over the SAME
        # default Poisson trace: f32 weights vs the --weights-dtype
        # packed side, everything else equal (same init, same KV pool,
        # same arrivals). Decode at serving batch sizes is
        # weight-bandwidth-bound, so the committed signals are
        # STRUCTURAL: the targeted-node byte ratio (~3.9x for int8
        # before the per-channel-scale overhead) and the paged
        # teacher-forced NLL delta under original vs packed params —
        # CPU walls are recorded but never the gate (off-TPU the
        # bandwidth saving prices emulation, not the policy).
        import jax as _jax
        import numpy as np

        from quintnet_tpu.serve.kv_pool import KVPool
        from quintnet_tpu.serve.kv_quant import paged_eval_nll
        from quintnet_tpu.serve.weight_quant import (make_weight_policy,
                                                     present_targets,
                                                     quantize_params)

        family, params = build_model(args)
        q_name = (args.weights_dtype if args.weights_dtype != "f32"
                  else "int8")
        prefix_cache = args.prefix_cache == "on"
        eng_f = build_engine(args, prefix_cache=prefix_cache,
                             params=params, weights_dtype="f32")
        trace = poisson_trace(args, eng_f.family.cfg.vocab_size)
        s_f = replay(eng_f, trace, args)
        eng_q = build_engine(args, prefix_cache=prefix_cache,
                             params=params, weights_dtype=q_name)
        s_q = replay(eng_q, trace, args)

        # quality: the SAME held-out rows scored through a fresh f32
        # KV pool under both param trees — the delta isolates the
        # weight rounding (KV layout held fixed)
        rng = np.random.default_rng(args.seed + 1)
        rows = rng.integers(
            0, family.cfg.vocab_size,
            (4, min(24, family.max_positions - 1))).astype(np.int32)
        targets = present_targets(params, family.weight_targets)
        qparams = quantize_params(params, targets,
                                  make_weight_policy(q_name))

        def _fresh_pool():
            return KVPool(n_layers=family.n_layers,
                          n_kv_heads=family.n_kv_heads,
                          head_dim=family.head_dim,
                          block_size=args.block_size,
                          num_blocks=args.num_blocks)

        nll_f = paged_eval_nll(family, params, _fresh_pool(), rows)
        nll_q = paged_eval_nll(family, qparams, _fresh_pool(), rows)

        extras = _common_extras(args, s_q)
        ratio = (round(eng_f.weight_bytes / eng_q.weight_bytes, 3)
                 if eng_q.weight_bytes else 0.0)
        extras.update({
            "weights_ab": True,
            "weights_dtype": q_name,
            "f32_weight_bytes": int(eng_f.weight_bytes),
            "q_weight_bytes": int(eng_q.weight_bytes),
            # THE structural gate (CI-pinned): targeted-node bytes,
            # f32 over packed — scale overhead included
            "weight_bytes_ratio": ratio,
            "eval_nll_f32": round(float(nll_f), 6),
            "eval_nll_q": round(float(nll_q), 6),
            "eval_nll_delta": round(float(nll_q - nll_f), 6),
            "f32_tokens_per_sec": s_f["tokens_per_sec"],
            "f32_wall_s": s_f["wall_s"],
            "f32_finished": s_f["finished"],
            "cpu_wall_not_gated": _jax.default_backend() != "tpu",
        })
        return {
            "metric": (f"serve_{args.model}_{tag}"
                       "_weights_tokens_per_sec"),
            "value": s_q["tokens_per_sec"],
            "unit": "tok/s",
            "vs_baseline": (round(s_q["tokens_per_sec"]
                                  / s_f["tokens_per_sec"], 3)
                            if s_f["tokens_per_sec"] else 0.0),
            "rc": 0,
            "extras": extras,
        }

    if args.tier_trace:
        # tiered-KV A/B (serve/kv_tier.py) over the many-tenant churn
        # trace: the SAME engine twice — host tier armed vs evict-only
        # — so every delta is the tier. The host budget defaults to 4x
        # the device pool's bytes (the spill-to-abundant-host-RAM
        # regime the tier is for); --tier-bytes pins it.
        from quintnet_tpu.serve.kv_quant import make_policy

        family, params = build_model(args)
        dims = dict(n_layers=family.n_layers,
                    n_kv_heads=family.n_kv_heads,
                    head_dim=family.head_dim, block_size=args.block_size)
        per_block = make_policy(args.kv_dtype).bytes_per_block(**dims)
        tier_bytes = int(args.tier_bytes
                         or 4 * args.num_blocks * per_block)
        promote_bytes = (args.tier_promote_blocks * per_block
                         if args.tier_promote_blocks else None)
        eng_t = build_engine(args, prefix_cache=True, params=params,
                             kv_tier_bytes=tier_bytes,
                             kv_tier_promote_budget_bytes=promote_bytes)
        trace = tier_trace_gen(args, eng_t.family.cfg.vocab_size)
        s_t = replay(eng_t, trace, args)
        eng_e = build_engine(args, prefix_cache=True, params=params)
        s_e = replay(eng_e, trace, args)
        # THE structural gate: a demotion copy must never ride a plain
        # decode dispatch — the tier's whole latency contract
        assert s_t["decode_blocked_demotions"] == 0, \
            "demotion blocked a decode step"
        extras = _common_extras(args, s_t)
        ratio = round(s_t["tokens_per_sec"]
                      / max(s_e["tokens_per_sec"], 1e-9), 3)
        extras.update({
            "tier_trace": True,
            "kv_dtype": args.kv_dtype,
            "shared_prefix": args.shared_prefix,
            "tier_prefixes": args.tier_prefixes,
            "tier_repeats": args.tier_repeats,
            "tier_byte_budget": tier_bytes,
            "tier_promote_blocks": args.tier_promote_blocks,
            "requests": len(trace),
            # the tier ledger (tiered side)
            "kv_demotions": s_t["kv_demotions"],
            "kv_promotions": s_t["kv_promotions"],
            "kv_host_evictions": s_t["kv_host_evictions"],
            "host_hit_tokens": s_t["host_hit_tokens"],
            "host_hit_rate": s_t["host_hit_rate"],
            "host_tier_bytes": s_t["host_tier_bytes"],
            "decode_blocked_demotions": s_t["decode_blocked_demotions"],
            "kv_cache_evictions": s_t["kv_cache_evictions"],
            # the A/B: a revisited prefix is a host hit on the tiered
            # side (promotion memcpy + tail prefill) and a cold
            # re-prefill on the evict-only side — hit rate and TTFT
            # are the committed wins
            "warm_hit_rate": s_t["prefix_hit_rate"],
            "evict_only_hit_rate": s_e["prefix_hit_rate"],
            "evict_only_ttft_p50_s": s_e["ttft_s"]["p50"],
            "evict_only_ttft_p95_s": s_e["ttft_s"]["p95"],
            "evict_only_tokens_per_sec": s_e["tokens_per_sec"],
            "evict_only_wall_s": s_e["wall_s"],
            "evict_only_prefill_tokens": s_e["prefill_tokens"],
            "evict_only_cache_evictions": s_e["kv_cache_evictions"],
            "evict_only_finished": s_e["finished"],
            "evict_only_preempted": s_e["preempted"],
            "speedup_vs_evict_only": ratio,
        })
        return {
            "metric": f"serve_{args.model}_{tag}_tier_tokens_per_sec",
            "value": s_t["tokens_per_sec"],
            "unit": "tok/s",
            "vs_baseline": ratio,
            "rc": 0,
            "extras": extras,
        }

    if args.moe_trace:
        # MoE routing A/B over the SAME engine config: a DIVERSE trace
        # (random prompts spread routed demand over the expert set) vs
        # a HOT-EXPERT trace (every request tiles one shared pattern —
        # skewed routing concentrates demand and drives capacity
        # drops). Wall clocks are reported, never gated; the gates are
        # structural: the routing ledger must account exactly and the
        # compile bound must not move (MoE adds zero programs).
        if not args.synthetic:
            raise SystemExit("--moe-trace needs --synthetic (the MoE "
                             "fields extend the tiny config)")
        family, params = build_model(args)
        eng_d = build_engine(args, prefix_cache=True, params=params)
        trace_d = poisson_trace(args, family.cfg.vocab_size)
        s_d = replay(eng_d, trace_d, args)
        eng_h = build_engine(args, prefix_cache=True, params=params)
        trace_h = hot_expert_trace(args, family.cfg.vocab_size)
        s_h = replay(eng_h, trace_h, args)
        for s in (s_d, s_h):
            # the ledger reads program outputs — it must account
            # exactly: per-expert demand sums to the routed total,
            # and drops never exceed it
            assert (sum(s["moe_expert_tokens"].values())
                    == s["moe_routed_tokens"]), "routing ledger leak"
            assert 0 <= s["moe_dropped_tokens"] <= s["moe_routed_tokens"]
        for eng in (eng_d, eng_h):
            # warmup compiles every ladder bucket once; MoE must not
            # add a single program beyond that bound
            eng.assert_compile_count(prefill=len(eng._prefills))
        extras = _common_extras(args, s_h)
        ratio = round(s_h["tokens_per_sec"]
                      / max(s_d["tokens_per_sec"], 1e-9), 3)
        extras.update({
            "moe_trace": True,
            "experts": args.experts,
            "expert_top_k": args.expert_top_k,
            "capacity_factor": args.capacity_factor,
            "expert_capacity": args.expert_capacity,
            "pattern": args.pattern,
            "compile_counts": eng_h.compile_stats(),
            # hot (skewed-routing) side — the committed skew evidence
            "hot_expert_skew": s_h["moe_expert_skew"],
            "hot_drop_rate": s_h["moe_drop_rate"],
            "hot_routed_tokens": s_h["moe_routed_tokens"],
            "hot_dropped_tokens": s_h["moe_dropped_tokens"],
            "hot_router_entropy": s_h["moe_router_entropy"],
            "hot_expert_tokens": s_h["moe_expert_tokens"],
            # diverse side — the balanced baseline
            "diverse_expert_skew": s_d["moe_expert_skew"],
            "diverse_drop_rate": s_d["moe_drop_rate"],
            "diverse_routed_tokens": s_d["moe_routed_tokens"],
            "diverse_dropped_tokens": s_d["moe_dropped_tokens"],
            "diverse_router_entropy": s_d["moe_router_entropy"],
            "diverse_expert_tokens": s_d["moe_expert_tokens"],
            "diverse_tokens_per_sec": s_d["tokens_per_sec"],
            "diverse_wall_s": s_d["wall_s"],
            "hot_vs_diverse": ratio,
        })
        return {
            "metric": f"serve_{args.model}_{tag}_moe_tokens_per_sec",
            "value": s_h["tokens_per_sec"],
            "unit": "tok/s",
            "vs_baseline": ratio,
            "rc": 0,
            "extras": extras,
        }

    if args.prefix_share:
        # A/B over the SAME shared-prefix trace: cache-on vs cache-off
        eng_on = build_engine(args, prefix_cache=True)
        trace = prefix_share_trace(args, eng_on.family.cfg.vocab_size)
        s_on = replay(eng_on, trace, args)
        eng_off = build_engine(args, prefix_cache=False)
        s_off = replay(eng_off, trace, args)
        extras = _common_extras(args, s_on)
        extras.update({
            "prefix_share": True,
            "shared_prefix": args.shared_prefix,
            "min_tail": args.min_tail,
            "max_tail": args.max_tail,
            "cache_off_tokens_per_sec": s_off["tokens_per_sec"],
            "cache_off_ttft_p50_s": s_off["ttft_s"]["p50"],
            "cache_off_ttft_p95_s": s_off["ttft_s"]["p95"],
            "cache_off_prefill_tokens": s_off["prefill_tokens"],
            "cache_off_wall_s": s_off["wall_s"],
            "speedup_vs_cache_off": (
                round(s_on["tokens_per_sec"]
                      / s_off["tokens_per_sec"], 3)
                if s_off["tokens_per_sec"] else 0.0),
        })
        return {
            "metric": f"serve_{args.model}_{tag}_prefix_share_"
                      "tokens_per_sec",
            "value": s_on["tokens_per_sec"],
            "unit": "tok/s",
            "vs_baseline": extras["speedup_vs_cache_off"],
            "rc": 0,
            "extras": extras,
        }

    if args.spec_trace:
        # A/B over the SAME repetitive trace: speculation on vs off
        eng_on = build_engine(args, prefix_cache=args.prefix_cache == "on",
                              spec=True)
        trace = repetitive_trace(args, eng_on.family.cfg.vocab_size)
        s_on = replay(eng_on, trace, args)
        eng_off = build_engine(args, prefix_cache=args.prefix_cache == "on",
                               spec=False)
        s_off = replay(eng_off, trace, args)
        extras = _common_extras(args, s_on)
        extras.update({
            "spec_trace": True,
            "spec": True,
            "pattern": args.pattern,
            "max_draft": args.max_draft,
            "spec_steps": s_on["spec_steps"],
            "draft_tokens": s_on["draft_tokens"],
            "accepted_draft_tokens": s_on["accepted_draft_tokens"],
            "draft_acceptance_rate": s_on["draft_acceptance_rate"],
            "spec_off_tokens_per_sec": s_off["tokens_per_sec"],
            "spec_off_decode_steps": s_off["decode_steps"],
            "spec_off_tokens_per_decode_step":
                s_off["tokens_per_decode_step"],
            "spec_off_wall_s": s_off["wall_s"],
            "speedup_vs_spec_off": (
                round(s_on["tokens_per_sec"] / s_off["tokens_per_sec"], 3)
                if s_off["tokens_per_sec"] else 0.0),
        })
        return {
            "metric": f"serve_{args.model}_{tag}_spec_tokens_per_sec",
            "value": s_on["tokens_per_sec"],
            "unit": "tok/s",
            "vs_baseline": extras["speedup_vs_spec_off"],
            "rc": 0,
            "extras": extras,
        }

    if args.long_trace:
        # A/B over the SAME long-document + short-decode-mix trace:
        # chunked prefill (budgeted, Sarathi) vs the stall-prone
        # monolithic baseline (prefill window widened to swallow the
        # whole prompt in one program call). The headline number is
        # decode tok/s DURING the long-prefill window — how fast
        # everyone else's streams move while a document is read in.
        max_seq = args.long_prompt + args.max_new
        budget = args.chunk_budget or args.prefill_window
        eng_ch = build_engine(args, prefix_cache=args.prefix_cache == "on",
                              max_seq=max_seq,
                              prefill_len=args.prefill_window,
                              chunked_prefill=True,
                              prefill_chunk_budget=budget)
        trace = long_trace(args, eng_ch.family.cfg.vocab_size)
        s_ch = replay_long(eng_ch, trace, args)
        eng_mono = build_engine(args,
                                prefix_cache=args.prefix_cache == "on",
                                max_seq=max_seq, prefill_len=max_seq)
        s_mono = replay_long(eng_mono, trace, args)
        extras = _common_extras(args, s_ch)
        ratio = (round(s_ch["decode_tps_during_long_prefill"]
                       / s_mono["decode_tps_during_long_prefill"], 3)
                 if s_mono["decode_tps_during_long_prefill"] else 0.0)
        extras.update({
            "long_trace": True,
            "long_prompts": args.long_prompts,
            "long_prompt": args.long_prompt,
            "prefill_window": args.prefill_window,
            "chunk_budget": budget,
            "prefill_chunks": s_ch["prefill_chunks"],
            "chunk_steps": s_ch["chunk_steps"],
            "chunk_tokens_per_step": s_ch["chunk_tokens_per_step"],
            "itl_p95_s": s_ch["itl_s"]["p95"],
            "itl_p99_s": s_ch["itl_s"]["p99"],
            "long_window_wall_s": s_ch["long_window_wall_s"],
            "long_window_decode_tokens":
                s_ch["long_window_decode_tokens"],
            "decode_tps_during_long_prefill":
                s_ch["decode_tps_during_long_prefill"],
            "unchunked_tokens_per_sec": s_mono["tokens_per_sec"],
            "unchunked_itl_p95_s": s_mono["itl_s"]["p95"],
            "unchunked_itl_p99_s": s_mono["itl_s"]["p99"],
            "unchunked_long_window_wall_s":
                s_mono["long_window_wall_s"],
            "unchunked_long_window_decode_tokens":
                s_mono["long_window_decode_tokens"],
            "unchunked_decode_tps_during_long_prefill":
                s_mono["decode_tps_during_long_prefill"],
            "unchunked_finished": s_mono["finished"],
            # THE acceptance signal: concurrent decode throughput
            # while a long prompt prefills, chunked / monolithic
            "decode_tps_ratio_vs_unchunked": ratio,
            "itl_p99_ratio_vs_unchunked": (
                round(s_mono["itl_s"]["p99"] / s_ch["itl_s"]["p99"], 3)
                if s_ch["itl_s"]["p99"] else 0.0),
        })
        return {
            "metric": f"serve_{args.model}_{tag}_long_tokens_per_sec",
            "value": s_ch["tokens_per_sec"],
            "unit": "tok/s",
            "vs_baseline": ratio,
            "rc": 0,
            "extras": extras,
        }

    if args.lora_trace:
        import tempfile

        from quintnet_tpu.serve import AdapterRegistry

        prefix_cache = args.prefix_cache == "on"
        spec = args.spec == "on"
        tmpdir = tempfile.mkdtemp(prefix="serve_bench_lora_")
        # A: ONE multi-LoRA engine serving the whole mixed-tenant trace
        _family, base_params = build_model(args)
        tenants = make_adapters(args, base_params, tmpdir)
        registry = AdapterRegistry()
        for aid, (_merged, path) in tenants.items():
            registry.register(aid, path)
        eng_lora = build_engine(args, prefix_cache=prefix_cache,
                                spec=spec, params=base_params,
                                adapters=registry)
        trace = lora_trace(args, eng_lora.family.cfg.vocab_size)
        s_on = replay(eng_lora, trace, args)
        # B: the merged-weight baseline — one DEDICATED engine per
        # tenant serving only its own requests (no cross-tenant
        # batching possible); walls and counters summed. The same
        # --spec/--prefix-cache settings apply to both sides.
        merged_wall = merged_gen = merged_steps = merged_dsteps = 0
        for aid, (merged, _path) in tenants.items():
            sub = [(t, p, m) for (t, p, m, a) in trace if a == aid]
            eng_m = build_engine(args, prefix_cache=prefix_cache,
                                 spec=spec, params=merged)
            s_m = replay(eng_m, sub, args)
            merged_wall += s_m["wall_s"]
            merged_gen += s_m["gen_tokens"]
            merged_steps += s_m["steps"]
            merged_dsteps += s_m["decode_steps"]
        merged_tps = (round(merged_gen / merged_wall, 2)
                      if merged_wall > 0 else 0.0)
        extras = _common_extras(args, s_on)
        extras.update({
            "lora_trace": True,
            "adapters": args.adapters,
            "lora_rank": args.lora_rank,
            "spec": spec,
            "prefix_cache": prefix_cache,
            "per_adapter": s_on["adapters"],
            "merged_tokens_per_sec": merged_tps,
            "merged_gen_tokens": merged_gen,
            "merged_wall_s": round(merged_wall, 4),
            "merged_decode_steps": merged_dsteps,
            "merged_steps": merged_steps,
            # the wall-noise-free signal: one shared multi-LoRA decode
            # step does the work of many dedicated-engine steps
            "decode_step_ratio_vs_merged": (
                round(merged_dsteps / s_on["decode_steps"], 3)
                if s_on["decode_steps"] else 0.0),
            "speedup_vs_merged": (
                round(s_on["tokens_per_sec"] / merged_tps, 3)
                if merged_tps else 0.0),
        })
        return {
            "metric": f"serve_{args.model}_{tag}_lora_tokens_per_sec",
            "value": s_on["tokens_per_sec"],
            "unit": "tok/s",
            "vs_baseline": extras["speedup_vs_merged"],
            "rc": 0,
            "extras": extras,
        }

    prefix_cache = args.prefix_cache == "on"
    spec = args.spec == "on"
    engine = build_engine(args, prefix_cache=prefix_cache, spec=spec)
    obs = None
    if args.trace_out:
        obs = _arm_obs(engine)     # standalone Perfetto export
    trace = poisson_trace(args, engine.family.cfg.vocab_size)
    s = replay(engine, trace, args)
    extras = _common_extras(args, s)
    extras["prefix_cache"] = prefix_cache
    extras["spec"] = spec
    extras["kv_dtype"] = args.kv_dtype
    extras["weights_dtype"] = args.weights_dtype
    extras["attn_kernel"] = args.kernel
    if obs is not None:
        extras.update(_obs_summary(*obs))
        extras.update(_write_trace_out(args.trace_out, *obs))
    if spec:
        extras.update({
            "spec_steps": s["spec_steps"],
            "draft_tokens": s["draft_tokens"],
            "accepted_draft_tokens": s["accepted_draft_tokens"],
            "draft_acceptance_rate": s["draft_acceptance_rate"],
        })
    return {
        "metric": f"serve_{args.model}_{tag}_tokens_per_sec",
        "value": s["tokens_per_sec"],
        "unit": "tok/s",
        "vs_baseline": 1.0,
        "rc": 0,
        "extras": extras,
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="gpt2", choices=("gpt2", "llama"))
    ap.add_argument("--synthetic", action="store_true",
                    help="tiny random-init config (CPU-testable)")
    ap.add_argument("--requests", type=int, default=16)
    ap.add_argument("--rate", type=float, default=0.5,
                    help="Poisson arrival rate (requests per engine step)")
    ap.add_argument("--steps", type=int, default=None,
                    help="cap on engine steps (default: run to completion)")
    ap.add_argument("--slots", type=int, default=8)
    ap.add_argument("--block-size", type=int, default=16)
    ap.add_argument("--num-blocks", type=int, default=64)
    ap.add_argument("--min-prompt", type=int, default=4)
    ap.add_argument("--max-prompt", type=int, default=24)
    ap.add_argument("--max-new", type=int, default=16)
    ap.add_argument("--eos", type=int, default=None)
    ap.add_argument("--temperature", type=float, default=0.0)
    ap.add_argument("--policy", default="fcfs", choices=("fcfs", "priority"))
    ap.add_argument("--prefix-cache", default="on", choices=("on", "off"),
                    help="prefix-cache A/B switch for the default trace")
    ap.add_argument("--kv-dtype", default="f32",
                    choices=("f32", "bf16", "int8", "fp8",
                             "fake_quant"),
                    help="KV-pool layout policy (serve/kv_quant.py): "
                         "int8 stores blocks quantized with per-block-"
                         "per-head scales, dequantized inside the "
                         "gathered-view attention kernels; fp8 is "
                         "unscaled float8_e4m3fn passthrough")
    ap.add_argument("--weights-dtype", default="f32",
                    choices=("f32", "bf16", "int8", "fp8",
                             "fake_quant"),
                    help="packed-weight layout policy "
                         "(serve/weight_quant.py): int8/fp8 store the "
                         "serving matmul weights with per-output-"
                         "channel absmax scales, dequantized inside "
                         "the dot (nn/layers.quantized_matmul)")
    ap.add_argument("--weights-ab", action="store_true",
                    help="weight-quant A/B over the default trace: "
                         "f32 weights vs --weights-dtype (int8 unless "
                         "set otherwise), everything else equal; the "
                         "committed gates are the targeted-node byte "
                         "ratio and the paged_eval_nll delta — CPU "
                         "walls recorded, never gated")
    ap.add_argument("--kernel", default="xla",
                    choices=("xla", "pallas"),
                    help="serving attention backend "
                         "(ops/paged_attention.py): 'xla' is the "
                         "gathered-view oracle, 'pallas' the fused "
                         "block-table-walking kernel (interpret mode "
                         "off-TPU)")
    ap.add_argument("--kernel-ab", action="store_true",
                    help="replay the SAME default trace through an "
                         "xla and a pallas engine: token-identity + "
                         "the auditor-verified structural win (zero "
                         "gathered-view gathers) are the committed "
                         "signals; CPU walls are recorded but NOT the "
                         "gate (interpret mode prices emulation, not "
                         "the kernel)")
    ap.add_argument("--kv-capacity", action="store_true",
                    help="equal-pool-BYTES capacity A/B over the "
                         "shared-prefix trace: f32 at --num-blocks vs "
                         "--kv-dtype (int8 unless set otherwise) at "
                         "however many blocks the same bytes buy")
    ap.add_argument("--tier-trace", action="store_true",
                    help="tiered-KV A/B over a many-tenant prefix-"
                         "churn trace: host tier armed (demote on "
                         "evict, promote on host-hit) vs the identical"
                         " evict-only engine; the prefix set is sized "
                         "--tier-prefix-ratio x the device pool so "
                         "every revisit has been evicted")
    ap.add_argument("--tier-prefixes", type=int, default=None,
                    help="distinct system prompts in the --tier-trace "
                         "(default: auto-sized from the ratio)")
    ap.add_argument("--tier-prefix-ratio", type=float, default=3.5,
                    help="prefix-set footprint as a multiple of the "
                         "usable device pool (--tier-trace)")
    ap.add_argument("--tier-repeats", type=int, default=3,
                    help="visits per prefix in the --tier-trace")
    ap.add_argument("--tier-bytes", type=int, default=None,
                    help="host-tier byte budget (--tier-trace; "
                         "default: 4x the device pool's bytes)")
    ap.add_argument("--tier-promote-blocks", type=int, default=None,
                    help="promotion budget in blocks per engine step "
                         "(--tier-trace; default: the engine's own)")
    ap.add_argument("--prefix-share", action="store_true",
                    help="shared-system-prompt trace, reported cache-on "
                         "vs cache-off over the same trace")
    ap.add_argument("--spec", default="off", choices=("on", "off"),
                    help="speculative decoding (n-gram self-drafting + "
                         "batched verify) for the default trace")
    ap.add_argument("--spec-trace", action="store_true",
                    help="repetitive greedy-friendly trace, reported "
                         "spec-on vs spec-off over the same trace")
    ap.add_argument("--pattern", type=int, default=8,
                    help="repeated-pattern length (--spec-trace prompts)")
    ap.add_argument("--long-trace", action="store_true",
                    help="long-document + short-decode-mix trace, "
                         "reported chunked-prefill vs monolithic "
                         "(widened single-bucket) over the same trace")
    ap.add_argument("--long-prompts", type=int, default=2,
                    help="long prompts in the --long-trace")
    ap.add_argument("--long-prompt", type=int, default=192,
                    help="long-prompt length (--long-trace); must "
                         "exceed --prefill-window to exercise chunking")
    ap.add_argument("--long-spacing", type=int, default=24,
                    help="engine steps between long arrivals")
    ap.add_argument("--prefill-window", type=int, default=64,
                    help="chunked engine's prefill_len (top bucket)")
    ap.add_argument("--chunk-budget", type=int, default=None,
                    help="prefill tokens per engine step (default: "
                         "--prefill-window)")
    ap.add_argument("--lora-trace", action="store_true",
                    help="multi-tenant LoRA trace: requests spread over "
                         "--adapters adapters through ONE multi-LoRA "
                         "engine, vs dedicated merged-weight engines "
                         "per adapter over the same trace")
    ap.add_argument("--adapters", type=int, default=4,
                    help="distinct LoRA adapters in the --lora-trace")
    ap.add_argument("--lora-rank", type=int, default=4,
                    help="rank of the synthetic --lora-trace adapters "
                         "(and the engine's top rank bucket)")
    ap.add_argument("--max-draft", type=int, default=8,
                    help="max drafted tokens per request per step "
                         "(pins the largest verify bucket)")
    ap.add_argument("--shared-prefix", type=int, default=None,
                    help="shared system-prompt length (--prefix-share; "
                         "default 36 for --synthetic, 96 for full "
                         "configs — tiny models have few positions)")
    ap.add_argument("--min-tail", type=int, default=4,
                    help="min unique-tail length (--prefix-share)")
    ap.add_argument("--max-tail", type=int, default=12,
                    help="max unique-tail length (--prefix-share)")
    ap.add_argument("--n-layer", type=int, default=None,
                    help="synthetic-config depth override")
    ap.add_argument("--n-embd", type=int, default=None,
                    help="synthetic-config width override")
    ap.add_argument("--n-head", type=int, default=None,
                    help="synthetic-config head-count override")
    ap.add_argument("--n-positions", type=int, default=None,
                    help="synthetic-config max-positions override")
    ap.add_argument("--vocab-size", type=int, default=None,
                    help="synthetic-config vocab override")
    ap.add_argument("--moe-trace", action="store_true",
                    help="MoE routing A/B: diverse Poisson trace vs "
                         "hot-expert (one shared tiled pattern) trace "
                         "through the same MoE engine; value = hot-side "
                         "tok/s, vs_baseline = hot/diverse")
    ap.add_argument("--experts", type=int, default=0,
                    help="expert count for the synthetic config (0 = "
                         "dense; --moe-trace defaults this to 4)")
    ap.add_argument("--expert-top-k", type=int, default=2,
                    help="routed experts per token (--experts)")
    ap.add_argument("--capacity-factor", type=float, default=1.25,
                    help="expert capacity slack multiplier (--experts)")
    ap.add_argument("--expert-capacity", type=int, default=None,
                    help="hard per-expert token capacity override "
                         "(--experts; default: derived from the factor)")
    ap.add_argument("--obs-ab", action="store_true",
                    help="observability overhead A/B over the default "
                         "trace: flight recorder (obs/) armed vs off; "
                         "value = obs-on tok/s, vs_baseline = on/off")
    ap.add_argument("--trace-out", default=None,
                    help="write the replay's flight-recorder ring + "
                         "request spans as Chrome trace-event JSON "
                         "(Perfetto-loadable; arms obs on the timed "
                         "engine)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--out", default=None,
                    help="append the record to this artifacts JSON file")
    args = ap.parse_args()
    if args.moe_trace and not args.experts:
        args.experts = 4
    if args.shared_prefix is None:
        args.shared_prefix = 36 if args.synthetic else 96
    if args.long_trace and args.synthetic and args.n_positions is None:
        # the tiny config's default positions cannot hold a document;
        # size it to the trace instead of failing admission
        args.n_positions = args.long_prompt + args.max_new + 16

    out = run(args)
    line = json.dumps(out)
    print(line)
    if args.out:
        records = []
        if os.path.exists(args.out):
            try:
                with open(args.out) as f:
                    prev = json.load(f)
                records = prev if isinstance(prev, list) else [prev]
            except (OSError, json.JSONDecodeError):
                records = []
        records.append(out)
        with open(args.out, "w") as f:
            json.dump(records, f, indent=1)


if __name__ == "__main__":
    main()

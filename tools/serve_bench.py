"""Serving benchmark: replay a synthetic Poisson request trace through
the continuous-batching engine (quintnet_tpu/serve/) and report
throughput + latency as ONE JSON line:

  {"metric": "serve_gpt2_tiny_tokens_per_sec", "value": N,
   "unit": "tok/s", "rc": 0, "extras": {"ttft_p50_s": ..,
   "ttft_p95_s": .., "peak_kv_utilization": .., ...}}

Arrivals are a Poisson process in ENGINE-STEP time (inter-arrival ~
Exp(rate)), prompt lengths uniform in [min_prompt, max_prompt] — the
mixed-length staggered workload the one-shot batch decoders
(models/gpt2_generate.py) cannot serve without padding everything to
the longest request.

Modes:
  python tools/serve_bench.py --synthetic              # tiny cfg, CPU-ok
  python tools/serve_bench.py --synthetic --model llama
  python tools/serve_bench.py --model gpt2             # 124M random init
  python tools/serve_bench.py --synthetic --steps 3    # smoke (CI runs
      this — tests/test_serve_bench.py — so the CLI can never rot)

``--steps N`` caps the engine-step budget (unfinished requests are
reported, not an error); default runs the trace to completion.
``--out FILE`` appends the record to an artifacts JSON list the same
way bench.py artifacts are kept (bench.last_known_result scans them —
the serve bench gets the same staleness story as the training bench).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def build_engine(args):
    import jax

    from quintnet_tpu.serve import ServeEngine, gpt2_family, llama_family

    if args.model == "gpt2":
        from quintnet_tpu.models.gpt2 import GPT2Config, gpt2_init

        cfg = (GPT2Config.tiny(n_layer=2) if args.synthetic
               else GPT2Config.base())
        params = gpt2_init(jax.random.key(args.seed), cfg)
        family = gpt2_family(cfg)
    elif args.model == "llama":
        from quintnet_tpu.models.llama import LlamaConfig, llama_init

        cfg = (LlamaConfig.tiny(n_layers=2) if args.synthetic
               else LlamaConfig())
        params = llama_init(jax.random.key(args.seed), cfg)
        family = llama_family(cfg)
    else:
        raise SystemExit(f"unknown --model {args.model}")

    max_seq = min(args.max_prompt + args.max_new, family.max_positions)
    return ServeEngine(
        family, params, max_slots=args.slots, block_size=args.block_size,
        num_blocks=args.num_blocks, max_seq_len=max_seq,
        eos_token_id=args.eos, temperature=args.temperature,
        policy=args.policy)


def poisson_trace(args, vocab_size: int):
    """[(arrival_step, prompt, max_new)] sorted by arrival."""
    import numpy as np

    rng = np.random.default_rng(args.seed)
    t = 0.0
    trace = []
    for _ in range(args.requests):
        t += rng.exponential(1.0 / args.rate)
        n = int(rng.integers(args.min_prompt, args.max_prompt + 1))
        prompt = rng.integers(0, vocab_size, (n,)).astype(np.int32)
        trace.append((int(t), prompt, args.max_new))
    return trace


def run(args) -> dict:
    import time

    import numpy as np

    import jax

    engine = build_engine(args)
    vocab = engine.family.cfg.vocab_size
    trace = poisson_trace(args, vocab)

    # warmup: compile both programs (one full request lifecycle =
    # prefill + decode + retire) OUTSIDE the timed window, then reset
    # the metrics so the replay starts from a clean ledger — tok/s
    # must measure serving, not XLA compile time
    engine.submit(np.ones((args.min_prompt,), "int32"), 2)
    engine.run()
    engine.metrics = type(engine.metrics)(clock=engine.clock)

    submitted = 0
    step = 0
    t0 = time.perf_counter()
    while submitted < len(trace) or engine.has_work:
        if args.steps is not None and step >= args.steps:
            break
        while submitted < len(trace) and trace[submitted][0] <= step:
            _, prompt, max_new = trace[submitted]
            engine.submit(prompt, max_new)
            submitted += 1
        engine.step()
        step += 1
    # the throughput wall clock must cover DEVICE work, not dispatch:
    # drain the in-flight pool writes before reading the clock (the
    # metrics' own wall starts at the first step's completion, which
    # also silently excluded the first prefill+decode from the window)
    jax.block_until_ready(engine.pool.caches())
    wall = time.perf_counter() - t0

    s = engine.metrics.summary()
    s["wall_s"] = round(wall, 4)
    s["tokens_per_sec"] = (round(s["gen_tokens"] / wall, 2) if wall > 0
                           else 0.0)
    tag = "tiny" if args.synthetic else "full"
    return {
        "metric": f"serve_{args.model}_{tag}_tokens_per_sec",
        "value": s["tokens_per_sec"],
        "unit": "tok/s",
        "vs_baseline": 1.0,
        "rc": 0,
        "extras": {
            "ttft_p50_s": s["ttft_s"]["p50"],
            "ttft_p95_s": s["ttft_s"]["p95"],
            "latency_p50_s": s["latency_s"]["p50"],
            "latency_p95_s": s["latency_s"]["p95"],
            "peak_kv_utilization": s["peak_kv_utilization"],
            "peak_running": s["peak_running"],
            "steps": s["steps"],
            "requests": args.requests,
            "submitted": submitted,
            "finished": s["finished"],
            "preempted": s["preempted"],
            "decode_tokens": s["decode_tokens"],
            "prefill_tokens": s["prefill_tokens"],
            "wall_s": s["wall_s"],
            "model": args.model,
            "synthetic": bool(args.synthetic),
            "slots": args.slots,
            "block_size": args.block_size,
            "num_blocks": args.num_blocks,
            "rate": args.rate,
        },
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="gpt2", choices=("gpt2", "llama"))
    ap.add_argument("--synthetic", action="store_true",
                    help="tiny random-init config (CPU-testable)")
    ap.add_argument("--requests", type=int, default=16)
    ap.add_argument("--rate", type=float, default=0.5,
                    help="Poisson arrival rate (requests per engine step)")
    ap.add_argument("--steps", type=int, default=None,
                    help="cap on engine steps (default: run to completion)")
    ap.add_argument("--slots", type=int, default=8)
    ap.add_argument("--block-size", type=int, default=16)
    ap.add_argument("--num-blocks", type=int, default=64)
    ap.add_argument("--min-prompt", type=int, default=4)
    ap.add_argument("--max-prompt", type=int, default=24)
    ap.add_argument("--max-new", type=int, default=16)
    ap.add_argument("--eos", type=int, default=None)
    ap.add_argument("--temperature", type=float, default=0.0)
    ap.add_argument("--policy", default="fcfs", choices=("fcfs", "priority"))
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--out", default=None,
                    help="append the record to this artifacts JSON file")
    args = ap.parse_args()

    out = run(args)
    line = json.dumps(out)
    print(line)
    if args.out:
        records = []
        if os.path.exists(args.out):
            try:
                with open(args.out) as f:
                    prev = json.load(f)
                records = prev if isinstance(prev, list) else [prev]
            except (OSError, json.JSONDecodeError):
                records = []
        records.append(out)
        with open(args.out, "w") as f:
            json.dump(records, f, indent=1)


if __name__ == "__main__":
    main()

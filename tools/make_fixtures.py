#!/usr/bin/env python
"""Generate the committed real-data-format fixtures under tests/fixtures/.

The environment has no network egress, so the real MNIST / CNN-DailyMail
files can't be downloaded — but the LOADERS can still be proven against
the real on-disk formats: this writes a byte-accurate IDX/gzip MNIST set
(magic 0x0803/0x0801 big-endian headers, uint8 payload, gzip member —
the exact format of yann.lecun.com's train-images-idx3-ubyte.gz) and a
CNN/DM-schema CSV (id/article/highlights columns, quoted multi-line
fields) small enough to commit. tests/test_realdata.py runs the real
loader paths end-to-end on them with the synthetic fallback DISABLED.

Deterministic: re-running reproduces identical bytes (fixed seeds,
mtime=0 in the gzip header).
"""

from __future__ import annotations

import csv
import gzip
import os
import struct

import numpy as np

HERE = os.path.dirname(os.path.abspath(__file__))
FIX = os.path.join(HERE, "..", "tests", "fixtures")

N_TRAIN, N_TEST = 24, 8


def idx_bytes(arr: np.ndarray) -> bytes:
    """Serialize uint8 ndarray in IDX format: 2 zero bytes, dtype code
    0x08 (ubyte), ndim, then big-endian u32 dims, then raw data."""
    assert arr.dtype == np.uint8
    header = struct.pack(">BBBB", 0, 0, 0x08, arr.ndim)
    header += struct.pack(">" + "I" * arr.ndim, *arr.shape)
    return header + arr.tobytes()


def write_gz(path: str, payload: bytes) -> None:
    with open(path, "wb") as raw:
        with gzip.GzipFile(fileobj=raw, mode="wb", mtime=0) as f:
            f.write(payload)


def main():
    mdir = os.path.join(FIX, "mnist")
    os.makedirs(mdir, exist_ok=True)

    rng = np.random.default_rng(7)
    for split, n in (("train", N_TRAIN), ("t10k", N_TEST)):
        # digit-ish content: a bright class-dependent block on a dark
        # background, uint8 like real MNIST pixels
        labels = (np.arange(n) % 10).astype(np.uint8)
        imgs = np.zeros((n, 28, 28), np.uint8)
        for i, lab in enumerate(labels):
            r, c = 2 + (lab // 5) * 12, 2 + (lab % 5) * 5
            imgs[i, r:r + 10, c:c + 4] = 200
        imgs += rng.integers(0, 30, imgs.shape, dtype=np.uint8)
        write_gz(os.path.join(mdir, f"{split}-images-idx3-ubyte.gz"),
                 idx_bytes(imgs))
        write_gz(os.path.join(mdir, f"{split}-labels-idx1-ubyte.gz"),
                 idx_bytes(labels))
    # train-* naming for the train split (t10k already matches)
    for kind in ("images-idx3", "labels-idx1"):
        src = os.path.join(mdir, f"train-{kind}-ubyte.gz")
        assert os.path.exists(src), src

    # CNN/DailyMail schema: id,article,highlights with quoted fields
    # containing commas and embedded newlines (the wire format csv
    # readers must actually survive)
    rows = [
        {"id": f"{i:08x}",
         "article": (f"(CNN) -- Story {i}, in which a framework, "
                     f"tested offline, loads \"real\" files.\n"
                     f"Paragraph two of story {i} adds detail."),
         "highlights": f"Story {i} summary line.\nSecond highlight {i}."}
        for i in range(6)
    ]
    with open(os.path.join(FIX, "cnn_dm_tiny.csv"), "w", newline="",
              encoding="utf-8") as f:
        w = csv.DictWriter(f, fieldnames=["id", "article", "highlights"])
        w.writeheader()
        w.writerows(rows)
    print(f"fixtures written under {os.path.normpath(FIX)}")


if __name__ == "__main__":
    main()
